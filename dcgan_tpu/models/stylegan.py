"""StyleGAN2-lite generator — the framework's third model family.

The reference is DCGAN-only (distriubted_model.py:83-128); this family is a
deliberately small take on the StyleGAN2 synthesis architecture (Karras et
al. 2020, arXiv:1912.04958), selected with `ModelConfig(arch="stylegan")`
and scaled by the same base_size·2^k rule as the other stacks:

- a 2-layer lrelu **mapping network** z -> w (w_dim = z_dim; z is
  pixel-normalized first, the paper's input normalization);
- a **learned constant** [base, base, top_ch] input instead of a z
  projection;
- k up-blocks of 2x nearest upsample + two **modulated 3x3 convolutions**
  — per-sample styles s = 1 + affine(w) scale the input channels and the
  output is demodulated by the per-sample, per-output-channel norm
  1/sqrt(Σ (W·s)²) — the TPU-friendly activation-scaling formulation,
  mathematically identical to StyleGAN2's grouped-conv weight modulation
  for stride-1 convs (the weight-scale cancels under demodulation, so the
  framework's N(0, 0.02) init convention stands in for equalized LR);
- a **skip (tRGB) output path**: each stage emits an RGB contribution via a
  modulated-without-demodulation 1x1 conv, summed with the upsampled
  running RGB; final image through tanh (framework contract: images live
  in tanh range end to end, unlike the paper's unbounded output).

Knowing omissions vs the paper, all documented here so nobody expects
paper-exact FID: no per-layer noise injection (`generator_apply` takes no
PRNG key by framework contract — adding one would fork every caller for a
texture-detail feature), no style mixing regularization, no path-length
regularization, and Adam β₂ stays at the repo default. The discriminator
is the existing norm-free residual critic (models/resnet.py — StyleGAN2's
own D is a plain resnet; pair with `--r1_gamma`/`--r1_interval`, the
regularizer the paper trains with).

There is no BatchNorm anywhere in G — styles carry the conditioning role —
so the generator's state tree is empty: nothing to sync across replicas,
and the sampler path is identical to the train path modulo `train` having
no effect. num_classes > 0 concatenates a one-hot onto z before the
mapping network (conditioning enters through w). conditional_bn / attn_res
/ spectral_norm="gd" are rejected in config validation for this family.

Entry points match dcgan.py's signatures; models/dcgan.py dispatches on
cfg.arch so every caller (steps, parallel backends, trainer, generate,
evals, bench) is untouched — the integration-surface conventions
docs/DESIGN.md §4 describes.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dcgan_tpu.config import ModelConfig
from dcgan_tpu.ops.layers import conv2d_init, linear_apply, linear_init, \
    lrelu
from dcgan_tpu.models.resnet import _g_channels, _upsample

Pytree = dict

_CONV_DIMS = ("NHWC", "HWIO", "NHWC")


# ---------------------------------------------------------------------------
# Generator
# ---------------------------------------------------------------------------

def generator_init(key, cfg: ModelConfig) -> Tuple[Pytree, Pytree]:
    """Returns (params, state). state is {} — no BN, no persistent
    moments; the whole generator is a pure function of (params, z)."""
    k = cfg.num_up_layers
    dtype = jnp.dtype(cfg.param_dtype)
    chans = _g_channels(cfg)
    # key budget: 3 head keys (map0/map1/const) + 6 per block, consumed as
    # keys[6*i - 3 : 6*i + 3] for i in 1..k — max index 6k+2, so exactly
    # 6k+3 keys
    keys = jax.random.split(key, 6 * k + 3)

    in_dim = cfg.z_dim + (cfg.num_classes if cfg.num_classes else 0)
    params: Pytree = {
        "map0": linear_init(keys[0], in_dim, cfg.z_dim, dtype=dtype),
        "map1": linear_init(keys[1], cfg.z_dim, cfg.z_dim, dtype=dtype),
        # the learned constant input IS the signal source: unit-scale init
        # (the paper's randn), not the 0.02 weight convention
        "const": jax.random.normal(
            keys[2], (cfg.base_size, cfg.base_size, chans[0]), dtype),
    }
    for i in range(1, k + 1):
        cin, cout = chans[i - 1], chans[i]
        kk = keys[6 * i - 3:6 * i + 3]
        params[f"b{i}_style1"] = linear_init(kk[0], cfg.z_dim, cin,
                                             dtype=dtype)
        params[f"b{i}_conv1"] = conv2d_init(kk[1], cin, cout,
                                            kernel=3, dtype=dtype)
        params[f"b{i}_style2"] = linear_init(kk[2], cfg.z_dim, cout,
                                             dtype=dtype)
        params[f"b{i}_conv2"] = conv2d_init(kk[3], cout, cout,
                                            kernel=3, dtype=dtype)
        params[f"b{i}_rgb_style"] = linear_init(kk[4], cfg.z_dim, cout,
                                                dtype=dtype)
        params[f"b{i}_trgb"] = conv2d_init(kk[5], cout, cfg.c_dim,
                                           kernel=1, dtype=dtype)
    return params, {}


def _mod_conv(layer: Pytree, style_layer: Pytree, x: jax.Array,
              w_lat: jax.Array, *, demod: bool, cdt) -> jax.Array:
    """Modulated conv as activation scaling (exact for stride-1, bias-free
    conv): scale input channels by s = 1 + affine(w), convolve, then (for
    demod) divide each output channel by its per-sample modulated weight
    norm sqrt(Σ_{kh,kw,i} (W s_i)²). Bias applies after demodulation."""
    s = 1.0 + linear_apply(style_layer, w_lat, compute_dtype=cdt)  # [B, cin]
    w = layer["w"].astype(cdt)                       # [kh, kw, cin, cout]
    y = lax.conv_general_dilated(
        x * s[:, None, None, :], w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=_CONV_DIMS)
    if demod:
        # Σ over kh,kw once (style-independent), then per-sample over cin —
        # f32 throughout: a bf16 sum over kernel*cin terms loses the low
        # bits the rsqrt then amplifies
        w2 = (layer["w"].astype(jnp.float32) ** 2).sum(axis=(0, 1))
        d = lax.rsqrt((s.astype(jnp.float32) ** 2) @ w2 + 1e-8)  # [B, cout]
        y = y * d.astype(cdt)[:, None, None, :]
    return y + layer["b"].astype(cdt)


def generator_apply(params: Pytree, state: Pytree, z: jax.Array, *,
                    cfg: ModelConfig, train: bool,
                    labels: Optional[jax.Array] = None,
                    axis_name: Optional[str] = None,
                    attn_mesh=None,
                    pallas_mesh=None,
                    capture: Optional[dict] = None
                    ) -> Tuple[jax.Array, Pytree]:
    """z [B, z_dim] (-1..1) -> image [B, S, S, c_dim] in tanh range.

    `train` is accepted for signature parity but has no effect: there is no
    batch-dependent state. The returned state is always {}.
    """
    del train, axis_name, attn_mesh, pallas_mesh  # no BN / attention here
    k = cfg.num_up_layers
    cdt = jnp.dtype(cfg.compute_dtype)

    if cfg.num_classes:
        if labels is None:
            raise ValueError("conditional generator requires labels")
        onehot = jax.nn.one_hot(labels, cfg.num_classes, dtype=z.dtype)
        z = jnp.concatenate([z, onehot], axis=-1)

    # pixel-normalize z (the paper's mapping-input normalization), then the
    # 2-layer lrelu mapping network -> w
    zn = z.astype(cdt)
    zn = zn * lax.rsqrt(jnp.mean(zn.astype(jnp.float32) ** 2, axis=-1,
                                 keepdims=True).astype(cdt) + 1e-8)
    w_lat = lrelu(linear_apply(params["map0"], zn, compute_dtype=cdt),
                  cfg.leak)
    w_lat = lrelu(linear_apply(params["map1"], w_lat, compute_dtype=cdt),
                  cfg.leak)
    if capture is not None:
        capture["w"] = w_lat

    h = jnp.broadcast_to(params["const"].astype(cdt),
                         (z.shape[0],) + params["const"].shape)
    rgb = None
    for i in range(1, k + 1):
        h = _upsample(h)
        h = lrelu(_mod_conv(params[f"b{i}_conv1"], params[f"b{i}_style1"],
                            h, w_lat, demod=True, cdt=cdt), cfg.leak)
        h = lrelu(_mod_conv(params[f"b{i}_conv2"], params[f"b{i}_style2"],
                            h, w_lat, demod=True, cdt=cdt), cfg.leak)
        y = _mod_conv(params[f"b{i}_trgb"], params[f"b{i}_rgb_style"],
                      h, w_lat, demod=False, cdt=cdt)
        rgb = y if rgb is None else _upsample(rgb) + y
        if capture is not None:
            capture[f"h{i}"] = h
    out = jnp.tanh(rgb.astype(jnp.float32))
    if capture is not None:
        capture[f"h{k + 1}"] = out
    return out, {}
