"""Standalone generation CLI: checkpoint -> images, no training loop.

The reference has NO standalone inference path — its `sampler` lives inside
the train graph and only runs as a side effect of training (SURVEY.md §3.4:
"There is no standalone inference/serve entry point"; image_train.py:179-192).
This module is that missing entry point:

    python -m dcgan_tpu.generate --checkpoint_dir ckpt --num_images 64
    python -m dcgan_tpu.generate --checkpoint_dir ckpt --preset cifar10-cond \
        --class_id 3 --num_images 256 --npz out.npz --platform cpu

Writes 8x8 PNG grids (the reference's sample-grid format, image_train.py:
197-215) into --out_dir and, optionally, the raw batch as float32 .npz in
tanh range. Conditional checkpoints take --class_id (one class) or default to
cycling all classes.
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dcgan_tpu.generate",
                                description="generate images from a "
                                            "trained checkpoint")
    p.add_argument("--checkpoint_dir", required=True)
    p.add_argument("--out_dir", default="generated")
    p.add_argument("--num_images", type=int, default=64)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--grid", default="8x8",
                   help="RxC tiling per PNG (e.g. 8x8); 0 disables PNGs")
    p.add_argument("--npz", default=None,
                   help="also dump all images (and labels) to this .npz")
    # model architecture — resolved from the checkpoint's own config.json
    # (written by the trainer) by default, so no flags are needed to sample
    # any checkpoint. Defaults are None so "explicitly passed" is
    # distinguishable from "omitted"; precedence is explicit flag > --preset
    # > checkpoint config.json > ModelConfig defaults.
    p.add_argument("--preset", default=None,
                   help="named config (presets.py) supplying the model "
                        "architecture instead of the checkpoint's "
                        "config.json; explicit flags override")
    from dcgan_tpu.config import add_model_override_flags

    add_model_override_flags(p)
    p.add_argument("--class_id", type=int, default=None,
                   help="conditional models: generate only this class "
                        "(default: cycle all classes)")
    p.add_argument("--use_ema", action="store_true",
                   help="sample from the EMA generator weights the checkpoint "
                        "carries (trained with --g_ema_decay > 0); default "
                        "samples the live weights")
    p.add_argument("--interpolate", action="store_true",
                   help="latent-space interpolation mode: each grid row "
                        "walks z linearly between two random endpoints (the "
                        "reference's declared-but-dead `visualize` flag, "
                        "image_train.py:24, actually implemented)")
    p.add_argument("--truncation", type=float, default=1.0,
                   help="truncation trick: scale z by psi in (0, 1] toward "
                        "the prior's mode — fidelity up, diversity down "
                        "(BigGAN-style, for the U(-1,1) prior); 1 = off")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None)
    return p


def _model_config(args: argparse.Namespace):
    from dcgan_tpu.config import MODEL_OVERRIDE_FLAGS, resolve_model_config

    return resolve_model_config(
        args.checkpoint_dir, preset=args.preset,
        overrides={name: getattr(args, name)
                   for name in MODEL_OVERRIDE_FLAGS})


def generate(args: argparse.Namespace) -> dict:
    """Runs generation; returns {"num_images", "step", "paths"}."""
    import jax

    from dcgan_tpu.config import TrainConfig
    from dcgan_tpu.parallel import make_mesh, make_parallel_train
    from dcgan_tpu.utils.checkpoint import Checkpointer
    from dcgan_tpu.utils.images import save_sample_grid

    mcfg = _model_config(args)
    if args.batch_size < 1:
        raise SystemExit(f"--batch_size must be >= 1, got {args.batch_size}")
    if args.num_images < 1:
        raise SystemExit(f"--num_images must be >= 1, got {args.num_images}")
    if not 0.0 < args.truncation <= 1.0:
        raise SystemExit(
            f"--truncation must be in (0, 1], got {args.truncation}")
    if args.class_id is not None:
        if not mcfg.num_classes:
            raise SystemExit("--class_id requires a conditional model "
                             "(--num_classes > 0)")
        if not 0 <= args.class_id < mcfg.num_classes:
            raise SystemExit(
                f"--class_id {args.class_id} out of range "
                f"[0, {mcfg.num_classes}) — an out-of-range id would one-hot "
                "to all zeros and generate unconditioned images")
    grid = None
    if args.grid and args.grid != "0":
        try:
            rows, cols = (int(v) for v in args.grid.lower().split("x"))
        except ValueError:
            raise SystemExit(f"--grid must be RxC (e.g. 8x8) or 0, "
                             f"got {args.grid!r}") from None
        if rows < 1 or cols < 1:
            raise SystemExit(f"--grid dimensions must be >= 1, "
                             f"got {args.grid!r}")
        grid = (rows, cols)

    cfg = TrainConfig(model=mcfg, batch_size=args.batch_size,
                      checkpoint_dir=args.checkpoint_dir,
                      # any value > 0 makes sample() read state["ema_gen"]
                      g_ema_decay=0.999 if args.use_ema else 0.0)
    mesh = make_mesh(cfg.mesh)
    pt = make_parallel_train(cfg, mesh)

    state = pt.init(jax.random.key(0))
    restored = Checkpointer(args.checkpoint_dir).restore_latest(state)
    if restored is None:
        raise SystemExit(f"no checkpoint under {args.checkpoint_dir}")
    state = restored
    step = int(jax.device_get(state["step"]))

    # batch must tile the data axis for the sharded sample fn; the tail
    # (num_images not divisible by batch_size) routes through the serving
    # plane's bucket ladder (ISSUE 9): it snaps to the smallest ladder
    # bucket covering the remainder — a small reused set of compiled
    # shapes — instead of either re-running the full batch for a handful
    # of images or tracing a one-off tail shape
    from dcgan_tpu.serve.buckets import build_ladder

    data_axis = mesh.shape["data"]
    batch = -(-args.batch_size // data_axis) * data_axis
    ladder = build_ladder(batch, data_axis)

    os.makedirs(args.out_dir, exist_ok=True)
    key = jax.random.key(args.seed)

    if args.interpolate:
        if not grid:
            raise SystemExit("--interpolate needs a grid (e.g. --grid 8x8)")
        return _interpolate(args, pt, state, mcfg, grid, data_axis, step, key)
    all_imgs: List[np.ndarray] = []
    all_labels: List[np.ndarray] = []
    made = 0
    batch_idx = 0
    while made < args.num_images:
        remaining = args.num_images - made
        n = batch if remaining >= batch else ladder.snap(remaining)
        z = args.truncation * jax.random.uniform(
            jax.random.fold_in(key, batch_idx),
            (n, mcfg.z_dim), minval=-1.0, maxval=1.0)
        if mcfg.num_classes:
            if args.class_id is not None:
                labels = np.full((n,), args.class_id, dtype=np.int32)
            else:
                # continue the class cycle across batches regardless of
                # each batch's bucket size
                labels = np.arange(made, made + n,
                                   dtype=np.int32) % mcfg.num_classes
            imgs = jax.device_get(pt.sample(state, z, jax.numpy.asarray(labels)))
        else:
            labels = None
            imgs = jax.device_get(pt.sample(state, z))
        take = min(n, remaining)
        all_imgs.append(np.asarray(imgs[:take], dtype=np.float32))
        if labels is not None:
            all_labels.append(labels[:take])
        made += take
        batch_idx += 1

    images = np.concatenate(all_imgs)
    paths: List[str] = []
    if grid:
        # tile from the full pool, not per generation batch, so grids larger
        # than batch_size still get written
        cells = grid[0] * grid[1]
        for chunk in range(len(images) // cells):
            path = os.path.join(args.out_dir,
                                f"gen_{step:08d}_{chunk:04d}.png")
            save_sample_grid(path, images[chunk * cells:(chunk + 1) * cells],
                             grid)
            paths.append(path)
        if not paths:
            import sys
            print(f"[dcgan_tpu.generate] warning: no PNGs written — "
                  f"--num_images {args.num_images} < grid {grid[0]}x{grid[1]} "
                  f"({cells} cells); lower --grid or raise --num_images",
                  file=sys.stderr)

    if args.npz:
        arrays = {"images": images}
        if all_labels:
            arrays["labels"] = np.concatenate(all_labels)
        np.savez(args.npz, **arrays)
        paths.append(args.npz)
    return {"num_images": made, "step": step, "paths": paths}


def _interpolate(args, pt, state, mcfg, grid, data_axis: int, step: int,
                 key) -> dict:
    """Latent-walk grid: row r interpolates z linearly from a random left
    endpoint to a random right endpoint across the columns; conditional
    models hold one class per row (--class_id fixes it grid-wide)."""
    import jax
    import jax.numpy as jnp

    from dcgan_tpu.utils.images import save_sample_grid

    rows, cols = grid
    z_ends = args.truncation * jax.random.uniform(
        key, (2, rows, mcfg.z_dim), minval=-1.0, maxval=1.0)
    t = jnp.linspace(0.0, 1.0, cols)[None, :, None]           # [1, C, 1]
    z = (1.0 - t) * z_ends[0][:, None, :] + t * z_ends[1][:, None, :]
    z = z.reshape(rows * cols, mcfg.z_dim)
    n = z.shape[0]
    pad = (-n) % data_axis
    if pad:
        # resize cycles rows, so this stays correct even when pad > n
        # (tiny grid on a wide data mesh)
        z = jnp.resize(z, (n + pad, mcfg.z_dim))

    labels = None
    if mcfg.num_classes:
        per_row = (np.full((rows,), args.class_id, dtype=np.int32)
                   if args.class_id is not None
                   else np.arange(rows, dtype=np.int32) % mcfg.num_classes)
        labels = np.resize(np.repeat(per_row, cols), (n + pad,))
        imgs = jax.device_get(pt.sample(state, z, jnp.asarray(labels)))
    else:
        imgs = jax.device_get(pt.sample(state, z))

    images = np.asarray(imgs[:n], dtype=np.float32)
    path = os.path.join(args.out_dir, f"interp_{step:08d}.png")
    save_sample_grid(path, images, grid)
    paths = [path]
    if args.npz:
        arrays = {"images": images}
        if labels is not None:
            arrays["labels"] = labels[:n]
        np.savez(args.npz, **arrays)
        paths.append(args.npz)
    return {"num_images": n, "step": step, "paths": paths}


def main(argv: Optional[List[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    result = generate(args)
    print(f"[dcgan_tpu.generate] {result['num_images']} images from "
          f"checkpoint step {result['step']} -> "
          f"{result['paths'][-1] if result['paths'] else args.out_dir}")


if __name__ == "__main__":
    main()
