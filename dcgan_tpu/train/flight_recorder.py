"""Crash flight recorder: the telemetry that led up to a failure (ISSUE 6).

PRs 3-5 made the trainer die loudly — watchdog trip (exit 43 + all-thread
stacks), NaN abort with step context, coordinated stop, services-worker
surfacing — but every one of those dumps STACKS without telemetry: what the
losses were doing, whether the services queue was backing up, whether
rollbacks or quarantines had started accumulating before the end. This
module keeps a fixed-size ring of the last K per-step records (step, wall
and host ms, materialized losses, services queue depth + dropped count,
gate verdict, rollback/quarantine/compile-cache counters — one
`CounterRegistry` snapshot per record) and writes it as a standalone JSONL
dump when the run dies, joining PR 4's stack dumps with the numbers that
preceded them.

Crash-path-only by construction: recording is an in-memory deque append on
the dispatch thread; the ONLY file this module ever writes is the dump, and
the only dump triggers are watchdog trip, NaN abort, coordinated stop, and
uncaught exceptions — so the default-flags JSONL event stream is untouched
(the parity contract) even though the recorder is on by default
(`--flight_recorder_steps`, 0 disables).

Dump format — one JSON object per line:

    {"kind": "flight_recorder", "reason": ..., "time": ..., "step": ...,
     "process": ..., "records": N, ...context/extra...}   # header
    {"step": ..., "gate": ..., "step_ms": ..., "metrics": {...},
     "counters": {...}}                                   # K records,
                                                          # oldest first

The header carries a partial `perf/startup/*` breakdown when the run died
before its first step (the StartupProfile satellite — a crash during
restore/warmup previously lost the phase timings entirely). Writes are
tmp+rename so a dump that itself crashed mid-write never parses as
complete, and a dump failure never masks the original error.

Thread contract: `record()` runs on the dispatch thread; `dump()` may run
on the dispatch thread (exception paths) or the watchdog thread (trip
path) — the ring is lock-guarded so a trip can snapshot it mid-append.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Callable, List, Optional, Tuple


def recorder_path(checkpoint_dir: str) -> str:
    """Per-process dump path: the chief owns the bare name; peers suffix
    their process index so a multi-host crash leaves one dump per host."""
    import jax

    idx = jax.process_index()
    name = "flight_recorder.jsonl" if idx == 0 \
        else f"flight_recorder.p{idx}.jsonl"
    return os.path.join(checkpoint_dir, name)


class FlightRecorder:
    """Fixed-size ring of per-step telemetry records + crash-path dump."""

    def __init__(self, path: str, *, capacity: int,
                 context: Optional[Callable[[], dict]] = None):
        self.path = path
        self.capacity = capacity
        self.enabled = capacity > 0 and bool(path)
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self._context = context
        self.dumps = 0
        # free-form context line owned by the trainer (the fleet health
        # plane parks its slowest-host attribution here so the dump-time
        # context callable can pick it up); plain str assignment — atomic
        self.note = ""

    def record(self, rec: dict) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._ring.append(rec)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def dump(self, reason: str, *, step: Optional[int] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write the dump; returns its path, or None (disabled, or the
        write itself failed — the crash path must never raise over the
        failure it is documenting). Last dump wins the filename: a
        stop-dump followed by an exception-dump leaves the later, more
        specific one."""
        if not self.enabled:
            return None
        header = {"kind": "flight_recorder", "reason": reason,
                  "time": time.time()}
        if step is not None:
            header["step"] = int(step)
        try:
            ctx = self._context() if self._context is not None else None
        except Exception:
            ctx = None
        header.update(ctx or {})
        header.update(extra or {})
        records = self.snapshot()
        header["records"] = len(records)
        tmp = self.path + ".tmp"
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                f.write(json.dumps(header) + "\n")
                for rec in records:
                    f.write(json.dumps(rec) + "\n")
            os.replace(tmp, self.path)
        except (OSError, TypeError, ValueError):
            return None
        self.dumps += 1
        return self.path


def read_dump(path: str) -> Tuple[dict, List[dict]]:
    """(header, records) of one dump — the drill/test parse helper."""
    with open(path) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    if not lines or lines[0].get("kind") != "flight_recorder":
        raise ValueError(f"{path} is not a flight-recorder dump")
    return lines[0], lines[1:]
