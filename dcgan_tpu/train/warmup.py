"""Warm-start subsystem: persistent compile cache + AOT pre-compilation.

PRs 3-4 made restarts the NORMAL response to faults (watchdog exit 43,
coordinated preemption stop, rollback recompiles), which moves the dominant
cost of a preemptible fleet from steady-state step time to startup: every
restart re-pays full XLA compilation of every program plus the checkpoint
read. The pjit/TPUv4 scaling work (PAPERS.md, arxiv 2204.06514) treats
compilation caching as first-class throughput infrastructure and ParaGAN
(arxiv 2411.03999) frames GAN efficiency as end-to-end goodput; this module
is that discipline for tpu-dcgan's time-to-first-step:

- `configure_compile_cache` wires JAX's persistent compilation cache behind
  `--compile_cache_dir` (config + CLI + `DCGAN_COMPILE_CACHE_DIR` env). The
  multi-host keying is safe by construction: JAX's cache layer only WRITES
  entries from process 0 (chief-writes) while every process reads, so one
  shared directory never sees write contention; for fleets without a shared
  filesystem, `--compile_cache_per_process` gives each process its own
  subdirectory instead (`proc<i>/` — same cache keys, disjoint stores).
  The min-compile-time threshold is dropped to 0: this trainer runs a
  handful of long-lived programs, every one of which is re-lowered on every
  restart, so "too cheap to cache" (JAX's default 1 s floor, tuned for
  jit-churn workloads) is the wrong default here.

- `CompileCacheMonitor` subscribes to JAX's monitoring events and turns
  them into the `perf/compile_cache_{requests,hits,misses}` counters the
  trainer surfaces as JSONL events — cache effectiveness is a recorded
  number per run, not a log grep.

- `build_warmup_plan` + `aot_compile` are the explicit AOT warmup phase
  (`--aot_warmup`): every program the run can dispatch — the k=1 n_critic
  tail, the `steps_per_call` scan variant, the sampler/probe/summarize
  shapes, and the LR-backoff rebuild variant (`backoff_config`, shared with
  the trainer's rollback executor so the two constructions cannot drift) —
  is `.lower().compile()`d up front with per-program `perf/compile_ms/*`
  timings. With the persistent cache active, each warmup compile primes the
  cache entry the loop's live dispatch then deserializes, so first-dispatch
  cost is bounded IO, not compile — which is what lets the trainer's
  watchdog arm from warmup PROOF (mesh_warm + the `compiled_ks` exemption
  set) instead of waiting for first live steps.

Plan-row naming: the launch surface's rows carry plain program names;
variant surfaces suffix theirs so one plan can warm several compiled
surfaces without name collisions — `@lr_backoff` (the rollback rebuild,
this module), `@r<res>` (progressive phases, progressive/phases.py),
`@t<data>x<model>` (live-elasticity topologies, elastic/live.py). The
semantic tier's coverage rows (analysis/semantic.py, DCG009) pin the
suffixed names, so a renamed row is a lock diff, not a silent miss.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

CACHE_ENV_VAR = "DCGAN_COMPILE_CACHE_DIR"

#: monitoring event name -> counter key (the three adoption counters JAX's
#: compile path records around the persistent cache)
_EVENT_COUNTERS = {
    "/jax/compilation_cache/compile_requests_use_cache": "requests",
    "/jax/compilation_cache/cache_hits": "hits",
    "/jax/compilation_cache/cache_misses": "misses",
}
_SAVED_EVENT = "/jax/compilation_cache/compile_time_saved_sec"


def resolve_cache_dir(cfg_dir: str, env=None) -> str:
    """The effective cache dir: the config/CLI value, else the
    DCGAN_COMPILE_CACHE_DIR environment override, else "" (off)."""
    env = os.environ if env is None else env
    return cfg_dir or env.get(CACHE_ENV_VAR, "")


def configure_compile_cache(cache_dir: str, *,
                            per_process: bool = False) -> Optional[str]:
    """Point JAX's persistent compilation cache at `cache_dir`; returns the
    effective directory (per-process subdir under `per_process`) or None
    when caching stays off. Must run before the first compile — the trainer
    calls it right after `initialize_multihost()` (the per-process keying
    needs the real process index), before any program is built.
    """
    if not cache_dir:
        # explicit OFF: a previous train() in this process may have pointed
        # the GLOBAL jax cache somewhere — leaving it set would keep
        # deserializing executables in a run whose donation-safety guards
        # (trainer/rollback/checkpoint, keyed on the cache being active)
        # believe the cache is off
        if getattr(jax.config, "jax_compilation_cache_dir", None):
            jax.config.update("jax_compilation_cache_dir", None)
            _reset_cache_object()
        return None
    if per_process and jax.process_count() > 1:
        # no shared filesystem: disjoint per-process stores. Keys are
        # process-independent, so this trades dedup for zero cross-host
        # filesystem assumptions. jaxlib <= 0.4.37 only WRITES cache
        # entries from process 0, so non-chief stores stay empty (reads
        # are harmless) — the trainer excludes this mode from watchdog
        # warm proof and warns, rather than arming deadlines over peers
        # that will in fact recompile.
        cache_dir = os.path.join(cache_dir, f"proc{jax.process_index()}")
    changed = getattr(jax.config, "jax_compilation_cache_dir",
                      None) != cache_dir
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache EVERY program: this trainer compiles a handful of long-lived
    # programs per run, all re-lowered on every restart — the "skip cheap
    # compiles" defaults exist for jit-churn workloads, not this shape
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if changed:
        # jax memoizes the cache OBJECT on first use; without this a
        # process that re-points the dir keeps reading/writing the old one
        _reset_cache_object()
    return cache_dir


def _reset_cache_object() -> None:
    """Drop jax's memoized persistent-cache object so the current
    `jax_compilation_cache_dir` value takes effect (jax initializes the
    object lazily ONCE and never re-reads the config)."""
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:
        pass  # future jax: internal module moved; first-use init wins


def cache_serves_all_processes(per_process: bool) -> bool:
    """Whether a warm restart can expect cache HITS on every process —
    the condition watchdog warm proof rides on. True for single-process
    and for the shared-dir multi-host mode (the chief writes during its
    AOT compiles, the warmup barrier orders those writes before any peer's
    live dispatch reads them). False for per-process dirs under multi-host
    on jaxlib <= 0.4.37: only process 0's store is ever written, so every
    other process recompiles at first live dispatch no matter how warm its
    warmup looked."""
    return jax.process_count() == 1 or not per_process


class CompileCacheMonitor:
    """Counts persistent-cache adoption through jax.monitoring.

    The counters are process-local and monotonic from construction;
    `counters()` snapshots them, `delta(since)` diffs two snapshots (the
    trainer brackets phases with it). `close()` unregisters the listeners —
    required in multi-`train()` processes (tests, drills) or each monitor
    would keep double-counting forever.
    """

    def __init__(self) -> None:
        from jax._src import monitoring

        self._monitoring = monitoring
        self._counts: Dict[str, int] = {k: 0 for k in
                                        _EVENT_COUNTERS.values()}
        self._saved_secs = 0.0
        self._closed = False

        def _on_event(event: str, **kw) -> None:
            key = _EVENT_COUNTERS.get(event)
            if key is not None:
                self._counts[key] += 1

        def _on_duration(event: str, duration_secs: float, **kw) -> None:
            if event == _SAVED_EVENT:
                self._saved_secs += duration_secs

        self._on_event = _on_event
        self._on_duration = _on_duration
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)

    def counters(self) -> Dict[str, float]:
        out: Dict[str, float] = dict(self._counts)
        out["saved_ms"] = self._saved_secs * 1e3
        return out

    @staticmethod
    def delta(now: Dict[str, float],
              since: Dict[str, float]) -> Dict[str, float]:
        return {k: now[k] - since.get(k, 0) for k in now}

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for unreg, cb in (
                (self._monitoring._unregister_event_listener_by_callback,
                 self._on_event),
                (self._monitoring
                 ._unregister_event_duration_listener_by_callback,
                 self._on_duration)):
            try:
                unreg(cb)
            except Exception:
                pass  # listener registry changed under us — nothing to leak


def backoff_config(cfg, scale: float):
    """The rollback LR-backoff TrainConfig variant — ONE construction shared
    by the trainer's rollback executor and the warmup plan, so the program
    the warmup pre-compiles is bit-identical (same HLO constants, same cache
    key) to the one a live rollback rebuilds."""
    import dataclasses

    def _bk(lr):
        return None if lr is None else lr * scale

    return dataclasses.replace(
        cfg, learning_rate=cfg.learning_rate * scale,
        d_learning_rate=_bk(cfg.d_learning_rate),
        g_learning_rate=_bk(cfg.g_learning_rate))


def _identity_copy():
    """A jit of the SAME identity lambda rollback.device_copy and the
    checkpoint rebase compile — byte-identical HLO, so one persistent-cache
    entry serves all three jit objects. Deliberately a FRESH jit per call:
    a memoized object would serve repeat warmups from its in-memory AOT
    cache and skip the persistent-cache write a newly-pointed cache dir
    needs (multi-`train()` processes — tests, drills)."""
    return jax.jit(lambda t: jax.tree_util.tree_map(lambda a: a + 0, t))


def state_example(pt):
    """The train-state example argument for `.lower()`ing `pt`'s programs
    WITHOUT allocating it: sharded ShapeDtypeStructs from `eval_shape` over
    `pt.init` + `pt.shardings`. The warmup plan itself receives the live
    state from the trainer; the semantic analyzer (ISSUE 11) lowers the
    same plan pre-allocation, so the derivation lives here where the plan
    is built and the two callers cannot shape-drift. The lambda matters:
    under the armed tripwire pt.init is a _GuardedFn, which eval_shape
    cannot weakref — a plain closure can."""
    shapes = jax.eval_shape(lambda k: pt.init(k), jax.random.key(0))
    return jax.tree_util.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, pt.shardings)


def _program_args(cfg, pt, state, *, sample_z=None, sample_labels=None,
                  eval_z=None) -> List[Tuple[str, Callable, tuple]]:
    """(name, jitted fn, example args) for every program `pt` can dispatch
    this run, with the trainer's exact live shapes/shardings: images as
    sharded ShapeDtypeStructs (never allocated), z/labels/state as the
    concrete arrays the loop itself feeds."""
    import jax.numpy as jnp

    from dcgan_tpu.parallel import batch_sharding

    mesh = pt.mesh
    size = cfg.model.output_size
    img_sh = batch_sharding(mesh, 4, spatial=cfg.mesh.spatial)
    img = jax.ShapeDtypeStruct(
        (cfg.batch_size, size, size, cfg.model.c_dim), jnp.float32,
        sharding=img_sh)
    conditional = cfg.model.num_classes > 0
    key = jax.random.key(0)
    lbls = (jax.ShapeDtypeStruct((cfg.batch_size,), jnp.int32,
                                 sharding=batch_sharding(mesh, 1)),) \
        if conditional else ()

    def _scan_sds(sds, k):
        return jax.ShapeDtypeStruct(
            (k,) + sds.shape, sds.dtype,
            sharding=jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(None, *sds.sharding.spec)))

    if cfg.pipeline_gd:
        # pipelined dispatch (ISSUE 7): the loop runs the three stage
        # programs, never the fused step — warm exactly what it dispatches.
        # The fake stack example arg is a ShapeDtypeStruct with the
        # slot-axis-in-front scan sharding (batch on axis 1), the shape
        # gen_fakes/g_update produce and d_update consumes.
        fakes = _scan_sds(img, cfg.n_critic)
        step_programs: List[Tuple[str, Callable, tuple]] = [
            ("gen_fakes", pt.programs["gen_fakes"], (state, key)),
            ("d_update", pt.programs["d_update"],
             (state, img, fakes, key)),
            ("g_update", pt.programs["g_update"], (state, key)),
        ]
    else:
        step_programs = [("train_step", pt.programs["train_step"],
                          (state, img, key) + lbls)]
    programs: List[Tuple[str, Callable, tuple]] = step_programs + [
        # the state-tree identity copy: the program behind BOTH the
        # checkpoint restore's buffer rebase (utils/checkpoint.py) and the
        # rollback device-resident snapshot (train/rollback.device_copy) —
        # same lambda, same HLO, one cache entry serves all three jit
        # objects, so a warm restart's restore-time rebase deserializes
        # instead of being the one cold compile left on the restart path
        ("state_copy", _identity_copy(), (state,)),
    ]
    k = cfg.steps_per_call
    if k > 1:
        scan_img = _scan_sds(img, k)
        keys = jax.vmap(jax.random.fold_in, (None, 0))(key, jnp.arange(k))
        scan_lbls = (_scan_sds(lbls[0], k),) if conditional else ()
        programs.append((f"multi_step@k{k}", pt.programs["multi_step"],
                         (state, scan_img, keys) + scan_lbls))
    if sample_z is not None:
        s_lbls = (sample_labels,) if sample_labels is not None else ()
        programs.append(("sampler", pt.programs["sampler"],
                         (state, sample_z) + s_lbls))
    if eval_z is not None:
        programs.append(("eval_losses", pt.programs["eval_losses"],
                         (state, img, eval_z) + lbls))
    if cfg.activation_summary_steps:
        programs.append(("summarize", pt.programs["summarize"],
                         (state, img, key) + lbls))
    return programs


def build_warmup_plan(cfg, pt, state, *, sample_z=None, sample_labels=None,
                      eval_z=None, make_backoff_pt: Optional[Callable] = None
                      ) -> Tuple[List[Tuple[str, Callable, tuple]],
                                 Optional[Any]]:
    """Every (name, program, args) this run can dispatch, plus — when the
    run arms `rollback_lr_backoff` — a fully-built ParallelTrain for the
    FIRST rollback's LR scale whose step programs join the plan, so a live
    rollback swaps in a pre-warmed surface instead of recompiling mid-
    recovery. `make_backoff_pt` maps the backoff TrainConfig to that
    surface (the trainer passes make_parallel_train pinned to its mesh)."""
    plan = _program_args(cfg, pt, state, sample_z=sample_z,
                         sample_labels=sample_labels, eval_z=eval_z)
    pt_backoff = None
    if (cfg.nan_policy == "rollback" and cfg.rollback_lr_backoff < 1.0
            and make_backoff_pt is not None):
        pt_backoff = make_backoff_pt(
            backoff_config(cfg, cfg.rollback_lr_backoff))
        for name, fn, args in _program_args(
                cfg, pt_backoff, state, sample_z=sample_z,
                sample_labels=sample_labels, eval_z=eval_z):
            # only the step programs rebuild on rollback; sampler/probe/
            # summarize are LR-independent (identical HLO, already planned).
            # Under --pipeline_gd the step programs are the d_update/
            # g_update stages (optimizer constants bake the LR in);
            # gen_fakes is LR-independent like the sampler
            if name.startswith(("train_step", "multi_step",
                                "d_update", "g_update")):
                plan.append((f"{name}@lr_backoff", fn, args))
    return plan, pt_backoff


def aot_compile(plan: List[Tuple[str, Callable, tuple]],
                ) -> Dict[str, float]:
    """`.lower().compile()` every planned program; {name: compile_ms}.

    Each compile lands in the persistent cache (when configured), so the
    loop's live dispatch of the same program deserializes instead of
    compiling — warmup converts unbounded compile time into bounded IO at
    a point where nothing is blocked on it.
    """
    timings: Dict[str, float] = {}
    for name, fn, args in plan:
        t0 = time.perf_counter()
        fn.lower(*args).compile()
        timings[name] = (time.perf_counter() - t0) * 1e3
    return timings
