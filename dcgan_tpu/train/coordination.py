"""Multi-host coordination: the primitives that turn per-process recovery
decisions into deterministic collective agreements (ISSUE 4 tentpole).

PR 3's fail-operational layer was single-process by construction: every
recovery branch was a local `if`, and a local `if` on one host of a
multi-host job is a deadlock generator — the other hosts keep dispatching
collectives the branching host never joins. ParaGAN (PAPERS.md, arxiv
2411.03999) and the pjit/TPUv4 scaling work (arxiv 2204.06514) both land on
the same discipline this module implements: any decision that changes which
collectives run next must itself be a collective, taken at a step boundary
every process reaches, and every blocking collective needs a deadline so a
lost peer fails the job fast instead of hanging it forever.

Three primitives, each a cheap no-op in single-process runs:

- `anomaly_consensus(local_bad)` — allgathers each process's NaN-gate
  verdict (one int32 per process) so all hosts take the identical
  abort/rollback branch, even when the non-finite value is visible on one
  host only (a host-side readback fault, or a per-process chaos plan).
- `CoordinatedStop` — SIGTERM/SIGINT on *any* host sets a process-local
  flag; `poll()` allgathers the flags at each step boundary, so the whole
  job agrees to break together and runs the existing *collective* final
  save. This is what makes a TPU-VM preemption notice a resumable stop on
  real topologies — PR 3 had to skip signal handling entirely under
  multi-host because a one-host save would deadlock the collective.
- `CollectiveWatchdog` — a daemon thread arms a deadline around each
  dispatch/save/consensus section; on expiry it dumps per-process
  diagnostics (process index, step, phase, every thread's live stack) to
  stderr and exits nonzero (`WATCHDOG_EXIT_CODE`) so the supervising
  launcher restarts the job from the last checkpoint instead of burning
  accelerator-hours in a hung allreduce.

Testability: the collective transport is the module-level `_allgather_i32`
(tests shim it together with `jax.process_count` — no subprocess needed),
and the watchdog takes an `on_trip` hook so units can observe a trip
without the process exiting.
"""

from __future__ import annotations

import faulthandler
import os
import signal
import sys
import threading
import time
from typing import Callable, List, Optional, Tuple

import jax
import numpy as np

# Distinct from any Python/launcher default so a supervisor (and the chaos
# drill) can attribute the exit to the watchdog specifically.
WATCHDOG_EXIT_CODE = 43

#: DCG008 census declarations for the host-side collective transports
#: (ISSUE 11). `multihost_utils.process_allgather` is opaque to `.lower()`
#: (its collective is inserted when jax reshards the host-local array), so
#: unlike the jit programs these rows cannot be counted from a jaxpr — they
#: are declared HERE, next to the transport code, and flow into the
#: committed program manifest (analysis/programs.lock.jsonl) and DESIGN
#: §6c.1's generated dispatch-stream table. The semantic tier cross-checks
#: each entry's transport function still exists in this module, and the
#: tripwire wraps the same names — three systems, one declaration.
#: Rows: name -> (transport fn, {collective op: count}, default-knob cadence).
TRANSPORT_CENSUS = {
    "stop_consensus": ("_allgather_i32", {"all_gather": 1},
                       "every step boundary (multi-host, `--coord_stop` "
                       "default on; single-process: local flag, no "
                       "collective)"),
    "anomaly_consensus": ("_allgather_i32", {"all_gather": 1},
                          "every `nan_check_steps`-th boundary "
                          "(multi-host, BOTH nan policies; single-process: "
                          "local verdict, no collective)"),
    "fleet_health": ("_allgather_f32", {"all_gather": 1},
                     "every `fleet_health_steps`-th boundary (default 0 = "
                     "off; single-process: 1-row table, no collective)"),
    "notice_consensus": ("_allgather_i32", {"all_gather": 1},
                         "every step boundary when `--elastic_target_"
                         "devices` arms live elasticity (default 0 = off; "
                         "single-process: local verdict, no collective)"),
}


#: env knob: a file path; when set, every REAL multi-host collective the
#: transports below run appends its logical op name (one line per
#: collective, `.{process_index}` suffixed per process). The protocol
#: tier's replay contract (ISSUE 14): tools/chaos_drill.py arms this in
#: the mh-sigterm-stop scenario and compares both processes' logged
#: sequences against the committed simulator schedule in
#: analysis/protocol.lock.jsonl — the proof the simulated trainer mirror
#: and the live trainer issue the same collective stream. Off (unset) in
#: production: zero IO, zero branches beyond one env read.
SCHEDULE_LOG_ENV = "DCGAN_PROTOCOL_LOG"


def _sched_log(op: str) -> None:
    """Append one logical collective-op line to the replay log, if armed.
    Best-effort by contract — observation must never break the protocol
    it observes."""
    path = os.environ.get(SCHEDULE_LOG_ENV, "")
    if not path:
        return
    try:
        with open(f"{path}.{jax.process_index()}", "a",
                  encoding="utf-8") as f:
            f.write(op + "\n")
    except OSError:
        pass


def _allgather_i32(value: int) -> np.ndarray:
    """One int32 from every process, index-ordered. The single collective
    primitive everything here is built from — kept module-level so tests
    can shim the transport without a real multi-process job."""
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray(value, np.int32))
    return np.asarray(gathered).reshape(-1)


def _allgather_f32(vec: np.ndarray) -> np.ndarray:
    """One float32 vector from every process, index-ordered [P, F] — the
    fleet-health transport (ISSUE 6). Module-level like `_allgather_i32`
    so tests shim it without a real multi-process job."""
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray(vec, np.float32))
    return np.asarray(gathered).reshape(jax.process_count(), -1)


#: order of the per-host fleet health vector's slots (ISSUE 6). Every
#: process builds its local vector in this order on the DISPATCH thread
#: (collective-thread rule: the allgather below is a mesh-wide collective)
#: and the chief materializes the fleet/* metrics from the gathered table.
HEALTH_FIELDS = ("step", "step_ms_mean", "host_ms_mean", "queue_depth",
                 "dropped", "rollbacks", "corrupt_records", "phase")


def fleet_health_gather(vec) -> np.ndarray:
    """Allgather one health vector per host -> [P, F] table, identical on
    every process. Single-process: the local vector as a 1-row table, no
    collective — the same shape so the metric path is testable on CPU."""
    local = np.asarray(vec, np.float32).reshape(1, -1)
    if jax.process_count() == 1:
        return local
    _sched_log("fleet_health")
    return _allgather_f32(local.ravel())


def fleet_metrics(table: np.ndarray) -> Tuple[dict, str]:
    """(fleet/* scalar row, slowest-host note) from a gathered [P, F]
    health table (HEALTH_FIELDS order).

    Straggler skew is max/min of the per-host windowed step_ms_mean — the
    fleet-level number "Scalable Training of LMs using pjit" treats as a
    first-class operational signal; the note names the slowest host so a
    watchdog trip header can point at the likely wedged peer.
    """
    table = np.asarray(table, np.float64)
    ms = table[:, HEALTH_FIELDS.index("step_ms_mean")]
    slowest = int(np.argmax(ms))
    col = {name: table[:, i] for i, name in enumerate(HEALTH_FIELDS)}
    row = {
        "fleet/step_ms_max": float(ms.max()),
        "fleet/step_ms_min": float(ms.min()),
        "fleet/step_ms_skew": float(ms.max() - ms.min()),
        "fleet/slowest_host": float(slowest),
        "fleet/host_ms_max": float(col["host_ms_mean"].max()),
        "fleet/queue_depth_max": float(col["queue_depth"].max()),
        "fleet/dropped_total": float(col["dropped"].sum()),
        "fleet/rollbacks_total": float(col["rollbacks"].sum()),
        "fleet/corrupt_total": float(col["corrupt_records"].sum()),
        # the active progressive-schedule phase (ISSUE 15; 0 in fixed-
        # resolution runs). max == min by construction — the switch is
        # step-keyed, so a fleet split across phases is a protocol bug
        # worth seeing in the row
        "fleet/phase": float(col["phase"].max()),
    }
    note = (f"slowest host: process {slowest} "
            f"(step_ms_mean {ms[slowest]:.1f} vs fleet min {ms.min():.1f})")
    return row, note


def anomaly_consensus(local_bad: bool) -> Tuple[bool, List[int]]:
    """Agree on the NaN-gate verdict: (any process tripped, which ones).

    Every process must call this at the same gate invocation (the gate
    cadence is step-keyed, so they do); the return value is identical on
    every process, which is what keeps the downstream abort/rollback
    branch — and every collective it issues — mesh-consistent.
    """
    if jax.process_count() == 1:
        return bool(local_bad), [0] if local_bad else []
    _sched_log("anomaly_consensus")
    gathered = _allgather_i32(int(bool(local_bad)))
    return bool(gathered.any()), [int(i) for i in np.nonzero(gathered)[0]]


def warmup_barrier(tag: str = "aot-warmup") -> None:
    """Block until EVERY process has finished its AOT warmup phase.

    The warm proof the watchdog's arming gate needs (ISSUE 5): compile time
    is per-process, so one host finishing ITS warmup says nothing about its
    peers — but every host returning from this barrier proves no peer can
    still be inside a first compile, which is exactly the startup-skew
    hazard `mesh_warm` exists to wait out. Free in single-process runs; the
    trainer only calls it when `--aot_warmup` is on, so default dispatch
    streams gain no collective.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    _sched_log("warmup_barrier")
    multihost_utils.sync_global_devices(tag)


def notice_consensus(local: int) -> Tuple[int, List[int]]:
    """Agree on a preemption/capacity notice: (agreed verdict, raisers).

    The live-elasticity analogue of `CoordinatedStop.poll` (ISSUE 18): a
    scheduler's advance notice lands on ONE host (touch-file, SIGUSR1, or a
    chaos plan), but a mesh shrink is a collective act — every process must
    take the identical switch branch at the identical step boundary, or the
    survivors dispatch collectives the leaver never joins. `local` is this
    process's verdict (0 none / 1 grow / 2 shrink — the
    testing/chaos NOTICE_* encoding); the return is identical on every
    process: the max verdict (shrink outranks grow outranks none, so a
    simultaneous shrink+grow resolves to the safe direction) plus the
    processes that raised it. Single-process: the local verdict, no
    collective — same shape, so the switch path is testable on CPU.
    """
    if jax.process_count() == 1:
        return int(local), [0] if local else []
    _sched_log("notice_consensus")
    gathered = _allgather_i32(int(local))
    if not gathered.any():
        return 0, []
    return (int(gathered.max()),
            [int(i) for i in np.nonzero(gathered)[0]])


class CoordinatedStop:
    """Signal-flag consensus for a resumable whole-job stop.

    `install()` registers one-shot SIGTERM/SIGINT handlers that only set a
    process-local flag (async-signal-safe; the handler restores default
    semantics on first delivery so a second signal can still kill a hung
    final save). `poll()` runs at each step boundary on every process:
    single-process it reads the local flag; multi-host it allgathers the
    flags, so the job breaks in unison and the final save stays a valid
    collective. Handlers are installed only on the main thread (signal
    module constraint) and restored by `restore()` in the trainer's
    finally block.
    """

    def __init__(self) -> None:
        self._signal_num: Optional[int] = None
        self._restore: dict = {}

    def install(self) -> None:
        if threading.current_thread() is not threading.main_thread():
            return
        def _on_signal(signum, frame):
            self._signal_num = signum
            for sig, handler in self._restore.items():
                signal.signal(sig, handler)

        for s in (signal.SIGTERM, signal.SIGINT):
            self._restore[s] = signal.signal(s, _on_signal)

    def restore(self) -> None:
        for s, h in self._restore.items():
            signal.signal(s, h)
        self._restore.clear()

    @property
    def local_signal(self) -> Optional[int]:
        return self._signal_num

    def poll(self) -> Tuple[Optional[int], List[int]]:
        """(agreed stop signal or None, processes that raised it).

        Multi-host this is one tiny allgather per step boundary — the
        price of never letting one host break out of a collective loop
        alone. The gathered value is identical on every process, so either
        the whole job breaks or none of it does.
        """
        local = self._signal_num or 0
        if jax.process_count() == 1:
            return (self._signal_num, [0] if self._signal_num else [])
        _sched_log("stop_consensus")
        gathered = _allgather_i32(local)
        if not gathered.any():
            return None, []
        # a deterministic representative signal (the max: SIGTERM beats
        # SIGINT) so every process logs/acts identically
        return (int(gathered.max()),
                [int(i) for i in np.nonzero(gathered)[0]])


class CollectiveWatchdog:
    """Deadline guard for sections that block on mesh-wide collectives.

    `guard(phase, step)` arms a deadline for the enclosed section and
    disarms it on exit. Expiry means some process never joined the
    collective this one is blocked in. TWO enforcement layers, because a
    hung runtime call does not reliably release the GIL:

    - a daemon thread checks the armed deadline every `poll_interval`
      seconds; on expiry it prints a diagnostic header (process, step,
      phase, seconds stuck), dumps every thread's live stack via
      faulthandler, and `os._exit`s with WATCHDOG_EXIT_CODE — the
      informative path, needs the GIL to run;
    - `faulthandler.dump_traceback_later` armed at `timeout_secs * 1.5 + 2`
      as the GIL-immune backstop: its timer lives in C, so even a blocked
      call that never yields the interpreter still gets its stacks dumped
      and the process exits nonzero (status 1 — faulthandler's fixed code).

    Either way the job dies loudly with per-process stack context instead
    of hanging forever; a restart from the last checkpoint is strictly
    better than an accelerator pod wedged in a dead allreduce.

    `on_trip(phase, step)` replaces both enforcement layers for unit tests.
    `pre_dump(phase, step)` runs on ANY trip — real or on_trip — before
    enforcement: the trainer hangs the flight recorder here (ISSUE 6) so a
    trip ships the telemetry ring alongside the stacks; it must never
    raise into the trip path, so failures are swallowed. `set_note()`
    attaches fleet context (the slowest-host line from the last health
    gather) to the trip header.
    """

    def __init__(self, timeout_secs: float, *,
                 poll_interval: Optional[float] = None,
                 on_trip: Optional[Callable[[str, int], None]] = None,
                 pre_dump: Optional[Callable[[str, int], None]] = None):
        if timeout_secs <= 0:
            raise ValueError(
                f"timeout_secs must be > 0, got {timeout_secs}")
        self.timeout_secs = timeout_secs
        self._backstop_secs = timeout_secs * 1.5 + 2.0
        self._poll = poll_interval if poll_interval is not None \
            else max(0.05, min(1.0, timeout_secs / 4))
        self._on_trip = on_trip
        self._pre_dump = pre_dump
        self._note = ""
        self._lock = threading.Lock()
        self._deadline: Optional[float] = None
        self._phase = ""
        self._step = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="dcgan-collective-watchdog", daemon=True)
        self._thread.start()

    def _set_backstop(self, seconds: Optional[float]) -> None:
        """(Re)arm or cancel the C-level faulthandler timer. Process-global
        by nature — one watchdog instance per process, which the trainer
        guarantees."""
        if self._on_trip is not None:
            return  # unit tests must not arm a process-killing timer
        if seconds is None:
            faulthandler.cancel_dump_traceback_later()
        else:
            faulthandler.dump_traceback_later(
                max(0.1, seconds), repeat=False, file=sys.stderr, exit=True)

    def arm(self, phase: str, step: int) -> tuple:
        """Start (or refresh) the deadline; returns the previous
        (deadline, phase, step) so nested guards can restore it."""
        with self._lock:
            prev = (self._deadline, self._phase, self._step)
            self._deadline = time.monotonic() + self.timeout_secs
            self._phase = phase
            self._step = int(step)
            self._set_backstop(self._backstop_secs)
            return prev

    def disarm(self) -> None:
        with self._lock:
            self._deadline = None
            self._set_backstop(None)

    def _restore(self, prev: tuple) -> None:
        with self._lock:
            self._deadline, self._phase, self._step = prev
            self._set_backstop(
                None if self._deadline is None
                else max(0.1, self._deadline - time.monotonic())
                + (self._backstop_secs - self.timeout_secs))

    def set_note(self, note: str) -> None:
        """Context line for the trip header (e.g. the fleet health
        plane's slowest-host attribution); plain assignment — atomic."""
        self._note = note

    def guard(self, phase: str, step: int) -> "_WatchdogGuard":
        return _WatchdogGuard(self, phase, step)

    def close(self) -> None:
        self.disarm()
        self._stop.set()
        self._thread.join(timeout=5.0)

    # -- watchdog thread -----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            with self._lock:
                deadline, phase, step = self._deadline, self._phase, \
                    self._step
            if deadline is None or time.monotonic() < deadline:
                continue
            if self._pre_dump is not None:
                # flight-recorder hook: best-effort, BEFORE enforcement —
                # a failing dump must not stop the trip from killing the
                # process (the whole point is dying instead of hanging)
                try:
                    self._pre_dump(phase, step)
                except Exception:
                    pass
            if self._on_trip is not None:
                self._on_trip(phase, step)
                self.disarm()  # a test hook keeps the process alive
                continue
            self._dump_and_exit(phase, step)

    def _dump_and_exit(self, phase: str, step: int) -> None:
        try:
            note = f" [{self._note}]" if self._note else ""
            print(f"[dcgan_tpu] hung-collective watchdog: process "
                  f"{jax.process_index()} stuck > {self.timeout_secs:.1f}s "
                  f"in phase {phase!r} at step {step}{note} — dumping all "
                  f"thread stacks and exiting {WATCHDOG_EXIT_CODE} so the "
                  f"job restarts from the last checkpoint instead of "
                  f"hanging", file=sys.stderr, flush=True)
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
            sys.stderr.flush()
        finally:
            os._exit(WATCHDOG_EXIT_CODE)


class _WatchdogGuard:
    """Arms on enter, RESTORES the previous arm state on exit — so a short
    guarded collective (the NaN-consensus allgather) nested inside a longer
    guarded section (the step dispatch/consume window) hands the deadline
    back instead of silently disarming the outer section."""

    __slots__ = ("_wd", "_phase", "_step", "_prev")

    def __init__(self, wd: CollectiveWatchdog, phase: str, step: int):
        self._wd = wd
        self._phase = phase
        self._step = step
        self._prev = None

    def __enter__(self):
        self._prev = self._wd.arm(self._phase, self._step)
        return self

    def __exit__(self, *exc):
        self._wd._restore(self._prev)
        return False


class _NullWatchdog:
    """`collective_timeout_secs=0`: every guard is a free no-op."""

    class _Guard:
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    _GUARD = _Guard()

    def arm(self, phase: str, step: int) -> None:
        pass

    def disarm(self) -> None:
        pass

    def set_note(self, note: str) -> None:
        pass

    def guard(self, phase: str, step: int):
        return self._GUARD

    def close(self) -> None:
        pass


#: A ready-made no-op guard for call sites that decide per-invocation
#: whether a section should run under the deadline (the trainer suppresses
#: arming until the mesh is proven warm — see `_guard` there).
NULL_GUARD = _NullWatchdog._GUARD


def make_watchdog(timeout_secs: float, **kw):
    """The trainer's one switch between a real deadline and the no-op."""
    return CollectiveWatchdog(timeout_secs, **kw) if timeout_secs > 0 \
        else _NullWatchdog()
