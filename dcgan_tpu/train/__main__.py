from dcgan_tpu.train.cli import main

main()
