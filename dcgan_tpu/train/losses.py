"""GAN losses.

`bce_gan_losses` is the reference's loss trio (image_train.py:91-96):

    d_loss_real = BCE(D_logits(real), 1)
    d_loss_fake = BCE(D_logits(fake), 0)
    g_loss      = BCE(D_logits(fake), 1)        # non-saturating generator loss
    d_loss      = d_loss_real + d_loss_fake

computed from logits (numerically stable log-sigmoid form — the reference relies
on TF's `sigmoid_cross_entropy_with_logits` for the same reason).

`wgan_gp` is the BASELINE.json WGAN-GP variant: Wasserstein critic losses plus a
gradient penalty on interpolates, the grad-of-grad exercising `jax.grad` nesting
(and, under a sharded mesh, differentiation through the GSPMD-inserted psum —
SURVEY.md §7 hard part (c)).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def sigmoid_bce(logits: jax.Array, target: float) -> jax.Array:
    """Mean BCE-with-logits against a constant 0/1 target.

    log(1+e^-|x|) form: stable for large |logits|.
    """
    neg_abs = -jnp.abs(logits)
    loss = jnp.maximum(logits, 0.0) - logits * target + jnp.log1p(jnp.exp(neg_abs))
    return jnp.mean(loss)


def bce_gan_losses(real_logits: jax.Array, fake_logits: jax.Array, *,
                   label_smoothing: float = 0.0
                   ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (d_loss, d_loss_real, d_loss_fake, g_loss).

    label_smoothing > 0 softens D's REAL target to 1-eps (one-sided
    smoothing, Salimans et al. 2016 — the fake target and the generator's
    target stay hard, as the paper prescribes)."""
    d_loss_real = sigmoid_bce(real_logits, 1.0 - label_smoothing)
    d_loss_fake = sigmoid_bce(fake_logits, 0.0)
    g_loss = sigmoid_bce(fake_logits, 1.0)
    return d_loss_real + d_loss_fake, d_loss_real, d_loss_fake, g_loss


def wgan_losses(real_logits: jax.Array, fake_logits: jax.Array
                ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Wasserstein critic/generator losses (no penalty term).

    Returns (d_loss, d_loss_real, d_loss_fake, g_loss) with the same arity as
    `bce_gan_losses` so the train step is loss-agnostic.
    """
    d_loss_real = -jnp.mean(real_logits)
    d_loss_fake = jnp.mean(fake_logits)
    g_loss = -jnp.mean(fake_logits)
    return d_loss_real + d_loss_fake, d_loss_real, d_loss_fake, g_loss


def hinge_losses(real_logits: jax.Array, fake_logits: jax.Array
                 ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Geometric-GAN / SAGAN hinge losses (beyond-reference loss family):

        d_loss_real = E[relu(1 - D(real))]
        d_loss_fake = E[relu(1 + D(fake))]
        g_loss      = -E[D(fake)]

    Same arity as `bce_gan_losses` so the train step is loss-agnostic.
    """
    d_loss_real = jnp.mean(jax.nn.relu(1.0 - real_logits))
    d_loss_fake = jnp.mean(jax.nn.relu(1.0 + fake_logits))
    g_loss = -jnp.mean(fake_logits)
    return d_loss_real + d_loss_fake, d_loss_real, d_loss_fake, g_loss


def _sq_grad_norms(critic_fn: Callable[[jax.Array], jax.Array],
                   x: jax.Array) -> jax.Array:
    """Per-example squared input-gradient norms ||∇_x D(x)||^2, [B].
    The inner jax.grad sits under the outer d-loss grad in both penalty
    users — double differentiation."""
    grads = jax.grad(lambda x: jnp.sum(critic_fn(x)))(x)
    return jnp.sum(jnp.square(grads.astype(jnp.float32)),
                   axis=tuple(range(1, grads.ndim)))


def r1_penalty(critic_fn: Callable[[jax.Array], jax.Array],
               real: jax.Array) -> jax.Array:
    """R1 regularization (Mescheder et al. 2018, arXiv:1801.04406):
    E[||∇_x D(x)||^2] on REAL images only (zero-centered, no interpolates,
    no target norm — the modern default stabilizer, composing with the BCE
    and hinge families rather than replacing them like WGAN-GP does).
    The caller scales by gamma/2."""
    return jnp.mean(_sq_grad_norms(critic_fn, real))


def gradient_penalty(critic_fn: Callable[[jax.Array], jax.Array],
                     real: jax.Array, fake: jax.Array,
                     key: jax.Array) -> jax.Array:
    """WGAN-GP penalty E[(||∇_x D(x̂)|| - 1)^2] on x̂ = ε·real + (1-ε)·fake.

    `critic_fn` maps a batch of images to per-example logits [B].
    """
    eps = jax.random.uniform(key, (real.shape[0],) + (1,) * (real.ndim - 1),
                             dtype=real.dtype)
    interp = eps * real + (1.0 - eps) * fake
    norms = jnp.sqrt(_sq_grad_norms(critic_fn, interp) + 1e-12)
    return jnp.mean(jnp.square(norms - 1.0))
