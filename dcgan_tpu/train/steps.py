"""The GAN train step: two Adam optimizers, one compiled XLA program.

Reference semantics being replaced (image_train.py:109-112, 147-194): two
independent `AdamOptimizer(2e-4, β1=0.5).minimize` ops run in *one* sess.run on
the same batch, with numpy-fed z and a device→host→device image round-trip per
step (SURVEY.md §2.4 #2, #10). Here the whole step — z sampling, G forward,
D forward ×2, both backward passes, both Adam applies, BN EMA updates — is one
pure function built for `jax.jit(fn, donate_argnums=(0,))` (the trainer and
`__graft_entry__` compile it exactly that way): zero host round-trips, and z is
drawn on-device from a threaded PRNG key instead of `np.random.uniform` feeds
(image_train.py:151-152).

Two update modes (TrainConfig.update_mode):
- "sequential" (default): D updates on the current G, then G updates against the
  *updated* D — the canonical alternating GAN step the reference intended.
- "fused": both gradients are taken at the same (pre-update) params and both
  updates applied together — the reference's actual one-sess.run semantics,
  kept behind a flag for strict-parity experiments.

TrainConfig.n_critic > 1 (canonical WGAN-GP: 5) runs that many critic updates
per generator update as a lax.scan inside the same compiled program — fresh z
per critic iteration, same real batch, critic body compiled once.

Under jit-with-sharding (parallel/), gradient all-reduce and synced-BN moments
are inserted by GSPMD; for explicit-collective execution (shard_map) pass
`axis_name` and grads/metrics are pmean'd by hand. Both replace the reference's
per-worker async parameter-server pulls/pushes (image_train.py:55-67).

Pipelined stage split (ISSUE 7, ParaGAN's separable-stage framing): the same
step semantics factored into three independently-dispatchable programs —
`gen_fakes` (G forward producing a [n_critic, B, ...] fake stack, the fill/
refill program), `d_update` (the critic update(s) CONSUMING a provided fake
stack instead of regenerating it), and `g_update` (the generator update,
which RETURNS the fake stack it generated so the next step's `d_update` can
consume it at staleness 1). Per-step FLOPs are conservation-equal to the
fused program — every consumed fake is produced exactly once, and XLA
already CSEs the fused step's shared-z generator forward (cost-analysis-
verified; DESIGN.md §6f) — the split's wins are the largest program's
peak temp memory and the stage separation itself (cross-stage placement/
overlap substrate). The stage bodies reuse the exact loss/penalty/
accumulation code paths of the fused step (n_critic critic scan, grad_accum
microbatch scan), so the two surfaces cannot drift; only the fake batch's
PROVENANCE differs — fused regenerates per step, pipelined consumes the
stack produced during the previous step. The stack lives OUTSIDE the
checkpoint pytree (trainer-held device buffer): both modes save and restore
the identical state tree.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from dcgan_tpu.config import TrainConfig
from dcgan_tpu.models.dcgan import (
    discriminator_apply,
    gan_init,
    generator_apply,
    sampler_apply,
)
from dcgan_tpu.train import losses as L

Pytree = Any


def make_lr_schedule(cfg: TrainConfig, base_lr: float, *,
                     updates_per_step: int = 1):
    """Learning-rate schedule as an update-count -> lr callable.

    "constant" is the reference's fixed 2e-4 (image_train.py:11); "linear"
    and "cosine" decay to 0 over max_steps, with an optional linear warmup.
    Always returned as a callable — even for constant — so the optimizer
    state carries its count in every configuration and the checkpoint tree
    shape never depends on the schedule flags.

    `updates_per_step`: optax advances the schedule once per opt.update()
    call, and the critic updates n_critic times per generator step — the
    discriminator's schedule horizon is stretched by that factor so both
    nets decay on the same *trainer-step* timeline.
    """
    warmup = cfg.warmup_steps * updates_per_step
    decay_steps = max(1, cfg.max_steps * updates_per_step - warmup)
    if cfg.lr_schedule == "constant":
        main = optax.constant_schedule(base_lr)
    elif cfg.lr_schedule == "linear":
        main = optax.linear_schedule(base_lr, 0.0, decay_steps)
    else:  # cosine
        main = optax.cosine_decay_schedule(base_lr, decay_steps)
    if warmup:
        ramp = optax.linear_schedule(0.0, base_lr, warmup)
        return optax.join_schedules([ramp, main], [warmup])
    return main


def make_optimizer(cfg: TrainConfig, lr: Optional[float] = None, *,
                   updates_per_step: int = 1) -> optax.GradientTransformation:
    """Adam(lr=2e-4, β1=0.5, β2=0.999, ε=1e-8) — the reference's optimizer
    (image_train.py:109-112; β2/ε are TF AdamOptimizer defaults). `lr`
    overrides the base rate (TTUR per-net rates); the schedule applies on
    top of whichever base is used."""
    base_lr = cfg.learning_rate if lr is None else lr
    # Reduced-precision ladder (ISSUE 17): under bf16/fp8 params the Adam
    # FIRST moment is kept as an f32 master copy (mu_dtype) — it is a small
    # signed running mean whose bf16 rounding visibly biases updates. nu
    # (second moment) follows the param dtype: it is a variance consumed
    # through sqrt, where bf16's ~3 significant digits are plenty. mu_dtype
    # changes leaf DTYPES only, never the optimizer tree SHAPE, so the
    # checkpoint-structure contract below survives the ladder, and the
    # rule-engine specs (elastic/rules.py) shard mu like any same-shaped
    # param leaf.
    mu_dtype = jnp.float32 if cfg.precision in ("bf16", "fp8") else None
    adam = optax.adam(make_lr_schedule(cfg, base_lr,
                                       updates_per_step=updates_per_step),
                      b1=cfg.beta1, b2=0.999, eps=1e-8, mu_dtype=mu_dtype)
    # ALWAYS a 2-element chain: identity and clip_by_global_norm both carry
    # EmptyState, so the optimizer-state tree (and therefore the checkpoint
    # structure) is identical whatever grad_clip is — a clipped run's
    # checkpoint restores under generate/evals configs that never heard of
    # the flag (the same shape-invariance contract as ema_gen and the lr
    # schedule's count, above).
    clip = optax.clip_by_global_norm(cfg.grad_clip) if cfg.grad_clip > 0 \
        else optax.identity()
    return optax.chain(clip, adam)


def init_train_state(key, cfg: TrainConfig) -> Pytree:
    """Build the full training state pytree.

    The checkpointed logical set matches the reference's Saver contents
    (SURVEY.md §5: G/D weights, BN β/γ + running stats, Adam moments, step),
    plus an EMA copy of the generator weights.
    """
    params, bn = gan_init(key, cfg.model)
    opt_g = make_optimizer(cfg, cfg.g_learning_rate)
    opt_d = make_optimizer(cfg, cfg.d_learning_rate,
                           updates_per_step=cfg.n_critic)
    # ema_gen is ALWAYS part of the state so the checkpoint tree structure is
    # independent of cfg.g_ema_decay — a checkpoint trained with EMA on
    # restores under an eval/generate/resume config with it off (and vice
    # versa). With decay=0 it is just a live mirror (one G-param-tree write
    # per step, negligible next to the step's compute).
    return {
        "params": params,
        "bn": bn,
        "opt": {
            "gen": opt_g.init(params["gen"]),
            "disc": opt_d.init(params["disc"]),
        },
        "ema_gen": jax.tree_util.tree_map(jnp.copy, params["gen"]),
        "step": jnp.zeros((), jnp.int32),
    }


@dataclasses.dataclass(frozen=True)
class ZeroHooks:
    """ZeRO-2/3 layout hooks (ISSUE 13, arXiv:2004.13336): the three points
    where state sharding changes the weight-update computation's layout,
    injected by the parallel backends so the step bodies stay
    layout-agnostic. Every callable takes (tree, net) with net in
    {"gen", "disc"} (the EMA mirror rides the "gen" specs). With no hooks
    (zero_stage=1, the default) the step is bit-identical to the pre-ZeRO
    program — the parity contract the committed jaxpr fingerprints pin.

    reduce_grads: full per-replica gradient tree -> the optimizer's input.
        Replaces the gradient `_pmean` at EVERY site: gspmd constrains the
        grads to the rule engine's ZeRO grad specs (the partitioner lowers
        the cross-replica sum as a reduce-scatter); shard_map writes the
        `lax.psum_scatter` mean per sharded leaf explicitly (pmean for
        leaves the policy leaves replicated). The result shards exactly
        like the mu/nu moments, so Adam runs shard-local.
    gather_updates: the shard-local Adam update tree -> the resident
        params' layout. Stage 2: the ONE fused all-gather per update that
        rebuilds replicated params; stage 3: identity (params stay
        resident sharded).
    gather_params: resident params -> the full view a forward/grad needs.
        Stage 3's just-in-time all-gather (gspmd: a replication
        constraint, shard_map: explicit `lax.all_gather`); identity at
        stage 2, where params are already full between steps.

    The collective overlap plane (ISSUE 20, DESIGN §6n) swaps hook
    BODIES, never the seam: under `--comm_overlap bucket` the shard_map
    backend's reduce_grads/gather_updates pack leaves into dtype-grouped
    flat buffers (parallel/comm.py) so each hook issues one collective
    per bucket instead of one per leaf — and because each bucket's
    psum_scatter depends only on its own leaves' cotangents, the
    scheduler issues it while the rest of the backward is still running,
    instead of after the full walk. Under `--comm_overlap prefetch`
    (stage 3) gather_params becomes a layer-ahead staged walk whose
    optimization_barrier chain lets layer i+1's gather overlap layer i's
    compute. The step bodies cannot tell: every arm is bit-exact vs
    "off", and "off" leaves the original per-leaf bodies byte-identical
    (parity-pinned).
    """
    reduce_grads: Callable
    gather_updates: Callable
    gather_params: Callable


@dataclasses.dataclass(frozen=True)
class TrainStepFns:
    """Bundle of the compiled-surface functions for one TrainConfig."""
    train_step: Callable  # (state, images, key[, labels]) -> (state, metrics)
    sample: Callable      # (state, z[, labels]) -> images (EMA-stat BN)
    init: Callable        # (key,) -> state
    summarize: Callable   # (state, images, key[, labels]) -> per-layer
                          # activation histogram/sparsity stats (on device)
    eval_losses: Callable  # (state, images, z[, labels]) -> loss metrics,
                           # no state update — the reference's sample-batch
                           # loss probe (image_train.py:179-192)
    # pipelined stage programs (ISSUE 7; unconditional models only — the
    # trainer's --pipeline_gd validation enforces that):
    gen_fakes: Callable   # (state, key) -> [n_critic, B, H, W, C] fake
                          # stack — fresh z per critic slot, train-mode BN
                          # (updates discarded, like the fused D branch),
                          # constrain_fake applied. The FILL program: run
                          # start, restart, and rollback refill
    d_update: Callable    # (state, images, fakes, key) -> (state, metrics):
                          # the critic update(s) consuming a provided fake
                          # stack; touches ONLY the disc half of the state
                          # (params/opt/bn.disc) — gen/ema_gen/step ride
                          # through untouched, so the tree shape is the
                          # fused step's exactly
    g_update: Callable    # (state, key) -> (state, fakes, metrics): the
                          # generator update against the CURRENT D
                          # (sequential semantics — the trainer dispatches
                          # it after d_update), returning the fake stack it
                          # generated from its PRE-update weights as the
                          # next step's d_update input (staleness 1);
                          # increments state["step"]


def make_train_step(cfg: TrainConfig, *, axis_name: Optional[str] = None,
                    constrain_fake: Optional[Callable] = None,
                    constrain_micro: Optional[Callable] = None,
                    attn_mesh=None, pallas_mesh=None,
                    local_batch: Optional[int] = None,
                    zero_hooks: Optional[ZeroHooks] = None) -> TrainStepFns:
    """constrain_fake, if given, is applied to every generator output that is
    fed to the discriminator during training. The parallel layer passes a
    `with_sharding_constraint` to the real-image sharding here when the mesh
    spatially shards images: without it GSPMD is free to leave the fake branch
    replicated over the "model" axis while the real branch is height-sharded,
    and the partitioner then DOUBLE-COUNTS the fake branch's contribution to
    the shared conv-kernel gradients (observed ~2x grads on the 8-device CPU
    mesh; the constraint restores f64-level agreement — see
    tests/test_parallel.py::test_sharded_step_matches_single_device[dp4xsp2]).

    constrain_micro, if given, pins the (grad_accum, micro, ...) reshapes of
    the step inputs to scan-over-microbatches shardings (leading axis
    unsharded, batch sharded on axis 1) — without it the partitioner may
    shard the scan axis after the reshape, serializing the mesh.

    local_batch: the batch size the pipelined stage programs (gen_fakes /
    g_update, ISSUE 7) draw their z at. The fused step derives every batch
    shape from its `images` argument, but gen_fakes/g_update take no images
    — so the generator-side stages need the size stated. Defaults to
    cfg.batch_size (the global batch — correct under jit-with-sharding,
    where programs see global shapes); the shard_map backend passes its
    per-device batch instead, since each shard's program sees local shapes.

    zero_hooks (ISSUE 13): the ZeroHooks bundle a backend passes under
    zero_stage >= 2. None (the default) keeps every code path bit-identical
    to the pre-ZeRO step — the hooks' identity/default forms below ARE the
    original call sites, so the committed program fingerprints only move
    when the knob does.
    """
    mcfg = cfg.model
    opt_g = make_optimizer(cfg, cfg.g_learning_rate)   # TTUR-capable:
    opt_d = make_optimizer(cfg, cfg.d_learning_rate,   # per-net base rates
                           updates_per_step=cfg.n_critic)
    wgan = cfg.loss == "wgan-gp"
    r1 = cfg.r1_gamma > 0.0
    from dcgan_tpu.ops.augment import diff_augment, parse_policy
    aug_policy = parse_policy(cfg.diffaug)

    def _aug(x, key, idx):
        # DiffAugment on every D input; off (or the eval probe's aug-free
        # path, key=None) = identity. `idx` decorrelates the per-input
        # transform streams within one step — callers never fold keys
        # themselves, so a new call site cannot reuse a stream by accident.
        if not aug_policy or key is None:
            return x
        return diff_augment(x, jax.random.fold_in(key, idx), aug_policy)

    gan_losses = {
        "gan": functools.partial(L.bce_gan_losses,
                                 label_smoothing=cfg.label_smoothing),
        "wgan-gp": L.wgan_losses,
        "hinge": L.hinge_losses}[cfg.loss]
    _cf = constrain_fake if constrain_fake is not None else (lambda x: x)

    def _pmean(x):
        return lax.pmean(x, axis_name) if axis_name is not None else x

    # --- ZeRO layout hooks (ISSUE 13): resolved ONCE here so every
    # gradient/update/forward site below reads layout-agnostic names. The
    # defaults reproduce the pre-ZeRO program exactly: reduce_grads is the
    # gradient _pmean, the two gathers are python identity (same tracer
    # out, no jaxpr change).
    if zero_hooks is None:
        def _reduce_grads(g, net):
            return _pmean(g)

        def _gather_updates(u, net):
            return u

        def _gather_params(p, net):
            return p
    else:
        _reduce_grads = zero_hooks.reduce_grads
        _gather_updates = zero_hooks.gather_updates
        _gather_params = zero_hooks.gather_params

    def _opt_arg(p):
        # optax.update's `params` argument: our chain (clip + adam) never
        # reads it, but under ZeRO the grads are SHARDS while the resident
        # params may be full (stage 2) — pass None rather than a
        # shape-mismatched tree a future transform might consume
        return None if zero_hooks is not None else p

    # --- grad_accum microbatch helpers, shared by the fused accum step and
    # the pipelined stage bodies (ISSUE 7) so the accumulate-in-f32 /
    # average-then-pmean semantics are single-sourced ----------------------

    def _split_micro(x):
        """(B, ...) -> (grad_accum, micro, ...) with the scan-axis sharding
        constraint applied (see constrain_micro above)."""
        K = cfg.grad_accum
        out = x.reshape(K, x.shape[0] // K, *x.shape[1:])
        return constrain_micro(out) if constrain_micro is not None else out

    def _zeros_f32(tree):
        # accumulate in f32 whatever the param dtype: K bf16 adds would
        # round away low-magnitude contributions
        return jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), tree)

    def _acc(acc, grads):
        return jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), acc, grads)

    def _avg(acc, like, net):
        return _reduce_grads(jax.tree_util.tree_map(
            lambda a, p: (a / cfg.grad_accum).astype(p.dtype), acc, like),
            net)

    def _critic_streams(iter_key, batch):
        """Per-critic-iteration randomness: fresh z against the same real
        batch, the gradient-penalty key, and the DiffAugment key. One
        definition shared by the accum and non-accum critic loops so their
        training semantics cannot silently desynchronize."""
        zk, gpk = jax.random.split(iter_key)
        aug_k = jax.random.fold_in(iter_key, 3) if aug_policy else None
        z_i = jax.random.uniform(zk, (batch, mcfg.z_dim),
                                 minval=-1.0, maxval=1.0, dtype=jnp.float32)
        return z_i, gpk, aug_k

    def _zero_metric():
        # Under shard_map (axis_name set) the critic-scan metric carry must
        # be data-axis-VARYING to match the loop body's per-device metric
        # outputs — an unvarying f32 zero fails the scan's carry-type check
        # at trace time. `lax.pcast` only exists once the VMA type system
        # graduated (jax >= 0.6); this container's 0.4.37 experimental
        # shard_map has no replicated->varying cast, and its check_rep
        # tracker accepts the plain replicated zero as a carry init — so
        # fall back to it instead of crashing every shard_map stage-program
        # trace at `lax.pcast` (caught by the semantic analyzer, DCG009).
        z0 = jnp.zeros((), jnp.float32)
        pcast = getattr(lax, "pcast", None)
        return pcast(z0, axis_name, to="varying") \
            if (axis_name and pcast is not None) else z0

    def _d_metrics(d_loss, d_real, d_fake, gp) -> dict:
        # the discriminator half of the step's metric row — the fused
        # assembly below and the pipelined d_update stage both build from
        # this, so the two surfaces report identical keys; the gp slot
        # carries whichever penalty the config runs (WGAN-GP or R1)
        metrics = {
            "d_loss": _pmean(d_loss),
            "d_loss_real": _pmean(d_real),
            "d_loss_fake": _pmean(d_fake),
        }
        if wgan:
            metrics["gp"] = _pmean(gp)
        elif r1:
            metrics["r1"] = _pmean(gp)
        return metrics

    def _loss_metrics(d_loss, d_real, d_fake, g_loss, gp) -> dict:
        # one assembly for train_step and eval_losses so the sample/* probe
        # can never silently diverge from the training metrics. (Key ORDER
        # is irrelevant: jitted outputs flatten through the dict pytree,
        # which sorts keys.)
        metrics = _d_metrics(d_loss, d_real, d_fake, gp)
        metrics["g_loss"] = _pmean(g_loss)
        return metrics

    def d_loss_fn(d_params: Pytree, g_params: Pytree, bn: Pytree,
                  images: jax.Array, z: jax.Array, gp_key,
                  labels, step=0, r1_every_step=False,
                  aug_key=None) -> Tuple[jax.Array, Tuple]:
        fake, _ = generator_apply(g_params, bn["gen"], z, cfg=mcfg, train=True,
                                  labels=labels, axis_name=axis_name,
                                  attn_mesh=attn_mesh, pallas_mesh=pallas_mesh)
        fake = _cf(fake)
        return _d_loss_on_fake(d_params, bn, images, fake, gp_key, labels,
                               step, r1_every_step, aug_key)

    def _d_loss_on_fake(d_params: Pytree, bn: Pytree, images: jax.Array,
                        fake: jax.Array, gp_key, labels, step=0,
                        r1_every_step=False,
                        aug_key=None) -> Tuple[jax.Array, Tuple]:
        """The D loss on an ALREADY-MATERIALIZED fake batch — the shared
        body of the fused step (which generates `fake` just above) and the
        pipelined d_update stage (which consumes the previous step's
        device-resident stack), so the two can never diverge on loss,
        penalty, or BN-chaining semantics."""
        # D sees real then fake, chaining BN state through both applications —
        # the functional analogue of the reference's two discriminator() calls
        # with reuse=True (image_train.py:82,85). Each D input is
        # independently DiffAugmented when the policy is on.
        _, real_logits, d_bn1 = discriminator_apply(
            d_params, bn["disc"], _aug(images, aug_key, 0),
            cfg=mcfg, train=True, labels=labels,
            axis_name=axis_name, attn_mesh=attn_mesh, pallas_mesh=pallas_mesh)
        _, fake_logits, d_bn2 = discriminator_apply(
            d_params, d_bn1, _aug(fake, aug_key, 1),
            cfg=mcfg, train=True, labels=labels,
            axis_name=axis_name, attn_mesh=attn_mesh, pallas_mesh=pallas_mesh)
        d_loss, d_real, d_fake = gan_losses(real_logits, fake_logits)[:3]
        gp = jnp.zeros((), jnp.float32)
        if wgan or r1:
            # Penalty critic runs with train=False (running BN stats):
            # batch-stat BN couples D(x_i) to every x_j in the batch, which
            # would contaminate the per-example ||grad_x D(x)|| both
            # penalties are defined on. Penalties act on the RAW inputs —
            # the Lipschitz constraint lives in image space, not in
            # DiffAugment's transformed space.
            def critic(x):
                return discriminator_apply(
                    d_params, bn["disc"], x, cfg=mcfg, train=False,
                    labels=labels, axis_name=axis_name,
                    attn_mesh=attn_mesh, pallas_mesh=pallas_mesh)[1][:, 0]
            if wgan:
                gp = L.gradient_penalty(critic, images.astype(jnp.float32),
                                        fake.astype(jnp.float32), gp_key)
                d_loss = d_loss + cfg.gp_weight * gp
            elif cfg.r1_interval == 1 or r1_every_step:
                # R1: zero-centered penalty on reals only, every step.
                # r1_every_step is the eval probe's path: unscaled gamma, so
                # the held-out d_loss is comparable across r1_interval
                # settings (the lazy form's k-scaling is a training-schedule
                # artifact, not a different regularizer)
                gp = L.r1_penalty(critic, images.astype(jnp.float32))
                d_loss = d_loss + 0.5 * cfg.r1_gamma * gp
            else:
                # lazy regularization (StyleGAN2): the penalty (an extra D
                # forward + double backward) runs only on every k-th step —
                # lax.cond executes one branch — with gamma scaled by k so
                # the time-averaged pressure matches
                gp = lax.cond(
                    step % cfg.r1_interval == 0,
                    lambda _: L.r1_penalty(critic,
                                           images.astype(jnp.float32)),
                    lambda _: jnp.zeros((), jnp.float32), None)
                d_loss = d_loss + 0.5 * cfg.r1_gamma * cfg.r1_interval * gp
        return d_loss, (d_bn2, d_real, d_fake, gp)

    def g_loss_fn(g_params: Pytree, d_params: Pytree, bn: Pytree,
                  z: jax.Array, labels, aug_key=None,
                  return_fake: bool = False) -> Tuple[jax.Array, Tuple]:
        fake, g_bn = generator_apply(g_params, bn["gen"], z, cfg=mcfg,
                                     train=True, labels=labels,
                                     axis_name=axis_name, attn_mesh=attn_mesh, pallas_mesh=pallas_mesh)
        fake = _cf(fake)
        # generator gradients flow THROUGH the augmentation — the property
        # DiffAugment needs (arXiv:2006.10738)
        _, fake_logits, _ = discriminator_apply(
            d_params, bn["disc"], _aug(fake, aug_key, 2), cfg=mcfg,
            train=True, labels=labels, axis_name=axis_name,
            attn_mesh=attn_mesh, pallas_mesh=pallas_mesh)
        # the family's own generator loss (4th return) — single-sourced with
        # the D-side dispatch; every family's g_loss depends only on the
        # fake logits, so the real-logits slot gets a dummy (its unused
        # d-side outputs are DCE'd by XLA). BCE: non-saturating generator
        # loss (image_train.py:96).
        g_loss = gan_losses(fake_logits, fake_logits)[3]
        # return_fake (pipelined g_update only): ride the already-computed
        # fake out through the aux so the stage can hand it to the NEXT
        # step's d_update — a Python-level branch, so the fused path's
        # jaxpr is untouched
        if return_fake:
            return g_loss, (g_bn, fake)
        return g_loss, (g_bn,)

    def _ema_update(state: Pytree, new_gen: Pytree) -> Pytree:
        d_ema = cfg.g_ema_decay  # 0 -> ema_gen mirrors the live weights
        return jax.tree_util.tree_map(
            lambda e, p: d_ema * e + (1.0 - d_ema) * p,
            state["ema_gen"], new_gen)

    def _accum_train_step(state: Pytree, images: jax.Array, z: jax.Array,
                          gp_key, aug_key, labels) -> Tuple[Pytree, dict]:
        """grad_accum > 1: K scanned microbatches per optimizer update.

        Gradients for each net are taken at the same (pre-update) params
        on every microbatch and averaged — the full-batch mean gradient at
        one microbatch's activation memory. BN state chains through the
        microbatches exactly as it chains through consecutive steps; the
        single pmean/all-reduce per net happens on the AVERAGED gradient,
        so the collective cost per optimizer update is unchanged.

        With n_critic > 1 the accumulation nests inside the scanned critic
        loop: each critic iteration draws its own fresh full z batch
        (matching the non-accum loop's semantics), splits it into K
        microbatches, and applies one Adam update from the accumulated
        gradient — n_critic Adam applies per step, each from a K-microbatch
        mean, at one microbatch's activation memory throughout.
        """
        K = cfg.grad_accum
        params, bn = state["params"], state["bn"]
        # ZeRO-3: the resident (possibly data-sharded) trees stay the
        # update targets; forwards and grads run on the gathered full view
        gen_full = _gather_params(params["gen"], "gen")

        imgs_s = _split_micro(images)
        lbls_s = _split_micro(labels) if labels is not None else None

        def _micro_xs(z_full, gpk, augk):
            """One optimizer update's worth of per-microbatch scan inputs."""
            xs = {"img": imgs_s, "z": _split_micro(z_full),
                  "gpk": jax.random.split(gpk, K)}
            if lbls_s is not None:
                xs["lbl"] = lbls_s
            if augk is not None:
                xs["augk"] = jax.random.split(augk, K)
            return xs

        # --- D: each Adam apply from K accumulated microbatch grads ---------
        def d_accum_update(d_params, d_opt_state, bn_d_start, xs):
            """Scan K microbatches at fixed d_params, apply Adam once."""
            d_full = _gather_params(d_params, "disc")

            def d_micro(carry, x):
                g_acc, bn_d = carry
                bn_in = {"gen": bn["gen"], "disc": bn_d}
                (d_loss, (d_bn_i, d_real, d_fake, gp)), grads = \
                    jax.value_and_grad(d_loss_fn, has_aux=True)(
                        d_full, gen_full, bn_in, x["img"], x["z"],
                        x["gpk"], x.get("lbl"), state["step"], False,
                        x.get("augk"))
                return ((_acc(g_acc, grads), d_bn_i),
                        (d_loss, d_real, d_fake, gp))

            (g_acc, bn_d), ms = lax.scan(
                d_micro, (_zeros_f32(d_full), bn_d_start), xs)
            updates, d_opt_state = opt_d.update(
                _avg(g_acc, d_full, "disc"), d_opt_state,
                _opt_arg(d_params))
            return (optax.apply_updates(
                        d_params, _gather_updates(updates, "disc")),
                    d_opt_state, bn_d, tuple(m.mean() for m in ms))

        if cfg.n_critic == 1:
            new_disc, d_opt, d_bn, (d_loss, d_real, d_fake, gp) = \
                d_accum_update(params["disc"], state["opt"]["disc"],
                               bn["disc"], _micro_xs(z, gp_key, aug_key))
        else:
            # the non-accum critic loop's semantics (fresh full z per
            # iteration against the same real batch), each iteration's
            # update accumulated over K microbatches
            def critic_iter(carry, iter_key):
                d_params_c, d_opt_c, d_bn_c, _ = carry
                z_i, gpk, aug_k = _critic_streams(iter_key, images.shape[0])
                out = d_accum_update(d_params_c, d_opt_c, d_bn_c,
                                     _micro_xs(z_i, gpk, aug_k))
                return out, None

            zero = _zero_metric()
            (new_disc, d_opt, d_bn,
             (d_loss, d_real, d_fake, gp)), _ = lax.scan(
                critic_iter,
                (params["disc"], state["opt"]["disc"], bn["disc"],
                 (zero, zero, zero, zero)),
                jax.random.split(gp_key, cfg.n_critic))

        if cfg.update_mode == "sequential":
            g_target_disc, disc_bn_for_g = \
                _gather_params(new_disc, "disc"), d_bn
        else:  # "fused": G grads at pre-update D params (reference parity)
            g_target_disc, disc_bn_for_g = \
                _gather_params(params["disc"], "disc"), bn["disc"]

        # --- G: same accumulation against the (possibly updated) D ----------
        # the top-level z/aug streams, like the non-accum G step (with
        # n_critic > 1 the critic iterations drew their own)
        g_xs = _micro_xs(z, gp_key, aug_key)

        def g_micro(carry, x):
            g_acc, bn_g = carry
            bn_in = {"gen": bn_g, "disc": disc_bn_for_g}
            (g_loss, (g_bn_i,)), grads = \
                jax.value_and_grad(g_loss_fn, has_aux=True)(
                    gen_full, g_target_disc, bn_in, x["z"],
                    x.get("lbl"), x.get("augk"))
            return (_acc(g_acc, grads), g_bn_i), g_loss

        (g_gacc, g_bn), g_losses = lax.scan(
            g_micro, (_zeros_f32(gen_full), bn["gen"]), g_xs)
        g_grads = _avg(g_gacc, gen_full, "gen")
        g_updates, g_opt = opt_g.update(g_grads, state["opt"]["gen"],
                                        _opt_arg(params["gen"]))
        new_gen = optax.apply_updates(params["gen"],
                                      _gather_updates(g_updates, "gen"))

        new_state = {
            "params": {"gen": new_gen, "disc": new_disc},
            "bn": {"gen": g_bn, "disc": d_bn},
            "opt": {"gen": g_opt, "disc": d_opt},
            "step": state["step"] + 1,
        }
        new_state["ema_gen"] = _ema_update(state, new_gen)
        # metrics: microbatch means (with n_critic > 1, the LAST critic
        # iteration's — matching the non-accum loop's last-iter reporting)
        return new_state, _loss_metrics(d_loss, d_real, d_fake,
                                        g_losses.mean(), gp)

    def train_step(state: Pytree, images: jax.Array, key: jax.Array,
                   labels: Optional[jax.Array] = None
                   ) -> Tuple[Pytree, dict]:
        # the 3-way split happens only when DiffAugment is on, so every
        # stream (z, gp) is bit-identical to reference-parity runs otherwise
        if aug_policy:
            z_key, gp_key, aug_key = jax.random.split(key, 3)
        else:
            z_key, gp_key = jax.random.split(key)
            aug_key = None
        z = jax.random.uniform(
            z_key, (images.shape[0], mcfg.z_dim),
            minval=-1.0, maxval=1.0, dtype=jnp.float32)

        if cfg.grad_accum > 1:
            return _accum_train_step(state, images, z, gp_key, aug_key,
                                     labels)

        params, bn = state["params"], state["bn"]
        # ZeRO-3: resident (possibly data-sharded) trees are the update
        # targets; forwards and grads run on the gathered full view
        gen_full = _gather_params(params["gen"], "gen")

        # --- D step(s) ------------------------------------------------------
        if cfg.n_critic == 1:
            (d_loss, (d_bn, d_real, d_fake, gp)), d_grads = jax.value_and_grad(
                d_loss_fn, has_aux=True)(
                    _gather_params(params["disc"], "disc"), gen_full, bn,
                    images, z, gp_key,
                    labels, state["step"], False, aug_key)
            d_grads = _reduce_grads(d_grads, "disc")
            d_updates, d_opt = opt_d.update(d_grads, state["opt"]["disc"],
                                            _opt_arg(params["disc"]))
            new_disc = optax.apply_updates(params["disc"],
                                           _gather_updates(d_updates,
                                                           "disc"))
        else:
            # n_critic > 1 (canonical WGAN-GP: 5) — scanned critic updates
            # inside the same compiled program. Each iteration draws fresh z
            # (and a fresh interpolation key) against the same real batch;
            # the loop is lax.scan so XLA compiles the critic body once.
            def critic_iter(carry, iter_key):
                d_params_c, d_opt_c, d_bn_c, _ = carry
                z_i, gpk, aug_k = _critic_streams(iter_key, images.shape[0])
                bn_in = {"gen": bn["gen"], "disc": d_bn_c}
                (loss_i, (bn_i, real_i, fake_i, gp_i)), grads = \
                    jax.value_and_grad(d_loss_fn, has_aux=True)(
                        _gather_params(d_params_c, "disc"), gen_full,
                        bn_in, images, z_i, gpk,
                        labels, state["step"], False, aug_k)
                grads = _reduce_grads(grads, "disc")
                updates, d_opt_c = opt_d.update(grads, d_opt_c,
                                                _opt_arg(d_params_c))
                d_params_c = optax.apply_updates(
                    d_params_c, _gather_updates(updates, "disc"))
                # last iteration's metrics ride the carry; note they are
                # evaluated at that iteration's PRE-update params (one Adam
                # step stale relative to the critic G trains against)
                return ((d_params_c, d_opt_c, bn_i,
                         (loss_i, real_i, fake_i, gp_i)), None)

            iter_keys = jax.random.split(gp_key, cfg.n_critic)
            zero = _zero_metric()
            (new_disc, d_opt, d_bn,
             (d_loss, d_real, d_fake, gp)), _ = lax.scan(
                critic_iter,
                (params["disc"], state["opt"]["disc"], bn["disc"],
                 (zero, zero, zero, zero)),
                iter_keys)

        if cfg.update_mode == "sequential":
            g_target_disc = _gather_params(new_disc, "disc")
            g_bn_in = {"gen": bn["gen"], "disc": d_bn}
        else:  # "fused": reference parity — G grads at pre-update D params
            g_target_disc = _gather_params(params["disc"], "disc")
            g_bn_in = bn

        # --- G step ---------------------------------------------------------
        (g_loss, (g_bn,)), g_grads = jax.value_and_grad(
            g_loss_fn, has_aux=True)(
                gen_full, g_target_disc, g_bn_in, z, labels, aug_key)
        g_grads = _reduce_grads(g_grads, "gen")
        g_updates, g_opt = opt_g.update(g_grads, state["opt"]["gen"],
                                        _opt_arg(params["gen"]))
        new_gen = optax.apply_updates(params["gen"],
                                      _gather_updates(g_updates, "gen"))

        new_state = {
            "params": {"gen": new_gen, "disc": new_disc},
            "bn": {"gen": g_bn, "disc": d_bn},
            "opt": {"gen": g_opt, "disc": d_opt},
            # Unlike the reference's global_step (G-updates only, SURVEY.md
            # §2.4 #3), this counts full D+G steps.
            "step": state["step"] + 1,
        }
        new_state["ema_gen"] = _ema_update(state, new_gen)
        return new_state, _loss_metrics(d_loss, d_real, d_fake, g_loss, gp)

    # --- pipelined stage programs (ISSUE 7) --------------------------------
    # The fused step factored into three independently-dispatchable
    # programs with IDENTICAL loss/penalty/accumulation code paths (every
    # loss goes through _d_loss_on_fake / g_loss_fn above — the stage
    # surfaces cannot drift from the fused ones). Unconditional models
    # only (labels=None throughout; TrainConfig validation enforces it),
    # sequential update_mode only (the trainer dispatches g_update after
    # d_update, so G trains against the updated critic — the fused
    # sequential ordering).

    stage_batch = local_batch if local_batch is not None else cfg.batch_size

    def _fake_stack(g_params: Pytree, g_bn: Pytree, key: jax.Array,
                    n: int) -> jax.Array:
        """[n, B, H, W, C] generator batches from FIXED (params, bn) —
        fresh z per slot (the fused critic loop's per-iteration z
        semantics via _critic_streams), train-mode BN with the updates
        discarded (the fused D branch's convention), constrain_fake
        applied. lax.scan so the body compiles once whatever n is."""
        def one(carry, iter_key):
            z_i, _, _ = _critic_streams(iter_key, stage_batch)
            fake, _ = generator_apply(g_params, g_bn, z_i, cfg=mcfg,
                                      train=True, labels=None,
                                      axis_name=axis_name,
                                      attn_mesh=attn_mesh,
                                      pallas_mesh=pallas_mesh)
            return carry, _cf(fake)
        keys = jax.random.split(key, n)
        if n == 1:
            # no 1-trip scan (see d_update: a single-iteration while loop
            # serializes the CPU backend)
            return one((), keys[0])[1][None]
        _, stack = lax.scan(one, (), keys)
        return stack

    # Every stage folds a stage-unique tag into the per-step key INSIDE
    # its traced body, so the three stage streams are independent while
    # the trainer hands all three the same key (the fused step splits its
    # single key inside its program the same way). Folding here instead
    # of in the dispatch loop matters: a host-side fold_in is a tiny
    # device program per call — three extra per-step dispatches that
    # stretch the pipelined span on dispatch-bound hosts.
    _D_TAG, _G_TAG, _FILL_TAG = 0, 1, 2

    def gen_fakes(state: Pytree, key: jax.Array) -> jax.Array:
        """The FILL program: an [n_critic, B, ...] fake stack from the
        CURRENT generator — dispatched at run start, after a restore, and
        after a rollback invalidated the in-flight buffer."""
        return _fake_stack(_gather_params(state["params"]["gen"], "gen"),
                           state["bn"]["gen"],
                           jax.random.fold_in(key, _FILL_TAG),
                           cfg.n_critic)

    def d_update(state: Pytree, images: jax.Array, fakes: jax.Array,
                 key: jax.Array) -> Tuple[Pytree, dict]:
        """The critic update(s) CONSUMING a provided fake stack (slot i
        feeds critic iteration i) instead of regenerating it — the fake
        production moves to g_update (where the G-loss forward doubles as
        slot 0), which is what makes this program's peak temp memory the
        pipeline's headroom win and decouples D's fake source from G's z.
        Touches ONLY the disc half of the state; gen/ema_gen/step ride
        through untouched, so the tree shape is exactly the fused
        step's."""
        params, bn = state["params"], state["bn"]
        iter_keys = jax.random.split(jax.random.fold_in(key, _D_TAG),
                                     cfg.n_critic)
        zero = _zero_metric()

        if cfg.grad_accum > 1:
            imgs_s = _split_micro(images)

            def critic_iter(carry, xs):
                d_params_c, d_opt_c, d_bn_c, _ = carry
                fake_i, iter_key = xs
                d_full = _gather_params(d_params_c, "disc")
                _, gpk, aug_k = _critic_streams(iter_key, stage_batch)
                xs_m = {"img": imgs_s, "fake": _split_micro(fake_i),
                        "gpk": jax.random.split(gpk, cfg.grad_accum)}
                if aug_k is not None:
                    xs_m["augk"] = jax.random.split(aug_k, cfg.grad_accum)

                def d_micro(c, x):
                    g_acc, bn_d = c
                    bn_in = {"gen": bn["gen"], "disc": bn_d}
                    (loss, (bn_i, real, fk, gp)), grads = \
                        jax.value_and_grad(_d_loss_on_fake, has_aux=True)(
                            d_full, bn_in, x["img"], x["fake"],
                            x["gpk"], None, state["step"], False,
                            x.get("augk"))
                    return ((_acc(g_acc, grads), bn_i),
                            (loss, real, fk, gp))

                (g_acc, bn_d), ms = lax.scan(
                    d_micro, (_zeros_f32(d_full), d_bn_c), xs_m)
                updates, d_opt_c = opt_d.update(
                    _avg(g_acc, d_full, "disc"), d_opt_c,
                    _opt_arg(d_params_c))
                return ((optax.apply_updates(
                             d_params_c, _gather_updates(updates, "disc")),
                         d_opt_c, bn_d, tuple(m.mean() for m in ms)), None)
        else:
            def critic_iter(carry, xs):
                d_params_c, d_opt_c, d_bn_c, _ = carry
                fake_i, iter_key = xs
                _, gpk, aug_k = _critic_streams(iter_key, stage_batch)
                bn_in = {"gen": bn["gen"], "disc": d_bn_c}
                (loss_i, (bn_i, real_i, fake_m, gp_i)), grads = \
                    jax.value_and_grad(_d_loss_on_fake, has_aux=True)(
                        _gather_params(d_params_c, "disc"), bn_in, images,
                        fake_i, gpk, None,
                        state["step"], False, aug_k)
                grads = _reduce_grads(grads, "disc")
                updates, d_opt_c = opt_d.update(grads, d_opt_c,
                                                _opt_arg(d_params_c))
                return ((optax.apply_updates(
                             d_params_c, _gather_updates(updates, "disc")),
                         d_opt_c, bn_i,
                         (loss_i, real_i, fake_m, gp_i)), None)

        carry0 = (params["disc"], state["opt"]["disc"], bn["disc"],
                  (zero, zero, zero, zero))
        if cfg.n_critic == 1:
            # direct call, no 1-trip scan — the fused step's own
            # n_critic==1 branch skips the scan too (a single-iteration
            # while loop measurably serializes the CPU backend), and the
            # SAME critic_iter body runs either way so the two paths
            # cannot drift
            (new_disc, d_opt, d_bn,
             (d_loss, d_real, d_fake, gp)), _ = critic_iter(
                carry0, (fakes[0], iter_keys[0]))
        else:
            (new_disc, d_opt, d_bn,
             (d_loss, d_real, d_fake, gp)), _ = lax.scan(
                critic_iter, carry0, (fakes, iter_keys))
        new_state = {
            "params": {"gen": params["gen"], "disc": new_disc},
            "bn": {"gen": bn["gen"], "disc": d_bn},
            "opt": {"gen": state["opt"]["gen"], "disc": d_opt},
            "ema_gen": state["ema_gen"],
            "step": state["step"],
        }
        return new_state, _d_metrics(d_loss, d_real, d_fake, gp)

    def g_update(state: Pytree, key: jax.Array
                 ) -> Tuple[Pytree, jax.Array, dict]:
        """The generator update against the CURRENT critic (the trainer
        dispatches it after d_update — sequential semantics), RETURNING
        the fake stack the next step's d_update consumes at staleness 1.
        Slot 0 is the g-loss forward's own fake (from the PRE-update
        weights — computed anyway, so the steady-state step gets its next
        D input for free); n_critic > 1 generates the remaining slots
        with fresh z from the same pre-update weights. Increments
        state["step"]."""
        key = jax.random.fold_in(key, _G_TAG)
        if aug_policy:
            z_key, extra_key, aug_key = jax.random.split(key, 3)
        else:
            z_key, extra_key = jax.random.split(key)
            aug_key = None
        params, bn = state["params"], state["bn"]
        gen_full = _gather_params(params["gen"], "gen")
        disc_full = _gather_params(params["disc"], "disc")

        if cfg.grad_accum > 1:
            z = jax.random.uniform(z_key, (stage_batch, mcfg.z_dim),
                                   minval=-1.0, maxval=1.0,
                                   dtype=jnp.float32)
            xs = {"z": _split_micro(z)}
            if aug_key is not None:
                xs["augk"] = jax.random.split(aug_key, cfg.grad_accum)

            def g_micro(carry, x):
                g_acc, bn_g = carry
                bn_in = {"gen": bn_g, "disc": bn["disc"]}
                (g_loss_i, (g_bn_i, fake_i)), grads = \
                    jax.value_and_grad(g_loss_fn, has_aux=True)(
                        gen_full, disc_full, bn_in, x["z"],
                        None, x.get("augk"), return_fake=True)
                return (_acc(g_acc, grads), g_bn_i), (g_loss_i, fake_i)

            (g_gacc, g_bn), (g_losses, fakes_m) = lax.scan(
                g_micro, (_zeros_f32(gen_full), bn["gen"]), xs)
            g_grads = _avg(g_gacc, gen_full, "gen")
            g_loss = g_losses.mean()
            # (K, micro, ...) -> (B, ...): the full-batch fake the next
            # d_update re-splits into its own microbatches
            fake = _cf(fakes_m.reshape(stage_batch, *fakes_m.shape[2:]))
        else:
            z = jax.random.uniform(z_key, (stage_batch, mcfg.z_dim),
                                   minval=-1.0, maxval=1.0,
                                   dtype=jnp.float32)
            (g_loss, (g_bn, fake)), g_grads = jax.value_and_grad(
                g_loss_fn, has_aux=True)(
                    gen_full, disc_full, bn, z, None, aug_key,
                    return_fake=True)
            g_grads = _reduce_grads(g_grads, "gen")
        g_updates, g_opt = opt_g.update(g_grads, state["opt"]["gen"],
                                        _opt_arg(params["gen"]))
        new_gen = optax.apply_updates(params["gen"],
                                      _gather_updates(g_updates, "gen"))

        if cfg.n_critic > 1:
            extra = _fake_stack(gen_full, bn["gen"], extra_key,
                                cfg.n_critic - 1)
            fakes = jnp.concatenate([fake[None], extra], axis=0)
        else:
            fakes = fake[None]
        new_state = {
            "params": {"gen": new_gen, "disc": params["disc"]},
            "bn": {"gen": g_bn, "disc": bn["disc"]},
            "opt": {"gen": g_opt, "disc": state["opt"]["disc"]},
            "step": state["step"] + 1,
        }
        new_state["ema_gen"] = _ema_update(state, new_gen)
        return new_state, fakes, {"g_loss": _pmean(g_loss)}

    def sample(state: Pytree, z: jax.Array,
               labels: Optional[jax.Array] = None) -> jax.Array:
        # EMA weights when tracking is on (g_ema_decay > 0); the reference
        # samples live weights (image_train.py:181-184), which remains the
        # default. Selected by config, not key presence — ema_gen always
        # exists in the state (see init_train_state) but under decay=0 it is
        # a by-construction mirror and live weights are the clearer choice.
        g_params = (state["ema_gen"] if cfg.g_ema_decay > 0.0
                    else state["params"]["gen"])
        # ZeRO-3: the EMA mirror shards like the live G params — one
        # just-in-time gather serves both sources
        g_params = _gather_params(g_params, "gen")
        return sampler_apply(g_params, state["bn"]["gen"], z,
                             cfg=mcfg, labels=labels,
                             pallas_mesh=pallas_mesh)

    def summarize(state: Pytree, images: jax.Array, key: jax.Array,
                  labels: Optional[jax.Array] = None) -> dict:
        """Per-layer activation histograms + sparsity, reduced on device.

        The functional replacement for the reference's `_activation_summary`
        (distriubted_model.py:75-80): one extra forward of G and of D (on the
        real batch) with train-mode BN, run on a step-count cadence
        (TrainConfig.activation_summary_steps — never a per-process time gate;
        it is a mesh collective) — the hot step is untouched.
        """
        from dcgan_tpu.utils.metrics import activation_stats

        params, bn = state["params"], state["bn"]
        params = {"gen": _gather_params(params["gen"], "gen"),
                  "disc": _gather_params(params["disc"], "disc")}
        z = jax.random.uniform(key, (images.shape[0], mcfg.z_dim),
                               minval=-1.0, maxval=1.0, dtype=jnp.float32)
        g_cap: dict = {}
        d_cap: dict = {}
        fake, _ = generator_apply(params["gen"], bn["gen"], z, cfg=mcfg,
                                  train=True, labels=labels,
                                  axis_name=axis_name,
                                  attn_mesh=attn_mesh, pallas_mesh=pallas_mesh, capture=g_cap)
        d_real_prob, _, _ = discriminator_apply(
            params["disc"], bn["disc"], images, cfg=mcfg,
            train=True, labels=labels, axis_name=axis_name,
            attn_mesh=attn_mesh, pallas_mesh=pallas_mesh, capture=d_cap)
        # the reference's input/output histogram channels (image_train.py:
        # 86-89): z itself, D(x), and D(G(z)) — one extra D forward on the
        # fakes, paid only on the summary cadence
        d_fake_prob, _, _ = discriminator_apply(
            params["disc"], bn["disc"], fake, cfg=mcfg,
            train=True, labels=labels, axis_name=axis_name,
            attn_mesh=attn_mesh, pallas_mesh=pallas_mesh)
        acts = {**{f"gen/{k}": v for k, v in g_cap.items()},
                **{f"disc/{k}": v for k, v in d_cap.items()},
                "z": z, "d_real_prob": d_real_prob,
                "d_fake_prob": d_fake_prob}
        return activation_stats(acts, axis_name=axis_name)

    def eval_losses(state: Pytree, images: jax.Array, z: jax.Array,
                    labels: Optional[jax.Array] = None) -> dict:
        """Loss probe on a held-out batch with a caller-fixed z, no update —
        the reference's every-100-steps sample evaluation: it feeds the
        *sample* pipeline's batch and the fixed sample_z through the train
        graph's loss tensors without running the optimizers
        (image_train.py:179-192). Train-mode BN (batch statistics), matching
        the reference's reuse of the train graph; the returned BN state is
        discarded. WGAN-GP's interpolation uses a fixed key: a deterministic
        probe, not a training signal."""
        params, bn = state["params"], state["bn"]
        params = {"gen": _gather_params(params["gen"], "gen"),
                  "disc": _gather_params(params["disc"], "disc")}
        gp_key = jax.random.key(0)
        d_loss, (_, d_real, d_fake, gp) = d_loss_fn(
            params["disc"], params["gen"], bn, images, z, gp_key, labels,
            r1_every_step=True)
        g_loss, _ = g_loss_fn(params["gen"], params["disc"], bn, z, labels)
        return _loss_metrics(d_loss, d_real, d_fake, g_loss, gp)

    def init(key):
        return init_train_state(key, cfg)

    return TrainStepFns(train_step=train_step, sample=sample, init=init,
                        summarize=summarize, eval_losses=eval_losses,
                        gen_fakes=gen_fakes, d_update=d_update,
                        g_update=g_update)
