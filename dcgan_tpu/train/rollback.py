"""NaN rollback-and-skip: the fail-operational alternative to gate-abort.

The numerical-health gate (trainer `_consume_metrics`, SURVEY.md §5) turns a
non-finite loss into a `FloatingPointError` with step context. Under the
default `--nan_policy abort` that kills the job — correct for debugging,
wasteful for a multi-day run where one pathological batch (or one cosmic-ray
bit) poisons a step that a different batch window would have sailed through
(ParaGAN's recovery argument for long GAN runs, PAPERS.md arxiv 2411.03999).

`--nan_policy rollback` keeps a copy of the last gate-verified state every
`rollback_snapshot_steps` steps; when the gate trips, the manager puts the
snapshot back, rewinds the host's step counter, and training continues —
the data iterator is NOT rewound, so the batches that fed the poisoned
window are naturally skipped, and the trainer folds the rollback count into
its step-key stream so the replayed steps also draw fresh z (a bitwise
replay would deterministically re-diverge). Optional LR backoff multiplies
both nets' base rates per rollback. `max_rollbacks` bounds the whole
mechanism: persistent divergence is a real bug and must still abort.

Two snapshot representations, same restore contract:

- host (`device_resident=False`, single-process default): a host copy via
  `jax.device_get`, put back with the captured shardings. Zero extra HBM;
  requires every leaf to be fully addressable from this process.
- sharded device-resident (`device_resident=True`, the multi-host mode —
  ISSUE 4): a jitted identity copy keeps each host's *addressable shards*
  on its own devices, restored through the same jitted copy so the
  returned buffers are fresh (the step's donate_argnums invalidates only
  the arrays actually passed in — the snapshot survives to serve a second
  rollback). No process ever holds the full state, which is exactly what
  unblocked multi-host rollback: the snapshot/restore dispatches run on
  every process at the same consensus-agreed point, so they are ordinary
  mesh-consistent programs. Costs one extra copy of the train state in
  device memory — the price of a restore that needs no host gather.

Accounting: `rollbacks` is surfaced as the `anomaly/rollbacks` scalar
through utils/metrics.MetricWriter — one event at each rollback plus the
running value on every scalars row while nonzero.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

Pytree = Any


class RollbackExhausted(FloatingPointError):
    """The gate tripped more than max_rollbacks times; carries the last
    gate failure as __cause__."""


_DEVICE_COPY = None


def device_copy(tree: Pytree) -> Pytree:
    """Fresh device buffers with the same values/shardings: `a + 0` under
    jit compiles to a copy whose outputs alias nothing a later step program
    can donate. One module-level jitted identity shared by every caller
    (the rollback snapshot here, the trainer's param-histogram capture) —
    jax caches per tree structure/shape, so one function serves them all,
    and a future fix to the copy idiom lands in one place."""
    global _DEVICE_COPY
    if _DEVICE_COPY is None:
        _DEVICE_COPY = jax.jit(
            lambda t: jax.tree_util.tree_map(lambda a: a + 0, t))
    return _DEVICE_COPY(tree)


class RollbackManager:
    """Last-good snapshot keeper + restore executor for one training run."""

    def __init__(self, *, every: int, max_rollbacks: int,
                 lr_backoff: float = 1.0, chief: bool = True,
                 device_resident: bool = False):
        if every < 1:
            raise ValueError(f"snapshot cadence must be >= 1, got {every}")
        self.every = every
        self.max_rollbacks = max_rollbacks
        self.lr_backoff = lr_backoff
        self.chief = chief
        self.device_resident = device_resident
        self.rollbacks = 0
        self._snap: Optional[Pytree] = None
        self._snap_step: Optional[int] = None
        self._shardings = None

    @property
    def snapshot_step(self) -> Optional[int]:
        return self._snap_step

    def due(self, step: int) -> bool:
        return step % self.every == 0

    def snapshot(self, step: int, state: Pytree) -> None:
        """Capture `state` as the new restore point. The caller passes only
        gate-verified state (the trainer forces a finiteness check at
        snapshot boundaries)."""
        if self.device_resident:
            self._snap = device_copy(state)
        else:
            self._shardings = jax.tree_util.tree_map(
                lambda x: x.sharding if hasattr(x, "sharding") else None,
                state)
            # owned_host_copy, not bare device_get: under the persistent
            # compile cache, device_get's zero-copy views get donated over
            # in place by deserialized executables, and the "snapshot"
            # would silently track the very divergence it exists to flee
            from dcgan_tpu.utils.checkpoint import owned_host_copy

            self._snap = owned_host_copy(state)
        self._snap_step = int(step)

    def restore(self, exc: FloatingPointError) -> tuple:
        """Consume one rollback: returns (state, step) rebuilt on device
        from the snapshot. Raises RollbackExhausted (from `exc`) once the
        budget is spent."""
        if self._snap is None:
            raise exc  # no restore point was ever armed
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            raise RollbackExhausted(
                f"NaN gate tripped {self.rollbacks} times with "
                f"max_rollbacks={self.max_rollbacks} — persistent "
                f"divergence, aborting (last failure: {exc})") from exc
        if self.chief:
            print(f"[dcgan_tpu] NaN gate tripped ({exc}); rolling back to "
                  f"last-good snapshot at step {self._snap_step} "
                  f"(rollback {self.rollbacks}/{self.max_rollbacks}, "
                  f"offending batch window will be skipped)", flush=True)
        if self.device_resident:
            state = device_copy(self._snap)
        else:
            state = jax.tree_util.tree_map(
                lambda host, sh: jax.device_put(host, sh)
                if sh is not None else host,
                self._snap, self._shardings)
            from dcgan_tpu.utils.checkpoint import persistent_cache_active

            if persistent_cache_active():
                # device_put buffers are not XLA-executable outputs, and
                # DONATING any such buffer into an executable DESERIALIZED
                # from the persistent compile cache corrupts the heap
                # (jaxlib 0.4.37 CPU — same class as checkpoint.py's
                # _rebase_onto_xla_buffers; empirically owned device_put
                # buffers crash too, not just externally-referenced ones).
                # One non-donating identity copy rebases the restored
                # state onto XLA-owned buffers before the trainer's
                # donated step programs touch it; the AOT warmup plan
                # pre-compiles this exact variant ("state_copy@restore")
                # so no compile runs in the guarded restore window.
                state = device_copy(state)
        return state, self._snap_step

    def lr_scale(self) -> float:
        """Cumulative LR multiplier after the rollbacks so far."""
        return self.lr_backoff ** self.rollbacks
