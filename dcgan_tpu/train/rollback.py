"""NaN rollback-and-skip: the fail-operational alternative to gate-abort.

The numerical-health gate (trainer `_consume_metrics`, SURVEY.md §5) turns a
non-finite loss into a `FloatingPointError` with step context. Under the
default `--nan_policy abort` that kills the job — correct for debugging,
wasteful for a multi-day run where one pathological batch (or one cosmic-ray
bit) poisons a step that a different batch window would have sailed through
(ParaGAN's recovery argument for long GAN runs, PAPERS.md arxiv 2411.03999).

`--nan_policy rollback` keeps a HOST-side copy of the last gate-verified
state every `rollback_snapshot_steps` steps; when the gate trips, the
manager puts the snapshot back on device (same shardings), rewinds the
host's step counter, and training continues — the data iterator is NOT
rewound, so the batches that fed the poisoned window are naturally skipped,
and the trainer folds the rollback count into its step-key stream so the
replayed steps also draw fresh z (a bitwise replay would deterministically
re-diverge). Optional LR backoff multiplies both nets' base rates per
rollback. `max_rollbacks` bounds the whole mechanism: persistent divergence
is a real bug and must still abort.

Host snapshots require fully-addressable arrays, so the policy is
single-process only (the trainer validates); multi-host keeps abort, whose
restart-from-checkpoint path is already collective-safe.

Accounting: `rollbacks` is surfaced as the `anomaly/rollbacks` scalar
through utils/metrics.MetricWriter — one event at each rollback plus the
running value on every scalars row while nonzero.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

Pytree = Any


class RollbackExhausted(FloatingPointError):
    """The gate tripped more than max_rollbacks times; carries the last
    gate failure as __cause__."""


class RollbackManager:
    """Last-good snapshot keeper + restore executor for one training run."""

    def __init__(self, *, every: int, max_rollbacks: int,
                 lr_backoff: float = 1.0, chief: bool = True):
        if every < 1:
            raise ValueError(f"snapshot cadence must be >= 1, got {every}")
        self.every = every
        self.max_rollbacks = max_rollbacks
        self.lr_backoff = lr_backoff
        self.chief = chief
        self.rollbacks = 0
        self._snap: Optional[Pytree] = None
        self._snap_step: Optional[int] = None
        self._shardings = None

    @property
    def snapshot_step(self) -> Optional[int]:
        return self._snap_step

    def due(self, step: int) -> bool:
        return step % self.every == 0

    def snapshot(self, step: int, state: Pytree) -> None:
        """Host-copy `state` as the new restore point. The caller passes
        only gate-verified state (the trainer forces a finiteness check at
        snapshot boundaries)."""
        self._shardings = jax.tree_util.tree_map(
            lambda x: x.sharding if hasattr(x, "sharding") else None, state)
        self._snap = jax.device_get(state)
        self._snap_step = int(step)

    def restore(self, exc: FloatingPointError) -> tuple:
        """Consume one rollback: returns (state, step) rebuilt on device
        from the snapshot. Raises RollbackExhausted (from `exc`) once the
        budget is spent."""
        if self._snap is None:
            raise exc  # no restore point was ever armed
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            raise RollbackExhausted(
                f"NaN gate tripped {self.rollbacks} times with "
                f"max_rollbacks={self.max_rollbacks} — persistent "
                f"divergence, aborting (last failure: {exc})") from exc
        if self.chief:
            print(f"[dcgan_tpu] NaN gate tripped ({exc}); rolling back to "
                  f"last-good snapshot at step {self._snap_step} "
                  f"(rollback {self.rollbacks}/{self.max_rollbacks}, "
                  f"offending batch window will be skipped)", flush=True)
        state = jax.tree_util.tree_map(
            lambda host, sh: jax.device_put(host, sh)
            if sh is not None else host,
            self._snap, self._shardings)
        return state, self._snap_step

    def lr_scale(self) -> float:
        """Cumulative LR multiplier after the rollbacks so far."""
        return self.lr_backoff ** self.rollbacks
