"""Training subsystem: losses, jitted train step, trainer loop, CLI."""

from dcgan_tpu.train.losses import bce_gan_losses  # noqa: F401
from dcgan_tpu.train.steps import TrainStepFns, init_train_state, make_train_step  # noqa: F401
