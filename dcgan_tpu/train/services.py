"""Async host services: the observability work the dispatch thread sheds.

The hot loop's job is dispatching compiled step programs; every host-side
service the reference ran inline — summary materialization, histogram
reduction, PNG encode, event-file IO (image_train.py:155-192) — stalls
dispatch for exactly its own duration. This module provides the trainer's
background executor for that work (ISSUE 2 tentpole; the host-ahead-of-
device discipline of pjit-era TPU trainers, arxiv 2204.06514, and
ParaGAN's congestion-aware host pipeline, arxiv 2411.03999):

- `HostServices`: ONE worker thread draining a bounded deque. Telemetry
  must never stall training, so when the queue is full the OLDEST
  droppable task is discarded (drop-oldest backpressure: the newest
  telemetry is the most valuable, and a slow filesystem degrades
  observability rather than throughput). Worker exceptions are captured
  and re-raised on the dispatch thread at the next `raise_if_failed()` /
  `drain()` — telemetry failures kill the job loudly, not silently.
- `InlineServices`: the `--async_services=false` escape hatch. `submit`
  executes the task immediately on the calling thread, reproducing the
  pre-async trainer's synchronous behavior (same call sites, same
  ordering, same metric values; the JSONL differs from pre-async builds
  only by the perf/* occupancy keys StepTimer now always emits).

Threading contract: the MetricWriter (JSONL + TensorBoard event files) is
NOT thread-safe; in async mode every writer call must be submitted here so
the single worker serializes them. Work that participates in mesh-wide
collectives (the FID probe's all-gathers, Orbax collective saves, the
pt.summarize/pt.sample dispatches themselves) must STAY on the dispatch
thread: a collective issued from a per-process background thread has no
ordering guarantee against the main thread's collectives, and two
processes interleaving them differently deadlock the mesh. Only the
host-local tails (device_get of already-dispatched outputs, reduction,
encode, file IO) move here.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Optional

# default queue bound: deep enough to absorb a burst (scalars + histograms
# + grid + activations landing on one step), shallow enough that a wedged
# filesystem drops telemetry within seconds instead of hoarding device-
# array references
DEFAULT_QUEUE_DEPTH = 16


class ServiceError(RuntimeError):
    """A background service task failed; carries the original traceback."""


class _Task:
    __slots__ = ("fn", "tag", "droppable")

    def __init__(self, fn: Callable[[], None], tag: str, droppable: bool):
        self.fn = fn
        self.tag = tag
        self.droppable = droppable


class HostServices:
    """Single-worker background executor with drop-oldest backpressure."""

    def __init__(self, *, max_queue: int = DEFAULT_QUEUE_DEPTH,
                 name: str = "dcgan-host-services"):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        self.dropped = 0          # tasks discarded by backpressure
        self.completed = 0
        self._queue: "collections.deque[_Task]" = collections.deque()
        self._lock = threading.Lock()
        self._has_work = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._busy = False        # worker currently executing a task
        self._stop = False
        self._error: Optional[BaseException] = None
        self._error_tag = ""
        self._worker = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._worker.start()

    # -- worker side --------------------------------------------------------

    def _run(self) -> None:
        from dcgan_tpu.testing import chaos
        from dcgan_tpu.utils.retry import retry_io

        n_tasks = 0
        while True:
            with self._lock:
                while not self._queue and not self._stop:
                    self._has_work.wait()
                if self._stop and not self._queue:
                    self._idle.notify_all()
                    return
                task = self._queue.popleft()
                self._busy = True
            n_tasks += 1
            try:
                if chaos.should_crash_worker(n_tasks):
                    raise RuntimeError(
                        "chaos: injected services worker crash")
                # writer tasks are filesystem IO at heart: one transient
                # OSError (full/fsync-flaky/NFS-hiccup) gets the bounded
                # jittered-backoff treatment instead of poisoning the
                # worker; persistent failure still surfaces on the
                # dispatch thread via raise_if_failed. Trade-off: appends
                # are not idempotent, so a failure MID-write followed by a
                # successful retry can leave one torn JSONL line or a
                # duplicate step row — acceptable for telemetry (readers
                # should skip unparseable lines), where the alternative
                # was the whole run dying on the same transient error
                retry_io(task.fn, tag="services")
                with self._lock:
                    self.completed += 1
            except BaseException as e:  # noqa: BLE001 — reported to main
                with self._lock:
                    if self._error is None:
                        self._error = e
                        self._error_tag = task.tag
                    # a failed worker stops accepting work; pending tasks
                    # are dropped so close()/drain() can't hang behind a
                    # poisoned writer
                    self._stop = True
                    self._queue.clear()
            finally:
                with self._lock:
                    self._busy = False
                    self._idle.notify_all()

    # -- dispatch-thread side -----------------------------------------------

    def submit(self, fn: Callable[[], None], *, tag: str = "",
               droppable: bool = True) -> bool:
        """Enqueue `fn` for the worker; returns False if it was rejected
        (executor stopped) or immediately displaced. When the queue is
        full, the oldest droppable task is discarded to make room; if
        nothing is droppable the NEW task blocks until space frees (never
        silently lost — non-droppable is reserved for barrier-adjacent
        work like final flushes)."""
        with self._lock:
            if self._stop:
                return False
            while len(self._queue) >= self.max_queue:
                victim = next((t for t in self._queue if t.droppable), None)
                if victim is not None:
                    self._queue.remove(victim)
                    self.dropped += 1
                else:
                    self._idle.wait(timeout=0.1)
                    if self._stop:
                        return False
                    continue
            self._queue.append(_Task(fn, tag, droppable))
            self._has_work.notify()
        return True

    def pending(self) -> int:
        with self._lock:
            return len(self._queue) + (1 if self._busy else 0)

    def raise_if_failed(self) -> None:
        """Propagate a worker failure to the calling (dispatch) thread."""
        with self._lock:
            err, tag = self._error, self._error_tag
        if err is not None:
            raise ServiceError(
                f"background host service {tag or 'task'!r} failed: "
                f"{err!r}") from err

    def drain(self, timeout: Optional[float] = None) -> None:
        """Barrier: block until every queued task has executed (or the
        worker failed — which re-raises). Called at checkpoint boundaries
        and on exit so telemetry ordered before a checkpoint is durable
        before training proceeds past it."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while (self._queue or self._busy) and self._error is None:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"host-services drain timed out with "
                        f"{len(self._queue)} task(s) pending")
                self._idle.wait(timeout=remaining)
        self.raise_if_failed()

    def close(self, timeout: float = 30.0) -> None:
        """Drain then stop the worker. Safe to call twice. Re-raises a
        worker failure (after the thread is down) so close-on-exception
        paths still surface the original error."""
        try:
            self.drain(timeout=timeout)
        except TimeoutError:
            pass  # stop anyway; daemon thread cannot block interpreter exit
        finally:
            with self._lock:
                self._stop = True
                self._has_work.notify_all()
            self._worker.join(timeout=timeout)
        self.raise_if_failed()


class InlineServices:
    """Synchronous stand-in: `submit` runs the task on the calling thread.

    The `--async_services=false` escape hatch: every service executes at
    its original call site, in its original order, so the event stream
    carries the same values and structure the inline trainer wrote.
    Exceptions propagate immediately (no deferral)."""

    max_queue = 0
    dropped = 0
    completed = 0

    def submit(self, fn: Callable[[], None], *, tag: str = "",
               droppable: bool = True) -> bool:
        fn()
        self.completed += 1
        return True

    def pending(self) -> int:
        return 0

    def raise_if_failed(self) -> None:
        pass

    def drain(self, timeout: Optional[float] = None) -> None:
        pass

    def close(self, timeout: float = 30.0) -> None:
        pass


def make_services(async_services: bool, *,
                  max_queue: int = DEFAULT_QUEUE_DEPTH):
    """The trainer's one switch between the async executor and the
    inline escape hatch."""
    return HostServices(max_queue=max_queue) if async_services \
        else InlineServices()
