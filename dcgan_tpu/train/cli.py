"""CLI: the reference's flag surface (image_train.py:10-38) as argparse.

Every live knob of the reference exists here under the same name where
sensible; flags the reference declared but never read (epoch, train_size,
image_size, is_train, is_crop, visualize, log_device_placement — SURVEY.md
§2.3) are intentionally absent, and cluster flags (ps_hosts/worker_hosts/
job_name/task_index) are replaced by the mesh/multi-host knobs since no
parameter-server role exists.

    python -m dcgan_tpu.train --data_dir /data/celeba --checkpoint_dir ckpt
    python -m dcgan_tpu.train --synthetic --max_steps 200   # smoke run
"""

from __future__ import annotations

import argparse
import dataclasses
import pprint
from typing import List, Optional

from dcgan_tpu.config import TrainConfig


def _parse_bool(s: str) -> bool:
    """Explicit true/false flag values (--async_services=false); argparse's
    bool() would treat any non-empty string, 'false' included, as True."""
    low = s.strip().lower()
    if low in ("true", "1", "yes", "on"):
        return True
    if low in ("false", "0", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(f"expected true/false, got {s!r}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dcgan_tpu.train",
        description="TPU-native distributed DCGAN trainer")
    from dcgan_tpu.presets import PRESETS
    p.add_argument("--preset", choices=sorted(PRESETS), default=None,
                   help="named BASELINE.json config (presets.py); explicit "
                        "flags override preset defaults")
    # optimization (reference defaults: image_train.py:11-14)
    p.add_argument("--learning_rate", type=float, default=2e-4)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--batch_size", type=int, default=64,
                   help="global batch size (sharded over the data axis)")
    p.add_argument("--max_steps", type=int, default=1_200_000)
    p.add_argument("--loss", choices=["gan", "wgan-gp", "hinge"],
                   default="gan")
    p.add_argument("--update_mode", choices=["sequential", "fused"],
                   default="sequential")
    p.add_argument("--n_critic", type=int, default=1,
                   help="D updates per G update (WGAN-GP canonical: 5)")
    p.add_argument("--grad_accum", type=int, default=1,
                   help=">1 scans that many microbatches per optimizer "
                        "update (full-batch gradient at 1/K activation "
                        "memory; batch_size must divide by it)")
    p.add_argument("--gp_weight", type=float, default=10.0,
                   help="WGAN-GP gradient-penalty coefficient")
    p.add_argument("--r1_gamma", type=float, default=0.0,
                   help=">0 adds R1 regularization ((gamma/2)*||grad D||^2 "
                        "on reals) to the gan/hinge families")
    p.add_argument("--r1_interval", type=int, default=1,
                   help="lazy regularization: compute R1 every k-th step "
                        "with gamma scaled by k (StyleGAN2; 1 = every step)")
    p.add_argument("--diffaug", default="",
                   help="DiffAugment policy for every D input, e.g. "
                        "'color,translation,cutout' (small datasets); "
                        "'' = off")
    p.add_argument("--grad_clip", type=float, default=0.0,
                   help=">0 clips both nets' grads by global norm before "
                        "Adam")
    p.add_argument("--label_smoothing", type=float, default=0.0,
                   help="one-sided label smoothing: D's real target becomes "
                        "1-eps (gan loss only)")
    # model (image_train.py:15-18 — wired here, unlike the reference)
    p.add_argument("--arch", choices=["dcgan", "resnet", "stylegan"],
                   default="dcgan",
                   help="model family: the reference's DCGAN stacks, the "
                        "WGAN-GP/SNGAN residual blocks, or StyleGAN2-lite "
                        "(modulated convs + resnet critic; pair with "
                        "--r1_gamma)")
    p.add_argument("--output_size", type=int, default=64)
    p.add_argument("--c_dim", type=int, default=3)
    p.add_argument("--z_dim", type=int, default=100)
    p.add_argument("--gf_dim", type=int, default=64)
    p.add_argument("--df_dim", type=int, default=64)
    p.add_argument("--num_classes", type=int, default=0,
                   help=">0 = class-conditional G/D")
    p.add_argument("--conditional_bn", action="store_true",
                   help="conditional models: per-class BN affine in G "
                        "(SAGAN/BigGAN cBN)")
    p.add_argument("--use_pallas", action="store_true",
                   help="fused Pallas BN+activation kernels (single-chip)")
    p.add_argument("--pallas_fused", action="store_true",
                   help="fuse each interior G/D stage (conv/deconv + bias + "
                        "BN + act) into one Pallas block (ops/pallas_fused); "
                        "requires --use_pallas, dcgan arch only")
    p.add_argument("--precision", choices=["", "f32", "bf16", "fp8"],
                   default="",
                   help="reduced-precision ladder: f32 (reference arm), "
                        "bf16 (bf16 params+compute, f32 master Adam mu), "
                        "fp8 (bf16 + simulated-fp8 conv operands at >=64px "
                        "stages); default '' leaves model dtypes alone")
    p.add_argument("--attn_res", type=int, default=0,
                   help=">0 inserts SAGAN self-attention into both stacks at "
                        "this feature-map resolution (ring attention under "
                        "--mesh_spatial); 0 = off")
    p.add_argument("--attn_heads", type=int, default=1,
                   help="attention heads (1 = SAGAN paper; apply-time split, "
                        "checkpoint-compatible across head counts)")
    p.add_argument("--seq_strategy", choices=["ring", "ulysses"],
                   default="ring",
                   help="sequence-parallel attention under --mesh_spatial: "
                        "ppermute ring vs two all_to_alls (Ulysses; needs "
                        "attn_heads divisible by the model axis)")
    p.add_argument("--spectral_norm", choices=["none", "d", "gd"],
                   default="none",
                   help="spectral-normalize discriminator (d) or both nets' "
                        "(gd) weights — SN-GAN / SAGAN Lipschitz control")
    # data (image_train.py:19-26)
    p.add_argument("--dataset", default="celebA")
    p.add_argument("--data_dir", default="train")
    p.add_argument("--sample_image_dir", default="sample_data")
    p.add_argument("--synthetic", action="store_true",
                   help="train on synthetic data (no shards needed)")
    p.add_argument("--no_normalize", action="store_true",
                   help="feed raw pixel scale (strict reference parity, "
                        "SURVEY.md 2.4 #1)")
    p.add_argument("--record_dtype", default="float64",
                   choices=["float64", "float32", "uint8"],
                   help="wire format for manifest-less corpora; a "
                        "dataset.json manifest's record_dtype is "
                        "authoritative (adopted, like evals)")
    p.add_argument("--label_feature", default="label",
                   help="int64 class feature name in the records "
                        "(used when --num_classes > 0)")
    p.add_argument("--prefetch_device_batches", type=int, default=2,
                   help="depth of the background device-feed queue (a "
                        "transfer thread keeps N sharded batches ready "
                        "ahead of the dispatch thread); 0 = legacy inline "
                        "double buffer")
    p.add_argument("--synthetic_device_cache", type=int, default=0,
                   help="with --synthetic: pre-stage N batches on device "
                        "and cycle them (loop-speed measurement; see "
                        "tools/bench_trainer_loop.py)")
    p.add_argument("--synthetic_global_stream", type=_parse_bool,
                   default=False, metavar="{true,false}",
                   help="with --synthetic: generate the full global batch "
                        "on every process and cut the local block, so the "
                        "batch sequence is identical across process "
                        "layouts of the same mesh (the elastic shrink/"
                        "grow drills' loss-replay invariance; costs P x "
                        "the host generation)")
    # observability / checkpoint (image_train.py:20-21,37,129)
    p.add_argument("--async_services", type=_parse_bool, default=True,
                   metavar="{true,false}",
                   help="run observability (metric materialization, "
                        "histograms, sample PNGs, event-file IO) on a "
                        "background executor with lag-by-one metric "
                        "logging; --async_services=false runs every "
                        "service inline on the dispatch thread (the "
                        "pre-async loop, identical metric values and "
                        "event structure)")
    p.add_argument("--checkpoint_dir", default="checkpoint")
    p.add_argument("--sample_dir", default="samples")
    p.add_argument("--no_tensorboard", action="store_true",
                   help="disable the TensorBoard event-file mirror "
                        "(JSONL metrics are always written)")
    p.add_argument("--save_summaries_secs", type=float, default=10.0)
    p.add_argument("--save_model_secs", type=float, default=600.0)
    p.add_argument("--max_checkpoints", type=int, default=5,
                   help="checkpoints retained (oldest pruned beyond this)")
    p.add_argument("--sample_every_steps", type=int, default=100)
    p.add_argument("--fid_every_steps", type=int, default=0,
                   help=">0: periodic in-training surrogate FID/KID probe "
                        "against the held-out sample stream (eval/fid + "
                        "eval/kid scalars; multihost jobs split the budget "
                        "per process and gather one global score); 0 = off")
    p.add_argument("--fid_num_samples", type=int, default=2048,
                   help="samples per side for the in-training FID probe "
                        "(must divide evenly over the process count)")
    p.add_argument("--nan_check_steps", type=int, default=100,
                   help="all-process numerical-health gate cadence (0 = "
                        "off); each check reads metric values, which costs "
                        "a device round-trip")
    p.add_argument("--nan_policy", choices=["abort", "rollback"],
                   default="abort",
                   help="tripped NaN gate: abort with step context "
                        "(reference parity) or restore the last-good "
                        "snapshot, skip the offending batch window, and "
                        "keep training (bounded by --max_rollbacks). "
                        "Multi-host: gate verdicts are allgathered so every "
                        "process takes the same branch, and the snapshot "
                        "is a sharded device-resident copy")
    p.add_argument("--coord_stop", type=_parse_bool, default=True,
                   metavar="{true,false}",
                   help="multi-host: SIGTERM/SIGINT on any host is "
                        "allgathered at each step boundary so the whole "
                        "job stops together through the collective final "
                        "save (a preemption notice becomes a resumable "
                        "stop); false = default signal semantics, restart "
                        "from the last periodic save")
    p.add_argument("--collective_timeout_secs", type=float, default=0.0,
                   help=">0 arms the hung-collective watchdog: a deadline "
                        "around each dispatch/save/consensus section that "
                        "dumps per-process stacks and exits nonzero on "
                        "expiry so the launcher restarts the job instead "
                        "of hanging; 0 = off")
    p.add_argument("--rollback_snapshot_steps", type=int, default=100,
                   help="with --nan_policy rollback: host-snapshot the "
                        "gate-verified state every K steps (the restore "
                        "point)")
    p.add_argument("--max_rollbacks", type=int, default=3,
                   help="rollbacks allowed per run before the gate aborts "
                        "anyway")
    p.add_argument("--rollback_lr_backoff", type=float, default=1.0,
                   help="<1.0: multiply both base learning rates by this "
                        "on every rollback (1.0 = off)")
    p.add_argument("--max_corrupt_records", type=int, default=0,
                   help=">0: quarantine (skip + log + count) corrupt "
                        "TFRecord entries up to this budget before hard-"
                        "failing; 0 = first corruption is fatal")
    p.add_argument("--log_every_steps", type=int, default=1,
                   help="stdout loss-line cadence (1 = the reference's "
                        "every-step log; 0 = off)")
    p.add_argument("--activation_summary_steps", type=int, default=500,
                   help="per-layer activation histogram cadence (0 = off)")
    # warm start (DESIGN.md §6d)
    p.add_argument("--compile_cache_dir", default="",
                   help="non-empty wires JAX's persistent compilation "
                        "cache here (DCGAN_COMPILE_CACHE_DIR env honored "
                        "when unset): restarts deserialize already-seen "
                        "programs instead of recompiling; adoption is "
                        "surfaced as perf/compile_cache_* counters")
    p.add_argument("--compile_cache_per_process", type=_parse_bool,
                   default=False, metavar="{true,false}",
                   help="multi-host without a shared filesystem: each "
                        "process keeps its own proc<i>/ cache subdirectory "
                        "instead of the chief-writes/all-read shared store")
    p.add_argument("--aot_warmup", type=_parse_bool, default=False,
                   metavar="{true,false}",
                   help="AOT-compile every program and known future call "
                        "shape (k=1 tail, steps_per_call scan, sampler/"
                        "probe, rollback LR-backoff variant) before the "
                        "loop, with per-program perf/compile_ms timings; "
                        "pair with --compile_cache_dir so live dispatches "
                        "deserialize the warmed entries")
    # profiling (SURVEY.md §5 — trace capture the reference never had)
    p.add_argument("--profile_dir", default="",
                   help="capture a jax.profiler trace into this dir")
    p.add_argument("--profile_start_step", type=int, default=10)
    p.add_argument("--profile_num_steps", type=int, default=5)
    p.add_argument("--profile_trigger", default="",
                   help="on-demand tracing: touch this file mid-run to "
                        "capture the next --profile_num_steps steps (the "
                        "file is deleted as the ack; touch again for "
                        "another capture); each capture is digested into "
                        "perf/device/* events — compute/collective/"
                        "idle-gap ms and the device's own step time")
    p.add_argument("--timing_window", type=int, default=50,
                   help="sliding window (steps) for step-time stats")
    p.add_argument("--flight_recorder_steps", type=int, default=64,
                   help="crash flight recorder: ring of the last K "
                        "per-step telemetry records dumped as JSONL on "
                        "watchdog trip / NaN abort / coordinated stop / "
                        "uncaught exception (crash-path-only IO; 0 = off)")
    p.add_argument("--fleet_health_steps", type=int, default=0,
                   help=">0: allgather a compact per-host health vector "
                        "every N steps and write fleet/* metrics — "
                        "straggler skew (max/min step_ms), slowest host, "
                        "queue/drop/recovery totals (0 = off)")
    # mesh (replaces ps_hosts/worker_hosts/job_name/task_index,
    # image_train.py:27-36)
    p.add_argument("--mesh_data", type=int, default=-1,
                   help="data-parallel axis size (-1 = all devices)")
    p.add_argument("--mesh_model", type=int, default=1,
                   help="tensor-parallel axis size")
    p.add_argument("--d_learning_rate", type=float, default=None,
                   help="TTUR: discriminator base lr (default: learning_rate)")
    p.add_argument("--g_learning_rate", type=float, default=None,
                   help="TTUR: generator base lr (default: learning_rate)")
    p.add_argument("--lr_schedule", choices=["constant", "linear", "cosine"],
                   default="constant",
                   help="decay to 0 over max_steps (constant = reference)")
    p.add_argument("--warmup_steps", type=int, default=0)
    p.add_argument("--g_ema_decay", type=float, default=0.0,
                   help="EMA decay for a shadow copy of generator weights "
                        "used for sampling (0 = off, reference parity; "
                        "typical 0.999)")
    p.add_argument("--pipeline_gd", type=_parse_bool, default=False,
                   metavar="{true,false}",
                   help="software-pipelined G/D dispatch: the step runs as "
                        "separable stage programs (gen_fakes / d_update / "
                        "g_update) with the D step consuming the fake "
                        "batch produced during the previous step "
                        "(staleness 1, double-buffered on device, outside "
                        "the checkpoint tree) — compute-neutral per step, "
                        "but the largest program's peak temp memory drops "
                        "~15% and the stage split is the substrate for "
                        "cross-stage placement (DESIGN.md §6f). "
                        "Sequential update_mode, unconditional models, "
                        "steps_per_call=1 only")
    p.add_argument("--progressive", default="",
                   help="progressive-resolution schedule (phase table "
                        "\"RES:STEPS[:BATCH],...,RES:*\", e.g. "
                        "\"64:2000,128:2000,256:*\"): train each phase at "
                        "its resolution and switch mid-run with zero "
                        "recompiles after --aot_warmup (every phase's "
                        "programs pre-lowered AND primed at startup). "
                        "Resolutions ascend to --output_size; state "
                        "carries across the model growth (new layers init "
                        "fresh); loaders re-open at each phase's decode "
                        "resolution ({res} in --data_dir substitutes per "
                        "phase); the checkpoint sidecar records the phase "
                        "so resumes land mid-schedule correctly")
    p.add_argument("--progressive_fade_steps", type=int, default=0,
                   help=">0 with --progressive: linear fade-in over the "
                        "first N steps of each later phase (real images "
                        "blend toward their previous-resolution content; "
                        "alpha is a traced scalar, one compile per phase)")
    p.add_argument("--elastic_target_devices", type=int, default=0,
                   help=">0 arms live in-run elasticity: a second topology "
                        "surface over the first N devices is AOT-warmed at "
                        "startup, and a preemption notice (SIGUSR1, "
                        "--elastic_notice_file, or a chaos plan) shrinks "
                        "the live mesh to it — drain, reshard, resume, no "
                        "restart; a grow notice switches back. "
                        "Single-controller runs only; 0 = off")
    p.add_argument("--elastic_notice_file", type=str, default="",
                   help="with --elastic_target_devices: notice file polled "
                        "each step boundary (touch = shrink, content "
                        "'grow' = grow-back); consumed notices rename to "
                        "*.consumed and the switch record lands in *.ack")
    p.add_argument("--steps_per_call", type=int, default=1,
                   help=">1 dispatches K steps as one compiled scan program "
                        "(sheds per-dispatch RPC overhead; observability "
                        "cadences must be multiples of K)")
    p.add_argument("--backend", choices=["gspmd", "shard_map"],
                   default="gspmd",
                   help="collective strategy: gspmd = jit + sharding "
                        "annotations; shard_map = explicit per-device "
                        "psum/pmean (DP-only, composes with --use_pallas)")
    p.add_argument("--mesh_shard_opt", action="store_true",
                   help="ZeRO-1: shard optimizer state over the data axis "
                        "(reduce-scatter/all-gather weight updates)")
    p.add_argument("--zero_stage", type=int, choices=[1, 2, 3], default=1,
                   help="state-sharding stage (both backends): 1 = today's "
                        "behavior (parity); 2 = gradients + optimizer state "
                        "shard over the data axis (reduce-scatter grads, "
                        "shard-local Adam, one fused all-gather rebuilds "
                        "params per update); 3 = params + EMA additionally "
                        "stay resident sharded between steps with a just-"
                        "in-time all-gather inside each forward. Stages "
                        ">= 2 need a data axis of size > 1")
    p.add_argument("--comm_overlap", choices=["off", "bucket", "prefetch"],
                   default="off",
                   help="collective overlap plane (DESIGN §6n): off = "
                        "per-leaf ZeRO collectives (parity); bucket = pack "
                        "leaves into dtype-grouped flat buffers, one large "
                        "collective per bucket (bit-exact); prefetch "
                        "(zero_stage=3 only) = bucket plus layer-ahead "
                        "staged param gathers so gather i+1 overlaps "
                        "compute i")
    p.add_argument("--comm_bucket_mb", type=int, default=4,
                   help="bucket size cap in MiB for --comm_overlap (per "
                        "dtype group; an oversized leaf gets its own "
                        "bucket)")
    p.add_argument("--mesh_spatial", action="store_true",
                   help="use the model axis to shard image height instead of "
                        "weights (conv halo exchange; the sequence-parallel "
                        "analogue for image models)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (e.g. cpu for local debug; "
                        "overrides plugins that pin jax_platforms at startup)")
    return p


# flag name -> (config section, field); sections: "model", "mesh", "" (top).
_FLAG_FIELDS = {
    "learning_rate": ("", "learning_rate"), "beta1": ("", "beta1"),
    "batch_size": ("", "batch_size"), "max_steps": ("", "max_steps"),
    "loss": ("", "loss"), "update_mode": ("", "update_mode"),
    "n_critic": ("", "n_critic"), "grad_accum": ("", "grad_accum"),
    "gp_weight": ("", "gp_weight"),
    "r1_gamma": ("", "r1_gamma"), "r1_interval": ("", "r1_interval"),
    "grad_clip": ("", "grad_clip"), "diffaug": ("", "diffaug"),
    "label_smoothing": ("", "label_smoothing"),
    "g_ema_decay": ("", "g_ema_decay"),
    "d_learning_rate": ("", "d_learning_rate"),
    "g_learning_rate": ("", "g_learning_rate"),
    "lr_schedule": ("", "lr_schedule"), "warmup_steps": ("", "warmup_steps"),
    "steps_per_call": ("", "steps_per_call"),
    "pipeline_gd": ("", "pipeline_gd"),
    "progressive": ("", "progressive"),
    "progressive_fade_steps": ("", "progressive_fade_steps"),
    "elastic_target_devices": ("", "elastic_target_devices"),
    "elastic_notice_file": ("", "elastic_notice_file"),
    "dataset": ("", "dataset"), "data_dir": ("", "data_dir"),
    "sample_image_dir": ("", "sample_image_dir"),
    "record_dtype": ("", "record_dtype"),
    "label_feature": ("", "label_feature"),
    "prefetch_device_batches": ("", "prefetch_device_batches"),
    "synthetic_device_cache": ("", "synthetic_device_cache"),
    "synthetic_global_stream": ("", "synthetic_global_stream"),
    "async_services": ("", "async_services"),
    "checkpoint_dir": ("", "checkpoint_dir"), "sample_dir": ("", "sample_dir"),
    "save_summaries_secs": ("", "save_summaries_secs"),
    "save_model_secs": ("", "save_model_secs"),
    "max_checkpoints": ("", "max_checkpoints"),
    "sample_every_steps": ("", "sample_every_steps"),
    "fid_every_steps": ("", "fid_every_steps"),
    "fid_num_samples": ("", "fid_num_samples"),
    "log_every_steps": ("", "log_every_steps"),
    "nan_check_steps": ("", "nan_check_steps"),
    "nan_policy": ("", "nan_policy"),
    "coord_stop": ("", "coord_stop"),
    "collective_timeout_secs": ("", "collective_timeout_secs"),
    "rollback_snapshot_steps": ("", "rollback_snapshot_steps"),
    "max_rollbacks": ("", "max_rollbacks"),
    "rollback_lr_backoff": ("", "rollback_lr_backoff"),
    "max_corrupt_records": ("", "max_corrupt_records"),
    "activation_summary_steps": ("", "activation_summary_steps"),
    "compile_cache_dir": ("", "compile_cache_dir"),
    "compile_cache_per_process": ("", "compile_cache_per_process"),
    "aot_warmup": ("", "aot_warmup"),
    "profile_dir": ("", "profile_dir"),
    "profile_start_step": ("", "profile_start_step"),
    "profile_num_steps": ("", "profile_num_steps"),
    "profile_trigger": ("", "profile_trigger"),
    "flight_recorder_steps": ("", "flight_recorder_steps"),
    "fleet_health_steps": ("", "fleet_health_steps"),
    "timing_window": ("", "timing_window"), "seed": ("", "seed"),
    "arch": ("model", "arch"),
    "output_size": ("model", "output_size"), "c_dim": ("model", "c_dim"),
    "z_dim": ("model", "z_dim"), "gf_dim": ("model", "gf_dim"),
    "df_dim": ("model", "df_dim"), "num_classes": ("model", "num_classes"),
    "use_pallas": ("model", "use_pallas"),
    "pallas_fused": ("model", "pallas_fused"),
    "precision": ("", "precision"),
    "conditional_bn": ("model", "conditional_bn"),
    "attn_res": ("model", "attn_res"),
    "attn_heads": ("model", "attn_heads"),
    "seq_strategy": ("model", "attn_seq_strategy"),
    "spectral_norm": ("model", "spectral_norm"),
    "mesh_data": ("mesh", "data"), "mesh_model": ("mesh", "model"),
    "mesh_spatial": ("mesh", "spatial"), "backend": ("", "backend"),
    "mesh_shard_opt": ("mesh", "shard_opt"),
    "zero_stage": ("mesh", "zero_stage"),
    "comm_overlap": ("", "comm_overlap"),
    "comm_bucket_mb": ("", "comm_bucket_mb"),
}


def explicit_flags(argv: Optional[List[str]]) -> argparse.Namespace:
    """Namespace containing ONLY the flags the user actually passed.

    A second parse with every default suppressed — so preset defaults and
    explicit overrides can be told apart.
    """
    p = build_parser()
    for action in p._actions:
        if action.dest != "help":
            action.default = argparse.SUPPRESS
    return p.parse_args(argv)


def apply_overrides(cfg: TrainConfig, given: argparse.Namespace) -> TrainConfig:
    """Apply explicitly-passed flags on top of a preset TrainConfig."""
    top, model_kw, mesh_kw = {}, {}, {}
    for flag, value in vars(given).items():
        if flag == "no_normalize":
            top["normalize_inputs"] = not value
            continue
        if flag == "no_tensorboard":
            top["tensorboard"] = not value
            continue
        if flag not in _FLAG_FIELDS:
            continue  # preset / synthetic / platform — not config fields
        section, field = _FLAG_FIELDS[flag]
        {"": top, "model": model_kw, "mesh": mesh_kw}[section][field] = value
    if model_kw:
        top["model"] = dataclasses.replace(cfg.model, **model_kw)
    if mesh_kw:
        top["mesh"] = dataclasses.replace(cfg.mesh, **mesh_kw)
    return dataclasses.replace(cfg, **top) if top else cfg


def config_from_args(args: argparse.Namespace) -> TrainConfig:
    # Same mapping as the preset-override path (a fully-populated namespace
    # over the defaults) so there is exactly one flag->field table.
    return apply_overrides(TrainConfig(), args)


def main(argv: Optional[List[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    if args.preset:
        from dcgan_tpu.presets import get_preset
        cfg = apply_overrides(get_preset(args.preset), explicit_flags(argv))
    else:
        from dcgan_tpu.config import load_config

        from dcgan_tpu.utils.checkpoint import has_restorable_checkpoint

        saved = load_config(args.checkpoint_dir)
        if saved is not None and not has_restorable_checkpoint(
                args.checkpoint_dir):
            # ADVICE r2: a config.json from a run that died before its first
            # save must not claim the directory — a fresh launch would
            # silently inherit the dead run's entire config for every flag
            # not explicitly passed. The trainer's own arch-mismatch check
            # applies the same gate.
            print(f"[dcgan_tpu] ignoring config.json in "
                  f"{args.checkpoint_dir!r}: no restorable checkpoint step "
                  f"(stale file from a run that died before its first save)")
            saved = None
        if saved is not None:
            # Resume adopts the checkpoint's own config (VERDICT r1 #3):
            # only explicitly-passed flags override it, so
            # `dcgan_tpu.train --checkpoint_dir ckpt` resumes any
            # architecture with zero flags. checkpoint_dir is pinned to
            # where the config was found — the stored path may be stale if
            # the directory moved.
            cfg = dataclasses.replace(
                apply_overrides(saved, explicit_flags(argv)),
                checkpoint_dir=args.checkpoint_dir)
            print(f"[dcgan_tpu] adopted config.json from "
                  f"{args.checkpoint_dir!r}; explicit flags override")
        else:
            cfg = config_from_args(args)
    # echo the effective config at startup, like the reference's
    # pp.pprint(FLAGS.__flags) (image_train.py:223)
    pprint.pprint(dataclasses.asdict(cfg))

    if cfg.comm_overlap != "off":
        # Arm XLA's async-collective scheduler before jax initializes its
        # backend (TPU-only inside the helper — unknown XLA_FLAGS entries
        # are fatal on other backends, so the helper also honors an
        # explicit non-TPU --platform/JAX_PLATFORMS request). This is the
        # gspmd half of the backward-overlap story (DESIGN §6n); the
        # shard_map half is the bucketed/staged hook placement itself.
        from dcgan_tpu.parallel.comm import maybe_apply_xla_overlap_flags
        added = maybe_apply_xla_overlap_flags(
            platform=args.platform or "")
        if added:
            print(f"[dcgan_tpu] comm_overlap={cfg.comm_overlap}: armed "
                  f"{len(added)} async-collective XLA flags")

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    from dcgan_tpu.train.trainer import train
    train(cfg, synthetic_data=args.synthetic)


if __name__ == "__main__":
    main()
