"""The trainer's declared JSONL event-key inventory (ISSUE 8, DCG004).

One entry per metric key (or wildcard prefix) the trainer can emit,
mapped to the knob that gates it — "always" means the key may appear in a
default-flags run and is therefore covered by the byte-parity contract
(tests/test_services.py async-vs-inline, tests/test_chaos.py
rollback-armed-vs-default). Everything else must be invisible until its
knob activates, which is exactly what the gating annotation documents.

The static half of the enforcement is analysis/parity.py (DCG004): every
namespaced key literal in trainer.py/coordination.py must appear here, so
a new ungated key fails the lint before it fails the parity A/B. The
runtime half is tests/test_analysis.py's completeness tests: the keys
StepTimer / StartupProfile / fleet_metrics actually produce are checked
against this inventory, closing the loop for keys built from prefix
parameters the static pass cannot see.

Un-namespaced scalar keys (d_loss, g_loss, r1, gp, ...) are the device
metric dict from train/steps.py — replicated program outputs, identical
in every mode by the step-equivalence tests — and are deliberately
outside this inventory.

This module must stay import-light (no jax): the analyzer loads it on
every lint pass.
"""

from __future__ import annotations

from typing import Dict

EVENT_KEYS: Dict[str, str] = {
    # -- StepTimer window stats (utils/profiling.py, prefix "perf/") -----
    "perf/step_ms_mean": "always",
    "perf/step_ms_p50": "always",
    "perf/step_ms_p90": "always",
    "perf/step_ms_max": "always",
    "perf/steps_per_sec": "always",
    "perf/images_per_sec": "always",
    "perf/host_ms_mean": "always",
    "perf/dispatch_occupancy": "always",

    # -- startup report (written only when a warm-start knob is active;
    #    always printed to stdout) ---------------------------------------
    "perf/startup/*": "compile_cache_dir|aot_warmup",
    "perf/compile_cache_requests": "compile_cache_dir",
    "perf/compile_cache_hits": "compile_cache_dir",
    "perf/compile_cache_misses": "compile_cache_dir",
    "perf/compile_ms/*": "aot_warmup",
    "perf/restore/verify_files": "compile_cache_dir|aot_warmup",
    "perf/restore/verify_bytes": "compile_cache_dir|aot_warmup",
    "perf/restore/verify_cached_bytes": "compile_cache_dir|aot_warmup",
    "perf/restore/verify_ms": "compile_cache_dir|aot_warmup",

    # -- on-demand device-trace digest (ISSUE 6) -------------------------
    "perf/device/compute_ms": "profile_dir|profile_trigger",
    "perf/device/collective_ms": "profile_dir|profile_trigger",
    "perf/device/idle_gap_ms": "profile_dir|profile_trigger",
    "perf/device/span_ms": "profile_dir|profile_trigger",
    "perf/device/step_ms": "profile_dir|profile_trigger",
    # collective-time-hidden-behind-compute fraction (ISSUE 20): the
    # `--comm_overlap` A/B's trace-level attribution; rides the same
    # digest row as the other perf/device keys, so it stays gated on
    # the capture knobs and out of default streams
    "perf/device/overlap_frac": "profile_dir|profile_trigger",

    # -- recovery counters (absent until nonzero — the parity contract's
    #    "new keys only when the feature activates" clause) --------------
    "anomaly/rollbacks": "nan_policy=rollback",
    "data/corrupt_records": "nonzero quarantine count",

    # -- elastic topology (ISSUE 12): a restore that RESHARDED because the
    #    checkpoint's sharding sidecar names a different topology. Gated by
    #    the reshard event itself, never by a knob — same-topology streams
    #    (sidecar present, reshard path not taken) stay byte-identical ----
    "elastic/resharded": "cross-topology restore",
    "elastic/saved_processes": "cross-topology restore",
    "elastic/saved_devices": "cross-topology restore",
    "elastic/host_stage": "cross-topology restore",
    "perf/restore/reshard_ms": "cross-topology restore",
    "perf/restore/reshard_leaves": "cross-topology restore",

    # -- live in-run elasticity (ISSUE 18): one scalar row per
    #    notice-driven topology switch. Gated by the switch EVENT, not
    #    the knob — an armed-but-unnotified run emits none of these, so
    #    its stream stays byte-identical to an unarmed run (the
    #    default-off parity A/B in tests/test_live_elastic.py) ------------
    "elastic/live_notice_step": "live elasticity switch",
    "elastic/live_switch_ms": "live elasticity switch",
    "elastic/live_target_mesh": "live elasticity switch",
    "elastic/live_resumed_step": "live elasticity switch",

    # -- fleet health plane (ISSUE 6, coordination.fleet_metrics) --------
    "fleet/step_ms_max": "fleet_health_steps",
    "fleet/step_ms_min": "fleet_health_steps",
    "fleet/step_ms_skew": "fleet_health_steps",
    "fleet/slowest_host": "fleet_health_steps",
    "fleet/host_ms_max": "fleet_health_steps",
    "fleet/queue_depth_max": "fleet_health_steps",
    "fleet/dropped_total": "fleet_health_steps",
    "fleet/rollbacks_total": "fleet_health_steps",
    "fleet/corrupt_total": "fleet_health_steps",

    # -- progressive-resolution schedule (ISSUE 15): the active phase /
    #    resolution ride every scalar row of a progressive run, alpha
    #    only inside a fade window, switch_ms once per phase switch.
    #    Gated on the knob — default (fixed-resolution) streams carry
    #    none of these (parity-pinned) -------------------------------------
    "progressive/phase": "progressive schedule",
    "progressive/resolution": "progressive schedule",
    "progressive/alpha": "progressive schedule (fade window)",
    "progressive/switch_ms": "progressive schedule",

    # -- fleet health: the active progressive phase (0 in fixed-resolution
    #    runs; max across hosts — the switch is step-keyed so max == min) -
    "fleet/phase": "fleet_health_steps",

    # -- reduced-precision ladder (ISSUE 17): one startup row naming the
    #    active policy (numeric code: 0=f32, 1=bf16, 2=fp8) and the f32
    #    master-moment census from elastic/rules.py. Gated on the knob —
    #    precision="" (the default) emits neither, so default streams stay
    #    byte-identical (parity A/B-pinned); the policy STRING rides the
    #    flight-recorder header, which is crash-path-only IO ---------------
    "perf/precision/policy": "precision",
    "perf/precision/master_f32_leaves": "precision",

    # -- probes ----------------------------------------------------------
    "sample/*": "sample_every_steps",
    "eval/fid": "fid_every_steps",
    "eval/kid": "fid_every_steps",

    # -- serving plane (ISSUE 9, dcgan_tpu/serve) ------------------------
    # These keys appear only in the serve entry point's own event stream
    # (`python -m dcgan_tpu.serve --events_dir`/`--report`), never in the
    # trainer's JSONL — the trainer parity contract cannot see them by
    # construction; the annotation names the subsystem that emits them.
    # DCG004 lints serve/server.py and serve/__main__.py against this
    # inventory the same way it lints the trainer.
    "serve/requests": "serve entrypoint",
    "serve/completed": "serve entrypoint",
    "serve/dropped": "serve entrypoint",
    "serve/batches": "serve entrypoint",
    "serve/images": "serve entrypoint",
    "serve/queue_depth_max": "serve entrypoint",
    "serve/pad_frac": "serve entrypoint",
    "serve/samples_per_sec": "serve entrypoint",
    "serve/p50_ms": "serve entrypoint",
    "serve/p99_ms": "serve entrypoint",
    "serve/mean_ms": "serve entrypoint",
    "serve/restore_ms": "serve entrypoint",
    "serve/warmup_ms": "serve entrypoint",
    "serve/cold_start_ms": "serve entrypoint",
    "serve/compile_ms/*": "serve entrypoint",
    "serve/recompiles_after_warmup": "serve entrypoint (compile cache on)",

    # -- serving fleet (ISSUE 19, serve/fleet.py + router.py): the drop
    #    split makes fleet shedding attributable (overload = deliberate
    #    backpressure, failover = no healthy peer could absorb), and the
    #    fleet_* / promotion keys ride only the fleet-mode report row.
    #    DCG004 lints serve/fleet.py and serve/router.py against this
    #    inventory too. -------------------------------------------------
    "serve/dropped_overload": "serve entrypoint",
    "serve/dropped_failover": "serve entrypoint (--fleet)",
    "serve/fleet_replicas": "serve entrypoint (--fleet)",
    "serve/fleet_unhealthy": "serve entrypoint (--fleet)",
    "serve/fleet_failovers": "serve entrypoint (--fleet)",
    "serve/promotions": "serve entrypoint (weight promotion)",
    "serve/promote_swap_ms": "serve entrypoint (weight promotion)",
}
