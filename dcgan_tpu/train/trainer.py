"""The training driver: mesh bring-up, data feed, hot loop, observability.

This is the capability-parity replacement for the reference's `train()`
(image_train.py:51-194) with the cluster machinery swapped for SPMD:

reference                                   | here
--------------------------------------------|----------------------------------
ClusterSpec/Server/ps-role (55-63)          | initialize_multihost + Mesh
replica_device_setter (65-67)               | sharding rules (parallel/)
distorted_inputs + feed_dict loop (69,153)  | make_dataset -> sharded arrays
numpy batch_z feeds (151-152)               | on-device PRNG inside the step
combined D+G sess.run (156-158)             | one jitted sharded train step
Supervisor summaries @10s (155-178)         | MetricWriter (JSONL), chief-only
fixed-z 8x8 grid every 100 steps (179-192)  | sample() + save_sample_grid
Supervisor 600s checkpoints (123-129)       | Checkpointer.maybe_save
load() restore-latest (142-146)             | Checkpointer.restore_latest
per-step stdout log (160-169)               | per-step stdout log (chief)

The loop is step-bounded (max_steps, reference :150) and restartable: state
(params, BN stats, both Adam moments, step) round-trips through Orbax.

Host-services layer (docs/DESIGN.md "Host services"): the dispatch thread's
per-step work is pulling an already-transferred device batch from the
background feed queue (data/pipeline.DevicePrefetcher) and dispatching the
next compiled program; metric materialization runs lag-by-one (step N's
scalars while step N+1 computes) and every expensive writer path — param/
activation histograms, sample-grid PNGs, JSONL/TB IO — runs on the
train/services.py background worker. `--async_services=false` restores the
fully-inline loop.

Multi-host fail-operational layer (docs/DESIGN.md §6c.1,
train/coordination.py): every recovery decision that changes which
collectives run next is itself a collective — NaN-gate verdicts are
allgathered (anomaly consensus, so rollback works under multi-host with a
sharded device-resident snapshot), a signal on any host becomes a
whole-job coordinated stop through the collective final save
(`--coord_stop`), and `--collective_timeout_secs` arms a watchdog that
turns a hung collective into per-process stack dumps + a nonzero exit.

Observability plane (docs/DESIGN.md §6e): `--profile_trigger` starts an
on-demand device trace mid-run (digested in-process into `perf/device/*`
compute/collective/idle-gap attribution), the crash flight recorder
(`--flight_recorder_steps`) dumps the last K steps of telemetry on every
dying exit path, `--fleet_health_steps` allgathers a per-host health
vector into `fleet/*` straggler metrics, and one counter registry
(utils/metrics.CounterRegistry) feeds all three.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Iterator, Optional

import jax
import numpy as np

from dcgan_tpu.analysis import tripwire
from dcgan_tpu.config import TrainConfig, load_config, save_config
from dcgan_tpu.data import (
    DataConfig,
    make_dataset,
    quarantine,
    synthetic_batches,
    to_global,
)
from dcgan_tpu.parallel import (
    batch_sharding,
    initialize_multihost,
    is_chief,
    make_mesh,
    make_parallel_train,
)
from dcgan_tpu.testing import chaos
from dcgan_tpu.train import coordination, warmup
from dcgan_tpu.train.flight_recorder import FlightRecorder, recorder_path
from dcgan_tpu.train.gd_pipeline import GDPipeline
from dcgan_tpu.train.rollback import RollbackManager
from dcgan_tpu.train.services import make_services
from dcgan_tpu.utils.checkpoint import Checkpointer
from dcgan_tpu.utils.images import save_sample_grid
from dcgan_tpu.utils.metrics import (
    CounterRegistry,
    MetricWriter,
    param_histograms,
)
from dcgan_tpu.utils.profiling import StartupProfile, StepTimer, TraceCapture

Pytree = Any


def _data_iterator(cfg: TrainConfig, mesh, *, synthetic: bool,
                   data_dir: Optional[str] = None,
                   seed_offset: int = 0,
                   n_threads: Optional[int] = None,
                   min_after_dequeue: Optional[int] = None,
                   skip_batches: int = 0) -> Iterator:
    """Yields sharded image batches — (images, labels) pairs for conditional
    models (cfg.model.num_classes > 0).

    `skip_batches` fast-forwards a REBUILT iterator past batches an earlier
    incarnation already consumed (the live-elasticity switch, ISSUE 18:
    the yielded arrays are committed to the mesh, so a mesh change forces
    a rebuild — but the stream position must carry, or the synthetic
    generator restarts at batch 0 and the post-switch run diverges from
    its same-topology control). Synthetic streams skip at the host
    generator (cheap — no slicing, no upload); real-data loaders discard
    yielded batches (best-effort: a threaded shuffle stream has no exact
    position to restore anyway)."""
    sharding = batch_sharding(mesh, 4, spatial=cfg.mesh.spatial)
    conditional = cfg.model.num_classes > 0
    label_sharding = batch_sharding(mesh, 1) if conditional else None
    if synthetic:
        # to_global needs this process's ADDRESSABLE BLOCK of the
        # global batch (pipeline.process_local_box). The naive
        # per-process slice (batch/process_count x full height) is that
        # block only while each process's devices cover whole mesh
        # rows; under a spatial mesh whose "model" axis spans
        # processes, the block is a batch-slice x height-slice instead
        # — and processes sharing a batch row MUST contribute
        # height-slices of the SAME images. Seeding the stream by the
        # block's BATCH OFFSET (not the process index) guarantees
        # that: co-row processes draw identical full-height images and
        # cut different height slices, while batch-disjoint processes
        # draw distinct streams at 1/P of the global host cost.
        # Single-process keeps the exact previous stream (offset 0,
        # full box).
        from dcgan_tpu.data.pipeline import (
            DevicePrefetcher,
            process_local_box,
        )

        size = cfg.model.output_size
        box = process_local_box(
            sharding, (cfg.batch_size, size, size, cfg.model.c_dim))
        n_local = box[0].stop - box[0].start
        if cfg.synthetic_global_stream:
            # layout-invariant stream (ISSUE 12): every process draws the
            # FULL global batch from the offset-0 seed and cuts its own
            # block, so the global batch sequence is bit-identical for
            # every process layout over the same mesh — the property the
            # elastic shrink/grow drills replay losses across. Costs P x
            # the host generation; single-process (full box) it IS the
            # default stream, byte for byte.
            src = synthetic_batches(
                cfg.batch_size, size, cfg.model.c_dim,
                seed=cfg.seed + seed_offset,
                num_classes=cfg.model.num_classes)

            def cut(batch):
                if isinstance(batch, tuple):
                    return batch[0][tuple(box)], batch[1][box[0]]
                return batch[tuple(box)]
        else:
            src = synthetic_batches(
                n_local, size, cfg.model.c_dim,
                seed=cfg.seed + seed_offset + box[0].start,
                num_classes=cfg.model.num_classes)
            hwc = (box[1], box[2], box[3])

            def cut(batch):
                if isinstance(batch, tuple):
                    return batch[0][(slice(None),) + hwc], batch[1]
                return batch[(slice(None),) + hwc]

        for _ in range(skip_batches):
            next(src)
        if cfg.synthetic_device_cache > 0:
            def it():
                # pre-staged device pool, cycled forever: the loop consumes
                # already-resident sharded arrays, so measurements see the
                # trainer machinery, not the host->device transport
                pool = [to_global(cut(next(src)), sharding, label_sharding)
                        for _ in range(cfg.synthetic_device_cache)]
                while True:
                    yield from pool
            return it()
        host_batches = (cut(b) for b in src)
        if cfg.prefetch_device_batches > 0:
            # same background feed thread as the real-data path: synthetic
            # batch generation + H2D transfer overlap device compute
            # (labels, when present, are generated in-range — no gate)
            return DevicePrefetcher(host_batches, sharding, label_sharding,
                                    depth=cfg.prefetch_device_batches)

        def it():
            for batch in host_batches:
                yield to_global(batch, sharding, label_sharding)
        return it()
    if jax.process_count() > 1:
        # The file-shard ownership model (process i owns shards i, i+P, ...)
        # assumes batch-disjoint processes. A spatial mesh whose "model"
        # (height) axis spans processes makes two processes co-own one batch
        # row — they would need to assemble height-slices of the SAME
        # images, which a threaded shuffle loader cannot reproduce
        # deterministically across processes. The synthetic path supports
        # such layouts (common-seed global batch, sliced per process);
        # real data requires the model axis to fit within each process's
        # devices (height sharding then happens on-device, not at load).
        from dcgan_tpu.data.pipeline import process_local_box

        size = cfg.model.output_size
        box = process_local_box(
            sharding, (cfg.batch_size, size, size, cfg.model.c_dim))
        full = (size, size, cfg.model.c_dim)
        if any(b.stop - b.start != g for b, g in zip(box[1:], full)):
            raise ValueError(
                "real-data loading requires each process's devices to cover "
                "full images (the spatial 'model' axis must not span "
                f"processes; this process's block is {box}). Lay the mesh "
                "out with model <= local_device_count, or use synthetic "
                "data for cross-process height-sharding experiments.")
    the_dir = data_dir if data_dir is not None else cfg.data_dir
    # The dataset.json manifest's wire format is authoritative — the same
    # policy evals/__main__.py applies (no flag there at all). The
    # cfg.record_dtype knob covers manifest-less corpora (e.g. shards in
    # the reference's own layout, which has no manifest). Without this,
    # prepare's uint8 default + the trainer's float64 parity default would
    # fail the manifest check on the README quickstart.
    from dcgan_tpu.data.pipeline import read_manifest

    wire_dtype = read_manifest(the_dir).get("record_dtype",
                                            cfg.record_dtype)
    if wire_dtype != cfg.record_dtype and is_chief():
        print(f"[dcgan_tpu] adopting record_dtype={wire_dtype!r} from "
              f"{the_dir}/dataset.json (config said {cfg.record_dtype!r})")
    dcfg = DataConfig(
        data_dir=the_dir,
        image_size=cfg.model.output_size,
        channels=cfg.model.c_dim,
        batch_size=cfg.batch_size // jax.process_count(),
        record_dtype=wire_dtype,
        min_after_dequeue=min_after_dequeue if min_after_dequeue is not None
        else cfg.shuffle_buffer,
        n_threads=n_threads if n_threads is not None
        else cfg.num_loader_threads,
        seed=cfg.seed + seed_offset,
        normalize=cfg.normalize_inputs,
        label_feature=cfg.label_feature if conditional else "",
        num_classes=cfg.model.num_classes if conditional else 0,
        prefetch_device_batches=cfg.prefetch_device_batches,
        max_corrupt_records=cfg.max_corrupt_records)
    ds = make_dataset(dcfg, sharding, label_sharding)
    for _ in range(skip_batches):
        next(ds)
    return ds


def _sample_data_iterator(cfg: TrainConfig, mesh, *, synthetic: bool,
                          skip_batches: int = 0) -> Optional[Iterator]:
    """The reference's SECOND input pipeline over sample_image_dir
    (image_train.py:84), feeding the every-100-steps sample-loss probe
    (:179-192). Optional here: present in synthetic mode (held-out stream,
    different seed) or when sample_image_dir exists on disk; absent
    otherwise — the probe is skipped, not an error (the reference crashed
    without the directory)."""
    if synthetic:
        return _data_iterator(cfg, mesh, synthetic=True, seed_offset=100,
                              skip_batches=skip_batches)
    exists = os.path.isdir(cfg.sample_image_dir)
    if jax.process_count() > 1:
        # The probe runs mesh-wide collectives; every process must make the
        # same enabled/disabled decision or the job deadlocks at the first
        # probe step. Enabled only if ALL hosts see the directory.
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(np.asarray([exists]))
        all_exist = bool(np.all(gathered))
        # warn on ANY partial visibility — including the chief itself missing
        # the mount — since the probe silently disables mesh-wide
        if bool(np.any(gathered)) and not all_exist and is_chief():
            print("[dcgan_tpu] sample_image_dir "
                  f"{cfg.sample_image_dir!r} is not visible on every host "
                  f"(visibility per process: {gathered.ravel().tolist()}); "
                  "sample-loss probe disabled")
        exists = all_exist
    if exists:
        # a light pipeline: the probe consumes one batch per 100 steps, so a
        # small shuffle pool and few threads are plenty
        return _data_iterator(
            cfg, mesh, synthetic=False, data_dir=cfg.sample_image_dir,
            seed_offset=100, n_threads=2,
            min_after_dequeue=4 * cfg.batch_size,
            skip_batches=skip_batches)
    return None


def _install_stop_handlers(cfg: TrainConfig) -> coordination.CoordinatedStop:
    """Graceful shutdown: SIGTERM/SIGINT set a process-local flag the hot
    loop polls, and the loop breaks at the next step boundary to force a
    final checkpoint — a TPU-VM preemption notice becomes a resumable
    stop. One-shot: the handler restores default semantics on first
    delivery so a second signal can still kill a hung final save.

    Multi-host (ISSUE 4): handlers are installed only under
    `cfg.coord_stop`, because the flag alone is not enough — save() is a
    collective, and one process breaking out alone would deadlock the
    others. CoordinatedStop.poll() allgathers the flags at each step
    boundary so the whole job agrees to break together; with
    coord_stop=False multi-host keeps PR 3's default signal semantics (the
    job restarts from the last periodic save — the reference Supervisor's
    recovery contract, image_train.py:123-141).

    The caller restores the original handlers in a finally block so an
    exception mid-run cannot leave the flag-only handler installed on a
    process whose loop is gone."""
    stop = coordination.CoordinatedStop()
    if jax.process_count() == 1 or cfg.coord_stop:
        stop.install()
    return stop


def train(cfg: TrainConfig, *, synthetic_data: bool = False,
          max_steps: Optional[int] = None) -> Pytree:
    """Run the training loop; returns the final state pytree."""
    # form the multi-host job BEFORE deciding on signal handlers: on an
    # env-driven bring-up (JAX_COORDINATOR_ADDRESS) process_count() is
    # still 1 until this runs, and installing the flag-only handler on a
    # coord_stop=False multi-host process would swallow the first SIGTERM
    # without anyone ever polling the flag (idempotent — _train's own call
    # is then a no-op)
    initialize_multihost()
    # thread-discipline tripwire (ISSUE 8, DCGAN_THREAD_CHECKS=1): wrap
    # the collective entry points and mark THIS thread as the dispatch
    # thread for the run — any collective issued from a background thread
    # raises instead of deadlocking the mesh minutes later. Free when the
    # env knob is off (nothing wrapped, the scope is a bare yield).
    tripwire.maybe_install()
    stop = _install_stop_handlers(cfg)
    try:
        with tripwire.dispatch_scope():
            return _train(cfg, synthetic_data=synthetic_data,
                          max_steps=max_steps, stop=stop)
    finally:
        stop.restore()


def _flight_context(cfg: TrainConfig, startup: StartupProfile,
                    flight: FlightRecorder) -> dict:
    """Dump-time header context for the crash flight recorder."""
    out = {"process": jax.process_index()}
    if cfg.precision:
        # name the active precision policy in every crash dump (ISSUE 17):
        # a NaN abort under bf16/fp8 must be attributable to the ladder at
        # a glance. Crash-path-only IO — absent under the default policy,
        # so this never touches the parity-pinned event stream.
        out["precision"] = cfg.precision
    if not startup.done:
        # ISSUE 6 satellite: a run that died before its first step ships
        # the startup phases it DID complete (init/restore/warmup so far)
        # instead of losing the breakdown with the crash
        out["startup_partial"] = {k: round(v, 1) for k, v in
                                  startup.summary().items()}
    if flight.note:
        out["fleet_note"] = flight.note
    return out


def _train(cfg: TrainConfig, *, synthetic_data: bool,
           max_steps: Optional[int],
           stop: coordination.CoordinatedStop) -> Pytree:
    initialize_multihost()
    # Warm start (DESIGN.md §6d): the persistent compile cache must be
    # configured before the FIRST compile of this run (pt.init in the run
    # body), and after the multi-host bring-up (per-process keying reads
    # the real process index). Startup phases are profiled from here —
    # restarts are this trainer's normal response to faults (PRs 3-4), so
    # time-to-first-step is tracked like throughput.
    startup = StartupProfile()
    # Crash flight recorder (ISSUE 6, DESIGN.md §6e): created before ANY
    # fallible setup so a death in config validation, restore, or warmup
    # still dumps (with the partial startup breakdown); the ring fills
    # once the loop records steps. Crash-path-only IO — nothing is
    # written unless the run dies.
    flight = FlightRecorder(
        recorder_path(cfg.checkpoint_dir),
        capacity=cfg.flight_recorder_steps,
        context=lambda: _flight_context(cfg, startup, flight))
    cache_dir = warmup.configure_compile_cache(
        warmup.resolve_cache_dir(cfg.compile_cache_dir),
        per_process=cfg.compile_cache_per_process)
    cache_mon = warmup.CompileCacheMonitor() if cache_dir is not None \
        else None
    try:
        return _train_run(cfg, synthetic_data=synthetic_data,
                          max_steps=max_steps, stop=stop, startup=startup,
                          cache_dir=cache_dir, cache_mon=cache_mon,
                          flight=flight)
    except BaseException as e:
        # every non-returning exit ships the telemetry ring: the NaN
        # abort keeps its step attribution (the gate stamps e.step), any
        # other exception records where the loop had gotten to. The dump
        # is best-effort by contract — it can never mask the error.
        flight.dump("nan-abort" if isinstance(e, FloatingPointError)
                    else "exception",
                    step=getattr(e, "step", None),
                    extra={"error": repr(e)[:500]})
        raise
    finally:
        if cache_mon is not None:
            # unregister the monitoring listeners on EVERY exit — config
            # validation errors and failed warmups included; a process
            # that calls train() again (tests, drills) must not accumulate
            # double-counting listeners
            cache_mon.close()


def _train_run(cfg: TrainConfig, *, synthetic_data: bool,
               max_steps: Optional[int],
               stop: coordination.CoordinatedStop, startup: StartupProfile,
               cache_dir: Optional[str],
               cache_mon, flight: FlightRecorder) -> Pytree:
    if cfg.fid_every_steps and jax.process_count() > 1 \
            and cfg.fid_num_samples % jax.process_count():
        raise ValueError(
            f"fid_num_samples ({cfg.fid_num_samples}) must divide evenly "
            f"over {jax.process_count()} processes — the in-training probe "
            "splits the sample budget per process (VERDICT r2 #5)")
    total_steps = max_steps if max_steps is not None else cfg.max_steps
    with startup.phase("init"):
        mesh = make_mesh(cfg.mesh)
        # Progressive-resolution schedule (ISSUE 15, DESIGN.md §6j):
        # resolution becomes a scheduled training dimension — the run is a
        # sequence of phases, each with its own compiled ParallelTrain
        # surface over the ONE shared mesh. The runtime owns the phase
        # table, the per-phase surfaces, and the cross-phase state carry;
        # pt below always points at the CURRENT phase's surface. None for
        # fixed-resolution runs — every progressive branch is strictly
        # opt-in (the parity contract).
        prog = None
        if cfg.progressive:
            from dcgan_tpu.progressive import PhaseRuntime, parse_schedule

            prog = PhaseRuntime(
                cfg, mesh,
                parse_schedule(cfg.progressive, model=cfg.model,
                               batch_size=cfg.batch_size,
                               max_steps=cfg.max_steps,
                               steps_per_call=cfg.steps_per_call,
                               grad_accum=cfg.grad_accum,
                               fade_steps=cfg.progressive_fade_steps),
                total_steps, make_pt=make_parallel_train)
            pt = None  # chosen after the latest checkpoint step is known
        else:
            pt = make_parallel_train(cfg, mesh)
    chief = is_chief()
    # Pipelined G/D dispatch (ISSUE 7, DESIGN.md §6f): the step runs as
    # three stage programs with the D step consuming the fake stack
    # produced during the PREVIOUS step (staleness 1). The stack lives in
    # this trainer-held buffer, OUTSIDE the checkpoint pytree — both modes
    # save/restore the identical state tree. None under the default fused
    # mode: every pipeline branch below is strictly opt-in, so the
    # default-flags dispatch stream and event values are untouched (the
    # parity contract).
    pipeline = GDPipeline() if cfg.pipeline_gd else None
    # Live in-run elasticity (ISSUE 18, DESIGN.md §6l): a preemption/
    # capacity notice switches the run onto `--elastic_target_devices`
    # (or back) WITHOUT a restart. Two halves, both strictly opt-in (every
    # live_* branch below is gated on live_rt, so the default dispatch
    # stream and event bytes are untouched — the parity contract):
    # NoticePlane folds the local notice sources (touch file, SIGUSR1,
    # chaos fault) into a boundary-poll consensus with the stop plane's
    # shape, and LiveTopologyRuntime holds one warmed ParallelTrain per
    # topology so the switch dispatches only cached executables. The
    # runtime adopts the launch surface built above; the target surface
    # builds lazily (warmup builds it eagerly so both get primed).
    live_rt = None
    notice = None
    if cfg.elastic_target_devices:
        from dcgan_tpu.elastic import live as live_elastic

        live_rt = live_elastic.LiveTopologyRuntime(
            cfg, mesh, make_pt=make_parallel_train, launch_pt=pt)
        notice = live_elastic.NoticePlane(cfg.elastic_notice_file)
        notice.install()
    # the quarantine tally is process-global (it spans both loader
    # implementations and the train+sample pipelines); this run reports its
    # own delta — captured BEFORE any loader thread starts — so counts from
    # an earlier run in the same process don't bleed into the event stream
    corrupt_base = quarantine.count()

    ckpt = Checkpointer(cfg.checkpoint_dir,
                        save_interval_secs=cfg.save_model_secs,
                        save_interval_steps=cfg.save_model_steps,
                        max_to_keep=cfg.max_checkpoints)

    # Checkpoints carry their config (VERDICT r1 #3): a resume with a
    # different architecture must fail HERE with a readable message, not
    # deep inside Orbax as a tree/shape mismatch; generate/evals read the
    # same file so sampling needs zero architecture flags. The check is
    # gated on an actual checkpoint existing — a stale config.json from a
    # run that died before its first save must not claim the directory.
    saved_cfg = load_config(cfg.checkpoint_dir)
    if saved_cfg is not None and ckpt.latest_step() is not None \
            and saved_cfg.model != cfg.model:
        changed = {
            f.name: (getattr(saved_cfg.model, f.name),
                     getattr(cfg.model, f.name))
            for f in dataclasses.fields(cfg.model)
            if getattr(saved_cfg.model, f.name) != getattr(cfg.model, f.name)}
        raise ValueError(
            f"checkpoint_dir {cfg.checkpoint_dir!r} holds a run with a "
            f"different architecture (saved != requested): {changed}. "
            "Resume without architecture flags (the config.json is "
            "adopted), or point --checkpoint_dir at a fresh directory.")
    if chief:
        save_config(cfg, cfg.checkpoint_dir)
    writer = MetricWriter(cfg.checkpoint_dir,
                          every_secs=cfg.save_summaries_secs,
                          enabled=chief,
                          tensorboard=cfg.tensorboard)

    # Progressive resume (ISSUE 15): the restore template must be the
    # phase tree that PRODUCED the latest checkpoint — a boundary-step
    # save carries the pre-switch tree (the switch below runs before the
    # first new-phase dispatch), so the schedule-derived phase is
    # deterministic; the sidecar's phase tag cross-checks it, catching a
    # --progressive spec edited between runs before Orbax turns it into
    # an opaque tree mismatch. The tag itself is stamped on every save.
    if prog is not None:
        latest = ckpt.latest_step()
        prog.start(latest)
        if latest is not None:
            from dcgan_tpu.elastic import sidecar as _sidecar

            payload = _sidecar.read(cfg.checkpoint_dir, latest) or {}
            prog.check_resume_tag(payload.get("progressive"), latest)
        pcfg = prog.cfg
        pt = prog.pt
        ckpt.progressive_tag = prog.tag()
        if chief:
            print(f"[dcgan_tpu] progressive schedule "
                  f"{cfg.progressive!r}: starting in phase {prog.index} "
                  f"(r{prog.resolution}, batch {pcfg.batch_size}, "
                  f"{prog.n_phases} phase(s) this run)", flush=True)
    else:
        pcfg = cfg

    with startup.phase("init"):
        state = pt.init(jax.random.key(cfg.seed))
    with startup.phase("restore"):
        restored = ckpt.restore_latest(state)
    if restored is not None:
        state = restored
        if chief:
            print(f"[dcgan_tpu] restored checkpoint at step "
                  f"{int(jax.device_get(state['step']))}")

    # NaN rollback-and-skip (train/rollback.py): under nan_policy="rollback"
    # a last-good snapshot is refreshed every K steps and a gate trip
    # restores it instead of aborting; None under the default policy — the
    # snapshot cost is strictly opt-in. Single-process keeps the host copy
    # (zero extra HBM); multi-host keeps a sharded DEVICE-RESIDENT copy —
    # each process holds only its addressable shards, and the jitted
    # snapshot/restore copies run on every process at the same
    # consensus-agreed point (ISSUE 4: the decision to take this branch is
    # itself allgathered in _nan_gate, so the dispatches stay
    # mesh-consistent).
    rollback = None
    if cfg.nan_policy == "rollback":
        rollback = RollbackManager(every=cfg.rollback_snapshot_steps,
                                   max_rollbacks=cfg.max_rollbacks,
                                   lr_backoff=cfg.rollback_lr_backoff,
                                   chief=chief,
                                   device_resident=jax.process_count() > 1)

    # fixed z for comparable sample grids across the run — drawn once, like
    # the reference's graph-build-time sample_z (image_train.py:77)
    rows, cols = cfg.sample_grid
    n_samples = max(cfg.sample_size, rows * cols)
    data_axis = mesh.shape["data"]
    n_samples = -(-n_samples // data_axis) * data_axis  # data-axis multiple
    sample_z = jax.random.uniform(
        jax.random.key(cfg.seed + 1), (n_samples, cfg.model.z_dim),
        minval=-1.0, maxval=1.0)
    sample_labels = None
    if cfg.model.num_classes:
        sample_labels = jax.numpy.arange(sample_z.shape[0]) \
            % cfg.model.num_classes

    rebucketer = None
    with startup.phase("data"):
        if prog is not None:
            # mid-run re-bucketing (ISSUE 15, progressive/rebucket.py):
            # the loaders bake decode resolution and batch into their
            # threads at construction, so each phase switch closes and
            # re-opens them through this one factory — same iterators the
            # fixed-resolution path builds, pointed at the phase config
            # (with {res} data-dir placeholders resolved per phase)
            from dcgan_tpu.progressive import Rebucketer

            def _open_phase(phase_cfg):
                d = _data_iterator(phase_cfg, mesh,
                                   synthetic=synthetic_data)
                s = _sample_data_iterator(phase_cfg, mesh,
                                          synthetic=synthetic_data) \
                    if cfg.sample_every_steps else None
                return d, s
            rebucketer = Rebucketer(_open_phase)
            data, sample_data = rebucketer.open(pcfg)
        else:
            data = _data_iterator(cfg, mesh, synthetic=synthetic_data)
            # The global-mesh held-out stream feeds the sample-loss probe
            # and, in single-process runs, the FID probe's real side; the
            # multihost FID probe streams its own local-mesh iterator
            # instead, so don't spin a producerless loader for it.
            sample_data = _sample_data_iterator(
                cfg, mesh, synthetic=synthetic_data) \
                if cfg.sample_every_steps or (cfg.fid_every_steps
                                              and jax.process_count() == 1) \
                else None
    # fixed z for the loss probe, tiled to the probe batch size (the
    # reference feeds the same sample_z every time, image_train.py:77,181)
    eval_z = jax.numpy.resize(sample_z, (pcfg.batch_size, cfg.model.z_dim)) \
        if sample_data is not None else None
    base_key = jax.random.key(cfg.seed + 2)
    conditional = cfg.model.num_classes > 0

    # In-training surrogate FID/KID probe (evals/ rig; fid_every_steps > 0).
    # Single-process: streams the shared held-out iterator and samples via
    # pt.sample. Multi-host (VERDICT r2 #5): the probe splits the budget per
    # process through the evals rig's distributed scoring path — each
    # process streams its own real share over a LOCAL mesh (the global-mesh
    # sample_data yields arrays this host cannot fully address) and
    # generates with a process-distinct z stream on a local sampler (the
    # global-mesh pt.sample is a collective over one shared z — the wrong
    # program for split scoring, same reasoning as evals/__main__), then
    # the moment statistics and reservoirs all-gather into one global score
    # identical on every process.
    fid_feature = None
    fid_probe_data = None  # multihost: per-process local-mesh stream
    n_proc = jax.process_count()
    if cfg.fid_every_steps:
        if n_proc > 1:
            from dcgan_tpu.config import MeshConfig

            if not synthetic_data and os.path.isdir(cfg.sample_image_dir):
                # Same guard as evals --multihost: with fewer shards than
                # processes, shard_for_process falls back to "everyone
                # reads everything" and the merged real moments sample
                # with replacement — a silently biased score driving
                # best-checkpoint retention.
                from dcgan_tpu.data.pipeline import list_shards

                n_shards = len(list_shards(cfg.sample_image_dir))
                if n_shards < n_proc:
                    raise ValueError(
                        f"the multihost FID probe needs at least one "
                        f"TFRecord shard per process for a disjoint real "
                        f"split: {n_shards} shard(s) < {n_proc} processes "
                        f"in {cfg.sample_image_dir!r} (re-shard with "
                        f"`python -m dcgan_tpu.data.prepare "
                        f"--num_shards {n_proc}`)")
            probe_mesh = make_mesh(MeshConfig(), jax.local_devices())
            fid_probe_data = _sample_data_iterator(cfg, probe_mesh,
                                                   synthetic=synthetic_data)
        else:
            fid_probe_data = sample_data
        if fid_probe_data is None:
            raise ValueError(
                "fid_every_steps needs a held-out stream: provide "
                "sample_image_dir (or run synthetic), the same source the "
                "sample-loss probe uses")
        from dcgan_tpu.evals.features import make_random_feature_fn

        fid_feature = make_random_feature_fn(cfg.model.output_size,
                                             cfg.model.c_dim)
    fid_real_side = None  # (StreamingStats, FeaturePool) after first probe
    fid_best = float("inf")
    fid_local_sampler = None  # lazy jit, multihost probe only
    best_ckpt = None      # lazy Checkpointer for checkpoint_dir/best
    if cfg.fid_every_steps:
        # resume re-seeds the best score from the persisted record —
        # otherwise the first post-restart probe (fid < inf) would
        # OVERWRITE a genuinely better pre-preemption best checkpoint
        # (max_to_keep=1 deletes it)
        import json

        try:
            with open(os.path.join(cfg.checkpoint_dir, "best",
                                   "score.json")) as f:
                fid_best = float(json.load(f)["fid"])
        except (OSError, ValueError, KeyError, TypeError):
            pass
        if n_proc > 1:
            # score.json lives on the chief's filesystem; every process
            # must carry the SAME best score or the collective best-save
            # deadlocks when branches diverge
            from jax.experimental import multihost_utils

            fid_best = float(multihost_utils.broadcast_one_to_all(
                np.asarray(fid_best, np.float64)))

    # AOT warmup (DESIGN.md §6d): compile every program and every known
    # future call shape up front — the k=1 n_critic tail, the
    # steps_per_call scan, the sampler/probe/summarize shapes, and (when
    # the cache can make it stick) the rollback LR-backoff rebuild variant
    # as a fully-built pre-warmed ParallelTrain. With the persistent cache
    # active the loop's first dispatches deserialize the warmed entries, so
    # `warm_proof` below can seed the watchdog's mesh-warm gate and
    # `compiled_ks` exemption set from warmup proof instead of waiting for
    # first live steps.
    # "fleet-warm": every process's live dispatches will HIT the primed
    # cache — true single-process and in the shared-dir multi-host mode,
    # false for per-process dirs under multi-host (jaxlib writes entries
    # chief-only, so non-chief proc<i>/ stores never fill and their live
    # dispatches still compile). Everything that assumes warm hits —
    # watchdog warm proof, the compiled_ks seed, the pre-warmed backoff
    # swap that deliberately skips the recompile exemption — rides on this
    # one predicate, never on the cache dir merely being set.
    cache_fleet_wide = cache_dir is not None and \
        warmup.cache_serves_all_processes(cfg.compile_cache_per_process)
    pt_backoff = None   # pre-warmed LR-backoff surface for the 1st rollback
    warm_ms: dict = {}
    warm_base = None    # cache counters at end of warmup: the progressive
                        # switch prints its compile-request delta from here
    if cfg.aot_warmup:
        if chief and cache_dir is None:
            print("[dcgan_tpu] --aot_warmup without --compile_cache_dir: "
                  "warmed programs are recompiled at first live dispatch "
                  "(compile timings still recorded); set a cache dir so "
                  "dispatches deserialize the warmed entries", flush=True)
        if chief and cache_dir is not None and not cache_fleet_wide:
            print("[dcgan_tpu] --compile_cache_per_process under "
                  "multi-host: this jaxlib writes cache entries from the "
                  "chief only, so non-chief proc<i>/ stores stay empty — "
                  "warm restarts still recompile there, and warmup is NOT "
                  "used as watchdog warm proof (use one shared "
                  "--compile_cache_dir to warm the whole fleet)",
                  flush=True)
        with startup.phase("warmup"):
            if prog is not None:
                # progressive warmup (ISSUE 15): EVERY phase's programs
                # enter the plan up front (@r<res> rows for the other
                # phases), then each is PRIMED with one throwaway
                # dispatch — the PR 9 serve-plane mechanism that makes
                # zero-compile-requests-after-warmup literal, so a
                # mid-run resolution switch dispatches only
                # already-executed programs
                plan = prog.build_warmup_plan(
                    state,
                    sample_z=sample_z if cfg.sample_every_steps else None,
                    sample_labels=sample_labels)
                warm_ms = warmup.aot_compile(plan)
                prime_ms = prog.prime(
                    sample_z=sample_z if cfg.sample_every_steps else None,
                    sample_labels=sample_labels)
                if chief:
                    print("[dcgan_tpu] progressive warmup primed "
                          + ", ".join(f"{k} {v:.0f}ms"
                                      for k, v in prime_ms.items()),
                          flush=True)
            elif live_rt is not None:
                # live-elastic warmup (ISSUE 18): BOTH topologies' programs
                # enter the plan (@t<data>x<model> rows for the target
                # submesh), then each topology is primed with one
                # throwaway dispatch per program — the same PR 14
                # mechanism, transposed from resolution phases to mesh
                # change, so a notice-driven switch dispatches only
                # already-executed programs (compile-request delta 0)
                plan = live_rt.build_warmup_plan(
                    state,
                    sample_z=sample_z if cfg.sample_every_steps else None,
                    sample_labels=sample_labels)
                warm_ms = warmup.aot_compile(plan)
                prime_ms = live_rt.prime(
                    sample_z=sample_z if cfg.sample_every_steps else None,
                    sample_labels=sample_labels)
                if chief:
                    print("[dcgan_tpu] live-elastic warmup primed "
                          + ", ".join(f"{k} {v:.0f}ms"
                                      for k, v in prime_ms.items()),
                          flush=True)
            else:
                plan, pt_backoff = warmup.build_warmup_plan(
                    cfg, pt, state,
                    sample_z=sample_z if cfg.sample_every_steps else None,
                    sample_labels=sample_labels, eval_z=eval_z,
                    make_backoff_pt=(lambda c: make_parallel_train(c, mesh))
                    if cache_fleet_wide else None)
                warm_ms = warmup.aot_compile(plan)
            # every peer past its compiles before anyone proceeds: the warm
            # proof the watchdog gate needs, and the point where startup
            # skew is paid once instead of surfacing inside guarded windows
            coordination.warmup_barrier()
        if cache_mon is not None:
            warm_base = cache_mon.counters()
        if chief:
            print("[dcgan_tpu] aot warmup compiled "
                  + f"{len(warm_ms)} program(s): "
                  + ", ".join(f"{k} {v:.0f}ms"
                              for k, v in warm_ms.items()), flush=True)
    # priming (progressive) warms every process's in-process dispatch
    # caches directly, so it is warm proof even without a fleet-wide
    # persistent cache; the plain AOT path still needs cache hits to stick
    warm_proof = cfg.aot_warmup and (
        cache_fleet_wide or (prog is not None and prog.primed)
        or (live_rt is not None and live_rt.primed))

    start_step = int(jax.device_get(state["step"]))
    t_start = time.time()
    metrics = {}
    timer = StepTimer(window=cfg.timing_window,
                      images_per_step=pcfg.batch_size)

    # Async host services (train/services.py): every non-step host action —
    # metric materialization, param/activation histograms, sample-grid PNG
    # encode, JSONL/TB IO — runs on a single background worker so the
    # dispatch thread's only per-step jobs are pulling a prefetched device
    # batch and dispatching the next program. Mesh-wide collectives (the
    # FID probe's all-gathers, Orbax collective saves, the pt.summarize/
    # pt.sample dispatches themselves) stay HERE on the dispatch thread:
    # collectives issued from per-process background threads have no
    # cross-process ordering guarantee against this thread's and would
    # deadlock the mesh. cfg.async_services=False degrades every submit()
    # to an inline call at the same site — the pre-async loop structure,
    # same metric values and event ordering.
    svc = make_services(cfg.async_services)
    deferred = cfg.async_services

    # Counter registry (ISSUE 6, utils/metrics.py): ONE typed read surface
    # over the counters that previously lived in four unrelated places —
    # the scalar rows' recovery extras, the flight-recorder records, and
    # the fleet health vector all read the same snapshot, so they can
    # never drift apart on what "the run's counters" means.
    registry = CounterRegistry()
    registry.provide("services_queue", svc.pending)
    registry.provide("services_dropped",
                     lambda: int(getattr(svc, "dropped", 0)))
    registry.provide("corrupt_records",
                     lambda: quarantine.count() - corrupt_base)
    if rollback is not None:
        registry.provide("rollbacks", lambda: rollback.rollbacks)
    if prog is not None:
        # flight-recorder records and the fleet health vector both name
        # the active phase through the one counter surface (ISSUE 15)
        registry.provide("progressive_phase", lambda: prog.index)
    if live_rt is not None:
        # the ACTIVE topology's device count (ISSUE 18): flight-recorder
        # dumps after a switch name the mesh the run was actually on
        registry.provide("live_topology", lambda: live_rt.device_count)
    if cache_mon is not None:
        registry.provide_group(
            ("compile_cache_requests", "compile_cache_hits",
             "compile_cache_misses"),
            lambda: {"compile_cache_" + k: v
                     for k, v in cache_mon.counters().items()})
    master_f32 = 0
    if cfg.precision:
        # f32 master-moment census (ISSUE 17): counted ONCE at startup —
        # the optimizer tree's dtype layout is static for the run — and
        # exposed through the one counter surface so flight-recorder dumps
        # and the fleet health vector can both see a bf16 run that lost
        # its master copy (count 0 where the param census says sub-f32)
        from dcgan_tpu.elastic.rules import count_master_f32_leaves

        master_f32 = count_master_f32_leaves(state)
        registry.provide("master_f32_leaves", lambda: master_f32)

    # Trace capture (ISSUE 6): the scheduled window arms only when
    # --profile_dir was explicitly set (its PR-1 contract); the trigger
    # file adds ON-DEMAND capture — touch it mid-run, the next boundary
    # starts a profile_num_steps capture, and the digest below turns the
    # closed capture into perf/device/* attribution without any offline
    # tool pass. Trigger-only runs park traces under checkpoint_dir/trace.
    trace_dir = cfg.profile_dir or (
        os.path.join(cfg.checkpoint_dir, "trace")
        if cfg.profile_trigger else "")

    # call sizes (k) dispatched while a capture window was open: the digest
    # normalizes the busiest program's median by the LARGEST k actually in
    # the window, not cfg.steps_per_call — a window caught entirely inside
    # a k=1 realign/tail stretch would otherwise report a step time
    # steps_per_call x too small
    capture_ks: list = []

    def _on_trace_capture(stop_step: int) -> None:
        """A capture just closed: resolve THE file it wrote here on the
        dispatch thread (one glob — back-to-back captures or shared-dir
        peers would misattribute a worker-time "newest" lookup), then
        digest it on the services worker — host-local file IO + parsing
        only, so the collective-thread rule is untouched. Chief-only:
        peers capture traces (per-process timelines are themselves useful
        artifacts) but only the chief materializes events."""
        ks = capture_ks[:]
        del capture_ks[:]
        if not chief:
            return
        spc = max(ks) if ks else max(1, cfg.steps_per_call)
        import socket

        from dcgan_tpu.utils.trace import digest, find_trace, stage_step_ms
        try:
            trace_path = find_trace(trace_dir, host=socket.gethostname())
        except OSError as e:
            print(f"[dcgan_tpu] trace capture ending at step {stop_step} "
                  f"left no trace file: {e!r}", flush=True)
            return

        def _digest_task(s=stop_step, path=trace_path):
            d = digest(path)
            if d["source"] == "none":
                print(f"[dcgan_tpu] trace capture ending at step {s} has "
                      "no device events; nothing to digest", flush=True)
                return
            step_ms = d["program_ms_median"] / spc
            if cfg.pipeline_gd:
                # pipelined dispatch (ISSUE 7): one trainer step is the
                # d_update AND g_update executions — the busiest-program
                # median alone would report roughly half a step. Sum the
                # stage medians when the track names the stage programs
                # (TPU module tracks do; the CPU op-level fallback keeps
                # the busiest-program estimate).
                step_ms = stage_step_ms(d) or step_ms
            row = {
                "perf/device/compute_ms": d["compute_ms"],
                "perf/device/collective_ms": d["collective_ms"],
                "perf/device/idle_gap_ms": d["idle_gap_ms"],
                "perf/device/span_ms": d["span_ms"],
                # the device's own per-step time: the busiest program's
                # median execution, normalized for scanned multi-step
                # dispatch (stage-summed under --pipeline_gd)
                "perf/device/step_ms": step_ms,
                # collective time hidden behind compute (ISSUE 20): the
                # --comm_overlap A/B's trace-level attribution
                "perf/device/overlap_frac": d["overlap_frac"],
            }
            print(f"[dcgan_tpu] trace digest (ending step {s}, "
                  f"{d['source']} track, top program {d['program']!r} "
                  f"x{d['program_n']}): "
                  + " ".join(f"{k.rsplit('/', 1)[1]}={v:.3f}"
                             for k, v in row.items()), flush=True)
            writer.write_scalars(s, row)
        svc.submit(_digest_task, tag="trace-digest")

    trace = TraceCapture(trace_dir,
                         start_step=start_step + cfg.profile_start_step,
                         num_steps=cfg.profile_num_steps,
                         schedule=bool(cfg.profile_dir),
                         trigger_path=cfg.profile_trigger,
                         # chief-only removal: peers key off the mtime, so
                         # a shared-filesystem fleet all captures one touch
                         # and the digesting process can never lose the
                         # remove race
                         consume=chief,
                         on_capture=_on_trace_capture)

    # Hung-collective watchdog (train/coordination.py; off at the default
    # collective_timeout_secs=0): a deadline around each dispatch/consume
    # window, consensus allgather, and collective save. Expiry dumps every
    # thread's stack with step+phase context and exits nonzero so the
    # launcher restarts the job from the last checkpoint instead of letting
    # one lost peer hang the whole pod forever. The first loop iteration's
    # dispatch is exempt (it compiles); the FID probe and sample/summarize
    # telemetry tails are deliberately unguarded (legitimately long or
    # droppable — not the collectives that wedge a mesh). A trip now also
    # dumps the flight-recorder ring (ISSUE 6) so the stacks arrive with
    # the telemetry that led up to them.
    watchdog = coordination.make_watchdog(
        cfg.collective_timeout_secs,
        pre_dump=lambda phase, step: flight.dump(
            "watchdog", step=step, extra={"phase": phase}))

    # The watchdog must not arm until the mesh is PROVEN warm: compile
    # time is per-process, so right after THIS process's first dispatch a
    # guarded collective can legitimately block for however long the
    # SLOWEST peer's compile takes (startup skew), and a deadline there
    # would kill a healthy job. "Warm" = proof that every peer is past its
    # first compile: the first metric readback completing (_host_vals), a
    # boundary-N>0 stop poll returning (each device stream runs that
    # allgather only after its step program), or — ISSUE 5 — warmup proof:
    # every peer returned from the AOT warmup barrier with the persistent
    # cache primed, so live dispatches deserialize (bounded IO) instead of
    # compiling. Single-process has no peer skew to wait out.
    mesh_warm = n_proc == 1 or warm_proof

    def _guard(phase: str, step: int):
        """A watchdog guard that is a free no-op until the mesh is warm."""
        return watchdog.guard(phase, step) if mesh_warm \
            else coordination.NULL_GUARD

    if rollback is not None and pipeline is not None:
        # Drain-before-restore (ISSUE 7): the in-flight fake stack was
        # generated by the diverged weights the rollback is fleeing — it
        # must never train the restored state, and its device memory must
        # be free before the restore copies allocate. Parked on the
        # manager's restore hook (structurally tied to restore(), so no
        # call site can forget it); the nested guard names the phase if a
        # drain-window hang trips the watchdog, then hands the deadline
        # back to the enclosing rollback-restore arm.
        def _drain_for_restore():
            with _guard("pipeline-drain", step_num):
                if pipeline.drain("rollback") and chief:
                    print("[dcgan_tpu] rollback drained the in-flight "
                          "pipelined fake stack (stale generator output; "
                          "refilled from the restored state at the next "
                          "dispatch)", flush=True)
        rollback.on_restore = _drain_for_restore

    def _stage(tree) -> None:
        """Start D2H copies of a dispatched program's outputs now, so the
        background worker's device_get finds them (mostly) materialized."""
        for leaf in jax.tree_util.tree_leaves(tree):
            leaf.copy_to_host_async()

    def _snapshot_params(params):
        """A capture of `params` that survives the next step's buffer
        donation, for the background histogram writer.

        Single-process: a device-side copy (rollback.device_copy — the
        same jitted identity the snapshot manager uses) producing fresh
        buffers (pt.step's donate_argnums only invalidates the ORIGINAL
        leaves), which the worker device_gets while the next steps run.
        Multi-process: a synchronous device_get on the dispatch thread —
        the copy program would be a mesh-wide dispatch, and the histogram
        tick is chief-only + wall-clock-gated, so dispatching it from one
        process would wedge the other processes' collective queues (same
        reason the FID probe stays on this thread); only the histogram
        reduction + file IO move to the worker there."""
        if deferred and n_proc == 1:
            from dcgan_tpu.train.rollback import device_copy

            snap = device_copy(params)
            _stage(snap)
            return snap
        # owned_host_copy, not bare device_get: the histogram must capture
        # THIS step's params, not whatever the next donated dispatch
        # leaves in the buffer a cache-deserialized executable overwrote
        # in place (utils/checkpoint.owned_host_copy owns the workaround)
        from dcgan_tpu.utils.checkpoint import owned_host_copy

        return owned_host_copy(params)

    def _host_vals(p: dict) -> dict:
        """Materialized {name: float} metric scalars for one step's record,
        cached on the record — ONE transfer shared by every consumer
        (NaN gate, step log, summary writer); per-scalar float() would
        issue a device round-trip each (~0.65 ms/step measured over a
        high-latency transport, tools/bench_trainer_loop.py's 3.75 vs
        3.09 ms/step gap)."""
        nonlocal mesh_warm
        if p.get("host") is None:
            p["host"] = {k: float(v) for k, v in
                         jax.device_get(p["metrics"]).items()}
            # a completed cross-process readback is the warm proof the
            # watchdog gating waits for (see mesh_warm above)
            mesh_warm = True
            if not startup.done:
                # first proven device-progress point = time-to-first-step
                startup.first_step()
                _report_startup(p["step"])
        return p["host"]

    def _report_startup(step: int) -> None:
        """One startup-breakdown report per run, at the first completed
        step: phase ms + compile-cache counters + per-program warmup
        compile ms + restore stats. Always printed (stdout is free);
        written as JSONL perf/ keys ONLY when a warm-start knob is active —
        default-flags event streams stay byte-identical (parity contract).
        """
        row = startup.summary()
        if cache_mon is not None:
            c = cache_mon.counters()
            row.update({
                "perf/compile_cache_requests": c["requests"],
                "perf/compile_cache_hits": c["hits"],
                "perf/compile_cache_misses": c["misses"],
            })
        for name, ms in warm_ms.items():
            row[f"perf/compile_ms/{name}"] = ms
        rs = ckpt.last_restore_stats
        if rs is not None:
            row.update({
                "perf/restore/verify_files": rs["files"],
                "perf/restore/verify_bytes": rs["bytes_read"],
                "perf/restore/verify_cached_bytes": rs["bytes_cached"],
                "perf/restore/verify_ms": rs["verify_ms"],
            })
        rr = ckpt.last_reshard
        if rr is not None:
            # cross-topology restore (ISSUE 12): reshard cost joins the
            # startup breakdown so tools/bench_startup.py's cross arm can
            # report it alongside TTFS
            row.update({
                "perf/restore/reshard_ms": rr["reshard_ms"],
                "perf/restore/reshard_leaves": rr["leaves"],
            })
        if chief:
            import json as _json

            print("[dcgan_tpu] startup "
                  + _json.dumps({k: round(v, 1) for k, v in row.items()}),
                  flush=True)
            if cache_dir is not None or cfg.aot_warmup:
                svc.submit(lambda s=step, r=dict(row):
                           writer.write_scalars(s, r), tag="startup")
            if rr is not None:
                # gated by the reshard EVENT itself (never by warm-start
                # knobs): same-topology streams stay byte-identical —
                # sidecar present, keys absent (the parity contract's
                # absent-until-event clause, like anomaly/rollbacks)
                erow = {
                    "elastic/resharded": 1.0,
                    "elastic/saved_processes": rr["saved_processes"],
                    "elastic/saved_devices": rr["saved_devices"],
                    "elastic/host_stage": rr["host_stage"],
                    "perf/restore/reshard_ms": rr["reshard_ms"],
                    "perf/restore/reshard_leaves": rr["leaves"],
                }
                svc.submit(lambda s=step, r=erow:
                           writer.write_scalars(s, r), tag="elastic")
            if cfg.precision:
                # reduced-precision ladder (ISSUE 17): one row naming the
                # active policy (numeric code — write_scalars coerces to
                # float) + the f32 master-moment census. Gated on the knob:
                # precision="" streams carry neither key (parity contract;
                # the policy STRING rides the flight-recorder header)
                prow = {
                    "perf/precision/policy": float(
                        {"f32": 0, "bf16": 1, "fp8": 2}[cfg.precision]),
                    "perf/precision/master_f32_leaves": float(master_f32),
                }
                svc.submit(lambda s=step, r=prow:
                           writer.write_scalars(s, r), tag="precision")

    def _health_extras() -> dict:
        """Recovery counters riding the scalar rows — absent until nonzero,
        so default-config event streams are byte-identical to pre-recovery
        builds (the parity contract). Reads the counter registry (ISSUE 6)
        — the same snapshot the flight recorder and health vector see."""
        c = registry.snapshot()
        out = {}
        if c.rollbacks:
            out["anomaly/rollbacks"] = c.rollbacks
        if c.corrupt_records:
            out["data/corrupt_records"] = c.corrupt_records
        return out

    def _flight_record(p: dict, gate: str) -> None:
        """One flight-recorder ring record per consumed step: in-memory
        deque append + counter reads on the dispatch thread; the losses
        ride along only when this step's metrics already materialized
        (the recorder must never force a device readback)."""
        if not flight.enabled:
            return
        host = p.get("host")
        rec = {
            "step": p["step"], "time": time.time(), "gate": gate,
            "step_ms": timer.last_step_ms, "host_ms": timer.last_host_ms,
            "metrics": dict(host) if host else None,
            "counters": registry.snapshot().as_dict(),
        }
        if "pipeline" in p:
            # --pipeline_gd only (ISSUE 7): which pipeline phase this step
            # dispatched under ("fill"/"steady") — a crash dump from a
            # mid-fill or mid-drain hang must say so; absent in fused mode
            # so default dumps are unchanged
            rec["pipeline"] = p["pipeline"]
        flight.record(rec)

    def _nan_gate(p: dict, *, force: bool = False) -> bool:
        """Numerical-health gate (SURVEY.md §5) with anomaly CONSENSUS
        (ISSUE 4): each process computes a local verdict over its view of
        the replicated metrics, then the verdicts are allgathered so every
        host takes the identical abort/rollback branch — a non-finite
        value visible on one host only (host-side readback fault, or a
        per-process chaos plan) must never leave the others dispatching
        collectives into a dead mesh. The gate cadence is step-keyed, so
        every process enters the consensus collective at the same
        invocation; `force` (the rollback manager certifying a snapshot
        candidate off-cadence) is step-keyed too. testing/chaos.py can
        poison THIS process's view of the metrics (once) to drill the
        consensus path without real divergence. Returns whether the gate
        EVALUATED (False = off-cadence skip) — the flight recorder's
        gate-verdict column reads this instead of re-deriving the cadence,
        so the two can never disagree."""
        s = p["step"]
        if not force and not (cfg.nan_check_steps
                              and s % cfg.nan_check_steps == 0):
            return False
        vals = dict(_host_vals(p))
        if chaos.should_inject_nan(s):
            vals["d_loss"] = float("nan")
        local_bad = not all(np.isfinite(v) for v in vals.values())
        with _guard("nan-consensus", s):
            bad, trippers = coordination.anomaly_consensus(local_bad)
        if bad:
            where = f" (tripped on process(es) {trippers})" \
                if n_proc > 1 else ""
            err = FloatingPointError(
                f"non-finite training metrics at step {s}{where}: "
                f"{vals} — inspect the last checkpoint in "
                f"{cfg.checkpoint_dir}")
            err.step = s
            raise err
        return True

    def _consume_metrics(p: dict) -> None:
        """Host-side consumers of one step's replicated metric scalars:
        numerical-health gate (abort or hand the trainer's rollback
        handler a FloatingPointError, per nan_policy), stdout step log,
        and the time-throttled scalar events. With async services this
        runs lag-by-one: step N's scalars materialize while step N+1 runs
        on device, so the blocking device_get overlaps compute instead of
        serializing the pipeline; a NaN still trips with the right step
        number, one step later. All cadence math uses the record's own
        step, so attribution is identical in both modes."""
        s = p["step"]
        try:
            gated = _nan_gate(p)
        except FloatingPointError:
            # the failing step must be the ring's LAST record — the
            # acceptance contract a dump reader leans on
            _flight_record(p, "trip")
            raise
        if chief and cfg.log_every_steps and s % cfg.log_every_steps == 0:
            m = _host_vals(p)
            epoch = s * pcfg.batch_size // epoch_size
            print(f"[dcgan_tpu] epoch {epoch} step {s} "
                  f"time {time.time() - t_start:.1f}s "
                  f"d_loss {m['d_loss']:.4f} g_loss {m['g_loss']:.4f}")
        # record AFTER the step log so the ring rides the materialization
        # the log already paid for (default chief logs every step); still
        # never forces a readback of its own
        _flight_record(p, "ok" if gated else "")
        if p["write_scalars"]:
            row = {**_host_vals(p), **timer.summary(), **_health_extras(),
                   **(prog.scalar_extras(s) if prog is not None else {})}
            svc.submit(lambda: writer.write_scalars(s, row), tag="scalars")

    # one step's metrics record awaiting its lag-by-one consumption
    pending: Optional[dict] = None

    def _do_rollback(e: FloatingPointError) -> None:
        """Recovery executor for a tripped gate under nan_policy="rollback":
        restore the snapshot (raises RollbackExhausted past the budget),
        drop checkpoints saved inside the poisoned window (the NaN entered
        somewhere after the last verified snapshot — a save from that span
        may embed it), surface anomaly/rollbacks, apply LR backoff (a
        rebuild of the compiled step — rare-event cost), and re-key the
        step stream so the replayed window draws fresh z instead of
        bitwise re-running into the same divergence. The data iterator is
        NOT rewound: the offending batch window is skipped by construction.
        """
        nonlocal state, step_num, pending, pt, base_key, pt_backoff
        fail_step = getattr(e, "step", step_num)
        # recovery's COLLECTIVE half stays under the watchdog: the
        # device-resident restore dispatches and delete_steps_after's
        # named barrier are exactly the blocking points where a wedged
        # peer would otherwise hang every host with no process dying for
        # the coordination service to notice. (The jitted copy was already
        # compiled at snapshot time, so no compile runs in this window.)
        # Only the optional pt rebuild below — a real recompile — is
        # exempted.
        if mesh_warm:
            watchdog.arm("rollback-restore", fail_step)
        state, step_num = rollback.restore(e)
        pending = None
        # checkpoint_dir/best is deliberately NOT dropped: its retention is
        # score-gated (a best-save only happens when the FID probe improved,
        # and a diverging state scores badly), so a best snapshot from the
        # poisoned window is both unlikely and self-evidencing — deleting a
        # possibly-genuinely-best checkpoint would destroy data on a guess
        dropped = ckpt.delete_steps_after(step_num)
        if chief:
            if dropped:
                print(f"[dcgan_tpu] dropped checkpoint step(s) {dropped} "
                      f"saved inside the poisoned window", flush=True)
            svc.submit(lambda s=fail_step, n=rollback.rollbacks:
                       writer.write_scalars(s, {"anomaly/rollbacks": n}),
                       tag="anomaly")
        watchdog.disarm()  # collectives done; the rebuild below compiles
        if rollback.lr_backoff < 1.0:
            scale = rollback.lr_scale()
            if pt_backoff is not None and rollback.rollbacks == 1:
                # the AOT warmup phase pre-built and cache-primed exactly
                # this variant (warmup.backoff_config — one shared
                # construction, so the HLO and cache key are bit-identical):
                # the swapped-in surface deserializes at its next dispatch
                # instead of recompiling mid-recovery, and compiled_ks
                # stays intact — no recompile event, no exemption needed
                pt = pt_backoff
                pt_backoff = None  # scale^2 at a 2nd rollback: rebuild then
                if chief:
                    print(f"[dcgan_tpu] rollback LR backoff: base rates "
                          f"scaled by {scale:.3g} (pre-warmed surface "
                          f"swapped in — no recompile)", flush=True)
            else:
                pt = make_parallel_train(
                    warmup.backoff_config(cfg, scale), mesh)
                # the rebuilt step programs compile on their next dispatch
                # — exempt those windows from the watchdog like the first
                # ones
                compiled_ks.clear()
                if chief:
                    print(f"[dcgan_tpu] rollback LR backoff: base rates "
                          f"scaled by {scale:.3g}", flush=True)
        base_key = jax.random.fold_in(jax.random.key(cfg.seed + 2),
                                      rollback.rollbacks)

    def _consume_or_rollback(p: dict) -> bool:
        """Consume one metrics record; True = consumed clean, False = the
        gate tripped and the run was rolled back (the caller restarts its
        iteration from the restored state). With nan_policy="abort"
        (default) the FloatingPointError propagates exactly as before."""
        try:
            _consume_metrics(p)
            return True
        except FloatingPointError as e:
            if rollback is None:
                raise
            _do_rollback(e)
            return False

    # step_num is tracked on the host (it equals state["step"], which the
    # trainer fully determines) — touching the device array every iteration
    # would force a per-step host sync and serialize the pipeline.
    # hoisted: reads the manifest once per phase; progressive runs resolve
    # the {res} data-dir placeholder so the epoch counter reads the REAL
    # phase manifest, and the switch below re-reads it for the next phase
    def _phase_epoch_size() -> int:
        if prog is None:
            return max(1, _epoch_size(cfg))
        from dcgan_tpu.progressive import phase_data_cfg

        return max(1, _epoch_size(phase_data_cfg(pcfg)))

    epoch_size = _phase_epoch_size()
    step_num = start_step
    # call shapes (steps_per_call k values) already dispatched against the
    # CURRENT `pt` — the watchdog only arms dispatch windows for these;
    # cleared when a rollback LR backoff rebuilds the compiled step.
    # Warmup proof seeds the set (both the k=1 tail and the scan shape were
    # AOT-compiled into the persistent cache), so guarded dispatch starts
    # at the FIRST boundary instead of after one live pass per shape.
    compiled_ks: set = set()
    if warm_proof:
        compiled_ks.add(1)
        if cfg.steps_per_call > 1:
            compiled_ks.add(cfg.steps_per_call)
    if rollback is not None:
        # arm the initial restore point: a fresh init or a checkpoint
        # restore — both trusted (the checkpoint passed integrity
        # verification; a NaN could not have been saved past the gate)
        rollback.snapshot(step_num, state)
    # PROTOCOL ANCHOR (ISSUE 14): the boundary-poll branch structure of
    # this loop — self-signal fault, stop poll, hang fault, dispatch,
    # lag-by-one consume, fleet-health cadence, snapshot-certify, and the
    # post-loop final flush + final save — is mirrored step-for-step by
    # the protocol simulator (analysis/simulate.py::_virtual_trainer),
    # which drives the REAL coordination/rollback/checkpoint decision
    # code through it and lockstep-audits every collective schedule.
    # Reordering collectives here WILL drift analysis/protocol.lock.jsonl
    # (a DCG012 finding); update the mirror with the change and
    # regenerate the lock deliberately.
    try:
        while step_num < total_steps:
            svc.raise_if_failed()  # a dead telemetry worker fails loudly
            chaos.maybe_self_signal(step_num)  # drill: preemption notice
            # Coordinated stop (ISSUE 4): single-process reads the local
            # flag; multi-host under coord_stop allgathers the flags at
            # EVERY boundary — the decision to enter a collective must be
            # symmetric, so it cannot be gated on the local flag alone.
            stop_sig, stop_origins = None, []
            if n_proc == 1:
                stop_sig, stop_origins = stop.poll()
            elif cfg.coord_stop:
                with _guard("stop-consensus", step_num):
                    stop_sig, stop_origins = stop.poll()
                if not mesh_warm and step_num > start_step:
                    # warm proof for NON-chief processes (which may not
                    # materialize metrics for many steps): a boundary-N>0
                    # poll returning means every peer dispatched its first
                    # step — each device stream runs the allgather only
                    # after that step's program, so everyone is past
                    # compile
                    mesh_warm = True
            if stop_sig is not None:
                if chief:
                    where = f" on process(es) {stop_origins}" \
                        if n_proc > 1 else ""
                    print(f"[dcgan_tpu] received signal {stop_sig}{where} "
                          f"— checkpointing at step {step_num} and exiting")
                # preemption post-mortem context (ISSUE 6): the telemetry
                # that led into the stop, stamped with the step being
                # saved — crash-path-only IO, so parity holds
                flight.dump("coordinated-stop", step=step_num,
                            extra={"signal": int(stop_sig)})
                if pipeline is not None:
                    # release the in-flight fake stack before the final
                    # collective save allocates (ISSUE 7) — the stop
                    # decision is consensus-agreed, so every process
                    # drains at the same boundary
                    with _guard("pipeline-drain", step_num):
                        pipeline.drain("coordinated-stop")
                # drain the services queue BEFORE the final save below: the
                # emergency checkpoint must not outrun queued JSONL/TB
                # events, or a post-stop inspection sees a stream truncated
                # mid-write relative to the state that was saved
                svc.drain()
                break
            # Live-elasticity notice poll (ISSUE 18, DESIGN.md §6l): the
            # same boundary-poll consensus shape as the stop poll above —
            # the local sources (touch file, SIGUSR1, chaos fault) fold
            # into one verdict through notice_consensus, so every process
            # takes the identical switch branch at the identical boundary.
            # Single-process (the live-switch scope) reads the local
            # verdict with no collective; the guarded multi-host arm is
            # the consensus half the protocol tier proves symmetric.
            notice_sig = 0
            if live_rt is not None:
                if n_proc == 1:
                    notice_sig, notice_origins = notice.poll(step_num)
                else:
                    with _guard("notice-consensus", step_num):
                        notice_sig, notice_origins = notice.poll(step_num)
            if notice_sig:
                live_target = live_rt.target_index(notice_sig)
                verdict_name = live_elastic.VERDICT_NAMES.get(
                    notice_sig, "?")
                if live_target is None:
                    # already on the asked-for topology (a grow notice on
                    # the full mesh, a repeated shrink): consume the
                    # notice — an unacked file would re-raise every
                    # boundary — and carry on without a switch
                    notice.ack(step=step_num, verdict=notice_sig,
                               target=live_rt.tag(), switch_ms=0.0)
                    if chief:
                        print(f"[dcgan_tpu] {verdict_name} notice at step "
                              f"{step_num}: already on {live_rt.tag()} — "
                              f"consumed, no switch", flush=True)
                else:
                    # Live topology switch: the PR 14 phase-boundary
                    # sequence pointed at a mesh change. Flush the
                    # lag-by-one record (pre-switch metrics; a gate trip
                    # rolls back BEHIND the boundary — the consumed
                    # notice is NOT re-raised, the scheduler re-notifies
                    # if it still wants the capacity) -> services drain
                    # (queued telemetry referencing old-mesh arrays lands
                    # before their buffers die) -> G/D pipeline drain
                    # (the fake stack is mesh-committed) -> state
                    # re-scatter onto the target surface -> loader
                    # rebuild (batches are mesh-committed too),
                    # fast-forwarded past the consumed prefix -> fresh
                    # rollback snapshot -> StepTimer/compiled_ks re-armed.
                    # With --aot_warmup both topologies were primed at
                    # startup, so the switch issues zero compile requests
                    # (the printed delta, drill-pinned).
                    if pending is not None:
                        prev, pending = pending, None
                        if not _consume_or_rollback(prev):
                            continue
                    t_sw = time.perf_counter()
                    svc.drain()
                    if pipeline is not None:
                        with _guard("pipeline-drain", step_num):
                            pipeline.drain("elastic-switch")
                    old_tag = live_rt.tag()
                    state = live_rt.switch(state, notice_sig)
                    pt = live_rt.pt
                    mesh = live_rt.mesh
                    for closing in (data, sample_data):
                        if closing is not None and hasattr(closing,
                                                           "close"):
                            try:
                                closing.close()
                            except Exception:
                                pass
                    data = _data_iterator(
                        cfg, mesh, synthetic=synthetic_data,
                        skip_batches=step_num - start_step)
                    if sample_data is not None:
                        se = cfg.sample_every_steps
                        probes = (step_num // se - start_step // se) \
                            if se else 0
                        if cfg.fid_every_steps and fid_real_side is not None:
                            # the one-shot real side consumed its batches
                            # from this stream too
                            probes += -(-cfg.fid_num_samples
                                        // cfg.batch_size)
                        sample_data = _sample_data_iterator(
                            cfg, mesh, synthetic=synthetic_data,
                            skip_batches=probes)
                        if n_proc == 1 and cfg.fid_every_steps:
                            # single-process probe aliases the held-out
                            # stream — re-point it at the rebuilt one
                            fid_probe_data = sample_data
                    timer = StepTimer(window=cfg.timing_window,
                                      images_per_step=pcfg.batch_size)
                    compiled_ks.clear()
                    if live_rt.primed:
                        compiled_ks.add(1)
                        if cfg.steps_per_call > 1:
                            compiled_ks.add(cfg.steps_per_call)
                    if rollback is not None:
                        # a NaN right after the switch must restore the
                        # NEW topology's tree, never re-scatter the old
                        rollback.snapshot(step_num, state)
                    switch_ms = (time.perf_counter() - t_sw) * 1e3
                    note = ""
                    if cache_mon is not None and warm_base is not None:
                        d = warmup.CompileCacheMonitor.delta(
                            cache_mon.counters(), warm_base)
                        note = f" compile_requests_delta=" \
                               f"{int(d['requests'])}"
                    if chief:
                        print(f"[dcgan_tpu] live elastic switch at step "
                              f"{step_num}: {old_tag} -> {live_rt.tag()} "
                              f"({verdict_name} notice, "
                              f"{live_rt.last_switch_ms:.1f}ms state "
                              f"move) switch_ms={switch_ms:.1f}{note}",
                              flush=True)
                        srow = {
                            "elastic/live_notice_step": float(step_num),
                            "elastic/live_switch_ms": switch_ms,
                            "elastic/live_target_mesh":
                                float(live_rt.device_count),
                            "elastic/live_resumed_step": float(step_num)}
                        svc.submit(lambda s=step_num, r=srow:
                                   writer.write_scalars(s, r),
                                   tag="elastic")
                    notice.ack(step=step_num, verdict=notice_sig,
                               target=live_rt.tag(), switch_ms=switch_ms)
            # Phase boundary (ISSUE 15, DESIGN.md §6j): the switch decision
            # is a pure function of step_num and the schedule, so every
            # process takes it at the same boundary with zero extra
            # collectives (the protocol tier's progressive config pins the
            # symmetry). Sequence: flush the lag-by-one record (old-phase
            # metrics; a trip here rolls back BEHIND the boundary and the
            # switch re-evaluates) -> services drain barrier (queued
            # telemetry referencing old-phase arrays lands before their
            # buffers die) -> G/D pipeline drain -> state carry onto the
            # next phase's surface -> loader re-bucket -> fresh rollback
            # snapshot (a NaN right after the switch must restore the NEW
            # tree) -> watchdog compiled_ks re-armed for the new surface.
            # With --aot_warmup every dispatched program was primed at
            # startup, so the whole switch issues zero compile requests
            # (the printed delta, CompileCacheMonitor-pinned).
            if prog is not None and prog.switch_due(step_num):
                if pending is not None:
                    prev, pending = pending, None
                    if not _consume_or_rollback(prev):
                        continue
                t_sw = time.perf_counter()
                svc.drain()
                if pipeline is not None:
                    with _guard("pipeline-drain", step_num):
                        pipeline.drain("phase-switch")
                old_res = prog.resolution
                state = prog.advance(state)
                pt = prog.pt
                pcfg = prog.cfg
                ckpt.progressive_tag = prog.tag()
                data, sample_data = rebucketer.reopen(pcfg)
                eval_z = jax.numpy.resize(
                    sample_z, (pcfg.batch_size, cfg.model.z_dim)) \
                    if sample_data is not None else None
                timer = StepTimer(window=cfg.timing_window,
                                  images_per_step=pcfg.batch_size)
                epoch_size = _phase_epoch_size()
                compiled_ks.clear()
                if prog.primed:
                    compiled_ks.add(1)
                    if cfg.steps_per_call > 1:
                        compiled_ks.add(cfg.steps_per_call)
                if rollback is not None:
                    rollback.snapshot(step_num, state)
                switch_ms = (time.perf_counter() - t_sw) * 1e3
                note = ""
                if cache_mon is not None and warm_base is not None:
                    d = warmup.CompileCacheMonitor.delta(
                        cache_mon.counters(), warm_base)
                    note = f" compile_requests_delta={int(d['requests'])}"
                if chief:
                    print(f"[dcgan_tpu] progressive phase {prog.index} at "
                          f"step {step_num}: r{old_res} -> "
                          f"r{prog.resolution} (batch {pcfg.batch_size}, "
                          f"{prog.last_carried} leaves carried) "
                          f"switch_ms={switch_ms:.1f}{note}", flush=True)
                    srow = {**prog.scalar_extras(step_num + 1),
                            "progressive/switch_ms": switch_ms}
                    svc.submit(lambda s=step_num, r=srow:
                               writer.write_scalars(s, r),
                               tag="progressive")
            # steps_per_call > 1: dispatch K steps as one scanned program
            # when aligned to a K boundary with K steps remaining (a
            # checkpoint restore can land mid-boundary; single steps
            # realign, and the tail below max_steps runs single too). Keys
            # are per-step fold-ins, identical to the single-step path, so
            # a run produces the same step keys whatever the call size.
            k = cfg.steps_per_call
            if not (k > 1 and step_num % k == 0
                    and step_num + k <= total_steps):
                k = 1
            # dispatch/consume window under the watchdog deadline — except
            # iterations that COMPILE: the first dispatch of each call
            # shape (the k=1 tail after scanned k=K calls included), the
            # first dispatch after a rollback LR-backoff rebuilt `pt`, and
            # everything before the mesh is warm (a peer may still be in
            # ITS first compile) — compile time is legitimate and
            # unbounded by this knob
            if mesh_warm and k in compiled_ks:
                # stage-resolved phase labels under --pipeline_gd (ISSUE 7):
                # a trip inside the refill after a rollback reads
                # "pipeline-fill", a steady-state trip "pipeline-dispatch"
                # — the fused path keeps its historical label
                if pipeline is None:
                    phase = "step-dispatch"
                else:
                    phase = "pipeline-dispatch" if pipeline.primed \
                        else "pipeline-fill"
                watchdog.arm(phase, step_num)
            chaos.maybe_hang(step_num)  # drill: a peer that goes silent
            trace.maybe_start(step_num)
            if trace.active:
                capture_ks.append(k)  # this boundary is inside the window
            labels = None
            if k == 1:
                key = jax.random.fold_in(base_key, step_num)
                if conditional:
                    images, labels = next(data)
                    if prog is not None:
                        images = prog.fade_images(images, step_num)
                    state, metrics = pt.step(state, images, key, labels)
                elif pipeline is not None:
                    # pipelined dispatch (ISSUE 7): d_update consumes the
                    # stack g_update produced during the previous step;
                    # an unprimed buffer (run start, post-rollback, post-
                    # drain) dispatches the gen_fakes fill first — the
                    # watchdog phase armed above names which case a hang
                    # died in
                    images = next(data)
                    if prog is not None:
                        # image-space fade-in (ISSUE 15): inside a fade
                        # window the real batch blends toward its
                        # previous-resolution content through the phase's
                        # jitted blend (alpha a traced scalar); a no-op
                        # dispatch-free identity at alpha == 1
                        images = prog.fade_images(images, step_num)
                    state, metrics = pipeline.step(pt, state, images, key)
                else:
                    images = next(data)
                    if prog is not None:
                        images = prog.fade_images(images, step_num)
                    state, metrics = pt.step(state, images, key)
            else:
                # one vmapped dispatch for all K per-step keys (a python
                # loop of fold_ins would pay K of the per-dispatch
                # overheads this path exists to shed); same per-step keys
                # as the single-step path
                keys = jax.vmap(jax.random.fold_in, (None, 0))(
                    base_key, jax.numpy.arange(step_num, step_num + k))
                key = keys[-1]  # for the cadence consumers below
                if conditional:
                    pairs = [next(data) for _ in range(k)]
                    imgs_k = jax.numpy.stack([p[0] for p in pairs])
                    lbls_k = jax.numpy.stack([p[1] for p in pairs])
                    state, metrics = pt.multi_step(state, imgs_k, keys,
                                                   lbls_k)
                    images, labels = pairs[-1]
                else:
                    batches = [next(data) for _ in range(k)]
                    imgs_k = jax.numpy.stack(batches)
                    state, metrics = pt.multi_step(state, imgs_k, keys)
                    images = batches[-1]
            compiled_ks.add(k)  # dispatch returned: this shape is compiled
            new_step = step_num + k
            cur = {"step": new_step, "metrics": metrics,
                   "write_scalars": False}
            if pipeline is not None:
                # the step's pipeline phase rides the record so the flight
                # recorder can stamp it (fill vs steady), lag-by-one safe —
                # the tag is captured at dispatch, consumed whenever
                cur["pipeline"] = pipeline.last_phase

            host_t0 = time.perf_counter()
            if deferred:
                # lag-by-one metric window: consume the PREVIOUS step's
                # scalars now — its D2H copies have had a full step to
                # land, so the materialization below reads cached values
                # instead of blocking dispatch on the device — and start
                # this step's copies for the next iteration.
                if pending is not None:
                    prev, pending = pending, None
                    if not _consume_or_rollback(prev):
                        continue  # rolled back: restart from restored state
                _stage(metrics)
            else:
                # inline escape hatch: NaN gate + step log at the original
                # call site, synced to THIS step (true step latency)
                if not _consume_or_rollback(cur):
                    continue
            timer.note_host(time.perf_counter() - host_t0)
            # With per-step logging (the default, matching the reference's
            # every-step stdout log) each tick follows one metric
            # materialization — true step latency, lagged by one step in
            # async mode; with log_every_steps=0 it measures dispatch
            # cadence only.
            timer.tick(steps=k)

            host_t0 = time.perf_counter()
            if chief and writer.ready():
                if deferred:
                    cur["write_scalars"] = True  # written at the next flush
                else:
                    row = {**_host_vals(cur), **timer.summary(),
                           **_health_extras(),
                           **(prog.scalar_extras(new_step)
                              if prog is not None else {})}
                    svc.submit(lambda s=new_step, r=row:
                               writer.write_scalars(s, r), tag="scalars")
                snap = _snapshot_params(state["params"])
                svc.submit(lambda s=new_step, t=snap:
                           writer.write_histograms(s, param_histograms(t)),
                           tag="histograms")
            if deferred:
                pending = cur
            watchdog.disarm()  # dispatch/consume window completed

            # Fleet health plane (ISSUE 6): one compact float32 allgather
            # per cadence, issued HERE on the dispatch thread (collective-
            # thread rule — a background-thread collective would interleave
            # nondeterministically against step dispatches and wedge the
            # mesh). Every process contributes its HEALTH_FIELDS vector;
            # the chief materializes fleet/* (straggler skew, slowest
            # host, queue/drop/recovery totals) and the slowest-host line
            # is parked on the watchdog + flight recorder so a later trip
            # names the likely wedged peer.
            if cfg.fleet_health_steps and \
                    new_step % cfg.fleet_health_steps == 0:
                tsum = timer.summary()
                c = registry.snapshot()
                vec = np.asarray(
                    [new_step, tsum.get("perf/step_ms_mean", 0.0),
                     tsum.get("perf/host_ms_mean", 0.0), c.services_queue,
                     c.services_dropped, c.rollbacks, c.corrupt_records,
                     c.progressive_phase],
                    np.float32)
                with _guard("fleet-health", new_step):
                    table = coordination.fleet_health_gather(vec)
                frow, fleet_note = coordination.fleet_metrics(table)
                watchdog.set_note(fleet_note)
                flight.note = fleet_note
                if chief:
                    svc.submit(lambda s=new_step, r=frow:
                               writer.write_scalars(s, r),
                               tag="fleet-health")

            # per-layer activation histograms + sparsity (the reference's
            # _activation_summary channel, distriubted_model.py:75-80). The
            # summarize DISPATCH runs on every process — it is a compiled
            # mesh program — only the chief's device_get + write moves to
            # the worker (the outputs are fresh replicated arrays; nothing
            # donates them).
            if cfg.activation_summary_steps and \
                    new_step % cfg.activation_summary_steps == 0:
                acts = pt.summarize(state, images,
                                    jax.random.fold_in(key, 1),
                                    labels) if conditional else \
                    pt.summarize(state, images, jax.random.fold_in(key, 1))
                if chief:
                    _stage(acts)
                    svc.submit(lambda s=new_step, a=acts:
                               writer.write_activations(s,
                                                        jax.device_get(a)),
                               tag="activations")

            if cfg.sample_every_steps and \
                    new_step % cfg.sample_every_steps == 0:
                imgs_dev = pt.sample(state, sample_z, sample_labels) \
                    if sample_labels is not None \
                    else pt.sample(state, sample_z)
                if chief:
                    _stage(imgs_dev)
                    path = os.path.join(cfg.sample_dir,
                                        f"train_{new_step:08d}.png")

                    def _grid_task(s=new_step, a=imgs_dev, p=path):
                        imgs = jax.device_get(a)
                        save_sample_grid(p, imgs[:rows * cols], (rows, cols))
                        writer.write_image_event(s, "samples", p)
                    svc.submit(_grid_task, tag="sample-grid")
                # held-out loss probe on the sample pipeline's batch with
                # the fixed z — the reference's sess.run([sampler, d_loss,
                # g_loss]) + print every 100 steps (image_train.py:179-192)
                if sample_data is not None:
                    if conditional:
                        s_imgs, s_labels = next(sample_data)
                        ev = pt.eval_losses(state, s_imgs, eval_z, s_labels)
                    else:
                        s_imgs = next(sample_data)
                        ev = pt.eval_losses(state, s_imgs, eval_z)
                    if chief:
                        _stage(ev)

                        def _probe_task(s=new_step, e=ev):
                            vals = {k: float(v) for k, v in
                                    jax.device_get(e).items()}
                            print(f"[dcgan_tpu] [sample] step {s} "
                                  f"d_loss {vals['d_loss']:.8f} "
                                  f"g_loss {vals['g_loss']:.8f}")
                            writer.write_scalars(
                                s, {f"sample/{k}": v
                                    for k, v in vals.items()})
                        svc.submit(_probe_task, tag="sample-probe")
            timer.note_host(time.perf_counter() - host_t0)

            # The in-training FID/KID probe stays ENTIRELY on the dispatch
            # thread: its real-side streaming, feature all-gathers, and
            # the best-checkpoint Orbax save are mesh-wide collectives,
            # and collectives issued from a background thread have no
            # cross-process ordering against this thread's step dispatches
            # — two processes interleaving them differently deadlocks the
            # mesh. Only the two result scalars go through the writer
            # queue (the writer itself is single-threaded).
            if cfg.fid_every_steps and new_step % cfg.fid_every_steps == 0:
                from dcgan_tpu.evals.job import (
                    FeaturePool,
                    compute_fid,
                    stats_from_batches,
                )

                dist = n_proc > 1
                if dist:
                    # Local sampler over the gathered generator tree:
                    # compiled once (weights are arguments, not closed-over
                    # constants), fed fresh weights each probe. Mirrors
                    # steps.py sample's EMA selection.
                    from jax.experimental import multihost_utils as mh

                    g_src = state["ema_gen"] if cfg.g_ema_decay > 0.0 \
                        else state["params"]["gen"]
                    host_gen = jax.tree_util.tree_map(
                        lambda x: mh.process_allgather(x, tiled=True),
                        (g_src, state["bn"]["gen"]))
                    if fid_local_sampler is None:
                        from dcgan_tpu.models import sampler_apply

                        fid_local_sampler = jax.jit(
                            lambda p, b, z, lbls=None: sampler_apply(
                                p, b, z, cfg=cfg.model, labels=lbls))

                    def _sample_fn(z, lbls=None, _g=host_gen):
                        return fid_local_sampler(_g[0], _g[1], z, lbls) \
                            if lbls is not None \
                            else fid_local_sampler(_g[0], _g[1], z)
                else:
                    def _sample_fn(z, lbls=None, _s=state):
                        return pt.sample(_s, z, lbls) if lbls is not None \
                            else pt.sample(_s, z)

                n = cfg.fid_num_samples
                t_fid = time.time()
                if fid_real_side is None:
                    # real-side statistics are computed ONCE, at the first
                    # probe: the held-out set is fixed, so re-streaming it
                    # each probe would double probe cost and add real-side
                    # sampling noise to the eval/fid trend. Multihost: each
                    # process streams its share, then the sides merge into
                    # one global real side (treated as already-global by
                    # compute_fid).
                    reals = (b[0] for b in fid_probe_data) if conditional \
                        else fid_probe_data
                    r_pool = FeaturePool(fid_feature[1], n, seed=cfg.seed)
                    r_stats = stats_from_batches(fid_feature[0], reals,
                                                 n // n_proc,
                                                 fid_feature[1], pool=r_pool)
                    if dist:
                        from dcgan_tpu.evals.job import (
                            allgather_merge_pool,
                            allgather_merge_stats,
                        )

                        r_stats = allgather_merge_stats(r_stats)
                        r_pool = allgather_merge_pool(r_pool)
                    fid_real_side = (r_stats, r_pool)
                fid_result = compute_fid(
                    _sample_fn, None, image_size=cfg.model.output_size,
                    c_dim=cfg.model.c_dim, z_dim=cfg.model.z_dim,
                    num_samples=n, batch_size=cfg.batch_size,
                    num_classes=cfg.model.num_classes, seed=cfg.seed,
                    feature_fn=fid_feature[0], feature_dim=fid_feature[1],
                    kid=True, kid_subset_size=max(2, min(1000, n // 4)),
                    kid_subsets=20, kid_pool_size=n,
                    distributed=dist, real_side=fid_real_side)
                if chief:
                    print(f"[dcgan_tpu] [fid] step {new_step} "
                          f"fid {fid_result['fid']:.6f} "
                          f"kid {fid_result['kid']:.3e} "
                          f"({n} samples, {time.time() - t_fid:.1f}s)")
                    svc.submit(lambda s=new_step, r=dict(
                        fid_result): writer.write_scalars(s, {
                            "eval/fid": r["fid"],
                            "eval/kid": r["kid"],
                        }), tag="fid-scalars")
                # best-checkpoint retention: when the probe improves on the
                # best FID seen this run, snapshot into checkpoint_dir/best
                # (its own manager, max_to_keep=1) — training ends with
                # both the latest state AND the best-scoring one on disk.
                # The periodic/latest cadence is untouched. Multihost: the
                # gathered score is identical on every process, so every
                # process takes this branch together and the Orbax save
                # stays a valid collective; only the chief touches
                # score.json/config.json.
                if fid_result["fid"] < fid_best:
                    import json

                    fid_best = fid_result["fid"]
                    best_dir = os.path.join(cfg.checkpoint_dir, "best")
                    if best_ckpt is None:
                        # sync save: each best-save is final before
                        # training continues, so async machinery would
                        # only be joined
                        best_ckpt = Checkpointer(best_dir, max_to_keep=1,
                                                 async_save=False)
                        # its own config.json so `generate
                        # --checkpoint_dir ckpt/best` works zero-flag like
                        # any checkpoint dir
                        if chief:
                            save_config(cfg, best_dir)
                    best_ckpt.save(new_step, state, force=True)
                    if chief:
                        # persisted score: resume re-seeds fid_best from
                        # this
                        tmp = os.path.join(best_dir, "score.json.tmp")
                        with open(tmp, "w") as f:
                            json.dump({"fid": fid_best,
                                       "step": int(new_step)}, f)
                        os.replace(tmp,
                                   os.path.join(best_dir, "score.json"))
                        print(f"[dcgan_tpu] [fid] new best "
                              f"({fid_best:.6f}) — saved "
                              f"{cfg.checkpoint_dir}/best/{new_step}")

            trace.maybe_stop(new_step, sync=metrics)
            if rollback is not None and rollback.due(new_step):
                # refresh the restore point — but only with VERIFIED state:
                # force the gate on this step's metrics (off-cadence too),
                # and flush the lag-by-one record first so a trip here
                # attributes to the right step. Forcing materialization
                # costs one host sync per K steps — the snapshot's price.
                # Guarded: the forced readback and the mesh-wide snapshot
                # copy both block on peers (the copy compiled at the
                # pre-loop snapshot, so no compile runs here).
                try:
                    with _guard("snapshot-certify", new_step):
                        _nan_gate(cur, force=True)
                        if pending is not None:
                            _consume_metrics(pending)
                            pending = None
                        rollback.snapshot(new_step, state)
                except FloatingPointError as e:
                    _do_rollback(e)
                    continue
            with _guard("collective-save", new_step):
                if ckpt.maybe_save(new_step, state):
                    # drain-on-checkpoint barrier: every telemetry event
                    # submitted before this checkpoint is durable before
                    # training proceeds past it — a preemption right after
                    # a save cannot lose events older than the checkpoint
                    svc.drain()
            step_num = new_step

        # final lag-by-one flush: the last step's NaN gate / log / scalars
        # (fires before the final forced save below, so a NaN in the last
        # step still aborts the run rather than being checkpointed quietly)
        if pending is not None:
            _consume_metrics(pending)
            pending = None
        if chief:
            svc.submit(writer.flush, tag="tb-flush", droppable=False)
        svc.close()  # drain-on-exit barrier; re-raises worker failures
        if chief and getattr(svc, "dropped", 0):
            print(f"[dcgan_tpu] host-services backpressure dropped "
                  f"{svc.dropped} telemetry event(s) (training was never "
                  f"stalled for them; raise the queue bound or slow the "
                  f"summary cadence to keep them all)")
    except BaseException:
        # exception exit: the tail below (final save, watchdog.close())
        # never runs, so close the enforcement thread here — a driver
        # that catches aborts and calls train() in a loop must not
        # accumulate one daemon thread per failed run. An explicit except
        # (not sys.exc_info() in the finally) because train() may itself
        # be running inside a caller's except block, where exc_info() is
        # non-None even on a clean exit.
        watchdog.close()
        raise
    finally:
        # clean shutdown on EVERY exit path (normal, signal break, NaN
        # abort, loader error): stop the device-feed threads and the
        # services worker without masking an in-flight exception. The
        # watchdog is DISARMED (not closed — the final collective save
        # below still wants its deadline) so a fast abort path cannot race
        # a stale deadline into a spurious process exit during cleanup.
        watchdog.disarm()
        if notice is not None:
            # hand SIGUSR1 back on every exit path — a process that calls
            # train() again (tests, drills) must not deliver a late
            # notice into a dead plane
            notice.restore()
        if pipeline is not None:
            # release the buffer on every exit path (normal completion,
            # abort, loader error) — nothing past the loop consumes it
            pipeline.drain("shutdown")
        for closing in (svc, data, sample_data, fid_probe_data):
            if closing is None or not hasattr(closing, "close"):
                continue
            try:
                closing.close()
            except Exception:
                pass
    # final forced save at the step actually reached (== total_steps unless
    # a shutdown signal broke the loop early); skip if the periodic save
    # already wrote this exact step. Guarded: this is THE collective a
    # coordinated stop must complete on every process, and the one PR 3
    # feared enough to skip multi-host signal handling entirely.
    try:
        trace.close()
        writer.close()
        if ckpt.latest_step() != step_num:
            if mesh_warm:
                watchdog.arm("final-save", step_num)
            ckpt.save(step_num, state, force=True)
        ckpt.wait()
    finally:
        # close() disarms both enforcement layers even when a closer or
        # the save raises — a caller handling that exception must not be
        # os._exit'd by a stale deadline mid-cleanup, nor leak the
        # enforcement thread
        watchdog.close()
    return state


def _epoch_size(cfg: TrainConfig) -> int:
    """Examples per epoch for the log's epoch counter.

    The dataset.json manifest's num_examples when the data_dir carries one
    (prepare.py writes it), else the reference's hard-coded
    image_num = 107766*3 (image_train.py:44) — which was wrong for every
    non-CelebA dataset; strict-parity runs without a manifest keep it.
    """
    import json

    from dcgan_tpu.data.pipeline import MANIFEST_NAME

    try:
        with open(os.path.join(cfg.data_dir, MANIFEST_NAME)) as f:
            manifest = json.load(f)
        n = manifest.get("num_examples") if isinstance(manifest, dict) \
            else None
        if n:
            return int(n)
    except (OSError, ValueError):
        pass
    return 323_298
