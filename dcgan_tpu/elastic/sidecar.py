"""The checkpoint sharding sidecar: a step's topology as data.

Every checkpoint step gets `integrity/<step>.sharding.json` beside its
integrity manifest: the saving mesh's axis names and sizes, the saving
process count, and the resolved per-leaf partition specs — everything
`restore_latest` needs to DETECT a topology change before any payload
byte moves, and everything an operator needs to answer "what was this
checkpoint sharded like?" without booting the saving fleet.

The sidecar is derived from the live state tree at save time (each leaf's
NamedSharding carries the global mesh and spec on every process), so all
savers — the trainer's periodic/final saves, best-checkpoint retention,
tools — get one for free. Absence is never an error: legacy steps and
states without NamedShardings (host-tree tests) restore exactly as
before, same-topology.

Schema (version 1):

    {"version": 1,
     "process_count": 2,
     "mesh": {"axes": ["data", "model"], "sizes": [32, 1]},
     "specs": {"params/gen/proj/w": [null, "model"], ...}}
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

from dcgan_tpu.elastic.rules import path_str

Pytree = Any

VERSION = 1

#: sidecars live beside the integrity manifests (utils/checkpoint.py owns
#: the directory constant; re-declared here to keep this module jax-free
#: at import)
INTEGRITY_DIRNAME = "integrity"


def sidecar_path(directory: str, step: int) -> str:
    return os.path.join(directory, INTEGRITY_DIRNAME,
                        f"{int(step)}.sharding.json")


def _mesh_of(state: Pytree):
    """The (global) Mesh of the first NamedSharding leaf, or None for
    host/np trees — which simply don't get a sidecar."""
    import jax

    for leaf in jax.tree_util.tree_leaves(state):
        sh = getattr(leaf, "sharding", None)
        if sh is not None and getattr(sh, "mesh", None) is not None \
                and hasattr(sh, "spec"):
            return sh.mesh
    return None


def build_payload(state: Pytree) -> Optional[Dict[str, Any]]:
    """The sidecar dict for a live sharded state tree, or None when the
    tree carries no NamedShardings (nothing to record)."""
    import jax

    mesh = _mesh_of(state)
    if mesh is None:
        return None
    specs: Dict[str, list] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        sh = getattr(leaf, "sharding", None)
        spec = getattr(sh, "spec", None)
        ndim = len(getattr(leaf, "shape", ()))
        parts = [None] * ndim
        if spec is not None:
            for d, axis in enumerate(tuple(spec)[:ndim]):
                # a PartitionSpec entry may be a tuple of axis names;
                # record it verbatim (json-serializable either way)
                parts[d] = list(axis) if isinstance(axis, tuple) else axis
        specs[path_str(path)] = parts
    return {
        "version": VERSION,
        "process_count": int(jax.process_count()),
        "mesh": {"axes": [str(a) for a in mesh.axis_names],
                 "sizes": [int(mesh.shape[a]) for a in mesh.axis_names]},
        "specs": specs,
    }


def read(directory: str, step: int) -> Optional[Dict[str, Any]]:
    """The step's sidecar payload, or None when absent/unreadable — an
    unreadable sidecar degrades to the pre-elastic behavior (assume the
    saving topology), it never condemns a step."""
    path = sidecar_path(directory, step)
    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) or "mesh" not in payload:
        return None
    return payload


def current_topology(state: Pytree) -> Optional[Tuple[Tuple[str, ...],
                                                      Tuple[int, ...], int]]:
    """(axis names, axis sizes, process count) of a sharded tree, or None
    for host trees."""
    import jax

    mesh = _mesh_of(state)
    if mesh is None:
        return None
    return (tuple(str(a) for a in mesh.axis_names),
            tuple(int(mesh.shape[a]) for a in mesh.axis_names),
            int(jax.process_count()))


def topology_mismatch(payload: Dict[str, Any],
                      state: Pytree) -> Optional[str]:
    """Why the saved topology differs from the target tree's, or None
    when they match (or when either side is unknowable — no sharded
    leaves, malformed payload — in which case the same-topology path is
    the only safe answer)."""
    cur = current_topology(state)
    if cur is None:
        return None
    try:
        saved_axes = tuple(str(a) for a in payload["mesh"]["axes"])
        saved_sizes = tuple(int(s) for s in payload["mesh"]["sizes"])
        saved_procs = int(payload.get("process_count", 1))
    except (KeyError, TypeError, ValueError):
        return None
    axes, sizes, procs = cur
    diffs = []
    if saved_axes != axes or saved_sizes != sizes:
        diffs.append(f"mesh {dict(zip(saved_axes, saved_sizes))} -> "
                     f"{dict(zip(axes, sizes))}")
    if saved_procs != procs:
        diffs.append(f"processes {saved_procs} -> {procs}")
    return "; ".join(diffs) if diffs else None


def restore_decision(payload: Optional[Dict[str, Any]],
                     state: Pytree) -> Tuple[str, Optional[str]]:
    """THE restore-path choice, as data: ("direct"|"device"|"host",
    human-readable mismatch or None).

    - "direct": topology matches (or is unknowable) — the untouched
      pre-elastic read path;
    - "device": mesh changed, process census did not — the Orbax read is
      directed at the current NamedShardings;
    - "host": process census changed — numpy staging + per-shard upload
      (collective-free by construction).

    One function, two consumers (ISSUE 14): `Checkpointer.restore_latest`
    branches on it, and the protocol simulator
    (analysis/simulate.py) replays it under a virtual process census —
    the decision's inputs (committed sidecar payload, target tree's mesh,
    jax.process_count()) are mesh-uniform, so the chosen path is
    identical on every process BY CONSTRUCTION, and the lockstep audit
    pins that construction.
    """
    mismatch = topology_mismatch(payload, state) \
        if payload is not None else None
    if mismatch is None:
        return "direct", None
    import jax

    saved_procs = int(payload.get("process_count", 1))
    if saved_procs != jax.process_count():
        return "host", mismatch
    return "device", mismatch
