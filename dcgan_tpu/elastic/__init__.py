"""Elastic topology (ISSUE 12): sharding specs as data, restore across
meshes.

- `rules`: the regex sharding-rule engine — one table mapping pytree
  paths to logical PartitionSpecs for params, optimizer state, and EMA
  across all three model families (`parallel/sharding.py` keeps its
  public names as thin wrappers over it).
- `sidecar`: the per-checkpoint sharding sidecar — logical specs + mesh
  axis names/sizes + process count, written next to the integrity
  manifests so a checkpoint carries its topology instead of assuming it.
- `reshard`: topology-change-aware restore — host-side staging when the
  process count changed, a NamedSharding-directed device read otherwise.
"""

from dcgan_tpu.elastic.rules import (  # noqa: F401
    PARTITION_RULES,
    REPLICATED,
    logical_spec,
    matching_rules,
    path_str,
    resolve_spec,
    state_partition_specs,
    state_shardings,
)
