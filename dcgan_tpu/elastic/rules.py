"""Sharding-rule engine: one regex table, pytree path -> logical spec.

Before ISSUE 12, the placement policy lived as code — `parallel/
sharding.py::_spec_for_leaf` walked each leaf's path objects and
hand-tested names ("w", "proj", "head") and ranks. That worked for one
model family and died the moment specs had to become DATA: a checkpoint
that wants to restore onto a different topology must carry its placement
policy as inspectable metadata, and a new model family must extend a
table, not a function. This module is the SNIPPETS [3]
`match_partition_rules` idiom applied to this repo's whole train state:

- `PARTITION_RULES` is an ordered table of (regex, logical spec) rows.
  A leaf's coordinate is its "/"-joined tree path ("params/gen/proj/w",
  "opt/disc/1/0/mu/head/w", "ema_gen/deconv1/w", ...), so the SAME rows
  cover params, both Adam states (mu/nu mirror the param tree), and the
  EMA copy for all three model families (dcgan / resnet / stylegan,
  attention + spectral-norm + conditional variants included).
- A logical spec is a tuple of mesh-AXIS NAMES (or None) per dim — never
  device counts. Resolution against a concrete Mesh happens separately
  (`resolve_spec`), which is what makes specs portable across
  topologies: the same logical row yields a valid PartitionSpec on a
  v5e-32 and on the v5e-16 it restores onto ("Scalable Training of LMs
  using pjit"'s mesh-axis discipline).
- Matching is EXACT-ONE by construction: a leaf matching zero rules
  raises (a new layer must be classified, not silently replicated — the
  SNIPPETS [3] contract), and the DCG011 analyzer audits the whole
  table offline for unmatched AND multiply-matched paths over every
  model family's full train state.

Resolution policies (`resolve_spec`) reproduce the previous derivation
bit-for-bit — the semantic-tier program fingerprints must not move:

- divisibility guard: a dim keeps its axis only when the mesh axis size
  divides it (the c_dim-output deconv stays replicated under model > 1);
- `spatial=True` replicates ALL weights (the "model" axis then carries
  activation height via `batch_sharding`, and sharding kernels over the
  same axis would force all-gathers around every conv);
- `shard_opt=True` (ZeRO-1) additionally inserts the "data" axis on the
  first unsharded dim it divides, for optimizer-state paths only — the
  cross-replica weight-update sharding of arXiv:2004.13336;
- `zero_stage >= 2` (ISSUE 13, DESIGN §6i) applies the same insertion —
  via ONE shared `zero_insert` policy, with a co-sharding second pass for
  dims already carrying mesh axes — to the optimizer state (stage 2) and
  to params + the EMA mirror (stage 3), and derives the matching GRADIENT
  specs (`grad_shardings`) and the shard_map backend's explicit
  psum_scatter/all_gather dims (`zero_scatter_dims`) from the same table,
  so the four layouts can never disagree.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from dcgan_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

Pytree = Any

#: the any-rank "fully replicated" logical spec (rank-specific tuples of
#: None would need one row per rank for no information)
REPLICATED = "replicated"

LogicalSpec = Any  # REPLICATED or Tuple[Optional[str], ...]

#: The rule table. Ordered for readability only — the engine enforces
#: that every leaf matches EXACTLY one row (DCG011), so order never
#: decides a placement. Patterns are re.search'd against the "/"-joined
#: path; every row's tail is anchored with `$` and the leading `(^|/)`
#: keeps a component match from binding mid-name (plain `conv1/w` would
#: also hit `b0_conv1/w`, which has its own row).
PARTITION_RULES: Tuple[Tuple[str, LogicalSpec], ...] = (
    # -- tensor-parallel weights (the widest matmuls) --------------------
    # generator projection [z_dim, top_ch*S*S]: shard the huge output dim
    (r"(^|/)proj/w$", (None, MODEL_AXIS)),
    # discriminator head [flat, 1]: shard the huge input dim
    (r"(^|/)head/w$", (MODEL_AXIS, None)),
    # conv / deconv kernels [kh, kw, in, out] — every 4-d kernel in the
    # three families — shard output channels
    (r"(^|/)(deconv\d+|conv\d+|out_conv|b\d+_conv\d+|b\d+_skip|b\d+_trgb)"
     r"/w$", (None, None, None, MODEL_AXIS)),

    # -- replicated by policy --------------------------------------------
    # attention projections and the stylegan mapping/style/rgb-style
    # linears: small [c, c]-ish matmuls, not worth a collective per block
    (r"(^|/)(query|key|value|out|map\d+|b\d+_style\d+|b\d+_rgb_style)/w$",
     REPLICATED),
    # biases of every layer kind
    (r"(^|/)b$", REPLICATED),
    # BatchNorm scale/bias (params) and mean/var (running stats)
    (r"(^|/)(bn\d+|bn_out|b\d+_bn\d+)/(scale|bias|mean|var)$", REPLICATED),
    # spectral-norm power-iteration vectors (state-side sn_<layer> leaves)
    (r"(^|/)sn_[A-Za-z0-9_]+$", REPLICATED),
    # attention output gate (scalar)
    (r"(^|/)attn/gamma$", REPLICATED),
    # stylegan learned constant input [S, S, C]
    (r"(^|/)const$", REPLICATED),
    # Adam step counts (optax ScaleByAdamState / schedule counts)
    (r"(^|/)count$", REPLICATED),
    # the trainer's global step
    (r"^step$", REPLICATED),
)


def count_master_f32_leaves(state: Pytree) -> int:
    """Census of the reduced-precision ladder's f32 MASTER leaves: Adam
    first-moment (`.../mu/...`) leaves stored as float32 while their
    mirrored param leaf is sub-f32 (precision='bf16'/'fp8' sets
    optax.adam(mu_dtype=f32) — train/steps.py::make_optimizer).

    Master-weight LAYOUT note for the rule table above: mu/nu mirror the
    param tree by PATH ("opt/<net>/1/0/mu/<leaf>"), and every row keys on
    the path TAIL — so an f32 master mu shards exactly like its bf16
    param twin without any precision-specific row. dtype is storage, not
    placement; the ladder must never add rules here. This count feeds the
    `perf/precision/master_f32_leaves` metric + CounterSnapshot so a
    restore/config drift that silently drops the master copy (e.g. a
    rebuilt optimizer without mu_dtype) is visible in telemetry and
    pinned by tests.
    """
    import jax
    import jax.numpy as jnp

    params = state.get("params", {})
    param_dtypes = {
        path_str(p): jnp.dtype(leaf.dtype)
        for p, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    n = 0
    for p, leaf in jax.tree_util.tree_flatten_with_path(
            state.get("opt", {}))[0]:
        path = path_str(p)
        if "/mu/" not in path:
            continue
        net, tail = path.split("/", 1)[0], path.split("/mu/", 1)[1]
        twin = param_dtypes.get(f"{net}/{tail}")
        if twin is not None and twin.itemsize < 4 \
                and jnp.dtype(leaf.dtype) == jnp.float32:
            n += 1
    return n


def path_str(path: Sequence[Any]) -> str:
    """The "/"-joined coordinate of one tree_flatten_with_path entry —
    DictKey.key / SequenceKey.idx / GetAttrKey.name, in tree order. This
    is the string the rule regexes and the checkpoint sidecar key on."""
    parts: List[str] = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:  # future jax: unknown key kind — still deterministic
            parts.append(str(k))
    return "/".join(parts)


def matching_rules(path: str, ndim: int,
                   rules: Optional[Sequence[Tuple[str, LogicalSpec]]] = None
                   ) -> List[int]:
    """Indices of every rule that applies to (path, rank). A sharded row
    applies only at its own rank (its spec names one axis per dim);
    REPLICATED rows are rank-free. DCG011 runs this over every leaf of
    every family and flags len != 1. `rules` defaults to the module's
    PARTITION_RULES at CALL time (so table fixtures can patch it)."""
    if rules is None:
        rules = PARTITION_RULES
    out: List[int] = []
    for i, (pat, spec) in enumerate(rules):
        if re.search(pat, path) is None:
            continue
        if spec is not REPLICATED and len(spec) != ndim:
            continue
        out.append(i)
    return out


def logical_spec(path: str, ndim: int,
                 rules: Optional[Sequence[Tuple[str, LogicalSpec]]] = None
                 ) -> LogicalSpec:
    """The single rule row for one leaf; raises on an unclassified path
    (a new layer name must be added to the table — the loud-failure
    contract of SNIPPETS [3] match_partition_rules)."""
    if rules is None:
        rules = PARTITION_RULES
    hits = matching_rules(path, ndim, rules)
    if not hits:
        raise ValueError(
            f"no sharding rule matches state leaf {path!r} (rank {ndim}) — "
            "add a row to dcgan_tpu/elastic/rules.PARTITION_RULES "
            "(`python -m dcgan_tpu.analysis --semantic --checks DCG011` "
            "audits coverage over every model family)")
    return rules[hits[0]][1]


def zero_insert(parts: Sequence[Optional[str]], shape: Sequence[int],
                mesh_shape, *, co_shard: bool = False
                ) -> Tuple[Optional[int], Tuple[Any, ...]]:
    """The data-axis insertion policy shared by every ZeRO stage: pad
    `parts` to the leaf's rank and place DATA_AXIS on the first unsharded
    dim with `size >= data_size` that it divides. Returns (dim, spec) —
    dim None (and `parts` unpadded, matching the pre-engine derivation
    bit-for-bit) when no dim is eligible. ONE definition serves the
    optimizer-state shardings, the ZeRO-2 gradient specs, the ZeRO-3
    param/EMA residency, and the shard_map backend's explicit
    psum_scatter/all_gather dims, so the four can never disagree on where
    a leaf splits.

    co_shard=True (the ZeRO-2/3 form; ZeRO-1 keeps the historical
    first-pass-only behavior so shard_opt placements never move) adds a
    SECOND pass when no free dim divides: a dim already carrying mesh
    axes takes DATA_AXIS as a trailing co-axis — `("model", "data")` on a
    conv kernel's out-channels is the classic TP x ZeRO layout — when the
    dim divides the combined axis product. Without this, any leaf whose
    only large dim is model-annotated (e.g. the first conv's
    [5, 5, c_dim, out] kernel) would silently stay replicated."""
    if DATA_AXIS not in mesh_shape:
        return None, tuple(parts)
    data_size = int(mesh_shape[DATA_AXIS])
    padded: List[Any] = \
        list(parts) + [None] * (len(shape) - len(parts))
    for d, (axis, size) in enumerate(zip(padded, shape)):
        if axis is None and int(size) >= data_size \
                and int(size) % data_size == 0:
            padded[d] = DATA_AXIS
            return d, tuple(padded)
    if co_shard:
        for d, (axis, size) in enumerate(zip(padded, shape)):
            if axis is None:
                continue
            axes = (axis,) if isinstance(axis, str) else tuple(axis)
            combined = data_size
            for a in axes:
                combined *= int(mesh_shape.get(a, 1))
            if int(size) >= combined and int(size) % combined == 0:
                padded[d] = axes + (DATA_AXIS,)
                return d, tuple(padded)
    return None, tuple(parts)


def resolve_spec(spec: LogicalSpec, shape: Sequence[int], mesh_shape,
                 *, spatial: bool = False, shard_opt: bool = False,
                 is_opt: bool = False,
                 zero: bool = False) -> Tuple[Optional[str], ...]:
    """One leaf's logical spec -> the concrete PartitionSpec entries
    (`P(*result)`) for the mesh at hand (`mesh_shape`: {axis: size}).

    Policies, in order, each reproducing the pre-engine derivation
    BIT-FOR-BIT (the committed semantic-tier program fingerprints ride on
    the spec objects, not just the placements):

    - scalars, spatial-mode leaves, and REPLICATED rows resolve to `()`;
    - a sharded row survives only when every named axis exists on the
      current mesh and divides its dim — otherwise the WHOLE spec
      collapses to `()` (the old single `ok(dim)` gate; a size-1 axis
      divides everything, so `model=1` meshes keep the axis name in the
      spec exactly as before);
    - ZeRO-1 (`shard_opt`, optimizer-state leaves only) pads the spec to
      the leaf's rank and inserts the data axis on the first unsharded
      dim with `size >= data_size` that it divides; no eligible dim
      leaves the spec untouched (arXiv:2004.13336 as annotations);
    - `zero=True` applies the same insertion unconditionally — the
      ZeRO-2/3 form, where the caller (state_shardings/grad_shardings)
      decides which leaves the stage shards (opt at stage 2, plus
      params/EMA at stage 3, gradients in both)."""
    shape = tuple(int(d) for d in shape)
    if spec is REPLICATED or len(shape) == 0 or spatial:
        parts: Tuple[Optional[str], ...] = ()
    else:
        keep = True
        for d, axis in enumerate(spec):
            if axis is None:
                continue
            size = mesh_shape.get(axis)
            if size is None or shape[d] % int(size) != 0:
                keep = False
                break
        parts = tuple(spec) if keep else ()
    if zero or (shard_opt and is_opt):
        d, padded = zero_insert(parts, shape, mesh_shape, co_shard=zero)
        if d is not None:
            return padded
    return parts


def zero_targets_leaf(path: str, zero_stage: int) -> bool:
    """Whether the ZeRO stage shards this STATE leaf over the data axis:
    stage >= 2 takes the optimizer state (the ZeRO-2 shard-local update),
    stage 3 additionally keeps params and the EMA mirror resident sharded
    between steps. BN statistics and the step counter never shard — they
    are updated inside the forward, not by the weight-update computation
    this stage partitions (arXiv:2004.13336's scope), and they are a
    rounding error of the state footprint."""
    if zero_stage >= 2 and path.startswith("opt/"):
        return True
    return zero_stage >= 3 and (path.startswith("params/")
                                or path.startswith("ema_gen"))


def state_partition_specs(state_shapes: Pytree, mesh_shape, *,
                          spatial: bool = False,
                          shard_opt: bool = False,
                          zero_stage: int = 1) -> Dict[str, Tuple]:
    """{path: resolved per-dim axis tuple} over a ShapeDtypeStruct tree —
    the flat, serializable form (the checkpoint sidecar stores exactly
    this). `mesh_shape` is {axis name: size}."""
    import jax

    out: Dict[str, Tuple] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state_shapes)[0]:
        p = path_str(path)
        shape = tuple(getattr(leaf, "shape", ()))
        out[p] = resolve_spec(
            logical_spec(p, len(shape)), shape, mesh_shape,
            spatial=spatial, shard_opt=shard_opt,
            is_opt=p.startswith("opt/"),
            zero=zero_targets_leaf(p, zero_stage))
    return out


def state_shardings(state_shapes: Pytree, mesh, *, spatial: bool = False,
                    shard_opt: bool = False,
                    zero_stage: int = 1) -> Pytree:
    """ShapeDtypeStruct tree -> matching NamedSharding tree, via the rule
    table resolved against `mesh`. The engine form of the derivation
    `parallel/sharding.state_shardings` wraps (both backends and the
    serve sources stay callers of that name)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh_shape = dict(mesh.shape)

    def to_sharding(path, leaf):
        p = path_str(path)
        shape = tuple(getattr(leaf, "shape", ()))
        parts = resolve_spec(
            logical_spec(p, len(shape)), shape, mesh_shape,
            spatial=spatial, shard_opt=shard_opt,
            is_opt=p.startswith("opt/"),
            zero=zero_targets_leaf(p, zero_stage))
        return NamedSharding(mesh, P(*parts))
    return jax.tree_util.tree_map_with_path(to_sharding, state_shapes)


def grad_shardings(param_shapes: Pytree, mesh) -> Pytree:
    """NamedSharding tree for one net's GRADIENT tree under ZeRO >= 2
    (the gspmd backend's reduce-scatter constraint targets): the same
    rule rows as the params with the `zero_insert` data-axis policy
    applied — a gradient leaf shards exactly like its mu/nu mirrors (the
    tail of "opt/<net>/.../mu/<leaf>" matches the same row as "<leaf>",
    audited by DCG011's grad-spec-derivation check), which is what makes
    the reduce-scattered gradient the shard-local Adam update's input
    with zero re-layout."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh_shape = dict(mesh.shape)

    def to_sharding(path, leaf):
        p = path_str(path)
        shape = tuple(getattr(leaf, "shape", ()))
        parts = resolve_spec(logical_spec(p, len(shape)), shape, mesh_shape,
                             zero=True)
        return NamedSharding(mesh, P(*parts))
    return jax.tree_util.tree_map_with_path(to_sharding, param_shapes)


def zero_scatter_dims(param_shapes: Pytree, mesh_shape) -> Pytree:
    """int tree over one net's params: the dim `zero_insert` places the
    data axis on, -1 when the leaf stays replicated (-1, not None — None
    is an empty pytree subtree and would break mapping this tree against
    a gradient tree). The shard_map backend's explicit collectives read
    this — psum_scatter's scatter_dimension and all_gather's axis must be
    THE dim the NamedSharding derivation chose, or the stored shards and
    the wire layout disagree."""
    import jax

    def to_dim(path, leaf):
        p = path_str(path)
        shape = tuple(int(d) for d in getattr(leaf, "shape", ()))
        base = resolve_spec(logical_spec(p, len(shape)), shape, mesh_shape)
        d, _ = zero_insert(base, shape, mesh_shape, co_shard=True)
        return -1 if d is None else d
    return jax.tree_util.tree_map_with_path(to_dim, param_shapes)


def validate_zero_state(state_shapes: Pytree, mesh_shape, *,
                        zero_stage: int) -> None:
    """The mesh-concrete half of the zero_stage validation (the config
    dataclass cannot see the device count). Raises when:

    - stage >= 2 runs over a data axis of size 1 (every reduce-scatter
      would be elided and the 'sharded' state would silently be the
      replicated state — the knob must fail loudly, not no-op);
    - a leaf the stage targets has >= 2x the data axis's elements yet NO
      dim the axis divides — the stage's memory model silently degrades
      for that leaf, so the error names it (leaves smaller than 2x the
      axis replicate for free and are exempt)."""
    import jax

    data_size = int(mesh_shape.get(DATA_AXIS, 1))
    if zero_stage >= 2 and data_size < 2:
        raise ValueError(
            f"zero_stage={zero_stage} shards state over the data axis, "
            f"which needs size > 1 (got data={data_size}); use "
            "zero_stage=1 on single-replica meshes")
    if zero_stage < 2:
        return
    for path, leaf in jax.tree_util.tree_flatten_with_path(state_shapes)[0]:
        p = path_str(path)
        if not zero_targets_leaf(p, zero_stage):
            continue
        shape = tuple(int(d) for d in getattr(leaf, "shape", ()))
        size = 1
        for d in shape:
            size *= d
        if size < 2 * data_size:
            continue
        base = resolve_spec(logical_spec(p, len(shape)), shape, mesh_shape)
        d, _ = zero_insert(base, shape, mesh_shape, co_shard=True)
        if d is None:
            raise ValueError(
                f"zero_stage={zero_stage} cannot shard state leaf {p!r} "
                f"(shape {shape}) over the {data_size}-way data axis: no "
                f"dim is divisible by {data_size}. Pad the offending dim, "
                "shrink the data axis, or drop to zero_stage=1")


def zero_bucket_plan(param_shapes: Pytree, mesh_shape, *,
                     bucket_mb: int = 4) -> Tuple[Tuple[int, ...], ...]:
    """Bucket plan for the collective overlap plane (ISSUE 20, DESIGN
    §6n): group one net's scatter-targeted leaves (`zero_scatter_dims`
    dim >= 0; replicated leaves stay outside every bucket) by dtype —
    packing mixed dtypes would force a cast and break the bit-exactness
    contract — and greedily cap each bucket at `bucket_mb` MiB of
    full-leaf bytes. A single leaf larger than the cap gets a bucket of
    its own. Deriving the plan HERE, from the same rule table that
    placed the shards, is what keeps the wire layout and the stored
    layout from ever disagreeing (the zero_scatter_dims contract).

    Returns a tuple of buckets, each a tuple of indices into the
    tree_leaves order of `param_shapes` — deterministic for a given
    (tree, mesh, cap), so the lowered program is cache-stable."""
    import math

    import jax
    import numpy as np

    dims_tree = zero_scatter_dims(param_shapes, mesh_shape)
    leaves = jax.tree_util.tree_leaves(param_shapes)
    dleaves = jax.tree_util.tree_leaves(dims_tree)
    cap = int(bucket_mb) * (1 << 20)
    if cap <= 0:
        raise ValueError(f"bucket_mb must be > 0, got {bucket_mb!r}")
    plan: List[Tuple[int, ...]] = []
    open_buckets: Dict[str, Tuple[List[int], int]] = {}
    for i, (leaf, d) in enumerate(zip(leaves, dleaves)):
        if d < 0:
            continue
        shape = tuple(int(s) for s in getattr(leaf, "shape", ()))
        nbytes = math.prod(shape) * np.dtype(leaf.dtype).itemsize
        dt = str(np.dtype(leaf.dtype))
        idxs, used = open_buckets.get(dt, ([], 0))
        if idxs and used + nbytes > cap:
            plan.append(tuple(idxs))
            idxs, used = [], 0
        idxs.append(i)
        used += nbytes
        if used >= cap:
            plan.append(tuple(idxs))
            idxs, used = [], 0
        open_buckets[dt] = (idxs, used)
    for dt in sorted(open_buckets):
        idxs, _ = open_buckets[dt]
        if idxs:
            plan.append(tuple(idxs))
    return tuple(plan)
