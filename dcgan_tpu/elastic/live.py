"""Live in-run elasticity (ISSUE 18): preemption-notice-driven mesh
shrink/grow without a restart.

PR 12's elastic layer made topology a restart-time degree of freedom: a
checkpoint carries its sharding sidecar, and the next launch reshards onto
whatever mesh it finds. This module removes the restart from the loop for
the advance-notice case — a scheduler that says "you lose half the slice
in 30s" (or "your capacity is back") mid-run:

- `NoticePlane` is the signal half: a notice lands on ONE host as a touch
  file (`--elastic_notice_file`), a SIGUSR1, or a `testing/chaos.py`
  `preempt_notice_at_step`/`grow_notice_at_step` fault. `poll(step)` turns
  the process-local observation into a mesh-uniform verdict through
  `coordination.notice_consensus` — the same boundary-poll consensus shape
  as `CoordinatedStop.poll`, so every process takes the identical switch
  branch at the identical step boundary. File reads and the post-switch
  ack write ride `utils/retry.retry_io` ("notice-poll" / "notice-ack"):
  a transient stat/read blip is retried instead of being misread as
  "no notice" on one host and "notice" on another.

- `LiveTopologyRuntime` is the compiled-surface half, the
  progressive-plane mechanism (progressive/phases.py::PhaseRuntime)
  transposed from model-surface growth to mesh change: one
  `ParallelTrain` per topology (the launch mesh and the
  `--elastic_target_devices` submesh), both AOT-warmed up front under
  `@t<data>x<model>` plan suffixes and primed with one throwaway dispatch
  per program, so the switch itself dispatches only cached executables —
  compile-request delta 0 across a shrink or grow-back. `switch(state)`
  moves the LIVE state between meshes through the elastic host path
  (`jax.device_get` -> `reshard.put_host_tree` onto the target surface's
  sharded templates), which re-scatters ZeRO-2/3 resident shards and
  replicated leaves alike, then (persistent compile cache active) rebases
  the tree onto XLA-owned buffers so donation into deserialized
  executables stays safe (DESIGN §6d).

The trainer (train/trainer.py) sequences the two around the PR 14
phase-boundary machinery: lag-by-one metric flush -> services drain ->
GD-pipeline drain -> fresh rollback snapshot -> `switch` -> re-armed
StepTimer/compiled_ks/fleet cadence on the new mesh. Scope: the switch is
single-controller (process_count == 1) — a *process* cannot leave a live
jax job; multi-host runs keep the consensus plane (the notice still
coordinates a clean stop) but reject `--elastic_target_devices` at
validation, and the restart-based sidecar path (DESIGN §6h) remains the
cross-process-count story. The protocol tier's `live-elastic-switch`
lattice config proves switch symmetry for the consensus half on virtual
multi-host meshes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from dcgan_tpu.testing import chaos
from dcgan_tpu.utils.retry import retry_io

Pytree = Any

#: re-exported verdict encoding (testing/chaos.py is the one definition:
#: the chaos hook's return value IS a consensus vote)
NOTICE_NONE = chaos.NOTICE_NONE
NOTICE_GROW = chaos.NOTICE_GROW
NOTICE_SHRINK = chaos.NOTICE_SHRINK

VERDICT_NAMES = {NOTICE_NONE: "none", NOTICE_GROW: "grow",
                 NOTICE_SHRINK: "shrink"}


def _parse_notice_text(text: str) -> int:
    """Notice-file content -> verdict. An empty file is a shrink notice
    (`touch $file` is the operational fast path); "grow"/"restore" ask for
    the grow-back direction; anything else reads as shrink."""
    word = text.strip().split("\n", 1)[0].strip().lower()
    return NOTICE_GROW if word in ("grow", "restore", "grow-back") \
        else NOTICE_SHRINK


class NoticePlane:
    """Process-local notice sources + the mesh-uniform consensus poll.

    Mirrors `coordination.CoordinatedStop`: `install()` registers a
    one-shot SIGUSR1 handler that only sets a flag (main thread only —
    signal module constraint; restored by `restore()` in the trainer's
    finally block); `poll(step)` folds the local sources (signal flag,
    notice file, chaos plan) into one int verdict and runs it through
    `notice_consensus`, so the returned verdict is identical on every
    process. `ack(...)` renames a consumed notice file out of the poll
    path and writes `<file>.ack` with the switch record — the contract a
    notifying scheduler can wait on.
    """

    def __init__(self, notice_file: str = "") -> None:
        self.notice_file = notice_file
        self._sig_verdict = NOTICE_NONE
        self._restore: dict = {}

    def install(self) -> None:
        if threading.current_thread() is not threading.main_thread():
            return

        def _on_signal(signum, frame):
            self._sig_verdict = NOTICE_SHRINK

        self._restore[signal.SIGUSR1] = signal.signal(
            signal.SIGUSR1, _on_signal)

    def restore(self) -> None:
        for s, h in self._restore.items():
            signal.signal(s, h)
        self._restore.clear()

    # -- local sources -------------------------------------------------------

    def _read_notice_file(self) -> int:
        """One retry_io-guarded stat+read of the notice file. The read is
        inside the retried closure so EVERY failure mode (stat, open,
        read) gets the same bounded-retry treatment — the hazard this
        guards is asymmetry: one host's transient EIO reading "no notice"
        while its peers read "notice" would still converge via consensus,
        but a *flaky* yes/no on the same host across boundaries is noise
        the retries squeeze out at the source."""
        def read():
            if not os.path.exists(self.notice_file):
                return NOTICE_NONE
            with open(self.notice_file, "r", encoding="utf-8") as f:
                return _parse_notice_text(f.read())

        try:
            return retry_io(read, tag="notice-poll")
        except OSError as e:
            # still failing after the retry budget: treat as no-notice
            # (the file is still there — the next boundary re-polls) but
            # say so; silent misreads are the failure mode this plane
            # exists to kill
            print(f"[dcgan_tpu] notice-file poll failed after retries "
                  f"({e}) — treating as no notice this boundary",
                  flush=True)
            return NOTICE_NONE

    def local_verdict(self, step: int) -> int:
        """Fold this process's sources; consuming reads (the signal flag
        clears, the chaos hook is one-shot) are safe because the verdict
        feeds straight into the consensus collective below — once
        observed locally it WILL be agreed fleet-wide this boundary."""
        v = chaos.poll_notice(step)
        if self._sig_verdict:
            v = max(v, self._sig_verdict)
            self._sig_verdict = NOTICE_NONE
        if self.notice_file:
            v = max(v, self._read_notice_file())
        return v

    # -- consensus -----------------------------------------------------------

    def poll(self, step: int) -> Tuple[int, List[int]]:
        """(mesh-uniform verdict, processes that raised it) — the
        boundary-poll collective. Same shape as CoordinatedStop.poll: in
        multi-host runs this is one tiny allgather per boundary; single
        process it is the local verdict with no collective."""
        from dcgan_tpu.train import coordination

        return coordination.notice_consensus(self.local_verdict(step))

    def ack(self, *, step: int, verdict: int, target: str,
            switch_ms: float) -> None:
        """Consume the notice file (rename — a second notice can land at
        the same path later) and write the ack record a notifying
        scheduler polls for. Best-effort beyond the retry budget: the
        switch already happened; bookkeeping must not unwind it."""
        if not self.notice_file:
            return
        record = json.dumps({
            "step": int(step), "verdict": VERDICT_NAMES.get(verdict, "?"),
            "target_mesh": target, "switch_ms": round(switch_ms, 3)})

        def write():
            if os.path.exists(self.notice_file):
                os.replace(self.notice_file,
                           self.notice_file + ".consumed")
            with open(self.notice_file + ".ack", "w",
                      encoding="utf-8") as f:
                f.write(record + "\n")

        try:
            retry_io(write, tag="notice-ack")
        except OSError as e:
            print(f"[dcgan_tpu] notice ack write failed after retries: {e}",
                  flush=True)


def submesh_config(cfg, n_devices: int):
    """The target topology's TrainConfig: identical run semantics (global
    batch, model, schedule — the math is layout-invariant), only the mesh
    data axis resized to fit `n_devices`."""
    model = cfg.mesh.model
    if n_devices % model:
        raise ValueError(
            f"elastic_target_devices={n_devices} is not divisible by the "
            f"model axis ({model}) — the live switch keeps the model axis "
            "and resizes data")
    return dataclasses.replace(
        cfg, mesh=dataclasses.replace(cfg.mesh, data=n_devices // model))


class LiveTopologyRuntime:
    """The trainer's live-elasticity companion: two compiled topology
    surfaces (launch mesh + target submesh), warmup/priming for both, and
    the state move between them. Deliberately shaped like
    progressive/phases.py::PhaseRuntime so the trainer's switch block is
    the same sequence with a different `advance`."""

    def __init__(self, cfg, mesh, *, make_pt: Optional[Callable] = None,
                 launch_pt: Any = None):
        import jax

        if jax.process_count() != 1:
            raise ValueError(
                "--elastic_target_devices requires a single-controller run "
                f"(process_count == 1, got {jax.process_count()}): a "
                "process cannot leave a live jax job — multi-host "
                "elasticity is the restart-based sidecar path (DESIGN §6h)")
        self.base_cfg = cfg
        n_full = int(mesh.devices.size)
        n_target = int(cfg.elastic_target_devices)
        if n_target == n_full:
            raise ValueError(
                f"elastic_target_devices={n_target} equals the launch "
                "topology — nothing to switch to")
        if not 0 < n_target <= len(jax.devices()):
            raise ValueError(
                f"elastic_target_devices={n_target} must be in "
                f"[1, {len(jax.devices())}] (available devices)")
        if make_pt is None:
            from dcgan_tpu.parallel import make_parallel_train

            make_pt = make_parallel_train
        self._make_pt = make_pt
        # index 0 = launch topology (trainer's existing cfg/mesh/pt slot
        # in); index 1 = the configured target. Direction maps onto
        # device count: SHRINK -> fewer devices, GROW -> more.
        self._counts = (n_full, n_target)
        self._surfaces: Dict[int, Tuple[Any, Any, Any]] = {}
        self.index = 0
        self.primed = False
        self.last_switch_ms: float = 0.0
        self.switches = 0
        self._launch = (cfg, mesh)
        if launch_pt is not None:
            # adopt the trainer's already-built launch surface instead of
            # constructing a duplicate compiled-program table for it
            self._surfaces[0] = (cfg, mesh, launch_pt)

    # -- surfaces ------------------------------------------------------------

    def surface(self, i: int) -> Tuple[Any, Any, Any]:
        """(cfg_i, mesh_i, pt_i) for topology i, built lazily and kept —
        the switch must land on an already-built, already-warmed
        surface."""
        if i not in self._surfaces:
            import jax

            from dcgan_tpu.parallel import make_mesh

            if i == 0:
                cfg_i, mesh_i = self._launch
            else:
                cfg_i = submesh_config(self.base_cfg, self._counts[i])
                mesh_i = make_mesh(
                    cfg_i.mesh,
                    list(jax.devices())[:self._counts[i]])
            self._surfaces[i] = (cfg_i, mesh_i, self._make_pt(cfg_i,
                                                              mesh_i))
        return self._surfaces[i]

    @property
    def cfg(self):
        return self.surface(self.index)[0]

    @property
    def mesh(self):
        return self.surface(self.index)[1]

    @property
    def pt(self):
        return self.surface(self.index)[2]

    @property
    def device_count(self) -> int:
        """Devices on the ACTIVE topology — the CounterSnapshot
        `live_topology` value the flight recorder stamps on records."""
        return self._counts[self.index]

    def tag(self, i: Optional[int] = None) -> str:
        """`t<data>x<model>` — the warmup-plan suffix and the
        `elastic/live_target_mesh` event value for topology i."""
        i = self.index if i is None else i
        cfg_i = self.surface(i)[0]
        n = self._counts[i]
        model = cfg_i.mesh.model
        return f"t{n // model}x{model}"

    # -- switching -----------------------------------------------------------

    def target_index(self, verdict: int) -> Optional[int]:
        """Which topology a verdict asks for, or None when already there
        (a grow notice on the full mesh, a second shrink on the submesh —
        consume without switching)."""
        if verdict == NOTICE_SHRINK:
            want = min(range(2), key=lambda i: self._counts[i])
        elif verdict == NOTICE_GROW:
            want = max(range(2), key=lambda i: self._counts[i])
        else:
            return None
        return None if want == self.index else want

    def switch(self, state: Pytree, verdict: int) -> Pytree:
        """Move the LIVE state onto the verdict's topology: host-stage the
        full arrays (`jax.device_get` — single-controller, every shard is
        addressable; ZeRO-2/3 resident shards gather here) and re-scatter
        them per the target surface's shardings via the elastic host path.
        The caller has already drained the GD pipeline and services and
        flushed lag-by-one metrics; it re-snapshots rollback and re-arms
        the timers after. Times itself into `last_switch_ms` (the trainer
        adds drain/re-arm time on top for the event row)."""
        import jax

        from dcgan_tpu.elastic.reshard import put_host_tree
        from dcgan_tpu.train import warmup

        target = self.target_index(verdict)
        if target is None:
            return state
        t0 = time.perf_counter()
        _cfg_t, _mesh_t, pt_t = self.surface(target)
        # the target-sharded template: eval_shape only — nothing allocates
        template = warmup.state_example(pt_t)
        moved = put_host_tree(jax.device_get(state), template)
        from dcgan_tpu.utils.checkpoint import persistent_cache_active

        if persistent_cache_active():
            # host-staged leaves must not be donated into deserialized
            # executables (DESIGN §6d) — one identity pass (the target
            # topology's primed state_copy signature) rebases the tree
            from dcgan_tpu.train.rollback import device_copy

            moved = device_copy(moved)
        self.index = target
        self.switches += 1
        self.last_switch_ms = (time.perf_counter() - t0) * 1e3
        return moved

    # -- warmup + priming ----------------------------------------------------

    def build_warmup_plan(self, state: Pytree, *, sample_z=None,
                          sample_labels=None
                          ) -> List[Tuple[str, Callable, tuple]]:
        """Every program BOTH topologies can dispatch, as warmup-plan rows;
        the launch topology's rows keep their plain names (existing
        per-program perf/compile_ms keys and coverage pins read
        unchanged), the target's are suffixed `@t<data>x<model>`. The
        non-current topology lowers against eval_shape templates and
        target-sharded ShapeDtypeStructs — nothing allocates there."""
        import jax
        import jax.numpy as jnp

        from dcgan_tpu.parallel import batch_sharding
        from dcgan_tpu.train import warmup

        plan: List[Tuple[str, Callable, tuple]] = []
        for i in range(2):
            cfg_i, mesh_i, pt_i = self.surface(i)
            if i == self.index:
                st = state
                z = sample_z
                lbl = sample_labels
                eval_z = jnp.resize(
                    jnp.zeros((1, cfg_i.model.z_dim), jnp.float32),
                    (cfg_i.batch_size, cfg_i.model.z_dim)) \
                    if cfg_i.sample_every_steps else None
            else:
                st = warmup.state_example(pt_i)
                z = None if sample_z is None else jax.ShapeDtypeStruct(
                    tuple(sample_z.shape), jnp.float32,
                    sharding=batch_sharding(mesh_i, 2))
                lbl = None if sample_labels is None \
                    else jax.ShapeDtypeStruct(
                        tuple(sample_labels.shape), sample_labels.dtype,
                        sharding=batch_sharding(mesh_i, 1))
                eval_z = jax.ShapeDtypeStruct(
                    (cfg_i.batch_size, cfg_i.model.z_dim), jnp.float32,
                    sharding=batch_sharding(mesh_i, 2)) \
                    if cfg_i.sample_every_steps else None
            rows, _bk = warmup.build_warmup_plan(
                cfg_i, pt_i, st,
                sample_z=z if cfg_i.sample_every_steps else None,
                sample_labels=lbl, eval_z=eval_z,
                make_backoff_pt=None)
            rows = [("init", pt_i.programs["init"],
                     (jax.random.key(0),))] + list(rows)
            suffix = "" if i == self.index else f"@{self.tag(i)}"
            plan += [(n + suffix, f, a) for n, f, a in rows]
        return plan

    def prime(self, *, sample_z=None, sample_labels=None
              ) -> Dict[str, float]:
        """One throwaway dispatch per program per topology — the PR 9/14
        mechanism that makes zero-compile-requests-after-warmup LITERAL:
        an AOT-compiled program's first __call__ still re-traces and,
        with host-fed args, builds an input transfer program; priming
        absorbs both for the submesh too, so the live switch re-traces
        nothing. Returns {topology tag: prime_ms}. Dispatch-thread only
        (mesh programs)."""
        import jax

        from dcgan_tpu.train.rollback import device_copy

        timings: Dict[str, float] = {}
        for i in range(2):
            t0 = time.perf_counter()
            cfg_i, mesh_i, pt_i = self.surface(i)
            key = jax.random.key(0)
            st = pt_i.init(jax.random.fold_in(key, 7))
            imgs = _zero_images(cfg_i, mesh_i)
            lbls = ()
            if cfg_i.model.num_classes:
                lbls = (_zero_labels(cfg_i, mesh_i),)
            if cfg_i.pipeline_gd:
                fakes = pt_i.gen_fakes(st, key)
                st, m = pt_i.d_update(st, imgs, fakes, key)
                st, _fakes, m = pt_i.g_update(st, key)
            else:
                st, m = pt_i.step(st, imgs, key, *lbls)
            k = cfg_i.steps_per_call
            if k > 1:
                import jax.numpy as jnp

                keys = jax.vmap(jax.random.fold_in, (None, 0))(
                    key, jnp.arange(k))
                imgs_k = jnp.broadcast_to(imgs, (k,) + imgs.shape)
                lbls_k = tuple(jnp.broadcast_to(x, (k,) + x.shape)
                               for x in lbls)
                st, m = pt_i.multi_step(st, imgs_k, keys, *lbls_k)
            if cfg_i.sample_every_steps and sample_z is not None:
                z_i = _zero_z(tuple(sample_z.shape), mesh_i)
                s_lbls = ()
                if sample_labels is not None:
                    s_lbls = (_zero_labels_like(sample_labels, mesh_i),)
                pt_i.sample(st, z_i, *s_lbls)
                import jax.numpy as jnp

                eval_z = jnp.resize(jnp.zeros_like(z_i[:1]),
                                    (cfg_i.batch_size, cfg_i.model.z_dim))
                pt_i.eval_losses(st, imgs, eval_z, *lbls)
            if cfg_i.activation_summary_steps:
                pt_i.summarize(st, imgs, key, *lbls)
            # identity-copy signatures the run dispatches later on this
            # topology: the switch's donation rebase (full state) and the
            # histogram snapshot (params subtree)
            st = device_copy(st)
            device_copy(st["params"])
            jax.block_until_ready(jax.tree_util.tree_leaves(m))
            del st
            timings[self.tag(i)] = (time.perf_counter() - t0) * 1e3
        self.primed = True
        return timings


def _image_sds(cfg, mesh):
    import jax
    import jax.numpy as jnp

    from dcgan_tpu.parallel import batch_sharding

    size = cfg.model.output_size
    return jax.ShapeDtypeStruct(
        (cfg.batch_size, size, size, cfg.model.c_dim), jnp.float32,
        sharding=batch_sharding(mesh, 4, spatial=cfg.mesh.spatial))


def _zero_images(cfg, mesh):
    """All-zero image batch with the topology's live sharding, assembled
    per-shard (each device uploads only its slice)."""
    import jax
    import numpy as np

    sds = _image_sds(cfg, mesh)
    return jax.make_array_from_callback(
        sds.shape, sds.sharding,
        lambda idx: np.zeros([len(range(*s.indices(sds.shape[d])))
                              for d, s in enumerate(idx)], np.float32))


def _zero_z(shape, mesh):
    import jax
    import numpy as np

    from dcgan_tpu.parallel import batch_sharding

    sh = batch_sharding(mesh, len(shape))
    return jax.make_array_from_callback(
        tuple(shape), sh,
        lambda idx: np.zeros([len(range(*s.indices(shape[d])))
                              for d, s in enumerate(idx)], np.float32))


def _zero_labels(cfg, mesh):
    import jax
    import numpy as np

    from dcgan_tpu.parallel import batch_sharding

    sh = batch_sharding(mesh, 1)
    return jax.make_array_from_callback(
        (cfg.batch_size,), sh,
        lambda idx: np.zeros(
            len(range(*idx[0].indices(cfg.batch_size))), np.int32))


def _zero_labels_like(labels, mesh):
    import jax
    import numpy as np

    from dcgan_tpu.parallel import batch_sharding

    n = int(labels.shape[0])
    sh = batch_sharding(mesh, 1)
    return jax.make_array_from_callback(
        (n,), sh,
        lambda idx: np.zeros(len(range(*idx[0].indices(n))),
                             np.asarray(labels).dtype))
