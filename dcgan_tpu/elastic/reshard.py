"""Cross-mesh resharding restore: per-array placement onto the CURRENT
topology.

Two paths, chosen by what changed (DESIGN.md §6h's decision tree):

- **Device path** (same process count, different mesh): the Orbax/
  tensorstore read is simply DIRECTED at the new placement — the
  abstract tree's ShapeDtypeStructs carry the current NamedShardings
  (resolved by the rule engine against the current mesh), and each
  process reads exactly the bytes its new shards need. One pass, no
  staging copy.

- **Host path** (process count changed): the checkpoint's OCDBT layout
  was committed by a different process set, and a sharded device read
  under a different process census would have each process depend on
  chunk files a missing writer may never have made visible to it
  identically; instead every process restores the FULL arrays host-side
  (numpy — no device memory for the staging copy), then
  `jax.make_array_from_callback` uploads only each device's addressable
  shard of the target NamedSharding. Collective-free: every process
  performs the same local reads and puts, so the dispatch-thread
  contract is untouched.

Both paths return trees with exactly the target state's shardings, so
everything downstream of restore (warmup plan lowering, rollback
snapshots, the donation-safety rebase) sees the same tree it would after
a same-topology restore.
"""

from __future__ import annotations

from typing import Any

Pytree = Any


def host_abstract(target_state: Pytree) -> Pytree:
    """Numpy-template abstract tree: StandardRestore hands back plain
    np.ndarrays (full arrays, host memory) for these leaves — the host
    path's staging form."""
    import jax
    import numpy as np

    return jax.tree_util.tree_map(
        lambda x: np.zeros(x.shape, x.dtype)
        if hasattr(x, "shape") else x, target_state)


def device_abstract(target_state: Pytree) -> Pytree:
    """Sharded ShapeDtypeStruct abstract tree carrying the CURRENT
    shardings — the device path's read direction (also the same-topology
    restore's abstract; one derivation for both keeps them in lockstep)."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding",
                                                        None))
        if hasattr(x, "shape") else x,
        target_state)


def put_host_tree(host_tree: Pytree, target_state: Pytree) -> Pytree:
    """Host-staged full arrays -> device arrays with the target tree's
    shardings. Each device uploads only its shard (the callback slices
    the host array per addressable index), so peak device memory is the
    final footprint, not a replicated copy."""
    import jax
    import numpy as np

    def put(host, like):
        sharding = getattr(like, "sharding", None)
        arr = np.asarray(host)
        if sharding is None:
            return jax.device_put(arr)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx, _a=arr: _a[idx])
    return jax.tree_util.tree_map(put, host_tree, target_state)
