"""Named training presets — the BASELINE.json config matrix as one-call configs.

BASELINE.json lists five benchmark configurations for this framework; each is a
`TrainConfig` factory here so `python -m dcgan_tpu.train --preset <name>` (and
tests/bench code) can materialize them without repeating knob soup:

- ``celeba64``    — DCGAN 64x64 CelebA, single-host, z=100, batch 64: the
  reference's headline workload (image_train.py:42-48, distriubted_model.py:7-12).
- ``lsun64-dp8``  — DCGAN 64x64 LSUN-bedroom, data-parallel over 8 chips
  (v5e-8): global batch 64*8 sharded over the "data" mesh axis, grads psum'd
  over ICI — the sync replacement for the reference's async PS workers
  (SURVEY.md §2.5).
- ``dcgan128``    — 128x128: one extra stride-2 stage in both stacks
  (ModelConfig.num_up_layers == 5) with cross-replica synced BatchNorm.
- ``cifar10-cond`` — class-conditional DCGAN on CIFAR-10 (32x32, 10 classes):
  activates the reference's accepted-but-ignored `y` argument
  (distriubted_model.py:83, SURVEY.md §2.4 #7).
- ``wgan-gp``     — WGAN-GP loss variant: Wasserstein critic + gradient
  penalty (grad-of-grad), canonical lr 1e-4 / β1 0 hyperparameters.

Plus five beyond-BASELINE presets across three further model/recipe
families (ten registered configs total — keep this count in sync with
``PRESETS`` below):

- ``sagan64``     — self-attention GAN (hinge + TTUR + EMA, attention at
  32x32), whose attention block is the framework's sequence-parallel
  (ring-attention) showcase under ``--mesh_spatial``.
- ``sagan128``    — the same recipe with attention at 64x64 (4096 tokens).
- ``sagan256-lc`` — the long-context configuration: attention over a
  128x128 feature map (16384 tokens) on the flash kernels, where the
  dense form cannot allocate at batch 64 (DESIGN.md §8b).
- ``sngan-cifar10`` / ``stylegan64`` — the resnet and stylegan families'
  canonical recipes (see their factory docstrings).

Every preset factory takes overrides as keyword arguments forwarded to
`dataclasses.replace`-style reconstruction, so the CLI's explicit flags win
over preset defaults.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig


def _build(model: ModelConfig, mesh: MeshConfig, **train_kw) -> TrainConfig:
    return TrainConfig(model=model, mesh=mesh, **train_kw)


def celeba64(**overrides) -> TrainConfig:
    """DCGAN 64x64 CelebA, single-host (the reference's headline workload)."""
    cfg = _build(ModelConfig(output_size=64), MeshConfig(),
                 batch_size=64, dataset="celebA")
    return dataclasses.replace(cfg, **overrides)


def lsun64_dp8(**overrides) -> TrainConfig:
    """DCGAN 64x64 LSUN-bedroom, data-parallel over an 8-chip mesh."""
    cfg = _build(ModelConfig(output_size=64), MeshConfig(data=8),
                 batch_size=64 * 8, dataset="lsun-bedroom")
    return dataclasses.replace(cfg, **overrides)


def dcgan128(**overrides) -> TrainConfig:
    """DCGAN 128x128: deeper G/D (5 up/down stages), synced BN across mesh."""
    cfg = _build(ModelConfig(output_size=128), MeshConfig(),
                 batch_size=64)
    return dataclasses.replace(cfg, **overrides)


def cifar10_cond(**overrides) -> TrainConfig:
    """Class-conditional DCGAN on CIFAR-10 (32x32 RGB, 10 classes)."""
    cfg = _build(ModelConfig(output_size=32, num_classes=10),
                 MeshConfig(), batch_size=64, dataset="cifar10")
    return dataclasses.replace(cfg, **overrides)


def wgan_gp(**overrides) -> TrainConfig:
    """WGAN-GP on 64x64: critic + gradient penalty, lr 1e-4, β1=0, n_critic=5.

    The BCE defaults (lr 2e-4, β1 0.5, image_train.py:11-13) destabilize a
    Wasserstein critic; these are the standard WGAN-GP settings (Gulrajani et
    al. 2017) — including 5 critic updates per generator update — and apply
    only when the flags are left at their defaults. One documented deviation
    from the paper's Algorithm 1: all 5 critic iterations see the *same* real
    batch (with fresh z each) rather than 5 fresh real minibatches, so the
    whole n_critic loop stays inside one compiled step on one incoming batch.
    """
    cfg = _build(ModelConfig(output_size=64), MeshConfig(),
                 batch_size=64, loss="wgan-gp",
                 learning_rate=1e-4, beta1=0.0, n_critic=5)
    return dataclasses.replace(cfg, **overrides)


def sagan64(**overrides) -> TrainConfig:
    """Self-attention GAN on 64x64: DCGAN stacks with attention at 32x32.

    The canonical SAGAN recipe (Zhang et al. 2018): hinge loss, spectral
    norm on both nets, TTUR (d_lr 4e-4 / g_lr 1e-4), beta1=0, generator
    weight EMA. Beyond-reference model family; under `--mesh_spatial` the
    attention runs as sequence-parallel ring attention (ops/attention.py).
    One documented divergence: G normalization is the reference's plain
    (synced) BatchNorm, not the paper's conditional BN.
    """
    cfg = _build(ModelConfig(output_size=64, attn_res=32,
                             spectral_norm="gd",
                             # measured-best execution split (r5 chip probe:
                             # 10.75 vs 15.70 ms/step, +46% throughput):
                             # attention on the flash kernels, BN on XLA —
                             # fused-BN Pallas loses ~20% at these shapes
                             # (DESIGN.md §8b) while flash wins at S=1024.
                             # Composes with every mesh: per-shard nested
                             # shard_map on DP gspmd (attn_apply's
                             # pallas_mesh route), ring x flash under
                             # --mesh_spatial, per-shard under shard_map
                             use_pallas=True, bn_pallas=False),
                 MeshConfig(),
                 batch_size=64, loss="hinge", beta1=0.0,
                 d_learning_rate=4e-4, g_learning_rate=1e-4,
                 g_ema_decay=0.999)
    return dataclasses.replace(cfg, **overrides)


def sagan128(**overrides) -> TrainConfig:
    """SAGAN at 128x128 — the long-sequence attention demonstrator
    (VERDICT r1 #7): attention at the 64x64 stage is a 4096-token sequence,
    the scale where the sequence-parallel machinery (ring/ulysses under
    --mesh_spatial) and the flash kernels (--use_pallas) earn their keep.
    Same recipe as sagan64 otherwise (hinge, SN both nets, TTUR, EMA)."""
    cfg = _build(ModelConfig(output_size=128, attn_res=64,
                             spectral_norm="gd",
                             # same measured-best split as sagan64: flash
                             # attention + XLA BN (S=4096 is deeper into
                             # flash's winning regime, DESIGN.md §8)
                             use_pallas=True, bn_pallas=False),
                 MeshConfig(),
                 batch_size=64, loss="hinge", beta1=0.0,
                 d_learning_rate=4e-4, g_learning_rate=1e-4,
                 g_ema_decay=0.999)
    return dataclasses.replace(cfg, **overrides)


def sagan256_lc(**overrides) -> TrainConfig:
    """The long-context configuration: 256x256 DCGAN stacks with attention
    over the 128x128 feature map — a 16 384-token sequence — on the flash
    kernels (use_pallas). This is the config the chip measurements pin as
    flash-ONLY at the reference's batch 64: XLA's dense lowering needs a
    64 GiB f32[64, 16384, 16384] score buffer and cannot allocate, while
    the flash path trains at 51.3 img/s (BASELINE.md dcgan256-attn128-*
    rows; DESIGN.md §8/8b). SAGAN recipe (hinge, SN on D, TTUR, EMA); SN
    is D-only here — G's 2048-channel early stages make G-side power
    iteration the dominant non-attention cost at this depth."""
    cfg = _build(ModelConfig(output_size=256, attn_res=128,
                             spectral_norm="d", use_pallas=True,
                             # r5: BN back on XLA — use_pallas exists here
                             # for the flash ATTENTION path; the fused-BN
                             # half measurably loses (DESIGN.md §8b)
                             bn_pallas=False),
                 MeshConfig(),
                 # shard_map backend: use_pallas + attn_res composes with
                 # data-parallel meshes at ANY device count there (each
                 # shard runs the kernels locally; the gspmd partitioner
                 # would reject the combination on a multi-device mesh —
                 # parallel/api.py)
                 backend="shard_map",
                 batch_size=64, loss="hinge", beta1=0.0,
                 d_learning_rate=4e-4, g_learning_rate=1e-4,
                 g_ema_decay=0.999)
    return dataclasses.replace(cfg, **overrides)


def sngan_cifar10(**overrides) -> TrainConfig:
    """SNGAN on CIFAR-10 (32x32), after Miyato et al. 2018 (table 3):
    residual G/D, norm-free spectrally-normalized critic, hinge loss,
    Adam(2e-4, β1=0), 5 critic steps per G step. Two knowing deviations
    from the paper, so don't expect paper-exact FID: β2 stays at the repo
    default 0.999 (paper: 0.9), and the critic architecture differs —
    models/resnet.py doubles channel width per stage and downsamples in
    EVERY block (final 4x4 map), where the paper's CIFAR-10 D keeps
    constant 128-ch blocks with the last two blocks not downsampling
    (final 8x8 map). Beyond-reference model family (models/resnet.py)."""
    cfg = _build(ModelConfig(arch="resnet", output_size=32,
                             spectral_norm="d"),
                 MeshConfig(), batch_size=64, dataset="cifar10",
                 loss="hinge", learning_rate=2e-4, beta1=0.0, n_critic=5)
    return dataclasses.replace(cfg, **overrides)


def stylegan64(**overrides) -> TrainConfig:
    """StyleGAN2-lite at 64x64 (models/stylegan.py): mapping network +
    modulated convs + skip tRGB, paired with the norm-free residual critic
    and the paper's training regularizer — lazy R1 (gamma 10, every 16th
    step) — plus generator-weight EMA. Knowing deviations from the paper
    (documented in models/stylegan.py): no noise injection / style mixing /
    path-length regularization, Adam(2e-4, β1 0.5, β2 0.999) instead of
    (2.5e-3, 0, 0.99), tanh-range output. Beyond-reference model family."""
    cfg = _build(ModelConfig(arch="stylegan", output_size=64),
                 MeshConfig(), batch_size=64,
                 r1_gamma=10.0, r1_interval=16, g_ema_decay=0.999)
    return dataclasses.replace(cfg, **overrides)


PRESETS: Dict[str, Callable[..., TrainConfig]] = {
    "celeba64": celeba64,
    "lsun64-dp8": lsun64_dp8,
    "dcgan128": dcgan128,
    "cifar10-cond": cifar10_cond,
    "wgan-gp": wgan_gp,
    "sagan64": sagan64,
    "sagan128": sagan128,
    "sagan256-lc": sagan256_lc,
    "sngan-cifar10": sngan_cifar10,
    "stylegan64": stylegan64,
}

# Preset revisions: bump when a preset's PERF-RELEVANT config changes
# (execution form, backend, batch policy — anything that moves its bench
# row). bench.py stamps the revision into each preset capture and
# tools/capture_all.py publishes best/spread over the highest revision
# only, so a row's spread never mixes configs that no longer exist —
# the same contract ops/pallas_attention.py::ATTN_GEN gives kernel
# changes. Unlisted presets are revision 1.
# rev 2 (r5): sagan64/sagan128 adopt flash attention + XLA BN
# (chip-measured +46% on the sagan64-shape step); sagan256-lc splits
# bn_pallas off its use_pallas flag.
PRESET_REVS: Dict[str, int] = {
    "sagan64": 2,
    "sagan128": 2,
    "sagan256-lc": 2,
}


def get_preset(name: str, **overrides) -> TrainConfig:
    try:
        factory = PRESETS[name]
    except KeyError:
        raise ValueError(
            f"unknown preset {name!r}; available: {sorted(PRESETS)}") from None
    return factory(**overrides)
