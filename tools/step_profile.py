"""Per-component chip profile of a train step (the MFU numerator).

VERDICT r3 #1 (weak #2): the headline's "~64 TFLOP/s" effective rate had no
in-repo breakdown — no per-op split of the 2.91 ms step and no reproducible
FLOP count. VERDICT r4 #5 extended the same question to the families below
the 4x north star (dcgan128, wgan-gp, sagan64-attn): are they at THEIR
roofs, or leaving throughput on the table? This tool measures both — for
the headline config by default, or any preset/knob combo via the same
BENCH_PRESET / BENCH_ATTN / BENCH_SN / BENCH_PALLAS / BENCH_SIZE env vars
bench.py reads (so a profile always describes exactly the config of a
captured bench row) — standalone or under capture_all (section
"roofline"):

- `compiled.cost_analysis()` on the exact headline train-step program gives
  the XLA FLOP count (the numerator of every TFLOP/s claim in DESIGN.md).
- Component timings through the same scanned-dispatch + value-readback
  harness bench.py uses (each component is scanned K times inside ONE
  compiled program so the tunnel's ~7 ms/dispatch RPC tax cannot pollute a
  ~ms-scale component):
    train_step      full D-then-G step (2 fwd passes + 2 bwd + 2 Adam + BN)
    fwd_losses      forward only: G fwd, D fwd on real and fake (eval_losses)
    g_forward       generator forward alone (the sampler path)
    adam_applies    both optax Adam chains applied to synthetic grads
  The scan body varies its inputs from the scanned-over axis so XLA cannot
  hoist loop-invariant work out and time an empty loop.

The decomposition is arithmetic, not a trace: bwd+opt = step - fwd_losses is
reported as the derived residual (fusion blurs any finer split — XLA fuses
elementwise/BN work into the convs, which is the design, DESIGN.md §1).

Prints one JSON line per component and a summary:
  {"component": "train_step", "ms": t, "images_per_sec": r}
  {"label": "step-profile", "step_ms": t, "flops_per_step": F,
   "tflops_effective": F/t, ...}

PIPELINE_GD=1 additionally emits per-stage FLOP rows for the pipelined
G/D stage programs (ISSUE 7) — {"component": "stage/d_update", ...} for
gen_fakes / d_update / g_update, with the same scan_trips stamp — so cost
attribution under --pipeline_gd describes the programs that run, not only
the fused one.

PALLAS_FUSED=1 / PRECISION={bf16,fp8} (ISSUE 17) profile the knobbed
program (the fused Pallas conv⊕BN⊕act blocks / the reduced-precision
policy), and PALLAS_FUSED=1 additionally emits one
{"component": "fused_kernel/gen/deconv1", ...} row per fused launch —
analytic flops/bytes/peak_temp_mib from ops/pallas_fused.kernel_cost —
plus a fused-conservation summary pinning the analytic count against the
XLA-counted unfused im2col parts.

Per-program rows additionally carry a `collectives` column (ISSUE 20):
op counts by kind plus total collective bytes from the traced jaxpr's
census walk (the same CENSUS_PRIMS mapping the semantic tier uses). The
single-device programs honestly census zero; ZERO_STAGE={2,3} (devices
permitting) appends census-only rows for the SHARDED shard_map step at
that stage — {"component": "census/train_step@zero2@off", ...} vs the
COMM_OVERLAP={bucket,prefetch} arm — so bucket coalescing is visible
per program: the @bucket arm's op count collapses from one collective
per leaf to one per dtype bucket while its bytes stay equal.

Workload anchor: the hot loop being replaced, image_train.py:147-194.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

BATCH = int(os.environ.get("BENCH_BATCH", 64))
SCAN = int(os.environ.get("BENCH_SCAN", 50))
WINDOWS = int(os.environ.get("BENCH_WINDOWS", 3))
# calls per window: one value-readback sync per window, amortized over
# CALLS dispatches (bench.py's policy — a per-call sync puts a full
# transport round-trip inside every measurement at ~RTT/SCAN ms/step)
CALLS = max(1, int(os.environ.get("BENCH_STEPS", 400)) // SCAN)


def main() -> None:
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    import jax.numpy as jnp
    from jax import lax

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import dataclasses

    from dcgan_tpu.config import TrainConfig
    from dcgan_tpu.train.steps import make_optimizer, make_train_step
    from dcgan_tpu.utils.backend import acquire_devices

    acquire_devices()
    # same config knobs as bench.py — one shared parser
    # (dcgan_tpu/utils/bench_env.py), so every profile row decomposes
    # exactly a captured bench config (VERDICT r4 #5)
    from dcgan_tpu.utils.bench_env import (
        apply_attn_res_override,
        bench_model_config,
    )

    preset_name = os.environ.get("BENCH_PRESET", "")
    if preset_name:
        from dcgan_tpu.presets import get_preset

        cfg = dataclasses.replace(get_preset(preset_name),
                                  batch_size=BATCH)
        profile_of = preset_name
    else:
        mcfg, profile_of = bench_model_config()
        cfg = TrainConfig(model=mcfg, batch_size=BATCH)
    cfg = apply_attn_res_override(cfg)
    if preset_name and os.environ.get("BENCH_ATTN_RES"):
        # non-preset labels already carry the attn/flash/dense naming from
        # bench_model_config (computed post-override, ADVICE r5 #2); preset
        # labels only need the attn_res marker appended
        profile_of += f"-attn{os.environ['BENCH_ATTN_RES']}"
    # PRECISION / PALLAS_FUSED compose like bench.py's A/B knobs (ISSUE 17):
    # the profiled train step IS the knobbed program, and PALLAS_FUSED=1
    # additionally emits the per-fused-kernel rows below
    fused = os.environ.get("PALLAS_FUSED") == "1"
    if fused:
        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model, use_pallas=True, pallas_fused=True))
        profile_of += "-fused"
    if os.environ.get("PRECISION"):
        cfg = dataclasses.replace(cfg, precision=os.environ["PRECISION"])
        profile_of += f"-{cfg.precision}"
    if cfg.model.num_classes:
        raise SystemExit(
            "step_profile does not thread class labels; profile the "
            "unconditional families")
    fns = make_train_step(cfg)

    state = jax.jit(fns.init)(jax.random.key(0))
    size = cfg.model.output_size
    images = jnp.asarray(np.random.default_rng(0).uniform(
        -1, 1, size=(BATCH, size, size, cfg.model.c_dim)).astype(np.float32))
    base = jax.random.key(1)
    keys = jax.random.split(base, SCAN)
    zs = jax.random.uniform(base, (SCAN, BATCH, cfg.model.z_dim),
                            minval=-1.0, maxval=1.0)
    # per-iteration input scale ~1.0: defeats loop-invariant hoisting of the
    # real-image branch without changing the work's shape or magnitude
    scales = 1.0 + 1e-6 * jnp.arange(SCAN, dtype=jnp.float32)

    def _sync(out):
        float(jax.tree_util.tree_leaves(out)[0].ravel()[0])

    def _timed(call, carry):
        """Best-of-WINDOWS ms/step; each window is CALLS dispatches with
        ONE value-readback sync at the end (the per-dispatch RTT amortizes
        like bench.py's windows). `call(carry) -> (carry, syncable)`."""
        carry, out = call(carry)      # compile + warmup
        _sync(out)
        dt = float("inf")
        for _ in range(WINDOWS):
            t0 = time.perf_counter()
            for _ in range(CALLS):
                carry, out = call(carry)
            _sync(out)
            dt = min(dt, time.perf_counter() - t0)
        return dt / (CALLS * SCAN) * 1e3

    # --- collective census of a traced program (ISSUE 20) -----------------
    # The same primitive mapping the semantic tier's manifest census uses
    # (analysis/semantic.py::CENSUS_PRIMS), plus output bytes per
    # collective eqn — op COUNT is what bucketing shrinks, BYTES is what
    # it must conserve.
    from dcgan_tpu.analysis.semantic import CENSUS_PRIMS, _walk_jaxpr

    def _census(closed_jaxpr):
        ops, nbytes = {}, 0

        def visit(eqn):
            nonlocal nbytes
            kind = CENSUS_PRIMS.get(eqn.primitive.name)
            if kind is None:
                return
            ops[kind] = ops.get(kind, 0) + 1
            for ov in eqn.outvars:
                aval = getattr(ov, "aval", None)
                if aval is None or not hasattr(aval, "dtype"):
                    continue
                n = 1
                for d in getattr(aval, "shape", ()):
                    n *= int(d)
                nbytes += n * np.dtype(aval.dtype).itemsize
        _walk_jaxpr(closed_jaxpr.jaxpr, visit)
        return {"ops": dict(sorted(ops.items())), "bytes": int(nbytes)}

    # --- XLA cost analysis of the single-step program (lowered up front:
    # the donated train-step timing below consumes `state`'s buffers;
    # traced first so the census walk sees the jaxpr) ----------------------
    traced_step = jax.jit(fns.train_step, donate_argnums=(0,)).trace(
        state, images, base)
    step_census = _census(traced_step.jaxpr)
    lowered = traced_step.lower()
    compiled = lowered.compile()

    # --- per-program resident-bytes split (ISSUE 13) ----------------------
    # What a program keeps LIVE in HBM across dispatches is exactly its
    # donated state — read from the lowering's donation map (args_info),
    # grouped by top-level state key — plus the f32 gradient tree its
    # backward materializes transiently (mirrors the differentiated param
    # subtree). Under --zero_stage these are the buffers the data axis
    # splits; this column is the per-program form of bench.py's
    # peak_state_mib.
    def _grads_mib(*trees):
        """Transient f32 gradient peak: the LARGEST single net's tree —
        the D backward's gradients are consumed (Adam applied, buffers
        free) before the G backward materializes its own, so the fused
        step's peak is max(gen, disc), never the sum."""
        return round(max(
            sum(int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(t))
            for t in trees) * 4 / 2**20, 2)

    def _resident_split(low, grads_mib=None):
        import jax.tree_util as jtu

        groups = {}
        for path, info in jtu.tree_flatten_with_path(low.args_info)[0]:
            if not getattr(info, "donated", False):
                continue
            group = "other"
            for k in path[1:]:
                if hasattr(k, "key"):
                    group = str(k.key)
                    break
            n = 1
            for d in info.shape:
                n *= int(d)
            groups[group] = groups.get(group, 0) \
                + n * np.dtype(info.dtype).itemsize
        row = {f"{k}_mib": round(v / 2**20, 2)
               for k, v in sorted(groups.items())}
        row["state_total_mib"] = round(sum(groups.values()) / 2**20, 2)
        if grads_mib is not None:
            row["grads_mib"] = grads_mib
        return row

    print(json.dumps({"component": "resident/train_step",
                      **_resident_split(
                          lowered,
                          _grads_mib(state["params"]["gen"],
                                     state["params"]["disc"]))}),
          flush=True)

    # --- per-fused-kernel rows (ISSUE 17, PALLAS_FUSED=1) ------------------
    # One row per fused conv⊕BN⊕act launch of a train forward (G + D), from
    # the analytic model in ops/pallas_fused.py (XLA's cost_analysis cannot
    # see inside a pallas_call on TPU, and the CPU interpreter lowers the
    # grid as a loop it counts once). The conservation check is the
    # independent cross-check: the analytic fused count must equal the
    # XLA-counted flops of the SAME im2col formulation unfused — the
    # patches @ w2d GEMM program plus the BN(+act) program the block
    # replaces. (Not lax.conv's own count: XLA skips multiplies against
    # padding/dilation zeros, which the materialized patch GEMM — and the
    # MXU — pay; at small resolutions that bookkeeping difference is >4x,
    # so it would be the wrong denominator for kernel time. Patch
    # extraction itself is excluded for the dual reason — it is 0-flop
    # data movement, but XLA prices its identity-kernel conv lowering as
    # real multiplies.) GEMM dominates, 2% tolerance covers the
    # moment/EMA accounting tails on both sides.
    if fused:
        from dcgan_tpu.ops.norm import batch_norm_apply, batch_norm_init
        from dcgan_tpu.ops.pallas_fused import fused_sites, kernel_cost

        def _xla_flops(fn, *args):
            c = jax.jit(fn).lower(*args).compile()
            ca = c.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            return ca.get("flops")

        cdt = jnp.dtype(cfg.model.compute_dtype)
        totals = {"fused": 0, "parts": 0}
        conserved_all = True
        for s in fused_sites(cfg.model, BATCH):
            cost = kernel_cost(s["m"], s["k"], s["c"], train=True,
                               compute_dtype=cdt)
            parts_flops = None
            try:
                p2d = jax.ShapeDtypeStruct((s["m"], s["k"]), cdt)
                w2d = jax.ShapeDtypeStruct((s["k"], s["c"]), cdt)
                bias = jax.ShapeDtypeStruct((s["c"],), cdt)
                bn_p, bn_s = batch_norm_init(jax.random.key(0), s["c"])
                u = jax.ShapeDtypeStruct(
                    (BATCH, s["out_res"], s["out_res"], s["c"]), cdt)
                parts_flops = _xla_flops(
                    lambda p, w2, bb: jnp.dot(p, w2) + bb, p2d, w2d, bias) \
                    + _xla_flops(functools.partial(
                        batch_norm_apply, train=True, act=s["act"],
                        leak=cfg.model.leak), bn_p, bn_s, u)
            except Exception as e:  # platform may not expose cost analysis
                print(f"{s['name']} unfused cost_analysis unavailable: {e}",
                      file=sys.stderr)
            row = {"component": f"fused_kernel/{s['name']}",
                   "gemm_m": s["m"], "gemm_k": s["k"], "gemm_c": s["c"],
                   "flops": cost["flops"],
                   "flops_parts": cost["flops_parts"],
                   "bytes_accessed": cost["bytes"],
                   "peak_temp_mib": cost["peak_temp_mib"]}
            if parts_flops:
                totals["fused"] += cost["flops"]
                totals["parts"] += int(parts_flops)
                row["xla_unfused_parts_flops"] = int(parts_flops)
                row["conserved"] = bool(
                    abs(cost["flops"] - parts_flops) <= 0.02 * parts_flops)
                conserved_all &= row["conserved"]
            print(json.dumps(row), flush=True)
        if totals["parts"]:
            print(json.dumps({
                "label": "fused-conservation",
                "fused_flops_total": totals["fused"],
                "xla_unfused_parts_total": totals["parts"],
                "ratio": round(totals["fused"] / totals["parts"], 4),
                "conserved": conserved_all}), flush=True)

    # VERDICT Weak #6: XLA's cost model counts a lax.scan (while-loop) body
    # ONCE regardless of trip count, so any in-step scan — the n_critic
    # critic loop (wgan-gp: 5), the grad_accum microbatch loops — under-
    # counts the step's true FLOP/bytes by ~(trips-1) bodies. When the
    # config scans, lower a SECOND, fully-unrolled variant purely for cost
    # analysis (scan with unroll=length emits the body `length` times, so
    # the per-op accounting is exact; verified flops(unroll=k) == k*body on
    # this backend). Timing always uses the real rolled program.
    scan_trips = {}
    if cfg.n_critic > 1:
        scan_trips["n_critic"] = cfg.n_critic
    if cfg.grad_accum > 1:
        scan_trips["grad_accum"] = cfg.grad_accum
    compiled_for_cost = compiled
    if scan_trips:
        orig_scan = lax.scan

        def _unrolled_scan(f, init, xs=None, length=None, **kw):
            n = length if length is not None else \
                jax.tree_util.tree_leaves(xs)[0].shape[0]
            kw["unroll"] = max(1, int(n))
            return orig_scan(f, init, xs, length=length, **kw)

        # contained monkeypatch: steps.py references the same jax.lax
        # module object, so every in-step scan unrolls for this one lowering
        lax.scan = _unrolled_scan
        try:
            cost_fns = make_train_step(cfg)
            compiled_for_cost = jax.jit(
                cost_fns.train_step, donate_argnums=(0,)).lower(
                    state, images, base).compile()
        finally:
            lax.scan = orig_scan

    # --- pipelined stage programs (ISSUE 7, PIPELINE_GD=1) ----------------
    # Under --pipeline_gd the trainer dispatches gen_fakes / d_update /
    # g_update instead of the fused program; without these rows the cost
    # attribution would silently keep describing a program the pipelined
    # run never executes. Same unrolled-scan discipline as the fused count
    # (the d_update critic loop and the microbatch scans under-count by
    # ~(trips-1) bodies otherwise), same scan_trips stamp on each row.
    if os.environ.get("PIPELINE_GD") == "1":
        def _stage_cost(fn, *args, donate=()):
            traced = jax.jit(fn, donate_argnums=donate).trace(*args)
            low = traced.lower()
            c = low.compile()
            ca = c.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            try:
                peak = getattr(c.memory_analysis(), "temp_size_in_bytes",
                               None)
            except Exception:
                peak = None
            return (ca.get("flops"), ca.get("bytes accessed"), peak, low,
                    _census(traced.jaxpr))

        stage_fns = cost_fns if scan_trips else fns
        fakes = jnp.zeros((cfg.n_critic, BATCH, size, size,
                           cfg.model.c_dim), jnp.float32)
        # donation mirrors the backends' (state-only — parallel/api.py);
        # the donated-leaf walk is the resident column's source. Each
        # stage's transient grad tree is the net it differentiates.
        stage_args = {
            "gen_fakes": (stage_fns.gen_fakes, (), None, state, base),
            "d_update": (stage_fns.d_update, (0,), state["params"]["disc"],
                         state, images, fakes, base),
            "g_update": (stage_fns.g_update, (0,), state["params"]["gen"],
                         state, base),
        }
        if scan_trips:
            # the unrolled lowering for exact counts (see above): re-enter
            # the contained monkeypatch for the stage programs' own scans
            lax.scan = _unrolled_scan
        try:
            for name, (fn, donate, grads_tree, *args) in stage_args.items():
                try:
                    s_flops, s_bytes, s_peak, s_low, s_census = \
                        _stage_cost(fn, *args, donate=donate)
                except Exception as e:  # platform may not expose it
                    print(f"{name} cost_analysis unavailable: {e}",
                          file=sys.stderr)
                    continue
                row = {"component": f"stage/{name}", "flops": s_flops,
                       "bytes_accessed": s_bytes,
                       "collectives": s_census}
                if donate:
                    row.update(_resident_split(s_low,
                                               _grads_mib(grads_tree)))
                if s_peak is not None:
                    # the pipelined mode's honest single-device win: the
                    # largest stage program's peak temp is below the fused
                    # program's (measured -15% at the flagship config) —
                    # per-step flops are conservation-equal (d+g == fused;
                    # the fused program's shared-z generator forward is
                    # already CSE'd by XLA)
                    row["peak_temp_mib"] = round(s_peak / 2**20, 1)
                if scan_trips:
                    row["scan_trips"] = scan_trips
                print(json.dumps(row), flush=True)
        finally:
            if scan_trips:
                lax.scan = orig_scan

    # --- sharded-program census rows (ISSUE 20, ZERO_STAGE={2,3}) ---------
    # make_train_step's single-device program censuses zero collectives by
    # construction, so bucket coalescing can't show up in the rows above.
    # These rows trace (never compile) the SHARDED shard_map step at the
    # requested stage, off vs the COMM_OVERLAP arm, purely for the census:
    # the arm's op count collapses to one collective per dtype bucket
    # while its bytes stay conserved.
    zero_env = int(os.environ.get("ZERO_STAGE", "0") or 0)
    if zero_env >= 2:
        if len(jax.devices()) < 2:
            print("ZERO_STAGE census rows need >= 2 devices; skipping",
                  file=sys.stderr)
        else:
            from dcgan_tpu.config import MeshConfig
            from dcgan_tpu.parallel import make_mesh, make_parallel_train
            from dcgan_tpu.train import warmup

            overlap = os.environ.get("COMM_OVERLAP", "")
            if overlap in ("", "1"):
                overlap = "bucket"
            mesh_cfg = MeshConfig(data=2, zero_stage=zero_env)
            mesh = make_mesh(mesh_cfg, jax.devices()[:2])
            for mode in ("off", overlap):
                cfg_s = dataclasses.replace(
                    cfg, backend="shard_map", mesh=mesh_cfg,
                    comm_overlap=mode)
                pt_s = make_parallel_train(cfg_s, mesh)
                st_s = warmup.state_example(pt_s)
                img_s = jax.ShapeDtypeStruct(
                    (BATCH, size, size, cfg.model.c_dim), jnp.float32)
                tr = jax.jit(pt_s.step).trace(st_s, img_s, base)
                print(json.dumps(
                    {"component":
                         f"census/train_step@zero{zero_env}@{mode}",
                     "collectives": _census(tr.jaxpr)}), flush=True)

    # --- forward only: G fwd + D fwd on real and fake (no grads, no Adam) --
    @jax.jit
    def many_fwd(state, images, zs, scales):
        def body(acc, xs):
            z, s = xs
            m = fns.eval_losses(state, images * s, z)
            return acc + m["d_loss"], None
        acc, _ = lax.scan(body, jnp.float32(0), (zs, scales))
        return acc

    fwd_ms = _timed(lambda c: (c, many_fwd(state, images, zs, scales)),
                    None)
    print(json.dumps({"component": "fwd_losses", "ms": round(fwd_ms, 4)}),
          flush=True)

    # --- generator forward alone (the sampler path) ------------------------
    @jax.jit
    def many_gen(state, zs):
        def body(acc, z):
            return acc + fns.sample(state, z).sum(), None
        acc, _ = lax.scan(body, jnp.float32(0), zs)
        return acc

    gen_ms = _timed(lambda c: (c, many_gen(state, zs)), None)
    print(json.dumps({"component": "g_forward", "ms": round(gen_ms, 4)}),
          flush=True)

    # --- both Adam applies alone -------------------------------------------
    import optax

    opt_g = make_optimizer(cfg, cfg.g_learning_rate)
    opt_d = make_optimizer(cfg, cfg.d_learning_rate,
                           updates_per_step=cfg.n_critic)

    @jax.jit
    def many_adam(params, opt_state, _keys):
        def body(carry, _):
            params, opt_state = carry
            # grads derived from the carry: cannot be hoisted, stays O(1)
            gg = jax.tree_util.tree_map(lambda p: p * 1e-8, params["gen"])
            gd = jax.tree_util.tree_map(lambda p: p * 1e-8, params["disc"])
            ug, og = opt_g.update(gg, opt_state["gen"], params["gen"])
            ud, od = opt_d.update(gd, opt_state["disc"], params["disc"])
            params = {"gen": optax.apply_updates(params["gen"], ug),
                      "disc": optax.apply_updates(params["disc"], ud)}
            return (params, {"gen": og, "disc": od}), None
        (params, opt_state), _ = lax.scan(body, (params, opt_state), _keys)
        return params

    adam_ms = _timed(
        lambda c: (c, many_adam(state["params"], state["opt"], keys)), None)
    print(json.dumps({"component": "adam_applies", "ms": round(adam_ms, 4)}),
          flush=True)

    # --- full train step LAST (donation consumes the state buffers) --------
    # donated like the real consumers (trainer/bench): without donation the
    # same program measures ~0.8 ms/step slower on the chip
    @functools.partial(jax.jit, donate_argnums=(0,))
    def many_steps(state, images, keys):
        def body(s, k):
            s, m = fns.train_step(s, images, k)
            return s, m["d_loss"]
        return lax.scan(body, state, keys)

    step_ms = _timed(lambda s: many_steps(s, images, keys), state)
    print(json.dumps({"component": "train_step", "ms": round(step_ms, 4),
                      "images_per_sec": round(BATCH / step_ms * 1e3, 1),
                      "collectives": step_census}),
          flush=True)

    flops = bytes_accessed = None
    try:
        ca = compiled_for_cost.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops = ca.get("flops")
        bytes_accessed = ca.get("bytes accessed")
    except Exception as e:  # platform may not expose cost analysis
        print(f"cost_analysis unavailable: {e}", file=sys.stderr)
    peak_hbm = None
    try:
        ma = compiled.memory_analysis()
        peak_hbm = getattr(ma, "temp_size_in_bytes", None)
    except Exception as e:
        print(f"memory_analysis unavailable: {e}", file=sys.stderr)

    summary = {
        "label": "step-profile",
        "preset": profile_of,
        "batch": BATCH, "scan": SCAN,
        "step_ms": round(step_ms, 4),
        "fwd_ms": round(fwd_ms, 4),
        "bwd_opt_ms_derived": round(step_ms - fwd_ms, 4),
        "g_forward_ms": round(gen_ms, 4),
        "adam_ms": round(adam_ms, 4),
    }
    if scan_trips:
        # stamp the rows so capture_all's tables can distinguish trip-exact
        # counts (this build onward) from pre-fix counted-once captures
        summary["scan_trips"] = scan_trips
    if flops:
        summary["flops_per_step"] = flops
        summary["tflops_effective"] = round(flops / (step_ms * 1e-3) / 1e12,
                                            2)
    if bytes_accessed:
        summary["bytes_accessed"] = bytes_accessed
        summary["hbm_gbps_effective"] = round(
            bytes_accessed / (step_ms * 1e-3) / 1e9, 1)
    if peak_hbm is not None:
        summary["peak_temp_hbm_mib"] = round(peak_hbm / 2**20, 1)
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
