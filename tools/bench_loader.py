"""Input-pipeline throughput benchmark (host-side; no TPU involved).

SURVEY.md §7 hard part (d): the pipeline must feed >~10k images/s/host so the
chip is never input-bound (the training step consumes ~20k img/s on one v5e
at DCGAN-64 — bench.py). This measures the native C++ loader and the
pure-Python fallback over synthetic shards in the reference's on-disk schema.

    python tools/bench_loader.py                  # defaults: 64px f64, 16 threads
    python tools/bench_loader.py --record_dtype uint8 --threads 4 8 16
    python tools/bench_loader.py --data_dir /data/celeba   # real shards

Prints one JSON line per configuration.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dcgan_tpu.data.pipeline import PythonLoader, list_shards  # noqa: E402
from dcgan_tpu.data.synthetic import write_image_tfrecords  # noqa: E402


def measure(loader, batch: int, *, warmup: int = 3, batches: int = 50,
            windows: int = 3) -> float:
    """Best of `windows` measurement windows — host throughput swings 30%+
    run-to-run on small shared machines; steady-state capability is the best
    window, not the mean (same methodology as bench.py on the TPU side)."""
    for _ in range(warmup):
        loader.next()
    dt = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(batches):
            loader.next()
        dt = min(dt, time.perf_counter() - t0)
    return batch * batches / dt


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--data_dir", default="",
                   help="existing TFRecord shards; default: synthetic tmp set")
    p.add_argument("--image_size", type=int, default=64)
    p.add_argument("--record_dtype", default="float64",
                   choices=["float64", "float32", "uint8"])
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--num_examples", type=int, default=4096)
    p.add_argument("--num_shards", type=int, default=8)
    p.add_argument("--threads", type=int, nargs="+", default=[16])
    p.add_argument("--batches", type=int, default=50)
    p.add_argument("--python_loader", action="store_true",
                   help="also measure the pure-Python fallback")
    args = p.parse_args()

    if args.data_dir:
        paths = list_shards(args.data_dir)
        tmp = None
    else:
        tmp = tempfile.TemporaryDirectory()
        paths = write_image_tfrecords(
            tmp.name, num_examples=args.num_examples,
            image_size=args.image_size, num_shards=args.num_shards,
            record_dtype=args.record_dtype)

    shape = (args.image_size, args.image_size, 3)
    kw = dict(batch=args.batch, example_shape=shape,
              record_dtype=args.record_dtype,
              min_after_dequeue=4 * args.batch, prefetch_batches=4,
              seed=0, normalize=True, loop=True)

    from dcgan_tpu.data.native import NativeLoader

    kinds = ["native"] + (["python"] if args.python_loader else [])
    for kind in kinds:
        for n in args.threads:
            cls = NativeLoader if kind == "native" else PythonLoader
            ld = cls(paths, n_threads=n, **kw)
            try:
                rate = measure(ld, args.batch, batches=args.batches)
            finally:
                ld.close()
            print(json.dumps({
                "loader": kind, "threads": n,
                # both loaders clamp readers to the shard count; report the
                # count that actually ran, not the request
                "effective_readers": min(n, len(paths)),
                "record_dtype": args.record_dtype,
                "image_size": args.image_size,
                "images_per_sec": round(rate, 1),
            }))

    if tmp is not None:
        tmp.cleanup()


if __name__ == "__main__":
    main()
