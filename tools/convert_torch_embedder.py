"""Convert a torch conv tower to the evals feature-extractor .npz schema.

`dcgan_tpu.evals.features.make_npz_feature_fn` loads arrays named
`conv{i}/w` (HWIO), `conv{i}/b`, and `proj` [total_pooled, D] and runs them
as a stride-2 LeakyReLU(0.2) tower with per-stage global-average-pool
features (VERDICT r1 #2: this script is the missing conversion path onto
that schema).

Two modes:

  # generic: any torch nn.Sequential of Conv2d (stride 2) [+ LeakyReLU]
  python tools/convert_torch_embedder.py --state_dict tower.pt --proj_dim 512 \
      --out features.npz

  # torchvision InceptionV3 (needs torchvision + its weights — NOT available
  # in the no-egress build environment; run wherever they are)
  python tools/convert_torch_embedder.py --inception --out features.npz

The generic mode is weight-exact: the exported npz reproduces the torch
tower's forward (up to f32 rounding) under make_npz_feature_fn — proven by
tests/test_convert_embedder.py against torch itself. One semantic caveat:
the harness convolves with XLA SAME padding, which is asymmetric at
stride 2 (e.g. (1,2) for 5x5), while torch's `padding=k//2` is symmetric —
a tower *trained* under torch padding shifts by one pixel at each stage.
That offset is immaterial for global-average-pooled Fréchet features (the
only consumer), and scores remain comparable within the extractor.

The --inception mode is approximate by necessity: InceptionV3 is not a plain
stride-2 conv tower, so it exports the five initial Conv2d_1a..4a conv
layers (folding their BatchNorm into w/b) which capture the stem's texture
statistics, plus a fixed-seed projection. Fréchet distances under these
features are comparable within the extractor (the same contract as the
random-feature surrogate, features.py:8-13); they are NOT canonical
pool3-FID numbers. For canonical FID, export pool3 features in an
environment with TF/torchvision and feed them to evals/fid.py directly.
"""

from __future__ import annotations

import argparse

import numpy as np


def _fold_bn(w_oihw: np.ndarray, bn_gamma, bn_beta, bn_mean, bn_var,
             eps: float = 1e-3):
    """Fold an eval-mode BatchNorm into the preceding conv's kernel/bias."""
    scale = bn_gamma / np.sqrt(bn_var + eps)
    w = w_oihw * scale[:, None, None, None]
    b = bn_beta - bn_mean * scale
    return w, b


def _oihw_to_hwio(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (2, 3, 1, 0))


def convert_state_dict(state_dict, proj_dim: int, *, seed: int = 42) -> dict:
    """Torch Conv2d state dict ({i}.weight/{i}.bias, OIHW) -> npz arrays.

    Layers are taken in key order; every `<prefix>.weight` of rank 4 becomes
    conv{i}/w (transposed to HWIO) with its `.bias` (zeros if absent).
    """
    arrays: dict = {}
    total = 0
    i = 0
    for key in state_dict:
        if not key.endswith(".weight"):
            continue
        w = np.asarray(state_dict[key], np.float32)
        if w.ndim != 4:
            continue
        bias_key = key[: -len(".weight")] + ".bias"
        b = (np.asarray(state_dict[bias_key], np.float32)
             if bias_key in state_dict else np.zeros((w.shape[0],),
                                                     np.float32))
        arrays[f"conv{i}/w"] = _oihw_to_hwio(w)
        arrays[f"conv{i}/b"] = b
        total += w.shape[0]
        i += 1
    if i == 0:
        raise ValueError("state dict contains no rank-4 conv weights")
    rng = np.random.default_rng(seed)
    proj = rng.standard_normal((total, proj_dim)).astype(np.float32)
    arrays["proj"] = proj / np.sqrt(np.float32(total))
    return arrays


def convert_inception(proj_dim: int, *, seed: int = 42) -> dict:
    """torchvision InceptionV3 stem convs (BN folded) -> npz arrays."""
    from torchvision.models import Inception_V3_Weights, inception_v3

    net = inception_v3(weights=Inception_V3_Weights.IMAGENET1K_V1)
    net.eval()
    arrays: dict = {}
    total = 0
    stem = ["Conv2d_1a_3x3", "Conv2d_2a_3x3", "Conv2d_2b_3x3",
            "Conv2d_3b_1x1", "Conv2d_4a_3x3"]
    for i, name in enumerate(stem):
        block = getattr(net, name)
        w = block.conv.weight.detach().numpy().astype(np.float32)
        bn = block.bn
        w, b = _fold_bn(w, bn.weight.detach().numpy(),
                        bn.bias.detach().numpy(),
                        bn.running_mean.detach().numpy(),
                        bn.running_var.detach().numpy(), eps=bn.eps)
        arrays[f"conv{i}/w"] = _oihw_to_hwio(w)
        arrays[f"conv{i}/b"] = b.astype(np.float32)
        total += w.shape[0]
    rng = np.random.default_rng(seed)
    proj = rng.standard_normal((total, proj_dim)).astype(np.float32)
    arrays["proj"] = proj / np.sqrt(np.float32(total))
    return arrays


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        prog="convert_torch_embedder",
        description="torch conv tower -> evals feature .npz")
    p.add_argument("--state_dict", default=None,
                   help="path to a torch .pt/.pth state dict of Conv2d layers")
    p.add_argument("--inception", action="store_true",
                   help="convert torchvision InceptionV3 stem convs instead")
    p.add_argument("--proj_dim", type=int, default=512)
    p.add_argument("--seed", type=int, default=42,
                   help="projection seed (features comparable per seed)")
    p.add_argument("--out", required=True)
    args = p.parse_args(argv)

    if bool(args.state_dict) == bool(args.inception):
        raise SystemExit("pass exactly one of --state_dict / --inception")
    if args.inception:
        arrays = convert_inception(args.proj_dim, seed=args.seed)
    else:
        import torch

        sd = torch.load(args.state_dict, map_location="cpu",
                        weights_only=True)
        arrays = convert_state_dict(sd, args.proj_dim, seed=args.seed)
    np.savez(args.out, **arrays)
    n = len([k for k in arrays if k.endswith("/w")])
    print(f"wrote {args.out}: {n} conv layers, proj "
          f"{arrays['proj'].shape[0]} -> {arrays['proj'].shape[1]}")


if __name__ == "__main__":
    main()
