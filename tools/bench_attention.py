"""Attention benchmark: dense vs flash (and ring vs ulysses on a mesh).

The long-context evidence artifact: measures one attention forward+backward
at growing sequence lengths, per execution form (ops/attention.py,
ops/pallas_attention.py). On the TPU chip this is where the flash kernels'
O(S) HBM property shows up as "still runs" after the dense path stops
compiling (~S=64k on one v5e); on a multi-device mesh it compares the two
sequence-parallel strategies. CPU runs are for smoke only.

    python tools/bench_attention.py                      # dense vs flash
    python tools/bench_attention.py --seq 1024 4096 16384
    python tools/bench_attention.py --mesh 4 --heads 4   # + ring/ulysses
    JAX_PLATFORMS=cpu python tools/bench_attention.py --seq 256 --steps 2

Prints one JSON line per (form, S): {"form", "seq", "ms", "heads", ...};
forms that fail to compile/allocate report {"error": ...} instead of dying,
since hitting the dense wall IS the measurement.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, nargs="+",
                   default=[1024, 4096, 16384])
    p.add_argument("--d", type=int, default=64, help="qk head dim")
    p.add_argument("--dv", type=int, default=64, help="value head dim")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--heads", type=int, default=1)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--mesh", type=int, default=0,
                   help=">1: also run ring/ulysses over this many devices "
                        "(sequence axis)")
    p.add_argument("--forward_only", action="store_true")
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (e.g. cpu — overrides plugins "
                        "that pin jax_platforms at startup)")
    args = p.parse_args()

    import jax
    from dcgan_tpu.utils.backend import shard_map

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from dcgan_tpu.ops.attention import (
        full_attention,
        ring_attention,
        ulysses_attention,
    )
    from dcgan_tpu.ops.pallas_attention import ATTN_GEN, flash_attention

    scale = args.d ** -0.5
    h = args.heads

    def make_qkv(S, key):
        ks = jax.random.split(key, 3)
        mk = lambda k, dim: jax.random.normal(
            k, (args.batch * h, S, dim), jnp.bfloat16)
        return mk(ks[0], args.d), mk(ks[1], args.d), mk(ks[2], args.dv)

    forms = {
        "dense": lambda q, k, v: full_attention(q, k, v, scale=scale),
        "flash": lambda q, k, v: flash_attention(q, k, v, scale),
    }
    if args.mesh == 1:
        sys.exit("--mesh must be > 1 (a 1-device ring/ulysses is the dense "
                 "path)")
    if args.mesh > 1:
        devices = jax.devices()[:args.mesh]
        if len(devices) < args.mesh:
            sys.exit(f"need {args.mesh} devices, have {len(devices)}")
        mesh = Mesh(np.asarray(devices).reshape(1, args.mesh),
                    ("data", "model"))
        spec = P("data", "model", None)

        def smap(fn):
            f = shard_map(fn, mesh=mesh, in_specs=(spec,) * 3,
                              out_specs=spec)
            return f

        forms["ring"] = smap(functools.partial(
            ring_attention, axis_name="model", n_shards=args.mesh,
            scale=scale))
        if h % args.mesh:
            print(json.dumps({"form": "ulysses",
                              "skipped": f"heads {h} not divisible by "
                                         f"mesh {args.mesh}"}))
        if h % args.mesh == 0:
            # ulysses works on [B, S, h*d] with heads unfolded
            def uly(q, k, v):
                B = args.batch
                qq = q.reshape(B, h, *q.shape[1:]).transpose(0, 2, 1, 3) \
                    .reshape(B, q.shape[1], -1)
                kk = k.reshape(B, h, *k.shape[1:]).transpose(0, 2, 1, 3) \
                    .reshape(B, k.shape[1], -1)
                vv = v.reshape(B, h, *v.shape[1:]).transpose(0, 2, 1, 3) \
                    .reshape(B, v.shape[1], -1)
                out = shard_map(
                    functools.partial(ulysses_attention, axis_name="model",
                                      n_shards=args.mesh, num_heads=h,
                                      scale=scale),
                    mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)(
                        qq, kk, vv)
                return out
            forms["ulysses"] = uly

    for S in args.seq:
        q, k, v = make_qkv(S, jax.random.key(0))
        for name, fn in forms.items():
            if args.forward_only:
                step = jax.jit(fn)
            else:
                # all three grads: argnums=0 alone would let XLA DCE the
                # dk/dv matmuls out of the dense backward while the flash
                # custom VJP always computes them — an unfair comparison
                step = jax.jit(jax.grad(
                    lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
                    argnums=(0, 1, 2)))

            def sync(out):
                float(jnp.sum(jax.tree_util.tree_leaves(out)[0]
                              .astype(jnp.float32)))

            try:
                sync(step(q, k, v))  # compile + warm
                # best of 3 windows — same methodology as bench.py /
                # bench_loader.py (shared hosts and the tunneled transport
                # swing 30%+ run to run)
                dt = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    for _ in range(args.steps):
                        out = step(q, k, v)
                    sync(out)
                    dt = min(dt, time.perf_counter() - t0)
                ms = dt / args.steps * 1e3
                print(json.dumps({"form": name, "seq": S,
                                  "ms": round(ms, 2), "heads": h,
                                  "batch": args.batch,
                                  "backward": not args.forward_only,
                                  "gen": ATTN_GEN}))
            except Exception as e:  # the dense wall is the measurement
                print(json.dumps({"form": name, "seq": S,
                                  "error": f"{type(e).__name__}: "
                                           f"{str(e)[:160]}",
                                  "heads": h, "gen": ATTN_GEN}))


if __name__ == "__main__":
    main()
