"""Run the CANONICAL feature path at the 50k contract with a stand-in embedder.

The eval duty being replaced is the reference's only quality signal
(image_train.py:179-192 — the human eyeballing sample grids); this repo's
replacement is FID-50k (evals/job.py). Its default embedder is the fixed-seed
random-conv surrogate; the CANONICAL path — a trained torch embedder imported
through tools/convert_torch_embedder.py's .npz schema — was parity-tested but
had never carried a real eval at contract scale (VERDICT r4 #4). This tool
closes that gap without egress:

1. builds a RANDOM-weight torch conv tower (torch is in the image; weights
   need no downloads — the point is exercising the code path, not the score),
2. exports its state_dict and converts it with tools/convert_torch_embedder.py
   (the exact command a user with real InceptionV3/trained-tower weights runs),
3. materializes a step-0 checkpoint (flagship DCGAN-64 config.json + Orbax
   state — evals restores it like any trained checkpoint),
4. runs `python -m dcgan_tpu.evals --feature_npz <npz> --num_samples 50000
   --kid --synthetic` end to end and re-emits its JSON.

With real weights the ONLY change is step 1 (see README "Canonical FID").

Prints one JSON line:
  {"label": "canonical-npz-50k", "fid": ..., "kid": ..., "num_samples": ...,
   "feature_dim": ..., "embedder": "...", "elapsed_s": ...}

Env knobs: BENCH_PLATFORM=cpu + CANON_SAMPLES=1024 for a smoke run;
defaults are the chip + the full 50k contract.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
N_SAMPLES = int(os.environ.get("CANON_SAMPLES", 50_000))


def _build_torch_tower(pt_path: str) -> str:
    """A 4-stage stride-2 conv tower with torch-native random init — the
    stand-in for a trained embedder (same state_dict schema torchvision
    towers or custom-trained towers export)."""
    import torch
    from torch import nn

    torch.manual_seed(0)
    tower = nn.Sequential(
        nn.Conv2d(3, 32, 5, stride=2, padding=2), nn.LeakyReLU(0.2),
        nn.Conv2d(32, 64, 5, stride=2, padding=2), nn.LeakyReLU(0.2),
        nn.Conv2d(64, 128, 3, stride=2, padding=1), nn.LeakyReLU(0.2),
        nn.Conv2d(128, 256, 3, stride=2, padding=1),
    )
    torch.save(tower.state_dict(), pt_path)
    return "random-torch-4conv(32,64,128,256)"


def _make_checkpoint(ckpt_dir: str) -> None:
    """Step-0 flagship checkpoint + config.json, exactly what evals restores
    (random weights: the contract under test is the feature path, not the
    generator's quality)."""
    import jax

    from dcgan_tpu.config import ModelConfig, TrainConfig, save_config
    from dcgan_tpu.parallel import make_mesh, make_parallel_train
    from dcgan_tpu.utils.checkpoint import Checkpointer

    cfg = TrainConfig(model=ModelConfig(), batch_size=64,
                      checkpoint_dir=ckpt_dir)
    pt = make_parallel_train(cfg, make_mesh(cfg.mesh))
    state = pt.init(jax.random.key(0))
    ckpt = Checkpointer(ckpt_dir)
    ckpt.save(0, state, force=True)
    ckpt.wait()
    ckpt.close()
    save_config(cfg, ckpt_dir)


def main() -> None:
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory() as tmp:
        pt_path = os.path.join(tmp, "tower.pt")
        npz_path = os.path.join(tmp, "features.npz")
        ckpt_dir = os.path.join(tmp, "ckpt")

        embedder = _build_torch_tower(pt_path)
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "convert_torch_embedder.py"),
             "--state_dict", pt_path, "--proj_dim", "512",
             "--out", npz_path],
            capture_output=True, text=True)
        if res.returncode != 0:
            raise SystemExit(f"convert failed:\n{res.stderr[-2000:]}")
        print(res.stdout.strip(), file=sys.stderr)

        _make_checkpoint(ckpt_dir)

        argv = [sys.executable, "-m", "dcgan_tpu.evals",
                "--checkpoint_dir", ckpt_dir, "--synthetic",
                "--feature_npz", npz_path,
                "--num_samples", str(N_SAMPLES), "--kid"]
        if os.environ.get("BENCH_PLATFORM"):
            argv += ["--platform", os.environ["BENCH_PLATFORM"]]
        res = subprocess.run(argv, cwd=REPO, capture_output=True, text=True)
        sys.stderr.write((res.stderr or "")[-1500:])
        if res.returncode != 0:
            raise SystemExit(f"evals failed:\n{(res.stdout or '')[-800:]}")
        score = None
        for line in (res.stdout or "").splitlines():
            line = line.strip()
            if line.startswith("{"):
                score = json.loads(line)
        if score is None or score.get("num_samples") != N_SAMPLES:
            raise SystemExit(f"no {N_SAMPLES}-sample score line in evals "
                             f"output:\n{(res.stdout or '')[-800:]}")

    print(json.dumps({
        "label": "canonical-npz-50k",
        "fid": score["fid"],
        "kid": score.get("kid"),
        "num_samples": score["num_samples"],
        "feature_dim": score.get("feature_dim"),
        "embedder": embedder,
        "elapsed_s": round(time.monotonic() - t0, 1),
    }))


if __name__ == "__main__":
    main()
