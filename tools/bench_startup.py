"""Cold-vs-warm startup A/B: is time-to-first-step tracked and improving?

PRs 3-4 made restarts the NORMAL response to faults (watchdog exit,
coordinated preemption stop, rollback), so startup cost is recurring
throughput cost, not a one-off. This tool runs the real trainer entry twice
against one persistent compile cache:

  arm "cold": fresh cache dir + fresh checkpoint dir — every program
      compiles, the cache is primed, a final checkpoint lands;
  arm "warm": same cache dir, same checkpoint dir — the restart path:
      programs deserialize from the primed cache, the checkpoint restores
      through the fused single-pass verified read;
  arm "cross" (ISSUE 12): a CLONE of that checkpoint restored on HALF the
      devices (2 -> 1) — the elastic-topology restart path: the sharding
      sidecar detects the mesh change and the restore reshards through
      the rule engine, reporting perf/restore/reshard_ms alongside the
      cold/warm TTFS row (cold/warm are pinned to 2 virtual devices so
      the cross arm is a real topology change on any host).

and emits ONE BENCH-style JSON line with each arm's startup breakdown
(init / data / restore / compile / time-to-first-step, parsed from the
trainer's own `perf/startup/*` event) plus the pass/fail of the warm-start
invariants it exists to pin:

  - compile phase strictly lower warm than cold, with zero cache misses
    and nonzero hits on the warm arm (the cache actually served);
  - restore bytes read once: the warm arm's verified restore reads each
    manifest byte at most once through the checksum layer
    (bytes_read + bytes_cached == manifest total, no double pass).

`--smoke` shrinks the model and step count to the tier-1 budget
(test_tools pins it, mirroring the chaos_drill pattern); the full-size run
is the standalone capture. CPU-only by design — chip startup trajectory is
bench.py's `startup_ms` field; this tool certifies the MECHANISM.

    JAX_PLATFORMS=cpu python tools/bench_startup.py [--smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STARTUP_PREFIX = "perf/startup/"


def _run_arm(name: str, *, workdir: str, cache_dir: str, ckpt_dir: str,
             max_steps: int, size: int, batch: int, timeout: float,
             device_count: int = 2) -> dict:
    """One trainer subprocess pinned to `device_count` virtual CPU
    devices (a full XLA_FLAGS replace — the ambient test env may pin 8);
    returns its parsed perf/ startup event."""
    argv = [
        sys.executable, "-m", "dcgan_tpu.train",
        "--synthetic",
        "--max_steps", str(max_steps),
        "--batch_size", str(batch),
        "--output_size", str(size),
        "--gf_dim", "8", "--df_dim", "8",
        "--compile_cache_dir", cache_dir,
        "--aot_warmup", "true",
        "--sample_every_steps", "0",
        "--activation_summary_steps", "0",
        "--save_summaries_secs", "0",
        "--save_model_secs", "1e9",
        "--no_tensorboard",
        "--checkpoint_dir", ckpt_dir,
        "--sample_dir", os.path.join(workdir, f"samples-{name}"),
    ]
    t0 = time.perf_counter()
    res = subprocess.run(
        argv, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 XLA_FLAGS="--xla_force_host_platform_device_count="
                           f"{device_count}"),
        capture_output=True, text=True, timeout=timeout)
    wall_ms = (time.perf_counter() - t0) * 1e3
    if res.returncode != 0:
        raise RuntimeError(
            f"{name} trainer rc={res.returncode}: "
            f"{(res.stderr or '')[-800:]}")
    startup = None
    with open(os.path.join(ckpt_dir, "events.jsonl")) as f:
        for line in f:
            e = json.loads(line)
            if e["kind"] == "scalars" and \
                    f"{STARTUP_PREFIX}total_ms" in e["values"]:
                startup = e["values"]
    if startup is None:
        raise RuntimeError(f"{name}: no {STARTUP_PREFIX} event in "
                           f"{ckpt_dir}/events.jsonl")
    perf = {k: v for k, v in startup.items() if k.startswith("perf/")}
    return {"wall_ms": wall_ms, "resumed": "restored checkpoint"
            in (res.stdout or ""), "perf": perf}


def _breakdown(arm: dict) -> dict:
    """The BENCH-style phase row for one arm (ms, rounded)."""
    p = arm["perf"]

    def g(k):
        return round(p.get(STARTUP_PREFIX + k + "_ms", 0.0), 1)

    return {
        "init_ms": g("init"),
        "data_ms": g("data"),
        "restore_ms": g("restore"),
        "compile_ms": g("warmup"),
        "time_to_first_step_ms": round(
            p.get(STARTUP_PREFIX + "total_ms", 0.0), 1),
        "process_wall_ms": round(arm["wall_ms"], 1),
        "cache": {k: int(p.get(f"perf/compile_cache_{k}", 0))
                  for k in ("requests", "hits", "misses")},
        "compile_ms_per_program": {
            k[len("perf/compile_ms/"):]: round(v, 1)
            for k, v in p.items() if k.startswith("perf/compile_ms/")},
    }


def _manifest_bytes(ckpt_dir: str, step: int) -> float:
    """Total manifest-listed bytes of `step`'s integrity manifest — the
    step the warm arm restored, so the read-once check compares the verify
    layer's byte count against exactly the bytes it was verifying."""
    path = os.path.join(ckpt_dir, "integrity", f"{step}.json")
    with open(path) as f:
        return float(sum(rec["size"]
                         for rec in json.load(f)["files"].values()))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes + short budget (the tier-1 pin)")
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-arm subprocess budget (seconds)")
    args = ap.parse_args()
    size, batch, steps = (16, 8, 3) if args.smoke else (64, 16, 5)

    with tempfile.TemporaryDirectory() as tmp:
        cache = os.path.join(tmp, "compile-cache")
        ckpt = os.path.join(tmp, "ckpt")
        cold = _run_arm("cold", workdir=tmp, cache_dir=cache,
                        ckpt_dir=ckpt,
                        max_steps=steps, size=size, batch=batch,
                        timeout=args.timeout)
        # the cold arm's final save is at `steps` — the step warm restores
        manifest_bytes = _manifest_bytes(ckpt, steps)
        warm = _run_arm("warm", workdir=tmp, cache_dir=cache, ckpt_dir=ckpt,
                        max_steps=2 * steps, size=size, batch=batch,
                        timeout=args.timeout)
        # cross-topology arm (ISSUE 12): the warm arm's final save (made
        # on 2 devices) restored on 1 — a CLONE, so the reshard arm can
        # never contaminate the warm dir; the sidecar drives a
        # device-read reshard and the startup event reports its cost
        sys.path.insert(0, REPO)
        from dcgan_tpu.testing.chaos import clone_checkpoint_dir

        ckpt_x = clone_checkpoint_dir(ckpt, os.path.join(tmp, "ckpt-cross"))
        cross = _run_arm("cross", workdir=tmp, cache_dir=cache,
                         ckpt_dir=ckpt_x, max_steps=3 * steps, size=size,
                         batch=batch, timeout=args.timeout,
                         device_count=1)

    c, w = _breakdown(cold), _breakdown(warm)
    x = _breakdown(cross)
    xp = cross["perf"]
    wp = warm["perf"]
    verify_read = wp.get("perf/restore/verify_bytes", -1.0)
    verify_cached = wp.get("perf/restore/verify_cached_bytes", 0.0)
    checks = {
        # the cache actually served the restart: no program recompiled
        "warm_compile_strictly_lower": w["compile_ms"] < c["compile_ms"],
        "warm_zero_misses": w["cache"]["misses"] == 0,
        "warm_has_hits": w["cache"]["hits"] > 0,
        "cold_has_misses": c["cache"]["misses"] > 0,
        # the warm arm resumed from the cold arm's final checkpoint through
        # the fused verified restore, reading each manifest byte ONCE
        "warm_resumed": warm["resumed"],
        "restore_verified": wp.get("perf/restore/verify_files", 0) > 0,
        "restore_bytes_read_once":
            0 <= verify_read <= manifest_bytes
            and verify_read + verify_cached == manifest_bytes,
        # the cross arm actually took the elastic reshard path (and the
        # same-topology warm arm did NOT — sidecar present, path untaken)
        "cross_resharded": xp.get("perf/restore/reshard_ms", 0.0) > 0,
        "warm_no_reshard": "perf/restore/reshard_ms" not in wp,
        "cross_resumed": cross["resumed"],
    }
    row = {
        "label": "bench-startup",
        "platform": "cpu",
        "model": f"dcgan{size}", "batch": batch, "steps": steps,
        "devices": {"cold": 2, "warm": 2, "cross": 1},
        "cold": c,
        "warm": w,
        "cross": dict(
            x, reshard_ms=round(xp.get("perf/restore/reshard_ms", 0.0), 1),
            reshard_leaves=int(
                xp.get("perf/restore/reshard_leaves", 0.0))),
        "restore": {
            "manifest_bytes": manifest_bytes,
            "verify_bytes_read": verify_read,
            "verify_bytes_cached": verify_cached,
            "verify_ms": round(wp.get("perf/restore/verify_ms", 0.0), 1),
        },
        "speedup": {
            "compile_ms": round(c["compile_ms"] / max(w["compile_ms"], 1e-9),
                                2),
            "time_to_first_step": round(
                c["time_to_first_step_ms"]
                / max(w["time_to_first_step_ms"], 1e-9), 2),
        },
        "checks": checks,
        "ok": all(checks.values()),
    }
    print(json.dumps(row))
    sys.exit(0 if row["ok"] else 1)


if __name__ == "__main__":
    main()
