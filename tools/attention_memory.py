"""Attention HBM footprint: dense O(S²) vs flash O(S), by memory analysis.

The flash kernels' value claim on one chip is the memory wall — dense
attention materializes [S, S] score tensors, flash streams fixed blocks
(DESIGN.md §8). Timing cannot show this below the wall, and this
environment's transport cannot COMPILE past S≈45k (the remote-compile
helper dies — §8's boundary mapping), so "dense fails to allocate at 64k"
was CPU-inferred. This tool measures the claim a third way: compile both
forms' forward+backward at growing S and read `compiled.memory_analysis()`
— the XLA-reported temp (scratch) HBM each program needs. No execution, so
the numbers are exact program requirements, not samples; the dense curve's
O(S²) growth extrapolated against the 16 GB HBM IS the wall, measured from
chip-compiled programs.

Prints one JSON line per (form, S):
  {"form": ..., "seq": S, "temp_mib": ..., "args_mib": ...}
plus a summary with the fitted dense S² coefficient and the projected
S where dense temp alone exceeds HBM.

    python tools/attention_memory.py --seq 8192 16384 32768 40960
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, nargs="+",
                   default=[8192, 16384, 32768, 40960])
    p.add_argument("--d", type=int, default=64)
    p.add_argument("--hbm_gib", type=float, default=16.0,
                   help="HBM capacity to project the dense wall against")
    p.add_argument("--platform", default=None)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from dcgan_tpu.ops.attention import full_attention
    from dcgan_tpu.ops.pallas_attention import flash_attention

    scale = args.d ** -0.5
    forms = {
        "dense": lambda q, k, v: full_attention(q, k, v, scale=scale),
        "flash": lambda q, k, v: flash_attention(q, k, v, scale),
    }

    dense_pts = []
    for S in args.seq:
        qkv_aval = jax.ShapeDtypeStruct((1, S, args.d), jnp.bfloat16)
        for name, fn in forms.items():
            step = jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
                argnums=(0, 1, 2)))
            try:
                compiled = step.lower(qkv_aval, qkv_aval, qkv_aval).compile()
                ma = compiled.memory_analysis()
                temp = getattr(ma, "temp_size_in_bytes", None)
                arg = getattr(ma, "argument_size_in_bytes", None)
                row = {"form": name, "seq": S,
                       "temp_mib": round(temp / 2**20, 1)
                       if temp is not None else None,
                       "args_mib": round(arg / 2**20, 1)
                       if arg is not None else None}
                if name == "dense" and temp:
                    dense_pts.append((S, temp))
                print(json.dumps(row), flush=True)
            except Exception as e:  # compile wall: also a data point
                print(json.dumps({"form": name, "seq": S,
                                  "error": f"{type(e).__name__}: "
                                           f"{str(e)[:120]}"}), flush=True)

    # fit temp ~ c*S^2 + fixed by least squares over ALL measured dense
    # points (dedup'd — repeated/unsorted --seq must not skew or crash the
    # fit; the quadratic term dominates at large S, small-S rows carry the
    # fixed overhead the intercept absorbs)
    dense_pts = sorted(dict(dense_pts).items())
    if len(dense_pts) >= 2:
        import numpy as np

        s2 = np.asarray([s ** 2 for s, _ in dense_pts], dtype=np.float64)
        t = np.asarray([t for _, t in dense_pts], dtype=np.float64)
        a = np.stack([s2, np.ones_like(s2)], axis=1)
        (c, fixed), *_ = np.linalg.lstsq(a, t, rcond=None)
        hbm = args.hbm_gib * 2**30
        s_wall = int(((hbm - fixed) / c) ** 0.5) if c > 0 else None
        print(json.dumps({
            "label": "attention-memory",
            "dense_s2_bytes_coeff": round(float(c), 4),
            "projected_dense_wall_seq": s_wall,
            "hbm_gib": args.hbm_gib,
        }), flush=True)


if __name__ == "__main__":
    main()
