"""Summarize a jax.profiler trace: device program durations per step.

The tracing subsystem (utils/profiling.py::TraceCapture, wired into the
trainer as --profile_dir/--profile_start_step/--profile_num_steps, plus the
on-demand --profile_trigger file) captures a Chrome-trace timeline of the
training loop. This tool reads the `*.trace.json.gz` it writes and reports,
for each device-track program, the execution count and per-execution
duration — the device's OWN measurement of step time, independent of every
host-side wall-clock harness (bench.py, StepTimer, tools/step_profile.py
all sync through the transport; the trace does not).

    python -m dcgan_tpu.train --synthetic --profile_dir /tmp/tr ...
    python tools/trace_summary.py /tmp/tr
    python tools/trace_summary.py docs/assets/trace_train_step_v5e.json.gz

The parser lives in dcgan_tpu/utils/trace.py (ISSUE 6) — the same code the
trainer uses to digest trigger-file captures in-process — so this tool and
the live perf/device/* events can never disagree about what a trace says.
CPU captures have no TPU-named process; the shared parser falls back to
the busiest XLA-executor (or non-python) thread track and this tool says
so on stderr instead of silently printing nothing (the pre-ISSUE-6
behavior). A trace with no duration events at all exits nonzero with a
usage hint.

The committed artifact docs/assets/trace_train_step_v5e.json.gz is a real
v5e capture of 5 per-step train_step dispatches: 2.8441-2.8458 ms each
(±0.06%), the cleanest confirmation of the headline step time
(DESIGN.md §1b). Note: the tunneled transport exposes PROGRAM-level device
events only — per-XLA-op rows are not available through it, which is why
the §1b component split uses tools/step_profile.py's compiled sub-programs
instead.

Prints one JSON line per device program.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dcgan_tpu.utils.trace import find_trace  # noqa: E402
from dcgan_tpu.utils.trace import summarize as _summarize  # noqa: E402


def summarize(trace_path: str) -> list:
    """Per-program rows (back-compat shim over the shared parser)."""
    rows, _ = _summarize(trace_path)
    return rows


def main(argv=None) -> None:
    args = argv if argv is not None else sys.argv[1:]
    if len(args) != 1:
        print("usage: trace_summary.py <trace.json.gz | profile_dir>",
              file=sys.stderr)
        sys.exit(2)
    try:
        path = find_trace(args[0])
        rows, source = _summarize(path)
        if not rows:
            print(f"no duration events in {path} — capture one with "
                  "`python -m dcgan_tpu.train --profile_dir <dir>` (or "
                  "touch a --profile_trigger file mid-run) and point this "
                  "tool at the dir or the *.trace.json.gz",
                  file=sys.stderr)
            sys.exit(1)
        if source != "tpu":
            print(f"note: no TPU-named process in {path}; reporting the "
                  f"{source} track (CPU captures time host-side execution "
                  "— device numbers need a chip capture)", file=sys.stderr)
        for row in rows:
            print(json.dumps(row))
    except BrokenPipeError:  # e.g. piped into head
        sys.stderr.close()


if __name__ == "__main__":
    main()
