"""Summarize a jax.profiler trace: device program durations per step.

The tracing subsystem (utils/profiling.py::TraceCapture, wired into the
trainer as --profile_dir/--profile_start_step/--profile_num_steps) captures
a Chrome-trace timeline of the training loop. This tool reads the
`*.trace.json.gz` it writes and reports, for each device-track program,
the execution count and per-execution duration — the device's OWN
measurement of step time, independent of every host-side wall-clock
harness (bench.py, StepTimer, tools/step_profile.py all sync through the
transport; the trace does not).

    python -m dcgan_tpu.train --synthetic --profile_dir /tmp/tr ...
    python tools/trace_summary.py /tmp/tr
    python tools/trace_summary.py docs/assets/trace_train_step_v5e.json.gz

The committed artifact docs/assets/trace_train_step_v5e.json.gz is a real
v5e capture of 5 per-step train_step dispatches: 2.8441-2.8458 ms each
(±0.06%), the cleanest confirmation of the headline step time
(DESIGN.md §1b). Note: the tunneled transport exposes PROGRAM-level device
events only — per-XLA-op rows are not available through it, which is why
the §1b component split uses tools/step_profile.py's compiled sub-programs
instead.

Prints one JSON line per device program plus a host-overhead line.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys


def find_trace(path: str) -> str:
    """Accept a trace file or a --profile_dir root (finds the newest)."""
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(
        path, "**", "*.trace.json.gz"), recursive=True))
    if not hits:
        raise FileNotFoundError(f"no *.trace.json.gz under {path}")
    return hits[-1]


def summarize(trace_path: str) -> list:
    with gzip.open(trace_path) as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    device_pids = {e["pid"] for e in events
                   if e.get("ph") == "M" and e.get("name") == "process_name"
                   and "TPU" in str(e.get("args", {}).get("name", ""))}
    rows: dict = {}
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if e.get("pid") not in device_pids:
            continue
        r = rows.setdefault(e["name"], {"n": 0, "durs": []})
        r["n"] += 1
        r["durs"].append(e["dur"] / 1e3)  # us -> ms
    out = []
    for name, r in sorted(rows.items(),
                          key=lambda kv: -sum(kv[1]["durs"])):
        ds = sorted(r["durs"])
        out.append({
            "program": name[:80], "n": r["n"],
            "total_ms": round(sum(ds), 3),
            "ms_min": round(ds[0], 4), "ms_max": round(ds[-1], 4),
            "ms_median": round(ds[len(ds) // 2], 4),
        })
    return out


def main(argv=None) -> None:
    args = argv if argv is not None else sys.argv[1:]
    if len(args) != 1:
        print("usage: trace_summary.py <trace.json.gz | profile_dir>",
              file=sys.stderr)
        sys.exit(2)
    try:
        for row in summarize(find_trace(args[0])):
            print(json.dumps(row))
    except BrokenPipeError:  # e.g. piped into head
        sys.stderr.close()


if __name__ == "__main__":
    main()
