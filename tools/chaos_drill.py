"""Chaos drill: the fail-operational layer's scenario matrix, end to end.

Each scenario arms ONE deterministic fault (dcgan_tpu/testing/chaos.py,
selected per subprocess through the DCGAN_CHAOS env var, or applied to the
bytes on disk between launches) and runs the REAL trainer on CPU, then
asserts the recovery contract: the run either completes with the right
final step and recovery counters, or fails loudly with the right error —
never silently trains garbage, never hangs.

    scenario              fault                          asserted recovery
    --------------------  -----------------------------  --------------------
    nan-rollback          NaN into the health gate       rollback to last-good
                          mid-run                        snapshot, run
                                                         completes, anomaly/
                                                         rollbacks surfaced
    corrupt-record        payload bit-flip in a shard    record skipped +
                          (within budget)                data/corrupt_records
                                                         counted, run completes
    corrupt-budget        same flip, budget exhausted    hard failure naming
                                                         the budget
    truncate-checkpoint   newest checkpoint truncated    integrity fallback to
                          between runs                   the previous step,
                                                         step marked .corrupt,
                                                         resume completes
    io-error-once         one transient OSError in the   retried with backoff,
                          manifest write path            run completes
    services-crash        background services worker     ServiceError surfaces
                          dies                           on the dispatch
                                                         thread, run aborts
    flight-recorder       NaN under the default abort    flight-recorder dump
                          policy                         written; last record
                                                         = the failing step
    watchdog-dump         hang inside the guarded        watchdog trip dumps
                          dispatch window (1-process)    stacks AND the
                                                         telemetry ring
    trace-trigger         (no fault) pre-touched         N-step capture +
                          --profile_trigger file         in-process digest ->
                                                         perf/device/* events
    pipeline-rollback     NaN mid-run under              rollback drains the
                          --pipeline_gd                  in-flight fake stack,
                                                         refills from the
                                                         restored state, run
                                                         completes; replay is
                                                         bit-exact
    zero-rollback         NaN mid-run under              sharded snapshot
                          --zero_stage 3 (shard_map,     restores, run
                          2 virtual devices)             completes; losses +
                                                         STATE_SUM replay
                                                         BIT-EXACT vs a
                                                         --zero_stage 1
                                                         control (ISSUE 13)
    thread-checks         (no fault) DCGAN_THREAD_       tripwire arms, wraps
                          CHECKS=1 runtime tripwire      every collective
                                                         entry point, run
                                                         completes with zero
                                                         trips (ISSUE 8)
    serve-drain           SIGTERM mid-load to the        intake stops, every
                          sampler server                 in-flight/queued
                          (`python -m dcgan_tpu.serve`)  request completes,
                                                         queue drains, report
                                                         lands, clean exit 0
                                                         (ISSUE 9)
    fleet-replica-kill    chaos kill of one of 3 serve   router drains the dead
                          replicas mid-trace, then a     replica into failover
                          newly finalized checkpoint     (ZERO failed client
                          step lands on disk             requests), watcher
                                                         hot-swaps the
                                                         survivors to the new
                                                         step with zero
                                                         recompiles (ISSUE 19)
    elastic-shrink        2-proc save resumed by 1       sidecar-driven
                          proc (2 devices — same mesh,   host-staged reshard;
                          different process census)      losses + STATE_SUM
                                                         replay BIT-EXACT vs
                                                         a 2-proc control
                                                         resume (ISSUE 12)
    elastic-grow          1-proc (2-device) save         same contract, the
                          resumed by 2 procs            other direction

Multi-host matrix (ISSUE 4, `--multihost`): the same contract under a REAL
2-process jax.distributed job over localhost gRPC (tests/multihost_worker.py
style — each subprocess owns one virtual CPU device, faults armed on ONE
process via the per-process DCGAN_CHAOS map keyed by MH_PID):

    scenario              fault                          asserted recovery
    --------------------  -----------------------------  --------------------
    mh-nan-rollback       NaN into ONE process's gate    consensus spreads the
                          view mid-run                   verdict; both hosts
                                                         roll back together,
                                                         complete, and end
                                                         with IDENTICAL state
    mh-sigterm-stop       SIGTERM delivered to host 1    stop consensus breaks
                          only                           both hosts together
                                                         through a collective
                                                         final save host 0
                                                         resumes BIT-EXACT
    mh-watchdog           host 1 goes silent inside a    watchdog trips on
                          collective window              every process: stack
                                                         dumps + exit 43, no
                                                         hang

Usage:
    JAX_PLATFORMS=cpu python tools/chaos_drill.py            # full matrix
    JAX_PLATFORMS=cpu python tools/chaos_drill.py --smoke    # CI subset
    ... --multihost                                  # 2-process matrix
    ... --multihost --smoke                          # cheapest MH scenario
    ... --only nan-rollback truncate-checkpoint              # cherry-pick

Prints one JSON row per scenario and exits nonzero if any scenario's
contract does not hold. Tiny model (16px, gf/df 8, batch 8): the matrix is a
protocol check, ~10 s/launch on CPU — the numbers mean nothing, the
recovery paths everything.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# jax-free import (config never touches jax at module scope): the
# zero-rollback scenario passes a MeshConfig — and progressive-switch a
# ModelConfig — through the driver's repr-round-tripped `extra` dict
from dcgan_tpu.config import MeshConfig, ModelConfig  # noqa: E402

# CI subset (tests/test_tools.py pins --smoke into tier-1): the cheapest
# scenarios that still cross every new layer — quarantine (data), retry
# (checkpoint IO), worker-crash surfacing (services). The two-phase
# checkpoint-fallback and rollback scenarios run in the full matrix (and
# in-process in tests/test_chaos.py).
SMOKE_SCENARIOS = ("corrupt-record", "io-error-once", "services-crash")

_DRIVER = """
import os
import jax; jax.config.update("jax_platforms", "cpu")
if os.environ.get("DRILL_THREEFRY_PARTITIONABLE"):
    # the elastic cross-topology arms compare losses bit-exactly against
    # 2-process phases, whose workers standardize on partitionable
    # threefry (testing/multihost.py) — the flag changes the generated
    # random STREAM, so both layouts must agree on it
    jax.config.update("jax_threefry_partitionable", True)
from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.train.trainer import train
base = dict(model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32"),
            batch_size=8, tensorboard=False, sample_every_steps=0,
            save_summaries_secs=0.0, log_every_steps=1)
base.update({extra!r})  # scenario overrides WIN over the driver defaults
cfg = TrainConfig(**base)
state = train(cfg, synthetic_data={synthetic!r}, max_steps={max_steps!r})
import numpy as np
total = sum(float(np.abs(np.asarray(jax.device_get(leaf),
                                    np.float64)).sum())
            for leaf in jax.tree_util.tree_leaves(state["params"]))
print("STATE_SUM=%.9e" % total, flush=True)
print("TRAIN_DONE step=%d" % int(jax.device_get(state["step"])), flush=True)
"""


def _state_sum(out: str) -> str:
    """The driver's STATE_SUM line (full-precision text — compared for
    bit-exact equality where the contract supports it)."""
    return next(line for line in out.splitlines()
                if line.startswith("STATE_SUM="))


def _state_sum_value(out: str) -> float:
    """The STATE_SUM line parsed back to a float — for the contracts that
    compare across DIFFERENT reduction orders, where the right check is a
    tight relative tolerance, not text equality."""
    return float(_state_sum(out).split("=", 1)[1])


def _run_train(extra: dict, *, max_steps: int, synthetic: bool = True,
               chaos: dict = None, timeout: int = 600,
               env_extra: dict = None):
    """One trainer subprocess; returns (rc, combined output)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DCGAN_CHAOS", None)
    if chaos:
        env["DCGAN_CHAOS"] = json.dumps(chaos)
    if env_extra:
        env.update(env_extra)
    code = _DRIVER.format(extra=extra, synthetic=synthetic,
                          max_steps=max_steps)
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=timeout)
    return res.returncode, res.stdout + res.stderr


def _events(ckpt_dir: str):
    path = os.path.join(ckpt_dir, "events.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f]


def _scalar_values(events, key):
    return [e["values"][key] for e in events
            if e["kind"] == "scalars" and key in e["values"]]


class Failure(AssertionError):
    pass


def _check(cond, why):
    if not cond:
        raise Failure(why)


# -- scenarios ---------------------------------------------------------------

def scenario_nan_rollback(root: str) -> dict:
    """NaN mid-run -> rollback to last-good snapshot, training resumes and
    completes; anomaly/rollbacks lands in the event stream."""
    ck = os.path.join(root, "ck")
    rc, out = _run_train(
        dict(checkpoint_dir=ck, sample_dir=os.path.join(root, "sm"),
             nan_policy="rollback", nan_check_steps=1,
             rollback_snapshot_steps=2, max_rollbacks=2,
             rollback_lr_backoff=0.5, save_model_secs=1e9),
        max_steps=6, chaos={"nan_at_step": 3})
    _check(rc == 0, f"trainer failed (rc={rc}): {out[-800:]}")
    _check("rolling back to last-good snapshot at step 2" in out,
           f"no rollback message in output: {out[-800:]}")
    _check("TRAIN_DONE step=6" in out, f"run did not complete: {out[-400:]}")
    rollbacks = _scalar_values(_events(ck), "anomaly/rollbacks")
    _check(rollbacks and max(rollbacks) >= 1,
           f"anomaly/rollbacks missing from events (got {rollbacks})")
    return {"rollbacks": max(rollbacks), "final_step": 6}


def _make_corrupt_shards(root: str) -> str:
    from dcgan_tpu.data.synthetic import write_image_tfrecords
    from dcgan_tpu.testing.chaos import corrupt_tfrecord_payload

    data_dir = os.path.join(root, "data")
    paths = write_image_tfrecords(data_dir, num_examples=64, image_size=16,
                                  num_shards=2)
    for p in paths:   # one bad record per shard
        corrupt_tfrecord_payload(p, record_index=2)
    return data_dir


def scenario_corrupt_record(root: str) -> dict:
    """Flipped payload bytes within budget -> records skipped, counter
    surfaced, run completes."""
    data_dir = _make_corrupt_shards(root)
    ck = os.path.join(root, "ck")
    rc, out = _run_train(
        dict(checkpoint_dir=ck, sample_dir=os.path.join(root, "sm"),
             data_dir=data_dir, max_corrupt_records=1000,
             shuffle_buffer=16, num_loader_threads=2, save_model_secs=1e9),
        max_steps=6, synthetic=False)
    _check(rc == 0, f"trainer failed (rc={rc}): {out[-800:]}")
    _check("quarantined corrupt record" in out,
           f"no quarantine log line: {out[-800:]}")
    _check("TRAIN_DONE step=6" in out, f"run did not complete: {out[-400:]}")
    counts = _scalar_values(_events(ck), "data/corrupt_records")
    _check(counts and max(counts) >= 1,
           f"data/corrupt_records missing from events (got {counts})")
    return {"corrupt_records": int(max(counts)), "final_step": 6}


def scenario_corrupt_budget(root: str) -> dict:
    """Same corruption with budget 1 and >1 bad records on disk -> the run
    must HARD-FAIL naming the budget (bounded quarantine, not unbounded
    tolerance)."""
    data_dir = _make_corrupt_shards(root)
    rc, out = _run_train(
        dict(checkpoint_dir=os.path.join(root, "ck"),
             sample_dir=os.path.join(root, "sm"),
             data_dir=data_dir, max_corrupt_records=1,
             shuffle_buffer=16, num_loader_threads=2, save_model_secs=1e9),
        max_steps=200, synthetic=False)
    _check(rc != 0, "budget-exhausted run unexpectedly succeeded")
    _check("budget" in out, f"failure does not name the budget: {out[-800:]}")
    return {"failed_as_required": True}


def scenario_truncate_checkpoint(root: str) -> dict:
    """Truncate the newest checkpoint between runs -> integrity fallback
    restores the previous step, marks .corrupt, resume completes."""
    from dcgan_tpu.testing.chaos import truncate_file

    ck = os.path.join(root, "ck")
    common = dict(checkpoint_dir=ck, sample_dir=os.path.join(root, "sm"),
                  save_model_secs=0.0)  # save (and manifest) every step
    rc, out = _run_train(common, max_steps=4)
    _check(rc == 0, f"phase-A trainer failed (rc={rc}): {out[-800:]}")
    _check(os.path.isdir(os.path.join(ck, "4")), "no step-4 checkpoint")
    _check(os.path.exists(os.path.join(ck, "integrity", "4.json")),
           "no integrity manifest for step 4")
    # truncate the biggest array file in the newest step
    files = [p for p in glob.glob(os.path.join(ck, "4", "**"),
                                  recursive=True) if os.path.isfile(p)]
    victim = max(files, key=os.path.getsize)
    truncate_file(victim, drop_bytes=max(64, os.path.getsize(victim) // 2))

    rc, out = _run_train(common, max_steps=6)
    _check(rc == 0, f"phase-B trainer failed (rc={rc}): {out[-800:]}")
    _check("failed integrity check" in out,
           f"no integrity-failure message: {out[-800:]}")
    _check(os.path.isdir(os.path.join(ck, "4.corrupt")),
           "truncated step was not marked .corrupt")
    _check("restored checkpoint at step 3" in out,
           f"did not fall back to step 3: {out[-800:]}")
    _check("TRAIN_DONE step=6" in out, f"resume did not complete: "
           f"{out[-400:]}")
    return {"fell_back_to": 3, "final_step": 6}


def scenario_io_error_once(root: str) -> dict:
    """One transient OSError in the checkpoint-manifest write -> retried
    with backoff, run completes, manifests intact."""
    ck = os.path.join(root, "ck")
    rc, out = _run_train(
        dict(checkpoint_dir=ck, sample_dir=os.path.join(root, "sm"),
             save_model_secs=0.0),
        max_steps=3, chaos={"io_error_once": "ckpt-manifest"})
    _check(rc == 0, f"trainer failed (rc={rc}): {out[-800:]}")
    _check("transient IO error at 'ckpt-manifest'" in out
           and "retrying" in out, f"no retry log line: {out[-800:]}")
    _check("TRAIN_DONE step=3" in out, f"run did not complete: {out[-400:]}")
    _check(glob.glob(os.path.join(ck, "integrity", "*.json")),
           "no integrity manifests written")
    return {"retried": True, "final_step": 3}


def scenario_services_crash(root: str) -> dict:
    """Background services worker dies -> the error surfaces on the
    DISPATCH thread (ServiceError) and the run aborts loudly."""
    rc, out = _run_train(
        dict(checkpoint_dir=os.path.join(root, "ck"),
             sample_dir=os.path.join(root, "sm"), save_model_secs=1e9),
        max_steps=50, chaos={"services_worker_crash": 1})
    _check(rc != 0, "run with a dead services worker unexpectedly succeeded")
    _check("ServiceError" in out and "background host service" in out,
           f"worker crash did not surface as ServiceError: {out[-800:]}")
    _check("TRAIN_DONE" not in out, "run claimed completion after crash")
    return {"failed_as_required": True}


def scenario_flight_recorder(root: str) -> dict:
    """NaN under the default abort policy -> the run dies loudly AND
    leaves a parseable flight-recorder dump whose LAST record is the
    failing step with a tripped gate verdict (ISSUE 6: the stacks' missing
    telemetry context)."""
    from dcgan_tpu.train.flight_recorder import read_dump

    ck = os.path.join(root, "ck")
    rc, out = _run_train(
        dict(checkpoint_dir=ck, sample_dir=os.path.join(root, "sm"),
             nan_check_steps=1, save_model_secs=1e9),
        max_steps=6, chaos={"nan_at_step": 3})
    _check(rc != 0, "NaN-abort run unexpectedly succeeded")
    _check("non-finite training metrics at step 3" in out,
           f"no NaN abort message: {out[-800:]}")
    path = os.path.join(ck, "flight_recorder.jsonl")
    _check(os.path.exists(path), "no flight-recorder dump after NaN abort")
    header, records = read_dump(path)
    _check(header["reason"] == "nan-abort" and header["step"] == 3,
           f"dump header misattributes the abort: {header}")
    _check(records and records[-1]["step"] == 3
           and records[-1]["gate"] == "trip",
           f"last record is not the tripped step: {records[-1:]}")
    _check(all("counters" in r for r in records),
           "records missing the counter-registry snapshot")
    return {"reason": header["reason"], "dump_records": len(records),
            "failing_step": records[-1]["step"]}


def scenario_watchdog_dump(root: str) -> dict:
    """Single-process watchdog trip (a hang inside the guarded dispatch
    window) -> stack dump + exit 43 as before, now joined by a
    flight-recorder dump naming the phase (ISSUE 6)."""
    from dcgan_tpu.train.flight_recorder import read_dump

    ck = os.path.join(root, "ck")
    rc, out = _run_train(
        dict(checkpoint_dir=ck, sample_dir=os.path.join(root, "sm"),
             collective_timeout_secs=3.0, save_model_secs=1e9),
        max_steps=20, chaos={"hang_at_step": 3, "hang_secs": 60},
        timeout=180)
    _check(rc != 0, "hung run unexpectedly succeeded")
    _check("hung-collective watchdog" in out or "Timeout (" in out,
           f"no watchdog diagnostic: {out[-800:]}")
    _check("TRAIN_DONE" not in out, "hung run claimed completion")
    path = os.path.join(ck, "flight_recorder.jsonl")
    _check(os.path.exists(path), "no flight-recorder dump on watchdog trip")
    header, records = read_dump(path)
    _check(header["reason"] == "watchdog"
           and header.get("phase") == "step-dispatch",
           f"dump header misattributes the trip: {header}")
    _check(header["step"] == 3, f"dump header wrong step: {header}")
    _check(records and records[-1]["step"] >= 1,
           f"ring empty at trip: {records[-1:]}")
    return {"rc": rc, "phase": header["phase"],
            "dump_records": len(records)}


def scenario_trace_trigger(root: str) -> dict:
    """A touched --profile_trigger file -> the next boundary starts an
    N-step device capture, the services worker digests it in-process, and
    perf/device/* attribution (compute/collective/idle-gap/step) lands in
    the event stream; the trigger file is consumed as the ack."""
    trig = os.path.join(root, "trigger")
    open(trig, "w").close()   # pre-touched: fires at the first boundary
    ck = os.path.join(root, "ck")
    rc, out = _run_train(
        dict(checkpoint_dir=ck, sample_dir=os.path.join(root, "sm"),
             profile_trigger=trig, profile_num_steps=2, save_model_secs=1e9),
        max_steps=6)
    _check(rc == 0, f"trainer failed (rc={rc}): {out[-800:]}")
    _check("TRAIN_DONE step=6" in out, f"run did not complete: {out[-400:]}")
    _check(not os.path.exists(trig), "trigger file was not consumed")
    _check("trace digest" in out, f"no digest log line: {out[-800:]}")
    keys = ("perf/device/compute_ms", "perf/device/collective_ms",
            "perf/device/idle_gap_ms", "perf/device/step_ms")
    rows = [e["values"] for e in _events(ck) if e["kind"] == "scalars"
            and "perf/device/compute_ms" in e["values"]]
    _check(rows, "no perf/device/* events after the trigger capture")
    missing = [k for k in keys if k not in rows[-1]]
    _check(not missing, f"digest row missing {missing}")
    _check(rows[-1]["perf/device/compute_ms"] > 0,
           f"empty device attribution: {rows[-1]}")
    return {"device_compute_ms": round(rows[-1][keys[0]], 3),
            "device_idle_gap_ms": round(rows[-1][keys[2]], 3)}


def scenario_pipeline_rollback(root: str) -> dict:
    """NaN mid-run under --pipeline_gd (ISSUE 7) -> the anomaly rollback
    DRAINS the in-flight fake stack (generated by the diverged weights the
    rollback is fleeing — it must never train the restored state), refills
    from the restored generator at the next dispatch, and the run
    completes with the same rollback protocol as fused mode. Determinism
    is asserted the strong way: a second identical pipelined run must
    reproduce STATE_SUM to the printed digit — the drain/refill schedule
    is part of the deterministic replay, not a wall-clock accident. (The
    pipelined and fused final states legitimately differ: staleness-1
    fakes are a different — equally valid — training trajectory.)"""
    knobs = dict(pipeline_gd=True, nan_policy="rollback", nan_check_steps=1,
                 rollback_snapshot_steps=2, max_rollbacks=2,
                 save_model_secs=1e9)

    def one(tag):
        ck = os.path.join(root, f"ck-{tag}")
        rc, out = _run_train(
            dict(checkpoint_dir=ck,
                 sample_dir=os.path.join(root, f"sm-{tag}"), **knobs),
            max_steps=6, chaos={"nan_at_step": 3})
        _check(rc == 0, f"{tag}: trainer failed (rc={rc}): {out[-800:]}")
        _check("rolling back to last-good snapshot at step 2" in out,
               f"{tag}: no rollback message: {out[-800:]}")
        _check("rollback drained the in-flight pipelined fake stack" in out,
               f"{tag}: rollback did not drain the fake buffer: "
               f"{out[-800:]}")
        _check("TRAIN_DONE step=6" in out,
               f"{tag}: run did not complete: {out[-400:]}")
        rollbacks = _scalar_values(_events(ck), "anomaly/rollbacks")
        _check(rollbacks and max(rollbacks) >= 1,
               f"{tag}: anomaly/rollbacks missing (got {rollbacks})")
        return _state_sum(out), max(rollbacks)

    sum_a, rollbacks = one("a")
    sum_b, _ = one("b")
    _check(sum_a == sum_b,
           f"pipelined rollback replay diverged: {sum_a} != {sum_b}")
    return {"rollbacks": rollbacks, "final_step": 6,
            "replay_bit_exact": True}


def scenario_zero_rollback(root: str) -> dict:
    """NaN mid-run under --zero_stage 3 (ISSUE 13): the anomaly rollback
    snapshots and restores the data-SHARDED state (params, EMA, and both
    Adam moments live as rule-engine shards between steps), training
    completes, and the post-rollback losses AND final STATE_SUM replay
    BIT-EXACT against a --zero_stage 1 control fed the same fault — the
    state sharding is a layout, not a different trajectory. backend=
    shard_map: its explicit psum_scatter/all_gather round trip reproduces
    the stage-1 pmean arithmetic to the last bit on CPU (the gspmd
    partitioner reassociates reductions, so stage parity there is
    tolerance-level — tests/test_zero.py). Both arms run single-process
    over 2 virtual devices, the 2-way data axis stage 3 needs."""
    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=2",
           "DRILL_THREEFRY_PARTITIONABLE": "1"}
    knobs = dict(backend="shard_map", nan_policy="rollback",
                 nan_check_steps=1, rollback_snapshot_steps=2,
                 max_rollbacks=2, save_model_secs=1e9,
                 save_summaries_secs=0.0)

    def one(tag, stage):
        ck = os.path.join(root, f"ck-{tag}")
        rc, out = _run_train(
            dict(checkpoint_dir=ck,
                 sample_dir=os.path.join(root, f"sm-{tag}"),
                 mesh=MeshConfig(zero_stage=stage), **knobs),
            max_steps=6, chaos={"nan_at_step": 3}, env_extra=env)
        _check(rc == 0, f"{tag}: trainer failed (rc={rc}): {out[-800:]}")
        _check("rolling back to last-good snapshot at step 2" in out,
               f"{tag}: no rollback message: {out[-800:]}")
        _check("TRAIN_DONE step=6" in out,
               f"{tag}: run did not complete: {out[-400:]}")
        rollbacks = _scalar_values(_events(ck), "anomaly/rollbacks")
        _check(rollbacks and max(rollbacks) >= 1,
               f"{tag}: anomaly/rollbacks missing (got {rollbacks})")
        return _state_sum(out), _loss_rows(_events(ck)), max(rollbacks)

    sum_z, loss_z, rollbacks = one("zero3", 3)
    sum_c, loss_c, _ = one("zero1", 1)
    for s in sorted(loss_c):
        _check(loss_z.get(s) == loss_c[s],
               f"step-{s} losses diverged across zero stages: "
               f"{loss_z.get(s)} != {loss_c[s]}")
    _check(sum_z == sum_c,
           f"zero_stage=3 rollback state diverged from the stage-1 "
           f"control: {sum_z} != {sum_c}")
    return {"rollbacks": rollbacks, "final_step": 6,
            "replay_bit_exact": True, "state_sum": sum_z}


def scenario_progressive_switch(root: str) -> dict:
    """NaN at the step right AFTER a progressive phase switch (ISSUE 15):
    the rollback must restore the POST-switch snapshot (taken at the
    boundary, the new phase's tree — restoring the old tree would feed
    r16 state to r32 programs), the run completes, and determinism holds
    two ways: the faulted run replays STATE_SUM bit-exactly, and the
    pre-switch phase's losses are bit-exact against an UNFAULTED control
    (the rollback re-keys the replayed window by design, so post-rollback
    steps legitimately diverge from the control — the unpoisoned phase
    must not)."""
    model = ModelConfig(output_size=32, gf_dim=8, df_dim=8,
                        compute_dtype="float32")
    knobs = dict(model=model, progressive="16:3,32:*",
                 nan_policy="rollback", nan_check_steps=1,
                 rollback_snapshot_steps=100,  # only init + switch snapshots
                 max_rollbacks=2, save_model_secs=1e9)
    switch_step = 3

    def one(tag, chaos_plan):
        ck = os.path.join(root, f"ck-{tag}")
        rc, out = _run_train(
            dict(checkpoint_dir=ck,
                 sample_dir=os.path.join(root, f"sm-{tag}"), **knobs),
            max_steps=6, chaos=chaos_plan)
        _check(rc == 0, f"{tag}: trainer failed (rc={rc}): {out[-800:]}")
        _check(f"progressive phase 1 at step {switch_step}: r16 -> r32"
               in out, f"{tag}: no phase-switch line: {out[-800:]}")
        _check("TRAIN_DONE step=6" in out,
               f"{tag}: run did not complete: {out[-400:]}")
        return _state_sum(out), _loss_rows(_events(ck)), out

    sum_a, loss_a, out_a = one("a", {"nan_at_step": switch_step + 1})
    _check(f"rolling back to last-good snapshot at step {switch_step}"
           in out_a,
           f"rollback did not restore the post-switch snapshot: "
           f"{out_a[-800:]}")
    rollbacks = _scalar_values(_events(os.path.join(root, "ck-a")),
                               "anomaly/rollbacks")
    _check(rollbacks and max(rollbacks) >= 1,
           f"anomaly/rollbacks missing (got {rollbacks})")
    sum_b, _loss_b, _out_b = one("b", {"nan_at_step": switch_step + 1})
    _check(sum_a == sum_b,
           f"faulted progressive replay diverged: {sum_a} != {sum_b}")
    sum_c, loss_c, _out_c = one("control", None)
    for s in range(1, switch_step + 1):
        _check(loss_a.get(s) == loss_c.get(s),
               f"pre-switch phase losses diverged at step {s}: "
               f"{loss_a.get(s)} != {loss_c.get(s)}")
    _check(sum_a != sum_c or loss_a == loss_c,
           "sanity: faulted and control runs are byte-identical yet a "
           "rollback fired")
    return {"rollbacks": max(rollbacks), "final_step": 6,
            "replay_bit_exact": True, "preswitch_losses_bit_exact": True}


def scenario_thread_checks(root: str) -> dict:
    """(no fault) a short train under DCGAN_THREAD_CHECKS=1 (ISSUE 8): the
    runtime thread-discipline tripwire wraps every collective entry point
    (coordination transports, Checkpointer save/restore, the pt.* program
    dispatches) and the DEFAULT dispatch path must complete with zero
    trips — the end-to-end proof that the collective-thread rule
    (DESIGN.md §6b) holds on the paths the AST walk cannot resolve. The
    per-step save cadence exercises the wrapped Checkpointer.save on
    every boundary."""
    ck = os.path.join(root, "ck")
    rc, out = _run_train(
        dict(checkpoint_dir=ck, sample_dir=os.path.join(root, "sm"),
             save_model_secs=0.0),
        max_steps=6, env_extra={"DCGAN_THREAD_CHECKS": "1"})
    _check(rc == 0, f"trainer failed (rc={rc}): {out[-800:]}")
    _check("thread-discipline tripwire armed" in out,
           f"tripwire never armed: {out[-800:]}")
    _check("ThreadDisciplineError" not in out,
           f"tripwire tripped on the default dispatch path: {out[-800:]}")
    _check("TRAIN_DONE step=6" in out, f"run did not complete: {out[-400:]}")
    return {"tripwire_armed": True, "trips": 0, "final_step": 6}


def scenario_serve_drain(root: str) -> dict:
    """SIGTERM mid-load to the serving plane (ISSUE 9) -> the graceful
    drain contract: intake stops, every already-submitted request
    completes (none dropped, none stranded), the report row lands, and
    the process exits 0 — a preemption notice becomes a clean handoff.
    The demo load is sized so the signal always lands mid-trace."""
    import signal
    import threading
    import time

    ck = os.path.join(root, "ck")
    rc, out = _run_train(
        dict(checkpoint_dir=ck, sample_dir=os.path.join(root, "sm"),
             save_model_secs=1e9),
        max_steps=1)
    _check(rc == 0, f"checkpoint trainer failed (rc={rc}): {out[-800:]}")

    report = os.path.join(root, "serve-report.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DCGAN_CHAOS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "dcgan_tpu.serve",
         "--checkpoint_dir", ck, "--max_batch", "8", "--max_wait_ms", "20",
         "--demo_requests", "2000", "--demo_rps", "25",
         "--report", report, "--platform", "cpu"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    lines = []
    reader = threading.Thread(
        target=lambda: [lines.append(l) for l in proc.stdout], daemon=True)
    reader.start()
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline \
                and not any("warm: serving" in l for l in lines):
            if proc.poll() is not None:
                break
            time.sleep(0.2)
        _check(any("warm: serving" in l for l in lines),
               f"server never turned warm: {''.join(lines)[-800:]}")
        time.sleep(1.5)           # let some of the load land first
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    reader.join(timeout=10)
    out = "".join(lines)
    _check(rc == 0, f"serve exited rc={rc} after SIGTERM: {out[-800:]}")
    _check("received signal 15" in out,
           f"no signal acknowledgement: {out[-800:]}")
    _check("drain:" in out and "clean exit" in out,
           f"no drain summary line: {out[-800:]}")
    _check(os.path.exists(report), "no report row written after the drain")
    with open(report) as f:
        row = json.load(f)
    _check(row["interrupted"] is True, f"report not marked interrupted: "
           f"{row}")
    _check(0 < row["submitted"] < 2000,
           f"signal did not land mid-load (submitted={row['submitted']})")
    _check(row["completed"] == row["submitted"],
           f"in-flight requests lost: submitted {row['submitted']}, "
           f"completed {row['completed']}")
    _check(row["serve/dropped"] == 0,
           f"drain dropped requests: {row['serve/dropped']}")
    return {"submitted": row["submitted"], "completed": row["completed"],
            "unsubmitted": row["unsubmitted"], "clean_exit": True}


def _inject_step(donor_dir: str, serve_dir: str, step: int) -> None:
    """Deliver `step` into `serve_dir` the way a trainer would: integrity
    sidecars first, then the step dir copied under a tmp name and RENAMED
    in — a digit-named dir is finalized by the Orbax contract, so the
    fleet's promotion watcher can never see a half-copied step."""
    import shutil

    integ = os.path.join(donor_dir, "integrity")
    if os.path.isdir(integ):
        dst = os.path.join(serve_dir, "integrity")
        os.makedirs(dst, exist_ok=True)
        for name in os.listdir(integ):
            if name.startswith(f"{step}."):
                shutil.copy2(os.path.join(integ, name),
                             os.path.join(dst, name))
    tmp = os.path.join(serve_dir, f"tmp.promote.{step}")
    shutil.copytree(os.path.join(donor_dir, str(step)), tmp)
    os.rename(tmp, os.path.join(serve_dir, str(step)))


def scenario_fleet_replica_kill(root: str) -> dict:
    """Serving fleet under fire (ISSUE 19): 3 replicas behind the
    failover router; a chaos fault kills replica 1's dispatch thread
    mid-trace, then a newly finalized checkpoint step lands on disk and
    the promotion watcher hot-swaps the SURVIVORS' weights live. The
    contract: zero failed client requests (the kill becomes failover,
    the promotion a drain), the dead replica is drained from rotation
    and logged, and every surviving replica's promotion result proves
    compile_requests_delta == 0 — fleet weight delivery mid-trace is
    recompile-free."""
    import shutil
    import signal
    import threading
    import time

    # two checkpoint dirs from one training lineage: the fleet serves
    # step 1; the donor's step 2 is the "newly finalized" step injected
    # mid-trace for the watcher to promote
    ck = os.path.join(root, "ck")
    rc, out = _run_train(
        dict(checkpoint_dir=ck, sample_dir=os.path.join(root, "sm"),
             save_model_secs=1e9),
        max_steps=1)
    _check(rc == 0, f"checkpoint trainer failed (rc={rc}): {out[-800:]}")
    donor = os.path.join(root, "donor")
    shutil.copytree(ck, donor)
    rc, out = _run_train(
        dict(checkpoint_dir=donor, sample_dir=os.path.join(root, "sm"),
             save_model_secs=1e9),
        max_steps=2)  # resumes @1 -> finalizes step 2
    _check(rc == 0, f"donor trainer failed (rc={rc}): {out[-800:]}")
    _check(os.path.isdir(os.path.join(donor, "2")),
           "donor run left no finalized step-2 dir")

    report = os.path.join(root, "serve-report.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["DCGAN_CHAOS"] = json.dumps(
        {"fault_replica": 1, "replica_kill_at_dispatch": 2})
    proc = subprocess.Popen(
        [sys.executable, "-m", "dcgan_tpu.serve",
         "--checkpoint_dir", ck, "--fleet", "3",
         "--compile_cache_dir", os.path.join(root, "cache"),
         "--watch_promotions", "--watch_interval_secs", "0.25",
         "--max_batch", "8", "--max_wait_ms", "20",
         "--demo_requests", "2000", "--demo_rps", "25",
         "--report", report, "--platform", "cpu"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    lines = []
    reader = threading.Thread(
        target=lambda: [lines.append(l) for l in proc.stdout], daemon=True)
    reader.start()

    def _wait_for(token: str, secs: float) -> None:
        deadline = time.monotonic() + secs
        while time.monotonic() < deadline \
                and not any(token in l for l in lines):
            if proc.poll() is not None:
                break
            time.sleep(0.2)
        _check(any(token in l for l in lines),
               f"never saw {token!r}: {''.join(lines)[-1200:]}")

    try:
        # 3 sequential cold starts share one compile cache; the 1-core
        # CI host still pays replica 0's compiles in full
        _wait_for("warm: serving", 300)
        # phase 1: load lands, replica 1's 2nd dispatch fires the kill,
        # the router drains it from rotation and hedges its work over
        _wait_for("replica 1 UNHEALTHY", 60)
        # phase 2: step 2 lands FINALIZED (sidecars first, then the
        # digit rename) and the watcher promotes the two survivors
        _inject_step(donor, ck, 2)
        _wait_for("serve fleet: promoted", 120)
        time.sleep(1.0)  # a little post-promotion load on new weights
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
    reader.join(timeout=10)
    out = "".join(lines)
    _check(rc == 0, f"serve exited rc={rc} after SIGTERM: {out[-1200:]}")
    _check(os.path.exists(report), "no report row written after the drain")
    with open(report) as f:
        row = json.load(f)
    _check(row["interrupted"] is True,
           f"report not marked interrupted: {row}")
    _check(0 < row["submitted"] < 2000,
           f"signal did not land mid-load (submitted={row['submitted']})")
    _check(row["failed"] == 0,
           f"{row['failed']} client request(s) FAILED — the kill leaked "
           f"past the failover router")
    _check(row["completed"] == row["submitted"],
           f"in-flight requests lost: submitted {row['submitted']}, "
           f"completed {row['completed']}")
    _check(row["serve/dropped"] == 0,
           f"fleet dropped requests: {row['serve/dropped']}")
    fl = row["fleet"]
    _check(fl["replicas"] == 3, f"wrong fleet size in report: {fl}")
    unhealthy = {i for i, _ in fl["unhealthy"]}
    _check(1 in unhealthy,
           f"killed replica missing from unhealthy events: "
           f"{fl['unhealthy']}")
    # the chaos kill surfaces exactly once, as the DEAD replica's stop
    # error (stop() re-raises the worker's failure; fleet.stop collects)
    _check(all(i == 1 for i, _ in fl["stop_errors"]),
           f"a SURVIVOR failed to stop cleanly: {fl['stop_errors']}")
    _check(any("chaos: replica 1 killed" in err
               for _, err in fl["stop_errors"]),
           f"chaos kill never fired (stop_errors={fl['stop_errors']}, "
           f"unhealthy={fl['unhealthy']})")
    _check(fl["promotions"], "watcher never promoted the injected step")
    last = fl["promotions"][-1]
    _check({r.get("replica") for r in last} == {0, 2},
           f"promotion did not target exactly the survivors: {last}")
    _check(all("error" not in r and r["step"] == 2 for r in last),
           f"a survivor's promotion failed or got the wrong step: {last}")
    _check(all(r.get("compile_requests_delta") == 0 for r in last),
           f"promotion compiled something: {last}")
    _check(row["serve/recompiles_after_warmup"] == 0,
           f"post-warmup recompiles: "
           f"{row['serve/recompiles_after_warmup']}")
    return {"submitted": row["submitted"], "completed": row["completed"],
            "failed": 0, "unhealthy": sorted(unhealthy),
            "failovers": fl["failovers"],
            "promoted_replicas": sorted(r["replica"] for r in last),
            "promoted_step": 2, "compile_requests_delta": 0}


SCENARIOS = {
    "nan-rollback": scenario_nan_rollback,
    "serve-drain": scenario_serve_drain,
    "fleet-replica-kill": scenario_fleet_replica_kill,
    "thread-checks": scenario_thread_checks,
    "pipeline-rollback": scenario_pipeline_rollback,
    "zero-rollback": scenario_zero_rollback,
    "progressive-switch": scenario_progressive_switch,
    "corrupt-record": scenario_corrupt_record,
    "corrupt-budget": scenario_corrupt_budget,
    "truncate-checkpoint": scenario_truncate_checkpoint,
    "io-error-once": scenario_io_error_once,
    "services-crash": scenario_services_crash,
    "flight-recorder": scenario_flight_recorder,
    "watchdog-dump": scenario_watchdog_dump,
    "trace-trigger": scenario_trace_trigger,
}


# -- multi-host scenarios (ISSUE 4) ------------------------------------------
#
# Two real OS processes form a jax.distributed job over localhost gRPC (one
# virtual CPU device each — the cheapest topology that still makes every
# save/allgather a true cross-process collective). Faults arm on process 1
# only, through the per-process DCGAN_CHAOS map ({"1": {...}} keyed by
# MH_PID), so every scenario proves a LOCAL fault becoming a GLOBAL,
# deterministic decision.

# cheapest multi-host scenario, pinned into tier-1 (tests/test_tools.py)
MH_SMOKE_SCENARIOS = ("mh-sigterm-stop",)

_MH_DRIVER = """
import json, os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")
import jax
from dcgan_tpu.testing.multihost import configure_cpu_multiprocess
configure_cpu_multiprocess(jax)
jax.distributed.initialize(
    coordinator_address=os.environ["MH_COORD"],
    num_processes=int(os.environ["MH_NPROC"]),
    process_id=int(os.environ["MH_PID"]))
import numpy as np
from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.train.trainer import train
base = dict(batch_size=8, tensorboard=False, sample_every_steps=0,
            activation_summary_steps=0, save_summaries_secs=1e9,
            log_every_steps=1, save_model_steps=10_000)
base.update(json.loads(os.environ["MH_EXTRA"]))  # scenario overrides WIN
cfg = TrainConfig(model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                                    compute_dtype="float32"),
                  **base)
state = train(cfg, synthetic_data=True,
              max_steps=int(os.environ["MH_MAX_STEPS"]))
total = sum(float(np.abs(np.asarray(jax.device_get(leaf),
                                    np.float64)).sum())
            for leaf in jax.tree_util.tree_leaves(state["params"]))
print("STATE_SUM=%.9e" % total, flush=True)
print("TRAIN_DONE step=%d" % int(jax.device_get(state["step"])), flush=True)
"""


def _free_port() -> int:
    from dcgan_tpu.testing.multihost import free_port

    return free_port()


def _run_mh_train(extra: dict, *, max_steps: int, chaos: dict = None,
                  nproc: int = 2, timeout: int = 600,
                  extra_per_pid: dict = None, env_common: dict = None):
    """One 2-process trainer job; returns [(rc, output) per process].

    `chaos` may be a flat FaultPlan dict (armed on every process) or a
    per-process map like {"1": {...}} (armed on that MH_PID only).
    `extra_per_pid` ({pid: {config overrides}}) layers per-process config
    on top of `extra` — only for knobs that are legitimately per-process
    (watchdog deadlines); anything steering collectives must stay common.
    `env_common` adds environment variables to EVERY process (the
    protocol-replay scenario arms DCGAN_PROTOCOL_LOG this way)."""
    port = _free_port()
    procs = []
    for pid in range(nproc):
        cfg_extra = dict(extra, **(extra_per_pid or {}).get(pid, {}))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   MH_COORD=f"127.0.0.1:{port}", MH_NPROC=str(nproc),
                   MH_PID=str(pid), MH_EXTRA=json.dumps(cfg_extra),
                   MH_MAX_STEPS=str(max_steps))
        env.pop("DCGAN_CHAOS", None)
        env.pop("JAX_COORDINATOR_ADDRESS", None)
        env.pop("DCGAN_PROTOCOL_LOG", None)
        if env_common:
            env.update(env_common)
        if chaos:
            env["DCGAN_CHAOS"] = json.dumps(chaos)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _MH_DRIVER], cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            results.append((p.returncode, out))
    except subprocess.TimeoutExpired:
        raise Failure(
            f"multihost job hung past {timeout}s — the exact failure the "
            "watchdog exists to prevent")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return results


def scenario_mh_nan_rollback(root: str) -> dict:
    """NaN visible on process 1's gate only -> the allgathered verdict makes
    BOTH hosts roll back to the same sharded device-resident snapshot; the
    job completes and both hosts end bit-identical."""
    results = _run_mh_train(
        dict(checkpoint_dir=os.path.join(root, "ck"),
             sample_dir=os.path.join(root, "sm"),
             nan_policy="rollback", nan_check_steps=1,
             rollback_snapshot_steps=2, max_rollbacks=2),
        max_steps=6, chaos={"1": {"nan_at_step": 3}})
    for pid, (rc, out) in enumerate(results):
        _check(rc == 0, f"process {pid} failed (rc={rc}): {out[-800:]}")
        _check("TRAIN_DONE step=6" in out,
               f"process {pid} did not complete: {out[-400:]}")
    chief_out = results[0][1]
    _check("rolling back to last-good snapshot at step 2" in chief_out,
           f"no rollback message on chief: {chief_out[-800:]}")
    _check("process(es) [1]" in chief_out,
           f"consensus did not attribute the trip to process 1: "
           f"{chief_out[-800:]}")
    sums = [next(line for line in out.splitlines()
                 if line.startswith("STATE_SUM=")) for _, out in results]
    _check(len(set(sums)) == 1,
           f"post-restore states diverged across hosts: {sums}")
    return {"rollbacks": 1, "final_step": 6, "state_sum": sums[0]}


def scenario_mh_sigterm_stop(root: str) -> dict:
    """SIGTERM on host 1 only -> the stop consensus breaks both hosts at
    the same boundary, the collective final save lands, and a fresh job
    restores it bit-exact.

    Protocol replay (ISSUE 14): phase A runs with DCGAN_PROTOCOL_LOG
    armed, so every real stop-consensus allgather logs its logical op;
    both processes' logged sequences must be identical AND equal to the
    committed simulator schedule for this exact scenario
    (analysis/protocol.lock.jsonl, drill-defaults/sigterm@p1@3) — the
    proof the simulated trainer mirror and the live trainer issue the
    same collective stream."""
    common = dict(checkpoint_dir=os.path.join(root, "ck"),
                  sample_dir=os.path.join(root, "sm"))
    sched = os.path.join(root, "sched.log")
    results = _run_mh_train(common, max_steps=6,
                            chaos={"1": {"sigterm_at_step": 3}},
                            env_common={"DCGAN_PROTOCOL_LOG": sched})
    for pid, (rc, out) in enumerate(results):
        _check(rc == 0, f"process {pid} failed (rc={rc}): {out[-800:]}")
        _check("TRAIN_DONE step=3" in out,
               f"process {pid} did not stop at step 3: {out[-400:]}")
    chief_out = results[0][1]
    _check("received signal" in chief_out
           and "on process(es) [1]" in chief_out,
           f"chief did not log the coordinated stop: {chief_out[-800:]}")
    _check(os.path.isdir(os.path.join(root, "ck", "3")),
           "no collective final checkpoint at the stop step")
    saved_sum = next(line for line in chief_out.splitlines()
                     if line.startswith("STATE_SUM="))

    # replay: live collective sequence == committed simulator schedule
    from dcgan_tpu.analysis import protocol as protocol_lib

    logs = []
    for pid in range(2):
        path = f"{sched}.{pid}"
        _check(os.path.exists(path),
               f"process {pid} logged no collective sequence at {path}")
        with open(path, encoding="utf-8") as f:
            logs.append([ln.strip() for ln in f if ln.strip()])
    _check(logs[0] == logs[1],
           f"per-process collective logs diverged: {logs[0]} vs {logs[1]}")
    expected = protocol_lib.drill_replay_ops()
    _check(logs[0] == expected,
           f"live collective sequence {logs[0]} != the committed "
           f"simulator schedule {expected} — the trainer's boundary "
           "protocol and analysis/simulate.py's mirror drifted apart")

    # phase B: resume lands exactly on the stop step -> the printed state
    # is the restored checkpoint, byte-for-byte the state phase A saved
    results = _run_mh_train(common, max_steps=3)
    for pid, (rc, out) in enumerate(results):
        _check(rc == 0, f"resume process {pid} failed (rc={rc}): "
                        f"{out[-800:]}")
        _check("TRAIN_DONE step=3" in out,
               f"resume process {pid} wrong step: {out[-400:]}")
    _check("restored checkpoint at step 3" in results[0][1],
           f"resume did not restore the stop checkpoint: "
           f"{results[0][1][-800:]}")
    restored_sum = next(line for line in results[0][1].splitlines()
                        if line.startswith("STATE_SUM="))
    _check(restored_sum == saved_sum,
           f"resume is not bit-exact: saved {saved_sum}, restored "
           f"{restored_sum}")
    return {"stopped_at": 3, "resumed": True, "state_sum": saved_sum,
            "replayed_collectives": len(logs[0])}


def scenario_mh_watchdog(root: str) -> dict:
    """Process 1 goes silent inside a collective window -> process 0's
    watchdog trips while BLOCKED in the collective process 1 never joined:
    diagnostic header (phase + step), all-thread stack dump, exit 43. The
    whole job then dies fast — once one process is gone, jax's own
    coordination client reaps the others with a fatal error — instead of
    the pre-watchdog outcome: every host wedged in a dead collective until
    an operator notices.

    Staggered deadlines (8 s on the blocked process, 20 s on the hung one)
    make the trip order deterministic: the blocked process — the
    interesting one, proving the watchdog fires DURING a dead collective,
    not just during a Python-level sleep — always trips first."""
    results = _run_mh_train(
        dict(checkpoint_dir=os.path.join(root, "ck"),
             sample_dir=os.path.join(root, "sm"),
             collective_timeout_secs=8.0),
        max_steps=8, chaos={"1": {"hang_at_step": 3, "hang_secs": 300}},
        extra_per_pid={1: dict(collective_timeout_secs=20.0)},
        timeout=180)
    for pid, (rc, out) in enumerate(results):
        _check(rc != 0, f"process {pid} exited 0 despite the hang")
        _check("TRAIN_DONE" not in out,
               f"process {pid} claimed completion: {out[-400:]}")
    rc0, out0 = results[0]
    # the Python watchdog thread prints the full diagnostic header and
    # exits 43; the GIL-immune faulthandler backstop prints "Timeout
    # (...)!" and exits 1 — either way process 0 dies WITH a stack dump
    # while blocked, never hangs
    _check("hung-collective watchdog" in out0 or "Timeout (" in out0,
           f"blocked process 0 missing watchdog diagnostic: {out0[-800:]}")
    _check("Thread" in out0 or "Current thread" in out0,
           f"blocked process 0 missing stack dump: {out0[-800:]}")
    _check(rc0 in (43, 1),
           f"process 0 died by something other than the watchdog "
           f"(rc={rc0}): {out0[-800:]}")
    if rc0 == 43:
        _check("step-dispatch" in out0 or "stop-consensus" in out0
               or "collective-save" in out0,
               f"watchdog header does not name the blocked phase: "
               f"{out0[-800:]}")
        # ISSUE 6: the Python-watchdog trip path (not the GIL-immune
        # C backstop, which cannot run Python) also ships the chief's
        # flight-recorder ring
        from dcgan_tpu.train.flight_recorder import read_dump

        dump = os.path.join(root, "ck", "flight_recorder.jsonl")
        _check(os.path.exists(dump),
               "no flight-recorder dump on the blocked chief")
        header, _ = read_dump(dump)
        _check(header["reason"] == "watchdog",
               f"dump header misattributes the trip: {header}")
    return {"exit_codes": [rc for rc, _ in results],
            "watchdog_rc": rc0}


MH_SCENARIOS = {
    "mh-nan-rollback": scenario_mh_nan_rollback,
    "mh-sigterm-stop": scenario_mh_sigterm_stop,
    "mh-watchdog": scenario_mh_watchdog,
}


# -- elastic-topology scenarios (ISSUE 12) -----------------------------------
#
# A checkpoint saved on one topology resumes on another THROUGH the
# sharding sidecar + rule-engine reshard (utils/checkpoint.py,
# dcgan_tpu/elastic/). Both directions pin the strongest contract
# available on CPU: the shrink/grow pair keeps the MESH identical (2-way
# "data" axis) and changes only the process census (2 proc x 1 dev <->
# 1 proc x 2 dev), so the compiled SPMD programs — and therefore the
# post-resume losses — replay against a same-topology control resume of
# the same checkpoint to within reduction-order noise: the HLO is
# identical, but the cross-PROCESS collective implementation may reduce
# partials in a different order than the intra-process one, so individual
# reduced scalars (a logged loss, the host-side param sum) can differ in
# the last ulp — the diffs below use ulp-scale relative tolerances, not
# text equality, and any REAL divergence (wrong batch, wrong shard, wrong
# step) is orders of magnitude beyond them. `synthetic_global_stream`
# makes the data stream layout-invariant (every process draws the full
# global batch and cuts its block), which is what makes the comparison
# meaningful at all. The scenarios live in the single-process matrix:
# each orchestrates its own 2-process phases.

#: knobs common to every elastic arm — scalar rows every step (the loss
#: replay is diffed from events.jsonl), no periodic saves (one final save
#: per phase), layout-invariant synthetic stream
_ELASTIC_KNOBS = dict(save_summaries_secs=0.0, save_model_secs=1e9,
                      save_model_steps=10_000, activation_summary_steps=0,
                      synthetic_global_stream=True)

#: a single process with TWO virtual CPU devices — the other layout of
#: the same 2-way data mesh the 2-process phases train on (full replace,
#: not append: the ambient test env may pin 8 devices). Partitionable
#: threefry matches the multihost workers' standard, so the two layouts
#: draw identical random streams (the bit-exact replay rides on it).
_TWO_DEV_ENV = {"XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "DRILL_THREEFRY_PARTITIONABLE": "1"}


def _loss_rows(events) -> dict:
    """{step: (d_loss, g_loss)} from scalar rows — the replay record."""
    return {e["step"]: (e["values"]["d_loss"], e["values"]["g_loss"])
            for e in events
            if e["kind"] == "scalars" and "d_loss" in e["values"]}


def _elastic_scenario(root: str, *, shrink: bool) -> dict:
    """Save at 3 steps on the source topology, then resume to step 6 twice
    from clones of that checkpoint: once on the OTHER process layout
    (cross arm — must reshard through the sidecar's host-staged path) and
    once on the saving layout (control arm — sidecar present, reshard
    path NOT taken). Post-resume losses and final STATE_SUM must match
    bit-exactly; elastic/* keys must appear in the cross arm's events
    and nowhere in the control's."""
    from dcgan_tpu.testing.chaos import clone_checkpoint_dir

    ck = os.path.join(root, "ck")
    name = "shrink" if shrink else "grow"

    def run_two_proc(ckpt_dir, max_steps):
        results = _run_mh_train(
            dict(checkpoint_dir=ckpt_dir,
                 sample_dir=os.path.join(root, "sm"), **_ELASTIC_KNOBS),
            max_steps=max_steps)
        for pid, (rc, out) in enumerate(results):
            _check(rc == 0, f"{name}: 2-proc process {pid} failed "
                            f"(rc={rc}): {out[-800:]}")
            _check(f"TRAIN_DONE step={max_steps}" in out,
                   f"{name}: 2-proc process {pid} did not reach step "
                   f"{max_steps}: {out[-400:]}")
        return results[0][1]  # the chief's output (it logs and writes)

    def run_one_proc(ckpt_dir, max_steps):
        rc, out = _run_train(
            dict(checkpoint_dir=ckpt_dir,
                 sample_dir=os.path.join(root, "sm"), **_ELASTIC_KNOBS),
            max_steps=max_steps, env_extra=_TWO_DEV_ENV)
        _check(rc == 0,
               f"{name}: 1-proc trainer failed (rc={rc}): {out[-800:]}")
        _check(f"TRAIN_DONE step={max_steps}" in out,
               f"{name}: 1-proc run did not reach step {max_steps}: "
               f"{out[-400:]}")
        return out

    save, resume_cross = (run_two_proc, run_one_proc) if shrink \
        else (run_one_proc, run_two_proc)

    # phase A: train 3 steps on the source topology; the final forced
    # save carries the sharding sidecar
    save(ck, 3)
    _check(os.path.exists(os.path.join(ck, "integrity",
                                       "3.sharding.json")),
           f"{name}: no sharding sidecar beside the step-3 manifest")
    ck_cross = clone_checkpoint_dir(ck, os.path.join(root, "ck-cross"))
    ck_ctrl = clone_checkpoint_dir(ck, os.path.join(root, "ck-control"))

    # cross arm: the OTHER process layout of the same 2-way data mesh —
    # the process census changed, so the reshard must take the
    # host-staged path
    out_cross = resume_cross(ck_cross, 6)
    _check("cross-topology restore of step 3" in out_cross,
           f"{name}: resume did not take the reshard path: "
           f"{out_cross[-800:]}")
    _check("host-staged path" in out_cross,
           f"{name}: process-count change did not use the host-staged "
           f"reshard: {out_cross[-800:]}")
    _check("restored checkpoint at step 3" in out_cross,
           f"{name}: cross arm did not restore step 3: {out_cross[-800:]}")

    # control arm: the saving layout — sidecar present, reshard NOT taken
    out_ctrl = save(ck_ctrl, 6)
    _check("cross-topology restore" not in out_ctrl,
           f"{name}: same-topology control unexpectedly resharded: "
           f"{out_ctrl[-800:]}")
    _check("restored checkpoint at step 3" in out_ctrl,
           f"{name}: control arm did not restore step 3: "
           f"{out_ctrl[-800:]}")

    # loss replay: the same mesh ran the same programs over the same
    # (layout-invariant) batches — losses must agree to ulp scale. Not
    # text-exact: a loss reduced across PROCESSES (the 2-proc arm) may sum
    # partials in a different order than the intra-process all-reduce, and
    # float addition does not associate, so single-ulp diffs in a logged
    # scalar are legitimate (observed: g_loss, one ulp, grow direction).
    # 1e-6 relative is ~10 ulps of float32 — far above that noise, far
    # below any real divergence (wrong batch/shard/step shifts losses at
    # the 1e-2 scale here).
    lx, lc = _loss_rows(_events(ck_cross)), _loss_rows(_events(ck_ctrl))
    for s in (4, 5, 6):
        _check(s in lx and s in lc,
               f"{name}: missing step-{s} loss row (cross has "
               f"{sorted(lx)}, control {sorted(lc)})")
        _check(all(abs(a - b) <= 1e-6 * max(abs(a), abs(b), 1e-3)
                   for a, b in zip(lx[s], lc[s])),
               f"{name}: step-{s} losses diverged across topologies: "
               f"cross {lx[s]} != control {lc[s]}")
    # final params, same root cause wider window: the driver's host-side
    # STATE_SUM accumulates ~75 gathered leaves whose low-bit history
    # includes every boundary-order difference of the run, so it gets a
    # looser (still tiny) tolerance; 5e-4 is ~100x the observed drift and
    # far below any real state divergence.
    sum_cross = _state_sum_value(out_cross)
    sum_ctrl = _state_sum_value(out_ctrl)
    rel = abs(sum_cross - sum_ctrl) / max(abs(sum_ctrl), 1e-30)
    _check(rel <= 5e-4,
           f"{name}: post-resume states diverged beyond reduction-order "
           f"noise: {sum_cross!r} vs {sum_ctrl!r} (rel={rel:.2e})")

    # key gating: the reshard event surfaces elastic/*; the control stream
    # stays byte-identical in KEY SET to a pre-elastic resume
    cross_elastic = [e for e in _events(ck_cross) if e["kind"] == "scalars"
                     and "elastic/resharded" in e["values"]]
    ctrl_elastic = [e for e in _events(ck_ctrl) if e["kind"] == "scalars"
                    and any(k.startswith("elastic/") for k in e["values"])]
    _check(cross_elastic, f"{name}: no elastic/* event row in the cross "
                          "arm's stream")
    _check(not ctrl_elastic, f"{name}: elastic/* keys leaked into the "
                             f"same-topology control: {ctrl_elastic[:1]}")
    row = cross_elastic[-1]["values"]
    _check(row["elastic/host_stage"] == 1.0,
           f"{name}: elastic row does not record the host-staged path: "
           f"{row}")
    return {"direction": "2proc->1proc" if shrink else "1proc->2proc",
            "final_step": 6, "replay_within_tolerance": True,
            "state_sum_rel": rel,
            "reshard_ms": round(row["perf/restore/reshard_ms"], 1),
            "state_sum": sum_cross}


def scenario_elastic_shrink(root: str) -> dict:
    """2-process save -> 1-process (2-device) resume: the preemptible-
    fleet shrink. Ulp-tolerance loss replay vs a 2-process control
    resume."""
    return _elastic_scenario(root, shrink=True)


def scenario_elastic_grow(root: str) -> dict:
    """1-process (2-device) save -> 2-process resume: scale back out after
    a degraded period. Ulp-tolerance loss replay vs a 1-process control."""
    return _elastic_scenario(root, shrink=False)


SCENARIOS["elastic-shrink"] = scenario_elastic_shrink
SCENARIOS["elastic-grow"] = scenario_elastic_grow


# -- live in-run elasticity (ISSUE 18, dcgan_tpu/elastic/live.py) ------------
#
# No restart in these drills: ONE trainer process with two virtual devices
# receives a chaos preemption notice mid-run and switches its live mesh
# (t2x1 -> t1x1, and back on a grow notice) at a step boundary. The
# contract stack, strongest first:
#   1. pre-notice losses replay BIT-EXACTLY against an armed-but-unnotified
#      control (same config, no fault) — arming elasticity is free;
#   2. the switch dispatches only warmup-cached executables:
#      compile_requests_delta=0 printed on the switch line (a persistent
#      compile cache is configured so the delta is measured, not assumed);
#   3. post-switch the run COMPLETES, and the final params stay within the
#      same reduction-order tolerance as the restart-based arms above —
#      a 1-device and a 2-device data axis reduce the global batch in
#      different orders, so post-switch trajectories are near, not equal
#      (the state MOVE itself is bit-lossless — pinned in-process by
#      tests/test_live_elastic.py, where both sides are observable);
#   4. elastic/live_* event keys appear ONLY in the notified run.

#: the live-elastic arm's extra knobs: elasticity armed at 1 device,
#: AOT warmup on (the switch contract is warm-both-topologies), metrics
#: every step for the loss diff
def _live_knobs(root: str, ck: str) -> dict:
    return dict(checkpoint_dir=ck, sample_dir=os.path.join(root, "sm"),
                compile_cache_dir=os.path.join(root, "cache"),
                elastic_target_devices=1, aot_warmup=True,
                **_ELASTIC_KNOBS)


def _run_live(root: str, ck: str, *, chaos: dict = None):
    rc, out = _run_train(_live_knobs(root, ck), max_steps=6, chaos=chaos,
                         env_extra=_TWO_DEV_ENV)
    _check(rc == 0, f"live trainer failed (rc={rc}): {out[-800:]}")
    _check("TRAIN_DONE step=6" in out,
           f"live run did not reach step 6: {out[-400:]}")
    _check("live-elastic warmup primed" in out,
           f"live run did not prime both topologies: {out[-800:]}")
    return out


def _switch_line(out: str, step: int, arrow: str) -> str:
    want = f"live elastic switch at step {step}: {arrow}"
    line = next((ln for ln in out.splitlines() if want in ln), None)
    _check(line is not None,
           f"no '{want}' line in output: {out[-800:]}")
    _check("compile_requests_delta=0" in line,
           f"switch at step {step} compiled something: {line}")
    return line


def _live_compare(name: str, ck_fault: str, ck_ctrl: str,
                  out_fault: str, out_ctrl: str) -> float:
    lf, lc = _loss_rows(_events(ck_fault)), _loss_rows(_events(ck_ctrl))
    for s in (1, 2, 3):
        _check(s in lf and s in lc,
               f"{name}: missing step-{s} loss row (fault has "
               f"{sorted(lf)}, control {sorted(lc)})")
        _check(lf[s] == lc[s],
               f"{name}: PRE-notice step-{s} losses diverged — arming "
               f"elasticity must be free: {lf[s]} != {lc[s]}")
    sum_f, sum_c = _state_sum_value(out_fault), _state_sum_value(out_ctrl)
    rel = abs(sum_f - sum_c) / max(abs(sum_c), 1e-30)
    _check(rel <= 5e-4,
           f"{name}: post-switch state outside reduction-order tolerance: "
           f"{sum_f!r} vs {sum_c!r} (rel={rel:.2e})")
    live_rows = [e for e in _events(ck_fault) if e["kind"] == "scalars"
                 and "elastic/live_switch_ms" in e["values"]]
    _check(live_rows, f"{name}: no elastic/live_* event row in the "
                      "notified run's stream")
    ctrl_rows = [e for e in _events(ck_ctrl) if e["kind"] == "scalars"
                 and any(k.startswith("elastic/live_")
                         for k in e["values"])]
    _check(not ctrl_rows, f"{name}: elastic/live_* keys leaked into the "
                          f"unnotified control: {ctrl_rows[:1]}")
    return rel


def scenario_live_notice_shrink(root: str) -> dict:
    """Chaos preemption notice at step 3 -> live t2x1 -> t1x1 switch, no
    restart; completes to step 6 with zero compile requests across the
    switch, vs an armed-but-unnotified control."""
    out_ctrl = _run_live(root, os.path.join(root, "ck-control"))
    _check("live elastic switch" not in out_ctrl,
           f"control switched without a notice: {out_ctrl[-800:]}")
    ck = os.path.join(root, "ck")
    out = _run_live(root, ck, chaos={"preempt_notice_at_step": 3})
    _switch_line(out, 3, "t2x1 -> t1x1")
    rel = _live_compare("notice-shrink", ck,
                        os.path.join(root, "ck-control"), out, out_ctrl)
    row = [e for e in _events(ck) if e["kind"] == "scalars"
           and "elastic/live_switch_ms" in e["values"]][-1]["values"]
    _check(row["elastic/live_target_mesh"] == 1.0,
           f"live event row does not record the 1-device target: {row}")
    return {"final_step": 6, "compile_requests_delta": 0,
            "switch_ms": round(row["elastic/live_switch_ms"], 1),
            "state_sum_rel": rel}


def scenario_live_grow_back(root: str) -> dict:
    """Shrink notice at step 3 + grow notice at step 5: t2x1 -> t1x1 ->
    t2x1 in one uninterrupted run, both switches compile-free. The t1x1
    leg (steps 4-5) must replay BIT-EXACTLY against a shrink-only run —
    the grow-back surface was warmed at startup, and being ABLE to grow
    must not perturb the shrunken trajectory."""
    out_ctrl = _run_live(root, os.path.join(root, "ck-control"))
    ck_s = os.path.join(root, "ck-shrink")
    out_s = _run_live(root, ck_s, chaos={"preempt_notice_at_step": 3})
    ck = os.path.join(root, "ck")
    out = _run_live(root, ck, chaos={"preempt_notice_at_step": 3,
                                     "grow_notice_at_step": 5})
    _switch_line(out, 3, "t2x1 -> t1x1")
    _switch_line(out, 5, "t1x1 -> t2x1")
    rel = _live_compare("grow-back", ck, os.path.join(root, "ck-control"),
                        out, out_ctrl)
    lg, ls = _loss_rows(_events(ck)), _loss_rows(_events(ck_s))
    for s in (4, 5):
        _check(s in lg and s in ls,
               f"grow-back: missing step-{s} loss row (grow has "
               f"{sorted(lg)}, shrink-only {sorted(ls)})")
        _check(lg[s] == ls[s],
               f"grow-back: shrunken-leg step-{s} losses diverged from "
               f"the shrink-only run: {lg[s]} != {ls[s]}")
    return {"final_step": 6, "switches": 2, "compile_requests_delta": 0,
            "shrunken_leg_bit_exact": True, "state_sum_rel": rel}


SCENARIOS["notice-shrink"] = scenario_live_notice_shrink
SCENARIOS["grow-back"] = scenario_live_grow_back


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="chaos_drill",
        description="fault-injection scenario matrix for the trainer's "
                    "fail-operational layer (CPU)")
    p.add_argument("--smoke", action="store_true",
                   help=f"CI subset: {', '.join(SMOKE_SCENARIOS)} "
                        f"(with --multihost: "
                        f"{', '.join(MH_SMOKE_SCENARIOS)})")
    p.add_argument("--multihost", action="store_true",
                   help="run the 2-process coordinated-recovery matrix "
                        f"({', '.join(sorted(MH_SCENARIOS))}) instead of "
                        "the single-process one")
    p.add_argument("--only", nargs="+",
                   choices=sorted(SCENARIOS) + sorted(MH_SCENARIOS),
                   default=None, help="run just these scenarios")
    args = p.parse_args(argv)
    table = MH_SCENARIOS if args.multihost else SCENARIOS
    smoke = MH_SMOKE_SCENARIOS if args.multihost else SMOKE_SCENARIOS
    if args.only:
        bad = [n for n in args.only if n not in table]
        if bad:
            p.error(f"scenario(s) {bad} are not in the "
                    f"{'multihost' if args.multihost else 'single-process'} "
                    f"matrix; choose from {sorted(table)}")
        names = args.only
    else:
        names = smoke if args.smoke else sorted(table)
    failures = 0
    for name in names:
        with tempfile.TemporaryDirectory(prefix=f"chaos_{name}_") as root:
            row = {"scenario": name}
            try:
                row.update(table[name](root))
                row["ok"] = True
            except Failure as e:
                row.update(ok=False, error=str(e))
                failures += 1
            print(json.dumps(row), flush=True)
    print(json.dumps({"label": "chaos-drill-multihost" if args.multihost
                      else "chaos-drill", "scenarios": len(names),
                      "failed": failures}), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
