"""Measure the REAL trainer hot loop at bench throughput (VERDICT r3 #4).

Every chip img/s number in the captures table comes from bench.py's scanned
harness; the trainer's equivalent path (`--steps_per_call`,
train/trainer.py) was equivalence-tested on CPU but never captured on the
chip — leaving a "the fast path exists only in the benchmark" doubt. This
tool runs the actual `python -m dcgan_tpu.train` entry (synthetic stream so
the tunnel's host->device bandwidth is not what gets measured — that regime
is bench_realdata.py's row) with the same scan width bench.py uses, and
derives steady-state throughput from the trainer's own stdout step log
(each logged line follows a float() metric sync, so its timestamp is a true
device-progress point, not a dispatch-queue artifact).

Observability cadences are left at measurement-friendly values (no sample
grids, no activation summaries, no TensorBoard histogram pulls) — those
paths carry host transfers that measure the tunnel; their cost on a real
host is the trainer's documented per-cadence overhead, not loop speed.

Prints one JSON line:
  {"label": "trainer-loop", "images_per_sec_chip": R, "window_steps": [a,b],
   "ms_per_step": t, ...}

TRAINER_BENCH_OCCUPANCY=1 switches to the host-services A/B mode (ISSUE 2):
the same trainer runs twice — --async_services=true then =false — with
per-step logging and frequent summary ticks enabled (the observability
regime the async layer exists for), and the row reports each run's
perf/dispatch_occupancy and perf/step_ms_mean from its own metrics JSONL,
so the dispatch-thread overlap win is a recorded number, not a claim:
  {"label": "trainer-loop-occupancy",
   "services_on":  {"dispatch_occupancy": ..., "step_ms_mean": ...},
   "services_off": {"dispatch_occupancy": ..., "step_ms_mean": ...}, ...}

TRAINER_BENCH_PIPELINE=1 switches to the pipelined-G/D A/B mode (ISSUE 7):
the same trainer runs twice — --pipeline_gd=false then =true — with a
mid-run trace window each, and the row reports both arms' recorded
perf/device/{step_ms,idle_gap_ms} digests and host occupancy (see
_pipeline_mode).

Workload anchor: the hot loop being replaced, image_train.py:147-194.
"""

from __future__ import annotations

import json
import os
import re
import shlex
import subprocess
import sys
import tempfile

MAX_STEPS = int(os.environ.get("TRAINER_BENCH_STEPS", 5000))
SCAN = int(os.environ.get("TRAINER_BENCH_SCAN", 50))
# first sync point at/after this step starts the measurement window,
# excluding compile + the first dispatches' pipeline fill
WARMUP_STEPS = int(os.environ.get("TRAINER_BENCH_WARMUP", 1000))

LOG_RE = re.compile(r"\[dcgan_tpu\] epoch \d+ step (\d+) time ([0-9.]+)s")


def _occupancy_mode() -> None:
    """A/B the async host-services layer under an observability-heavy
    regime and report recorded dispatch-thread occupancy for both arms."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    steps = int(os.environ.get("TRAINER_BENCH_STEPS", 300))
    batch = os.environ.get("BENCH_BATCH", "64")
    row = {"label": "trainer-loop-occupancy", "batch": int(batch),
           "total_steps": steps}
    for arm, async_flag in (("services_on", "true"),
                            ("services_off", "false")):
        with tempfile.TemporaryDirectory() as tmp:
            ckpt = os.path.join(tmp, "ckpt")
            argv = [
                sys.executable, "-m", "dcgan_tpu.train",
                "--synthetic",
                "--synthetic_device_cache",
                os.environ.get("TRAINER_BENCH_CACHE", "8"),
                "--max_steps", str(steps),
                "--batch_size", batch,
                "--async_services", async_flag,
                # the observability regime the async layer targets:
                # per-step logging (the reference's contract) + a summary
                # tick (scalars AND full param histograms) every ~2 s
                "--log_every_steps",
                os.environ.get("TRAINER_BENCH_LOG", "1"),
                "--nan_check_steps", "100",
                "--save_summaries_secs",
                os.environ.get("TRAINER_BENCH_SUMMARY_SECS", "2"),
                "--sample_every_steps", "0",
                "--activation_summary_steps", "0",
                "--save_model_secs", "1e9",
                "--no_tensorboard",
                "--checkpoint_dir", ckpt,
                "--sample_dir", os.path.join(tmp, "samples"),
            ]
            res = subprocess.run(
                argv, cwd=repo, capture_output=True, text=True,
                timeout=float(os.environ.get("TRAINER_BENCH_TIMEOUT", 900)))
            if res.returncode != 0:
                print(json.dumps({**row, "error":
                                  f"{arm} trainer rc={res.returncode}",
                                  "stderr_tail": (res.stderr or "")[-300:]}))
                sys.exit(1)
            # last perf summary of the run = steady state (the sliding
            # window has long since shed warmup/compile iterations)
            perf = None
            with open(os.path.join(ckpt, "events.jsonl")) as f:
                for line in f:
                    e = json.loads(line)
                    if e["kind"] == "scalars" and \
                            "perf/dispatch_occupancy" in e["values"]:
                        perf = e["values"]
            if perf is None:
                print(json.dumps({**row, "error":
                                  f"{arm}: no perf scalars in events.jsonl"}))
                sys.exit(1)
            row[arm] = {
                "dispatch_occupancy":
                    round(perf["perf/dispatch_occupancy"], 4),
                "host_ms_mean": round(perf["perf/host_ms_mean"], 3),
                "step_ms_mean": round(perf["perf/step_ms_mean"], 2),
                "images_per_sec": round(perf.get("perf/images_per_sec", 0.0),
                                        1),
            }
    print(json.dumps(row))


def _pipeline_run(repo: str, flag: str, *, steps: int, trace_steps: int,
                  batch: str) -> dict:
    """One A/B arm: a trainer subprocess with a mid-run scheduled trace
    window, returning the arm's recorded perf + device-digest fields."""
    with tempfile.TemporaryDirectory() as tmp:
        ckpt = os.path.join(tmp, "ckpt")
        argv = [
            sys.executable, "-m", "dcgan_tpu.train",
            "--synthetic",
            "--synthetic_device_cache",
            os.environ.get("TRAINER_BENCH_CACHE", "8"),
            "--max_steps", str(steps),
            "--batch_size", batch,
            "--pipeline_gd", flag,
            # the pipelined mode's dispatch shape; the fused arm runs
            # the same so the A/B isolates the stage split, not scan
            # amortization (that regime is the main trainer-loop row)
            "--steps_per_call", "1",
            # value syncs OUT of the trace window (cadences past
            # max_steps): the window measures the steady dispatch stream,
            # not readback stalls — which hit both arms but add variance
            "--log_every_steps",
            os.environ.get("TRAINER_BENCH_LOG", str(steps * 2)),
            "--nan_check_steps", str(steps * 2),
            # one summary tick fires immediately (warmup) and the next
            # lands near end-of-run — the last perf row is steady-state
            # and the mid-run window stays summary-free on CPU smoke
            # timings; the median-of-reps absorbs a straggler tick
            "--save_summaries_secs",
            os.environ.get("TRAINER_BENCH_SUMMARY_SECS", "4"),
            "--sample_every_steps", "0",
            "--activation_summary_steps", "0",
            "--save_model_secs", "1e9",
            "--no_tensorboard",
            # mid-run scheduled window: past compile, the fill, and the
            # occupancy-timer warmup
            "--profile_dir", os.path.join(tmp, "trace"),
            "--profile_start_step", str(max(1, steps // 2)),
            "--profile_num_steps", str(trace_steps),
            "--checkpoint_dir", ckpt,
            "--sample_dir", os.path.join(tmp, "samples"),
        ]
        # extra trainer flags for smoke runs (e.g. a tiny model:
        # "--output_size 16 --gf_dim 8 --df_dim 8" — the flagship
        # 64x64 model runs ~10 s/step on a CPU test host)
        argv += shlex.split(os.environ.get("TRAINER_BENCH_EXTRA", ""))
        res = subprocess.run(
            argv, cwd=repo, capture_output=True, text=True,
            timeout=float(os.environ.get("TRAINER_BENCH_TIMEOUT", 900)))
        if res.returncode != 0:
            raise RuntimeError(f"trainer rc={res.returncode}: "
                               f"{(res.stderr or '')[-300:]}")
        perf, device = None, None
        with open(os.path.join(ckpt, "events.jsonl")) as f:
            for line in f:
                e = json.loads(line)
                if e["kind"] != "scalars":
                    continue
                if "perf/dispatch_occupancy" in e["values"]:
                    perf = e["values"]
                if "perf/device/step_ms" in e["values"]:
                    device = e["values"]
        if perf is None or device is None:
            raise RuntimeError(
                f"no {'perf' if perf is None else 'device'} scalars "
                "in events.jsonl")
        span = device["perf/device/span_ms"]
        return {
            "devstep_ms": device["perf/device/step_ms"],
            "compute_ms": device["perf/device/compute_ms"],
            "idle_gap_ms": device["perf/device/idle_gap_ms"],
            "span_ms": span,
            # the share of the captured window the device sat between
            # dispatches — THE number the pipeline exists to shrink
            "idle_share": (device["perf/device/idle_gap_ms"] / span
                           if span > 0 else None),
            "step_ms_mean": perf["perf/step_ms_mean"],
            "images_per_sec": perf.get("perf/images_per_sec", 0.0),
            "dispatch_occupancy": perf["perf/dispatch_occupancy"],
        }


def _pipeline_mode() -> None:
    """A/B the pipelined G/D dispatch (ISSUE 7) against the fused step.

    TRAINER_BENCH_REPS (default 3) INTERLEAVED trainer-run pairs —
    --pipeline_gd=false then =true per rep, both at steps_per_call=1 (the
    pipelined mode's dispatch shape) — each run with a mid-run scheduled
    trace window. The row reports each arm's per-field MEDIAN across the
    reps (plus the per-rep idle shares for spread): on a contended CPU
    smoke host the per-window idle share swings several points run to
    run, and interleaving + medians is what makes the A/B a number
    instead of a coin flip. The fields are the trainer's OWN recorded
    perf/device/{step_ms,idle_gap_ms,compute_ms,span_ms} digest next to
    the host-side occupancy numbers — the same measurement path the
    fleet runs, not a bench-only harness. Per-step FLOPs are
    conservation-equal across the arms (tools/step_profile.py
    PIPELINE_GD=1 proves it), so the A/B is a regression guard: the
    device idle share of the window must not grow and devstep_ms must be
    no worse. NOTE: on CPU test hosts the capture falls back to the
    op-level executor thread-group track (utils/trace.py), so the device
    fields prove the path end-to-end rather than attributing real device
    time; the attributing numbers come from TPU module tracks.
      {"label": "trainer-loop-pipeline",
       "fused":     {"devstep_ms": ..., "idle_share": ..., ...},
       "pipelined": {"devstep_ms": ..., "idle_share": ..., ...},
       "idle_shares": {"fused": [...], "pipelined": [...]},
       "idle_share_delta": ...}
    """
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    steps = int(os.environ.get("TRAINER_BENCH_STEPS", 200))
    trace_steps = int(os.environ.get("TRAINER_BENCH_TRACE_STEPS", 60))
    reps = max(1, int(os.environ.get("TRAINER_BENCH_REPS", 3)))
    batch = os.environ.get("BENCH_BATCH", "64")
    row = {"label": "trainer-loop-pipeline", "batch": int(batch),
           "total_steps": steps, "reps": reps}
    samples = {"fused": [], "pipelined": []}
    for rep in range(reps):
        for arm, flag in (("fused", "false"), ("pipelined", "true")):
            try:
                samples[arm].append(_pipeline_run(
                    repo, flag, steps=steps, trace_steps=trace_steps,
                    batch=batch))
            except (RuntimeError, OSError,
                    subprocess.TimeoutExpired) as e:
                print(json.dumps({**row, "error": f"{arm} rep {rep}: {e}"}))
                sys.exit(1)

    def median(vals):
        vs = sorted(v for v in vals if v is not None)
        return vs[len(vs) // 2] if vs else None

    for arm, runs in samples.items():
        row[arm] = {k: (round(median([r[k] for r in runs]), 4)
                        if median([r[k] for r in runs]) is not None
                        else None)
                    for k in runs[0]}
    row["idle_shares"] = {
        arm: [round(r["idle_share"], 4) for r in runs
              if r["idle_share"] is not None]
        for arm, runs in samples.items()}
    f, p = row["fused"], row["pipelined"]
    if f["idle_share"] is not None and p["idle_share"] is not None:
        row["idle_share_delta"] = round(p["idle_share"] - f["idle_share"], 4)
    print(json.dumps(row))


def main() -> None:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    devstep_ms = None
    with tempfile.TemporaryDirectory() as tmp:
        trace_dir = os.path.join(tmp, "trace")
        argv = [
            sys.executable, "-m", "dcgan_tpu.train",
            "--synthetic",
            # pre-staged device batch pool: without it the synthetic feed
            # itself is host->device traffic and the row measures the
            # tunnel again (~470 img/s observed), not the loop. Set
            # TRAINER_BENCH_CACHE=0 to measure the transport regime.
            "--synthetic_device_cache",
            os.environ.get("TRAINER_BENCH_CACHE", "8"),
            "--steps_per_call", str(SCAN),
            "--max_steps", str(MAX_STEPS),
            "--batch_size", os.environ.get("BENCH_BATCH", "64"),
            # value-sync cadence 500 (log + NaN gate together): each metric
            # read over the tunneled transport costs a ~100 ms round-trip,
            # so a 100-step cadence alone would tax the loop ~1 ms/step.
            # On a directly-attached host this knob is noise.
            "--log_every_steps", "500",
            "--nan_check_steps", "500",
            "--sample_every_steps", "0",
            "--activation_summary_steps", "0",
            "--save_summaries_secs", "1e9",
            "--save_model_secs", "1e9",
            "--no_tensorboard",
            "--checkpoint_dir", os.path.join(tmp, "ckpt"),
            "--sample_dir", os.path.join(tmp, "samples"),
        ]
        if os.environ.get("TRAINER_BENCH_DEVSTEP", "1") != "0":
            # devstep_ms (ISSUE 6): one scanned call traced at the very
            # END of the run (steady state; the capture's overhead sits in
            # <=SCAN of the MAX_STEPS-step measurement window) and
            # digested through the shared parser — the BENCH row carries
            # the device's own step time next to the host-derived number
            argv += ["--profile_dir", trace_dir,
                     "--profile_start_step", str(max(0, MAX_STEPS - SCAN)),
                     "--profile_num_steps", str(SCAN)]
        res = subprocess.run(argv, cwd=repo, capture_output=True, text=True,
                             timeout=float(os.environ.get(
                                 "TRAINER_BENCH_TIMEOUT", 900)))
        if os.path.isdir(trace_dir):
            try:
                sys.path.insert(0, repo)
                from dcgan_tpu.utils.trace import devstep_ms as devstep_of

                # the captured window is one steps_per_call scan program
                devstep_ms = devstep_of(trace_dir, per_exec=SCAN)
            except Exception as e:  # noqa: BLE001 — the field is optional
                print(f"devstep digest failed: {e!r}", file=sys.stderr)
    sys.stderr.write((res.stderr or "")[-2000:])
    if res.returncode != 0:
        print(json.dumps({"label": "trainer-loop", "error":
                          f"trainer rc={res.returncode}",
                          "stderr_tail": (res.stderr or "")[-300:]}))
        sys.exit(1)

    points = [(int(m.group(1)), float(m.group(2)))
              for m in LOG_RE.finditer(res.stdout or "")]
    window = [(s, t) for s, t in points if s >= WARMUP_STEPS]
    if len(window) < 2:
        print(json.dumps({"label": "trainer-loop",
                          "error": f"only {len(points)} log points "
                          f"({len(window)} after warmup)"}))
        sys.exit(1)
    (s1, t1), (s2, t2) = window[0], window[-1]
    batch = int(os.environ.get("BENCH_BATCH", "64"))
    steps = s2 - s1
    rate = steps * batch / (t2 - t1)
    print(json.dumps({
        "label": "trainer-loop",
        "images_per_sec_chip": round(rate, 1),
        "ms_per_step": round((t2 - t1) / steps * 1e3, 2),
        "devstep_ms": round(devstep_ms, 4) if devstep_ms else None,
        "window_steps": [s1, s2],
        "batch": batch, "steps_per_call": SCAN,
        "total_steps": MAX_STEPS,
    }))
    # context for the captures log
    print(f"ms_per_step={(t2 - t1) / steps * 1e3:.2f}", file=sys.stderr)


if __name__ == "__main__":
    if os.environ.get("TRAINER_BENCH_OCCUPANCY") == "1":
        _occupancy_mode()
    elif os.environ.get("TRAINER_BENCH_PIPELINE") == "1":
        _pipeline_mode()
    else:
        main()
