"""Surrogate-FID validity experiment + FID/KID training trajectory.

VERDICT r1 #2/#8: the claim that random-feature Fréchet distance tracks true
FID's *ordering* (evals/features.py:8-13) was asserted, not evidenced. This
tool produces the evidence: train a GAN, checkpoint at increasing step
counts, score every checkpoint with the surrogate rig against the SAME data
stream, and report the trajectory. Validity = the score improves
(near-)monotonically with training — the property the north star needs
(ranking checkpoints/trainers), independent of the absolute scale Inception
features would give.

    # CPU validity run (tiny model, synthetic data, ~minutes)
    python tools/fid_trajectory.py --platform cpu --tiny \
        --snapshots 0,50,100,200,400 --num_samples 2048

    # chip run on a real preset (writes one JSON line per snapshot)
    python tools/fid_trajectory.py --preset cifar10-cond \
        --snapshots 0,500,2000,5000 --num_samples 10000

Prints one JSON line per snapshot {"step", "fid", ("kid", "kid_std")} plus a
final {"monotonic": ..., "spearman": ...} summary line. The reference has no
counterpart (its only eval was eyeballing sample grids, SURVEY.md §4).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _spearman(xs, ys) -> float:
    """Spearman rank correlation (no scipy dependency)."""
    import numpy as np

    def ranks(v):
        order = np.argsort(v)
        r = np.empty(len(v))
        r[order] = np.arange(len(v), dtype=float)
        return r

    rx, ry = ranks(np.asarray(xs)), ranks(np.asarray(ys))
    rx -= rx.mean()
    ry -= ry.mean()
    denom = float(np.sqrt((rx ** 2).sum() * (ry ** 2).sum()))
    return float((rx * ry).sum() / denom) if denom else 0.0


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="fid_trajectory")
    p.add_argument("--preset", default=None,
                   help="named config (presets.py); default = tiny/flagship")
    p.add_argument("--tiny", action="store_true",
                   help="16x16 gf=df=8 f32 model — the CPU validity config")
    p.add_argument("--arch", choices=["dcgan", "resnet", "stylegan"],
                   default="dcgan",
                   help="model family for the --tiny/default configs")
    p.add_argument("--snapshots", default="0,50,100,200,400",
                   help="comma-joined step counts to score (ascending)")
    p.add_argument("--num_samples", type=int, default=2048)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--data_dir", default=None,
                   help="TFRecord shards; default trains/scoreS on the "
                        "synthetic stream")
    p.add_argument("--kid", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None)
    p.add_argument("--out_dir", default=None,
                   help="keep checkpoints here (default: temp dir)")
    args = p.parse_args(argv)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import tempfile

    from dcgan_tpu.config import ModelConfig, TrainConfig
    from dcgan_tpu.evals.job import compute_fid
    from dcgan_tpu.parallel import make_mesh, make_parallel_train
    from dcgan_tpu.train.trainer import train

    snapshots = sorted(int(s) for s in args.snapshots.split(","))
    root = args.out_dir or tempfile.mkdtemp(prefix="fid_traj_")

    if args.preset:
        from dcgan_tpu.presets import get_preset

        base = get_preset(args.preset)
    elif args.tiny:
        base = TrainConfig(model=ModelConfig(arch=args.arch, output_size=16,
                                             gf_dim=8, df_dim=8,
                                             compute_dtype="float32"),
                           batch_size=args.batch_size)
    else:
        base = TrainConfig(model=ModelConfig(arch=args.arch),
                           batch_size=args.batch_size)
    cfg = dataclasses.replace(
        base, checkpoint_dir=f"{root}/ckpt", sample_dir=f"{root}/samples",
        batch_size=args.batch_size, seed=args.seed,
        sample_every_steps=0, save_summaries_secs=1e18, save_model_secs=1e18,
        log_every_steps=0, nan_check_steps=0,
        data_dir=args.data_dir or base.data_dir)
    synthetic = args.data_dir is None
    mcfg = cfg.model

    # One growing run: train to each snapshot in turn (resume-from-latest
    # carries state forward), scoring a frozen copy of the state at each stop.
    mesh = make_mesh(cfg.mesh)
    pt = make_parallel_train(cfg, mesh)
    scores = []
    for target in snapshots:
        if target > 0:
            state = train(cfg, synthetic_data=synthetic, max_steps=target)
        else:
            state = pt.init(jax.random.key(cfg.seed))

        def sample_fn(z, labels=None, _s=state):
            return pt.sample(_s, z, labels) if labels is not None \
                else pt.sample(_s, z)

        if synthetic:
            from dcgan_tpu.data import synthetic_batches

            data = synthetic_batches(args.batch_size, mcfg.output_size,
                                     mcfg.c_dim, seed=args.seed + 1, pool=0)
        else:
            from dcgan_tpu.data import DataConfig, make_dataset
            from dcgan_tpu.data.pipeline import read_manifest
            from dcgan_tpu.parallel import batch_sharding

            manifest = read_manifest(args.data_dir)  # wire format is the
            wire = {k: manifest[k]                   # dataset's to declare
                    for k in ("record_dtype", "feature_name")
                    if k in manifest}
            data = make_dataset(
                DataConfig(data_dir=args.data_dir,
                           image_size=mcfg.output_size,
                           channels=mcfg.c_dim, batch_size=args.batch_size,
                           seed=args.seed, normalize=True, **wire),
                batch_sharding(mesh, 4))

        try:
            result = compute_fid(
                sample_fn, data, image_size=mcfg.output_size,
                c_dim=mcfg.c_dim,
                z_dim=mcfg.z_dim, num_samples=args.num_samples,
                batch_size=args.batch_size, num_classes=mcfg.num_classes,
                seed=args.seed, kid=args.kid)
        finally:
            # a fresh pipeline is built per checkpoint: release its feed
            # thread + queued device batches instead of accreting one per
            # scored step
            if hasattr(data, "close"):
                data.close()
        row = {"step": target, "fid": result["fid"]}
        if args.kid:
            row["kid"] = result["kid"]
            row["kid_std"] = result["kid_std"]
        scores.append(row)
        print(json.dumps(row), flush=True)

    fids = [r["fid"] for r in scores]
    steps = [r["step"] for r in scores]
    monotonic = all(b <= a for a, b in zip(fids, fids[1:]))
    print(json.dumps({
        "monotonic": monotonic,
        "spearman_steps_vs_fid": round(_spearman(steps, fids), 4),
        "snapshots": len(scores),
    }))


if __name__ == "__main__":
    main()
