"""Cross-seed surrogate-FID rank-stability experiment (VERDICT r3 #3).

Every surrogate-validity trajectory in BASELINE.md uses the one fixed
feature seed (42, evals/features.py). The objection that leaves open:
"your FID is one lucky random projection." This tool kills it with CPU
minutes: train ONE run, snapshot the state at an increasing step ladder,
then score the SAME snapshots under a grid of feature seeds x feature
dims, and report

- per-config Spearman(step, FID): does training order survive every
  random projection, not just seed 42's?
- inter-config rank agreement: pairwise Spearman between the checkpoint
  orderings two feature configs induce — 1.0 means every projection ranks
  the ladder identically.

Prints one JSON line per (seed, dim) config with its scores, then a
summary line {"label": "fid-seed-stability", ...} for capture_all.

    python tools/fid_seed_stability.py --platform cpu \
        --snapshots 0,100,300,600,1000 --num_samples 1024

Workload anchor: the eval duty being replaced, image_train.py:179-192.
"""

from __future__ import annotations

import argparse
import dataclasses
import itertools
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.fid_trajectory import _spearman  # noqa: E402


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="fid_seed_stability")
    p.add_argument("--arch", choices=["dcgan", "resnet", "stylegan"],
                   default="dcgan")
    p.add_argument("--snapshots", default="0,100,300,600,1000")
    p.add_argument("--num_samples", type=int, default=1024)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--feature_seeds", default="42,7,123",
                   help="comma-joined embedder seeds (>=3 for the claim)")
    p.add_argument("--feature_dims", default="512,256",
                   help="comma-joined embedder output dims")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None)
    args = p.parse_args(argv)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from dcgan_tpu.config import ModelConfig, TrainConfig
    from dcgan_tpu.data import synthetic_batches
    from dcgan_tpu.evals.features import make_random_feature_fn
    from dcgan_tpu.evals.job import compute_fid
    from dcgan_tpu.parallel import make_mesh, make_parallel_train
    from dcgan_tpu.train.trainer import train

    snapshots = sorted(int(s) for s in args.snapshots.split(","))
    seeds = [int(s) for s in args.feature_seeds.split(",")]
    dims = [int(d) for d in args.feature_dims.split(",")]
    root = tempfile.mkdtemp(prefix="fid_seed_")

    # the tiny CPU validity config (matches the BASELINE.md trajectories)
    cfg = TrainConfig(
        model=ModelConfig(arch=args.arch, output_size=16, gf_dim=8,
                          df_dim=8, compute_dtype="float32"),
        batch_size=args.batch_size, seed=args.seed,
        checkpoint_dir=f"{root}/ckpt", sample_dir=f"{root}/samples",
        sample_every_steps=0, save_summaries_secs=1e18,
        save_model_secs=1e18, log_every_steps=0, nan_check_steps=0)
    mcfg = cfg.model
    mesh = make_mesh(cfg.mesh)
    pt = make_parallel_train(cfg, mesh)

    # one growing run; hold a frozen state copy at each rung of the ladder
    states = []
    for target in snapshots:
        if target > 0:
            state = train(cfg, synthetic_data=True, max_steps=target)
        else:
            state = pt.init(jax.random.key(cfg.seed))
        states.append((target, state))
        print(f"snapshot {target} captured", file=sys.stderr)

    # score the whole ladder under every (seed, dim) feature config
    per_config = []
    for fseed, fdim in itertools.product(seeds, dims):
        feature_fn, _ = make_random_feature_fn(
            mcfg.output_size, mcfg.c_dim, feature_dim=fdim, seed=fseed)
        fids = []
        for target, state in states:
            def sample_fn(z, labels=None, _s=state):
                return pt.sample(_s, z, labels) if labels is not None \
                    else pt.sample(_s, z)

            data = synthetic_batches(args.batch_size, mcfg.output_size,
                                     mcfg.c_dim, seed=args.seed + 1, pool=0)
            result = compute_fid(
                sample_fn, data, image_size=mcfg.output_size,
                c_dim=mcfg.c_dim, z_dim=mcfg.z_dim,
                num_samples=args.num_samples, batch_size=args.batch_size,
                seed=args.seed, feature_fn=feature_fn, feature_dim=fdim)
            fids.append(result["fid"])
        sp = _spearman(snapshots, fids)
        row = {"feature_seed": fseed, "feature_dim": fdim,
               "fids": [round(f, 6) for f in fids],
               "spearman_steps_vs_fid": round(sp, 4)}
        per_config.append(row)
        print(json.dumps(row), flush=True)

    # inter-config rank agreement of the checkpoint orderings
    pair_sp = [
        _spearman(a["fids"], b["fids"])
        for a, b in itertools.combinations(per_config, 2)]
    spearmans = [r["spearman_steps_vs_fid"] for r in per_config]
    print(json.dumps({
        "label": "fid-seed-stability",
        "arch": args.arch,
        "snapshots": snapshots,
        "configs": len(per_config),
        "per_config_spearman_min": round(min(spearmans), 4),
        "per_config_spearman_max": round(max(spearmans), 4),
        "inter_config_spearman_min": round(min(pair_sp), 4),
        "inter_config_spearman_mean": round(
            sum(pair_sp) / len(pair_sp), 4),
    }), flush=True)


if __name__ == "__main__":
    main()
