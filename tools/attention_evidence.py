"""CPU-side attention scaling evidence (VERDICT r2 #7, DESIGN.md §8).

The chip crossover table needs live TPU time; this collects what a CPU host
CAN honestly measure so §8's table has evidence while the chip column stays
pending:

  * dense forward+backward wall-clock vs S (the O(S^2) growth shape);
  * the dense allocation wall (at S=65536 the [S,S] score matrix plus
    backward residuals exceed what this host can allocate — the same
    failure mode as one chip's HBM, at a host-sized threshold);
  * the collective structure of the two sequence-parallel forms, counted
    as op definitions in the OPTIMIZED HLO over an 8-virtual-device mesh:
    ring lowers to 2 static collective-permutes (the k and v rotations)
    inside the scanned hop body, each executed n-1 times at runtime
    (arXiv:2310.01889's neighbor hops); ulysses to 4 all-to-alls forward —
    one per q/k/v seq->head redistribute plus one head->seq for the output
    (arXiv:2309.14509's structure) — and zero all-gathers in either form.
    This pins the communication design the chip table would time.

Prints one JSON line per row. Flash interpret-mode timings are deliberately
NOT reported: interpret mode executes the kernel's block loop in Python, so
its wall clock measures the interpreter, not the kernel (memory truth —
no [S,S] materialization — still holds and is asserted by the test suite).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, nargs="+",
                   default=[1024, 4096, 16384])
    p.add_argument("--wall_seq", type=int, default=65536,
                   help="S at which to demonstrate the dense allocation "
                        "wall (0 = skip)")
    p.add_argument("--mesh", type=int, default=8)
    p.add_argument("--d", type=int, default=64)
    p.add_argument("--steps", type=int, default=5)
    args = p.parse_args()

    # before the first jax import, so --mesh sizes beyond the default 8
    # actually get that many virtual host devices
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.mesh}")

    import jax
    from dcgan_tpu.utils.backend import shard_map

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from dcgan_tpu.ops.attention import (
        full_attention,
        ring_attention,
        ulysses_attention,
    )

    scale = args.d ** -0.5

    def qkv(S, heads=1):
        ks = jax.random.split(jax.random.key(0), 3)
        return tuple(jax.random.normal(k, (heads, S, args.d), jnp.bfloat16)
                     for k in ks)

    def grad_step(fn):
        return jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
            argnums=(0, 1, 2)))

    # 1. dense wall-clock growth (forward+backward)
    for S in args.seq:
        q, k, v = qkv(S)
        step = grad_step(lambda q, k, v: full_attention(q, k, v, scale=scale))
        out = step(q, k, v)
        jax.block_until_ready(out)
        dt = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(args.steps):
                out = step(q, k, v)
            jax.block_until_ready(out)
            dt = min(dt, time.perf_counter() - t0)
        print(json.dumps({"row": "dense_cpu_ms", "seq": S,
                          "ms": round(dt / args.steps * 1e3, 1)}))

    # 2. the dense allocation wall
    if args.wall_seq:
        S = args.wall_seq
        try:
            q, k, v = qkv(S)
            step = grad_step(
                lambda q, k, v: full_attention(q, k, v, scale=scale))
            jax.block_until_ready(step(q, k, v))
            print(json.dumps({"row": "dense_wall", "seq": S,
                              "result": "unexpectedly succeeded"}))
        except Exception as e:
            print(json.dumps({"row": "dense_wall", "seq": S,
                              "result": f"{type(e).__name__}",
                              "detail": str(e)[:160]}))

    # 3. collective structure of the sequence-parallel forms (optimized HLO)
    n = args.mesh
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(1, n),
                ("data", "model"))
    spec = P("data", "model", None)
    S = 1024
    heads = n
    q, k, v = qkv(S, heads)

    def count(fn, *xs):
        # Count op DEFINITIONS: a def line is `%name = <type> <opcode>(...)`
        # — match the opcode immediately followed by its operand paren on a
        # line with ` = `. Result NAMES often echo the opcode
        # (%all-to-all.5) but not always (%ppermute.7 = ...
        # collective-permute(...)), and uses appear as `(%name)` with no
        # trailing paren — this pattern counts exactly the defs either way.
        txt = jax.jit(fn).lower(*xs).compile().as_text()

        def defs(op):
            return sum(1 for line in txt.splitlines()
                       if " = " in line
                       and re.search(rf"{op}(?:-start)?\(", line))

        return {
            "collective_permute": defs("collective-permute"),
            "all_to_all": defs("all-to-all"),
            "all_gather": defs("all-gather"),
        }

    ring = shard_map(
        functools.partial(ring_attention, axis_name="model", n_shards=n,
                          scale=scale),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
    uly = shard_map(
        functools.partial(ulysses_attention, axis_name="model", n_shards=n,
                          num_heads=heads, scale=scale),
        mesh=mesh, in_specs=(P("data", "model", None),) * 3,
        out_specs=P("data", "model", None))
    # ring/ulysses operate on [B, S, D]-like shards; reuse the bench's
    # shapes: [batch*heads, S, d] for ring, [B, S, h*d] for ulysses
    for name, fn, xs, expect in [
        ("ring", ring, (q, k, v),
         "2 static permutes (k and v rotation) inside the scanned hop "
         f"body, each executed n-1={n - 1} times at runtime"),
        ("ulysses", uly,
         tuple(x.transpose(1, 0, 2).reshape(1, S, heads * args.d)
               for x in (q, k, v)),
         "4 fwd ops: one seq->head all_to_all per q/k/v + one head->seq "
         "for the output (arXiv:2309.14509's 4-collective structure)"),
    ]:
        fwd = count(fn, *xs)
        g = jax.grad(lambda *a: jnp.sum(fn(*a).astype(jnp.float32)),
                     argnums=(0, 1, 2))
        fwdbwd = count(g, *xs)
        print(json.dumps({"row": f"{name}_collectives", "mesh": n,
                          "seq": S, "forward": fwd,
                          "forward_backward": fwdbwd,
                          "design": expect}))


if __name__ == "__main__":
    main()
