"""Op-level fused-BN kernel benchmark: Pallas vs XLA, out of conv context.

The captures table showed `use_pallas` losing ~23% at flagship shapes
*inside* the step, where XLA fuses the BN epilogue into the surrounding
convs. This tool isolates the op itself (VERDICT r3 #2's "find the config
where fusion wins" probe): forward+backward of batch-stat BN + lrelu on a
standalone activation tensor — no conv to fuse into, both forms reading and
writing the same HBM tensors — scanned K times per dispatch with
value-readback sync, best of 3 windows.

Measured conclusion (chip, 2026-07-31, DESIGN.md §8b): the kernels tie at
channel counts that fill the 128-wide vector lanes ([64,32,32,128] 0.95x,
[64,8,8,512] 0.99x) and lose 2-5x at C=64 or larger tensors — XLA's fusion
already saturates HBM for this op class, so `use_pallas` is a capability/
pattern flag, not a perf flag.

Prints one JSON line per shape:
  {"form": "bn_op", "shape": [...], "jnp_ms": a, "pallas_ms": b,
   "ratio_jnp_over_pallas": r}

Workload anchor: the BN the reference applies after nearly every conv
(distriubted_model.py:93-121).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EPS = 1e-5
K = int(os.environ.get("BENCH_OP_ITERS", 100))
SHAPES = [(64, 32, 32, 128), (64, 8, 8, 512), (64, 64, 64, 64),
          (256, 32, 32, 128), (256, 64, 64, 64)]


def main() -> None:
    import jax
    import jax.numpy as jnp

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    from dcgan_tpu.ops.pallas_kernels import channel_moments, fused_bn_act
    from dcgan_tpu.utils.backend import acquire_devices

    acquire_devices()

    def jnp_bn_act(x, gamma, beta):
        c = x.shape[-1]
        x2 = x.reshape(-1, c).astype(jnp.float32)
        mean = x2.mean(0)
        var = (x2 * x2).mean(0) - mean * mean
        inv = jax.lax.rsqrt(var + EPS)
        y = (x2 - mean) * inv * gamma + beta
        y = jnp.where(y > 0, y, 0.2 * y)
        return y.reshape(x.shape).astype(x.dtype)

    def pallas_bn_act(x, gamma, beta):
        c = x.shape[-1]
        x2 = x.reshape(-1, c)
        mean, msq = channel_moments(x2)
        var = msq - mean * mean
        return fused_bn_act(x, gamma, beta, mean, var, eps=EPS, act="lrelu")

    def bench(fn, shape):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal(shape), dtype=jnp.bfloat16)
        gamma = jnp.ones((shape[-1],), jnp.float32)
        beta = jnp.zeros((shape[-1],), jnp.float32)

        def loss(x, gamma, beta):
            return fn(x, gamma, beta).astype(jnp.float32).sum()

        grad = jax.grad(loss, argnums=(0, 1, 2))

        @jax.jit
        def many(x, gamma, beta):
            # the carry feeds a tiny nonzero x perturbation so XLA cannot
            # hoist the loop-invariant grad computation out of the scan
            # (a 0.0 coefficient could legally be folded away)
            def body(carry, _):
                g = grad(x * (1.0 + 1e-7 * carry), gamma, beta)
                return carry + g[1][0], None
            acc, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(K))
            return acc

        out = many(x, gamma, beta)
        float(out)
        dt = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = many(x, gamma, beta)
            float(out)
            dt = min(dt, time.perf_counter() - t0)
        return dt / K * 1e3

    for shape in SHAPES:
        tj = bench(jnp_bn_act, shape)
        tp = bench(pallas_bn_act, shape)
        print(json.dumps({
            "form": "bn_op", "shape": list(shape),
            "jnp_ms": round(tj, 4), "pallas_ms": round(tp, 4),
            "ratio_jnp_over_pallas": round(tj / tp, 3)}), flush=True)


if __name__ == "__main__":
    main()
