"""Sustained bf16 matmul rate microbenchmark (the MFU denominator).

DESIGN.md's roofline section cites the headline step as a fraction of "the
chip's observed sustained bf16 matmul rate through the same transport".
VERDICT r3 #1 (weak #2): that denominator existed only as narrative. This
tool IS the measurement — runnable standalone or under tools/capture_all.py
(section "roofline"), so the number regenerates with every harvest.

Method: the alternating pair y <- (y @ W1) @ W2 iterated ITERS times
inside one compiled lax.fori_loop — y [M, K], W1 [K, N], W2 [N, K], all
bf16, weights scaled by 1/sqrt(fan-in) so magnitudes stay O(1) across
iterations (bf16 never overflows; no renormalization work pollutes the
loop). Two matmuls per iteration let non-square (M, K, N) shapes chain,
which is how the sweep covers the model's own conv contractions, not just
square ceilings. The dependency chain serializes on purpose — each matmul
must stand on its own, and chaining keeps the loop compute-bound in
registers/VMEM rather than HBM-streaming fresh operands (we are measuring
the MXU ceiling, not HBM bandwidth). Sync is by value readback, not
block_until_ready, for the same reason bench.py's is (the tunneled
transport can report completion early). Best of MATMUL_WINDOWS windows,
like every other capture in this repo.

Prints one JSON line per shape and a final summary line:
  {"form": "matmul", "m": M, "k": K, "n": N, "tflops": T,
   "ms_per_matmul": t}
  {"label": "matmul-rate", "peak_tflops": T, "peak_shape": "MxKxN", ...}

The per-shape sweep is the defense of the number: if the sustained rate is
far below nameplate, the sweep shows whether bigger shapes close the gap
(transport/clock-bound) or not (shape-bound).

Workload anchor: the conv/deconv stacks this rate bounds replace the
reference's cuDNN kernels (distriubted_model.py:176-213); the MXU is the
"native code" executing them here (SURVEY.md §0).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# (M, K, N) triples: alternating y[M,K] @ W1[K,N] @ W2[N,K] chain (two
# matmuls per iteration, so non-square shapes chain too). The sweep covers
# the asymptotic MXU-filling regime (square 1k-8k — the ceiling claim) AND
# the headline model's own conv contractions as implicit im2col GEMMs
# (M = batch*out_h*out_w, K = kh*kw*cin, N = cout for the four
# discriminator stages, distriubted_model.py:114-121) — the per-layer
# ceilings the step's effective rate is bounded by.
# MATMUL_SHAPES="MxN,MxKxN,..." overrides (MxN means square: K=N=that).
_DEFAULT_SHAPES = [
    (1024, 1024, 1024), (2048, 2048, 2048), (4096, 4096, 4096),
    (8192, 8192, 8192), (4096, 8192, 8192),
    # DCGAN-64 discriminator stages at batch 64 (G's deconvs transpose them)
    (65536, 75, 64), (16384, 1600, 128), (4096, 3200, 256),
    (1024, 6400, 512),
]


def _parse_shape(s: str):
    v = [int(x) for x in s.split("x")]
    return (v[0], v[1], v[1]) if len(v) == 2 else tuple(v[:3])


SHAPES = ([_parse_shape(s) for s in os.environ["MATMUL_SHAPES"].split(",")]
          if os.environ.get("MATMUL_SHAPES") else _DEFAULT_SHAPES)
ITERS = int(os.environ.get("MATMUL_ITERS", 200))      # iterations per
# dispatch; each iteration is TWO matmuls (the alternating pair)
WINDOWS = int(os.environ.get("MATMUL_WINDOWS", 3))


def _bench_shape(m: int, k: int, n: int) -> dict:
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    y0 = jnp.asarray(rng.standard_normal((m, k)), dtype=jnp.bfloat16)
    w1 = jnp.asarray(rng.standard_normal((k, n)) / np.sqrt(k),
                     dtype=jnp.bfloat16)
    w2 = jnp.asarray(rng.standard_normal((n, k)) / np.sqrt(n),
                     dtype=jnp.bfloat16)

    @jax.jit
    def chain(y, w1, w2):
        def body(_, y):
            return jnp.dot(jnp.dot(y, w1), w2)
        return jax.lax.fori_loop(0, ITERS, body, y)

    y = chain(y0, w1, w2)       # compile + warmup
    float(y[0, 0])              # value-readback sync
    dt = float("inf")
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        y = chain(y0, w1, w2)
        float(y[0, 0])
        dt = min(dt, time.perf_counter() - t0)

    n_matmuls = 2 * ITERS       # the alternating pair per iteration
    flops = 4.0 * m * k * n * ITERS
    return {"form": "matmul", "m": m, "k": k, "n": n,
            # full precision for peak selection; rounded for display
            "tflops_raw": flops / dt / 1e12,
            "tflops": round(flops / dt / 1e12, 4),
            "ms_per_matmul": round(dt / n_matmuls * 1e3, 4)}


def main() -> None:
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from dcgan_tpu.utils.backend import acquire_devices

    dev = acquire_devices()[0]
    peak = None
    for m, k, n in SHAPES:
        row = _bench_shape(m, k, n)
        raw = row.pop("tflops_raw")
        print(json.dumps(row), flush=True)
        if peak is None or raw > peak[0]:
            peak = (raw, row)
    peak = peak[1]
    print(json.dumps({
        "label": "matmul-rate",
        "peak_tflops": peak["tflops"],
        "peak_shape": f"{peak['m']}x{peak['k']}x{peak['n']}",
        "iters_per_dispatch": ITERS,
        "device": str(dev),
    }), flush=True)


if __name__ == "__main__":
    main()
