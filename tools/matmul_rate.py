"""Sustained bf16 matmul rate microbenchmark (the MFU denominator).

DESIGN.md's roofline section cites the headline step as a fraction of "the
chip's observed sustained bf16 matmul rate through the same transport".
VERDICT r3 #1 (weak #2): that denominator existed only as narrative. This
tool IS the measurement — runnable standalone or under tools/capture_all.py
(section "roofline"), so the number regenerates with every harvest.

Method: y <- y @ W iterated K times inside one compiled lax.fori_loop, y
[M, N] and W [N, N] both bf16, W scaled by 1/sqrt(N) so magnitudes stay
O(1) across iterations (bf16 never overflows; no renormalization work
pollutes the loop). The dependency chain serializes iterations on purpose —
each matmul is large enough to fill the MXU on its own, and chaining keeps
the loop compute-bound in registers/VMEM rather than HBM-streaming fresh
operands (we are measuring the MXU ceiling, not HBM bandwidth). Sync is by
value readback, not block_until_ready, for the same reason bench.py's is
(the tunneled transport can report completion early). Best of
MATMUL_WINDOWS windows, like every other capture in this repo.

Prints one JSON line per shape and a final summary line:
  {"form": "matmul", "m": M, "n": N, "tflops": T, "ms_per_matmul": t}
  {"label": "matmul-rate", "peak_tflops": T, "peak_shape": "MxNxN", ...}

The per-shape sweep is the defense of the number: if the sustained rate is
far below nameplate, the sweep shows whether bigger shapes close the gap
(transport/clock-bound) or not (shape-bound).

Workload anchor: the conv/deconv stacks this rate bounds replace the
reference's cuDNN kernels (distriubted_model.py:176-213); the MXU is the
"native code" executing them here (SURVEY.md §0).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# (M, N) pairs: y [M, N] @ W [N, N]. The sweep brackets the headline
# model's real contraction sizes (conv-as-matmul K in the hundreds-to-few-
# thousand range) and the asymptotic MXU-filling regime (4k-8k).
# MATMUL_SHAPES="m1xn1,m2xn2" overrides (CPU smoke tests use tiny shapes).
_DEFAULT_SHAPES = [(1024, 1024), (2048, 2048), (4096, 4096), (8192, 8192),
                   (4096, 8192)]
SHAPES = ([tuple(int(v) for v in s.split("x"))
           for s in os.environ["MATMUL_SHAPES"].split(",")]
          if os.environ.get("MATMUL_SHAPES") else _DEFAULT_SHAPES)
ITERS = int(os.environ.get("MATMUL_ITERS", 200))      # matmuls per dispatch
WINDOWS = int(os.environ.get("MATMUL_WINDOWS", 3))


def _bench_shape(m: int, n: int) -> dict:
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    y0 = jnp.asarray(rng.standard_normal((m, n)), dtype=jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((n, n)) / np.sqrt(n),
                    dtype=jnp.bfloat16)

    @jax.jit
    def chain(y, w):
        def body(_, y):
            return jnp.dot(y, w)
        return jax.lax.fori_loop(0, ITERS, body, y)

    y = chain(y0, w)            # compile + warmup
    float(y[0, 0])              # value-readback sync
    dt = float("inf")
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        y = chain(y0, w)
        float(y[0, 0])
        dt = min(dt, time.perf_counter() - t0)

    flops = 2.0 * m * n * n * ITERS
    return {"form": "matmul", "m": m, "n": n,
            # full precision for peak selection; rounded for display
            "tflops_raw": flops / dt / 1e12,
            "tflops": round(flops / dt / 1e12, 4),
            "ms_per_matmul": round(dt / ITERS * 1e3, 4)}


def main() -> None:
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from dcgan_tpu.utils.backend import acquire_devices

    dev = acquire_devices()[0]
    peak = None
    for m, n in SHAPES:
        row = _bench_shape(m, n)
        raw = row.pop("tflops_raw")
        print(json.dumps(row), flush=True)
        if peak is None or raw > peak[0]:
            peak = (raw, row)
    peak = peak[1]
    print(json.dumps({
        "label": "matmul-rate",
        "peak_tflops": peak["tflops"],
        "peak_shape": f"{peak['m']}x{peak['n']}x{peak['n']}",
        "iters_per_dispatch": ITERS,
        "device": str(dev),
    }), flush=True)


if __name__ == "__main__":
    main()
