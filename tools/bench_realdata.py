"""Real-data end-to-end training throughput (VERDICT r2 #4).

Measures the disk -> TFRecord loader -> device training path the reference
was built for (image_input.py:98-143) against the synthetic-stream rate on
the SAME compiled program, so the output is directly the input-bound ratio
(Weak #4): a procedurally generated PNG corpus goes through the real
`data.prepare` converter into TFRecord shards (float64 — reference parity —
and uint8), then the flagship config trains from the real loader while the
step program, sync discipline (value readback, bench.py's rationale) and
batch shape stay identical to the synthetic measurement.

Prints one JSON line per measured source:
  {"metric": "...", "source": "synthetic"|"float64"|"uint8",
   "value": img/s, "unit": "images/sec", "vs_synthetic": ratio}

Corpus/records are cached under tools/_realdata/ (gitignored; delete to
regenerate). CPU smoke: --platform cpu --steps 30 --batch 8. The chip run
is a capture_all.py step.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
ROOT = os.path.join(REPO, "tools", "_realdata")


def ensure_corpus(n_images: int, side: int = 108, seed: int = 0) -> str:
    """Procedural PNG corpus: smooth random gradients + shapes (statistics
    non-trivial enough that crop/resize/normalize do real work; the POINT is
    the disk->loader->chip path, not the dataset)."""
    from PIL import Image

    d = os.path.join(ROOT, f"corpus_{n_images}x{side}")
    marker = os.path.join(d, ".complete")
    if os.path.exists(marker):
        return d
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32) / side
    for i in range(n_images):
        a, b, c = rng.uniform(-3, 3, 3)
        base = np.stack([np.sin(a * xx + b * yy + c + ch) for ch in range(3)],
                        -1)
        cx, cy, r = rng.uniform(0.2, 0.8, 2).tolist() + [rng.uniform(.05, .3)]
        mask = ((xx - cx) ** 2 + (yy - cy) ** 2 < r * r)[..., None]
        img = np.where(mask, rng.uniform(-1, 1, 3).astype(np.float32), base)
        img = img + rng.normal(0, 0.05, img.shape).astype(np.float32)
        arr = np.clip((img * 0.5 + 0.5) * 255, 0, 255).astype(np.uint8)
        Image.fromarray(arr).save(os.path.join(d, f"{i:06d}.png"))
    with open(marker, "w") as f:
        f.write("ok")
    return d


def ensure_records(corpus: str, dtype: str, image_size: int) -> str:
    from dcgan_tpu.data.prepare import convert

    # keyed by the corpus dir name too (it encodes count x side), so a
    # changed --corpus_images never silently reuses stale records
    out = os.path.join(ROOT, f"recs_{os.path.basename(corpus)}"
                             f"_{dtype}_{image_size}")
    if os.path.exists(os.path.join(out, "dataset.json")):
        return out
    convert(corpus, out, image_size=image_size, crop_size=108,
            record_dtype=dtype, overwrite=True)
    return out


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--preset", default="celeba64",
                   help="named config to bench (the flagship real-data run)")
    p.add_argument("--batch", type=int, default=64, help="per-chip batch")
    p.add_argument("--steps", type=int, default=200,
                   help="measured steps per source")
    p.add_argument("--warmup", type=int, default=8,
                   help="warmup steps per source (min 1: the first call "
                        "compiles and must stay out of the timed window)")
    p.add_argument("--corpus_images", type=int, default=2048)
    p.add_argument("--dtypes", nargs="+", default=["float64", "uint8"],
                   help="record dtypes to measure (float64 = reference "
                        "parity; uint8 = the steered fast path)")
    p.add_argument("--platform", default=None)
    args = p.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import dataclasses

    import jax.numpy as jnp

    from dcgan_tpu.config import MeshConfig
    from dcgan_tpu.data import DataConfig, make_dataset
    from dcgan_tpu.parallel import batch_sharding, make_mesh, \
        make_parallel_train
    from dcgan_tpu.presets import get_preset

    n_chips = len(jax.devices())
    cfg = dataclasses.replace(get_preset(args.preset),
                              batch_size=args.batch * n_chips,
                              mesh=MeshConfig())
    if cfg.model.num_classes > 0:
        # the procedural corpus is unlabeled and measure() feeds no labels
        # arg — a conditional step would fail its in_shardings arity before
        # measuring anything
        p.error(f"--preset {args.preset} is class-conditional; this bench "
                "drives the unconditional real-data path (use celeba64/"
                "dcgan128/wgan-gp style presets)")
    size = cfg.model.output_size
    mesh = make_mesh(cfg.mesh)
    pt = make_parallel_train(cfg, mesh)
    state = pt.init(jax.random.key(0))
    base = jax.random.key(1)

    corpus = ensure_corpus(args.corpus_images)

    args.warmup = max(1, args.warmup)

    def measure(batches, tag, state):
        """Warmup + timed steps over `batches`; value-readback sync."""
        it = iter(batches)
        for i in range(args.warmup):
            state, metrics = pt.step(state, next(it),
                                     jax.random.fold_in(base, i))
        float(metrics["d_loss"])
        t0 = time.perf_counter()
        for i in range(args.steps):
            state, metrics = pt.step(state, next(it),
                                     jax.random.fold_in(base, 1000 + i))
        float(metrics["d_loss"])  # hard sync ends the window
        dt = time.perf_counter() - t0
        rate = cfg.batch_size * args.steps / dt
        print(f"{tag}: {rate:.1f} img/s ({dt:.2f}s for {args.steps} steps)",
              file=sys.stderr)
        return rate, state

    # Synthetic ceiling first: one in-memory batch re-fed every step (the
    # loader entirely out of the picture), same program.
    imgs = jnp.asarray(np.random.default_rng(0).uniform(
        -1, 1, (cfg.batch_size, size, size, cfg.model.c_dim))
        .astype(np.float32))

    def constant_batches():
        while True:
            yield imgs

    syn_rate, state = measure(constant_batches(), "synthetic", state)
    print(json.dumps({
        "metric": f"{args.preset} train throughput (batch {args.batch}/chip)",
        "source": "synthetic", "value": round(syn_rate, 1),
        "unit": "images/sec", "vs_synthetic": 1.0}))

    for dtype in args.dtypes:
        recs = ensure_records(corpus, dtype, size)
        dcfg = DataConfig(data_dir=recs, image_size=size,
                          channels=cfg.model.c_dim,
                          batch_size=cfg.batch_size, record_dtype=dtype,
                          min_after_dequeue=min(1024, args.corpus_images),
                          n_threads=cfg.num_loader_threads,
                          seed=0, normalize=True)
        data = make_dataset(dcfg, batch_sharding(mesh, 4))
        try:
            rate, state = measure(data, f"real {dtype}", state)
        finally:
            if hasattr(data, "close"):  # stop the device-feed thread
                data.close()
        print(json.dumps({
            "metric": f"{args.preset} train throughput "
                      f"(batch {args.batch}/chip)",
            "source": dtype, "value": round(rate, 1),
            "unit": "images/sec",
            "vs_synthetic": round(rate / syn_rate, 3)}))


if __name__ == "__main__":
    main()
