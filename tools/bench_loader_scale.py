"""Multi-process loader scaling: M loader processes, per-process shard
ownership, one synchronized measurement window (VERDICT r4 #2).

The question the chip's own numbers raise: the b512 peak regime consumes
32.6k img/s/chip while the measured ONE-CORE uint8 loader ceiling is ~26.5k
img/s — can the pipeline feed the peak? The design answer is process-level
scaling: `pipeline.shard_for_process` gives process i shards i, i+P, ... (the
exact ownership `--multihost` training uses), so loader throughput scales by
adding reader PROCESSES pinned to distinct cores, no shared state to contend
on. This tool measures that aggregate:

- parent writes one synthetic shard set (reference wire schema, uint8 by
  default — prepare.py's default since r4);
- M worker processes each own their `shard_for_process` slice, warm up,
  then measure over the SAME wall-clock window (parent-assigned start/end
  timestamps, so per-process rates are concurrent and sum honestly);
- one JSON line per M with per-process and aggregate rates, plus the
  visible-core count (`os.sched_getaffinity`) — on a single-core host the
  aggregate stays flat by construction and the per-core rate is the budget
  number; on an N-core host the aggregate demonstrates the scaling itself.

    python tools/bench_loader_scale.py                 # M = 1, 2
    python tools/bench_loader_scale.py --processes 1 4 8 --seconds 10
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _worker() -> None:
    """Child body: own shard slice -> NativeLoader -> timed window."""
    spec = json.loads(os.environ["LOADER_SCALE_SPEC"])
    from dcgan_tpu.data.native import NativeLoader
    from dcgan_tpu.data.pipeline import shard_for_process

    paths = shard_for_process(spec["paths"], spec["pid"], spec["nproc"])
    shape = tuple(spec["shape"])
    batch = spec["batch"]
    ld = NativeLoader(paths, n_threads=spec["threads"], batch=batch,
                      example_shape=shape, record_dtype=spec["record_dtype"],
                      min_after_dequeue=4 * batch, prefetch_batches=4,
                      seed=spec["pid"], normalize=True, loop=True)
    try:
        for _ in range(3):
            ld.next()
        while time.time() < spec["start_ts"]:  # shared window start
            time.sleep(0.005)
        n = 0
        while time.time() < spec["end_ts"]:
            ld.next()
            n += batch
        # actual span can overshoot end_ts by one batch; charge the real
        # time — measured BEFORE close() so reader-thread teardown is not
        # billed to the throughput window
        span = time.time() - spec["start_ts"]
    finally:
        ld.close()
    print(json.dumps({"pid": spec["pid"], "images": n,
                      "span_s": round(span, 3),
                      "images_per_sec": round(n / span, 1),
                      "shards_owned": len(paths)}))


def main() -> None:
    if os.environ.get("LOADER_SCALE_SPEC"):
        _worker()
        return

    p = argparse.ArgumentParser()
    p.add_argument("--processes", type=int, nargs="+", default=[1, 2])
    p.add_argument("--threads", type=int, default=16,
                   help="reader threads per process (clamped to owned shards)")
    p.add_argument("--record_dtype", default="uint8",
                   choices=["float64", "float32", "uint8"])
    p.add_argument("--image_size", type=int, default=64)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--num_examples", type=int, default=8192)
    p.add_argument("--num_shards", type=int, default=32,
                   help="total shards; each of M processes owns ~shards/M")
    p.add_argument("--seconds", type=float, default=6.0,
                   help="shared measurement window length")
    p.add_argument("--warmup_s", type=float, default=8.0,
                   help="lead time for children to import + warm up")
    args = p.parse_args()

    from dcgan_tpu.data.synthetic import write_image_tfrecords

    cores = len(os.sched_getaffinity(0))
    with tempfile.TemporaryDirectory() as tmp:
        paths = write_image_tfrecords(
            tmp, num_examples=args.num_examples,
            image_size=args.image_size, num_shards=args.num_shards,
            record_dtype=args.record_dtype)
        shape = (args.image_size, args.image_size, 3)

        for m in args.processes:
            start = time.time() + args.warmup_s
            end = start + args.seconds
            procs = []
            for pid in range(m):
                spec = {"paths": paths, "pid": pid, "nproc": m,
                        "threads": args.threads, "batch": args.batch,
                        "shape": shape, "record_dtype": args.record_dtype,
                        "start_ts": start, "end_ts": end}
                procs.append(subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__)],
                    env=dict(os.environ,
                             LOADER_SCALE_SPEC=json.dumps(spec)),
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    text=True))
            rows = []
            for pr in procs:
                out, err = pr.communicate(timeout=args.warmup_s
                                          + args.seconds + 120)
                if pr.returncode != 0:
                    raise SystemExit(f"worker failed:\n{err[-2000:]}")
                rows.append(json.loads(out.strip().splitlines()[-1]))
            print(json.dumps({
                "label": "loader-scale",
                "processes": m,
                "threads_per_process": args.threads,
                "record_dtype": args.record_dtype,
                "cores_visible": cores,
                "aggregate_images_per_sec": round(
                    sum(r["images_per_sec"] for r in rows), 1),
                "per_process_images_per_sec": [r["images_per_sec"]
                                               for r in rows],
                "window_s": args.seconds,
            }))


if __name__ == "__main__":
    main()
