"""One-command live-tunnel harvester (VERDICT r2 #2).

The TPU tunnel works in bursts; every live window must yield everything.
This runs the runbook's sections in priority order — headline bench →
preset/variant matrix → attention crossovers → chip FID trajectory →
loader ceiling — each under its own bounded timeout, records every
result (value or failure) to ``tools/captures.jsonl``, and rewrites the
marker-delimited "Chip captures" blocks in BASELINE.md and DESIGN.md §8
from the accumulated log. Dead-tunnel steps are skipped cleanly: one
failed probe parks all remaining tunnel-bound sections (re-run on the
next burst; the JSONL is append-only, renders keep the best row per
label).

Usage:
    python tools/capture_all.py                  # everything, priority order
    python tools/capture_all.py --only headline matrix
    python tools/capture_all.py --render-only    # just re-render the docs

The workload anchor for the throughput sections is the reference's hot
loop, image_train.py:147-194; the FID section replaces its eval duty
(image_train.py:179-192).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPTURES = os.path.join(REPO, "tools", "captures.jsonl")
BASELINE_MD = os.path.join(REPO, "BASELINE.md")
DESIGN_MD = os.path.join(REPO, "docs", "DESIGN.md")

BEGIN = "<!-- capture_all:begin -->"
END = "<!-- capture_all:end -->"


def _today() -> str:
    return datetime.date.today().isoformat()


def probe(timeout: float = 60.0) -> bool:
    """RUNBOOK §0: jax.devices() in a throwaway child; hang == dead."""
    try:
        res = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            env=dict(os.environ), timeout=timeout, capture_output=True)
        return res.returncode == 0
    except subprocess.TimeoutExpired:
        return False


# ---------------------------------------------------------------------------
# Step table: (section, label, argv, env overrides, timeout_s, needs_tunnel)
# Priority order IS file order — the headline number first, because a burst
# may die at any moment.
# ---------------------------------------------------------------------------

def _bench(label: str, timeout: float = 420, **env: str):
    # bench.py probes for itself too; keep its internal budget under ours
    # and its probe short (the harvester just probed).
    e = {"BENCH_TOTAL_BUDGET": str(int(timeout - 30)),
         "BENCH_PROBE_TIMEOUT": "45", **env}
    return ("matrix", label, [sys.executable, "bench.py"], e, timeout, True)


STEPS = [
    ("headline", "dcgan64-headline", [sys.executable, "bench.py"],
     {"BENCH_TOTAL_BUDGET": "570", "BENCH_PROBE_TIMEOUT": "45"}, 600, True),
    _bench("dcgan128", BENCH_PRESET="dcgan128"),
    _bench("wgan-gp", BENCH_PRESET="wgan-gp"),
    _bench("cifar10-cond", BENCH_PRESET="cifar10-cond"),
    _bench("sngan-cifar10", BENCH_PRESET="sngan-cifar10"),
    _bench("sagan64-attn", BENCH_ATTN="1"),
    _bench("sagan64-attn-sn", BENCH_ATTN="1", BENCH_SN="1"),
    # the measured-best attention execution split (r5): flash kernels for
    # the attention block, XLA for BN — chip probe measured 10.75 vs
    # 15.70 ms/step against the dense rows above (+46%); these rows keep
    # that comparison live in the matrix (and the sagan presets default
    # to this split since rev 2)
    _bench("sagan64-attn-flash", BENCH_ATTN="1", BENCH_PALLAS="1",
           BENCH_BN_PALLAS="0"),
    _bench("sagan64-attn-sn-flash", BENCH_ATTN="1", BENCH_SN="1",
           BENCH_PALLAS="1", BENCH_BN_PALLAS="0"),
    # the attention family's batch-scaling points: does the flash form keep
    # the headline's rising-throughput curve (DESIGN.md §1b) once the
    # score-matrix traffic is gone?
    _bench("sagan64-attn-flash-b256", BENCH_ATTN="1", BENCH_PALLAS="1",
           BENCH_BN_PALLAS="0", BENCH_BATCH="256"),
    _bench("sagan64-attn-flash-b512", BENCH_ATTN="1", BENCH_PALLAS="1",
           BENCH_BN_PALLAS="0", BENCH_BATCH="512"),
    # the full sagan64 preset (hinge + SN both nets + TTUR + EMA on the
    # rev-2 flash/XLA-BN split) — the recipe row, vs the knob rows above
    _bench("sagan64", BENCH_PRESET="sagan64"),
    # sagan128: attention at 64x64 (S=4096) — deeper into flash's winning
    # regime; the preset's first captured number
    _bench("sagan128", timeout=600, BENCH_PRESET="sagan128",
           BENCH_STEPS="200", BENCH_SCAN="25"),
    # inference (sampler) rows for the attention family — the serve path
    # with the flash kernels in the generator
    _bench("sagan64-attn-flash-sample", BENCH_MODE="sample",
           BENCH_ATTN="1", BENCH_PALLAS="1", BENCH_BN_PALLAS="0"),
    _bench("dcgan64-pallas", BENCH_PALLAS="1"),
    _bench("dcgan64-shard_map", BENCH_BACKEND="shard_map"),
    _bench("dcgan64-sample", BENCH_MODE="sample"),
    _bench("dcgan128-sample", BENCH_MODE="sample", BENCH_PRESET="dcgan128"),
    _bench("dcgan64-b256", BENCH_BATCH="256"),
    # batch-scaling series: the step is HBM-bandwidth-bound at batch 64
    # (DESIGN.md §1b), so img/s should keep rising with batch as weights
    # and optimizer traffic amortize — these rows are that curve
    _bench("dcgan64-b128", BENCH_BATCH="128"),
    _bench("dcgan64-b512", BENCH_BATCH="512"),
    _bench("dcgan64-b1024", BENCH_BATCH="1024"),
    _bench("dcgan64-accum4", BENCH_ACCUM="4"),
    _bench("stylegan64", BENCH_PRESET="stylegan64"),
    # Long-context IN-MODEL rows (DESIGN.md §8): self-attention over the
    # 128x128 feature map (S = 16384) inside a 256x256 DCGAN train step.
    # At batch 8 both forms fit and flash measures ~3.4x faster (the [S, S]
    # materialization is pure overhead); at the reference's batch-64
    # contract the dense form needs a 64 GiB f32[64, 16384, 16384] score
    # buffer and CANNOT allocate (the compiler names it in the error) —
    # its recorded failure is the measurement, and the flash row at the
    # same batch is the capability.
    _bench("dcgan256-attn128-flash", timeout=600, BENCH_SIZE="256",
           BENCH_ATTN_RES="128", BENCH_PALLAS="1", BENCH_BATCH="8",
           BENCH_STEPS="100", BENCH_SCAN="10"),
    _bench("dcgan256-attn128-dense", timeout=600, BENCH_SIZE="256",
           BENCH_ATTN_RES="128", BENCH_BATCH="8",
           BENCH_STEPS="100", BENCH_SCAN="10"),
    _bench("dcgan256-attn128-flash-b64", timeout=900, BENCH_SIZE="256",
           BENCH_ATTN_RES="128", BENCH_PALLAS="1", BENCH_BATCH="64",
           BENCH_STEPS="40", BENCH_SCAN="5"),
    _bench("dcgan256-attn128-dense-b64", timeout=600, BENCH_SIZE="256",
           BENCH_ATTN_RES="128", BENCH_BATCH="64",
           BENCH_STEPS="40", BENCH_SCAN="5"),
    # the named long-context preset (hinge + SN-D on top of the raw rows)
    _bench("sagan256-lc", timeout=900, BENCH_PRESET="sagan256-lc",
           BENCH_STEPS="40", BENCH_SCAN="5"),
    ("attention", "attn-crossover-small",
     [sys.executable, "tools/bench_attention.py",
      "--seq", "1024", "4096", "16384"], {}, 600, True),
    ("attention", "attn-crossover-wall",
     [sys.executable, "tools/bench_attention.py",
      "--seq", "32768", "40960", "45056", "49152", "65536"], {}, 900, True),
    ("attention", "attn-memory",
     [sys.executable, "tools/attention_memory.py",
      "--seq", "8192", "16384", "32768", "40960", "45056", "49152",
      "65536"],
     {}, 900, True),
    ("roofline", "matmul-rate", [sys.executable, "tools/matmul_rate.py"],
     {}, 600, True),
    ("roofline", "step-profile", [sys.executable, "tools/step_profile.py"],
     {}, 600, True),
    # per-family profiles for the configs below the 4x north star
    # (VERDICT r4 #5): same tool, same knobs as their bench rows — the
    # numerator/denominator behind each family's binding-roof reading
    # (DESIGN.md §1c)
    ("roofline", "step-profile-dcgan128",
     [sys.executable, "tools/step_profile.py"],
     {"BENCH_PRESET": "dcgan128"}, 600, True),
    ("roofline", "step-profile-wgan-gp",
     [sys.executable, "tools/step_profile.py"],
     {"BENCH_PRESET": "wgan-gp"}, 600, True),
    ("roofline", "step-profile-sagan64-attn",
     [sys.executable, "tools/step_profile.py"],
     {"BENCH_ATTN": "1"}, 600, True),
    ("roofline", "step-profile-sagan64-attn-flash",
     [sys.executable, "tools/step_profile.py"],
     {"BENCH_ATTN": "1", "BENCH_PALLAS": "1", "BENCH_BN_PALLAS": "0"},
     600, True),
    ("roofline", "step-profile-stylegan64",
     [sys.executable, "tools/step_profile.py"],
     {"BENCH_PRESET": "stylegan64"}, 600, True),
    ("roofline", "trainer-loop",
     [sys.executable, "tools/bench_trainer_loop.py"], {}, 900, True),
    ("roofline", "pallas-op",
     [sys.executable, "tools/bench_pallas_op.py"], {}, 600, True),
    ("fid", "fid-trajectory-chip",
     [sys.executable, "tools/fid_trajectory.py", "--preset", "cifar10-cond",
      "--snapshots", "0,500,2000,5000", "--num_samples", "10000", "--kid"],
     {}, 1800, True),
    # dense early-phase ladder for the same conditional preset: the long
    # trajectory's tail oscillates (GAN non-monotonicity — why best-FID
    # retention exists); the improvement-dominated early phase is where
    # the ranking signal must show, and this row measures it at scale
    ("fid", "fid-trajectory-cond-early",
     [sys.executable, "tools/fid_trajectory.py", "--preset", "cifar10-cond",
      "--snapshots", "0,100,250,500,1000", "--num_samples", "10000",
      "--kid"], {}, 1500, True),
    # the CANONICAL feature path at the 50k contract, stand-in embedder
    # (VERDICT r4 #4): torch tower -> convert_torch_embedder -> evals
    ("fid", "fid-50k-canonical-npz",
     [sys.executable, "tools/canonical_50k.py"], {}, 1500, True),
    ("realdata", "realdata-celeba64",
     [sys.executable, "tools/bench_realdata.py"], {}, 1200, True),
    ("loader", "loader-ceiling", [sys.executable, "tools/bench_loader.py"],
     {}, 900, False),
    # the default wire format's ceiling (uint8 since r4 — prepare.py)
    ("loader", "loader-ceiling-uint8",
     [sys.executable, "tools/bench_loader.py", "--record_dtype", "uint8"],
     {}, 900, False),
    # multi-process shard-ownership scaling + the host-core budget behind
    # "can the loader feed the 32.6k b512 peak" (VERDICT r4 #2)
    ("loader", "loader-scale",
     [sys.executable, "tools/bench_loader_scale.py", "--processes", "1",
      "2"], {}, 900, False),
    # CPU-bound (no tunnel), last: ~20 min of host time. Regenerates the
    # cross-seed rank-stability evidence (BASELINE.md table).
    ("fid", "fid-seed-stability",
     [sys.executable, "tools/fid_seed_stability.py", "--platform", "cpu"],
     {"JAX_PLATFORMS": "cpu"}, 3600, False),
]


def run_step(section, label, argv, env, timeout, record):
    t0 = time.monotonic()
    row = {"date": _today(), "section": section, "label": label,
           "cmd": " ".join(argv)}
    try:
        res = subprocess.run(argv, cwd=REPO, env=dict(os.environ, **env),
                             timeout=timeout, capture_output=True, text=True)
        row["rc"] = res.returncode
        row["stderr_tail"] = (res.stderr or "")[-600:]
        parsed = []
        for line in (res.stdout or "").splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    parsed.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
        row["parsed"] = parsed
        m = re.search(r"ms_per_step=([0-9.]+)", res.stderr or "")
        if m:
            row["ms_per_step"] = float(m.group(1))
    except subprocess.TimeoutExpired:
        row["rc"] = None
        row["parsed"] = []
        row["stderr_tail"] = f"timed out after {timeout:.0f}s"
    row["elapsed_s"] = round(time.monotonic() - t0, 1)
    record(row)
    ok = row["rc"] == 0
    print(f"[capture_all] {label}: "
          f"{'ok' if ok else 'FAILED (' + str(row['rc']) + ')'} "
          f"in {row['elapsed_s']}s", file=sys.stderr)
    return ok, row


# ---------------------------------------------------------------------------
# Rendering: captures.jsonl -> marker-delimited doc blocks
# ---------------------------------------------------------------------------

def _load_captures():
    rows = []
    if os.path.exists(CAPTURES):
        with open(CAPTURES) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
    return rows


def _spread(values):
    """n / median / min / max over a value list (VERDICT r3 #5: best-of
    reporting alone hides the tunnel's run-to-run swing)."""
    vs = sorted(values)
    n = len(vs)
    med = vs[n // 2] if n % 2 else (vs[n // 2 - 1] + vs[n // 2]) / 2
    return {"n": n, "median": med, "min": vs[0], "max": vs[-1]}


def _best_bench_rows(rows):
    """Per label: best successful value (the tunnel swings 30%+ run-to-run;
    steady-state capability is the best capture, matching bench.py's own
    best-of-windows policy) PLUS the spread over every successful capture,
    so the best is presented against the distribution it came from.

    Attention-bearing configs stamp a kernel generation into their JSON
    (bench.py; pre-stamp history is gen 0) and only captures at the HIGHEST
    generation present for a label enter the best/spread — a median over
    mixed kernel generations describes no code that exists (VERDICT r4 #1:
    the published sagan64-attn median was the superseded kernel's)."""
    by_label = {}
    for r in rows:
        if r["section"] not in ("headline", "matrix") or r["rc"] != 0:
            continue
        for p in r.get("parsed", []):
            if p.get("value") is None:
                continue
            by_label.setdefault(r["label"], []).append((p, r))
    best = {}
    for label, entries in by_label.items():
        top_gen = max(p.get("gen", 0) for p, _ in entries)
        entries = [(p, r) for p, r in entries if p.get("gen", 0) == top_gen]
        # same contract for preset revisions (presets.py::PRESET_REVS):
        # spread over the current preset config only. Missing stamps
        # default to 1 — unlisted presets ARE revision 1, so pre-stamp
        # history of unchanged configs stays in the spread (only history
        # behind an explicit bump is retired).
        top_rev = max(p.get("rev", 1) for p, _ in entries)
        entries = [(p, r) for p, r in entries if p.get("rev", 1) == top_rev]
        cur = {"value": -1.0,
               # show the generation only where a stamp exists — non-
               # attention configs have no kernel-generation concept
               "gen": top_gen if any("gen" in p for p, _ in entries)
               else None,
               "rev": top_rev if any("rev" in p for p, _ in entries)
               else None}
        values = []
        for p, r in entries:
            values.append(p["value"])
            if p["value"] > cur["value"]:
                cur.update(
                    value=p["value"], unit=p.get("unit", ""),
                    vs=p.get("vs_baseline"), metric=p.get("metric", ""),
                    ms=r.get("ms_per_step"), date=r["date"])
        cur.update(_spread(values))
        best[label] = cur
    return best


def _attention_rows(rows):
    """Latest result per (form, seq): ms or the error row (an allocation
    failure IS the measurement — the dense wall). Returns (timing, memory)
    maps; memory rows come from tools/attention_memory.py (temp_mib)."""
    out = {}
    mem = {}
    # Timing rows are selected as PAIRS: per seq, the single harvest run
    # whose dense+flash measurements (which share one tunnel window) have
    # the lowest combined ms — a per-cell best-of would splice forms from
    # different windows and corrupt the dense/flash ratio the table exists
    # to show. Runs compete only within the HIGHEST kernel generation
    # present for that seq (bench_attention stamps ATTN_GEN into every
    # row; pre-tag history is gen 0), so measurements of superseded kernel
    # code never get published as the current kernels' numbers — the same
    # reason the memory branch keeps latest-only. A run with an error row
    # is only selected while no run of that generation has a complete pair
    # (the dense wall rows stay visible).
    pairs = {}   # seq -> {form: row} of the selected run
    for r in rows:
        if r["section"] != "attention":
            continue
        by_seq = {}
        for p in r.get("parsed", []):
            if "form" not in p or "seq" not in p:
                continue
            if r["label"] == "attn-memory":
                # memory rows are exact program properties of the CURRENT
                # kernels (the dense coefficient changed 8->6 bytes/S^2
                # with the precision policy) — keep the latest
                mem[(p["form"], p["seq"])] = dict(p, date=r["date"])
            else:
                by_seq.setdefault(p["seq"], {})[p["form"]] = \
                    dict(p, date=r["date"])
        def _score(cand):
            gen = max(p.get("gen", 0) for p in cand.values())
            oks = [p["ms"] for p in cand.values() if "ms" in p]
            # highest kernel generation first, then MOST ms-bearing forms
            # (a complete dense+flash pair must never lose to a single-form
            # run of the same generation just because the latter's sum(ms)
            # is smaller — advisor r4), then fastest window
            return (-gen, -len(oks), sum(oks))
        for seq, cand in by_seq.items():
            cur = pairs.get(seq)
            if cur is None or _score(cand) < _score(cur):
                pairs[seq] = cand
    for cand in pairs.values():
        for p in cand.values():
            out[(p["form"], p["seq"])] = p
    return out, mem


def _label_output_size(label):
    """Pixel resolution (H == W) of a bench label's workload, or None.

    The join key for the train table's workload-honest Mpx/s column
    (VERDICT Weak #2): resolution comes from the preset registry when the
    label IS a preset, else from the family token's trailing digits
    ("dcgan256-attn128-flash" -> 256 — the b<batch>/attn<res>/accum<k>
    tokens are knobs, not resolutions), with the cifar10 names pinned to
    their 32x32 workload.
    """
    try:
        from dcgan_tpu.presets import get_preset

        return get_preset(label).model.output_size
    except Exception:
        pass
    for tok in label.split("-"):
        if "cifar10" in tok:
            return 32
        m = re.fullmatch(r"([a-z]+)(\d+)", tok)
        if m and m.group(1) not in ("b", "attn", "accum", "x", "rev",
                                    "gen"):
            return int(m.group(2))
    return None


def _mpx_cell(label, img_per_sec):
    """Formatted Mpx/s (img/s x H x W / 1e6) or an em-dash."""
    size = _label_output_size(label)
    if not size or not isinstance(img_per_sec, (int, float)):
        return "—"
    return f"{img_per_sec * size * size / 1e6:.1f}"


def _render_roofline(rows):
    """Roofline group: matmul sweep (best per shape), step profile (best
    window = min step_ms), trainer hot loop (best + spread)."""
    shapes = {}      # (m, n) -> best tflops row (+date)
    profiles = []
    trainer = []
    bn_ops = {}      # shape -> LATEST jnp-vs-pallas row (a ratio has no
    #                  meaningful best-of; rows in one run share a window)
    for r in rows:
        if r["section"] != "roofline" or r["rc"] != 0:
            continue
        for p in r.get("parsed", []):
            if p.get("form") == "matmul":
                # older captures predate the K dim (square chains: K = N)
                key = (p["m"], p.get("k", p["n"]), p["n"])
                if key not in shapes or p["tflops"] > shapes[key]["tflops"]:
                    shapes[key] = dict(p, date=r["date"])
            elif p.get("form") == "bn_op":
                bn_ops[tuple(p["shape"])] = dict(p, date=r["date"])
            elif p.get("label") == "step-profile":
                profiles.append(dict(p, date=r["date"]))
            elif p.get("label") == "trainer-loop" and \
                    p.get("images_per_sec_chip"):
                trainer.append(dict(p, date=r["date"]))
    out = []
    if shapes:
        out += ["Roofline: sustained bf16 matmul rate (tools/"
                "matmul_rate.py, best per shape) — the "
                "MFU denominator, regenerated with every harvest:", "",
                "| shape (M×K×N) | TFLOP/s | ms/matmul | captured |",
                "|---|---|---|---|"]
        for (m, k, n) in sorted(shapes):
            p = shapes[(m, k, n)]
            out.append(f"| {m}×{k}×{n} | {p['tflops']} | "
                       f"{p['ms_per_matmul']} | {p['date']} |")
    by_preset = {}
    for p in profiles:
        by_preset.setdefault(p.get("preset", "headline"), []).append(p)
    head = by_preset.pop("headline", None)
    if head:
        best = min(head, key=lambda p: p["step_ms"])
        out += ["", f"Headline step profile (tools/step_profile.py, best "
                f"window of n={len(head)} capture(s), {best['date']}; "
                "scanned dispatch, batch "
                f"{best['batch']}): step {best['step_ms']} ms = forward "
                f"{best['fwd_ms']} ms + backward+opt "
                f"{best['bwd_opt_ms_derived']} ms (derived); G forward "
                f"alone {best['g_forward_ms']} ms, both Adam chains alone "
                f"{best['adam_ms']} ms."]
        if best.get("flops_per_step"):
            gflop = best["flops_per_step"] / 1e9
            out += [f"XLA cost model: {gflop:.1f} GFLOP and "
                    f"{best.get('bytes_accessed', 0) / 2**30:.2f} GiB "
                    "accessed per step "
                    f"(arithmetic intensity "
                    f"{best['flops_per_step'] / best['bytes_accessed']:.0f} "
                    "FLOP/byte) -> effective "
                    f"{best.get('tflops_effective', 0):.1f} TFLOP/s and "
                    f"{best.get('hbm_gbps_effective', 0):.0f} GB/s at the "
                    "best-window step time. See DESIGN.md \"Roofline\" for "
                    "the reading."]
    if by_preset:
        def _scan_tag(name, row):
            """In-step lax.scan annotation (VERDICT Weak #6). New captures
            carry a scan_trips stamp — step_profile now counts those
            programs through a fully-unrolled lowering, so their FLOP/bytes
            are trip-exact. Pre-stamp captures of scanning configs (the
            trip counts come from the preset registry) counted the scan
            body ONCE: flag them as undercounting instead of republishing
            the bad number as truth."""
            trips = row.get("scan_trips")
            if trips:
                mult = " ".join(f"×{v}" for v in trips.values())
                return f" (scanned {mult}, trip-exact)", None
            try:
                from dcgan_tpu.presets import get_preset

                cfg = get_preset(name)
                k = max(cfg.n_critic, cfg.grad_accum)
            except Exception:
                return "", None
            if k <= 1:
                return "", None
            return (f" (scanned ×{k})",
                    f"\\* {name}: this capture predates the scan-aware "
                    f"count — its GFLOP/GiB columns count the ×{k} scan "
                    f"body once (undercounted roughly ×{k}); re-harvest "
                    f"tools/step_profile.py for trip-exact numbers.")
        notes = []
        out += ["", "Per-family step profiles (same tool and knobs as each "
                "family's bench row; best window per family) — the measured "
                "numerator/denominator behind the binding-roof reading in "
                "DESIGN.md §1c:", "",
                "| family | step ms | fwd ms | GFLOP/step | GiB/step | "
                "eff TFLOP/s | eff GB/s | captured |",
                "|---|---|---|---|---|---|---|---|"]
        for name in sorted(by_preset):
            b = min(by_preset[name], key=lambda p: p["step_ms"])
            fl = b.get("flops_per_step")
            ba = b.get("bytes_accessed")
            tag, note = _scan_tag(name, b)
            if note:
                tag += "\\*"
                notes.append(note)
            out.append(
                f"| {name}{tag} (b{b['batch']}) | {b['step_ms']} | "
                f"{b['fwd_ms']} "
                + (f"| {fl / 1e9:.1f} | " if fl else "| — | "))
            out[-1] += (f"{ba / 2**30:.2f} | " if ba else "— | ")
            out[-1] += (f"{b.get('tflops_effective', 0):.1f} | "
                        f"{b.get('hbm_gbps_effective', 0):.0f} | "
                        f"{b['date']} |")
        for note in notes:
            out += ["", note]
    if bn_ops:
        date = max(p["date"] for p in bn_ops.values())
        out += ["", f"Op-level fused-BN+act, Pallas vs XLA (tools/"
                f"bench_pallas_op.py, fwd+bwd, latest run {date}) — the "
                "measurement behind use_pallas being a capability flag, "
                "not a perf flag (DESIGN.md §8b):", "",
                "| activation shape | XLA ms | Pallas ms | XLA/Pallas |",
                "|---|---|---|---|"]
        for shape in sorted(bn_ops):
            p = bn_ops[shape]
            out.append(f"| {list(shape)} | {p['jnp_ms']} | "
                       f"{p['pallas_ms']} | "
                       f"{p['ratio_jnp_over_pallas']}× |")
    if trainer:
        best = max(trainer, key=lambda p: p["images_per_sec_chip"])
        sp = _spread([p["images_per_sec_chip"] for p in trainer])
        out += ["", f"Real trainer hot loop (tools/bench_trainer_loop.py — "
                f"`python -m dcgan_tpu.train --synthetic` with a device-"
                f"cached batch pool, steps_per_call "
                f"{best['steps_per_call']}): best "
                f"{best['images_per_sec_chip']:.0f} img/s/chip "
                f"({best['ms_per_step']} ms/step, {best['date']}); median "
                f"{sp['median']:.0f} over n={sp['n']} run(s). Chip-bound "
                "regime: the synthetic pool isolates the loop from the "
                "tunneled host->device transport."]
    return out


def _render_block(path, block_lines):
    with open(path) as f:
        text = f.read()
    block = BEGIN + "\n" + "\n".join(block_lines) + "\n" + END
    if BEGIN in text:
        # repl as a callable: captured error text may contain backslash
        # sequences re.sub would misread as replacement escapes
        text = re.sub(re.escape(BEGIN) + r".*?" + re.escape(END),
                      lambda m: block, text, flags=re.S)
    else:
        text = text.rstrip() + "\n\n" + block + "\n"
    with open(path, "w") as f:
        f.write(text)


def render_docs() -> None:
    rows = _load_captures()

    bench = _best_bench_rows(rows)
    # inference (BENCH_MODE=sample) rows get their own table: their
    # "ms" is per ~1024-image dispatch, not per 64-image train step —
    # mixing the columns would misread as a 16x per-step slowdown
    train = {k: v for k, v in bench.items()
             if "sampler" not in v.get("metric", "")}
    sample = {k: v for k, v in bench.items()
              if "sampler" in v.get("metric", "")}
    lines = ["## Chip captures (tools/capture_all.py)", ""]

    def _sp(b):
        if b["n"] < 2:
            return f"(n={b['n']})"
        return (f"{b['median']:.0f} (n={b['n']}, "
                f"{b['min']:.0f}–{b['max']:.0f})")

    if train:
        lines += ["Best successful capture per config, with the spread of "
                  "ALL successful captures (median, n, min–max) — the "
                  "tunnel's throughput swings run-to-run and the best "
                  "column alone would hide it; see README \"Benchmarks\" "
                  "for methodology. Attention configs are tagged with the "
                  "kernel generation (ops/pallas_attention.py::ATTN_GEN) "
                  "their captures come from; best and spread include only "
                  "the highest generation on record, so both columns "
                  "describe the current kernel code. Mpx/s is the "
                  "workload-honest pixel rate (img/s × H×W): a 256² row "
                  "moves 16× the pixels of a 64² row per image, so its "
                  "img/s — and the vs-baseline ratio derived from it — "
                  "understates the work by that factor:", "",
                  "| Config | best img/s/chip | Mpx/s | "
                  "median (n, min–max) | ms/step | vs baseline | "
                  "captured |",
                  "|---|---|---|---|---|---|---|"]
        for label in sorted(train):
            b = train[label]
            ms = f"{b['ms']:.2f}" if b.get("ms") else "—"
            vs = f"{b['vs']:.2f}×" if b.get("vs") is not None else "—"
            tag = (f" (attn gen {b['gen']})" if b.get("gen") is not None
                   else "")
            if b.get("rev") and b["rev"] > 1:
                tag += f" (rev {b['rev']})"
            lines.append(f"| {label}{tag} | {b['value']} | "
                         f"{_mpx_cell(label, b['value'])} | {_sp(b)} | "
                         f"{ms} | {vs} | {b['date']} |")
    if sample:
        lines += ["", "Inference (sampler path, `BENCH_MODE=sample` — "
                  "ms is per generation dispatch at the batch named in "
                  "the metric, not per train step):", "",
                  "| Config | best img/s/chip | median (n, min–max) | "
                  "ms/dispatch | captured |", "|---|---|---|---|---|"]
        for label in sorted(sample):
            b = sample[label]
            ms = f"{b['ms']:.2f}" if b.get("ms") else "—"
            # same provenance tags as the train table: gen filtering
            # applies to these rows too, so it must be visible
            tag = (f" (attn gen {b['gen']})" if b.get("gen") is not None
                   else "")
            if b.get("rev") and b["rev"] > 1:
                tag += f" (rev {b['rev']})"
            lines.append(f"| {label}{tag} | {b['value']} | {_sp(b)} | {ms} "
                         f"| {b['date']} |")
    else:
        lines += ["No successful chip captures yet (tunnel down every "
                  "attempt so far — every attempt is logged in "
                  "`tools/captures.jsonl`)."]
    realdata = [r for r in rows
                if r["section"] == "realdata" and r["rc"] == 0
                and r.get("parsed")]
    if realdata:
        last = realdata[-1]  # latest complete run (rows are a matched set)
        lines += ["", f"Real-data loader-vs-chip balance "
                  f"(tools/bench_realdata.py, {last['date']}) — "
                  "TUNNEL-BOUND regime: the real-record rows measure the "
                  "tunneled host->device transport (~15-60 MB/s), not the "
                  "loader (CPU-bound ceilings above) or the chip "
                  "(chip-bound rows above); on a PCIe-attached host this "
                  "ratio is the loader-vs-chip balance instead:", "",
                  "| Source | img/s | vs synthetic |", "|---|---|---|"]
        for p in last["parsed"]:
            if "source" in p:
                lines.append(f"| {p['source']} | {p['value']} | "
                             f"{p.get('vs_synthetic', '—')} |")
    # canonical-path certification row (VERDICT r4 #4): its own paragraph,
    # not a trajectory table (one score, no steps axis)
    canon = [(p, r["date"]) for r in rows
             if r["label"] == "fid-50k-canonical-npz" and r["rc"] == 0
             for p in r.get("parsed", []) if "fid" in p]
    if canon:
        p, date = canon[-1]
        lines += ["", f"Canonical feature path at the 50k contract "
                  f"(tools/canonical_50k.py, {date}): a random-weight "
                  "torch conv tower "
                  f"({p.get('embedder', '?')}) exported, converted through "
                  "tools/convert_torch_embedder.py's .npz schema, and "
                  "scored end-to-end by `python -m dcgan_tpu.evals "
                  f"--feature_npz ...` over {p['num_samples']:,} samples "
                  f"per side (feature dim {p.get('feature_dim')}, "
                  f"{p.get('elapsed_s', '?')} s wall) — FID "
                  f"{p['fid']:.4f}, KID "
                  f"{(p['kid'] or 0):.6f}. The score itself is arbitrary "
                  "(random embedder, random generator); the row certifies "
                  "that the NON-surrogate eval path — the one real "
                  "Inception/trained-tower weights ride — executes the "
                  "full contract. See README \"Canonical FID\" for the "
                  "one-command recipe with real weights."]
    fid_rows = [r for r in rows
                if r["section"] == "fid" and r["rc"] == 0
                and r["label"] != "fid-50k-canonical-npz"
                and any("fid" in p for p in r.get("parsed", []))]
    # latest complete trajectory PER LABEL (each label is its own ladder —
    # e.g. the long oscillating-tail run vs the dense early-phase run)
    latest_by_label = {}
    for r in fid_rows:
        latest_by_label[r["label"]] = r
    for label in sorted(latest_by_label):
        last = latest_by_label[label]
        lines += ["", f"Chip FID/KID trajectory ({last['label']}, surrogate "
                  f"features, {last['date']} — `{last['cmd']}`):", "",
                  "| Step | surrogate FID | KID (×10³) |", "|---|---|---|"]
        for p in last["parsed"]:
            if "fid" in p:
                kid = (f"{p['kid'] * 1e3:.3f}" if p.get("kid") is not None
                       else "—")  # --kid is optional in fid_trajectory.py
                lines.append(f"| {p['step']} | {p['fid']:.4f} | {kid} |")
        summ = next((p for p in last["parsed"] if "monotonic" in p), None)
        if summ:
            lines += ["", f"monotonic={summ['monotonic']}, "
                      f"Spearman(steps, FID)="
                      f"{summ['spearman_steps_vs_fid']:.2f} over "
                      f"{summ['snapshots']} snapshots."]
    loader = [(p, r["date"]) for r in rows
              if r["section"] == "loader" and r["rc"] == 0
              for p in r["parsed"] if "images_per_sec" in p]
    if loader:
        # best capture per wire format, like the bench rows — with the
        # spread shown: the 1-core host swings ~2x run-to-run (and
        # harvests often share the core), which the best alone would hide
        lines += ["", "Loader re-check (CPU-bound, one host core), per "
                  "wire format:"]
        dtypes = sorted({p.get("record_dtype", "?") for p, _ in loader})
        for dt in dtypes:
            rows_dt = [(p, d) for p, d in loader
                       if p.get("record_dtype", "?") == dt]
            peak, date = max(rows_dt, key=lambda v: v[0]["images_per_sec"])
            sp = _spread([p["images_per_sec"] for p, _ in rows_dt])
            lines += [f"- {dt}: best {peak['images_per_sec']:.0f} img/s "
                      f"({peak.get('threads', '?')} threads, {date}); "
                      f"median {sp['median']:.0f}, range "
                      f"{sp['min']:.0f}–{sp['max']:.0f} over n={sp['n']} "
                      "captures."]
    scale = [(p, r["date"]) for r in rows
             if r["section"] == "loader" and r["rc"] == 0
             for p in r.get("parsed", [])
             if p.get("label") == "loader-scale"]
    if scale:
        cores = scale[-1][0].get("cores_visible", "?")
        lines += ["", f"Loader scaling by process-level shard ownership "
                  f"(tools/bench_loader_scale.py — M loader processes, "
                  f"each owning its `shard_for_process` slice, one shared "
                  f"measurement window; this host exposes {cores} "
                  "core(s), `os.sched_getaffinity`):", "",
                  "| processes | aggregate img/s | per-process img/s | "
                  "captured |", "|---|---|---|---|"]
        best_by_m = {}
        for p, d in scale:
            m = p["processes"]
            if m not in best_by_m or p["aggregate_images_per_sec"] > \
                    best_by_m[m][0]["aggregate_images_per_sec"]:
                best_by_m[m] = (p, d)
        for m in sorted(best_by_m):
            p, d = best_by_m[m]
            pp = ", ".join(f"{v:.0f}"
                           for v in p["per_process_images_per_sec"])
            lines.append(f"| {m} | {p['aggregate_images_per_sec']:.0f} | "
                         f"{pp} | {d} |")
        # host-core budget (VERDICT r4 #2): the per-core uint8 rate vs the
        # measured chip peak, derived from this same captures log so the
        # paragraph regenerates with every harvest
        # per-core uint8 rate: best of the single-thread-pool ceilings AND
        # the scale tool's M=1 row (same quantity, measured on the quiet
        # host through the shard-ownership path)
        uint8 = [p["images_per_sec"] for p, _ in loader
                 if p.get("record_dtype") == "uint8"]
        uint8 += [v for p, _ in scale
                  if p["processes"] == 1 and p.get("record_dtype") == "uint8"
                  for v in p["per_process_images_per_sec"]]

        def _vals(label):
            return [p["value"] for r in rows
                    if r["label"] == label and r["rc"] == 0
                    for p in r.get("parsed", []) if p.get("value")]

        peak_rows = _vals("dcgan64-b512")
        b64_rows = _vals("dcgan64-headline")
        if uint8 and peak_rows:
            per_core = max(uint8)
            peak = max(peak_rows)
            need = int((peak + per_core - 1) // per_core) if per_core else 0
            lines += ["", f"**Host-core budget at the peak-batch regime:** "
                      f"the b512 chip peak consumes {peak:,.0f} img/s/chip "
                      f"while one host core decodes uint8 records at "
                      f"{per_core:,.0f} img/s best — so the peak regime "
                      f"needs ~{need} loader processes on {need} host "
                      "cores per chip (per-process shard ownership; no "
                      "shared state). This build host exposes "
                      f"{cores} core(s) (the flat aggregate above is that "
                      "measurement, not a design ceiling); production TPU "
                      "hosts expose tens to hundreds."]
            if b64_rows:
                b64 = max(b64_rows)
                n64 = int((b64 + per_core - 1) // per_core) if per_core \
                    else 0
                lines[-1] += (
                    f" At the reference's batch-64 contract "
                    f"({b64:,.0f} img/s best) {n64} core(s) suffice at the "
                    "best-capture loader rate.")

    # roofline section (VERDICT r3 #1/#4): sustained matmul rate, step
    # cost/profile, and the real trainer loop measured as one group
    roof_lines = _render_roofline(rows)
    if roof_lines:
        lines += [""] + roof_lines
    _render_block(BASELINE_MD, lines)

    attn, attn_mem = _attention_rows(rows)
    lines = ["### Measured attention crossovers (chip)", ""]
    if attn:
        lines += ["| Form | S | ms (fwd+bwd) | status | captured |",
                  "|---|---|---|---|---|"]
        for (form, seq) in sorted(attn, key=lambda k: (k[1], k[0])):
            p = attn[(form, seq)]
            if "ms" in p:
                lines.append(f"| {form} | {seq} | {p['ms']:.2f} | ok | "
                             f"{p['date']} |")
            else:
                # table-safe error: first line only, ANSI stripped, bounded
                err = re.sub(r"\x1b\[[0-9;]*m", "",
                             p.get("error", "failed")).splitlines()[0][:90]
                lines.append(f"| {form} | {seq} | — | {err} | {p['date']} |")
    else:
        lines += ["Chip pending — the tunnel has not answered during a "
                  "capture window yet. CPU-side scaling evidence is in the "
                  "table above; `python tools/capture_all.py` harvests this "
                  "table on the next live burst."]
    if attn_mem:
        lines += ["", "Scratch-HBM requirement per compiled fwd+bwd "
                  "program (`compiled.memory_analysis()`, "
                  "tools/attention_memory.py — exact program requirements, "
                  "no execution involved; a compile failure at a size whose "
                  "dense requirement exceeds HBM IS the memory wall):", "",
                  "| Form | S | temp HBM (MiB) | captured |",
                  "|---|---|---|---|"]
        for (form, seq) in sorted(attn_mem, key=lambda k: (k[1], k[0])):
            p = attn_mem[(form, seq)]
            if p.get("temp_mib") is not None:
                lines.append(f"| {form} | {seq} | {p['temp_mib']} | "
                             f"{p['date']} |")
            else:
                err = re.sub(r"\x1b\[[0-9;]*m", "",
                             p.get("error", "failed")).splitlines()[0][:70]
                lines.append(f"| {form} | {seq} | — ({err}) | {p['date']} |")
    _render_block(DESIGN_MD, lines)
    print(f"[capture_all] rendered {len(bench)} bench row(s), "
          f"{len(attn)} attention row(s)", file=sys.stderr)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--only", nargs="+", default=None,
                   help="run only these sections "
                        "(headline matrix attention fid realdata loader)")
    p.add_argument("--skip", nargs="+", default=[],
                   help="skip these sections")
    p.add_argument("--labels", nargs="+", default=None,
                   help="run only these step labels (targeted re-captures; "
                        "composes with --only/--skip)")
    p.add_argument("--probe_timeout", type=float, default=60.0)
    p.add_argument("--render-only", action="store_true")
    args = p.parse_args(argv)

    if args.render_only:
        render_docs()
        return

    os.makedirs(os.path.dirname(CAPTURES), exist_ok=True)

    def record(row):
        with open(CAPTURES, "a") as f:
            f.write(json.dumps(row) + "\n")

    tunnel_ok: bool | None = None  # None = not yet probed
    ran = failures = 0
    for section, label, argv_, env, timeout, needs_tunnel in STEPS:
        if args.only and section not in args.only:
            continue
        if section in args.skip:
            continue
        if args.labels and label not in args.labels:
            continue
        if needs_tunnel:
            if tunnel_ok is None:
                print(f"[capture_all] probing tunnel "
                      f"({args.probe_timeout:.0f}s cap)...", file=sys.stderr)
                tunnel_ok = probe(args.probe_timeout)
                print(f"[capture_all] tunnel "
                      f"{'LIVE' if tunnel_ok else 'dead'}", file=sys.stderr)
            if not tunnel_ok:
                record({"date": _today(), "section": section, "label": label,
                        "cmd": " ".join(argv_), "rc": None, "parsed": [],
                        "stderr_tail": "skipped: tunnel dead at probe",
                        "elapsed_s": 0.0, "skipped": True})
                print(f"[capture_all] {label}: skipped (tunnel dead)",
                      file=sys.stderr)
                continue
        ok, row = run_step(section, label, argv_, env, timeout, record)
        ran += 1
        if not ok:
            failures += 1
            if needs_tunnel:
                tunnel_ok = None  # burst may have died: re-probe next step
    render_docs()
    print(f"[capture_all] done: {ran} step(s) run, {failures} failed",
          file=sys.stderr)


if __name__ == "__main__":
    main()
