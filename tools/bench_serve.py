"""Serving-plane load bench: bursty trace replay, cold vs warm start.

ROADMAP item 1's measurement: the continuous-batching sampler server
(`dcgan_tpu/serve`, ISSUE 9) replaying a heavy-traffic arrival trace —
Poisson steady-state with a burst segment at several times the base rate
— against one persistent compile cache, twice:

  arm "cold": fresh cache dir — every bucket program compiles, the cache
      is primed (the first-deploy cost);
  arm "warm": same cache dir — the restart path: bucket programs
      deserialize, cold-start drops to restore + bounded IO.

and emits ONE BENCH-style JSON line: per-arm p50/p99 request latency,
samples/sec/chip, queue depth, the cold-start breakdown, compile-cache
hit counters, and the pass/fail of the invariants the serving plane
exists to hold:

  - zero sampler recompiles after the AOT bucket warmup on BOTH arms
    (every served batch hits a precompiled bucket);
  - the warm arm's cache has zero misses and nonzero hits (the restart
    actually deserialized);
  - every submitted request completed (the drain contract under a finite
    trace).

`--smoke` shrinks the model, trace, and budgets to the tier-1 pin
(tests/test_tools.py, the chaos-marker pattern); the full-size run is
the standalone capture. CPU-only by design — the serving economics
story on chips comes from the module tracks; this tool certifies the
MECHANISM.

    JAX_PLATFORMS=cpu python tools/bench_serve.py [--smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_ckpt(ckpt_dir: str, workdir: str, *, size: int, batch: int,
               timeout: float) -> None:
    """One tiny trainer run to produce the checkpoint both arms serve."""
    argv = [
        sys.executable, "-m", "dcgan_tpu.train",
        "--synthetic", "--max_steps", "1",
        "--batch_size", str(batch), "--output_size", str(size),
        "--gf_dim", "8", "--df_dim", "8",
        "--sample_every_steps", "0", "--activation_summary_steps", "0",
        "--save_summaries_secs", "0", "--save_model_secs", "1e9",
        "--no_tensorboard",
        "--checkpoint_dir", ckpt_dir,
        "--sample_dir", os.path.join(workdir, "samples"),
    ]
    res = subprocess.run(argv, cwd=REPO,
                         env=dict(os.environ, JAX_PLATFORMS="cpu"),
                         capture_output=True, text=True, timeout=timeout)
    if res.returncode != 0:
        raise RuntimeError(f"checkpoint trainer rc={res.returncode}: "
                           f"{(res.stderr or '')[-800:]}")


def make_trace(path: str, *, requests: int, rps: float, burst_factor: float,
               burst_frac: float, max_images: int, seed: int) -> dict:
    """Deterministic Poisson arrivals with a mid-trace burst segment at
    burst_factor x the base rate — the 'heavy traffic' shape: steady
    load, then a spike that must ride the batcher + backpressure instead
    of a queue blowup. Returns the trace summary."""
    rng = np.random.default_rng(seed)
    burst_start = int(requests * (0.5 - burst_frac / 2))
    burst_end = int(requests * (0.5 + burst_frac / 2))
    t = 0.0
    arrivals = []
    for i in range(requests):
        rate = rps * (burst_factor if burst_start <= i < burst_end else 1.0)
        t += float(rng.exponential(1e3 / rate))
        arrivals.append({"t_ms": t,
                         "num_images": int(rng.integers(1, max_images + 1))})
    with open(path, "w") as f:
        json.dump({"arrivals": arrivals}, f)
    return {"requests": requests,
            "images": sum(a["num_images"] for a in arrivals),
            "span_ms": round(t, 1),
            "burst": {"factor": burst_factor,
                      "requests": burst_end - burst_start}}


def _run_arm(name: str, *, ckpt_dir: str, cache_dir: str, trace: str,
             workdir: str, max_batch: int, max_wait_ms: float,
             timeout: float) -> dict:
    report = os.path.join(workdir, f"report-{name}.json")
    argv = [
        sys.executable, "-m", "dcgan_tpu.serve",
        "--checkpoint_dir", ckpt_dir,
        "--compile_cache_dir", cache_dir,
        "--trace", trace,
        "--max_batch", str(max_batch),
        "--max_wait_ms", str(max_wait_ms),
        "--report", report,
        "--platform", "cpu",
    ]
    t0 = time.perf_counter()
    res = subprocess.run(argv, cwd=REPO,
                         env=dict(os.environ, JAX_PLATFORMS="cpu"),
                         capture_output=True, text=True, timeout=timeout)
    if res.returncode != 0:
        raise RuntimeError(f"{name} serve rc={res.returncode}: "
                           f"{(res.stdout or '')[-400:]} "
                           f"{(res.stderr or '')[-800:]}")
    with open(report) as f:
        row = json.load(f)
    row["process_wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    return row


def _arm_summary(r: dict) -> dict:
    return {
        "p50_ms": r.get("serve/p50_ms"),
        "p99_ms": r.get("serve/p99_ms"),
        "samples_per_sec_chip": round(
            r.get("serve/samples_per_sec", 0.0) / max(1, r["devices"]), 2),
        "queue_depth_max": r.get("serve/queue_depth_max"),
        "pad_frac": round(r.get("serve/pad_frac", 0.0), 4),
        "batches": r.get("serve/batches"),
        "completed": r.get("serve/completed"),
        "dropped": r.get("serve/dropped"),
        "cold_start_ms": round(r.get("serve/cold_start_ms", 0.0), 1),
        "restore_ms": round(r.get("serve/restore_ms", 0.0), 1),
        "warmup_ms": round(r.get("serve/warmup_ms", 0.0), 1),
        "recompiles_after_warmup": r.get("serve/recompiles_after_warmup"),
        "cache": {k: int(r.get(f"perf/compile_cache_{k}", 0))
                  for k in ("requests", "hits", "misses")},
        "process_wall_ms": r["process_wall_ms"],
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model + short trace (the tier-1 pin)")
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-subprocess budget (seconds)")
    args = ap.parse_args()
    if args.smoke:
        size, batch, requests, rps, max_images = 16, 8, 24, 40.0, 8
        max_batch, max_wait_ms = 16, 5.0
    else:
        size, batch, requests, rps, max_images = 64, 16, 200, 50.0, 16
        max_batch, max_wait_ms = 64, 10.0

    with tempfile.TemporaryDirectory() as tmp:
        ckpt = os.path.join(tmp, "ckpt")
        cache = os.path.join(tmp, "compile-cache")
        trace = os.path.join(tmp, "trace.json")
        _make_ckpt(ckpt, tmp, size=size, batch=batch, timeout=args.timeout)
        trace_meta = make_trace(trace, requests=requests, rps=rps,
                                burst_factor=8.0, burst_frac=0.25,
                                max_images=max_images, seed=0)
        cold = _run_arm("cold", ckpt_dir=ckpt, cache_dir=cache, trace=trace,
                        workdir=tmp, max_batch=max_batch,
                        max_wait_ms=max_wait_ms, timeout=args.timeout)
        warm = _run_arm("warm", ckpt_dir=ckpt, cache_dir=cache, trace=trace,
                        workdir=tmp, max_batch=max_batch,
                        max_wait_ms=max_wait_ms, timeout=args.timeout)

    c, w = _arm_summary(cold), _arm_summary(warm)
    checks = {
        # every served batch hit a precompiled bucket — on both arms
        "cold_zero_recompiles_after_warmup":
            c["recompiles_after_warmup"] == 0,
        "warm_zero_recompiles_after_warmup":
            w["recompiles_after_warmup"] == 0,
        # the warm restart actually deserialized from the primed cache
        "warm_has_hits": w["cache"]["hits"] > 0,
        "warm_zero_misses": w["cache"]["misses"] == 0,
        "cold_has_misses": c["cache"]["misses"] > 0,
        # finite trace + drain: nothing lost, nothing left queued
        "cold_all_completed": c["completed"] == requests
                              and c["dropped"] == 0,
        "warm_all_completed": w["completed"] == requests
                              and w["dropped"] == 0,
        "latency_percentiles_present":
            bool(c["p50_ms"] and c["p99_ms"] and w["p50_ms"]
                 and w["p99_ms"]),
    }
    row = {
        "label": "bench-serve",
        "platform": "cpu",
        "model": f"dcgan{size}",
        "buckets": cold.get("buckets"),
        "trace": trace_meta,
        "cold": c,
        "warm": w,
        "speedup": {
            "warmup_ms": round(c["warmup_ms"] / max(w["warmup_ms"], 1e-9),
                               2),
            "cold_start_ms": round(
                c["cold_start_ms"] / max(w["cold_start_ms"], 1e-9), 2),
        },
        "checks": checks,
        "ok": all(checks.values()),
    }
    print(json.dumps(row))
    sys.exit(0 if row["ok"] else 1)


if __name__ == "__main__":
    main()
