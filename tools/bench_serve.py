"""Serving-plane load bench: bursty trace replay, cold vs warm start.

ROADMAP item 1's measurement: the continuous-batching sampler server
(`dcgan_tpu/serve`, ISSUE 9) replaying a heavy-traffic arrival trace —
Poisson steady-state with a burst segment at several times the base rate
— against one persistent compile cache, twice:

  arm "cold": fresh cache dir — every bucket program compiles, the cache
      is primed (the first-deploy cost);
  arm "warm": same cache dir — the restart path: bucket programs
      deserialize, cold-start drops to restore + bounded IO.

and emits ONE BENCH-style JSON line: per-arm p50/p99 request latency,
samples/sec/chip, queue depth, the cold-start breakdown, compile-cache
hit counters, and the pass/fail of the invariants the serving plane
exists to hold:

  - zero sampler recompiles after the AOT bucket warmup on BOTH arms
    (every served batch hits a precompiled bucket);
  - the warm arm's cache has zero misses and nonzero hits (the restart
    actually deserialized);
  - every submitted request completed (the drain contract under a finite
    trace).

`--fleet N` (ISSUE 19) swaps the cold/warm pair for a SOLO vs FLEET
comparison over the same burst trace: one bare server vs N replicas
behind the failover router — with live fire in the fleet arm: a chaos
fault kills replica 1 mid-trace (must become a failover, zero failed
requests) and a newly finalized checkpoint step is injected on disk
mid-trace for the promotion watcher to hot-swap onto the survivors
(must be recompile-free: compile_requests_delta == 0 per survivor).
One BENCH line: per-arm p50/p99, dropped/failed, recompile counters,
and the promotion swap time.

`--smoke` shrinks the model, trace, and budgets to the tier-1 pin
(tests/test_tools.py, the chaos-marker pattern); the full-size run is
the standalone capture. CPU-only by design — the serving economics
story on chips comes from the module tracks; this tool certifies the
MECHANISM.

    JAX_PLATFORMS=cpu python tools/bench_serve.py [--smoke] [--fleet 3]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_ckpt(ckpt_dir: str, workdir: str, *, size: int, batch: int,
               timeout: float, max_steps: int = 1) -> None:
    """One tiny trainer run to produce the checkpoint both arms serve."""
    argv = [
        sys.executable, "-m", "dcgan_tpu.train",
        "--synthetic", "--max_steps", str(max_steps),
        "--batch_size", str(batch), "--output_size", str(size),
        "--gf_dim", "8", "--df_dim", "8",
        "--sample_every_steps", "0", "--activation_summary_steps", "0",
        "--save_summaries_secs", "0", "--save_model_secs", "1e9",
        "--no_tensorboard",
        "--checkpoint_dir", ckpt_dir,
        "--sample_dir", os.path.join(workdir, "samples"),
    ]
    res = subprocess.run(argv, cwd=REPO,
                         env=dict(os.environ, JAX_PLATFORMS="cpu"),
                         capture_output=True, text=True, timeout=timeout)
    if res.returncode != 0:
        raise RuntimeError(f"checkpoint trainer rc={res.returncode}: "
                           f"{(res.stderr or '')[-800:]}")


def make_trace(path: str, *, requests: int, rps: float, burst_factor: float,
               burst_frac: float, max_images: int, seed: int) -> dict:
    """Deterministic Poisson arrivals with a mid-trace burst segment at
    burst_factor x the base rate — the 'heavy traffic' shape: steady
    load, then a spike that must ride the batcher + backpressure instead
    of a queue blowup. Returns the trace summary."""
    rng = np.random.default_rng(seed)
    burst_start = int(requests * (0.5 - burst_frac / 2))
    burst_end = int(requests * (0.5 + burst_frac / 2))
    t = 0.0
    arrivals = []
    for i in range(requests):
        rate = rps * (burst_factor if burst_start <= i < burst_end else 1.0)
        t += float(rng.exponential(1e3 / rate))
        arrivals.append({"t_ms": t,
                         "num_images": int(rng.integers(1, max_images + 1))})
    with open(path, "w") as f:
        json.dump({"arrivals": arrivals}, f)
    return {"requests": requests,
            "images": sum(a["num_images"] for a in arrivals),
            "span_ms": round(t, 1),
            "burst": {"factor": burst_factor,
                      "requests": burst_end - burst_start}}


def _run_arm(name: str, *, ckpt_dir: str, cache_dir: str, trace: str,
             workdir: str, max_batch: int, max_wait_ms: float,
             timeout: float) -> dict:
    report = os.path.join(workdir, f"report-{name}.json")
    argv = [
        sys.executable, "-m", "dcgan_tpu.serve",
        "--checkpoint_dir", ckpt_dir,
        "--compile_cache_dir", cache_dir,
        "--trace", trace,
        "--max_batch", str(max_batch),
        "--max_wait_ms", str(max_wait_ms),
        "--report", report,
        "--platform", "cpu",
    ]
    t0 = time.perf_counter()
    res = subprocess.run(argv, cwd=REPO,
                         env=dict(os.environ, JAX_PLATFORMS="cpu"),
                         capture_output=True, text=True, timeout=timeout)
    if res.returncode != 0:
        raise RuntimeError(f"{name} serve rc={res.returncode}: "
                           f"{(res.stdout or '')[-400:]} "
                           f"{(res.stderr or '')[-800:]}")
    with open(report) as f:
        row = json.load(f)
    row["process_wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    return row


def _inject_step(donor_dir: str, serve_dir: str, step: int) -> None:
    """Deliver `step` into `serve_dir` the way a trainer would: integrity
    sidecars first, then the step dir copied under a tmp name and RENAMED
    in (the Orbax finalization contract) — the fleet's promotion watcher
    can never see a half-copied step."""
    import shutil

    integ = os.path.join(donor_dir, "integrity")
    if os.path.isdir(integ):
        dst = os.path.join(serve_dir, "integrity")
        os.makedirs(dst, exist_ok=True)
        for name in os.listdir(integ):
            if name.startswith(f"{step}."):
                shutil.copy2(os.path.join(integ, name),
                             os.path.join(dst, name))
    tmp = os.path.join(serve_dir, f"tmp.promote.{step}")
    shutil.copytree(os.path.join(donor_dir, str(step)), tmp)
    os.rename(tmp, os.path.join(serve_dir, str(step)))


def _run_fleet_arm(*, replicas: int, ckpt_dir: str, donor_dir: str,
                   cache_dir: str, trace: str, workdir: str,
                   max_batch: int, max_wait_ms: float,
                   timeout: float) -> dict:
    """The live-fire arm: N replicas replay the trace while a chaos
    fault kills replica 1 mid-trace and a newly finalized step-2
    checkpoint is injected for the promotion watcher. Orchestrated via
    Popen (the injection must land WHILE the fleet serves); ends with a
    SIGTERM drain once the promotion is observed."""
    import signal
    import threading

    report = os.path.join(workdir, "report-fleet.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["DCGAN_CHAOS"] = json.dumps(
        {"fault_replica": 1, "replica_kill_at_dispatch": 2})
    argv = [
        sys.executable, "-m", "dcgan_tpu.serve",
        "--checkpoint_dir", ckpt_dir,
        "--compile_cache_dir", cache_dir,
        "--fleet", str(replicas),
        "--watch_promotions", "--watch_interval_secs", "0.25",
        "--trace", trace,
        "--max_batch", str(max_batch),
        "--max_wait_ms", str(max_wait_ms),
        "--report", report,
        "--platform", "cpu",
    ]
    t0 = time.perf_counter()
    proc = subprocess.Popen(argv, cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    lines = []
    reader = threading.Thread(
        target=lambda: [lines.append(l) for l in proc.stdout], daemon=True)
    reader.start()

    def _wait_for(token: str, secs: float) -> None:
        deadline = time.monotonic() + secs
        while time.monotonic() < deadline \
                and not any(token in l for l in lines):
            if proc.poll() is not None:
                break
            time.sleep(0.1)
        if not any(token in l for l in lines):
            raise RuntimeError(
                f"fleet arm never saw {token!r}: {''.join(lines)[-1200:]}")

    try:
        _wait_for("warm: serving", timeout)
        _wait_for("replica 1 UNHEALTHY", 60)
        _inject_step(donor_dir, ckpt_dir, 2)
        _wait_for("serve fleet: promoted", 120)
        time.sleep(1.0)  # some post-promotion load on the new weights
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=timeout)
    finally:
        if proc.poll() is None:
            proc.kill()
    reader.join(timeout=10)
    if rc != 0:
        raise RuntimeError(f"fleet serve rc={rc}: "
                           f"{''.join(lines)[-1200:]}")
    with open(report) as f:
        row = json.load(f)
    row["process_wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    return row


def _arm_summary(r: dict) -> dict:
    return {
        "p50_ms": r.get("serve/p50_ms"),
        "p99_ms": r.get("serve/p99_ms"),
        "samples_per_sec_chip": round(
            r.get("serve/samples_per_sec", 0.0) / max(1, r["devices"]), 2),
        "queue_depth_max": r.get("serve/queue_depth_max"),
        "pad_frac": round(r.get("serve/pad_frac", 0.0), 4),
        "batches": r.get("serve/batches"),
        "completed": r.get("serve/completed"),
        "dropped": r.get("serve/dropped"),
        "cold_start_ms": round(r.get("serve/cold_start_ms", 0.0), 1),
        "restore_ms": round(r.get("serve/restore_ms", 0.0), 1),
        "warmup_ms": round(r.get("serve/warmup_ms", 0.0), 1),
        "recompiles_after_warmup": r.get("serve/recompiles_after_warmup"),
        "cache": {k: int(r.get(f"perf/compile_cache_{k}", 0))
                  for k in ("requests", "hits", "misses")},
        "process_wall_ms": r["process_wall_ms"],
    }


def _with_idle_tail(src: str, dst: str, *, every_ms: float,
                    count: int) -> None:
    """Copy a trace and append a low-rate single-image tail: headroom
    for the fleet arm's mid-trace orchestration (kill -> inject ->
    promote) so the process cannot drain before the promotion lands.
    The arm is SIGTERMed once the promotion is observed, so the tail's
    length bounds the wait, not the runtime."""
    with open(src) as fh:
        arrivals = json.load(fh)["arrivals"]
    t = arrivals[-1]["t_ms"]
    for _ in range(count):
        t += every_ms
        arrivals.append({"t_ms": t, "num_images": 1})
    with open(dst, "w") as fh:
        json.dump({"arrivals": arrivals}, fh)


def _run_fleet_bench(args, *, size: int, batch: int, requests: int,
                     rps: float, max_images: int, max_batch: int,
                     max_wait_ms: float) -> dict:
    """The --fleet comparison: a bare server vs N replicas over the same
    burst trace, with the kill + promotion live fire in the fleet arm."""
    import shutil

    with tempfile.TemporaryDirectory() as tmp:
        ckpt = os.path.join(tmp, "ckpt")
        donor = os.path.join(tmp, "donor")
        cache = os.path.join(tmp, "compile-cache")
        trace = os.path.join(tmp, "trace.json")
        trace_fleet = os.path.join(tmp, "trace-fleet.json")
        _make_ckpt(ckpt, tmp, size=size, batch=batch, timeout=args.timeout)
        # the donor lineage: resume the same run one step further — its
        # step-2 dir is the "newly finalized" step injected mid-trace
        shutil.copytree(ckpt, donor)
        _make_ckpt(donor, tmp, size=size, batch=batch,
                   timeout=args.timeout, max_steps=2)
        trace_meta = make_trace(trace, requests=requests, rps=rps,
                                burst_factor=8.0, burst_frac=0.25,
                                max_images=max_images, seed=0)
        _with_idle_tail(trace, trace_fleet, every_ms=400.0, count=150)
        solo = _run_arm("solo", ckpt_dir=ckpt, cache_dir=cache,
                        trace=trace, workdir=tmp, max_batch=max_batch,
                        max_wait_ms=max_wait_ms, timeout=args.timeout)
        fleet = _run_fleet_arm(replicas=args.fleet, ckpt_dir=ckpt,
                               donor_dir=donor, cache_dir=cache,
                               trace=trace_fleet, workdir=tmp,
                               max_batch=max_batch,
                               max_wait_ms=max_wait_ms,
                               timeout=args.timeout)

    s, f = _arm_summary(solo), _arm_summary(fleet)
    fl = fleet["fleet"]
    last = fl["promotions"][-1] if fl["promotions"] else []
    survivors = sorted(i for i in range(args.fleet) if i != 1)
    checks = {
        # the solo arm replays the finite burst trace to completion
        "solo_all_completed": s["completed"] == requests
                              and s["dropped"] == 0,
        # the kill became a failover, not client-visible failures
        "fleet_zero_failed": fleet["failed"] == 0,
        "fleet_all_submitted_completed":
            fleet["completed"] == fleet["submitted"]
            and f["dropped"] == 0,
        "fleet_killed_replica_drained":
            any(i == 1 for i, _ in fl["unhealthy"]),
        # the watcher promoted exactly the survivors to the new step
        "fleet_promoted_survivors":
            sorted(r.get("replica", -1) for r in last) == survivors
            and all("error" not in r and r.get("step") == 2
                    for r in last),
        "fleet_promotion_zero_recompiles":
            all(r.get("compile_requests_delta") == 0 for r in last),
        "zero_recompiles_after_warmup":
            s["recompiles_after_warmup"] == 0
            and f["recompiles_after_warmup"] == 0,
        "latency_percentiles_present":
            bool(s["p50_ms"] and s["p99_ms"] and f["p50_ms"]
                 and f["p99_ms"]),
    }
    return {
        "label": "bench-serve-fleet",
        "platform": "cpu",
        "model": f"dcgan{size}",
        "replicas": args.fleet,
        "buckets": solo.get("buckets"),
        "trace": trace_meta,
        "solo": s,
        "fleet": {**f,
                  "submitted": fleet["submitted"],
                  "failed": fleet["failed"],
                  "unhealthy": fl["unhealthy"],
                  "failovers": fl["failovers"],
                  "promote_swap_ms": fleet.get("serve/promote_swap_ms"),
                  "promotions": fl["promotions"]},
        "checks": checks,
        "ok": all(checks.values()),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model + short trace (the tier-1 pin)")
    ap.add_argument("--fleet", type=int, default=0,
                    help="N replicas: run the solo-vs-fleet live-fire "
                         "comparison (replica kill + weight promotion "
                         "mid-trace) instead of the cold/warm pair")
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-subprocess budget (seconds)")
    args = ap.parse_args()
    if args.smoke:
        size, batch, requests, rps, max_images = 16, 8, 24, 40.0, 8
        max_batch, max_wait_ms = 16, 5.0
    else:
        size, batch, requests, rps, max_images = 64, 16, 200, 50.0, 16
        max_batch, max_wait_ms = 64, 10.0

    if args.fleet:
        row = _run_fleet_bench(args, size=size, batch=batch,
                               requests=requests, rps=rps,
                               max_images=max_images, max_batch=max_batch,
                               max_wait_ms=max_wait_ms)
        print(json.dumps(row))
        sys.exit(0 if row["ok"] else 1)

    with tempfile.TemporaryDirectory() as tmp:
        ckpt = os.path.join(tmp, "ckpt")
        cache = os.path.join(tmp, "compile-cache")
        trace = os.path.join(tmp, "trace.json")
        _make_ckpt(ckpt, tmp, size=size, batch=batch, timeout=args.timeout)
        trace_meta = make_trace(trace, requests=requests, rps=rps,
                                burst_factor=8.0, burst_frac=0.25,
                                max_images=max_images, seed=0)
        cold = _run_arm("cold", ckpt_dir=ckpt, cache_dir=cache, trace=trace,
                        workdir=tmp, max_batch=max_batch,
                        max_wait_ms=max_wait_ms, timeout=args.timeout)
        warm = _run_arm("warm", ckpt_dir=ckpt, cache_dir=cache, trace=trace,
                        workdir=tmp, max_batch=max_batch,
                        max_wait_ms=max_wait_ms, timeout=args.timeout)

    c, w = _arm_summary(cold), _arm_summary(warm)
    checks = {
        # every served batch hit a precompiled bucket — on both arms
        "cold_zero_recompiles_after_warmup":
            c["recompiles_after_warmup"] == 0,
        "warm_zero_recompiles_after_warmup":
            w["recompiles_after_warmup"] == 0,
        # the warm restart actually deserialized from the primed cache
        "warm_has_hits": w["cache"]["hits"] > 0,
        "warm_zero_misses": w["cache"]["misses"] == 0,
        "cold_has_misses": c["cache"]["misses"] > 0,
        # finite trace + drain: nothing lost, nothing left queued
        "cold_all_completed": c["completed"] == requests
                              and c["dropped"] == 0,
        "warm_all_completed": w["completed"] == requests
                              and w["dropped"] == 0,
        "latency_percentiles_present":
            bool(c["p50_ms"] and c["p99_ms"] and w["p50_ms"]
                 and w["p99_ms"]),
    }
    row = {
        "label": "bench-serve",
        "platform": "cpu",
        "model": f"dcgan{size}",
        "buckets": cold.get("buckets"),
        "trace": trace_meta,
        "cold": c,
        "warm": w,
        "speedup": {
            "warmup_ms": round(c["warmup_ms"] / max(w["warmup_ms"], 1e-9),
                               2),
            "cold_start_ms": round(
                c["cold_start_ms"] / max(w["cold_start_ms"], 1e-9), 2),
        },
        "checks": checks,
        "ok": all(checks.values()),
    }
    print(json.dumps(row))
    sys.exit(0 if row["ok"] else 1)


if __name__ == "__main__":
    main()
