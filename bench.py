"""Benchmark: DCGAN-64 training throughput (images/sec/chip).

Flagship config = the reference's headline workload: DCGAN 64x64, batch 64,
z=100, Adam(2e-4, 0.5) — its hot loop ran two host<->device round-trips, a
numpy-fed z, and a gRPC weight sync per step (image_train.py:147-194,
SURVEY.md §3.1). Here the whole D+G step is one compiled XLA program with
donated state and on-device PRNG, so steady-state throughput is pure device
time.

Baseline: the reference publishes no numbers (BASELINE.md). The driver-defined
north star is >=4x a single-V100 TF DCGAN-64 baseline; public single-V100
TF DCGAN-64 trainers at batch 64 sustain roughly 2000 images/sec, which we
adopt (documented assumption) as baseline=2000 for vs_baseline.

Output contract (the driver parses the LAST stdout line): the headline
row {"metric", "value", "unit", "vs_baseline"} is always the FINAL JSON
line on stdout. Every A/B knob — PIPELINE_GD=1 (_bench_pipeline_ab),
ZERO_STAGE={2,3} (_bench_zero_ab), PROGRESSIVE=1, PRECISION /
PALLAS_FUSED (_bench_precision_ab), COMM_OVERLAP=1
(_bench_comm_overlap_ab) — prints its extra row(s) BEFORE the headline
row, and all non-row context goes to stderr, so adding a knob can never
break the last-line parse. tests/test_comm_overlap.py pins the row
order.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# process-start anchor for the startup_ms field: time-to-first-step is
# measured from interpreter entry (import cost included — restarts pay it)
_T_PROC_START = time.perf_counter()

V100_TF_BASELINE_IMG_PER_SEC = 2000.0

# The reference's headline workload knobs (image_train.py:42-48).
# BENCH_* env overrides exist for local smoke runs (e.g. BENCH_PLATFORM=cpu
# BENCH_BATCH=8 BENCH_STEPS=3); the driver's TPU run uses the defaults.
BATCH = int(os.environ.get("BENCH_BATCH", 64))
STEPS_MEASURE = int(os.environ.get("BENCH_STEPS", 400))
STEPS_WARMUP = 5
# Steps per dispatched program (ParallelTrain.multi_step, a lax.scan): over
# the tunneled transport each dispatch costs up to ~7 ms of RPC overhead —
# per-step dispatch measured 5.5k img/s where scan-20 measured 19.3k and
# scan-50 21.4k on the same chip minutes apart. 1 = the plain per-step path
# (also the default for CPU smoke runs, where compiling the scanned program
# costs minutes). Clamped to BENCH_STEPS so a smoke run never exceeds the
# requested steps.
_SCAN_DEFAULT = 1 if os.environ.get("BENCH_PLATFORM") == "cpu" else 50
SCAN = max(1, min(int(os.environ.get("BENCH_SCAN", _SCAN_DEFAULT)),
                  STEPS_MEASURE))


def _bench_sample(cfg, pt, state, n_chips: int) -> None:
    """BENCH_MODE=sample: generation (inference) throughput through
    ParallelTrain.sample — the serve analogue of the reference's only
    generation path, the in-graph sampler (image_train.py:179-192).

    One dispatch per call (there is no scanned multi-sample), so the z
    batch is deliberately large (default 1024/chip) to amortize the
    tunnel's ~7 ms per-dispatch RPC cost; z lives on device and is reused
    across calls — throughput needs device work, not fresh latents.
    """
    import jax
    import jax.numpy as jnp

    batch = int(os.environ.get("BENCH_SAMPLE_BATCH", 1024)) * n_chips
    z = jax.random.uniform(jax.random.key(2), (batch, cfg.model.z_dim),
                           minval=-1.0, maxval=1.0, dtype=jnp.float32)
    labels = (jnp.asarray(
        np.arange(batch) % cfg.model.num_classes),) \
        if cfg.model.num_classes else ()
    imgs = pt.sample(state, z, *labels)      # compile + warmup
    float(imgs[0, 0, 0, 0])                  # value-readback sync (see main)

    windows = int(os.environ.get("BENCH_WINDOWS", 3))
    # own knob: sample dispatch count must not silently track the
    # train-step BENCH_STEPS knob (the two measure different programs)
    n_calls = int(os.environ.get("BENCH_SAMPLE_CALLS", 20))
    dt = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(n_calls):
            imgs = pt.sample(state, z, *labels)
        float(imgs[0, 0, 0, 0])
        dt = min(dt, time.perf_counter() - t0)

    img_per_sec_chip = batch * n_calls / dt / n_chips
    arch = os.environ.get("BENCH_PRESET", "") or (
        f"SAGAN-{cfg.model.output_size}" if cfg.model.attn_res
        else f"DCGAN-{cfg.model.output_size}")
    row = {
        "metric": f"{arch} sampler (inference) throughput "
                  f"(batch {batch // n_chips}/chip, bf16)",
        "value": round(img_per_sec_chip, 1),
        "unit": "images/sec/chip",
        # vs the same adopted train baseline is meaningless for inference;
        # report the ratio to our own measured train rate out-of-band (docs)
        "vs_baseline": None,
    }
    if cfg.model.attn_res:
        # same generation stamp as the train rows (VERDICT r4 #1), with the
        # same flash/dense split (ADVICE r5 #1): stamp the generation of the
        # attention code this config actually EXECUTES, so a flash-only
        # ATTN_GEN bump can never retire dense sampler capture history
        if cfg.model.use_pallas:
            from dcgan_tpu.ops.pallas_attention import ATTN_GEN
            row["gen"] = ATTN_GEN
        else:
            from dcgan_tpu.ops.attention import DENSE_ATTN_GEN
            row["gen"] = DENSE_ATTN_GEN
    print(json.dumps(row))
    print(f"chips={n_chips} batch={batch} calls={n_calls} wall={dt:.2f}s "
          f"ms_per_step={dt / n_calls * 1e3:.2f}", file=sys.stderr)


def _state_mib_per_chip(state) -> float:
    """Per-chip resident train-state MiB — the number the ZeRO ladder
    moves (one shared derivation: parallel/sharding.state_bytes_per_chip,
    also what the zero-stage tests pin)."""
    from dcgan_tpu.parallel.sharding import state_bytes_per_chip

    return round(state_bytes_per_chip(state) / 2**20, 2)


def _time_arm(run, st, step_idx: int, windows: int):
    """One A/B arm's timing harness, shared by the pipelined and ZeRO
    rows so the two A/B methodologies cannot drift: a compile+warmup
    call, then best-of-`windows` wall clock with a value-readback sync
    per window (see main()'s sync rationale). `run(state, step_idx) ->
    (state, metrics, step_idx)`. Returns (state, metrics, step_idx,
    best_window_seconds)."""
    st, metrics, step_idx = run(st, step_idx)        # compile + warmup
    float(metrics["d_loss"])                         # value-readback sync
    dt = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        st, metrics, step_idx = run(st, step_idx)
        float(metrics["d_loss"])
        dt = min(dt, time.perf_counter() - t0)
    return st, metrics, step_idx, dt


def _bench_zero_ab(cfg, mesh, n_chips: int, images, base) -> None:
    """ZERO_STAGE={2,3}: the state-sharding A/B row (ISSUE 13).

    Measures the SAME config per-step at zero_stage 1 and each stage up
    to ZERO_STAGE, and prints one extra BENCH-style row with every arm's
    ms_per_step + peak_state_mib (per-chip resident state bytes from the
    live shardings). The contract the acceptance rides on: peak_state_mib
    strictly DECREASING from stage 1 -> 3 while throughput stays within
    noise — the ZeRO win as a number, not a claim. Printed BEFORE the
    headline row so the driver's last-line parse is unchanged.
    """
    import dataclasses

    import jax

    from dcgan_tpu.parallel import make_parallel_train

    top = int(os.environ["ZERO_STAGE"])
    steps = max(1, int(os.environ.get("BENCH_ZERO_STEPS",
                                      min(STEPS_MEASURE, 60))))
    windows = int(os.environ.get("BENCH_WINDOWS", 3))
    arms = {}
    for stage in [s for s in (1, 2, 3) if s <= top]:
        cfg_s = dataclasses.replace(
            cfg, mesh=dataclasses.replace(cfg.mesh, zero_stage=stage))
        pt_s = make_parallel_train(cfg_s, mesh)
        st = pt_s.init(jax.random.key(0))
        peak_state = _state_mib_per_chip(st)

        def run(st, step_idx, _pt=pt_s):
            for _ in range(steps):
                st, metrics = _pt.step(st, images,
                                       jax.random.fold_in(base, step_idx))
                step_idx += 1
            return st, metrics, step_idx

        st, _metrics, _idx, dt = _time_arm(run, st, 0, windows)
        arms[f"zero{stage}"] = {
            "ms_per_step": round(dt / steps * 1e3, 3),
            "images_per_sec_chip": round(
                cfg.batch_size * steps / dt / n_chips, 1),
            "peak_state_mib": peak_state,
        }
        del st  # free the arm's state before the next arm compiles
    arch = os.environ.get("BENCH_PRESET", "") or (
        f"DCGAN-{cfg.model.output_size}")
    z1, ztop = arms["zero1"], arms[f"zero{top}"]
    print(json.dumps({
        "metric": f"{arch} ZeRO state-sharding A/B (batch {BATCH}/chip, "
                  "per-step dispatch, bf16)",
        "value": ztop["images_per_sec_chip"],
        "unit": "images/sec/chip",
        "vs_baseline": round(ztop["images_per_sec_chip"]
                             / V100_TF_BASELINE_IMG_PER_SEC, 3),
        **arms,
        # the headline memory claim as one unitless number
        "state_mib_zero1_over_top": round(
            z1["peak_state_mib"] / ztop["peak_state_mib"], 3)
        if ztop["peak_state_mib"] else None,
    }))


def _bench_comm_overlap_ab(cfg, mesh, n_chips: int, images, base) -> None:
    """COMM_OVERLAP=1: the collective overlap A/B row (ISSUE 20).

    Measures the SAME workload per-step with `--comm_overlap off` vs
    `bucket` (vs `prefetch` too when the ZeRO stage is 3) on the
    shard_map backend — the backend whose hand-placed collectives the
    knob restructures (gspmd's half is scheduler flags; its program is
    unchanged) — at zero_stage = ZERO_STAGE when set, else 2. Each arm
    reports ms_per_step AND its collective-census op counts from the
    traced step program, so the row carries the acceptance contract
    directly: the bucket arm's op count strictly below the per-leaf
    baseline's, wall-clock alongside. Printed BEFORE the headline row
    so the driver's last-line parse is unchanged.
    """
    import dataclasses

    import jax

    from dcgan_tpu.analysis.semantic import CENSUS_PRIMS, _walk_jaxpr
    from dcgan_tpu.parallel import make_parallel_train

    stage = max(2, int(os.environ.get("ZERO_STAGE") or 2))
    if cfg.backend != "shard_map" and (cfg.mesh.model != 1
                                       or cfg.mesh.spatial
                                       or cfg.mesh.shard_opt
                                       or cfg.grad_clip > 0):
        print("COMM_OVERLAP=1 skipped: the A/B runs the shard_map "
              "backend and this config does not compose with it",
              file=sys.stderr)
        return
    steps = max(1, int(os.environ.get("BENCH_OVERLAP_STEPS",
                                      min(STEPS_MEASURE, 60))))
    windows = int(os.environ.get("BENCH_WINDOWS", 3))
    arms = {}
    for mode in ["off", "bucket"] + (["prefetch"] if stage == 3 else []):
        cfg_o = dataclasses.replace(
            cfg, backend="shard_map", comm_overlap=mode,
            mesh=dataclasses.replace(cfg.mesh, zero_stage=stage))
        pt_o = make_parallel_train(cfg_o, mesh)
        st = pt_o.init(jax.random.key(0))
        census = {}

        def visit(eqn, _c=census):
            kind = CENSUS_PRIMS.get(eqn.primitive.name)
            if kind is not None:
                _c[kind] = _c.get(kind, 0) + 1
        _walk_jaxpr(jax.jit(pt_o.step).trace(
            st, images, jax.random.fold_in(base, 0)).jaxpr.jaxpr, visit)

        def run(st, step_idx, _pt=pt_o):
            for _ in range(steps):
                st, metrics = _pt.step(st, images,
                                       jax.random.fold_in(base, step_idx))
                step_idx += 1
            return st, metrics, step_idx

        st, _metrics, _idx, dt = _time_arm(run, st, 0, windows)
        arms[mode] = {
            "ms_per_step": round(dt / steps * 1e3, 3),
            "images_per_sec_chip": round(
                cfg.batch_size * steps / dt / n_chips, 1),
            "collective_ops": dict(sorted(census.items())),
            "collective_ops_total": sum(census.values()),
        }
        del st  # free the arm's state before the next arm compiles
    arch = os.environ.get("BENCH_PRESET", "") or (
        f"DCGAN-{cfg.model.output_size}")
    best = arms.get("prefetch") or arms["bucket"]
    print(json.dumps({
        "metric": f"{arch} collective overlap A/B (shard_map, "
                  f"zero_stage={stage}, batch {BATCH}/chip)",
        "value": best["images_per_sec_chip"],
        "unit": "images/sec/chip",
        "vs_baseline": round(best["images_per_sec_chip"]
                             / V100_TF_BASELINE_IMG_PER_SEC, 3),
        **arms,
    }))


def _bench_precision_ab(cfg, mesh, n_chips: int, images, base) -> None:
    """PRECISION={bf16,fp8} / PALLAS_FUSED=1: the fused-kernel +
    reduced-precision A/B row (ISSUE 17).

    Measures the SAME workload per-step against an explicit f32-unfused
    control arm (precision="f32" forces f32 params+compute even when the
    headline config computes in bf16), plus one arm per armed knob —
    @pallas_fused (fused conv⊕BN⊕act Pallas GEMM blocks), @<precision>
    (the reduced-precision policy), and their composition when both are
    set. Every arm reports ms_per_step + images_per_sec_chip +
    peak_state_mib (bf16 params halve the resident param/nu bytes; mu
    stays f32 master). The acceptance contract rides on
    `ms_f32_over_best`: the best knobbed arm strictly faster than the
    f32-unfused control at >=128px. Printed BEFORE the headline row so
    the driver's last-line parse is unchanged.
    """
    import dataclasses

    import jax

    from dcgan_tpu.parallel import make_parallel_train

    precision = os.environ.get("PRECISION", "")
    fused = os.environ.get("PALLAS_FUSED") == "1"
    if fused and (cfg.model.arch != "dcgan" or cfg.model.num_classes):
        print("PALLAS_FUSED=1 skipped: fused blocks are plain-DCGAN "
              "batch-norm only", file=sys.stderr)
        fused = False
    if not (precision or fused):
        return
    steps = max(1, int(os.environ.get("BENCH_PRECISION_STEPS",
                                      min(STEPS_MEASURE, 60))))
    windows = int(os.environ.get("BENCH_WINDOWS", 3))

    def _variant(prec, fuse):
        m = cfg.model
        if fuse:
            m = dataclasses.replace(m, use_pallas=True, pallas_fused=True)
        return dataclasses.replace(cfg, model=m, precision=prec)

    arm_cfgs = [("f32", _variant("f32", False))]
    if fused:
        arm_cfgs.append(("pallas_fused", _variant("f32", True)))
    if precision:
        arm_cfgs.append((precision, _variant(precision, False)))
    if precision and fused:
        arm_cfgs.append((f"{precision}+fused", _variant(precision, True)))

    arms = {}
    for tag, cfg_a in arm_cfgs:
        pt_a = make_parallel_train(cfg_a, mesh)
        st = pt_a.init(jax.random.key(0))
        peak_state = _state_mib_per_chip(st)

        def run(st, step_idx, _pt=pt_a):
            for _ in range(steps):
                st, metrics = _pt.step(st, images,
                                       jax.random.fold_in(base, step_idx))
                step_idx += 1
            return st, metrics, step_idx

        st, _metrics, _idx, dt = _time_arm(run, st, 0, windows)
        arms[tag] = {
            "ms_per_step": round(dt / steps * 1e3, 3),
            "images_per_sec_chip": round(
                cfg.batch_size * steps / dt / n_chips, 1),
            "peak_state_mib": peak_state,
        }
        del st  # free the arm's state before the next arm compiles
    arch = os.environ.get("BENCH_PRESET", "") or (
        f"DCGAN-{cfg.model.output_size}")
    best_tag = min((t for t in arms if t != "f32"),
                   key=lambda t: arms[t]["ms_per_step"])
    f32, best = arms["f32"], arms[best_tag]
    print(json.dumps({
        "metric": f"{arch} precision/fusion A/B (batch {BATCH}/chip, "
                  "per-step dispatch)",
        "value": best["images_per_sec_chip"],
        "unit": "images/sec/chip",
        "vs_baseline": round(best["images_per_sec_chip"]
                             / V100_TF_BASELINE_IMG_PER_SEC, 3),
        **arms,
        "best_arm": best_tag,
        # the headline speed claim as one unitless number: control
        # ms_per_step over the best knobbed arm's (>1 = knobs won)
        "ms_f32_over_best": round(
            f32["ms_per_step"] / best["ms_per_step"], 4)
        if best["ms_per_step"] else None,
    }))


def _bench_progressive_ab(cfg, mesh, n_chips: int, base) -> None:
    """PROGRESSIVE=1: the progressive-resolution A/B rows (ISSUE 15).

    Two extra BENCH-style rows, both printed BEFORE the headline row so
    the driver's last-line parse is unchanged:

    1. the schedule A/B — the SAME model trained 64-only vs as a
       64 -> 128 schedule driven through the shipped PhaseRuntime
       (surface build, state carry, the lot), with per-phase ms_per_step
       and the measured switch_ms. The contract: phase-0 throughput ==
       the fixed-resolution arm within noise (the schedule machinery is
       free until a switch), and switch_ms is a one-off cost, not a
       per-step tax.
    2. a standalone 256px single-phase row — the perf story finally
       covers more than one shape (ROADMAP item 5). BENCH_256_BATCH
       overrides its per-chip batch (default: the headline batch).
    """
    import dataclasses

    import jax

    from dcgan_tpu.progressive import PhaseRuntime, parse_schedule

    steps = max(1, int(os.environ.get("BENCH_PROGRESSIVE_STEPS",
                                      min(STEPS_MEASURE, 40))))
    windows = int(os.environ.get("BENCH_WINDOWS", 3))
    base_res = cfg.model.output_size
    top_res = base_res * 2
    spec = f"{base_res}:{steps},{top_res}:*"
    cfg_p = dataclasses.replace(
        cfg, progressive=spec,
        model=dataclasses.replace(cfg.model, output_size=top_res))
    rt = PhaseRuntime(
        cfg_p, mesh,
        parse_schedule(spec, model=cfg_p.model,
                       batch_size=cfg_p.batch_size,
                       max_steps=cfg_p.max_steps,
                       grad_accum=cfg_p.grad_accum),
        cfg_p.max_steps)

    rng = np.random.default_rng(7)

    def _imgs(res, batch):
        import jax.numpy as jnp

        return jnp.asarray(rng.uniform(
            -1, 1, size=(batch, res, res, cfg.model.c_dim))
            .astype(np.float32))

    def _arm(pt_i, st, images, tag):
        def run(st, step_idx, _pt=pt_i, _img=images):
            for _ in range(steps):
                st, metrics = _pt.step(st, _img,
                                       jax.random.fold_in(base, step_idx))
                step_idx += 1
            return st, metrics, step_idx
        st, _m, _idx, dt = _time_arm(run, st, 0, windows)
        return st, {
            "ms_per_step": round(dt / steps * 1e3, 3),
            "images_per_sec_chip": round(
                cfg.batch_size * steps / dt / n_chips, 1),
        }

    arms = {}
    # fixed-resolution control: its own init, the phase-0 config alone
    _cfg0, pt0 = rt.surface(0)
    st = pt0.init(jax.random.key(0))
    st, arms[f"fixed{base_res}"] = _arm(pt0, st, _imgs(base_res,
                                                      cfg.batch_size),
                                        "fixed")
    del st
    # the scheduled run: phase 0, the live switch, phase 1
    st = pt0.init(jax.random.key(0))
    st, arms[f"phase_r{base_res}"] = _arm(pt0, st,
                                          _imgs(base_res, cfg.batch_size),
                                          "p0")
    t_sw = time.perf_counter()
    st = rt.advance(st)
    jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
    switch_ms = (time.perf_counter() - t_sw) * 1e3
    _cfg1, pt1 = rt.surface(1)
    st, arms[f"phase_r{top_res}"] = _arm(pt1, st,
                                         _imgs(top_res, cfg.batch_size),
                                         "p1")
    del st
    arch = os.environ.get("BENCH_PRESET", "") or f"DCGAN-{base_res}"
    f0 = arms[f"fixed{base_res}"]
    p1 = arms[f"phase_r{top_res}"]
    print(json.dumps({
        "metric": f"{arch} progressive {base_res}->{top_res} A/B "
                  f"(batch {BATCH}/chip, per-step dispatch, bf16)",
        "value": p1["images_per_sec_chip"],
        "unit": "images/sec/chip",
        "vs_baseline": None,  # cross-resolution rates have no 64px baseline
        **arms,
        "switch_ms": round(switch_ms, 1),
        "carried_leaves": rt.last_carried,
    }))

    # standalone 256px single-phase row (the new shape in the perf story)
    res = 256
    b256 = int(os.environ.get("BENCH_256_BATCH", BATCH)) * n_chips
    steps256 = max(1, int(os.environ.get("BENCH_256_STEPS",
                                         min(STEPS_MEASURE, 20))))
    from dcgan_tpu.parallel import make_parallel_train

    cfg256 = dataclasses.replace(
        cfg, batch_size=b256, progressive="",
        model=dataclasses.replace(cfg.model, output_size=res))
    pt256 = make_parallel_train(cfg256, mesh)
    st = pt256.init(jax.random.key(0))
    img256 = _imgs(res, b256)

    def run256(st, step_idx):
        for _ in range(steps256):
            st, metrics = pt256.step(st, img256,
                                     jax.random.fold_in(base, step_idx))
            step_idx += 1
        return st, metrics, step_idx

    st, _m, _idx, dt = _time_arm(run256, st, 0, windows)
    print(json.dumps({
        "metric": f"DCGAN-{res} train throughput "
                  f"(batch {b256 // n_chips}/chip, bf16)",
        "value": round(b256 * steps256 / dt / n_chips, 1),
        "unit": "images/sec/chip",
        "vs_baseline": None,  # the adopted V100 baseline is a 64px number
        "ms_per_step": round(dt / steps256 * 1e3, 3),
        "peak_state_mib": _state_mib_per_chip(st),
    }))
    del st


def _bench_pipeline_ab(cfg, pt, n_chips: int, images, base) -> None:
    """PIPELINE_GD=1: the pipelined G/D dispatch A/B row (ISSUE 7).

    Measures the SAME config twice at per-step dispatch — the fused
    train_step program vs the gen_fakes/d_update/g_update stage loop the
    trainer runs under --pipeline_gd (driven through the trainer's own
    GDPipeline buffer manager, so the benched dataflow is the shipped
    one) — and prints one extra BENCH-style row with both arms'
    ms_per_step + devstep_ms. Per-step FLOPs are conservation-equal
    across the arms (tools/step_profile.py PIPELINE_GD=1 proves it), so
    this row is the regression guard that the stage split's extra
    dispatches stay in the noise, not a speedup claim. Printed BEFORE
    the headline row so the driver's last-line parse is unchanged.
    """
    import jax

    from dcgan_tpu.train.gd_pipeline import GDPipeline

    steps = max(1, int(os.environ.get("BENCH_PIPELINE_STEPS",
                                      min(STEPS_MEASURE, 60))))
    windows = int(os.environ.get("BENCH_WINDOWS", 3))

    def _fused(state, step_idx):
        for _ in range(steps):
            state, metrics = pt.step(state, images,
                                     jax.random.fold_in(base, step_idx))
            step_idx += 1
        return state, metrics, step_idx

    pipe = GDPipeline()

    def _pipelined(state, step_idx):
        for _ in range(steps):
            state, metrics = pipe.step(pt, state, images,
                                       jax.random.fold_in(base, step_idx))
            step_idx += 1
        return state, metrics, step_idx

    arms = {}
    for arm, run in (("fused", _fused), ("pipelined", _pipelined)):
        # fresh state per arm (donation consumed the other arm's): arms
        # must not share optimizer history either
        st = pt.init(jax.random.key(0))
        st, metrics, step_idx, dt = _time_arm(run, st, 0, windows)
        devstep = None
        if os.environ.get("BENCH_DEVSTEP", "1") != "0":
            try:
                import tempfile

                from dcgan_tpu.utils.trace import digest, find_trace, \
                    stage_step_ms
                with tempfile.TemporaryDirectory() as td:
                    jax.profiler.start_trace(td)
                    try:
                        st, metrics, step_idx = run(st, step_idx)
                        float(metrics["d_loss"])
                    finally:
                        jax.profiler.stop_trace()
                    d = digest(find_trace(td))
                    if d["source"] != "none" and d["program_ms_median"] > 0:
                        # stage-summed per-step time when the track names
                        # the stage programs (TPU module tracks); busiest-
                        # program median otherwise — same convention as the
                        # trainer's perf/device/step_ms
                        devstep = (stage_step_ms(d)
                                   if arm == "pipelined" else 0.0) \
                            or d["program_ms_median"]
            except Exception as e:  # noqa: BLE001 — the field is optional
                print(f"{arm} devstep capture failed: {e!r}", file=sys.stderr)
        arms[arm] = {
            "ms_per_step": round(dt / steps * 1e3, 3),
            "images_per_sec_chip": round(
                cfg.batch_size * steps / dt / n_chips, 1),
            "devstep_ms": round(devstep, 4) if devstep else None,
        }
        pipe.drain("bench-arm-end")
    f, p = arms["fused"], arms["pipelined"]
    speedup = f["ms_per_step"] / p["ms_per_step"] \
        if p["ms_per_step"] > 0 else None
    arch = os.environ.get("BENCH_PRESET", "") or (
        f"DCGAN-{cfg.model.output_size}")
    print(json.dumps({
        "metric": f"{arch} pipelined G/D A/B (batch {BATCH}/chip, "
                  "per-step dispatch, bf16)",
        "value": p["images_per_sec_chip"],
        "unit": "images/sec/chip",
        "vs_baseline": round(p["images_per_sec_chip"]
                             / V100_TF_BASELINE_IMG_PER_SEC, 3),
        "fused": f, "pipelined": p,
        # unitless ratio: fused ms_per_step / pipelined ms_per_step
        "fused_over_pipelined": round(speedup, 4) if speedup else None,
    }))


def main() -> None:
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        # The ambient TPU plugin force-selects its platform via jax.config at
        # interpreter startup; honor an explicit override for CPU smoke runs.
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    if os.environ.get("BENCH_COMPILE_CACHE_DIR"):
        # warm-start the bench itself (ISSUE 5): with a primed cache the
        # startup_ms field below records the deserialize-not-compile path —
        # the same knob the trainer exposes as --compile_cache_dir
        from dcgan_tpu.train.warmup import configure_compile_cache

        configure_compile_cache(os.environ["BENCH_COMPILE_CACHE_DIR"])
    import jax.numpy as jnp

    from dcgan_tpu.config import MeshConfig, TrainConfig
    from dcgan_tpu.parallel import make_mesh, make_parallel_train
    from dcgan_tpu.utils.backend import acquire_devices

    # Bounded retry/backoff: one transient UNAVAILABLE from the tunneled
    # TPU plugin must not zero out the round's bench (BENCH_r01.json rc=1).
    n_chips = len(acquire_devices())
    preset_name = os.environ.get("BENCH_PRESET", "")
    if preset_name:
        # Bench any named config (VERDICT r1 #4): the preset supplies
        # architecture + loss + optimizer recipe; batch/mesh are re-derived
        # for the chips actually present (BENCH_BATCH stays per-chip).
        import dataclasses

        from dcgan_tpu.presets import get_preset

        base = get_preset(preset_name)
        cfg = dataclasses.replace(
            base,
            batch_size=BATCH * n_chips,
            mesh=MeshConfig(),
            grad_accum=int(os.environ.get("BENCH_ACCUM", 1)),
            # only an EXPLICIT BENCH_BACKEND overrides the preset's own
            # backend — clobbering it would measure a config that isn't
            # the preset (and stamp the preset's rev onto it)
            backend=os.environ.get("BENCH_BACKEND", base.backend))
    else:
        # the BENCH_* model knobs (shared with tools/step_profile.py so a
        # profile always decomposes exactly a benched config):
        # dcgan_tpu/utils/bench_env.py documents each
        from dcgan_tpu.utils.bench_env import bench_model_config

        mcfg, _ = bench_model_config()
        cfg = TrainConfig(
            model=mcfg,                 # flagship default: 64x64, gf=df=64
            batch_size=BATCH * n_chips,
            mesh=MeshConfig(),
            # BENCH_ACCUM=K: gradient-accumulation cost — same global batch,
            # K scanned microbatches per optimizer update. Composes with the
            # other BENCH_* model knobs rather than forking its own config.
            grad_accum=int(os.environ.get("BENCH_ACCUM", 1)),
            backend=os.environ.get("BENCH_BACKEND", "gspmd"))
    # BENCH_ATTN_RES=R: self-attention at an arbitrary feature-map
    # resolution (sequence length R*R) on top of WHATEVER config was built
    # above — preset or default. This is the long-context bench knob: at
    # R=128 (S=16384) the dense [S, S] form cannot allocate at train batch
    # sizes and only the flash path runs (DESIGN.md §8).
    from dcgan_tpu.utils.bench_env import apply_attn_res_override

    cfg = apply_attn_res_override(cfg)
    mesh = make_mesh(cfg.mesh)
    pt = make_parallel_train(cfg, mesh)

    size = cfg.model.output_size
    state = pt.init(jax.random.key(0))
    if os.environ.get("BENCH_MODE") == "sample":
        _bench_sample(cfg, pt, state, n_chips)
        return
    images = jnp.asarray(np.random.default_rng(0).uniform(
        -1, 1, size=(cfg.batch_size, size, size, cfg.model.c_dim))
        .astype(np.float32))
    labels = (jnp.asarray(np.arange(cfg.batch_size) % cfg.model.num_classes),
              ) if cfg.model.num_classes else ()
    base = jax.random.key(1)

    # Warmup compiles exactly the program the measurement uses. Sync by
    # VALUE READBACK, not block_until_ready: over the tunneled TPU transport
    # block_until_ready has been observed to return before queued work
    # finishes (30 "measured" steps in 0.02 s — 2.5x the chip's peak
    # FLOP/s); float() cannot lie. One readback per window: a synchronous
    # per-step fetch costs a full tunnel round-trip (~100 ms measured).
    if SCAN > 1:
        imgs_k = jnp.broadcast_to(images, (SCAN,) + images.shape)
        labels_k = tuple(jnp.broadcast_to(l, (SCAN,) + l.shape)
                         for l in labels)
        state, metrics = pt.multi_step(
            state, imgs_k, jax.random.split(jax.random.fold_in(base, 999),
                                            SCAN), *labels_k)
    else:
        for i in range(STEPS_WARMUP):
            state, metrics = pt.step(state, images,
                                     jax.random.fold_in(base, i), *labels)
    float(metrics["d_loss"])
    # time-to-first-step: interpreter entry -> the first compiled step's
    # value readback (compile + warmup included). BENCH_r*.json tracks the
    # startup trajectory the same way it tracks steady-state throughput;
    # a BENCH_COMPILE_CACHE_DIR warm run should show this dropping to the
    # deserialize floor.
    startup_ms = (time.perf_counter() - _T_PROC_START) * 1e3

    # Best of WINDOWS measurement windows: the tunneled transport's
    # throughput varies run to run (observed 3x swings on identical
    # programs); steady-state capability is the best window, not the mean.
    windows = int(os.environ.get("BENCH_WINDOWS", 3))
    n_calls = max(1, STEPS_MEASURE // SCAN)
    steps_window = n_calls * SCAN if SCAN > 1 else STEPS_MEASURE
    if steps_window != STEPS_MEASURE:
        print(f"note: BENCH_STEPS={STEPS_MEASURE} rounded to {steps_window} "
              f"(multiple of BENCH_SCAN={SCAN})", file=sys.stderr)
    dt = float("inf")
    final_d_loss = 0.0
    step_idx = STEPS_WARMUP
    for _ in range(windows):
        t0 = time.perf_counter()
        if SCAN > 1:
            for _ in range(n_calls):
                keys = jax.random.split(jax.random.fold_in(base, step_idx),
                                        SCAN)
                state, metrics = pt.multi_step(state, imgs_k, keys, *labels_k)
                step_idx += 1
        else:
            for _ in range(STEPS_MEASURE):
                state, metrics = pt.step(state, images,
                                         jax.random.fold_in(base, step_idx),
                                         *labels)
                step_idx += 1
        final_d_loss = float(metrics["d_loss"])  # hard sync ends the window
        dt = min(dt, time.perf_counter() - t0)

    # devstep_ms (ISSUE 6): the device's OWN step time from a short trace
    # digest — host wall-clock rows over the tunneled transport carry RPC
    # noise the device timeline does not, so BENCH rows now pin both.
    # Best-effort: a failed capture leaves the field null, never the row.
    devstep_ms = None
    if os.environ.get("BENCH_DEVSTEP", "1") != "0":
        try:
            import tempfile

            from dcgan_tpu.utils.trace import devstep_ms as devstep_of
            with tempfile.TemporaryDirectory() as td:
                jax.profiler.start_trace(td)
                try:
                    # stop_trace in the finally: a raise inside the traced
                    # region must not leave the profiler active for the
                    # rest of the process (any later start_trace would
                    # fail, and it would trace into a deleted tempdir)
                    if SCAN > 1:
                        keys = jax.random.split(
                            jax.random.fold_in(base, step_idx), SCAN)
                        state, metrics = pt.multi_step(state, imgs_k, keys,
                                                       *labels_k)
                    else:
                        for _ in range(min(5, STEPS_MEASURE)):
                            state, metrics = pt.step(
                                state, images,
                                jax.random.fold_in(base, step_idx), *labels)
                            step_idx += 1
                    # device work lands inside the trace
                    float(metrics["d_loss"])
                finally:
                    jax.profiler.stop_trace()
                devstep_ms = devstep_of(td, per_exec=max(1, SCAN))
        except Exception as e:  # noqa: BLE001 — the field is optional
            print(f"devstep capture failed: {e!r}", file=sys.stderr)

    img_per_sec = cfg.batch_size * steps_window / dt
    img_per_sec_chip = img_per_sec / n_chips
    if preset_name:
        arch = preset_name
    else:
        arch = (f"SAGAN-{cfg.model.output_size}" if cfg.model.attn_res
                else f"DCGAN-{cfg.model.output_size}")
        if cfg.grad_accum > 1:
            arch += f" grad_accum={cfg.grad_accum}"
    row = {
        "metric": f"{arch} train throughput (batch {BATCH}/chip, bf16)",
        "value": round(img_per_sec_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec_chip / V100_TF_BASELINE_IMG_PER_SEC, 3),
        "startup_ms": round(startup_ms, 1),
        # the device timeline's median per-step program time (null when
        # the capture failed); host ms_per_step minus this is transport +
        # host overhead, the split the captures log could not see before
        "devstep_ms": round(devstep_ms, 4) if devstep_ms else None,
        # per-chip resident state footprint (ISSUE 13): the number the
        # --zero_stage ladder moves; derived from the live shardings
        "peak_state_mib": _state_mib_per_chip(state),
    }
    if os.environ.get("PRECISION") or os.environ.get("PALLAS_FUSED") == "1":
        # the fused-kernel / precision-ladder A/B row (ISSUE 17) — printed
        # before the headline row so the driver's last-line parse holds
        _bench_precision_ab(cfg, mesh, n_chips, images, base)
    if os.environ.get("COMM_OVERLAP") == "1":
        # the collective overlap A/B row (ISSUE 20) — printed before the
        # headline row so the driver's last-line parse is unchanged
        if mesh.shape["data"] < 2:
            print("COMM_OVERLAP skipped: the overlap arms shard over the "
                  "data axis, which needs size > 1", file=sys.stderr)
        else:
            _bench_comm_overlap_ab(cfg, mesh, n_chips, images, base)
    if os.environ.get("ZERO_STAGE") in ("2", "3"):
        # the ZeRO state-sharding A/B row (ISSUE 13) — printed before the
        # headline row so the driver's last-line parse is unchanged
        if mesh.shape["data"] < 2:
            print("ZERO_STAGE skipped: stages >= 2 need a data axis of "
                  "size > 1", file=sys.stderr)
        else:
            _bench_zero_ab(cfg, mesh, n_chips, images, base)
    if os.environ.get("PROGRESSIVE") == "1":
        # the progressive-resolution A/B + 256px rows (ISSUE 15) — printed
        # before the headline row so the driver's last-line parse holds
        if cfg.model.attn_res:
            print("PROGRESSIVE=1 skipped: --progressive does not compose "
                  "with attention-bearing configs (resolution-anchored "
                  "site)", file=sys.stderr)
        else:
            _bench_progressive_ab(cfg, mesh, n_chips, base)
    if os.environ.get("PIPELINE_GD") == "1":
        # the pipelined G/D A/B row (ISSUE 7) — printed before the headline
        # row so the driver's last-line parse contract is unchanged
        if cfg.model.num_classes or cfg.update_mode != "sequential":
            print("PIPELINE_GD=1 skipped: pipelined stages are "
                  "unconditional sequential-update only", file=sys.stderr)
        else:
            _bench_pipeline_ab(cfg, pt, n_chips, images, base)
    if cfg.model.attn_res:
        # Attention-bearing configs stamp the generation of the attention
        # code they actually EXECUTE — flash kernels or the dense path —
        # so harvest renders never mix measurements of superseded attention
        # code into one spread column (VERDICT r4 #1), and a flash-only
        # generation bump never retires dense-config history.
        if cfg.model.use_pallas:
            from dcgan_tpu.ops.pallas_attention import ATTN_GEN
            row["gen"] = ATTN_GEN
        else:
            from dcgan_tpu.ops.attention import DENSE_ATTN_GEN
            row["gen"] = DENSE_ATTN_GEN
    if preset_name:
        # preset rows additionally stamp the preset revision (presets.py:
        # PRESET_REVS) — same never-mix-configs contract for preset changes
        from dcgan_tpu.presets import PRESET_REVS
        row["rev"] = PRESET_REVS.get(preset_name, 1)
    print(json.dumps(row))
    # context to stderr so the stdout contract stays one JSON line
    print(f"chips={n_chips} global_batch={cfg.batch_size} "
          f"steps={steps_window} scan={SCAN} wall={dt:.2f}s "
          f"ms_per_step={dt / steps_window * 1e3:.2f} "
          f"d_loss={final_d_loss:.3f}", file=sys.stderr)


def _text(s):
    return s.decode(errors="replace") if isinstance(s, bytes) else (s or "")


def _probe_once(timeout: float) -> tuple[int | None, str]:
    """Dial jax.devices() in a throwaway child.

    Returns (returncode, diagnostic tail).  returncode None means the child
    HUNG past ``timeout`` — the dead-tunnel signature (jax.devices() against
    a dead tunnel blocks instead of raising; observed all of rounds 1-2).
    A probe costs seconds when the backend answers (raise or success); only
    a dead tunnel pays the full timeout.
    """
    import subprocess

    try:
        res = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            env=dict(os.environ), timeout=timeout,
            capture_output=True, text=True)
        return res.returncode, _text(res.stderr)[-400:]
    except subprocess.TimeoutExpired:
        return None, f"jax.devices() hung >{timeout:.0f}s (dead tunnel)"


def _run_with_budget() -> None:
    """Parent wrapper: TOTAL-wall-budgeted probe-then-measure.

    Round 2's lesson (BENCH_r02.json rc=124): a retry harness whose
    worst-case wall (3 x 900 s) exceeds the driver's own timeout gets
    killed from outside before it can print its structured error line —
    the capture design itself guaranteed an empty round whenever the
    tunnel was dead.  This wrapper inverts the budgeting:

      * ``BENCH_TOTAL_BUDGET`` (default 780 s) is a hard deadline chosen
        UNDER the driver's wall clock; every path prints the one JSON
        line (value or structured error) before it expires.
      * A cheap subprocess ``jax.devices()`` probe (90 s cap — RUNBOOK
        §0's prescription) runs FIRST; dead-tunnel hangs are burned by
        the probe loop at 90 s apiece, never by a 900 s measurement
        child that was doomed from the start.
      * Once a probe answers, the measurement child gets the remaining
        budget in one shot.  A fast-failing child (transient UNAVAILABLE
        at compile) re-enters the probe loop while budget allows; a hung
        child consumes the budget exactly once.

    Child stdout (the one JSON line) is captured and forwarded only on
    success so a half-dead child can never leave a stale line ahead of a
    later attempt's.
    """
    import subprocess

    total = float(os.environ.get("BENCH_TOTAL_BUDGET", 780))
    probe_cap = float(os.environ.get("BENCH_PROBE_TIMEOUT", 90))
    # Floor for a meaningful measurement window: tunnel compile of the
    # scanned program is ~40-90 s, measurement adds ~30 s. Below this,
    # don't bother starting a child that cannot finish.
    min_measure = float(os.environ.get("BENCH_MIN_MEASURE", 150))
    margin = 15.0  # teardown + JSON-print reserve
    deadline = time.monotonic() + total

    def remaining() -> float:
        return deadline - time.monotonic()

    def fail(msg: str, **extra) -> None:
        print(json.dumps({
            "metric": "bench_error", "value": None,
            "unit": "images/sec/chip", "vs_baseline": None,
            "error": msg, **extra,
        }))
        sys.exit(1)

    on_cpu = os.environ.get("BENCH_PLATFORM") == "cpu"
    # Floor for launching/retrying a measurement child: CPU children need
    # only ~30 s, so the TPU floor must not gate CPU smoke retries.
    measure_floor = 0 if on_cpu else min_measure
    # Cap on measurement attempts: the wall budget bounds hangs, but a
    # deterministic fast failure (bad preset, import error) would otherwise
    # re-run every ~15 s until the whole budget burned.
    max_measures = max(1, int(os.environ.get("BENCH_MEASURE_ATTEMPTS", 3)))
    probes = 0
    measures = 0
    last_diag = ""
    rc: int | None = 1
    while True:
        # Phase 1: probe until the backend answers. CPU smoke runs skip it
        # (local CPU init cannot hang).
        if not on_cpu:
            fast_fails = 0  # consecutive fast rc!=0 probes (deterministic
            # failure class — broken install, plugin import error); hangs
            # (rc None) stay budget-bounded, they ARE the tunnel wait.
            while True:
                budget = min(probe_cap, remaining() - margin)
                if budget <= 5:
                    fail(f"tunnel never answered within budget "
                         f"({probes} probes, {measures} measure attempts)",
                         probes=probes, last=last_diag[-200:])
                probes += 1
                rc, last_diag = _probe_once(budget)
                if rc == 0:
                    break
                fast_fails = 0 if rc is None else fast_fails + 1
                state = "hang" if rc is None else f"rc={rc}"
                print(f"probe {probes} failed ({state}); "
                      f"{remaining():.0f}s of budget left", file=sys.stderr)
                if fast_fails >= 3 or remaining() - margin < min_measure:
                    fail(f"backend probe failed "
                         f"({probes} probes, {measures} measure attempts, "
                         f"last {state})",
                         probes=probes, last=last_diag[-200:])
                time.sleep(3)

        # Phase 2: one measurement child with the remaining budget.
        child_budget = remaining() - margin
        if child_budget < measure_floor or child_budget <= 5:
            fail(f"no budget left to measure after {probes} probes",
                 probes=probes, measures=measures, last=last_diag[-200:])
        measures += 1
        try:
            res = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=dict(os.environ, BENCH_CHILD="1"),
                timeout=child_budget, capture_output=True, text=True)
            rc = res.returncode
            sys.stderr.write(_text(res.stderr))
            if rc == 0:
                sys.stdout.write(_text(res.stdout))
                sys.exit(0)
            sys.stderr.write(_text(res.stdout))  # failed child's stdout
            last_diag = _text(res.stderr)
        except subprocess.TimeoutExpired as te:
            rc = None
            sys.stderr.write(_text(te.stderr))
            sys.stderr.write(_text(te.output))
            last_diag = _text(te.stderr) or "measurement child hung"
        state = "hang/timeout" if rc is None else f"rc={rc}"
        print(f"measure attempt {measures} failed ({state}); "
              f"{remaining():.0f}s of budget left", file=sys.stderr)
        if measures >= max_measures or remaining() - margin < max(
                measure_floor, 5):
            fail(f"measurement failed within budget "
                 f"({probes} probes, {measures} measure attempts, "
                 f"last {state})",
                 probes=probes, measures=measures, last=last_diag[-200:])
        time.sleep(3)  # then re-probe: the fast failure may be transient


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        main()
    else:
        _run_with_budget()
