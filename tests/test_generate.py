"""Standalone generation CLI (dcgan_tpu/generate.py) — the serve entry point
the reference never had (SURVEY.md §3.4)."""

import glob
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # see pytest.ini: excluded from the smoke tier

from dcgan_tpu.config import ModelConfig, TrainConfig
from dcgan_tpu.generate import build_parser, generate
from dcgan_tpu.train.trainer import train


@pytest.fixture(scope="module")
def trained_ckpt(tmp_path_factory):
    root = tmp_path_factory.mktemp("gen")
    cfg = TrainConfig(
        model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                          compute_dtype="float32"),
        batch_size=8,
        checkpoint_dir=str(root / "ckpt"),
        sample_dir=str(root / "samples"),
        sample_every_steps=0, save_summaries_secs=1e9, save_model_secs=1e9,
        log_every_steps=0)
    train(cfg, synthetic_data=True, max_steps=1)
    return str(root / "ckpt")


class TestGenerate:
    def test_grids_and_npz(self, trained_ckpt, tmp_path):
        args = build_parser().parse_args(
            ["--checkpoint_dir", trained_ckpt,
             "--out_dir", str(tmp_path / "out"),
             "--num_images", "10", "--batch_size", "8", "--grid", "2x2",
             "--npz", str(tmp_path / "gen.npz"),
             "--output_size", "16", "--gf_dim", "8", "--df_dim", "8"])
        result = generate(args)
        assert result["num_images"] == 10
        assert result["step"] == 1
        assert glob.glob(str(tmp_path / "out" / "gen_*.png"))
        data = np.load(tmp_path / "gen.npz")
        assert data["images"].shape == (10, 16, 16, 3)
        assert data["images"].dtype == np.float32
        assert np.abs(data["images"]).max() <= 1.0
        assert "labels" not in data

    def test_use_ema_selects_ema_weights(self, tmp_path):
        """--use_ema samples state['ema_gen']; after 2 steps at decay 0.5
        the EMA and live weights differ, so the outputs must too."""
        cfg = TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32"),
            batch_size=8, g_ema_decay=0.5,
            checkpoint_dir=str(tmp_path / "ckpt"),
            sample_dir=str(tmp_path / "samples"),
            sample_every_steps=0, save_summaries_secs=1e9,
            save_model_secs=1e9, log_every_steps=0)
        train(cfg, synthetic_data=True, max_steps=2)
        outs = {}
        for flag in (False, True):
            argv = ["--checkpoint_dir", cfg.checkpoint_dir,
                    "--out_dir", str(tmp_path / f"out{flag}"),
                    "--num_images", "8", "--batch_size", "8", "--grid", "0",
                    "--npz", str(tmp_path / f"g{flag}.npz"),
                    "--output_size", "16", "--gf_dim", "8", "--df_dim", "8"]
            if flag:
                argv.append("--use_ema")
            generate(build_parser().parse_args(argv))
            outs[flag] = np.load(tmp_path / f"g{flag}.npz")["images"]
        assert float(np.abs(outs[True] - outs[False]).max()) > 0

    def test_interpolate_mode(self, trained_ckpt, tmp_path):
        """--interpolate: one latent-walk grid PNG (the reference's dead
        `visualize` flag, image_train.py:24, actually implemented)."""
        args = build_parser().parse_args(
            ["--checkpoint_dir", trained_ckpt,
             "--out_dir", str(tmp_path / "out"),
             "--grid", "3x5", "--interpolate",
             "--output_size", "16", "--gf_dim", "8", "--df_dim", "8"])
        result = generate(args)
        assert result["num_images"] == 15
        assert len(result["paths"]) == 1
        assert os.path.exists(result["paths"][0])
        assert "interp_" in os.path.basename(result["paths"][0])

    def test_truncation_validated_and_applied(self, trained_ckpt, tmp_path):
        base = ["--checkpoint_dir", trained_ckpt,
                "--out_dir", str(tmp_path / "out"), "--grid", "0",
                "--num_images", "4", "--batch_size", "4",
                "--npz", str(tmp_path / "t.npz"),
                "--output_size", "16", "--gf_dim", "8", "--df_dim", "8"]
        generate(build_parser().parse_args(base + ["--truncation", "0.5"]))
        half = np.load(tmp_path / "t.npz")["images"]
        generate(build_parser().parse_args(base))
        full = np.load(tmp_path / "t.npz")["images"]
        assert np.abs(half - full).max() > 1e-5  # psi actually changes z
        with pytest.raises(SystemExit, match="truncation"):
            generate(build_parser().parse_args(base + ["--truncation", "0"]))

    def test_interpolate_requires_grid(self, trained_ckpt, tmp_path):
        args = build_parser().parse_args(
            ["--checkpoint_dir", trained_ckpt,
             "--out_dir", str(tmp_path / "out"), "--grid", "0",
             "--interpolate",
             "--output_size", "16", "--gf_dim", "8", "--df_dim", "8"])
        with pytest.raises(SystemExit, match="grid"):
            generate(args)

    def test_no_checkpoint_errors(self, tmp_path):
        args = build_parser().parse_args(
            ["--checkpoint_dir", str(tmp_path / "nope"),
             "--output_size", "16", "--gf_dim", "8", "--df_dim", "8"])
        with pytest.raises(SystemExit, match="no checkpoint"):
            generate(args)

    def test_conditional_class_id(self, tmp_path):
        cfg = TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              num_classes=4, compute_dtype="float32"),
            batch_size=8,
            checkpoint_dir=str(tmp_path / "ckpt"),
            sample_dir=str(tmp_path / "samples"),
            sample_every_steps=0, save_summaries_secs=1e9,
            save_model_secs=1e9, log_every_steps=0)
        train(cfg, synthetic_data=True, max_steps=1)
        args = build_parser().parse_args(
            ["--checkpoint_dir", str(tmp_path / "ckpt"),
             "--out_dir", str(tmp_path / "out"), "--num_images", "8",
             "--batch_size", "8", "--grid", "0",
             "--npz", str(tmp_path / "gen.npz"),
             "--output_size", "16", "--gf_dim", "8", "--df_dim", "8",
             "--num_classes", "4", "--class_id", "2"])
        result = generate(args)
        assert result["num_images"] == 8
        data = np.load(tmp_path / "gen.npz")
        assert (data["labels"] == 2).all()

    @pytest.mark.parametrize("argv,match", [
        (["--batch_size", "0"], "batch_size"),
        (["--num_images", "-3"], "num_images"),
        (["--grid", "0x0"], "grid"),
        (["--grid", "8"], "grid"),
    ])
    def test_bad_arguments_rejected(self, tmp_path, argv, match):
        args = build_parser().parse_args(
            ["--checkpoint_dir", str(tmp_path / "ckpt")] + argv)
        with pytest.raises(SystemExit, match=match):
            generate(args)

    def test_class_id_out_of_range_errors(self, tmp_path):
        args = build_parser().parse_args(
            ["--checkpoint_dir", str(tmp_path / "ckpt"),
             "--num_classes", "4", "--class_id", "42"])
        with pytest.raises(SystemExit, match="out of range"):
            generate(args)

    def test_class_id_without_conditional_model_errors(self, tmp_path):
        args = build_parser().parse_args(
            ["--checkpoint_dir", str(tmp_path / "ckpt"), "--class_id", "0"])
        with pytest.raises(SystemExit, match="conditional"):
            generate(args)

    def test_explicit_flag_equal_to_global_default_beats_preset(self):
        from dcgan_tpu.generate import _model_config
        # 64 is both the global default and explicitly passed; the preset's
        # 32 must NOT win
        args = build_parser().parse_args(
            ["--checkpoint_dir", "x", "--preset", "cifar10-cond",
             "--output_size", "64"])
        mcfg = _model_config(args)
        assert mcfg.output_size == 64
        assert mcfg.num_classes == 10  # untouched preset field survives

    def test_grid_larger_than_batch_written_from_pool(self, trained_ckpt,
                                                      tmp_path):
        # grid cells (4x4=16) > batch_size (8): tiles must come from the
        # accumulated pool, not be silently skipped
        args = build_parser().parse_args(
            ["--checkpoint_dir", trained_ckpt,
             "--out_dir", str(tmp_path / "out"),
             "--num_images", "32", "--batch_size", "8", "--grid", "4x4",
             "--output_size", "16", "--gf_dim", "8", "--df_dim", "8"])
        result = generate(args)
        pngs = glob.glob(str(tmp_path / "out" / "gen_*.png"))
        assert len(pngs) == 2  # 32 images / 16 cells
        assert set(result["paths"]) == set(pngs)

    def test_preset_architecture_with_overrides(self, trained_ckpt, tmp_path):
        # preset supplies the architecture; explicit flags shrink it to match
        # the tiny checkpoint
        args = build_parser().parse_args(
            ["--checkpoint_dir", trained_ckpt, "--preset", "celeba64",
             "--out_dir", str(tmp_path / "out"), "--num_images", "4",
             "--batch_size", "8", "--grid", "0",
             "--npz", str(tmp_path / "gen.npz"),
             "--output_size", "16", "--gf_dim", "8", "--df_dim", "8"])
        result = generate(args)
        assert result["num_images"] == 4
        assert np.load(tmp_path / "gen.npz")["images"].shape == (4, 16, 16, 3)
