"""Spectral normalization (ops/spectral.py): power-iteration correctness,
gradient convention, explicit-state semantics through the model stacks, and
sharded-vs-single-device equivalence of an SN train step."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.models.dcgan import discriminator_apply, gan_init
from dcgan_tpu.ops.spectral import spectral_normalize, spectral_u_init
from dcgan_tpu.parallel import make_parallel_train
from dcgan_tpu.train import make_train_step

SN_TINY = ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                      spectral_norm="gd", compute_dtype="float32")


def real_batch(n=16, size=16):
    rng = np.random.default_rng(0)
    return jnp.asarray(
        np.tanh(rng.normal(size=(n, size, size, 3))).astype(np.float32))


class TestPowerIteration:
    @pytest.mark.slow
    def test_converges_to_largest_singular_value(self):
        w = jnp.asarray(np.random.default_rng(0).normal(
            size=(48, 32)).astype(np.float32))
        true_sigma = float(np.linalg.svd(np.asarray(w),
                                         compute_uv=False)[0])
        u = spectral_u_init(jax.random.key(0), 32)
        for _ in range(50):
            w_sn, u = spectral_normalize(w, u, train=True)
        sn_sigma = float(np.linalg.svd(np.asarray(w_sn),
                                       compute_uv=False)[0])
        np.testing.assert_allclose(sn_sigma, 1.0, rtol=1e-3)
        # implied sigma = any w / w_sn element
        est = float(np.asarray(w).flat[0] / np.asarray(w_sn).flat[0])
        np.testing.assert_allclose(est, true_sigma, rtol=1e-3)

    def test_conv_kernel_rank_handled(self):
        w = jnp.asarray(np.random.default_rng(1).normal(
            size=(5, 5, 8, 16)).astype(np.float32))
        u = spectral_u_init(jax.random.key(1), 16)
        w_sn, u2 = spectral_normalize(w, u, train=True)
        assert w_sn.shape == w.shape and u2.shape == (16,)
        m = np.asarray(w_sn).reshape(-1, 16)
        assert np.linalg.svd(m, compute_uv=False)[0] < 3.0  # 1-ish, bounded

    def test_eval_mode_freezes_u(self):
        w = jnp.asarray(np.random.default_rng(2).normal(
            size=(16, 8)).astype(np.float32))
        u = spectral_u_init(jax.random.key(2), 8)
        _, u_eval = spectral_normalize(w, u, train=False)
        np.testing.assert_array_equal(np.asarray(u_eval), np.asarray(u))
        _, u_train = spectral_normalize(w, u, train=True)
        assert np.abs(np.asarray(u_train) - np.asarray(u)).max() > 0

    def test_gradient_flows_through_sigma(self):
        """The paper's convention: u/v are constants but sigma keeps W live,
        so the gradient of sum(w_sn) differs from naive (1/sigma) scaling."""
        w = jnp.asarray(np.random.default_rng(3).normal(
            size=(16, 8)).astype(np.float32))
        u = spectral_u_init(jax.random.key(3), 8)

        def loss(w):
            w_sn, _ = spectral_normalize(w, u, train=False)
            return jnp.sum(w_sn ** 2)

        g = jax.grad(loss)(w)
        assert np.all(np.isfinite(np.asarray(g)))
        # naive scaling gradient would be 2*w/sigma^2; the sigma term makes
        # them differ
        w_sn, _ = spectral_normalize(w, u, train=False)
        sigma = float(np.asarray(w).flat[0] / np.asarray(w_sn).flat[0])
        naive = 2.0 * np.asarray(w) / sigma ** 2
        assert np.abs(np.asarray(g) - naive).max() > 1e-6


class TestModelWiring:
    def test_state_leaves_created(self):
        params, state = gan_init(jax.random.key(0),
                                 dataclasses.replace(SN_TINY, attn_res=8))
        d_sn = {k for k in state["disc"] if k.startswith("sn_")}
        assert d_sn == {"sn_conv0", "sn_conv1", "sn_head", "sn_attn_query",
                        "sn_attn_key", "sn_attn_value", "sn_attn_out"}
        g_sn = {k for k in state["gen"] if k.startswith("sn_")}
        assert g_sn == {"sn_proj", "sn_deconv1", "sn_deconv2",
                        "sn_attn_query", "sn_attn_key", "sn_attn_value",
                        "sn_attn_out"}

    def test_d_only_mode(self):
        cfg = dataclasses.replace(SN_TINY, spectral_norm="d")
        _, state = gan_init(jax.random.key(0), cfg)
        assert any(k.startswith("sn_") for k in state["disc"])
        assert not any(k.startswith("sn_") for k in state["gen"])

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError, match="spectral_norm"):
            ModelConfig(spectral_norm="both")

    def test_layer_init_independent_of_flag(self):
        """Turning SN on must not shift any layer's weight init stream —
        a checkpoint's weights mean the same thing either way."""
        p_off, _ = gan_init(jax.random.key(0),
                            dataclasses.replace(SN_TINY, spectral_norm="none"))
        p_on, _ = gan_init(jax.random.key(0), SN_TINY)
        np.testing.assert_array_equal(
            np.asarray(p_off["disc"]["conv0"]["w"]),
            np.asarray(p_on["disc"]["conv0"]["w"]))
        np.testing.assert_array_equal(
            np.asarray(p_off["gen"]["proj"]["w"]),
            np.asarray(p_on["gen"]["proj"]["w"]))

    def test_eval_apply_preserves_state(self):
        params, state = gan_init(jax.random.key(0), SN_TINY)
        _, _, new_state = discriminator_apply(
            params["disc"], state["disc"], real_batch(4), cfg=SN_TINY,
            train=False)
        np.testing.assert_array_equal(np.asarray(new_state["sn_conv0"]),
                                      np.asarray(state["disc"]["sn_conv0"]))


class TestSNTraining:
    def test_train_step_advances_u_and_learns(self):
        cfg = TrainConfig(model=SN_TINY, batch_size=8,
                          mesh=MeshConfig(data=1), loss="hinge")
        fns = make_train_step(cfg)
        state = fns.init(jax.random.key(0))
        u0 = np.asarray(state["bn"]["disc"]["sn_conv0"])
        xs = real_batch(8)
        step = jax.jit(fns.train_step)
        first = None
        for i in range(10):
            state, m = step(state, xs, jax.random.fold_in(jax.random.key(1),
                                                          i))
            if first is None:
                first = float(m["d_loss"])
        assert float(m["d_loss"]) < first
        assert np.abs(np.asarray(state["bn"]["disc"]["sn_conv0"])
                      - u0).max() > 0
        for v in m.values():
            assert np.isfinite(float(v))

    @pytest.mark.slow
    def test_sharded_sn_step_matches_single_device(self):
        cfg = TrainConfig(model=SN_TINY, batch_size=16, mesh=MeshConfig(),
                          loss="hinge")
        xs, key = real_batch(), jax.random.key(3)
        fns = make_train_step(cfg)
        s_ref, m_ref = jax.jit(fns.train_step)(fns.init(jax.random.key(0)),
                                               xs, key)
        pt = make_parallel_train(cfg)
        s_par, m_par = pt.step(pt.init(jax.random.key(0)), xs, key)
        np.testing.assert_allclose(float(m_par["d_loss"]),
                                   float(m_ref["d_loss"]), rtol=1e-4)
        np.testing.assert_allclose(float(m_par["g_loss"]),
                                   float(m_ref["g_loss"]), rtol=1e-4)
        # same u trajectory (replicated state, deterministic iteration)
        np.testing.assert_allclose(
            np.asarray(s_par["bn"]["disc"]["sn_conv0"]),
            np.asarray(s_ref["bn"]["disc"]["sn_conv0"]), atol=1e-5)
