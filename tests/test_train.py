"""Training-layer tests: loss values, update modes, BN-state plumbing, WGAN-GP,
overfit smoke, determinism (SURVEY.md §4 test plan)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.config import ModelConfig, TrainConfig
from dcgan_tpu.train import make_train_step
from dcgan_tpu.train.losses import (
    bce_gan_losses,
    gradient_penalty,
    sigmoid_bce,
    wgan_losses,
)

TINY = ModelConfig(output_size=16, gf_dim=8, df_dim=8, base_size=4,
                   compute_dtype="float32")


def tiny_cfg(**kw):
    return TrainConfig(model=TINY, batch_size=8, **kw)


def real_batch(n=8, size=16):
    rng = np.random.default_rng(0)
    return jnp.asarray(
        np.tanh(rng.normal(size=(n, size, size, 3))).astype(np.float32))


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

class TestLosses:
    def test_sigmoid_bce_golden(self):
        """Golden values: BCE(0, t) = log 2 for either target."""
        z = jnp.zeros((4,))
        np.testing.assert_allclose(float(sigmoid_bce(z, 1.0)), np.log(2),
                                   rtol=1e-6)
        np.testing.assert_allclose(float(sigmoid_bce(z, 0.0)), np.log(2),
                                   rtol=1e-6)
        # large logits are numerically stable, not inf/nan
        big = jnp.array([1e4, -1e4])
        assert np.isfinite(float(sigmoid_bce(big, 1.0)))
        np.testing.assert_allclose(float(sigmoid_bce(jnp.array([1e4]), 1.0)),
                                   0.0, atol=1e-6)

    def test_bce_gan_losses_trio(self):
        """The reference's loss trio (image_train.py:91-96): d = real + fake."""
        r = jnp.array([2.0, -1.0])
        f = jnp.array([0.5, 0.0])
        d, dr, df, g = bce_gan_losses(r, f)
        np.testing.assert_allclose(float(d), float(dr) + float(df), rtol=1e-6)
        np.testing.assert_allclose(float(dr), float(sigmoid_bce(r, 1.0)))
        np.testing.assert_allclose(float(df), float(sigmoid_bce(f, 0.0)))
        np.testing.assert_allclose(float(g), float(sigmoid_bce(f, 1.0)))

    def test_wgan_losses(self):
        r = jnp.array([3.0, 1.0])
        f = jnp.array([0.5, 1.5])
        d, dr, df, g = wgan_losses(r, f)
        np.testing.assert_allclose(float(d), -2.0 + 1.0, rtol=1e-6)
        np.testing.assert_allclose(float(g), -1.0, rtol=1e-6)

    def test_hinge_losses_golden(self):
        """relu margins: real logits above 1 and fake below -1 cost nothing;
        inside the margin the cost is linear."""
        from dcgan_tpu.train.losses import hinge_losses

        r = jnp.array([2.0, 0.5])    # relu(1-2)=0, relu(1-0.5)=0.5
        f = jnp.array([-3.0, 0.0])   # relu(1-3)=0, relu(1+0)=1
        d, dr, df, g = hinge_losses(r, f)
        np.testing.assert_allclose(float(dr), 0.25, rtol=1e-6)
        np.testing.assert_allclose(float(df), 0.5, rtol=1e-6)
        np.testing.assert_allclose(float(d), 0.75, rtol=1e-6)
        np.testing.assert_allclose(float(g), 1.5, rtol=1e-6)  # -mean(f)

    def test_gradient_penalty_golden(self):
        """For D(x) = a.x, grad norm is ||a|| everywhere: gp = (||a||-1)^2."""
        a = jnp.array([3.0, 4.0])  # ||a|| = 5
        critic = lambda x: x @ a
        real = jnp.ones((16, 2))
        fake = -jnp.ones((16, 2))
        gp = gradient_penalty(critic, real, fake, jax.random.key(0))
        np.testing.assert_allclose(float(gp), 16.0, rtol=1e-5)

    def test_r1_penalty_golden(self):
        """For D(x) = a.x, R1 = E[||a||^2] = 25 regardless of the inputs
        (zero-centered: no -1 target, no interpolates)."""
        from dcgan_tpu.train.losses import r1_penalty

        a = jnp.array([3.0, 4.0])
        critic = lambda x: x @ a
        r1 = r1_penalty(critic, jnp.ones((16, 2)))
        np.testing.assert_allclose(float(r1), 25.0, rtol=1e-5)


# ---------------------------------------------------------------------------
# train step
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestTrainStep:
    def test_step_updates_everything(self):
        fns = make_train_step(tiny_cfg())
        s0 = fns.init(jax.random.key(0))
        s1, m = jax.jit(fns.train_step)(s0, real_batch(), jax.random.key(1))
        assert int(s1["step"]) == 1
        for net in ("gen", "disc"):
            # params moved
            diff = jax.tree_util.tree_map(
                lambda a, b: float(jnp.max(jnp.abs(a - b))),
                s0["params"][net], s1["params"][net])
            assert max(jax.tree_util.tree_leaves(diff)) > 0
        # BN running stats moved for both nets
        for net in ("gen", "disc"):
            diff = jax.tree_util.tree_map(
                lambda a, b: float(jnp.max(jnp.abs(a - b))),
                s0["bn"][net], s1["bn"][net])
            assert max(jax.tree_util.tree_leaves(diff)) > 0
        for k in ("d_loss", "d_loss_real", "d_loss_fake", "g_loss"):
            assert np.isfinite(float(m[k])), k

    def test_sequential_vs_fused_differ(self):
        """Sequential G-step sees the updated D; fused (reference parity,
        SURVEY.md §2.4 #2) sees the pre-update D — gen updates must differ."""
        xs, key = real_batch(), jax.random.key(1)
        outs = {}
        for mode in ("sequential", "fused"):
            fns = make_train_step(tiny_cfg(update_mode=mode))
            s = fns.init(jax.random.key(0))
            s1, _ = jax.jit(fns.train_step)(s, xs, key)
            outs[mode] = s1
        d_gen = jax.tree_util.tree_map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            outs["sequential"]["params"]["gen"], outs["fused"]["params"]["gen"])
        assert max(jax.tree_util.tree_leaves(d_gen)) > 0
        # D step itself is identical in both modes
        d_disc = jax.tree_util.tree_map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            outs["sequential"]["params"]["disc"], outs["fused"]["params"]["disc"])
        assert max(jax.tree_util.tree_leaves(d_disc)) == 0

    def test_wgan_gp_step(self):
        fns = make_train_step(tiny_cfg(loss="wgan-gp"))
        s = fns.init(jax.random.key(0))
        s, m = jax.jit(fns.train_step)(s, real_batch(), jax.random.key(1))
        assert "gp" in m and np.isfinite(float(m["gp"]))
        assert np.isfinite(float(m["d_loss"]))

    def test_r1_step(self):
        """R1 on the BCE family: the r1 metric appears and regularizes
        (double differentiation through the D apply, like WGAN-GP's)."""
        fns = make_train_step(tiny_cfg(r1_gamma=10.0))
        s = fns.init(jax.random.key(0))
        s, m = jax.jit(fns.train_step)(s, real_batch(), jax.random.key(1))
        assert "r1" in m and "gp" not in m
        assert float(m["r1"]) > 0 and np.isfinite(float(m["d_loss"]))

    def test_r1_rejects_wgan_gp(self):
        with pytest.raises(ValueError, match="r1_gamma"):
            tiny_cfg(loss="wgan-gp", r1_gamma=10.0)

    def test_r1_lazy_interval(self):
        """r1_interval=2: the penalty runs on even steps only (lax.cond) —
        the r1 metric is live at step 0 and exactly zero at step 1."""
        fns = make_train_step(tiny_cfg(r1_gamma=10.0, r1_interval=2))
        s = fns.init(jax.random.key(0))
        step = jax.jit(fns.train_step)
        s, m0 = step(s, real_batch(), jax.random.key(1))
        s, m1 = step(s, real_batch(), jax.random.key(2))
        assert float(m0["r1"]) > 0.0
        assert float(m1["r1"]) == 0.0
        with pytest.raises(ValueError, match="r1_interval"):
            tiny_cfg(r1_gamma=10.0, r1_interval=0)
        with pytest.raises(ValueError, match="no-op"):
            tiny_cfg(r1_interval=16)  # interval without gamma

    def test_label_smoothing(self):
        """One-sided smoothing: only d_loss_real changes; hard targets at
        eps=0 reproduce the reference trio exactly."""
        from dcgan_tpu.train.losses import bce_gan_losses, sigmoid_bce

        rl = jnp.array([2.0, -1.0])
        fl = jnp.array([0.5, -0.5])
        d, dr, df, g = bce_gan_losses(rl, fl, label_smoothing=0.1)
        d0, dr0, df0, g0 = bce_gan_losses(rl, fl)
        np.testing.assert_allclose(float(dr), float(sigmoid_bce(rl, 0.9)),
                                   rtol=1e-6)
        assert float(df) == float(df0) and float(g) == float(g0)
        with pytest.raises(ValueError, match="label_smoothing"):
            tiny_cfg(loss="hinge", label_smoothing=0.1)
        # wired through the step
        fns = make_train_step(tiny_cfg(label_smoothing=0.1))
        s, m = jax.jit(fns.train_step)(fns.init(jax.random.key(0)),
                                       real_batch(), jax.random.key(1))
        assert np.isfinite(float(m["d_loss"]))

    def test_grad_clip(self):
        """clip_by_global_norm chains BEFORE Adam: updating with huge grads
        under clip=1 must equal updating with the pre-clipped grads under no
        clip (Adam itself is scale-invariant per step, so parameter movement
        is the wrong observable)."""
        from dcgan_tpu.train.steps import make_optimizer

        params = {"w": jnp.ones((4,))}
        grads = {"w": jnp.full((4,), 500.0)}         # global norm 1000
        clipped = {"w": grads["w"] / 1000.0}         # norm 1

        opt_c = make_optimizer(tiny_cfg(grad_clip=1.0))
        u_c, _ = opt_c.update(grads, opt_c.init(params), params)

        opt_0 = make_optimizer(tiny_cfg())
        u_0, _ = opt_0.update(clipped, opt_0.init(params), params)

        np.testing.assert_allclose(np.asarray(u_c["w"]),
                                   np.asarray(u_0["w"]), rtol=1e-6)
        # and the step runs end to end with the chained optimizer
        fns = make_train_step(tiny_cfg(grad_clip=1.0))
        _, m = jax.jit(fns.train_step)(fns.init(jax.random.key(0)),
                                       real_batch(), jax.random.key(1))
        assert np.isfinite(float(m["d_loss"]))
        with pytest.raises(ValueError, match="grad_clip"):
            tiny_cfg(grad_clip=-1.0)

    def test_r1_eval_probe_interval_independent(self):
        """The held-out loss probe computes R1 unscaled every call, so its
        d_loss is comparable across r1_interval settings."""
        xs, z = real_batch(), jnp.zeros((8, 100))
        vals = []
        for k in (1, 4):
            fns = make_train_step(tiny_cfg(r1_gamma=10.0, r1_interval=k))
            s = fns.init(jax.random.key(0))
            vals.append(float(jax.jit(fns.eval_losses)(s, xs, z)["d_loss"]))
        np.testing.assert_allclose(vals[0], vals[1], rtol=1e-6)

    def test_hinge_step(self):
        fns = make_train_step(tiny_cfg(loss="hinge"))
        s0 = fns.init(jax.random.key(0))
        s1, m = jax.jit(fns.train_step)(s0, real_batch(), jax.random.key(1))
        assert "gp" not in m
        assert all(np.isfinite(float(v)) for v in m.values())
        moved = jax.tree_util.tree_map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            s0["params"], s1["params"])
        assert max(jax.tree_util.tree_leaves(moved)) > 0

    def test_n_critic_scan(self):
        """n_critic=3 runs three scanned critic updates per step: the critic
        params must move further than a single-update step from the same
        state/batch, and the step counter still advances by one."""
        xs, key = real_batch(), jax.random.key(1)
        states = {}
        for n in (1, 3):
            fns = make_train_step(tiny_cfg(loss="wgan-gp", n_critic=n))
            s = fns.init(jax.random.key(0))
            s1, m = jax.jit(fns.train_step)(s, xs, key)
            assert int(s1["step"]) == 1
            assert np.isfinite(float(m["d_loss"]))
            states[n] = (s, s1)
        s0, one = states[1]
        _, three = states[3]

        def total_move(a, b):
            return sum(float(jnp.sum(jnp.abs(x - y))) for x, y in zip(
                jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)))
        assert total_move(s0["params"]["disc"], three["params"]["disc"]) > \
            total_move(s0["params"]["disc"], one["params"]["disc"])

    def test_g_ema_tracking(self):
        """g_ema_decay > 0: ema_gen = d*ema + (1-d)*new_gen each step, and
        sample() draws from the EMA copy; off by default (reference samples
        live weights, image_train.py:181-184)."""
        d = 0.5  # large blend so one step moves the EMA measurably
        fns = make_train_step(tiny_cfg(g_ema_decay=d))
        state = fns.init(jax.random.key(0))
        ema0 = jax.tree_util.tree_map(np.asarray, state["ema_gen"])
        gen0 = jax.tree_util.tree_map(np.asarray, state["params"]["gen"])
        jax.tree_util.tree_map(np.testing.assert_array_equal, ema0, gen0)

        step = jax.jit(fns.train_step)
        state1, _ = step(state, real_batch(), jax.random.key(1))
        expected = jax.tree_util.tree_map(
            lambda e, p: d * e + (1 - d) * np.asarray(p),
            ema0, state1["params"]["gen"])
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), b,
                                                    rtol=1e-6),
            state1["ema_gen"], expected)

        # sampling uses the EMA copy: corrupt it and the output must change
        z = jax.random.uniform(jax.random.key(2), (8, 100),
                               minval=-1, maxval=1)
        img_ema = fns.sample(state1, z)
        live_state = dict(state1)
        live_state["ema_gen"] = state1["params"]["gen"]
        img_live = fns.sample(live_state, z)
        assert float(jnp.max(jnp.abs(img_ema - img_live))) > 0

        # decay=0 (default/reference parity): ema_gen still EXISTS — the
        # checkpoint tree must not change shape with the flag — but is a
        # live mirror, and sample() uses the live weights
        fns_off = make_train_step(tiny_cfg())
        s_off = fns_off.init(jax.random.key(0))
        assert "ema_gen" in s_off
        s_off1, _ = jax.jit(fns_off.train_step)(s_off, real_batch(),
                                                jax.random.key(1))
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            s_off1["ema_gen"], s_off1["params"]["gen"])

    def test_g_ema_checkpoint_shape_flag_independent(self):
        """The state tree structure is identical with EMA on or off, so an
        EMA-trained checkpoint restores under an eval/generate/resume config
        with the flag unset (and vice versa)."""
        s_on = make_train_step(tiny_cfg(g_ema_decay=0.999)).init(
            jax.random.key(0))
        s_off = make_train_step(tiny_cfg()).init(jax.random.key(0))
        assert jax.tree_util.tree_structure(s_on) == \
            jax.tree_util.tree_structure(s_off)

    def test_ttur_per_net_rates(self):
        """d_learning_rate=0 freezes D while G still moves (and vice versa) —
        the per-net rates really reach their respective Adam applies."""
        xs, key = real_batch(), jax.random.key(1)
        fns = make_train_step(tiny_cfg(d_learning_rate=0.0))
        s0 = fns.init(jax.random.key(0))
        s1, _ = jax.jit(fns.train_step)(s0, xs, key)

        def moved(a, b):
            return max(jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                lambda x, y: float(jnp.max(jnp.abs(x - y))), a, b)))
        assert moved(s0["params"]["disc"], s1["params"]["disc"]) == 0
        assert moved(s0["params"]["gen"], s1["params"]["gen"]) > 0

        fns = make_train_step(tiny_cfg(g_learning_rate=0.0))
        s0 = fns.init(jax.random.key(0))
        s1, _ = jax.jit(fns.train_step)(s0, xs, key)
        assert moved(s0["params"]["gen"], s1["params"]["gen"]) == 0
        assert moved(s0["params"]["disc"], s1["params"]["disc"]) > 0

    def test_lr_schedules(self):
        """Schedule curves: warmup ramps 0 -> base; linear hits 0 at
        max_steps; cosine halves at midpoint; constant stays flat — and the
        optimizer state tree has the same shape for every schedule flag."""
        from dcgan_tpu.train.steps import make_lr_schedule

        base = 2e-4
        cfg = tiny_cfg(max_steps=1000)
        const = make_lr_schedule(cfg, base)
        np.testing.assert_allclose(float(const(0)), base)
        np.testing.assert_allclose(float(const(999)), base)

        lin = make_lr_schedule(tiny_cfg(max_steps=1000, lr_schedule="linear"),
                               base)
        np.testing.assert_allclose(float(lin(0)), base)
        np.testing.assert_allclose(float(lin(500)), base / 2, rtol=1e-5)
        np.testing.assert_allclose(float(lin(1000)), 0.0, atol=1e-12)

        cos = make_lr_schedule(tiny_cfg(max_steps=1000, lr_schedule="cosine"),
                               base)
        np.testing.assert_allclose(float(cos(500)), base / 2, rtol=1e-5)

        warm = make_lr_schedule(
            tiny_cfg(max_steps=1000, lr_schedule="linear", warmup_steps=100),
            base)
        np.testing.assert_allclose(float(warm(0)), 0.0, atol=1e-12)
        np.testing.assert_allclose(float(warm(50)), base / 2, rtol=1e-5)
        np.testing.assert_allclose(float(warm(100)), base, rtol=1e-5)

        shapes = {
            sched: jax.tree_util.tree_structure(
                make_train_step(tiny_cfg(lr_schedule=sched)).init(
                    jax.random.key(0)))
            for sched in ("constant", "linear")
        }
        assert shapes["constant"] == shapes["linear"]

    def test_bad_schedule_rejected(self):
        with pytest.raises(ValueError, match="lr_schedule"):
            tiny_cfg(lr_schedule="step")
        with pytest.raises(ValueError, match="warmup_steps"):
            tiny_cfg(warmup_steps=-1)
        with pytest.raises(ValueError, match="decay schedule would never"):
            tiny_cfg(warmup_steps=2_000_000)  # >= max_steps default

    def test_critic_schedule_tracks_trainer_steps(self):
        """With n_critic=5, D's optimizer advances its schedule 5x per
        trainer step — the horizon stretch keeps its decay aligned to the
        generator's timeline (lr at update-count 5k equals the 1-critic lr
        at step k)."""
        from dcgan_tpu.train.steps import make_lr_schedule

        base = 2e-4
        cfg = tiny_cfg(max_steps=1000, lr_schedule="linear", n_critic=5,
                       loss="wgan-gp")
        d_sched = make_lr_schedule(cfg, base, updates_per_step=5)
        g_sched = make_lr_schedule(cfg, base)
        for step in (0, 250, 500, 999):
            np.testing.assert_allclose(float(d_sched(5 * step)),
                                       float(g_sched(step)), rtol=1e-5)

    def test_g_ema_decay_validated(self):
        with pytest.raises(ValueError, match="g_ema_decay"):
            tiny_cfg(g_ema_decay=1.0)

    def test_n_critic_fused_rejected(self):
        with pytest.raises(ValueError):
            tiny_cfg(n_critic=3, update_mode="fused")
        with pytest.raises(ValueError):
            tiny_cfg(n_critic=0)

    def test_determinism(self):
        """Fixed PRNG key -> bitwise-identical step on CPU (SURVEY.md §4)."""
        fns = make_train_step(tiny_cfg())
        step = jax.jit(fns.train_step)
        xs, key = real_batch(), jax.random.key(7)
        s_a, m_a = step(fns.init(jax.random.key(0)), xs, key)
        s_b, m_b = step(fns.init(jax.random.key(0)), xs, key)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), s_a["params"], s_b["params"])
        assert float(m_a["d_loss"]) == float(m_b["d_loss"])

    def test_overfit_smoke(self):
        """1-batch overfit: D separates real from fake within 40 steps
        (d_loss well below its log(4)≈1.386 untrained value) and G's loss
        responds — the end-to-end trajectory check from SURVEY.md §4."""
        fns = make_train_step(tiny_cfg())
        step = jax.jit(fns.train_step, donate_argnums=(0,))
        s = fns.init(jax.random.key(0))
        xs = real_batch()
        base = jax.random.key(1)
        first = last = None
        for i in range(40):
            s, m = step(s, xs, jax.random.fold_in(base, i))
            if first is None:
                first = {k: float(v) for k, v in m.items()}
            last = {k: float(v) for k, v in m.items()}
        assert last["d_loss"] < first["d_loss"]
        assert last["d_loss"] < 1.0
        assert np.isfinite(last["g_loss"])

    def test_conditional_step(self):
        cfg = TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              num_classes=4, compute_dtype="float32"),
            batch_size=8)
        fns = make_train_step(cfg)
        s = fns.init(jax.random.key(0))
        y = jnp.arange(8) % 4
        s, m = jax.jit(fns.train_step)(s, real_batch(), jax.random.key(1), y)
        assert np.isfinite(float(m["d_loss"]))
