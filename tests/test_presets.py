"""Preset configs (presets.py) — the BASELINE.json config matrix — and the
CLI --preset path with explicit-flag overrides."""

import pytest

from dcgan_tpu.presets import PRESETS, get_preset
from dcgan_tpu.train.cli import apply_overrides, explicit_flags


class TestPresets:
    def test_all_baseline_configs_named(self):
        # BASELINE.json lists exactly five configurations; sagan64 and
        # sngan-cifar10 are the beyond-BASELINE attention / resnet families
        # (presets.py docstrings).
        assert set(PRESETS) == {
            "celeba64", "lsun64-dp8", "dcgan128", "cifar10-cond", "wgan-gp",
            "sagan64", "sagan128", "sagan256-lc", "sngan-cifar10",
            "stylegan64"}

    def test_celeba64_is_reference_headline(self):
        cfg = get_preset("celeba64")
        assert cfg.model.output_size == 64 and cfg.model.z_dim == 100
        assert cfg.batch_size == 64 and cfg.dataset == "celebA"
        assert cfg.learning_rate == 2e-4 and cfg.beta1 == 0.5

    def test_lsun_dp8_mesh_and_global_batch(self):
        cfg = get_preset("lsun64-dp8")
        assert cfg.mesh.data == 8
        assert cfg.batch_size == 64 * 8
        assert cfg.dataset == "lsun-bedroom"

    def test_dcgan128_deepens_stacks(self):
        cfg = get_preset("dcgan128")
        assert cfg.model.output_size == 128
        assert cfg.model.num_up_layers == 5

    def test_cifar10_conditional(self):
        cfg = get_preset("cifar10-cond")
        assert cfg.model.num_classes == 10
        assert cfg.model.output_size == 32
        assert cfg.dataset == "cifar10"

    def test_wgan_gp_loss_and_hparams(self):
        cfg = get_preset("wgan-gp")
        assert cfg.loss == "wgan-gp"
        assert cfg.learning_rate == 1e-4 and cfg.beta1 == 0.0
        assert cfg.n_critic == 5

    def test_sagan64_recipe(self):
        cfg = get_preset("sagan64")
        assert cfg.model.attn_res == 32
        assert cfg.model.spectral_norm == "gd"
        assert cfg.loss == "hinge" and cfg.beta1 == 0.0
        assert cfg.d_learning_rate == 4e-4 and cfg.g_learning_rate == 1e-4
        assert cfg.g_ema_decay == 0.999

    def test_sagan128_long_sequence_demo(self):
        cfg = get_preset("sagan128")
        assert cfg.model.output_size == 128 and cfg.model.attn_res == 64
        # attention stage sequence length = 64*64 = 4096 tokens
        assert cfg.model.attn_res ** 2 == 4096
        assert cfg.model.spectral_norm == "gd" and cfg.loss == "hinge"

    def test_sagan256_lc_is_flash_only_config(self):
        cfg = get_preset("sagan256-lc")
        assert cfg.model.output_size == 256 and cfg.model.attn_res == 128
        # attention stage sequence length = 128*128 = 16384 tokens — the
        # scale where dense attention cannot allocate at batch 64 and the
        # flash kernels are what makes the config trainable (DESIGN.md §8b)
        assert cfg.model.attn_res ** 2 == 16384
        assert cfg.model.use_pallas
        # shard_map backend: the one backend where use_pallas + attn_res
        # composes on multi-device data-parallel meshes (parallel/api.py
        # rejects the pair under multi-device gspmd)
        assert cfg.backend == "shard_map"
        assert cfg.model.spectral_norm == "d" and cfg.loss == "hinge"

    def test_sngan_cifar10_recipe(self):
        cfg = get_preset("sngan-cifar10")
        assert cfg.model.arch == "resnet" and cfg.model.output_size == 32
        assert cfg.model.spectral_norm == "d" and cfg.loss == "hinge"
        assert cfg.n_critic == 5 and cfg.beta1 == 0.0

    def test_factory_overrides(self):
        cfg = get_preset("celeba64", batch_size=128, seed=7)
        assert cfg.batch_size == 128 and cfg.seed == 7

    def test_unknown_preset_rejected(self):
        with pytest.raises(ValueError, match="unknown preset"):
            get_preset("biggan")


class TestCLIPreset:
    def test_preset_defaults_flow_through(self):
        argv = ["--preset", "wgan-gp"]
        cfg = apply_overrides(get_preset("wgan-gp"), explicit_flags(argv))
        assert cfg.loss == "wgan-gp" and cfg.learning_rate == 1e-4

    def test_explicit_flags_beat_preset(self):
        argv = ["--preset", "wgan-gp", "--learning_rate", "3e-4",
                "--batch_size", "32", "--no_normalize"]
        cfg = apply_overrides(get_preset("wgan-gp"), explicit_flags(argv))
        assert cfg.learning_rate == 3e-4
        assert cfg.batch_size == 32
        assert not cfg.normalize_inputs
        assert cfg.loss == "wgan-gp" and cfg.beta1 == 0.0  # preset survives

    def test_model_and_mesh_overrides(self):
        argv = ["--preset", "lsun64-dp8", "--gf_dim", "32", "--mesh_data", "4"]
        cfg = apply_overrides(get_preset("lsun64-dp8"), explicit_flags(argv))
        assert cfg.model.gf_dim == 32
        assert cfg.mesh.data == 4
        assert cfg.batch_size == 64 * 8  # untouched preset field

    def test_untouched_flags_do_not_leak(self):
        # Flags left at argparse defaults must not clobber preset values.
        argv = ["--preset", "cifar10-cond"]
        cfg = apply_overrides(get_preset("cifar10-cond"), explicit_flags(argv))
        assert cfg.model.num_classes == 10      # argparse default is 0
        assert cfg.model.output_size == 32      # argparse default is 64
