"""Collective overlap plane (ISSUE 20, DESIGN §6n).

The contract this file pins: `--comm_overlap {bucket,prefetch}` is a
WIRE-PLAN change, never a math change. The bucketed reduce-scatter /
all-gather and the layer-ahead staged param gather must produce
BIT-identical training trajectories to the per-leaf `off` plan — full
params trees compared with np.array_equal after 8 real steps, at every
ZeRO stage, for both the fused step and the pipelined G/D stages. On
top of that: the pack/unpack round trip is exact leaf-for-leaf (mixed
dtypes, leaves larger than the bucket cap), the bucket plan groups by
dtype and respects the cap, the config validation rejects the
impossible arms (prefetch without ZeRO-3, a non-positive cap), the
XLA flag helper never fires on non-TPU hosts, the warmup plan still
covers every program an overlap run can dispatch (rollback drill with
zero compile-cache misses), and the bench A/B row rides before the
headline row with per-arm collective-op censuses.

The census-shrink half of the acceptance (one collective per bucket
instead of one per leaf) is pinned by the committed manifest's
`@overlap` rows, checked in tests/test_zero.py and the analyzer lock
byte-compare in tests/test_tools.py.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.elastic import rules
from dcgan_tpu.parallel import comm, make_parallel_train
from dcgan_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = dict(output_size=16, gf_dim=8, df_dim=8, compute_dtype="float32")


def _mesh2():
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:2]).reshape(2, 1),
                (DATA_AXIS, MODEL_AXIS))


def _batch():
    rng = np.random.default_rng(0)
    return jnp.asarray(np.tanh(rng.normal(size=(8, 16, 16, 3)))
                       .astype(np.float32))


# -- pack/unpack round trip (pure data movement, no mesh) -------------------

def _mixed_leaves():
    """Leaves exercising every packing regime: different ranks, different
    scatter dims, a dtype split, and one leaf big enough to overflow a
    tiny cap on its own."""
    rng = np.random.default_rng(7)
    leaves = [
        jnp.asarray(rng.normal(size=(4, 3)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(2, 6, 3)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(3, 4)).astype(np.float32)),  # dim 1
        jnp.asarray(rng.integers(0, 9, size=(6, 2)).astype(np.int32)),
        jnp.asarray(rng.normal(size=(512, 8)).astype(np.float32)),  # big
    ]
    dims = [0, 1, 1, 0, 0]
    return leaves, dims


def _dtype_groups(leaves):
    """Index groups per dtype, insertion-ordered — the dtype-purity the
    real bucket plan guarantees (mixed packs would promote)."""
    groups = {}
    for i, x in enumerate(leaves):
        groups.setdefault(str(x.dtype), []).append(i)
    return list(groups.values())


class TestPackUnpackRoundTrip:
    N = 2

    def test_scatter_pack_rows_are_per_shard_blocks(self):
        """Row k of the packed buffer must be exactly the flat of the
        block the per-leaf psum_scatter would hand shard k — that
        equivalence is the whole bit-exactness argument."""
        leaves, dims = _mixed_leaves()
        idxs = [0, 1, 2]
        buf, segs = comm.pack_scatter(leaves, dims, idxs, self.N)
        total = sum(w for _, w, _ in segs)
        view = np.asarray(buf).reshape(self.N, total)
        for k in range(self.N):
            o = 0
            for i, width, moved_shape in segs:
                row = view[k, o:o + width]
                o += width
                moved = np.moveaxis(np.asarray(leaves[i]), dims[i], 0)
                blk = moved.reshape(self.N, -1)[k]
                assert np.array_equal(row, blk), f"leaf {i} shard {k}"

    def test_scatter_unpack_reassembles_leaves_exactly(self):
        """Emulate the collective host-side: shard k keeps row k of the
        packed buffer; unpacking every shard and concatenating the local
        blocks along each leaf's scatter dim must reproduce the input
        bit-for-bit."""
        leaves, dims = _mixed_leaves()
        shards = [[None] * len(leaves) for _ in range(self.N)]
        # one pack per dtype group, exactly like the bucket plan (mixed
        # dtypes in one buffer would force a promoting concatenate)
        for idxs in _dtype_groups(leaves):
            buf, segs = comm.pack_scatter(leaves, dims, idxs, self.N)
            total = sum(w for _, w, _ in segs)
            view = jnp.reshape(buf, (self.N, total))
            for k in range(self.N):
                comm.unpack_scatter(view[k], segs, self.N, dims,
                                    shards[k])
        for i, d in enumerate(dims):
            full = jnp.concatenate([shards[k][i] for k in range(self.N)],
                                   axis=d)
            assert np.array_equal(np.asarray(full),
                                  np.asarray(leaves[i])), f"leaf {i}"
            assert full.dtype == leaves[i].dtype

    def test_gather_round_trip_reassembles_leaves_exactly(self):
        """Split each leaf into its shard-local blocks, pack each
        shard's blocks, emulate the tiled all_gather by concatenating
        the segments, and unpack — every FULL leaf must come back
        bit-identical."""
        leaves, dims = _mixed_leaves()
        out = [None] * len(leaves)
        for idxs in _dtype_groups(leaves):
            segments, segs = [], None
            for k in range(self.N):
                local = [jnp.moveaxis(jnp.split(jnp.moveaxis(x, d, 0),
                                                self.N, axis=0)[k], 0, d)
                         for x, d in zip(leaves, dims)]
                seg, segs = comm.pack_gather(local, dims, idxs)
                segments.append(seg)
            gathered = jnp.concatenate(segments)
            comm.unpack_gather(gathered, segs, self.N, dims, out)
        for i in range(len(leaves)):
            assert np.array_equal(np.asarray(out[i]),
                                  np.asarray(leaves[i])), f"leaf {i}"
            assert out[i].dtype == leaves[i].dtype


class TestBucketPlan:
    MESH = {"data": 2, "model": 1}

    def _shapes(self):
        cfg = TrainConfig(batch_size=8, backend="shard_map",
                          mesh=MeshConfig(data=2, zero_stage=2),
                          model=ModelConfig(**TINY))
        mesh = _mesh2()
        pt = make_parallel_train(cfg, mesh)
        state = jax.eval_shape(lambda: pt.init(jax.random.key(0)))
        return state["params"]["gen"], dict(mesh.shape)

    def test_covers_every_scatter_leaf_exactly_once(self):
        shapes, mesh_shape = self._shapes()
        dims = jax.tree_util.tree_leaves(
            rules.zero_scatter_dims(shapes, mesh_shape))
        plan = rules.zero_bucket_plan(shapes, mesh_shape, bucket_mb=4)
        flat = [i for b in plan for i in b]
        assert len(flat) == len(set(flat))  # no index twice
        scatter = {i for i, d in enumerate(dims) if d >= 0}
        assert set(flat) == scatter  # replicated leaves stay outside

    def test_buckets_are_dtype_pure_and_capped(self):
        shapes, mesh_shape = self._shapes()
        leaves = jax.tree_util.tree_leaves(shapes)
        cap_mb = 1
        plan = rules.zero_bucket_plan(shapes, mesh_shape,
                                      bucket_mb=cap_mb)
        for b in plan:
            dts = {str(np.dtype(leaves[i].dtype)) for i in b}
            assert len(dts) == 1, b  # a cast would break bit-exactness
            nbytes = sum(int(np.prod(leaves[i].shape))
                         * np.dtype(leaves[i].dtype).itemsize for i in b)
            if len(b) > 1:  # single oversized leaves own their bucket
                assert nbytes <= cap_mb * (1 << 20), b

    def test_oversized_leaf_gets_its_own_bucket(self):
        """A leaf bigger than the cap must never merge with neighbors —
        inflate one real scatter-targeted leaf past a 1-MiB cap (scaling
        its scatter dim keeps the rule resolution divisible) and check
        it rides alone."""
        shapes, mesh_shape = self._shapes()
        dims = jax.tree_util.tree_leaves(
            rules.zero_scatter_dims(shapes, mesh_shape))
        leaves, treedef = jax.tree_util.tree_flatten(shapes)
        target = next(i for i, d in enumerate(dims) if d >= 0)
        big = leaves[target]
        shape = list(big.shape)
        itemsize = np.dtype(big.dtype).itemsize
        while int(np.prod(shape)) * itemsize <= (1 << 20):
            shape[dims[target]] *= 2
        leaves[target] = jax.ShapeDtypeStruct(tuple(shape), big.dtype)
        shapes2 = jax.tree_util.tree_unflatten(treedef, leaves)
        plan = rules.zero_bucket_plan(shapes2, mesh_shape, bucket_mb=1)
        bucket = next(b for b in plan if target in b)
        assert bucket == (target,)
        # deterministic for a given (tree, mesh, cap): cache-stable
        assert plan == rules.zero_bucket_plan(shapes2, mesh_shape,
                                              bucket_mb=1)

    def test_nonpositive_cap_raises(self):
        shapes, mesh_shape = self._shapes()
        with pytest.raises(ValueError, match="bucket_mb"):
            rules.zero_bucket_plan(shapes, mesh_shape, bucket_mb=0)


# -- config validation ------------------------------------------------------

class TestConfigValidation:
    def test_prefetch_requires_zero3(self):
        with pytest.raises(ValueError, match="zero_stage=3"):
            TrainConfig(model=ModelConfig(**TINY), batch_size=8,
                        backend="shard_map", comm_overlap="prefetch",
                        mesh=MeshConfig(data=2, zero_stage=2))

    def test_prefetch_at_zero3_is_valid(self):
        cfg = TrainConfig(model=ModelConfig(**TINY), batch_size=8,
                          backend="shard_map", comm_overlap="prefetch",
                          mesh=MeshConfig(data=2, zero_stage=3))
        assert cfg.comm_overlap == "prefetch"

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError, match="comm_overlap"):
            TrainConfig(model=ModelConfig(**TINY), batch_size=8,
                        comm_overlap="aggressive")

    def test_nonpositive_bucket_mb_rejected(self):
        with pytest.raises(ValueError, match="comm_bucket_mb"):
            TrainConfig(model=ModelConfig(**TINY), batch_size=8,
                        comm_overlap="bucket", comm_bucket_mb=0,
                        mesh=MeshConfig(data=2, zero_stage=2))


# -- XLA flag helper --------------------------------------------------------

class TestXlaOverlapFlags:
    def test_noop_without_tpu_runtime(self):
        """Unknown --xla_tpu_* entries abort CPU/GPU XLA clients at init
        — on a host without libtpu the helper must add NOTHING."""
        import importlib.util

        if importlib.util.find_spec("libtpu") is not None:
            pytest.skip("host has libtpu; the guard cannot be observed")
        env = {}
        assert comm.maybe_apply_xla_overlap_flags(env) == ()
        assert env == {}

    def test_explicit_non_tpu_platform_suppresses(self):
        """Libtpu presence alone is the wrong gate: a `--platform cpu`
        debug run on a TPU-equipped host inits a CPU XLA client, which
        aborts on unknown --xla_tpu_* entries. An explicit non-TPU
        request — platform arg or JAX_PLATFORMS — must win over the
        libtpu probe, so this holds on EVERY host (caught live by a
        CPU-forced CLI run dying at client init)."""
        env = {}
        assert comm.maybe_apply_xla_overlap_flags(env, platform="cpu") == ()
        assert env == {}
        env = {"JAX_PLATFORMS": "cpu"}
        assert comm.maybe_apply_xla_overlap_flags(env) == ()
        assert "XLA_FLAGS" not in env
        # the explicit platform arg outranks the env var
        env = {"JAX_PLATFORMS": "tpu"}
        assert comm.maybe_apply_xla_overlap_flags(env, platform="cpu") == ()

    def test_force_appends_all_flags_once(self):
        env = {}
        added = comm.maybe_apply_xla_overlap_flags(env, force=True)
        assert added == comm.XLA_OVERLAP_FLAGS
        for f in comm.XLA_OVERLAP_FLAGS:
            assert f in env["XLA_FLAGS"]
        # idempotent: a second call finds every key present
        assert comm.maybe_apply_xla_overlap_flags(env, force=True) == ()

    def test_user_set_keys_are_respected(self):
        key = comm.XLA_OVERLAP_FLAGS[0].split("=", 1)[0]
        env = {"XLA_FLAGS": f"{key}=false"}
        added = comm.maybe_apply_xla_overlap_flags(env, force=True)
        assert comm.XLA_OVERLAP_FLAGS[0] not in added
        assert f"{key}=false" in env["XLA_FLAGS"]
        assert f"{key}=true" not in env["XLA_FLAGS"]


# -- bit-exact training arms ------------------------------------------------

def _run_arm(stage, mode, *, pipeline=False, steps=8):
    cfg = TrainConfig(batch_size=8, backend="shard_map",
                      comm_overlap=mode, comm_bucket_mb=1,
                      pipeline_gd=pipeline,
                      mesh=MeshConfig(data=2, zero_stage=stage),
                      model=ModelConfig(**TINY))
    pt = make_parallel_train(cfg, _mesh2())
    state = pt.init(jax.random.key(0))
    xs = _batch()
    metrics = []
    for i in range(steps):
        state, m = pt.step(state, xs,
                           jax.random.fold_in(jax.random.key(1), i))
        metrics.append({k: float(v) for k, v in m.items()})
    return jax.device_get(state), metrics


def _assert_bit_exact(a, b):
    la, ta = jax.tree_util.tree_flatten_with_path(a["params"])
    lb, _ = jax.tree_util.tree_flatten_with_path(b["params"])
    for (pa, xa), (_, xb) in zip(la, lb):
        assert np.array_equal(np.asarray(xa), np.asarray(xb)), \
            jax.tree_util.keystr(pa)


class TestBitExactArms:
    """THE acceptance criterion: every overlap arm is the SAME program
    in a different wire layout. 8 real optimizer steps, full params
    trees compared to the last bit against `--comm_overlap off`. The
    fast tier keeps one fused cell per mode; the full stage x mode x
    dispatch matrix is slow (every cell is two fresh 2-device
    compiles)."""

    @pytest.mark.parametrize("stage,mode,pipeline", [
        pytest.param(2, "bucket", False, id="fused-zero2-bucket"),
        pytest.param(3, "prefetch", False, id="fused-zero3-prefetch"),
        pytest.param(1, "bucket", False, id="fused-zero1-bucket",
                     marks=pytest.mark.slow),
        pytest.param(3, "bucket", False, id="fused-zero3-bucket",
                     marks=pytest.mark.slow),
        pytest.param(1, "bucket", True, id="pipeline-zero1-bucket",
                     marks=pytest.mark.slow),
        pytest.param(2, "bucket", True, id="pipeline-zero2-bucket",
                     marks=pytest.mark.slow),
        pytest.param(3, "bucket", True, id="pipeline-zero3-bucket",
                     marks=pytest.mark.slow),
        pytest.param(3, "prefetch", True, id="pipeline-zero3-prefetch",
                     marks=pytest.mark.slow),
    ])
    def test_arm_bit_exact_vs_off(self, stage, mode, pipeline):
        base, m_off = _run_arm(stage, "off", pipeline=pipeline)
        arm, m_arm = _run_arm(stage, mode, pipeline=pipeline)
        _assert_bit_exact(base, arm)
        for a, b in zip(m_off, m_arm):
            assert a == b  # loss stream identical too, step for step

    def test_ema_mirror_bit_exact_at_zero3(self):
        """Stage 3 shards the EMA mirror with the gen plan — the
        bucketed gather must reassemble it identically."""
        base, _ = _run_arm(3, "off")
        arm, _ = _run_arm(3, "bucket")
        for key in ("ema", "opt_g", "opt_d"):
            if key not in base:
                continue
            fa = jax.tree_util.tree_leaves(base[key])
            fb = jax.tree_util.tree_leaves(arm[key])
            for xa, xb in zip(fa, fb):
                assert np.array_equal(np.asarray(xa), np.asarray(xb))


# -- warmup-plan completeness + zero-recompile drill ------------------------

class TestWarmupAndRecompile:
    def _cfg(self, stage, mode, **kw):
        base = dict(batch_size=8, backend="shard_map", comm_overlap=mode,
                    comm_bucket_mb=1,
                    mesh=MeshConfig(data=2, zero_stage=stage),
                    model=ModelConfig(**TINY))
        base.update(kw)
        return TrainConfig(**base)

    @pytest.mark.parametrize("stage,mode", [(2, "bucket"),
                                            (3, "prefetch")])
    def test_plan_covers_overlap_variants(self, stage, mode):
        """build_warmup_plan under an overlap arm must enumerate the
        same program set as `off` — the overlap plane swaps hook bodies
        inside programs, it never adds dispatch surface."""
        from dcgan_tpu.train import warmup

        cfg = self._cfg(stage, mode, steps_per_call=2,
                        nan_policy="rollback", rollback_snapshot_steps=2,
                        rollback_lr_backoff=0.5)
        pt = make_parallel_train(cfg, _mesh2())
        state = pt.init(jax.random.key(0))
        plan, pt_backoff = warmup.build_warmup_plan(
            cfg, pt, state,
            make_backoff_pt=lambda c: make_parallel_train(c, _mesh2()))
        names = [n for n, _, _ in plan]
        assert "train_step" in names
        assert "multi_step@k2" in names
        assert "train_step@lr_backoff" in names
        assert pt_backoff is not None
        assert pt_backoff.cfg.comm_overlap == mode  # backoff keeps arm
        timings = warmup.aot_compile(plan)
        assert set(timings) == set(names)

    @pytest.mark.slow
    def test_rollback_drill_zero_recompiles_under_bucket(self, tmp_path):
        """The zero-recompile contract survives the overlap plane: a
        primed cache + AOT warmup under `--comm_overlap bucket`, then a
        live NaN rollback with LR backoff — the whole drill records
        compile_requests_delta == 0 misses."""
        from dcgan_tpu.testing import chaos
        from dcgan_tpu.train import warmup
        from dcgan_tpu.train.trainer import train

        prev_dir = jax.config.jax_compilation_cache_dir
        chaos.reset()
        try:
            # the trainer's mesh must cover the whole device set (8
            # virtual devices under tests/conftest.py), unlike the
            # direct-make_parallel_train tests' 2-device submesh
            kw = dict(batch_size=8, backend="shard_map",
                      comm_overlap="bucket", comm_bucket_mb=1,
                      mesh=MeshConfig(zero_stage=2),
                      model=ModelConfig(**TINY),
                      compile_cache_dir=str(tmp_path / "cache"),
                      aot_warmup=True, nan_policy="rollback",
                      nan_check_steps=1, rollback_snapshot_steps=2,
                      max_rollbacks=2, rollback_lr_backoff=0.5,
                      sample_every_steps=0, save_summaries_secs=0.0,
                      save_model_secs=1e9, log_every_steps=0,
                      tensorboard=False, activation_summary_steps=0)
            train(TrainConfig(checkpoint_dir=str(tmp_path / "p"), **kw),
                  synthetic_data=True, max_steps=3)  # prime, no fault
            mon = warmup.CompileCacheMonitor()
            before = mon.counters()
            chaos.set_plan(chaos.FaultPlan(nan_at_step=3))
            state = train(
                TrainConfig(checkpoint_dir=str(tmp_path / "d"), **kw),
                synthetic_data=True, max_steps=6)
            delta = mon.delta(mon.counters(), before)
            mon.close()
            assert int(jax.device_get(state["step"])) == 6
            assert delta["misses"] == 0, delta
        finally:
            chaos.reset()
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            from jax._src import compilation_cache

            compilation_cache.reset_cache()


# -- bench contract ---------------------------------------------------------

@pytest.mark.slow
class TestBenchCommOverlapAB:
    """ISSUE 20's bench contract: `COMM_OVERLAP=1 ZERO_STAGE=3 python
    bench.py` prints the overlap A/B row BEFORE the headline row (the
    driver parses the last line) with per-arm ms_per_step AND the
    collective-op census — the bucketed arms must issue strictly fewer
    collectives than `off`. Slow tier: several multi-device step
    compiles in a subprocess."""

    def test_overlap_ab_row_before_headline_with_op_counts(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_PLATFORM="cpu",
                   BENCH_BATCH="8", BENCH_STEPS="4", BENCH_WINDOWS="1",
                   BENCH_OVERLAP_STEPS="3", BENCH_DEVSTEP="0",
                   BENCH_SIZE="16", COMM_OVERLAP="1", ZERO_STAGE="3",
                   XLA_FLAGS="--xla_force_host_platform_device_count=2")
        res = subprocess.run([sys.executable, "bench.py"], cwd=REPO,
                             env=env, capture_output=True, text=True,
                             timeout=600)
        assert res.returncode == 0, (res.stdout[-800:], res.stderr[-800:])
        rows = [json.loads(l) for l in res.stdout.splitlines()
                if l.startswith("{")]
        ab = next(r for r in rows if "collective overlap" in r["metric"])
        # precedes the headline (last-line parse contract)
        assert rows.index(ab) < len(rows) - 1
        assert rows[-1]["metric"].endswith("(batch 8/chip, bf16)")
        for arm in ("off", "bucket", "prefetch"):
            assert ab[arm]["ms_per_step"] > 0, arm
            assert ab[arm]["collective_ops_total"] > 0, arm
        # THE census shrink, as numbers in the bench output
        assert (ab["bucket"]["collective_ops_total"]
                < ab["off"]["collective_ops_total"])
        assert (ab["bucket"]["collective_ops"]["reduce_scatter"]
                < ab["off"]["collective_ops"]["reduce_scatter"])
