"""Live in-run elasticity (ISSUE 18): the preemption-notice plane, the
two-topology runtime, and the trainer's no-restart mesh switch.

Layers covered, cheapest first: config validation (the combinations the
switch cannot honor are rejected at construction), NoticePlane local
sources (file/word parsing, SIGUSR1 flag, retry_io-wrapped reads, the ack
contract), the LiveTopologyRuntime surface/tag/verdict mapping, the
BIT-LOSSLESS state move between meshes (both sides observable in-process —
the cross-arm drills in tools/chaos_drill.py can only bound the
post-switch *trajectory*, which legitimately differs across device counts
because the data-axis reduction order changes), the warmup-plan naming
contract the semantic tier pins, and full in-process trainer runs: a
chaos-notice switch with compile_requests_delta=0, the --pipeline_gd
seam, the ZeRO-2/3 state-move seam, and the armed-but-unnotified parity
A/B (arming elasticity without a notice must not perturb the run).
"""

import json
import os
import signal

import numpy as np
import pytest

import jax

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.elastic import live
from dcgan_tpu.parallel import make_mesh, make_parallel_train
from dcgan_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.set_plan(None)
    yield
    chaos.set_plan(None)


def _model():
    return ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                       compute_dtype="float32")


def _cfg(tmp_path=None, **kw):
    kw.setdefault("model", _model())
    kw.setdefault("batch_size", 8)
    kw.setdefault("tensorboard", False)
    kw.setdefault("sample_every_steps", 0)
    kw.setdefault("activation_summary_steps", 0)
    kw.setdefault("save_summaries_secs", 0.0)
    kw.setdefault("save_model_secs", 1e9)
    kw.setdefault("save_model_steps", 10_000)
    kw.setdefault("log_every_steps", 1)
    kw.setdefault("synthetic_global_stream", True)
    if tmp_path is not None:
        kw.setdefault("checkpoint_dir", str(tmp_path / "ckpt"))
        kw.setdefault("sample_dir", str(tmp_path / "samples"))
    return TrainConfig(**kw)


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------

class TestConfigValidation:
    def test_negative_target_rejected(self):
        with pytest.raises(ValueError, match="must be >= 0"):
            _cfg(elastic_target_devices=-1)

    def test_progressive_combo_rejected(self):
        with pytest.raises(ValueError, match="does not compose with"):
            _cfg(elastic_target_devices=1, progressive="8:2,16:*")

    def test_model_axis_divisibility_rejected(self):
        with pytest.raises(ValueError, match="divisible by"):
            _cfg(elastic_target_devices=3,
                 mesh=MeshConfig(data=0, model=2))

    def test_notice_file_without_target_rejected(self):
        with pytest.raises(ValueError, match="silent no-op"):
            _cfg(elastic_notice_file="/tmp/notice")

    def test_armed_config_valid(self):
        cfg = _cfg(elastic_target_devices=4,
                   elastic_notice_file="/tmp/notice")
        assert cfg.elastic_target_devices == 4


# ---------------------------------------------------------------------------
# NoticePlane: local sources + consensus + ack
# ---------------------------------------------------------------------------

class TestNoticePlane:
    def test_parse_notice_text(self):
        assert live._parse_notice_text("") == live.NOTICE_SHRINK
        assert live._parse_notice_text("shrink\n") == live.NOTICE_SHRINK
        assert live._parse_notice_text("anything else") \
            == live.NOTICE_SHRINK
        for word in ("grow", "GROW", "restore", "grow-back"):
            assert live._parse_notice_text(word + "\n") == live.NOTICE_GROW

    def test_no_sources_is_none(self):
        plane = live.NoticePlane("")
        assert plane.poll(1) == (live.NOTICE_NONE, [])

    def test_touch_file_is_shrink_consensus(self, tmp_path):
        f = tmp_path / "notice"
        plane = live.NoticePlane(str(f))
        assert plane.poll(1) == (live.NOTICE_NONE, [])
        f.write_text("")
        assert plane.poll(2) == (live.NOTICE_SHRINK, [0])
        f.write_text("grow\n")
        assert plane.poll(3) == (live.NOTICE_GROW, [0])

    def test_file_read_rides_retry_io(self, tmp_path):
        # one injected transient EIO at the "notice-poll" tag must be
        # absorbed by the bounded retry, not misread as "no notice"
        f = tmp_path / "notice"
        f.write_text("grow\n")
        chaos.set_plan(chaos.FaultPlan(io_error_once="notice-poll"))
        plane = live.NoticePlane(str(f))
        assert plane.local_verdict(1) == live.NOTICE_GROW

    def test_sigusr1_sets_one_shot_shrink(self):
        plane = live.NoticePlane("")
        plane.install()
        try:
            os.kill(os.getpid(), signal.SIGUSR1)
            assert plane.local_verdict(1) == live.NOTICE_SHRINK
            # one-shot: the flag clears on consumption
            assert plane.local_verdict(2) == live.NOTICE_NONE
        finally:
            plane.restore()

    def test_chaos_plan_is_a_source(self):
        chaos.set_plan(chaos.FaultPlan(preempt_notice_at_step=3))
        plane = live.NoticePlane("")
        assert plane.local_verdict(2) == live.NOTICE_NONE
        assert plane.local_verdict(3) == live.NOTICE_SHRINK
        assert plane.local_verdict(4) == live.NOTICE_NONE  # one-shot

    def test_ack_consumes_file_and_writes_record(self, tmp_path):
        f = tmp_path / "notice"
        f.write_text("")
        plane = live.NoticePlane(str(f))
        plane.ack(step=7, verdict=live.NOTICE_SHRINK, target="t1x1",
                  switch_ms=12.5)
        assert not f.exists()
        assert (tmp_path / "notice.consumed").exists()
        record = json.loads((tmp_path / "notice.ack").read_text())
        assert record == {"step": 7, "verdict": "shrink",
                          "target_mesh": "t1x1", "switch_ms": 12.5}
        # a consumed notice no longer raises at the next boundary
        assert plane.poll(8) == (live.NOTICE_NONE, [])


# ---------------------------------------------------------------------------
# submesh_config + LiveTopologyRuntime mapping
# ---------------------------------------------------------------------------

class TestSubmeshConfig:
    def test_resizes_data_axis_only(self):
        cfg = _cfg(elastic_target_devices=1, mesh=MeshConfig(data=2))
        sub = live.submesh_config(cfg, 1)
        assert sub.mesh.data == 1 and sub.mesh.model == cfg.mesh.model
        assert sub.batch_size == cfg.batch_size
        assert sub.model == cfg.model

    def test_rejects_non_divisible(self):
        cfg = _cfg(elastic_target_devices=2,
                   mesh=MeshConfig(data=2, model=2))
        with pytest.raises(ValueError, match="not divisible"):
            live.submesh_config(cfg, 3)


def _runtime(zero_stage=1, target=1, data=2):
    cfg = _cfg(elastic_target_devices=target,
               mesh=MeshConfig(data=data, zero_stage=zero_stage))
    mesh = make_mesh(cfg.mesh, jax.devices()[:data])
    return cfg, live.LiveTopologyRuntime(cfg, mesh)


class TestRuntimeMapping:
    def test_rejects_target_equal_to_launch(self):
        cfg = _cfg(elastic_target_devices=2, mesh=MeshConfig(data=2))
        mesh = make_mesh(cfg.mesh, jax.devices()[:2])
        with pytest.raises(ValueError, match="nothing to switch"):
            live.LiveTopologyRuntime(cfg, mesh)

    def test_rejects_out_of_range_target(self):
        cfg = _cfg(elastic_target_devices=len(jax.devices()) + 1,
                   mesh=MeshConfig(data=2))
        mesh = make_mesh(cfg.mesh, jax.devices()[:2])
        with pytest.raises(ValueError, match="available devices"):
            live.LiveTopologyRuntime(cfg, mesh)

    def test_tags_and_device_count(self):
        _cfg_, rt = _runtime()
        assert rt.tag(0) == "t2x1" and rt.tag(1) == "t1x1"
        assert rt.tag() == "t2x1"
        assert rt.device_count == 2

    def test_verdict_to_target_index(self):
        _cfg_, rt = _runtime()
        assert rt.target_index(live.NOTICE_SHRINK) == 1
        assert rt.target_index(live.NOTICE_GROW) is None  # already full
        assert rt.target_index(live.NOTICE_NONE) is None
        rt.index = 1
        assert rt.target_index(live.NOTICE_SHRINK) is None  # already small
        assert rt.target_index(live.NOTICE_GROW) == 0


# ---------------------------------------------------------------------------
# the state move is bit-lossless (both directions, all ZeRO stages)
# ---------------------------------------------------------------------------

class TestLosslessMove:
    """The drill's cross-arm trajectories can only be compared within a
    reduction-order tolerance (a 1- vs 2-device data axis reduces the
    global batch in different orders). The MOVE itself has no such excuse:
    re-scattering the identical values onto another mesh must be
    bit-for-bit, and in-process both sides are observable."""

    # ZeRO-2/3 shard state over the data axis, which must stay > 1 — so
    # those stages shrink 4 -> 2, while stage 1 covers the 2 -> 1 floor
    @pytest.mark.parametrize("zero_stage,data,target",
                             [(1, 2, 1), (2, 4, 2), (3, 4, 2)])
    def test_shrink_then_grow_roundtrip_bit_exact(self, zero_stage, data,
                                                  target):
        _cfg_, rt = _runtime(zero_stage=zero_stage, data=data,
                             target=target)
        state = rt.pt.init(jax.random.key(0))
        ref = jax.device_get(state)

        moved = rt.switch(state, live.NOTICE_SHRINK)
        assert rt.index == 1 and rt.switches == 1
        assert rt.device_count == target
        got = jax.device_get(moved)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), ref, got)
        # the moved tree really lives on the target submesh
        for leaf in jax.tree_util.tree_leaves(moved):
            assert len(leaf.sharding.device_set) <= target

        back = rt.switch(moved, live.NOTICE_GROW)
        assert rt.index == 0 and rt.switches == 2
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            ref, jax.device_get(back))

    def test_zero_shrink_to_single_device_fails_loudly(self):
        """A ZeRO >= 2 run cannot shrink onto a size-1 data axis (nothing
        left to shard over) — the rules engine rejects the target surface
        the first time it is built, which under --aot_warmup is at
        STARTUP, never mid-run on a notice."""
        _cfg_, rt = _runtime(zero_stage=2, data=2, target=1)
        with pytest.raises(ValueError, match="zero_stage=2"):
            rt.surface(1)

    def test_switch_without_direction_change_is_identity(self):
        _cfg_, rt = _runtime()
        state = rt.pt.init(jax.random.key(0))
        assert rt.switch(state, live.NOTICE_GROW) is state
        assert rt.switches == 0


# ---------------------------------------------------------------------------
# warmup-plan naming (the contract the semantic tier pins)
# ---------------------------------------------------------------------------

class TestWarmupPlanNames:
    def test_both_topologies_planned_with_suffixes(self):
        from dcgan_tpu.train import warmup

        _cfg_, rt = _runtime()
        plan = rt.build_warmup_plan(warmup.state_example(rt.pt))
        names = {n for n, _, _ in plan}
        # launch rows keep plain names; target rows carry @t1x1
        assert {"init", "train_step", "state_copy"} <= names
        assert {"init@t1x1", "train_step@t1x1",
                "state_copy@t1x1"} <= names
        # no cross-contamination: every suffixed name is the target's
        assert all(n.endswith("@t1x1") for n in names if "@t" in n)


# ---------------------------------------------------------------------------
# trainer integration (in-process, 8-device env: t8x1 <-> t4x1)
# ---------------------------------------------------------------------------

class TestTrainerSwitch:
    # One persistent compile cache for the whole class: these tests all
    # lower the same tiny model on the same t8x1/t4x1 meshes, so the
    # first (AOT-warmed) test populates the cache and the rest
    # deserialize instead of re-compiling — CPU compile time dominates
    # this class otherwise. Cache HITS still count as compile REQUESTS,
    # so the compile_requests_delta=0 assertions are unaffected.
    @pytest.fixture(scope="class")
    def shared_cache(self, tmp_path_factory):
        return str(tmp_path_factory.mktemp("live_elastic_cc"))

    def test_notice_switch_completes_with_zero_compile_requests(
            self, tmp_path, capsys, shared_cache):
        """THE acceptance criterion: a chaos preemption notice mid-run
        shrinks the live mesh with compile-request delta == 0 (both
        topologies AOT-warmed + primed up front) and the run completes."""
        from dcgan_tpu.train.trainer import train

        chaos.set_plan(chaos.FaultPlan(preempt_notice_at_step=2))
        cfg = _cfg(tmp_path, elastic_target_devices=4, aot_warmup=True,
                   compile_cache_dir=shared_cache)
        state = train(cfg, synthetic_data=True, max_steps=4)
        assert int(jax.device_get(state["step"])) == 4
        out = capsys.readouterr().out
        assert "live-elastic warmup primed" in out
        switch = [l for l in out.splitlines()
                  if "live elastic switch at step 2" in l]
        assert switch and "t8x1 -> t4x1" in switch[0], out[-2000:]
        assert "compile_requests_delta=0" in switch[0], switch[0]
        # the event row landed, gated to the notified run
        events = [json.loads(l) for l in
                  open(os.path.join(cfg.checkpoint_dir, "events.jsonl"))]
        live_rows = [e["values"] for e in events if e["kind"] == "scalars"
                     and "elastic/live_switch_ms" in e["values"]]
        assert live_rows and live_rows[-1]["elastic/live_target_mesh"] == 4.0
        assert live_rows[-1]["elastic/live_notice_step"] == 2.0

    def test_notice_file_switch_writes_ack(self, tmp_path, capsys,
                                           shared_cache):
        """The operational path end-to-end: a pre-existing touch file is
        the notice, the switch consumes it and writes the ack record a
        notifying scheduler polls for."""
        from dcgan_tpu.train.trainer import train

        notice = tmp_path / "notice"
        notice.write_text("")
        cfg = _cfg(tmp_path, elastic_target_devices=4,
                   compile_cache_dir=shared_cache,
                   elastic_notice_file=str(notice))
        state = train(cfg, synthetic_data=True, max_steps=3)
        assert int(jax.device_get(state["step"])) == 3
        out = capsys.readouterr().out
        # a notice waiting at launch fires at the step-0 boundary, before
        # the first dispatch — the whole run trains on the target mesh
        assert "live elastic switch at step 0: t8x1 -> t4x1" in out
        assert not notice.exists()
        assert (tmp_path / "notice.consumed").exists()
        record = json.loads((tmp_path / "notice.ack").read_text())
        assert record["verdict"] == "shrink"
        assert record["target_mesh"] == "t4x1"
        assert record["step"] == 0 and record["switch_ms"] > 0

    def test_pipeline_gd_seam(self, tmp_path, capsys, shared_cache):
        """--pipeline_gd composes: the in-flight G/D stack (sharded on the
        OLD mesh) is drained at the boundary and the stage programs keep
        dispatching on the new mesh."""
        from dcgan_tpu.train.trainer import train

        chaos.set_plan(chaos.FaultPlan(preempt_notice_at_step=2))
        cfg = _cfg(tmp_path, elastic_target_devices=4, aot_warmup=True,
                   compile_cache_dir=shared_cache, pipeline_gd=True)
        state = train(cfg, synthetic_data=True, max_steps=4)
        assert int(jax.device_get(state["step"])) == 4
        out = capsys.readouterr().out
        assert "live elastic switch at step 2: t8x1 -> t4x1" in out

    def test_grow_notice_on_full_mesh_consumes_without_switch(
            self, tmp_path, capsys, shared_cache):
        from dcgan_tpu.train.trainer import train

        chaos.set_plan(chaos.FaultPlan(grow_notice_at_step=2))
        cfg = _cfg(tmp_path, elastic_target_devices=4,
                   compile_cache_dir=shared_cache)
        state = train(cfg, synthetic_data=True, max_steps=3)
        assert int(jax.device_get(state["step"])) == 3
        out = capsys.readouterr().out
        assert "already on t8x1 — consumed, no switch" in out
        assert "live elastic switch" not in out

    def test_armed_but_unnotified_parity(self, tmp_path, shared_cache):
        """Arming elasticity is free: with no notice, the armed run's
        trajectory and event stream are indistinguishable from an unarmed
        run — bit-equal final params, identical loss rows, identical
        event-key sets, and no elastic/live_* key anywhere."""
        from dcgan_tpu.train.trainer import train

        def run(sub, **kw):
            cfg = _cfg(tmp_path, checkpoint_dir=str(tmp_path / sub),
                       compile_cache_dir=shared_cache, **kw)
            state = train(cfg, synthetic_data=True, max_steps=3)
            events = [json.loads(l) for l in
                      open(os.path.join(cfg.checkpoint_dir,
                                        "events.jsonl"))]
            return state, events

        st_armed, ev_armed = run("armed", elastic_target_devices=4)
        st_off, ev_off = run("off")

        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)),
                np.asarray(jax.device_get(b))), st_armed, st_off)

        def keys(events):
            return {k for e in events if e["kind"] == "scalars"
                    for k in e["values"]}

        def losses(events):
            return {e["step"]: (e["values"]["d_loss"],
                                e["values"]["g_loss"])
                    for e in events if e["kind"] == "scalars"
                    and "d_loss" in e["values"]}

        assert keys(ev_armed) == keys(ev_off)
        assert losses(ev_armed) == losses(ev_off)
        assert not any(k.startswith("elastic/") for k in keys(ev_armed))


# ---------------------------------------------------------------------------
# flight-recorder counter field
# ---------------------------------------------------------------------------

class TestCounterField:
    def test_counter_snapshot_has_live_topology(self):
        from dcgan_tpu.utils.metrics import CounterSnapshot

        snap = CounterSnapshot()
        assert snap.live_topology == 0
        assert CounterSnapshot(live_topology=4).live_topology == 4
