"""Fail-operational layer (ISSUE 3): fault plans, quarantine, retry,
checkpoint integrity fallback, NaN rollback — the fast in-process half of
the proof (tools/chaos_drill.py is the subprocess end-to-end half; its
--smoke subset is pinned in tests/test_tools.py).

Everything here is `chaos`-marked and stays in the tier-1 (not-slow) suite
except the full-trainer rollback/parity runs at the bottom."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.data import quarantine
from dcgan_tpu.data.tfrecord import read_tfrecords, write_tfrecords
from dcgan_tpu.testing import chaos
from dcgan_tpu.utils.retry import retry_io

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_chaos_state():
    """No armed plan or quarantine tally may leak between tests (both are
    process-global by design)."""
    chaos.reset()
    quarantine.reset()
    yield
    chaos.reset()
    quarantine.reset()


class TestFaultPlan:
    def test_env_parse_and_unknown_key(self):
        plan = chaos.plan_from_env({chaos.ENV_VAR: json.dumps(
            {"nan_at_step": 7, "io_error_once": "services"})})
        assert plan.nan_at_step == 7 and plan.io_error_once == "services"
        assert chaos.plan_from_env({}) is None
        with pytest.raises(ValueError, match="unknown"):
            chaos.plan_from_env({chaos.ENV_VAR: '{"nope": 1}'})

    def test_nan_injection_is_one_shot(self):
        chaos.set_plan(chaos.FaultPlan(nan_at_step=3))
        assert not chaos.should_inject_nan(2)
        assert chaos.should_inject_nan(3)
        assert not chaos.should_inject_nan(3)  # replayed step after rollback
        chaos.set_plan(None)
        assert not chaos.should_inject_nan(3)

    def test_io_error_fires_only_on_matching_tag_and_once(self):
        chaos.set_plan(chaos.FaultPlan(io_error_once="ckpt-manifest"))
        chaos.maybe_io_error("services")  # wrong site: no-op
        with pytest.raises(OSError, match="chaos"):
            chaos.maybe_io_error("ckpt-manifest")
        chaos.maybe_io_error("ckpt-manifest")  # consumed

    def test_disk_helpers(self, tmp_path):
        path = str(tmp_path / "t.tfrecord")
        write_tfrecords(path, [b"a" * 40, b"b" * 40, b"c" * 40])
        chaos.corrupt_tfrecord_payload(path, record_index=1)
        got = list(read_tfrecords(path))                # no verify: 3 records
        assert len(got) == 3 and got[1] != b"b" * 40    # payload flipped
        with pytest.raises(IOError, match="data CRC"):
            list(read_tfrecords(path, verify_crc=True))
        size = os.path.getsize(path)
        assert chaos.truncate_file(path, 10) == size - 10


class TestRetryIO:
    def test_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        assert retry_io(flaky, tag="t", sleep=lambda s: None) == "ok"
        assert len(calls) == 3

    def test_exhausts_and_reraises(self):
        def always():
            raise OSError("persistent")

        with pytest.raises(OSError, match="persistent"):
            retry_io(always, tag="t", attempts=2, sleep=lambda s: None)

    def test_non_retryable_errors_propagate_immediately(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("logic bug")

        with pytest.raises(ValueError):
            retry_io(bad, tag="t", sleep=lambda s: None)
        assert len(calls) == 1

    def test_absorbs_injected_fault(self):
        chaos.set_plan(chaos.FaultPlan(io_error_once="site"))
        assert retry_io(lambda: "ok", tag="site",
                        sleep=lambda s: None) == "ok"


class TestTFRecordQuarantine:
    def _shard(self, tmp_path, n=4):
        path = str(tmp_path / "s.tfrecord")
        write_tfrecords(path, [bytes([i]) * 32 for i in range(n)])
        return path

    def test_data_crc_skips_exactly_that_record(self, tmp_path):
        path = self._shard(tmp_path)
        chaos.corrupt_tfrecord_payload(path, record_index=1)
        seen = []
        got = list(read_tfrecords(path, verify_crc=True,
                                  on_corrupt=lambda off, why:
                                  seen.append((off, why))))
        assert got == [bytes([i]) * 32 for i in (0, 2, 3)]
        assert len(seen) == 1 and "data CRC" in seen[0][1]
        assert seen[0][0] > 0  # offset of record 1, not the file head

    def test_truncated_tail_abandons_file_after_callback(self, tmp_path):
        path = self._shard(tmp_path)
        chaos.truncate_file(path, 10)
        seen = []
        got = list(read_tfrecords(path, verify_crc=True,
                                  on_corrupt=lambda off, why:
                                  seen.append(why)))
        assert len(got) == 3 and len(seen) == 1
        assert "truncated" in seen[0]

    def test_without_callback_still_raises(self, tmp_path):
        path = self._shard(tmp_path)
        chaos.corrupt_tfrecord_payload(path, 0)
        with pytest.raises(IOError, match="data CRC"):
            list(read_tfrecords(path, verify_crc=True))

    def test_budget_enforced_via_quarantine_record(self):
        quarantine.record("p", 0, "r", budget=2, seen=1)
        quarantine.record("p", 9, "r", budget=2, seen=2)
        with pytest.raises(quarantine.CorruptRecordError, match="budget"):
            quarantine.record("p", 18, "r", budget=2, seen=3)
        assert quarantine.count() == 3


def _labeled_shards(tmp_path, corrupt_index=None):
    from dcgan_tpu.data.synthetic import write_image_tfrecords

    data_dir = str(tmp_path / "data")
    paths = write_image_tfrecords(data_dir, num_examples=32, image_size=8,
                                  num_shards=1)
    if corrupt_index is not None:
        chaos.corrupt_tfrecord_payload(paths[0], corrupt_index)
    return paths


class TestLoaderQuarantine:
    KW = dict(batch=4, example_shape=(8, 8, 3), min_after_dequeue=4,
              n_threads=1, seed=0, loop=False)

    def test_python_loader_skips_and_counts(self, tmp_path):
        from dcgan_tpu.data.pipeline import PythonLoader

        paths = _labeled_shards(tmp_path, corrupt_index=3)
        loader = PythonLoader(paths, verify_crc=True, max_corrupt_records=8,
                              **self.KW)
        batches = list(loader)
        assert sum(b.shape[0] for b in batches) == 28  # 31 good, 7 batches
        assert loader.corrupt_records == 1
        assert quarantine.count() == 1

    def test_python_loader_counts_distinct_records_not_epochs(self,
                                                              tmp_path):
        """A looping dataset re-reads the same bad record every epoch; the
        budget must bound DISTINCT corrupt records, or one flipped bit
        still kills the run after budget-many epochs — the exact failure
        quarantine exists to prevent."""
        from dcgan_tpu.data.pipeline import PythonLoader

        paths = _labeled_shards(tmp_path, corrupt_index=3)
        kw = dict(self.KW, loop=True)
        loader = PythonLoader(paths, verify_crc=True, max_corrupt_records=1,
                              **kw)
        try:
            for _ in range(20):   # ~2.5 epochs of 31 good examples
                assert loader.next() is not None
            assert loader.corrupt_records == 1  # one distinct record
            assert quarantine.count() == 1
        finally:
            loader.close()

    def test_native_loader_counts_distinct_records_not_epochs(self,
                                                              tmp_path):
        from dcgan_tpu.data.native import NativeLoader

        paths = _labeled_shards(tmp_path, corrupt_index=3)
        kw = dict(self.KW, loop=True)
        loader = NativeLoader(paths, max_corrupt_records=1, **kw)
        try:
            for _ in range(20):
                assert loader.next() is not None
            assert loader.corrupt_records == 1
        finally:
            loader.close()

    def test_python_loader_fail_fast_without_budget(self, tmp_path):
        from dcgan_tpu.data.pipeline import PythonLoader

        paths = _labeled_shards(tmp_path, corrupt_index=3)
        loader = PythonLoader(paths, verify_crc=True, **self.KW)
        with pytest.raises(RuntimeError, match="data CRC"):
            list(loader)

    def test_python_loader_budget_exhaustion_fails(self, tmp_path):
        from dcgan_tpu.data.pipeline import PythonLoader

        paths = _labeled_shards(tmp_path, corrupt_index=1)
        chaos.corrupt_tfrecord_payload(paths[0], 5)
        loader = PythonLoader(paths, verify_crc=True, max_corrupt_records=1,
                              **self.KW)
        with pytest.raises(RuntimeError, match="budget"):
            list(loader)

    def test_native_loader_skips_and_counts(self, tmp_path):
        from dcgan_tpu.data.native import NativeLoader

        paths = _labeled_shards(tmp_path, corrupt_index=3)
        loader = NativeLoader(paths, max_corrupt_records=8, **self.KW)
        try:
            batches = list(loader)
            assert sum(b.shape[0] for b in batches) == 28
            assert loader.corrupt_records == 1
            assert quarantine.count() == 1  # bridge mirrors the native count
        finally:
            loader.close()

    def test_native_loader_budget_exhaustion_fails(self, tmp_path):
        from dcgan_tpu.data.native import NativeLoader, NativeLoaderError

        paths = _labeled_shards(tmp_path, corrupt_index=1)
        chaos.corrupt_tfrecord_payload(paths[0], 5)
        loader = NativeLoader(paths, max_corrupt_records=1, **self.KW)
        try:
            with pytest.raises(NativeLoaderError, match="budget"):
                list(loader)
        finally:
            loader.close()


def _tiny_state(value: float):
    return {"w": jnp.full((4, 4), value, jnp.float32),
            "step": jnp.asarray(int(value), jnp.int32)}


class TestCheckpointIntegrity:
    def _ckpt(self, tmp_path):
        from dcgan_tpu.utils.checkpoint import Checkpointer

        return Checkpointer(str(tmp_path / "ck"), async_save=False)

    def test_manifest_written_and_verifies(self, tmp_path):
        ck = self._ckpt(tmp_path)
        ck.save(1, _tiny_state(1.0), force=True)
        ck.save(2, _tiny_state(2.0), force=True)
        ck.wait()
        man = json.load(open(os.path.join(ck.directory, "integrity",
                                          "2.json")))
        assert man["step"] == 2 and man["files"]
        for rec in man["files"].values():
            assert rec["size"] > 0
        assert ck._verify_step(2) == (True, "verified")

    def test_truncated_latest_falls_back_to_previous(self, tmp_path, capsys):
        ck = self._ckpt(tmp_path)
        ck.save(1, _tiny_state(1.0), force=True)
        ck.save(2, _tiny_state(2.0), force=True)
        ck.wait()
        files = []
        for root, _, names in os.walk(os.path.join(ck.directory, "2")):
            files += [os.path.join(root, n) for n in names]
        chaos.truncate_file(max(files, key=os.path.getsize), 16)

        restored = ck.restore_latest(_tiny_state(0.0))
        assert int(restored["step"]) == 1
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4, 4), 1.0, np.float32))
        assert os.path.isdir(os.path.join(ck.directory, "2.corrupt"))
        assert "failed integrity check" in capsys.readouterr().out
        # the manager's view is consistent after the quarantine rename
        assert ck.latest_step() == 1

    def test_all_corrupt_restores_none(self, tmp_path):
        ck = self._ckpt(tmp_path)
        ck.save(1, _tiny_state(1.0), force=True)
        ck.wait()
        for root, _, names in os.walk(os.path.join(ck.directory, "1")):
            for n in names:
                chaos.truncate_file(os.path.join(root, n), 8)
        assert ck.restore_latest(_tiny_state(0.0)) is None

    def test_legacy_step_without_manifest_still_restores(self, tmp_path):
        import shutil

        ck = self._ckpt(tmp_path)
        ck.save(3, _tiny_state(3.0), force=True)
        ck.wait()
        shutil.rmtree(os.path.join(ck.directory, "integrity"))
        restored = ck.restore_latest(_tiny_state(0.0))
        assert int(restored["step"]) == 3

    def test_delete_steps_after(self, tmp_path):
        ck = self._ckpt(tmp_path)
        for s in (1, 2, 3):
            ck.save(s, _tiny_state(float(s)), force=True)
        ck.wait()
        assert ck.delete_steps_after(1) == [3, 2]
        assert ck.latest_step() == 1
        # the dropped steps' manifests die with them — a REPLAYED save at
        # the same step number (the rollback scenario) writes different
        # bytes and must be manifested fresh, not judged against the stale
        # checksums and falsely quarantined
        assert not os.path.exists(os.path.join(ck.directory, "integrity",
                                               "2.json"))
        ck.save(2, _tiny_state(9.0), force=True)
        ck.wait()
        assert ck._verify_step(2) == (True, "verified")
        restored = ck.restore_latest(_tiny_state(0.0))
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4, 4), 9.0, np.float32))
        # stale manifests of deleted-and-not-replayed steps are pruned on
        # the next manifest pass
        ck.save(4, _tiny_state(4.0), force=True)
        ck.wait()
        names = sorted(os.listdir(os.path.join(ck.directory, "integrity")))
        assert names == ["1.json", "2.json", "4.json"]

    def test_manifest_write_retries_injected_io_error(self, tmp_path,
                                                      capsys):
        chaos.set_plan(chaos.FaultPlan(io_error_once="ckpt-manifest"))
        ck = self._ckpt(tmp_path)
        ck.save(1, _tiny_state(1.0), force=True)
        ck.wait()
        assert "retrying" in capsys.readouterr().out
        assert ck._verify_step(1) == (True, "verified")

    def test_transient_verify_io_error_does_not_condemn(self, tmp_path,
                                                        capsys):
        """ISSUE 4 satellite: a transient OSError during verification gets
        its bounded retries — an INTACT checkpoint must restore, not be
        renamed `<step>.corrupt` (permanent condemnation) over one IO
        blip."""
        ck = self._ckpt(tmp_path)
        ck.save(1, _tiny_state(1.0), force=True)
        ck.wait()
        chaos.set_plan(chaos.FaultPlan(io_error_once="ckpt-verify"))
        restored = ck.restore_latest(_tiny_state(0.0))
        assert int(restored["step"]) == 1
        out = capsys.readouterr().out
        assert "retrying" in out
        assert not os.path.isdir(os.path.join(ck.directory, "1.corrupt"))
        assert "failed integrity check" not in out


class TestServicesFaults:
    def test_worker_crash_surfaces_on_dispatch_thread(self):
        from dcgan_tpu.train.services import HostServices, ServiceError

        chaos.set_plan(chaos.FaultPlan(services_worker_crash=1))
        svc = HostServices()
        try:
            svc.submit(lambda: None, tag="scalars")
            with pytest.raises(ServiceError, match="chaos"):
                svc.drain()
        finally:
            chaos.set_plan(None)
            try:
                svc.close()
            except ServiceError:
                pass

    def test_transient_os_error_in_task_is_retried(self):
        from dcgan_tpu.train.services import HostServices

        svc = HostServices()
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise OSError("transient")

        try:
            svc.submit(flaky, tag="scalars")
            svc.drain()  # would raise if the worker had failed
            assert len(calls) == 2 and svc.completed == 1
        finally:
            svc.close()


class TestRollbackManager:
    def test_snapshot_restore_roundtrip_and_exhaustion(self):
        from dcgan_tpu.train.rollback import (
            RollbackExhausted,
            RollbackManager,
        )

        mgr = RollbackManager(every=2, max_rollbacks=1, lr_backoff=0.5)
        state = {"w": jnp.arange(4.0), "step": jnp.asarray(4)}
        mgr.snapshot(4, state)
        trip = FloatingPointError("nan at step 5")
        restored, step = mgr.restore(trip)
        assert step == 4 and mgr.rollbacks == 1
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(4.0))
        assert restored["w"].sharding == state["w"].sharding
        assert mgr.lr_scale() == 0.5
        with pytest.raises(RollbackExhausted, match="max_rollbacks"):
            mgr.restore(trip)

    def test_no_snapshot_reraises(self):
        from dcgan_tpu.train.rollback import RollbackManager

        mgr = RollbackManager(every=2, max_rollbacks=3)
        with pytest.raises(FloatingPointError, match="boom"):
            mgr.restore(FloatingPointError("boom"))


class TestConfigAndCLI:
    def test_validation(self):
        from dcgan_tpu.config import TrainConfig

        with pytest.raises(ValueError, match="nan_policy"):
            TrainConfig(nan_policy="retry")
        with pytest.raises(ValueError, match="nan_check_steps"):
            TrainConfig(nan_policy="rollback", nan_check_steps=0)
        with pytest.raises(ValueError, match="rollback_lr_backoff"):
            TrainConfig(rollback_lr_backoff=0.0)
        with pytest.raises(ValueError, match="max_corrupt_records"):
            TrainConfig(max_corrupt_records=-1)
        with pytest.raises(ValueError, match="max_rollbacks"):
            TrainConfig(max_rollbacks=0)

    def test_flags_reach_config(self):
        from dcgan_tpu.train.cli import build_parser, config_from_args

        cfg = config_from_args(build_parser().parse_args(
            ["--nan_policy", "rollback", "--rollback_snapshot_steps", "50",
             "--max_rollbacks", "7", "--rollback_lr_backoff", "0.5",
             "--max_corrupt_records", "100"]))
        assert cfg.nan_policy == "rollback"
        assert cfg.rollback_snapshot_steps == 50
        assert cfg.max_rollbacks == 7
        assert cfg.rollback_lr_backoff == 0.5
        assert cfg.max_corrupt_records == 100

    def test_snapshot_cadence_constrains_scanned_dispatch_only_when_armed(
            self):
        """The snapshot cadence joins the steps_per_call alignment rule
        ONLY under nan_policy='rollback' — its default (100) must not
        reject steps_per_call=3 runs that never arm rollback."""
        from dcgan_tpu.config import TrainConfig

        TrainConfig(steps_per_call=3, sample_every_steps=3,
                    activation_summary_steps=3, nan_check_steps=3,
                    save_model_steps=3, log_every_steps=3)  # fine: inert
        with pytest.raises(ValueError, match="rollback_snapshot_steps"):
            TrainConfig(steps_per_call=3, sample_every_steps=3,
                        activation_summary_steps=3, nan_check_steps=3,
                        save_model_steps=3, log_every_steps=3,
                        nan_policy="rollback", rollback_snapshot_steps=100)

    def test_defaults_are_parity(self):
        from dcgan_tpu.config import TrainConfig

        cfg = TrainConfig()
        assert cfg.nan_policy == "abort"
        assert cfg.max_corrupt_records == 0

    def test_per_process_chaos_plan_selected_by_mh_pid(self):
        """ISSUE 4: an all-digit-keyed DCGAN_CHAOS object is a per-process
        map — the MH_PID process gets its entry, everyone else gets no
        plan, so one env value arms a fault on exactly one host."""
        env = {chaos.ENV_VAR: json.dumps({"1": {"nan_at_step": 3}}),
               "MH_PID": "1"}
        plan = chaos.plan_from_env(env)
        assert plan is not None and plan.nan_at_step == 3
        assert chaos.plan_from_env(dict(env, MH_PID="0")) is None
        assert chaos.plan_from_env(  # no MH_PID -> pid 0 -> no entry
            {chaos.ENV_VAR: json.dumps({"1": {"nan_at_step": 3}})}) is None
        with pytest.raises(ValueError, match="per-process"):
            chaos.plan_from_env({chaos.ENV_VAR: json.dumps({"1": 5}),
                                 "MH_PID": "1"})
        with pytest.raises(ValueError, match="unknown"):
            chaos.plan_from_env({chaos.ENV_VAR: json.dumps(
                {"1": {"nope": 1}}), "MH_PID": "1"})

    def test_new_fault_hooks_are_one_shot(self, monkeypatch):
        recorded = []
        monkeypatch.setattr(os, "kill",
                            lambda pid, sig: recorded.append((pid, sig)))
        chaos.set_plan(chaos.FaultPlan(sigterm_at_step=2))
        chaos.maybe_self_signal(1)
        assert recorded == []
        chaos.maybe_self_signal(2)
        chaos.maybe_self_signal(2)  # one-shot
        assert len(recorded) == 1

        slept = []
        import time as time_mod
        monkeypatch.setattr(time_mod, "sleep",
                            lambda s: slept.append(s))
        chaos.set_plan(chaos.FaultPlan(hang_at_step=3, hang_secs=5.0))
        chaos.maybe_hang(2)
        chaos.maybe_hang(3)
        chaos.maybe_hang(3)  # one-shot
        assert slept == [5.0]


def _tiny_cfg(tmp_path, **kw):
    from dcgan_tpu.config import ModelConfig, TrainConfig

    base = dict(
        model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                          compute_dtype="float32"),
        batch_size=16,
        checkpoint_dir=str(tmp_path / "ckpt"),
        sample_dir=str(tmp_path / "samples"),
        sample_every_steps=0, save_summaries_secs=0.0, save_model_secs=1e9,
        log_every_steps=0, tensorboard=False)
    base.update(kw)
    return TrainConfig(**base)


@pytest.mark.slow
class TestTrainerRollbackEndToEnd:
    def test_injected_nan_rolls_back_and_completes(self, tmp_path, capsys):
        from dcgan_tpu.train.trainer import train

        chaos.set_plan(chaos.FaultPlan(nan_at_step=3))
        cfg = _tiny_cfg(tmp_path, nan_policy="rollback", nan_check_steps=1,
                        rollback_snapshot_steps=2, max_rollbacks=2)
        state = train(cfg, synthetic_data=True, max_steps=6)
        assert int(jax.device_get(state["step"])) == 6
        out = capsys.readouterr().out
        assert "rolling back to last-good snapshot at step 2" in out
        events = [json.loads(l) for l in
                  open(tmp_path / "ckpt" / "events.jsonl")]
        rb = [e["values"]["anomaly/rollbacks"] for e in events
              if e["kind"] == "scalars"
              and "anomaly/rollbacks" in e["values"]]
        assert rb and max(rb) == 1

    def test_exhausted_rollbacks_abort(self, tmp_path):
        from dcgan_tpu.train.rollback import RollbackExhausted
        from dcgan_tpu.train.trainer import train

        # a genuinely divergent run (NaN learning rate poisons the params):
        # every restore re-trips, so the budget must end in a loud abort.
        # Summaries are off — the NaN params would crash the histogram
        # writer first, which is the abort path, not the one under test.
        cfg = _tiny_cfg(tmp_path, nan_policy="rollback", nan_check_steps=1,
                        rollback_snapshot_steps=2, max_rollbacks=2,
                        learning_rate=float("nan"), save_summaries_secs=1e9)
        with pytest.raises(RollbackExhausted, match="max_rollbacks"):
            train(cfg, synthetic_data=True, max_steps=8)

    def test_no_fault_parity_with_rollback_armed(self, tmp_path):
        """The A/B half of the acceptance parity criterion: arming the
        rollback machinery (snapshots, forced gate at boundaries) without
        any fault must leave every JSONL metric VALUE identical to the
        default-policy run — the snapshot path reads state, never touches
        it."""
        from dcgan_tpu.train.trainer import train

        def run(name, **kw):
            root = tmp_path / name
            cfg = _tiny_cfg(root, nan_check_steps=1, **kw)
            train(cfg, synthetic_data=True, max_steps=5)
            rows = {}
            for line in open(root / "ckpt" / "events.jsonl"):
                e = json.loads(line)
                if e["kind"] == "scalars":
                    rows[e["step"]] = {k: v for k, v in e["values"].items()
                                       if not k.startswith("perf/")}
            return rows

        a = run("abort")
        b = run("rollback", nan_policy="rollback",
                rollback_snapshot_steps=2, max_rollbacks=2,
                rollback_lr_backoff=0.5)
        assert a == b


@pytest.mark.slow
class TestQuarantineBaselineAcrossRuns:
    def test_second_train_call_does_not_inherit_counts(self, tmp_path):
        """ISSUE 4 satellite: the quarantine tally is process-global, so
        the trainer baselines it (`corrupt_base`) at startup — run 2's
        `data/corrupt_records` stream must report run 2's OWN corruption
        (zero here), not run 1's leftovers."""
        import dcgan_tpu.data.synthetic as synthetic
        from dcgan_tpu.train.trainer import train

        def events(root):
            out = []
            for line in open(root / "ckpt" / "events.jsonl"):
                e = json.loads(line)
                if e["kind"] == "scalars":
                    out.append(e["values"])
            return out

        # run 1: one corrupt record on disk, quarantined within budget
        data_dir = tmp_path / "data"
        paths = synthetic.write_image_tfrecords(
            str(data_dir), num_examples=48, image_size=16, num_shards=1)
        chaos.corrupt_tfrecord_payload(paths[0], record_index=2)
        root1 = tmp_path / "run1"
        cfg = _tiny_cfg(root1, data_dir=str(data_dir),
                        max_corrupt_records=10, shuffle_buffer=16,
                        num_loader_threads=1, save_summaries_secs=0.0)
        train(cfg, synthetic_data=False, max_steps=4)
        run1_counts = [v["data/corrupt_records"] for v in events(root1)
                       if "data/corrupt_records" in v]
        assert run1_counts and max(run1_counts) == 1

        # run 2, same process, clean synthetic data: the parity contract
        # says the counter key must be ABSENT (it only appears nonzero),
        # which is exactly what leaks from run 1 would violate
        root2 = tmp_path / "run2"
        cfg2 = _tiny_cfg(root2, max_corrupt_records=10,
                         save_summaries_secs=0.0)
        train(cfg2, synthetic_data=True, max_steps=4)
        assert all("data/corrupt_records" not in v for v in events(root2))
