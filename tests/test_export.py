"""Serving export (dcgan_tpu/export.py): checkpoint -> portable StableHLO
artifact with baked weights — the deployment surface the reference never had
(its sampler only exists inside the train graph, image_train.py:179-192)."""

import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # trains tiny checkpoints; see pytest.ini

from dcgan_tpu.config import (
    MODEL_OVERRIDE_FLAGS,
    ModelConfig,
    TrainConfig,
)
from dcgan_tpu.export import build_parser, export_sampler, load_sampler, main
from dcgan_tpu.train.trainer import train


def _train_ckpt(root, **model_kw):
    cfg = TrainConfig(
        model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                          compute_dtype="float32", **model_kw),
        batch_size=8,
        checkpoint_dir=str(root / "ckpt"),
        sample_dir=str(root / "samples"),
        sample_every_steps=0, save_summaries_secs=1e9, save_model_secs=1e9,
        log_every_steps=0)
    train(cfg, synthetic_data=True, max_steps=1)
    return str(root / "ckpt")


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    return _train_ckpt(tmp_path_factory.mktemp("export"))


class TestExportSampler:
    def test_artifact_matches_framework_sampler(self, ckpt, tmp_path):
        out = str(tmp_path / "sampler.jaxexport")
        meta = export_sampler(
            ckpt, out, overrides={"output_size": 16, "gf_dim": 8,
                                  "df_dim": 8},
            platforms=("cpu",))
        assert os.path.exists(out)
        sidecar = json.load(open(out + ".json"))
        assert sidecar["z_dim"] == meta["z_dim"] == 100
        assert sidecar["image_shape"] == [16, 16, 3]
        assert sidecar["step"] == 1

        exported = load_sampler(out)
        # batch 8 tiles the 8-virtual-device test mesh, so the same z can
        # feed the framework's sharded sample() below for the exact check
        z = np.random.default_rng(0).uniform(
            -1, 1, size=(8, 100)).astype(np.float32)
        imgs = np.asarray(exported.call(z))
        assert imgs.shape == (8, 16, 16, 3)
        assert np.abs(imgs).max() <= 1.0

        # the artifact must reproduce the framework's own sampler exactly
        # (same weights, same graph, just serialized)
        import jax

        from dcgan_tpu.parallel import make_mesh, make_parallel_train
        from dcgan_tpu.utils.checkpoint import Checkpointer

        cfg = TrainConfig(model=ModelConfig(output_size=16, gf_dim=8,
                                            df_dim=8,
                                            compute_dtype="float32"),
                          batch_size=8, checkpoint_dir=ckpt)
        pt = make_parallel_train(cfg, make_mesh(cfg.mesh))
        state = Checkpointer(ckpt).restore_latest(pt.init(jax.random.key(0)))
        ref = np.asarray(jax.device_get(pt.sample(state, jax.numpy.asarray(z))))
        np.testing.assert_allclose(imgs, ref, atol=1e-5)

    def test_symbolic_batch_serves_any_size(self, ckpt, tmp_path):
        out = str(tmp_path / "s.jaxexport")
        export_sampler(ckpt, out,
                       overrides={"output_size": 16, "gf_dim": 8,
                                  "df_dim": 8},
                       platforms=("cpu",))
        exported = load_sampler(out)
        for b in (1, 3, 8):
            z = np.zeros((b, 100), np.float32)
            assert np.asarray(exported.call(z)).shape == (b, 16, 16, 3)

    def test_conditional_artifact_takes_labels(self, tmp_path_factory,
                                               tmp_path):
        ckpt = _train_ckpt(tmp_path_factory.mktemp("export_cond"),
                           num_classes=4)
        out = str(tmp_path / "cond.jaxexport")
        meta = export_sampler(
            ckpt, out, overrides={"output_size": 16, "gf_dim": 8,
                                  "df_dim": 8, "num_classes": 4},
            platforms=("cpu",))
        assert meta["num_classes"] == 4
        exported = load_sampler(out)
        z = np.zeros((4, 100), np.float32)
        labels = np.arange(4, dtype=np.int32)
        imgs = np.asarray(exported.call(z, labels))
        assert imgs.shape == (4, 16, 16, 3)
        # conditioning must matter: different labels, different images
        other = np.asarray(exported.call(z, np.zeros(4, np.int32)))
        assert not np.allclose(imgs[1:], other[1:])

    def test_flash_trained_attention_checkpoint_exports_dense(
            self, tmp_path_factory, tmp_path):
        """The rev-2 sagan presets TRAIN with the flash kernels
        (use_pallas=True); their checkpoints must still export — the
        artifact forces the dense lowering for StableHLO portability
        (export.py's use_pallas=False replace), and attention parameters
        are execution-form-agnostic, so the flash-trained weights serve
        through the dense sampler unchanged."""
        ckpt = _train_ckpt(tmp_path_factory.mktemp("export_attn"),
                           attn_res=8, use_pallas=True, bn_pallas=False)
        out = str(tmp_path / "attn.jaxexport")
        meta = export_sampler(
            ckpt, out, overrides={"output_size": 16, "gf_dim": 8,
                                  "df_dim": 8},
            platforms=("cpu",))
        assert meta["z_dim"] == 100
        exported = load_sampler(out)
        z = np.random.default_rng(1).uniform(
            -1, 1, size=(8, 100)).astype(np.float32)
        imgs = np.asarray(exported.call(z))
        assert imgs.shape == (8, 16, 16, 3)
        assert np.abs(imgs).max() <= 1.0
        assert np.isfinite(imgs).all()

        # exact check against the framework sampler running the FLASH form
        # (interpret kernels on CPU): both attention forms are exact, so
        # the dense-lowered artifact must reproduce the flash-path images
        # to f32 tolerance — this is what pins the restored attention
        # parameters to the right wiring
        import jax

        from dcgan_tpu.parallel import make_mesh, make_parallel_train
        from dcgan_tpu.utils.checkpoint import Checkpointer

        cfg = TrainConfig(model=ModelConfig(output_size=16, gf_dim=8,
                                            df_dim=8, attn_res=8,
                                            use_pallas=True,
                                            bn_pallas=False,
                                            compute_dtype="float32"),
                          batch_size=8, checkpoint_dir=ckpt)
        pt = make_parallel_train(cfg, make_mesh(cfg.mesh))
        state = Checkpointer(ckpt).restore_latest(pt.init(jax.random.key(0)))
        ref = np.asarray(jax.device_get(
            pt.sample(state, jax.numpy.asarray(z))))
        np.testing.assert_allclose(imgs, ref, atol=1e-5)

    def test_resnet_checkpoint_exports_and_matches(self, tmp_path_factory,
                                                   tmp_path):
        """Round-trip for the second model family (VERDICT next-round #6):
        a spectral-norm resnet checkpoint — whose generator restore rides
        the same tree as the SN power-iteration state — must export to
        StableHLO and reproduce the framework sampler exactly."""
        import jax

        from dcgan_tpu.parallel import make_mesh, make_parallel_train
        from dcgan_tpu.utils.checkpoint import Checkpointer

        ckpt = _train_ckpt(tmp_path_factory.mktemp("export_resnet"),
                           arch="resnet", spectral_norm="d")
        ov = {"arch": "resnet", "output_size": 16, "gf_dim": 8, "df_dim": 8,
              "spectral_norm": "d"}
        out = str(tmp_path / "resnet.jaxexport")
        meta = export_sampler(ckpt, out, overrides=ov, platforms=("cpu",))
        assert meta["arch"] == "resnet"
        z = np.random.default_rng(2).uniform(
            -1, 1, size=(8, 100)).astype(np.float32)
        imgs = np.asarray(load_sampler(out).call(z))
        assert imgs.shape == (8, 16, 16, 3)
        assert np.isfinite(imgs).all()

        cfg = TrainConfig(model=ModelConfig(arch="resnet", output_size=16,
                                            gf_dim=8, df_dim=8,
                                            spectral_norm="d",
                                            compute_dtype="float32"),
                          batch_size=8, checkpoint_dir=ckpt)
        pt = make_parallel_train(cfg, make_mesh(cfg.mesh))
        state = Checkpointer(ckpt).restore_latest(pt.init(jax.random.key(0)))
        assert any(k.startswith("sn_") for k in state["bn"]["disc"])
        ref = np.asarray(jax.device_get(
            pt.sample(state, jax.numpy.asarray(z))))
        np.testing.assert_allclose(imgs, ref, atol=1e-5)

    def test_stylegan_ema_checkpoint_exports_and_matches(
            self, tmp_path_factory, tmp_path):
        """Third family: StyleGAN2-lite's per-sample weight modulation must
        survive both the symbolic-batch export (modulated convs reshape by
        the batch dim) and the EMA weight source."""
        import jax

        from dcgan_tpu.parallel import make_mesh, make_parallel_train
        from dcgan_tpu.utils.checkpoint import Checkpointer

        root = tmp_path_factory.mktemp("export_stylegan")
        cfg = TrainConfig(
            model=ModelConfig(arch="stylegan", output_size=16, gf_dim=8,
                              df_dim=8, compute_dtype="float32"),
            batch_size=8, g_ema_decay=0.5,
            checkpoint_dir=str(root / "ckpt"),
            sample_dir=str(root / "samples"),
            sample_every_steps=0, save_summaries_secs=1e9,
            save_model_secs=1e9, log_every_steps=0)
        train(cfg, synthetic_data=True, max_steps=2)
        ckpt = str(root / "ckpt")
        ov = {"arch": "stylegan", "output_size": 16, "gf_dim": 8,
              "df_dim": 8}
        out = str(tmp_path / "sg.jaxexport")
        meta = export_sampler(ckpt, out, overrides=ov, platforms=("cpu",),
                              use_ema=True)
        assert meta["arch"] == "stylegan" and meta["weights"] == "ema"
        exported = load_sampler(out)
        z = np.random.default_rng(3).uniform(
            -1, 1, size=(8, 100)).astype(np.float32)
        imgs = np.asarray(exported.call(z))
        assert imgs.shape == (8, 16, 16, 3)
        assert np.isfinite(imgs).all()
        # symbolic batch must serve odd sizes too — per-sample modulation
        # is the path most likely to have baked the trace batch
        assert np.asarray(exported.call(z[:3])).shape == (3, 16, 16, 3)
        np.testing.assert_allclose(np.asarray(exported.call(z[:3])),
                                   imgs[:3], atol=1e-5)

        # exact match against the framework's EMA sampler (pt.sample reads
        # ema_gen when g_ema_decay > 0)
        pt = make_parallel_train(cfg, make_mesh(cfg.mesh))
        state = Checkpointer(ckpt).restore_latest(pt.init(jax.random.key(0)))
        ref = np.asarray(jax.device_get(
            pt.sample(state, jax.numpy.asarray(z))))
        np.testing.assert_allclose(imgs, ref, atol=1e-5)

    def test_cli_and_flag_coverage(self, ckpt, tmp_path):
        parser = build_parser()
        args = parser.parse_args(["--checkpoint_dir", ckpt])
        for name in MODEL_OVERRIDE_FLAGS:
            assert hasattr(args, name), name
        out = str(tmp_path / "cli.jaxexport")
        main(["--checkpoint_dir", ckpt, "--out", out,
              "--output_size", "16", "--gf_dim", "8", "--df_dim", "8",
              "--platforms", "cpu", "--batch_size", "2"])
        exported = load_sampler(out)
        assert np.asarray(
            exported.call(np.zeros((2, 100), np.float32))).shape == \
            (2, 16, 16, 3)
        sidecar = json.load(open(out + ".json"))
        assert sidecar["batch"] == 2

    def test_ema_weights_differ_from_live(self, tmp_path_factory, tmp_path):
        root = tmp_path_factory.mktemp("export_ema")
        cfg = TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32"),
            batch_size=8, g_ema_decay=0.5,
            checkpoint_dir=str(root / "ckpt"),
            sample_dir=str(root / "samples"),
            sample_every_steps=0, save_summaries_secs=1e9,
            save_model_secs=1e9, log_every_steps=0)
        train(cfg, synthetic_data=True, max_steps=2)
        ckpt = str(root / "ckpt")
        ov = {"output_size": 16, "gf_dim": 8, "df_dim": 8}
        live = str(tmp_path / "live.jaxexport")
        ema = str(tmp_path / "ema.jaxexport")
        export_sampler(ckpt, live, overrides=ov, platforms=("cpu",))
        export_sampler(ckpt, ema, overrides=ov, platforms=("cpu",),
                       use_ema=True)
        z = np.random.default_rng(1).uniform(
            -1, 1, size=(2, 100)).astype(np.float32)
        a = np.asarray(load_sampler(live).call(z))
        b = np.asarray(load_sampler(ema).call(z))
        assert not np.allclose(a, b)
        assert json.load(open(ema + ".json"))["weights"] == "ema"
