"""Serving fleet (ISSUE 19): router policy, heartbeat health, failover,
and zero-downtime weight promotion.

The router units run against fake replicas (no threads, no device) so
the policy decisions — least-queue-depth tie-break, unhealthy exclusion,
sticky clients, hedge-once failover — are pinned deterministically. The
fleet tier runs real SamplerServers over fake sources: a poisoned
replica's requests fail over with zero failed client requests, a wedged
replica is drained by the heartbeat monitor and its backlog rescued, and
a promotion control op drains behind the in-flight batch. The end-to-end
tier serves a real checkpoint and pins the acceptance contract: a
mid-serve promotion to a newly finalized step swaps weights with ZERO
compile-cache requests (the prime() trick re-links the swapped state
through every cached executable) and zero dropped requests.
"""

import os
import shutil
import threading
import time

import numpy as np
import pytest

from dcgan_tpu.serve.fleet import PROMOTION_SEQUENCE, ServeFleet
from dcgan_tpu.serve.router import (
    MAX_ATTEMPTS,
    Router,
    RouterError,
    promotion_targets,
)
from dcgan_tpu.serve.server import (
    Response,
    SamplerServer,
    ServeError,
    ServeOverloadError,
)


class FakeSource:
    """No-device source: images encode their latent's first coordinate
    (the test_serve convention) plus reload() so promotions work."""

    def __init__(self, granule=1, z_dim=4, num_classes=0, block=None,
                 explode_at=0):
        self.granule = granule
        self.z_dim = z_dim
        self.num_classes = num_classes
        self.block = block            # optional Event: stall dispatches
        self.explode_at = explode_at  # raise on the n-th sample (1-based)
        self.calls = []
        self.events = []              # interleaving probe: sample/reload
        self.step = 0

    def prepare(self):
        return {"source": "fake", "step": self.step, "weights": "live"}

    def bucket_plan(self, ladder):
        return []

    def bind(self, compiled):
        pass

    def reload(self):
        self.step += 1
        self.events.append("reload")
        return {"source": "fake", "step": self.step, "weights": "live"}

    def sample(self, bucket, z, labels=None):
        if self.block is not None:
            self.block.wait()
        if self.explode_at and len(self.calls) + 1 >= self.explode_at:
            raise RuntimeError("replica device on fire")
        self.calls.append((bucket, z.shape[0]))
        self.events.append("sample")
        img = np.zeros((bucket, 2, 2, 1), np.float32)
        img[:, 0, 0, 0] = z[:, 0]
        return img


class FakeReplica:
    """The replica surface the router sees, with scripted behavior."""

    def __init__(self, depth=0, fail_with=None):
        self.depth = depth
        self.beats = 0
        self.is_poisoned = False
        self.fail_with = fail_with    # exception failing every submit
        self.responses = []           # unsettled Responses handed out
        self.evictions = 0
        self.failover_drops = 0

    def queue_depth(self):
        return self.depth

    def poisoned(self):
        return self.is_poisoned

    def submit(self, num_images=1, **kw):
        r = Response()
        self.responses.append(r)
        if self.fail_with is not None:
            r._fail(self.fail_with)
        return r

    def evict_pending(self):
        self.evictions += 1
        return 0

    def record_failover_drop(self, n=1):
        self.failover_drops += n


_LIVE_FLEETS = []


def make_fleet(sources, **kw):
    kw.setdefault("buckets", (4, 8))
    kw.setdefault("max_wait_ms", 5.0)
    f = ServeFleet(sources, **kw)
    _LIVE_FLEETS.append(f)
    return f


@pytest.fixture(autouse=True)
def _reap_fleets():
    """A failing test must never leave blocked workers alive holding
    dispatch scopes — unblock and stop every fleet this test created."""
    yield
    while _LIVE_FLEETS:
        f = _LIVE_FLEETS.pop()
        for s in f.servers:
            block = getattr(s.source, "block", None)
            if block is not None:
                block.set()
        try:
            f.stop(drain=False, timeout=10.0)
        except Exception:
            pass


class TestPromotionTargets:
    def test_targets_are_sorted_healthy_indices(self):
        assert promotion_targets({0: True, 1: True, 2: True}) == (0, 1, 2)
        assert promotion_targets({2: True, 0: True, 1: False}) == (0, 2)
        assert promotion_targets({0: False, 1: False}) == ()

    def test_sequence_is_the_committed_lattice(self):
        # the protocol tier's virtual fleet replays this exact tuple; a
        # rename or reorder must drift the committed lock deliberately
        assert PROMOTION_SEQUENCE == ("drain", "swap", "prime", "resume")


class TestRouterPolicy:
    def test_least_queue_depth_lowest_index_tie_break(self):
        r = Router([FakeReplica(depth=2), FakeReplica(depth=1),
                    FakeReplica(depth=1)])
        assert r.pick() == 1          # min depth, lowest index wins ties
        r._replicas[1].depth = 5
        assert r.pick() == 2

    def test_unhealthy_and_poisoned_replicas_excluded(self):
        r = Router([FakeReplica(), FakeReplica(depth=9), FakeReplica()])
        r.mark_unhealthy(0, "test")
        assert r.pick() == 2          # depth 9 still beats unhealthy 0
        assert r._replicas[0].evictions == 1   # drain rescued its queue
        r._replicas[2].is_poisoned = True
        assert r.pick() == 1          # poisoned excluded without marking
        r.mark_unhealthy(1, "test")
        with pytest.raises(RouterError, match="no healthy"):
            r.pick()

    def test_sticky_client_survives_depth_changes(self):
        r = Router([FakeReplica(), FakeReplica(depth=1)])
        assert r.pick(client_id="c") == 0
        r._replicas[0].depth = 50     # 1 is now far cheaper
        assert r.pick(client_id="c") == 0      # sticky: FIFO preserved
        assert r.pick(client_id="new") == 1    # new clients go by depth
        r.mark_unhealthy(0, "test")
        assert r.pick(client_id="c") == 1      # re-picked out of rotation

    def test_mark_healthy_readmits_but_never_poisoned(self):
        r = Router([FakeReplica(), FakeReplica()])
        r.mark_unhealthy(0, "test")
        r.mark_healthy(0)
        assert r.health()[0] is True
        r._replicas[1].is_poisoned = True
        r.mark_unhealthy(1, "poisoned")
        r.mark_healthy(1)
        assert r.health()[1] is False  # poisoning is permanent

    def test_poll_health_miss_beats_then_readmission(self):
        r = Router([FakeReplica(), FakeReplica()], miss_beats=3)
        r._replicas[1].beats = 5
        r.poll_health()                # baseline tick records beats
        for _ in range(2):
            r.poll_health()            # 2 silent polls: still in rotation
        assert r.health() == {0: True, 1: True}
        r.poll_health()                # 3rd silent poll: drained
        assert r.health() == {0: False, 1: False}
        r._replicas[0].beats += 1      # heartbeat resumes
        r.poll_health()
        assert r.health() == {0: True, 1: False}
        assert (0, "missed 3 heartbeats") in r.unhealthy_events

    def test_hedge_once_failover_rescues_request(self):
        dead = FakeReplica(fail_with=ServeError("worker died"))
        peer = FakeReplica(depth=1)
        r = Router([dead, peer])
        resp = r.submit(num_images=2, client_id="c")
        assert not resp.done()         # hedged onto the peer, in flight
        assert len(peer.responses) == 1
        img = np.zeros((2, 2, 2, 1), np.float32)
        peer.responses[0]._resolve(img, {"buckets": [4]})
        assert resp.result(1).shape == (2, 2, 2, 1)
        assert r.failovers == 1 and r.failover_drops == 0
        # the sticky mapping followed the failover
        assert r.pick(client_id="c") == 1

    def test_hedge_budget_is_one_retry(self):
        both_dead = [FakeReplica(fail_with=ServeError("worker died")),
                     FakeReplica(fail_with=ServeError("worker died"))]
        r = Router(both_dead)
        resp = r.submit(num_images=1)
        with pytest.raises(ServeError, match="worker died"):
            resp.result(1)
        assert MAX_ATTEMPTS == 2
        assert sum(len(x.responses) for x in both_dead) == 2
        assert r.failovers == 1 and r.failover_drops == 1
        assert sum(x.failover_drops for x in both_dead) == 1

    def test_overload_and_bad_requests_are_not_hedged(self):
        shed = FakeReplica(fail_with=ServeOverloadError(
            "queue full", queue_depth=7, oldest_wait_ms=12.5))
        idle = FakeReplica()
        r = Router([shed, idle])
        resp = r.submit(num_images=1)
        with pytest.raises(ServeOverloadError) as ei:
            resp.result(1)
        # the overload error carries live pressure telemetry (ISSUE 19
        # satellite): clients can back off proportionally
        assert ei.value.queue_depth == 7
        assert ei.value.oldest_wait_ms == 12.5
        assert idle.responses == []    # deliberate shedding: no hedge
        assert r.failovers == 0 and r.failover_drops == 0


class TestFleetOverFakeSources:
    def test_replica_death_fails_over_zero_failed_requests(self):
        """Kill one replica's device mid-trace: every client request
        still completes, the death is logged, the drop split shows NO
        failover drops (every orphan was rescued)."""
        fleet = make_fleet([FakeSource(explode_at=1), FakeSource(),
                            FakeSource()])
        fleet.start(timeout=30)
        fleet.router.stop_monitor()    # poll manually: deterministic
        # all depths 0: the tie-break routes request 1 to replica 0,
        # whose first dispatch explodes — the request must fail over
        resps = [fleet.submit(2, client_id=f"c{i}") for i in range(6)]
        out = [r.result(30) for r in resps]
        fleet.router.poll_health()     # notice the poisoned worker
        fleet.stop(drain=True)
        assert all(o.shape == (2, 2, 2, 1) for o in out)
        rep = fleet.report()
        assert rep["serve/completed"] == 6.0
        assert rep["serve/dropped_failover"] == 0.0
        assert rep["serve/fleet_unhealthy"] == 1.0
        assert rep["serve/fleet_failovers"] >= 1.0
        assert (0, "poisoned") in fleet.router.unhealthy_events
        # the dead replica's stop error was collected, not raised
        assert [i for i, _ in fleet.stop_errors] == [0]

    def test_wedged_replica_backlog_rescued_by_heartbeat(self):
        """A replica blocked in dispatch stops beating; the monitor
        drains it and its NEVER-dispatched backlog fails over to the
        peer. The in-flight request still completes when the wedge
        clears, and the resumed heartbeat re-admits the replica."""
        block = threading.Event()
        wedged = FakeSource(block=block)
        fleet = make_fleet([wedged, FakeSource()], miss_beats=2)
        fleet.start(timeout=30)
        fleet.router.stop_monitor()
        block.clear()                  # wedge AFTER warmup dispatches
        first = fleet.submit(1, client_id="c")   # sticks to replica 0
        time.sleep(0.1)                # worker now blocked in sample
        parked = fleet.submit(1, client_id="c")  # queued behind the wedge
        # poll slower than the idle beat cadence (~0.1s), like the real
        # monitor: an IDLE healthy peer must never accumulate misses
        deadline = time.monotonic() + 10.0
        while fleet.router.health()[0] and time.monotonic() < deadline:
            fleet.router.poll_health()
            time.sleep(0.15)
        assert fleet.router.health() == {0: False, 1: True}
        assert parked.result(10).shape == (1, 2, 2, 1)   # rescued
        assert fleet.router.failovers == 1
        block.set()                    # wedge clears: in-flight finishes
        assert first.result(10).shape == (1, 2, 2, 1)
        deadline = time.monotonic() + 10.0
        while not fleet.router.health()[0] \
                and time.monotonic() < deadline:
            fleet.router.poll_health()
            time.sleep(0.15)
        assert fleet.router.health()[0] is True   # re-admitted
        fleet.stop(drain=True)

    def test_promotion_drains_behind_inflight_batch(self):
        """The control op pops only between batches and ahead of queued
        requests: sample(in-flight) -> reload -> sample(queued) — the
        drain barrier is the sequential dispatch thread itself."""
        block = threading.Event()
        block.set()
        src = FakeSource(block=block)
        fleet = make_fleet([src], max_wait_ms=1.0)
        fleet.start(timeout=30)
        block.clear()
        inflight = fleet.submit(1)
        time.sleep(0.1)                # worker blocked inside sample 1
        ticket = fleet.servers[0].request_promote()
        queued = fleet.submit(1)
        time.sleep(0.05)
        assert not ticket.done()       # promotion waits on the drain
        block.set()
        info = ticket.result(10)
        assert inflight.result(10) is not None
        assert queued.result(10) is not None
        fleet.stop(drain=True)
        assert src.events == ["sample", "reload", "sample"]
        assert info["replica"] == 0 and info["step"] == 1
        assert info["compile_requests_delta"] is None   # no cache wired
        rep = fleet.report()
        assert rep["serve/promotions"] == 1.0
        assert rep["serve/promote_swap_ms"] >= 0.0

    def test_promote_targets_only_healthy_replicas(self):
        fleet = make_fleet([FakeSource(explode_at=1), FakeSource(),
                            FakeSource()])
        fleet.start(timeout=30)
        fleet.router.stop_monitor()
        fleet.submit(1).result(30)     # first pick poisons replica 0,
        fleet.router.poll_health()     # the request fails over
        results = fleet.promote()
        fleet.stop(drain=True)
        assert sorted(r["replica"] for r in results) == [1, 2]
        assert all("error" not in r for r in results)
        assert all(r["step"] == 1 for r in results)

    def test_overload_split_and_telemetry_on_fleet_report(self):
        block = threading.Event()
        src = FakeSource(block=block)
        fleet = make_fleet([src], max_queue=2, max_wait_ms=1.0)
        fleet.start(timeout=30)
        block.clear()
        first = fleet.submit(1)
        time.sleep(0.1)                # worker blocked: submits pile up
        shed = fleet.submit(1)
        fleet.submit(1)
        overflow = fleet.submit(1)     # displaces `shed` (drop-oldest)
        block.set()
        with pytest.raises(ServeOverloadError) as ei:
            shed.result(10)
        assert ei.value.queue_depth >= 1
        assert ei.value.oldest_wait_ms >= 0.0
        first.result(10), overflow.result(10)
        fleet.stop(drain=True)
        rep = fleet.report()
        assert rep["serve/dropped"] == 1.0
        assert rep["serve/dropped_overload"] == 1.0
        assert rep["serve/dropped_failover"] == 0.0
        assert fleet.servers[0].counters().serve_dropped_overload == 1

    def test_single_replica_fleet_matches_bare_server(self):
        """The router layer adds no transformation: the same latent rows
        through a 1-replica fleet and a bare server produce byte-
        identical images."""
        z = np.random.default_rng(7).uniform(
            -1, 1, (5, 4)).astype(np.float32)
        bare = SamplerServer(FakeSource(), buckets=(4, 8),
                             max_wait_ms=5.0)
        bare.start(timeout=30)
        want = bare.submit(z=z).result(10)
        bare.stop()
        fleet = make_fleet([FakeSource()])
        fleet.start(timeout=30)
        got = fleet.submit(z=z).result(10)
        fleet.stop(drain=True)
        np.testing.assert_array_equal(got, want)


@pytest.fixture(scope="module")
def promotable_ckpt(tmp_path_factory):
    """Two checkpoint dirs from one training lineage: `serve` holds only
    step 1 (what the fleet cold-starts on); `donor` holds step 2 (the
    newly finalized step a test injects mid-serve)."""
    from dcgan_tpu.config import ModelConfig, TrainConfig
    from dcgan_tpu.train.trainer import train

    root = tmp_path_factory.mktemp("fleet")
    serve_dir = str(root / "serve")

    def cfg(ckpt_dir):
        return TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32"),
            batch_size=8,
            checkpoint_dir=ckpt_dir,
            sample_dir=str(root / "samples"),
            sample_every_steps=0, save_summaries_secs=1e9,
            save_model_secs=1e9, log_every_steps=0, tensorboard=False)

    train(cfg(serve_dir), synthetic_data=True, max_steps=1)
    donor_dir = str(root / "donor")
    shutil.copytree(serve_dir, donor_dir)
    train(cfg(donor_dir), synthetic_data=True, max_steps=2)  # resumes @1
    assert os.path.isdir(os.path.join(donor_dir, "2"))
    return serve_dir, donor_dir


OVERRIDES = {"output_size": 16, "gf_dim": 8, "df_dim": 8}


def inject_step(donor_dir, serve_dir, step):
    """Deliver `step` into `serve_dir` the way a trainer would: integrity
    sidecars first, then the step dir copied under a tmp name and RENAMED
    in — a digit-named dir is finalized by the Orbax contract, so the
    watcher/promotion can never see a half-copied step."""
    integ = os.path.join(donor_dir, "integrity")
    if os.path.isdir(integ):
        dst = os.path.join(serve_dir, "integrity")
        os.makedirs(dst, exist_ok=True)
        for name in os.listdir(integ):
            if name.startswith(f"{step}."):
                shutil.copy2(os.path.join(integ, name),
                             os.path.join(dst, name))
    tmp = os.path.join(serve_dir, f"tmp.promote.{step}")
    shutil.copytree(os.path.join(donor_dir, str(step)), tmp)
    os.rename(tmp, os.path.join(serve_dir, str(step)))


@pytest.fixture
def _pristine_cache_state():
    """Point the process-global persistent cache at a tmp dir without
    leaking into later tests (the test_serve discipline)."""
    import jax

    prev = {
        "jax_compilation_cache_dir": jax.config.jax_compilation_cache_dir,
        "jax_persistent_cache_min_compile_time_secs":
            jax.config.jax_persistent_cache_min_compile_time_secs,
        "jax_persistent_cache_min_entry_size_bytes":
            jax.config.jax_persistent_cache_min_entry_size_bytes,
    }
    yield
    for k, v in prev.items():
        jax.config.update(k, v)
    from jax._src import compilation_cache

    compilation_cache.reset_cache()


class TestPromotionEndToEnd:
    def test_zero_recompile_promotion_serves_new_weights(
            self, promotable_ckpt, tmp_path, _pristine_cache_state):
        """The acceptance pin: a newly finalized step injected mid-serve
        promotes with compile_requests_delta == 0 (measured by the live
        CompileCacheMonitor across the swap + re-prime) and the swapped
        weights actually serve — same latents, different images."""
        from dcgan_tpu.serve import CheckpointSource

        serve_dir, donor_dir = promotable_ckpt
        fleet = make_fleet(
            [CheckpointSource(serve_dir, overrides=OVERRIDES)],
            buckets=None, max_batch=16, max_wait_ms=2.0,
            cache_dir=str(tmp_path / "cc"))
        metas = fleet.start(timeout=300)
        assert metas[0]["step"] == 1
        z = np.random.default_rng(11).uniform(
            -1, 1, (6, 100)).astype(np.float32)
        before = fleet.submit(z=z).result(60)

        inject_step(donor_dir, serve_dir, 2)
        results = fleet.promote()
        assert results == [{"replica": 0, "step": 2,
                            "swap_ms": results[0]["swap_ms"],
                            "compile_requests_delta": 0}]
        assert results[0]["swap_ms"] > 0

        after = fleet.submit(z=z).result(60)
        rep = fleet.report()
        fleet.stop(drain=True)
        assert rep["serve/recompiles_after_warmup"] == 0.0
        assert rep["serve/dropped"] == 0.0
        assert rep["serve/completed"] == 2.0
        assert rep["serve/promotions"] == 1.0
        assert before.shape == after.shape == (6, 16, 16, 3)
        # one optimizer step moved the generator: the swap was real
        assert not np.array_equal(before, after)

    def test_watcher_promotes_newly_finalized_step(
            self, promotable_ckpt, tmp_path):
        """The watch loop notices the renamed-in step and hot-swaps
        without an explicit promote() call."""
        from dcgan_tpu.serve import CheckpointSource, latest_finalized_step

        serve_dir, donor_dir = promotable_ckpt
        work = str(tmp_path / "watch")
        shutil.copytree(serve_dir, work)
        # the previous test may have already injected step 2 into the
        # module-scoped serve dir; the watcher needs a fresh copy at 1
        if os.path.isdir(os.path.join(work, "2")):
            shutil.rmtree(os.path.join(work, "2"))
        assert latest_finalized_step(work) == 1
        fleet = make_fleet(
            [CheckpointSource(work, overrides=OVERRIDES)],
            buckets=None, max_batch=16, max_wait_ms=2.0,
            watch_promotions=True, watch_interval_secs=0.05)
        fleet.start(timeout=300)
        inject_step(donor_dir, work, 2)
        deadline = time.monotonic() + 60.0
        while not fleet.promotion_results \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        fleet.stop(drain=True)
        assert fleet.promotion_results, "watcher never promoted"
        (result,) = fleet.promotion_results[0]
        assert result["step"] == 2 and "error" not in result
