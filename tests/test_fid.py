"""FID/KID rig: streaming-stat correctness vs numpy, Fréchet closed forms,
KID estimator properties, feature-extractor determinism, and the end-to-end
eval job (SURVEY.md §7 phase 8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.evals import (
    StreamingStats,
    compute_fid,
    frechet_distance,
    generator_stats,
    make_npz_feature_fn,
    make_random_feature_fn,
    stats_from_batches,
)


class TestStreamingStats:
    def test_matches_numpy_mean_cov(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(500, 7))
        stats = StreamingStats(7)
        for chunk in np.array_split(x, 9):  # uneven chunks
            stats.update(chunk)
        mu, cov = stats.finalize()
        np.testing.assert_allclose(mu, x.mean(axis=0), atol=1e-10)
        np.testing.assert_allclose(cov, np.cov(x, rowvar=False), atol=1e-10)

    def test_merge_equals_single_pass(self):
        rng = np.random.default_rng(1)
        a, b = rng.normal(size=(100, 4)), rng.normal(size=(150, 4))
        s1 = StreamingStats(4)
        s1.update(a)
        s2 = StreamingStats(4)
        s2.update(b)
        s1.merge(s2)
        mu, cov = s1.finalize()
        full = np.concatenate([a, b])
        np.testing.assert_allclose(mu, full.mean(axis=0), atol=1e-10)
        np.testing.assert_allclose(cov, np.cov(full, rowvar=False), atol=1e-10)

    def test_shape_and_count_validation(self):
        s = StreamingStats(3)
        with pytest.raises(ValueError):
            s.update(np.zeros((4, 5)))
        s.update(np.zeros((1, 3)))
        with pytest.raises(ValueError):
            s.finalize()  # n < 2


class TestFrechetDistance:
    def test_identical_gaussians_zero(self):
        rng = np.random.default_rng(2)
        a = rng.normal(size=(200, 6))
        cov = np.cov(a, rowvar=False)
        mu = a.mean(axis=0)
        assert frechet_distance(mu, cov, mu, cov) < 1e-8

    def test_univariate_closed_form(self):
        # FID(N(m1,s1^2), N(m2,s2^2)) = (m1-m2)^2 + s1^2 + s2^2 - 2 s1 s2
        m1, s1, m2, s2 = 0.0, 1.0, 3.0, 2.0
        got = frechet_distance([m1], [[s1 ** 2]], [m2], [[s2 ** 2]])
        want = (m1 - m2) ** 2 + s1 ** 2 + s2 ** 2 - 2 * s1 * s2
        assert abs(got - want) < 1e-10

    def test_diagonal_closed_form(self):
        d1 = np.array([1.0, 4.0])
        d2 = np.array([9.0, 1.0])
        mu1, mu2 = np.zeros(2), np.array([1.0, -1.0])
        want = (np.sum((mu1 - mu2) ** 2)
                + np.sum(d1 + d2 - 2 * np.sqrt(d1 * d2)))
        got = frechet_distance(mu1, np.diag(d1), mu2, np.diag(d2))
        assert abs(got - want) < 1e-10

    def test_separated_means_dominate(self):
        cov = np.eye(3)
        near = frechet_distance(np.zeros(3), cov, 0.1 * np.ones(3), cov)
        far = frechet_distance(np.zeros(3), cov, 5.0 * np.ones(3), cov)
        assert far > near > 0


class TestFeatureExtractors:
    def test_deterministic_across_builds(self):
        f1, d1 = make_random_feature_fn(32, 3, feature_dim=64)
        f2, d2 = make_random_feature_fn(32, 3, feature_dim=64)
        x = jnp.asarray(np.random.default_rng(0).uniform(
            -1, 1, size=(4, 32, 32, 3)).astype(np.float32))
        assert d1 == d2 == 64
        np.testing.assert_array_equal(np.asarray(f1(x)), np.asarray(f2(x)))

    def test_seed_changes_features(self):
        f1, _ = make_random_feature_fn(16, 3, feature_dim=32, seed=1)
        f2, _ = make_random_feature_fn(16, 3, feature_dim=32, seed=2)
        x = jnp.ones((2, 16, 16, 3))
        assert not np.allclose(np.asarray(f1(x)), np.asarray(f2(x)))

    def test_npz_roundtrip(self, tmp_path):
        # export a tiny embedder and reload it through the npz slot
        key = jax.random.key(0)
        from dcgan_tpu.ops.layers import conv2d_init

        conv = conv2d_init(key, 3, 8)
        proj = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
        path = str(tmp_path / "emb.npz")
        np.savez(path, **{"conv0/w": np.asarray(conv["w"]),
                          "conv0/b": np.asarray(conv["b"]), "proj": proj})
        fn, dim = make_npz_feature_fn(path)
        assert dim == 16
        out = fn(jnp.ones((2, 16, 16, 3)))
        assert out.shape == (2, 16) and np.isfinite(np.asarray(out)).all()

    def test_npz_missing_keys_rejected(self, tmp_path):
        path = str(tmp_path / "bad.npz")
        np.savez(path, foo=np.zeros(3))
        with pytest.raises(ValueError):
            make_npz_feature_fn(path)


def _image_stream(seed, n_per_batch, size, shift=0.0):
    rng = np.random.default_rng(seed)
    while True:
        yield np.clip(rng.normal(loc=shift, scale=0.3,
                                 size=(n_per_batch, size, size, 3)),
                      -1, 1).astype(np.float32)


class TestKID:
    def test_same_distribution_near_zero_unbiased(self):
        from dcgan_tpu.evals.kid import mmd2_unbiased

        rng = np.random.default_rng(0)
        x = rng.normal(size=(400, 16))
        y = rng.normal(size=(400, 16))
        z = rng.normal(loc=1.0, size=(400, 16))
        same = mmd2_unbiased(x, y)
        diff = mmd2_unbiased(x, z)
        # unbiased estimator: near zero (can be slightly negative) for same
        # distribution, clearly positive under a mean shift
        assert abs(same) < 0.1
        assert diff > 10 * abs(same)

    def test_kid_score_subset_averaging(self):
        from dcgan_tpu.evals.kid import kid_score

        rng = np.random.default_rng(1)
        real = rng.normal(size=(600, 8))
        fake = rng.normal(loc=0.5, size=(600, 8))
        mean, std = kid_score(real, fake, subset_size=100, num_subsets=20,
                              seed=0)
        assert mean > 0 and std >= 0
        mean2, _ = kid_score(real, fake, subset_size=100, num_subsets=20,
                             seed=0)
        assert mean == mean2  # deterministic under a fixed seed

    def test_feature_pool_reservoir_uniformity(self):
        from dcgan_tpu.evals.kid import FeaturePool

        pool = FeaturePool(1, capacity=64, seed=0)
        # stream 0..999 as 1-dim features; reservoir mean ~ stream mean
        for start in range(0, 1000, 50):
            pool.update(np.arange(start, start + 50,
                                  dtype=np.float32)[:, None])
        assert pool.features().shape == (64, 1)
        assert pool.n_seen == 1000
        assert abs(float(pool.features().mean()) - 499.5) < 120  # ~3 sigma

    def test_feature_pool_merge_counts(self):
        from dcgan_tpu.evals.kid import FeaturePool

        a = FeaturePool(2, capacity=16, seed=0)
        b = FeaturePool(2, capacity=16, seed=1)
        a.update(np.zeros((10, 2), np.float32))
        b.update(np.ones((30, 2), np.float32))
        a.merge(b)
        assert a.n_seen == 40
        # union sample leans toward the larger stream
        assert float(a.features().mean()) > 0.5

    def test_feature_pool_merge_not_early_stream_biased(self):
        """Merging two at-capacity pools must draw from each side's WHOLE
        uniform sample, not a stream-ordered prefix (ADVICE r1: the fill
        phase leaves buffers stream-ordered, so prefix draws skew early).
        Features encode stream position; the merged mean must sit near the
        union stream's mean, not the early-stream mean."""
        from dcgan_tpu.evals.kid import FeaturePool

        a = FeaturePool(1, capacity=200, seed=0)
        b = FeaturePool(1, capacity=200, seed=1)
        # both pools exactly at capacity -> buffers are fill-phase ordered,
        # the worst case for a prefix draw (take=200 of mine+theirs=400)
        a.update(np.arange(0, 200, dtype=np.float32)[:, None])
        b.update(np.arange(1000, 1200, dtype=np.float32)[:, None])
        a.merge(b)
        assert a.features().shape == (200, 1)
        # union mean = (99.5 + 1099.5)/2 = 599.5; a prefix-biased draw pulls
        # each side's early half, giving ~(49.75 + 1049.75)/2 when balanced
        # but skewing hard whenever p_other streaks — require the mean close
        # to uniform AND late-stream elements from both sides present
        feats = a.features().ravel()
        mine = feats[feats < 1000]
        theirs = feats[feats >= 1000]
        assert abs(feats.mean() - 599.5) < 80
        assert mine.max() > 150 and theirs.max() > 1150  # late tails drawn

    @pytest.mark.slow
    def test_compute_fid_with_kid_single_pass(self):
        from dcgan_tpu.config import ModelConfig
        from dcgan_tpu.models import gan_init, sampler_apply

        mcfg = ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                           compute_dtype="float32")
        params, bn = gan_init(jax.random.key(0), mcfg)

        def sample_fn(z):
            return sampler_apply(params["gen"], bn["gen"], z, cfg=mcfg)

        result = compute_fid(sample_fn, _image_stream(0, 64, 16),
                             image_size=16, z_dim=mcfg.z_dim,
                             num_samples=128, batch_size=64, kid=True,
                             kid_subset_size=64, kid_subsets=5)
        assert np.isfinite(result["kid"]) and result["kid_std"] >= 0
        # untrained G vs gaussian reals: clearly nonzero
        assert result["kid"] > 0


class TestEvalJob:
    def test_stats_from_batches_exact_count(self):
        fn, dim = make_random_feature_fn(16, 3, feature_dim=32)
        stats = stats_from_batches(fn, _image_stream(0, 24, 16), 100, dim)
        assert stats.n == 100  # trimmed mid-batch

    def test_stats_exhaustion_raises(self):
        fn, dim = make_random_feature_fn(16, 3, feature_dim=32)
        finite = [next(_image_stream(0, 8, 16)) for _ in range(2)]
        with pytest.raises(ValueError):
            stats_from_batches(fn, iter(finite), 100, dim)

    def test_same_distribution_scores_near_zero_vs_shifted(self):
        fn, dim = make_random_feature_fn(16, 3, feature_dim=32)
        a = stats_from_batches(fn, _image_stream(1, 64, 16), 512, dim)
        b = stats_from_batches(fn, _image_stream(2, 64, 16), 512, dim)
        c = stats_from_batches(fn, _image_stream(3, 64, 16, shift=0.8),
                               512, dim)
        same = frechet_distance(*a.finalize(), *b.finalize())
        diff = frechet_distance(*a.finalize(), *c.finalize())
        assert diff > 10 * same

    def test_compute_fid_end_to_end(self):
        """Untrained G vs gaussian 'reals': runs, finite, positive; and the
        generator scored against its own samples is near zero."""
        from dcgan_tpu.config import ModelConfig
        from dcgan_tpu.models import gan_init, sampler_apply

        mcfg = ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                           compute_dtype="float32")
        params, bn = gan_init(jax.random.key(0), mcfg)

        def sample_fn(z):
            return sampler_apply(params["gen"], bn["gen"], z, cfg=mcfg)

        result = compute_fid(sample_fn, _image_stream(0, 64, 16),
                             image_size=16, z_dim=mcfg.z_dim,
                             num_samples=256, batch_size=64)
        assert result["num_samples"] == 256
        assert np.isfinite(result["fid"]) and result["fid"] > 0

        fn, dim = make_random_feature_fn(16, 3)
        g1 = generator_stats(sample_fn, fn, dim, num_samples=256,
                             batch_size=64, z_dim=mcfg.z_dim, seed=5)
        g2 = generator_stats(sample_fn, fn, dim, num_samples=256,
                             batch_size=64, z_dim=mcfg.z_dim, seed=6)
        self_fid = frechet_distance(*g1.finalize(), *g2.finalize())
        assert self_fid < result["fid"]

    def test_conditional_generator_stats(self):
        from dcgan_tpu.config import ModelConfig
        from dcgan_tpu.models import gan_init, sampler_apply

        mcfg = ModelConfig(output_size=16, gf_dim=8, df_dim=8, num_classes=4,
                           compute_dtype="float32")
        params, bn = gan_init(jax.random.key(0), mcfg)

        def sample_fn(z, labels):
            return sampler_apply(params["gen"], bn["gen"], z, cfg=mcfg,
                                 labels=labels)

        fn, dim = make_random_feature_fn(16, 3, feature_dim=32)
        stats = generator_stats(sample_fn, fn, dim, num_samples=96,
                                batch_size=32, z_dim=mcfg.z_dim,
                                num_classes=4)
        assert stats.n == 96


class TestRealStatsCache:
    """--real_stats cache: pure-numpy round trip (smoke tier; the CLI
    integration lives in the slow tier's eval tests)."""

    def test_npz_round_trip_exact(self, tmp_path):
        from dcgan_tpu.evals.fid import StreamingStats
        from dcgan_tpu.evals.job import (
            real_side_from_npz,
            real_side_to_npz,
        )
        from dcgan_tpu.evals.kid import FeaturePool

        rng = np.random.default_rng(0)
        stats = StreamingStats(8)
        pool = FeaturePool(8, 16, seed=3)
        feats = rng.normal(size=(40, 8)).astype(np.float32)
        stats.update(feats)
        pool.update(feats)

        path = str(tmp_path / "real.npz")
        real_side_to_npz(path, stats, pool)
        s2, p2 = real_side_from_npz(path, need_pool=True)
        assert s2.n == stats.n
        np.testing.assert_array_equal(s2._sum, stats._sum)
        np.testing.assert_array_equal(s2._outer, stats._outer)
        np.testing.assert_array_equal(p2.features(), pool.features())
        assert p2.n_seen == pool.n_seen
        # finalized moments identical -> identical FID contribution
        np.testing.assert_array_equal(s2.finalize()[1], stats.finalize()[1])

    def test_missing_pool_rejected_when_kid(self, tmp_path):
        from dcgan_tpu.evals.fid import StreamingStats
        from dcgan_tpu.evals.job import (
            real_side_from_npz,
            real_side_to_npz,
        )

        stats = StreamingStats(4)
        stats.update(np.ones((4, 4), np.float32))
        path = str(tmp_path / "nopool.npz")
        real_side_to_npz(path, stats, None)
        assert real_side_from_npz(path, need_pool=False)[1] is None
        with pytest.raises(ValueError, match="no feature reservoir"):
            real_side_from_npz(path, need_pool=True)

    def test_extensionless_path_round_trips(self, tmp_path):
        """np.savez appends '.npz' to bare paths; save and load must agree
        on the final name or the cache never hits."""
        from dcgan_tpu.evals.fid import StreamingStats
        from dcgan_tpu.evals.job import real_side_from_npz, real_side_to_npz

        stats = StreamingStats(4)
        stats.update(np.ones((4, 4), np.float32))
        bare = str(tmp_path / "celeba_real")      # no extension
        real_side_to_npz(bare, stats, None)
        s2, _ = real_side_from_npz(bare, need_pool=False)
        assert s2.n == 4

    def test_pool_capacity_mismatch_rejected(self, tmp_path):
        from dcgan_tpu.evals.job import compute_fid, real_side_to_npz
        from dcgan_tpu.evals.fid import StreamingStats
        from dcgan_tpu.evals.kid import FeaturePool
        import jax.numpy as jnp

        stats = StreamingStats(512)
        stats.update(np.random.default_rng(0).normal(
            size=(64, 512)).astype(np.float32))
        pool = FeaturePool(512, 32)
        pool.update(np.random.default_rng(1).normal(
            size=(64, 512)).astype(np.float32))
        path = str(tmp_path / "real.npz")
        real_side_to_npz(path, stats, pool)

        with pytest.raises(ValueError, match="reservoir capacity"):
            compute_fid(lambda z: jnp.zeros((z.shape[0], 8, 8, 3)),
                        iter(()), image_size=8, num_samples=64,
                        batch_size=32, kid=True, kid_pool_size=16,
                        kid_subset_size=8, kid_subsets=2,
                        real_cache_path=path)
