"""Shared trace parser (ISSUE 6, dcgan_tpu/utils/trace.py): track
selection, per-program rows, and the compute/collective/idle-gap digest —
against both the committed v5e chip capture (the regression fixture) and
synthetic CPU-shaped traces."""

import gzip
import json
import os

import pytest

from dcgan_tpu.utils.trace import (
    devstep_ms,
    digest,
    find_trace,
    is_collective,
    select_device_tracks,
    summarize,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
V5E = os.path.join(REPO, "docs", "assets", "trace_train_step_v5e.json.gz")


def write_trace(path, events):
    with gzip.open(str(path), "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(path)


def meta(pid, name, tid=None):
    if tid is None:
        return {"ph": "M", "pid": pid, "name": "process_name",
                "args": {"name": name}}
    return {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": name}}


def span(pid, tid, name, ts, dur):
    return {"ph": "X", "pid": pid, "tid": tid, "name": name,
            "ts": ts, "dur": dur}


class TestV5eFixture:
    """The committed chip capture is the parser's ground truth: 5 train
    steps at ~2.845 ms on the XLA Modules track, with most of the span
    idle between dispatches (the tunneled-transport regime)."""

    def test_summarize_keeps_the_headline_step_time(self):
        rows, source = summarize(V5E)
        assert source == "tpu"
        step = next(r for r in rows if "train_step" in r["program"])
        assert step["n"] == 5
        assert 2.8 < step["ms_min"] <= step["ms_max"] < 2.9

    def test_digest_attribution(self):
        d = digest(V5E)
        assert d["source"] == "tpu"
        assert "train_step" in d["program"] and d["program_n"] == 5
        assert 2.8 < d["program_ms_median"] < 2.9
        # 5 steps of ~2.845 ms + tiny helper programs ~= 14.25 ms busy
        assert 14.0 < d["compute_ms"] < 15.0
        # the capture spans ~57.8 ms: the device sat idle most of it —
        # exactly the gap ROADMAP item 3's overlapped execution targets
        assert 40.0 < d["idle_gap_ms"] < 50.0
        assert abs((d["compute_ms"] + d["idle_gap_ms"]) - d["span_ms"]) < 0.1
        assert d["collective_ms"] == 0.0  # single-chip capture

    def test_devstep_helper_shared_by_the_bench_rows(self):
        """devstep_ms is THE definition bench.py / bench_trainer_loop.py /
        the trainer's perf/device/step_ms share: median busiest-program
        execution over per_exec steps."""
        assert devstep_ms(V5E) == pytest.approx(2.8449)
        assert devstep_ms(V5E, per_exec=5) == pytest.approx(2.8449 / 5)

    def test_steps_track_is_not_the_program_track(self):
        """The 'Steps' thread's whole-timeline spans must not leak into
        program accounting (they would zero out the idle gap)."""
        rows, _ = summarize(V5E)
        assert not any(r["program"].isdigit() for r in rows)


class TestSyntheticTraces:
    def test_tpu_pid_preferred_even_when_host_busier(self, tmp_path):
        ev = [meta(1, "/device:TPU:0"), meta(7, "/host:CPU"),
              meta(1, "XLA Modules", tid=2),
              span(1, 2, "jit_step", 0, 100),
              span(7, 9, "host_stuff", 0, 100000)]
        rows, source = summarize(write_trace(tmp_path / "t.json.gz", ev))
        assert source == "tpu"
        assert [r["program"] for r in rows] == ["jit_step"]

    def test_cpu_fallback_prefers_xla_thread_over_python(self, tmp_path):
        """CPU captures: the python thread's whole-call spans dominate by
        duration but the XLA executor thread is the device-work proxy."""
        ev = [meta(7, "/host:CPU"),
              meta(7, "python", tid=1),
              meta(7, "tf_XLATfrtCpuClient/123", tid=2),
              span(7, 1, "PjitFunction(step)", 0, 50000),
              span(7, 2, "dot.3", 100, 400),
              span(7, 2, "dot.3", 1000, 400)]
        rows, source = summarize(write_trace(tmp_path / "t.json.gz", ev))
        assert source == "xla-thread"
        assert rows[0]["program"] == "dot.3" and rows[0]["n"] == 2

    def test_xla_fallback_merges_the_thread_group(self, tmp_path):
        """Executor pools name threads '<pool>/<id>'; a capture whose
        programs spread across a pool's threads (the pipelined G/D stage
        dispatch does) must account the WHOLE group — a busiest-single-
        thread pick would leave roughly half the busy time invisible and
        inflate idle_gap_ms as a measurement artifact (ISSUE 7)."""
        ev = [meta(7, "/host:CPU"),
              meta(7, "tf_XLAEigen/111", tid=1),
              meta(7, "tf_XLAEigen/222", tid=2),
              span(7, 1, "d_update", 0, 400),
              span(7, 2, "g_update", 500, 400),
              span(7, 1, "d_update", 1000, 400)]
        d = digest(write_trace(tmp_path / "t.json.gz", ev))
        assert d["source"] == "xla-thread"
        # all three executions counted: 1.2 ms busy over a 1.4 ms span
        assert d["compute_ms"] == pytest.approx(1.2)
        assert d["idle_gap_ms"] == pytest.approx(0.2)
        assert {r["program"] for r in d["rows"]} == {"d_update", "g_update"}

    def test_xla_fallback_excludes_wait_spans(self, tmp_path):
        """Client '(wait for …)' spans are the executor WAITING, not
        executing: they must neither crown the wait-dominated client
        group during selection nor count as busy time."""
        ev = [meta(7, "/host:CPU"),
              meta(7, "tf_XLATfrtCpuClient/1", tid=1),
              meta(7, "tf_XLAEigen/1", tid=2),
              span(7, 1, "ThunkExecutor::Execute (wait for ready)",
                   0, 10000),
              span(7, 2, "conv.1", 0, 300),
              span(7, 2, "conv.1", 600, 300)]
        d = digest(write_trace(tmp_path / "t.json.gz", ev))
        assert d["source"] == "xla-thread"
        assert d["program"] == "conv.1" and d["program_n"] == 2
        assert d["compute_ms"] == pytest.approx(0.6)

    def test_busiest_nonpython_fallback(self, tmp_path):
        ev = [meta(7, "/host:CPU"),
              meta(7, "python", tid=1), meta(7, "worker", tid=2),
              span(7, 1, "trace_overhead", 0, 9000),
              span(7, 2, "exec", 0, 100)]
        rows, source = summarize(write_trace(tmp_path / "t.json.gz", ev))
        assert source == "busiest-thread"
        assert rows[0]["program"] == "exec"

    def test_no_duration_events_is_none(self, tmp_path):
        path = write_trace(tmp_path / "t.json.gz", [meta(7, "/host:CPU")])
        rows, source = summarize(path)
        assert rows == [] and source == "none"
        d = digest(path)
        assert d["source"] == "none" and d["rows"] == []
        assert devstep_ms(path) is None  # publish null, never fabricate

    def test_digest_merges_overlaps_and_measures_gaps(self, tmp_path):
        """Overlapping spans must not double count busy time; the idle gap
        is span minus the merged union."""
        ev = [meta(1, "/device:TPU:0"), meta(1, "XLA Modules", tid=2),
              span(1, 2, "jit_step", 0, 1000),
              span(1, 2, "overlap", 500, 1000),    # overlaps jit_step
              span(1, 2, "jit_step", 3000, 1000)]
        d = digest(write_trace(tmp_path / "t.json.gz", ev))
        assert d["compute_ms"] == pytest.approx(2.5)   # union, not 3.0
        assert d["idle_gap_ms"] == pytest.approx(1.5)  # [1500, 3000)
        assert d["span_ms"] == pytest.approx(4.0)

    def test_collectives_counted_from_ops_track(self, tmp_path):
        ev = [meta(1, "/device:TPU:0"),
              meta(1, "XLA Modules", tid=2), meta(1, "XLA Ops", tid=3),
              span(1, 2, "jit_step", 0, 2000),
              span(1, 3, "fusion.1", 0, 900),
              span(1, 3, "all-reduce.7", 900, 600),
              span(1, 3, "all-gather-start.2", 1500, 300)]
        d = digest(write_trace(tmp_path / "t.json.gz", ev))
        assert d["collective_ms"] == pytest.approx(0.9)
        assert d["compute_ms"] == pytest.approx(2.0)  # module track

    def test_overlap_frac_attributes_hidden_collective_time(self, tmp_path):
        """ISSUE 20's A/B attribution: collective busy time COVERED by
        non-collective ops counts as hidden, exposed tail does not —
        here [200, 800) of the 1000 us all-gather runs under fusion.1,
        so 0.6 of the collective time is hidden."""
        ev = [meta(1, "/device:TPU:0"),
              meta(1, "XLA Modules", tid=2), meta(1, "XLA Ops", tid=3),
              span(1, 2, "jit_step", 0, 3000),
              span(1, 3, "fusion.1", 0, 800),
              span(1, 3, "all-gather-start.2", 200, 1000),
              span(1, 3, "fusion.2", 2000, 500)]
        d = digest(write_trace(tmp_path / "t.json.gz", ev))
        assert d["collective_ms"] == pytest.approx(1.0)
        assert d["overlap_frac"] == pytest.approx(0.6)

    def test_overlap_frac_zero_without_collectives(self, tmp_path):
        ev = [meta(1, "/device:TPU:0"),
              meta(1, "XLA Modules", tid=2), meta(1, "XLA Ops", tid=3),
              span(1, 2, "jit_step", 0, 1000),
              span(1, 3, "fusion.1", 0, 900)]
        d = digest(write_trace(tmp_path / "t.json.gz", ev))
        assert d["overlap_frac"] == 0.0

    def test_is_collective_names(self):
        assert is_collective("all-reduce.13")
        assert is_collective("ALL-GATHER-start")
        assert is_collective("reduce-scatter.2")
        assert is_collective("collective-permute-done.1")
        assert not is_collective("fusion.4")
        assert not is_collective("jit_train_step(123)")

    def test_select_tracks_falls_back_without_module_thread(self, tmp_path):
        """Older capture layouts without an 'XLA Modules' thread name:
        everything on the TPU pid except 'Steps' spans counts."""
        ev = [meta(1, "/device:TPU:0"), meta(1, "Steps", tid=1),
              span(1, 1, "0", 0, 10000),
              span(1, 5, "jit_step", 0, 1000)]
        programs, ops, source = select_device_tracks(ev)
        assert source == "tpu"
        assert [e["name"] for e in programs] == ["jit_step"]
        assert ops == programs


class TestFindTrace:
    def test_file_dir_and_missing(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            find_trace(str(tmp_path))
        d = tmp_path / "plugins" / "profile" / "x"
        d.mkdir(parents=True)
        p = d / "vm.trace.json.gz"
        p.write_bytes(b"")
        assert find_trace(str(tmp_path)) == str(p)
        assert find_trace(str(p)) == str(p)

    def test_host_filter_prefers_own_file(self, tmp_path):
        """Shared-filesystem fleets: every process writes
        <hostname>.trace.json.gz into one session dir — the chief must
        digest ITS host's timeline, not whichever peer sorts last."""
        d = tmp_path / "plugins" / "profile" / "x"
        d.mkdir(parents=True)
        mine = d / "host-a.trace.json.gz"
        peer = d / "host-z.trace.json.gz"
        mine.write_bytes(b"")
        peer.write_bytes(b"")
        assert find_trace(str(tmp_path)) == str(peer)  # plain tail
        assert find_trace(str(tmp_path), host="host-a") == str(mine)
        # no filename matches the host: fall back to the newest hit
        assert find_trace(str(tmp_path), host="elsewhere") == str(peer)
