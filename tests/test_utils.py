"""Utils tests: image grids, metric writer throttling, checkpoint round-trip."""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.utils.checkpoint import Checkpointer
from dcgan_tpu.utils.images import (
    image_grid,
    inverse_transform,
    save_png,
    save_sample_grid,
)
from dcgan_tpu.utils.metrics import (
    MetricWriter,
    activation_stats,
    histogram_summary,
    param_histograms,
)


class TestImages:
    def test_inverse_transform(self):
        np.testing.assert_allclose(
            inverse_transform(np.array([-1.0, 0.0, 1.0])), [0.0, 0.5, 1.0])

    def test_grid_tiling(self):
        imgs = np.stack([np.full((4, 4, 3), i, np.float32) for i in range(6)])
        g = image_grid(imgs, (2, 3))
        assert g.shape == (8, 12, 3)
        assert g[0, 0, 0] == 0 and g[0, 5, 0] == 1 and g[4, 0, 0] == 3

    def test_grid_too_few_images(self):
        with pytest.raises(ValueError):
            image_grid(np.zeros((3, 4, 4, 3)), (2, 2))

    def test_save_sample_grid_roundtrip(self, tmp_path):
        from PIL import Image
        path = str(tmp_path / "grid.png")
        imgs = np.random.default_rng(0).uniform(
            -1, 1, size=(64, 8, 8, 3)).astype(np.float32)
        save_sample_grid(path, imgs, (8, 8))
        arr = np.asarray(Image.open(path))
        assert arr.shape == (64, 64, 3)
        # pixel values match the inverse transform of the first tile
        expect = np.clip(inverse_transform(imgs[0]) * 255, 0, 255).astype(np.uint8)
        np.testing.assert_array_equal(arr[:8, :8], expect)


class TestMetrics:
    def test_histogram_summary(self):
        h = histogram_summary(np.array([0.0, 0.0, 1.0, -1.0]), bins=4)
        assert h["count"] == 4 and h["zero_fraction"] == 0.5
        assert sum(h["bin_counts"]) == 4

    def test_writer_throttling_and_events(self, tmp_path):
        w = MetricWriter(str(tmp_path), every_secs=1000.0)
        assert w.ready()        # first call fires immediately
        assert not w.ready()    # throttled afterwards
        w.write_scalars(5, {"d_loss": 1.5, "g_loss": jnp.float32(0.5)})
        w.write_histograms(5, {"w": np.arange(10.0)})
        w.write_image_event(5, "samples", "x.png")
        events = [json.loads(l) for l in
                  open(tmp_path / "events.jsonl").read().splitlines()]
        assert [e["kind"] for e in events] == ["scalars", "histograms", "image"]
        assert events[0]["values"]["d_loss"] == 1.5
        assert events[1]["values"]["w"]["count"] == 10

    def test_disabled_writer_writes_nothing(self, tmp_path):
        w = MetricWriter(str(tmp_path / "sub"), enabled=False)
        assert not w.ready()
        w.write_scalars(0, {"x": 1.0})
        assert not os.path.exists(tmp_path / "sub")

    def test_param_histograms_paths(self):
        tree = {"gen": {"conv0": {"w": np.zeros((2, 2))}}}
        out = param_histograms(tree)
        assert list(out) == ["gen/conv0/w"]


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        state = {
            "params": {"w": jnp.arange(6.0).reshape(2, 3)},
            "step": jnp.int32(7),
        }
        ck = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
        ck.save(7, state)
        ck.wait()
        assert ck.latest_step() == 7

        target = jax.tree_util.tree_map(jnp.zeros_like, state)
        restored = Checkpointer(str(tmp_path / "ckpt"),
                                async_save=False).restore_latest(target)
        np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                      np.asarray(state["params"]["w"]))
        assert int(restored["step"]) == 7

    def test_restore_without_checkpoint_returns_none(self, tmp_path):
        ck = Checkpointer(str(tmp_path / "empty"), async_save=False)
        assert ck.restore_latest({"x": jnp.zeros(())}) is None

    def test_maybe_save_throttles(self, tmp_path):
        ck = Checkpointer(str(tmp_path / "ckpt"), save_interval_secs=1000.0,
                          async_save=False)
        state = {"x": jnp.zeros(())}
        assert not ck.maybe_save(1, state)  # inside the first interval
        ck._next_save = time.time() - 1     # force the interval boundary
        assert ck.maybe_save(2, state)
        ck.wait()
        assert ck.latest_step() == 2


class TestActivationStats:
    def test_device_stats_match_numpy(self):
        x = jax.random.normal(jax.random.key(0), (64, 7)) 
        x = jnp.where(x < 0, 0.0, x)  # relu-like: real zeros
        stats = jax.jit(lambda a: activation_stats({"l": a}))(x)
        rec = {k: np.asarray(v) for k, v in stats["l"].items()}
        arr = np.asarray(x).ravel()
        np.testing.assert_allclose(rec["mean"], arr.mean(), rtol=1e-6)
        np.testing.assert_allclose(rec["zero_fraction"], (arr == 0).mean(),
                                   rtol=1e-6)
        counts, edges = np.histogram(arr, bins=30)
        np.testing.assert_array_equal(rec["bin_counts"], counts)
        np.testing.assert_allclose(rec["bin_edges"], edges, rtol=1e-5)

    def test_write_activations_event(self, tmp_path):
        w = MetricWriter(str(tmp_path), every_secs=0.0)
        x = jnp.arange(12.0).reshape(3, 4)
        w.write_activations(3, activation_stats({"gen/h0": x}, bins=4))
        ev = json.loads(open(os.path.join(str(tmp_path),
                                          "events.jsonl")).read())
        assert ev["kind"] == "activations" and ev["step"] == 3
        rec = ev["values"]["gen/h0"]
        assert rec["count"] == 12 and len(rec["bin_counts"]) == 4
        assert isinstance(rec["min"], float)
