"""Multi-host coordination units (ISSUE 4): anomaly consensus, coordinated
stop, hung-collective watchdog, device-resident rollback snapshots — all
with fake process-count/allgather shims, no subprocesses. The end-to-end
2-process proofs live in tools/chaos_drill.py --multihost (smoke-pinned in
test_tools.py) and the parity A/B in test_multihost.py."""

import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.train import coordination
from dcgan_tpu.train.rollback import RollbackManager

pytestmark = pytest.mark.chaos


def _fake_allgather(values):
    """An allgather shim returning a fixed per-process verdict vector."""
    return lambda local: np.asarray(values, np.int32)


class TestAnomalyConsensus:
    def test_single_process_passthrough(self):
        assert coordination.anomaly_consensus(False) == (False, [])
        assert coordination.anomaly_consensus(True) == (True, [0])

    def test_any_tripped_process_trips_all(self, monkeypatch):
        monkeypatch.setattr(jax, "process_count", lambda: 3)
        monkeypatch.setattr(coordination, "_allgather_i32",
                            _fake_allgather([0, 1, 0]))
        bad, trippers = coordination.anomaly_consensus(False)
        assert bad and trippers == [1]

    def test_no_trip_anywhere_passes(self, monkeypatch):
        monkeypatch.setattr(jax, "process_count", lambda: 3)
        monkeypatch.setattr(coordination, "_allgather_i32",
                            _fake_allgather([0, 0, 0]))
        assert coordination.anomaly_consensus(False) == (False, [])

    def test_local_verdict_reaches_the_wire(self, monkeypatch):
        """The shim must SEE the local verdict — the transport carries it
        to the peers."""
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        sent = []

        def capture(local):
            sent.append(local)
            return np.asarray([local, 0], np.int32)

        monkeypatch.setattr(coordination, "_allgather_i32", capture)
        bad, trippers = coordination.anomaly_consensus(True)
        assert sent == [1] and bad and trippers == [0]


class TestCoordinatedStop:
    def test_single_process_signal_flag_roundtrip(self):
        stop = coordination.CoordinatedStop()
        stop.install()
        try:
            assert stop.poll() == (None, [])
            signal.raise_signal(signal.SIGTERM)
            assert stop.local_signal == signal.SIGTERM
            assert stop.poll() == (signal.SIGTERM, [0])
        finally:
            stop.restore()

    def test_handler_is_one_shot(self):
        """First delivery restores the original handlers, so a second
        signal can still kill a hung final save."""
        stop = coordination.CoordinatedStop()
        seen = []
        orig = signal.signal(signal.SIGTERM, lambda *a: seen.append(a[0]))
        try:
            stop.install()
            signal.raise_signal(signal.SIGTERM)   # flag only
            assert seen == []
            signal.raise_signal(signal.SIGTERM)   # restored handler fires
            assert seen == [signal.SIGTERM]
        finally:
            stop.restore()
            signal.signal(signal.SIGTERM, orig)

    def test_multihost_consensus_any_host_stops_all(self, monkeypatch):
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(coordination, "_allgather_i32",
                            _fake_allgather([0, signal.SIGTERM]))
        stop = coordination.CoordinatedStop()  # local flag NOT set
        sig, origins = stop.poll()
        assert sig == signal.SIGTERM and origins == [1]

    def test_multihost_no_signal_anywhere(self, monkeypatch):
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(coordination, "_allgather_i32",
                            _fake_allgather([0, 0]))
        assert coordination.CoordinatedStop().poll() == (None, [])

    def test_multihost_sigterm_beats_sigint(self, monkeypatch):
        """Mixed signals resolve to one deterministic representative so
        every process logs and acts identically."""
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            coordination, "_allgather_i32",
            _fake_allgather([signal.SIGINT, signal.SIGTERM]))
        sig, origins = coordination.CoordinatedStop().poll()
        assert sig == max(signal.SIGTERM, signal.SIGINT)
        assert origins == [0, 1]


class TestCollectiveWatchdog:
    def _make(self, timeout=0.15, **kw):
        trips = []
        wd = coordination.CollectiveWatchdog(
            timeout, poll_interval=0.02,
            on_trip=lambda phase, step: trips.append((phase, step)), **kw)
        return wd, trips

    def test_expired_deadline_trips_with_context(self):
        wd, trips = self._make()
        try:
            wd.arm("collective-save", 7)
            time.sleep(0.5)
            assert trips and trips[0] == ("collective-save", 7)
        finally:
            wd.close()

    def test_disarm_prevents_trip(self):
        wd, trips = self._make()
        try:
            wd.arm("step-dispatch", 3)
            wd.disarm()
            time.sleep(0.4)
            assert trips == []
        finally:
            wd.close()

    def test_guard_context_disarms_on_exit(self):
        wd, trips = self._make()
        try:
            with wd.guard("stop-consensus", 1):
                pass
            time.sleep(0.4)
            assert trips == []
        finally:
            wd.close()

    def test_nested_guard_restores_outer_deadline(self):
        """The NaN-consensus guard nests inside the step-dispatch window;
        its exit must hand the (still ticking) outer deadline back, not
        silently disarm the outer section."""
        wd, trips = self._make()
        try:
            wd.arm("step-dispatch", 5)
            with wd.guard("nan-consensus", 5):
                pass  # quick inner collective
            time.sleep(0.5)  # outer section hangs past its deadline
            assert trips and trips[0] == ("step-dispatch", 5)
        finally:
            wd.close()

    def test_trips_while_main_thread_sleeps(self):
        """The enforcement thread is independent of the armed thread — a
        process hung in a sleep (or a GIL-releasing collective) still
        trips on schedule."""
        wd, trips = self._make()
        try:
            wd.arm("step-dispatch", 2)
            t0 = time.monotonic()
            while not trips and time.monotonic() - t0 < 2.0:
                time.sleep(0.05)
            assert trips == [("step-dispatch", 2)]
        finally:
            wd.close()

    def test_rearm_refreshes_deadline(self):
        wd, trips = self._make(timeout=0.2)
        try:
            for _ in range(4):
                wd.arm("step-dispatch", 1)
                time.sleep(0.08)  # always re-armed before expiry
            assert trips == []
        finally:
            wd.close()

    def test_zero_timeout_is_null_watchdog(self):
        wd = coordination.make_watchdog(0.0)
        wd.arm("x", 1)
        with wd.guard("y", 2):
            pass
        wd.disarm()
        wd.close()  # all free no-ops

    def test_rejects_nonpositive_timeout(self):
        with pytest.raises(ValueError, match="timeout_secs"):
            coordination.CollectiveWatchdog(0.0)

    def test_close_stops_thread(self):
        wd, _ = self._make()
        wd.close()
        assert not wd._thread.is_alive()
        assert threading.active_count() >= 1  # no stray state


class TestDeviceResidentRollback:
    """The multi-host snapshot mode: device-resident jitted copies, no host
    gather — restore survives buffer donation and serves repeat rollbacks."""

    def _state(self, value):
        return {"w": jnp.full((4, 4), value, jnp.float32),
                "step": jnp.asarray(int(value), jnp.int32)}

    def test_snapshot_restore_roundtrip(self):
        mgr = RollbackManager(every=2, max_rollbacks=3,
                              device_resident=True)
        mgr.snapshot(4, self._state(4.0))
        restored, step = mgr.restore(FloatingPointError("nan at 5"))
        assert step == 4
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4, 4), 4.0, np.float32))

    def test_restore_returns_fresh_buffers(self):
        """The returned arrays must not alias the snapshot: the next step
        donates them, and the snapshot has to survive to serve a second
        rollback."""
        mgr = RollbackManager(every=2, max_rollbacks=3,
                              device_resident=True)
        mgr.snapshot(2, self._state(2.0))
        first, _ = mgr.restore(FloatingPointError("trip 1"))
        # simulate donation: delete the restored buffers entirely
        for leaf in jax.tree_util.tree_leaves(first):
            leaf.delete()
        second, step = mgr.restore(FloatingPointError("trip 2"))
        assert step == 2
        np.testing.assert_array_equal(np.asarray(second["w"]),
                                      np.full((4, 4), 2.0, np.float32))

    def test_snapshot_is_a_copy_not_a_reference(self):
        """Donating the ORIGINAL state after snapshotting must not corrupt
        the restore point — the jitted identity copy owns its buffers."""
        mgr = RollbackManager(every=1, max_rollbacks=3,
                              device_resident=True)
        state = self._state(7.0)
        mgr.snapshot(1, state)
        for leaf in jax.tree_util.tree_leaves(state):
            leaf.delete()
        restored, _ = mgr.restore(FloatingPointError("trip"))
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4, 4), 7.0, np.float32))

    def test_budget_still_enforced(self):
        from dcgan_tpu.train.rollback import RollbackExhausted

        mgr = RollbackManager(every=1, max_rollbacks=1,
                              device_resident=True)
        mgr.snapshot(1, self._state(1.0))
        mgr.restore(FloatingPointError("one"))
        with pytest.raises(RollbackExhausted, match="max_rollbacks"):
            mgr.restore(FloatingPointError("two"))


class TestFleetHealth:
    """ISSUE 6 fleet health plane: the allgathered per-host vector and the
    fleet/* derivation, with the same fake-transport shims as the rest of
    this file (the end-to-end single-process path is
    tests/test_flight_recorder.py::TestFleetHealthEndToEnd)."""

    def _vec(self, step, step_ms, host_ms=1.0, queue=0, dropped=0,
             rollbacks=0, corrupt=0, phase=0):
        return np.asarray([step, step_ms, host_ms, queue, dropped,
                           rollbacks, corrupt, phase], np.float32)

    def test_single_process_gather_is_local_table(self):
        table = coordination.fleet_health_gather(self._vec(4, 12.5))
        assert table.shape == (1, len(coordination.HEALTH_FIELDS))
        assert table[0, 1] == pytest.approx(12.5)

    def test_multihost_gather_uses_the_f32_transport(self, monkeypatch):
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        sent = []

        def capture(vec):
            sent.append(np.asarray(vec))
            return np.stack([np.asarray(vec), np.asarray(vec) * 2])

        monkeypatch.setattr(coordination, "_allgather_f32", capture)
        n = len(coordination.HEALTH_FIELDS)
        table = coordination.fleet_health_gather(self._vec(4, 10.0))
        assert sent and sent[0].shape == (n,)   # local vector on the wire
        assert table.shape == (2, n)
        assert table[1, 1] == pytest.approx(20.0)

    def test_fleet_metrics_skew_and_slowest_host(self):
        table = np.stack([self._vec(6, 10.0, host_ms=2.0, dropped=1),
                          self._vec(6, 35.0, host_ms=3.0, queue=4),
                          self._vec(6, 12.0, rollbacks=1, corrupt=2)])
        row, note = coordination.fleet_metrics(table)
        assert row["fleet/step_ms_max"] == pytest.approx(35.0)
        assert row["fleet/step_ms_min"] == pytest.approx(10.0)
        assert row["fleet/step_ms_skew"] == pytest.approx(25.0)
        assert row["fleet/slowest_host"] == 1.0
        assert row["fleet/queue_depth_max"] == 4.0
        assert row["fleet/dropped_total"] == 1.0
        assert row["fleet/rollbacks_total"] == 1.0
        assert row["fleet/corrupt_total"] == 2.0
        assert "process 1" in note and "35.0" in note

    def test_trip_header_names_the_slowest_host(self, capfd, monkeypatch):
        """The watchdog note (set at each health gather) must surface in
        the trip header — the operator's first straggler attribution.
        capfd, not capsys: faulthandler writes to the real fd."""
        import os as os_mod

        wd = coordination.CollectiveWatchdog(0.1, poll_interval=0.02,
                                             on_trip=lambda *a: None)
        try:
            wd.set_note("slowest host: process 3 (step_ms_mean 99.0 vs "
                        "fleet min 10.0)")
            monkeypatch.setattr(os_mod, "_exit", lambda code: None)
            wd._dump_and_exit("step-dispatch", 7)
            err = capfd.readouterr().err
            assert "slowest host: process 3" in err
            assert "step-dispatch" in err
        finally:
            wd.close()


class TestNewKnobs:
    def test_config_validation(self):
        from dcgan_tpu.config import TrainConfig

        assert TrainConfig().coord_stop is True
        assert TrainConfig().collective_timeout_secs == 0.0
        with pytest.raises(ValueError, match="collective_timeout_secs"):
            TrainConfig(collective_timeout_secs=-1.0)

    def test_observability_knob_validation(self):
        from dcgan_tpu.config import TrainConfig

        cfg = TrainConfig()
        assert cfg.fleet_health_steps == 0      # off: parity default
        assert cfg.flight_recorder_steps == 64  # on, crash-path-only IO
        assert cfg.profile_trigger == ""
        with pytest.raises(ValueError, match="fleet_health_steps"):
            TrainConfig(fleet_health_steps=-1)
        with pytest.raises(ValueError, match="flight_recorder_steps"):
            TrainConfig(flight_recorder_steps=-2)
        # the health gather is a collective: it joins the steps_per_call
        # cadence-alignment rule
        with pytest.raises(ValueError, match="fleet_health_steps"):
            TrainConfig(steps_per_call=4, sample_every_steps=4,
                        nan_check_steps=4, activation_summary_steps=4,
                        save_model_steps=4, log_every_steps=4,
                        fleet_health_steps=3)

    def test_observability_flags_reach_config(self):
        from dcgan_tpu.train.cli import build_parser, config_from_args

        cfg = config_from_args(build_parser().parse_args(
            ["--profile_trigger", "/tmp/t", "--flight_recorder_steps",
             "32", "--fleet_health_steps", "50"]))
        assert cfg.profile_trigger == "/tmp/t"
        assert cfg.flight_recorder_steps == 32
        assert cfg.fleet_health_steps == 50

    def test_flags_reach_config(self):
        from dcgan_tpu.train.cli import build_parser, config_from_args

        cfg = config_from_args(build_parser().parse_args(
            ["--coord_stop", "false", "--collective_timeout_secs", "45"]))
        assert cfg.coord_stop is False
        assert cfg.collective_timeout_secs == 45.0

    def test_multihost_rollback_no_longer_rejected(self):
        """PR 3 hard-errored nan_policy='rollback' under multi-host; the
        consensus + device-resident snapshot layer makes it legal, so the
        trainer constructs a device-resident manager instead of raising."""
        import inspect

        from dcgan_tpu.train import trainer

        # _train_run is the run body (PR 5 split _train into a setup
        # wrapper owning the compile-cache monitor's lifetime + this)
        src = inspect.getsource(trainer._train_run)
        assert "single-process only" not in src
        assert "device_resident=jax.process_count() > 1" in src
