"""Serving plane (ISSUE 9): bucket ladder, continuous batcher, lifecycle,
and the zero-recompile-after-warmup guarantee.

Batcher semantics are pinned against a fake source (no device work, so
the units are milliseconds): bucket snap + padding, deadline flush,
drop-oldest backpressure, FIFO drain-on-stop, oversized-request
chunking. The end-to-end tier serves a real tiny checkpoint through the
framework sampler and pins (a) request/response parity with generate.py
for the same latent rows and (b) zero compile requests after the AOT
bucket warmup, measured through CompileCacheMonitor under a live
persistent cache — every served batch hits a precompiled bucket.
"""

import os
import threading
import time

import numpy as np
import pytest

from dcgan_tpu.serve.buckets import (
    BucketLadder,
    build_ladder,
    parse_buckets,
    sampler_plan,
)
from dcgan_tpu.serve.server import (
    SamplerServer,
    ServeError,
    ServeOverloadError,
)


class FakeSource:
    """No-device source: images encode their latent's first coordinate so
    tests can assert per-request routing through shared batches."""

    def __init__(self, granule=1, z_dim=4, num_classes=0, block=None):
        self.granule = granule
        self.z_dim = z_dim
        self.num_classes = num_classes
        self.block = block            # optional Event: stall dispatches
        self.calls = []               # (bucket, z.shape[0]) per dispatch
        self.label_rows = []

    def prepare(self):
        return {"source": "fake", "step": 0, "weights": "live"}

    def bucket_plan(self, ladder):
        return []

    def bind(self, compiled):
        pass

    def sample(self, bucket, z, labels=None):
        if self.block is not None:
            self.block.wait()
        self.calls.append((bucket, z.shape[0]))
        if labels is not None:
            self.label_rows.append(np.asarray(labels))
        img = np.zeros((bucket, 2, 2, 1), np.float32)
        img[:, 0, 0, 0] = z[:, 0]
        return img


_LIVE_SERVERS = []


def make_server(source=None, **kw):
    kw.setdefault("ladder", BucketLadder((4, 8), 1))
    kw.setdefault("max_wait_ms", 5.0)
    s = SamplerServer(source if source is not None else FakeSource(), **kw)
    _LIVE_SERVERS.append(s)
    return s


@pytest.fixture(autouse=True)
def _reap_servers():
    """A failing test must never leave a blocked worker alive holding the
    global tripwire dispatch scope — unblock and stop every server this
    test created."""
    yield
    while _LIVE_SERVERS:
        s = _LIVE_SERVERS.pop()
        block = getattr(s.source, "block", None)
        if block is not None:
            block.set()
        try:
            s.stop(drain=False, timeout=10.0)
        except Exception:
            pass


class TestBucketLadder:
    def test_build_and_snap(self):
        lad = build_ladder(64, 8)
        assert lad.buckets == (8, 16, 32, 64)
        assert lad.snap(1) == 8 and lad.snap(9) == 16 and lad.snap(64) == 64
        # past the top rung the caller chunks: snap returns the top
        assert lad.snap(65) == 64

    def test_granule_alignment_and_validation(self):
        assert build_ladder(20, 8).buckets == (8, 16, 24)  # top rounded up
        with pytest.raises(ValueError, match="granule"):
            BucketLadder((4, 10), granule=4)
        with pytest.raises(ValueError, match="ascending"):
            BucketLadder((8, 8, 16), granule=1)
        with pytest.raises(ValueError, match="empty"):
            BucketLadder(())
        with pytest.raises(ValueError):
            build_ladder(0)

    def test_parse_buckets(self):
        assert parse_buckets("16,8,32").buckets == (8, 16, 32)
        with pytest.raises(ValueError, match="comma-separated"):
            parse_buckets("8;16")

    def test_sampler_plan_rows(self):
        fn = object()
        rows = sampler_plan(fn, BucketLadder((2, 4), 1), z_dim=7)
        assert [name for name, _, _ in rows] == ["sampler@b2", "sampler@b4"]
        for (_, f, args), b in zip(rows, (2, 4)):
            assert f is fn and args[0].shape == (b, 7)
        rows = sampler_plan(fn, BucketLadder((2,), 1), z_dim=7,
                            state={"s": 1}, num_classes=3)
        _, _, args = rows[0]
        assert args[0] == {"s": 1} and args[1].shape == (2, 7) \
            and args[2].shape == (2,)


class TestBatcher:
    def test_coalesce_snap_and_padding(self):
        """Requests coalesce into one bucket-snapped batch; every request
        gets exactly its own rows back."""
        src = FakeSource()
        s = make_server(src, max_wait_ms=20.0)
        s.start(timeout=10)
        r1 = s.submit(z=np.full((3, 4), 0.5, np.float32))
        r2 = s.submit(z=np.full((2, 4), -0.25, np.float32))
        a, b = r1.result(5), r2.result(5)
        s.stop()
        assert src.calls == [(8, 8)]        # 5 rows -> bucket 8, one batch
        assert a.shape == (3, 2, 2, 1) and b.shape == (2, 2, 2, 1)
        assert np.all(a[:, 0, 0, 0] == 0.5)
        assert np.all(b[:, 0, 0, 0] == -0.25)
        assert r1.meta["buckets"] == [8] and r1.meta["total_ms"] > 0
        rep = s.report()
        assert rep["serve/batches"] == 1 and rep["serve/pad_frac"] == 3 / 8

    def test_deadline_flush_bounds_latency(self):
        """A lone small request must not wait for batchmates past
        max_wait_ms."""
        s = make_server(FakeSource(), max_wait_ms=30.0)
        s.start(timeout=10)
        t0 = time.monotonic()
        r = s.submit(num_images=1)
        r.result(5)
        waited = (time.monotonic() - t0) * 1e3
        s.stop()
        assert 20.0 <= waited < 2000.0      # flushed by deadline, not full
        assert r.meta["buckets"] == [4]     # snapped to the SMALL rung

    def test_full_top_bucket_flushes_immediately(self):
        """Work filling the largest bucket dispatches without waiting for
        the deadline."""
        s = make_server(FakeSource(), max_wait_ms=10_000.0)
        s.start(timeout=10)
        r = s.submit(num_images=8)
        r.result(timeout=5)                 # way under the 10 s deadline
        s.stop()
        assert r.meta["buckets"] == [8]

    def test_oversized_request_chunks_fifo(self):
        """A request past the top rung chunks across dispatches; a later
        arrival never overtakes the earlier request's chunks."""
        block = threading.Event()
        src = FakeSource(block=block)
        s = make_server(src, max_wait_ms=1.0)
        s.start(timeout=10)
        big = s.submit(z=np.full((19, 4), 0.75, np.float32))
        small = s.submit(z=np.full((2, 4), -0.5, np.float32))
        block.set()
        b, sm = big.result(5), small.result(5)
        s.stop()
        assert b.shape[0] == 19 and np.all(b[:, 0, 0, 0] == 0.75)
        assert sm.shape[0] == 2 and np.all(sm[:, 0, 0, 0] == -0.5)
        assert big.meta["buckets"][:2] == [8, 8]  # chunked at the top rung
        # FIFO: the big request's final chunk rides no later than the
        # small request's rows
        assert src.calls[0] == (8, 8) and src.calls[1] == (8, 8)

    def test_drop_oldest_backpressure(self):
        """Queue full -> the OLDEST pending request is shed with
        ServeOverloadError; newest work keeps its place."""
        block = threading.Event()
        src = FakeSource(block=block)
        s = make_server(src, max_queue=2, max_wait_ms=1.0)
        s.start(timeout=10)
        # stall the worker on a first batch so later submits pile up
        first = s.submit(num_images=1)
        time.sleep(0.1)                     # worker is now blocked in sample
        r1 = s.submit(num_images=1)
        r2 = s.submit(num_images=1)
        r3 = s.submit(num_images=1)         # displaces r1
        block.set()
        with pytest.raises(ServeOverloadError):
            r1.result(5)
        assert r2.result(5).shape[0] == 1
        assert r3.result(5).shape[0] == 1
        first.result(5)
        s.stop()
        assert s.dropped == 1
        assert s.counters().serve_dropped == 1

    def test_drain_on_stop_completes_fifo(self):
        """stop(drain=True) finishes every queued request in submit
        order, then the worker exits; post-stop submits are rejected."""
        block = threading.Event()
        src = FakeSource(block=block)
        s = make_server(src, max_wait_ms=10_000.0, max_queue=64)
        s.start(timeout=10)
        resps = [s.submit(z=np.full((2, 4), i / 10, np.float32))
                 for i in range(5)]
        stopper = threading.Thread(target=lambda: s.stop(drain=True))
        stopper.start()
        time.sleep(0.05)
        block.set()
        stopper.join(timeout=10)
        assert not stopper.is_alive()
        order = []
        for i, r in enumerate(resps):
            imgs = r.result(1)
            order.append(float(imgs[0, 0, 0, 0]))
            assert imgs.shape[0] == 2
        assert order == pytest.approx([i / 10 for i in range(5)],
                                      abs=1e-6)     # FIFO held
        late = s.submit(num_images=1)
        with pytest.raises(ServeError, match="stopped"):
            late.result(1)
        assert s.counters().serve_completed == 5

    def test_worker_failure_poisons_server(self):
        class ExplodingSource(FakeSource):
            def sample(self, bucket, z, labels=None):
                raise RuntimeError("device on fire")

        s = make_server(ExplodingSource(), max_wait_ms=1.0)
        s.start(timeout=10)
        r = s.submit(num_images=1)
        with pytest.raises(RuntimeError, match="device on fire"):
            r.result(5)
        with pytest.raises(ServeError, match="failed"):
            s.submit(num_images=1).result(1)
        with pytest.raises(ServeError):
            s.stop()

    def test_conditional_labels_concatenate_and_pad(self):
        src = FakeSource(num_classes=3)
        s = make_server(src, max_wait_ms=20.0)
        s.start(timeout=10)
        r1 = s.submit(num_images=2, labels=np.array([1, 2]))
        r2 = s.submit(num_images=1)          # unlabeled -> class 0
        r1.result(5), r2.result(5)
        s.stop()
        (lbl,) = src.label_rows
        # 3 rows snap to bucket 4: one zero pad row after the coalesced
        # per-request labels (unlabeled requests default to class 0)
        assert lbl.tolist() == [1, 2, 0, 0]

    def test_submit_validation(self):
        s = make_server(FakeSource())
        with pytest.raises(ValueError, match="num_images"):
            s.submit(num_images=0)
        with pytest.raises(ValueError, match="z must be"):
            s.submit(z=np.zeros((4,), np.float32))
        with pytest.raises(ValueError, match="z width"):
            s.submit(z=np.zeros((2, 7), np.float32))   # z_dim is 4
        with pytest.raises(ValueError, match="labels length"):
            s.submit(num_images=3, labels=np.array([1, 2]))
        with pytest.raises(ValueError, match="max_queue"):
            make_server(FakeSource(), max_queue=0)

    def test_bad_width_cold_start_submit_fails_only_itself(self):
        """A wrong-width z submitted during the cold-start window (before
        the source has resolved z_dim) fails ITS response at assembly —
        it must never poison the server for other clients."""
        class ColdSource(FakeSource):
            def __init__(self):
                super().__init__()
                self.z_dim = 0            # unknown until prepare()

            def prepare(self):
                self.z_dim = 4
                return super().prepare()

        s = make_server(ColdSource(), max_wait_ms=5.0)
        bad = s.submit(z=np.zeros((2, 7), np.float32))  # width check skipped
        good = s.submit(num_images=1)
        s.start(timeout=10)
        with pytest.raises(ValueError, match="z width"):
            bad.result(5)
        assert good.result(5).shape[0] == 1   # server still serving
        later = s.submit(num_images=1)
        assert later.result(5).shape[0] == 1
        s.stop()

    def test_drop_oldest_spares_partially_dispatched_request(self):
        """Backpressure must not shed a request whose earlier chunks the
        device already computed — the oldest NEVER-dispatched request is
        the victim; with nothing undispatched, the NEW request is
        rejected instead."""
        class BlockNth(FakeSource):
            """Blocks only the n-th dispatch (the base class's `block`
            stalls EVERY dispatch, which would stop chunk 1 too)."""

            def __init__(self, n):
                super().__init__()
                self.block = threading.Event()
                self.n = n
                self.entered = threading.Event()

            def sample(self, bucket, z, labels=None):
                if len(self.calls) + 1 == self.n:
                    self.entered.set()
                    self.block.wait()
                self.calls.append((bucket, z.shape[0]))
                img = np.zeros((bucket, 2, 2, 1), np.float32)
                img[:, 0, 0, 0] = z[:, 0]
                return img

        src = BlockNth(2)                  # block the SECOND dispatch
        s = make_server(src, max_queue=2, max_wait_ms=1.0)
        s.start(timeout=10)
        big = s.submit(z=np.full((19, 4), 0.5, np.float32))  # chunks 8,8,3
        assert src.entered.wait(5)         # chunk 1 done, chunk 2 in flight
        r2 = s.submit(num_images=1)
        r3 = s.submit(num_images=1)        # queue full: sheds r2, NOT big
        src.block.set()
        assert big.result(5).shape[0] == 19
        assert np.all(big.result(0)[:, 0, 0, 0] == 0.5)
        with pytest.raises(ServeOverloadError):
            r2.result(5)
        assert r3.result(5).shape[0] == 1
        s.stop()
        assert s.dropped == 1

    def test_stop_timeout_raises_instead_of_claiming_clean_drain(self):
        """A drain that outlives the join timeout must raise, never
        return success over a still-running worker."""
        block = threading.Event()
        s = make_server(FakeSource(block=block), max_wait_ms=1.0)
        s.start(timeout=10)
        r = s.submit(num_images=1)
        time.sleep(0.05)                   # worker now blocked in sample
        with pytest.raises(TimeoutError, match="drain did not finish"):
            s.stop(drain=True, timeout=0.2)
        block.set()                        # now the drain can finish
        s.stop(drain=True, timeout=10.0)
        assert r.result(1).shape[0] == 1


@pytest.fixture(scope="module")
def trained_ckpt(tmp_path_factory):
    from dcgan_tpu.config import ModelConfig, TrainConfig
    from dcgan_tpu.train.trainer import train

    root = tmp_path_factory.mktemp("serve")
    cfg = TrainConfig(
        model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                          compute_dtype="float32"),
        batch_size=8,
        checkpoint_dir=str(root / "ckpt"),
        sample_dir=str(root / "samples"),
        sample_every_steps=0, save_summaries_secs=1e9, save_model_secs=1e9,
        log_every_steps=0, tensorboard=False)
    train(cfg, synthetic_data=True, max_steps=1)
    return str(root / "ckpt")


OVERRIDES = {"output_size": 16, "gf_dim": 8, "df_dim": 8}


@pytest.fixture
def _pristine_cache_state():
    """Serve tests point the process-global persistent cache at a tmp dir;
    none of that may leak into later tests (the test_warmup discipline)."""
    import jax

    prev = {
        "jax_compilation_cache_dir": jax.config.jax_compilation_cache_dir,
        "jax_persistent_cache_min_compile_time_secs":
            jax.config.jax_persistent_cache_min_compile_time_secs,
        "jax_persistent_cache_min_entry_size_bytes":
            jax.config.jax_persistent_cache_min_entry_size_bytes,
    }
    yield
    for k, v in prev.items():
        jax.config.update(k, v)
    from jax._src import compilation_cache

    compilation_cache.reset_cache()


class TestServeEndToEnd:
    def test_zero_recompiles_after_bucket_warmup(self, trained_ckpt,
                                                 tmp_path,
                                                 _pristine_cache_state):
        """The acceptance pin: under a live persistent compile cache,
        NO compile request fires after the AOT bucket warmup — every
        served batch (odd sizes included) rides a precompiled bucket
        executable, input-transfer programs primed at cold start."""
        from dcgan_tpu.serve import CheckpointSource

        src = CheckpointSource(trained_ckpt, overrides=OVERRIDES)
        s = SamplerServer(src, max_batch=16,
                          cache_dir=str(tmp_path / "cc"), max_wait_ms=2.0)
        s.start(timeout=300)
        assert s.ladder.buckets == (8, 16)   # granule 8: the test mesh
        for n in (3, 11, 5, 16, 2, 8):
            imgs = s.submit(num_images=n, seed=n).result(timeout=60)
            assert imgs.shape == (n, 16, 16, 3)
        rep = s.report()
        s.stop()
        assert rep["serve/recompiles_after_warmup"] == 0
        assert rep["perf/compile_cache_requests"] > 0  # warmup was real
        assert rep["serve/completed"] == 6
        assert rep["serve/p99_ms"] >= rep["serve/p50_ms"] > 0
        assert set(s.compile_ms) == {"sampler@b8", "sampler@b16"}

    def test_request_response_parity_with_generate(self, trained_ckpt,
                                                   tmp_path):
        """Submitting the exact latent rows generate.py draws for a seed
        returns byte-identical images — serving is the same program, not
        a lookalike."""
        import jax

        from dcgan_tpu.generate import build_parser, generate
        from dcgan_tpu.serve import CheckpointSource

        z = np.asarray(jax.random.uniform(
            jax.random.fold_in(jax.random.key(0), 0), (8, 100),
            minval=-1.0, maxval=1.0))
        s = SamplerServer(CheckpointSource(trained_ckpt,
                                           overrides=OVERRIDES),
                          max_batch=8, max_wait_ms=2.0)
        s.start(timeout=300)
        served = s.submit(z=z).result(timeout=60)
        s.stop()

        args = build_parser().parse_args(
            ["--checkpoint_dir", trained_ckpt,
             "--out_dir", str(tmp_path / "out"),
             "--num_images", "8", "--batch_size", "8", "--grid", "0",
             "--npz", str(tmp_path / "g.npz"), "--seed", "0",
             "--output_size", "16", "--gf_dim", "8", "--df_dim", "8"])
        generate(args)
        gen = np.load(tmp_path / "g.npz")["images"]
        assert served.shape == gen.shape == (8, 16, 16, 3)
        np.testing.assert_array_equal(served, gen)

    def test_requests_accepted_during_cold_start(self, trained_ckpt):
        """Lifecycle: submits queued while the plane is still cold serve
        as soon as it turns warm."""
        from dcgan_tpu.serve import CheckpointSource

        from dcgan_tpu.serve.worker import ServeWorker

        s = SamplerServer(CheckpointSource(trained_ckpt,
                                           overrides=OVERRIDES),
                          max_batch=8, max_wait_ms=2.0)
        # start the worker without blocking on readiness (what start()
        # does minus the wait), then submit while the plane is cold
        s._started = True
        s._worker = ServeWorker(s)
        s._worker.start()
        r = s.submit(num_images=2, seed=1)
        assert s._ready.wait(300)
        imgs = r.result(timeout=60)
        s.stop()
        assert imgs.shape == (2, 16, 16, 3)

    def test_missing_checkpoint_fails_start_loudly(self, tmp_path):
        from dcgan_tpu.serve import CheckpointSource

        s = SamplerServer(CheckpointSource(str(tmp_path / "nope"),
                                           overrides=OVERRIDES),
                          max_batch=8)
        with pytest.raises(ServeError, match="no checkpoint"):
            s.start(timeout=300)
        # queued-in-the-dark submits are rejected, not stranded
        with pytest.raises(ServeError):
            s.submit(num_images=1).result(1)


@pytest.mark.slow
class TestArtifactServing:
    def test_artifact_source_serves_without_checkpoint(self, trained_ckpt,
                                                       tmp_path):
        """Cold start from a .jaxexport artifact + sidecar alone: the
        sidecar's serving block supplies z_dim and the bucket-ladder
        hint, and the served images match the artifact's own call."""
        import jax

        from dcgan_tpu.export import export_sampler, load_sampler
        from dcgan_tpu.serve import ArtifactSource

        out = str(tmp_path / "sampler.jaxexport")
        meta = export_sampler(trained_ckpt, out, overrides=OVERRIDES,
                              platforms=("cpu",), max_serve_batch=8)
        assert meta["serving"]["bucket_ladder"] == [1, 2, 4, 8]
        assert meta["serving"]["source"] == "live"

        src = ArtifactSource(out)
        assert src.ladder_hint() == [1, 2, 4, 8]
        s = SamplerServer(src, max_wait_ms=2.0)
        s.start(timeout=300)
        assert s.ladder.buckets == (1, 2, 4, 8)  # the sidecar hint won
        z = np.random.default_rng(3).uniform(
            -1, 1, (5, 100)).astype(np.float32)
        served = s.submit(z=z).result(timeout=60)
        s.stop()
        direct = np.asarray(load_sampler(out).call(z))
        np.testing.assert_allclose(served, direct, atol=1e-6)
        assert served.shape == (5, 16, 16, 3)

    def test_pinned_batch_artifact_ladder_is_one_rung(self, trained_ckpt,
                                                      tmp_path):
        from dcgan_tpu.export import export_sampler

        out = str(tmp_path / "pinned.jaxexport")
        meta = export_sampler(trained_ckpt, out, overrides=OVERRIDES,
                              platforms=("cpu",), batch_size=4)
        assert meta["serving"]["bucket_ladder"] == [4]


@pytest.mark.slow
class TestGenerateTailBuckets:
    def test_tail_snaps_to_ladder_bucket(self, trained_ckpt, tmp_path):
        """generate.py satellite: --num_images not divisible by
        --batch_size pads the tail to a smaller compiled ladder bucket
        (16 + 8 here), not a second full batch and not a one-off
        shape."""
        from dcgan_tpu.generate import build_parser, generate

        args = build_parser().parse_args(
            ["--checkpoint_dir", trained_ckpt,
             "--out_dir", str(tmp_path / "out"),
             "--num_images", "20", "--batch_size", "16", "--grid", "0",
             "--npz", str(tmp_path / "t.npz"),
             "--output_size", "16", "--gf_dim", "8", "--df_dim", "8"])
        result = generate(args)
        assert result["num_images"] == 20
        imgs = np.load(tmp_path / "t.npz")["images"]
        assert imgs.shape == (20, 16, 16, 3)
