"""Checkpoints carry their config (VERDICT r1 #3, ADVICE r1 cBN finding).

The trainer writes config.json next to the Orbax step dirs;
generate/evals/resume read it back, so checkpoint consumers need zero
architecture flags and a mismatched resume fails with a readable error
instead of an Orbax tree/shape mismatch. (The reference's Saver had the
same silent-mismatch hazard — image_train.py:233-245.)
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from dcgan_tpu.config import (
    CONFIG_FILENAME,
    MeshConfig,
    ModelConfig,
    TrainConfig,
    config_from_dict,
    config_to_dict,
    load_config,
    resolve_model_config,
    save_config,
)
from dcgan_tpu.generate import build_parser, generate
from dcgan_tpu.train.trainer import train


def _tiny_cfg(tmp_path, **model_kw):
    return TrainConfig(
        model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                          compute_dtype="float32", **model_kw),
        batch_size=8,
        checkpoint_dir=str(tmp_path / "ckpt"),
        sample_dir=str(tmp_path / "samples"),
        sample_every_steps=0, save_summaries_secs=1e9, save_model_secs=1e9,
        log_every_steps=0)


class TestSerialization:
    def test_round_trip_non_default(self):
        cfg = TrainConfig(
            model=ModelConfig(output_size=32, gf_dim=16, df_dim=24,
                              num_classes=10, conditional_bn=True,
                              attn_res=8, attn_heads=2, spectral_norm="gd",
                              compute_dtype="float32"),
            mesh=MeshConfig(model=2, shard_opt=True),
            batch_size=32, loss="hinge", r1_gamma=1.0, r1_interval=4,
            sample_grid=(4, 4), g_ema_decay=0.999)
        assert config_from_dict(config_to_dict(cfg)) == cfg

    def test_unknown_keys_warn_not_fail(self, capsys):
        d = config_to_dict(TrainConfig())
        d["model"]["future_knob"] = 7
        d["brand_new_field"] = "x"
        cfg = config_from_dict(d)
        assert cfg == TrainConfig()
        err = capsys.readouterr().err
        assert "future_knob" in err and "brand_new_field" in err

    def test_save_load_file(self, tmp_path):
        cfg = TrainConfig(model=ModelConfig(output_size=32,
                                            compute_dtype="float32"))
        path = save_config(cfg, str(tmp_path))
        assert os.path.basename(path) == CONFIG_FILENAME
        assert load_config(str(tmp_path)) == cfg
        # valid JSON on disk, not a pickle
        with open(path) as f:
            assert json.load(f)["model"]["output_size"] == 32

    def test_load_absent_returns_none(self, tmp_path):
        assert load_config(str(tmp_path)) is None


class TestResolveModelConfig:
    def test_precedence_flag_over_saved(self, tmp_path):
        saved = TrainConfig(model=ModelConfig(output_size=32, gf_dim=16,
                                              compute_dtype="float32"))
        save_config(saved, str(tmp_path))
        m = resolve_model_config(str(tmp_path), overrides={
            "gf_dim": 8, "df_dim": None})  # None = not passed
        assert m.gf_dim == 8            # explicit flag wins
        assert m.output_size == 32      # from config.json
        assert m.df_dim == saved.model.df_dim

    def test_preset_replaces_saved_base(self, tmp_path):
        save_config(TrainConfig(model=ModelConfig(output_size=32,
                                                  compute_dtype="float32")),
                    str(tmp_path))
        m = resolve_model_config(str(tmp_path), preset="celeba64",
                                 overrides={})
        assert m.output_size == 64      # preset, not the saved 32

    def test_no_saved_no_preset_defaults(self, tmp_path):
        assert resolve_model_config(str(tmp_path)) == ModelConfig()


@pytest.mark.slow
class TestTrainerPersistence:
    def test_trainer_writes_and_generate_needs_no_flags(self, tmp_path):
        """The VERDICT's done-criterion: zero architecture flags on a
        non-default-architecture checkpoint."""
        cfg = _tiny_cfg(tmp_path)
        train(cfg, synthetic_data=True, max_steps=1)
        assert load_config(cfg.checkpoint_dir) == cfg

        args = build_parser().parse_args(
            ["--checkpoint_dir", cfg.checkpoint_dir,
             "--out_dir", str(tmp_path / "out"), "--num_images", "8",
             "--batch_size", "8", "--grid", "0",
             "--npz", str(tmp_path / "gen.npz")])
        result = generate(args)
        assert result["num_images"] == 8
        assert np.load(tmp_path / "gen.npz")["images"].shape == (8, 16, 16, 3)

    def test_resume_architecture_mismatch_fails_readably(self, tmp_path):
        cfg = _tiny_cfg(tmp_path)
        train(cfg, synthetic_data=True, max_steps=1)
        bad = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, gf_dim=16))
        with pytest.raises(ValueError, match="gf_dim.*(8, 16)"):
            train(bad, synthetic_data=True, max_steps=1)

    def test_cli_ignores_stale_config_without_checkpoint(self, tmp_path,
                                                         capsys):
        """ADVICE r2: a fresh CLI launch into a directory holding only a
        config.json (dead run, never saved) must NOT adopt it — unpassed
        flags keep their defaults instead of inheriting the dead run's."""
        from dcgan_tpu.train.cli import main as cli_main

        stale = _tiny_cfg(tmp_path, z_dim=77)
        save_config(stale, stale.checkpoint_dir)  # config, no checkpoint

        cli_main(["--checkpoint_dir", stale.checkpoint_dir, "--synthetic",
                  "--max_steps", "1", "--platform", "cpu",
                  "--output_size", "16", "--gf_dim", "8", "--df_dim", "8",
                  "--batch_size", "8",
                  "--sample_every_steps", "0", "--log_every_steps", "0"])
        out = capsys.readouterr().out
        assert "adopted config.json" not in out
        assert "ignoring config.json" in out
        # z_dim was not passed: must be the default, not the stale 77
        assert load_config(stale.checkpoint_dir).model.z_dim == \
            ModelConfig().z_dim

    def test_has_restorable_checkpoint(self, tmp_path):
        from dcgan_tpu.utils.checkpoint import has_restorable_checkpoint

        assert not has_restorable_checkpoint(str(tmp_path / "absent"))
        d = tmp_path / "ckpt"
        d.mkdir()
        assert not has_restorable_checkpoint(str(d))
        (d / "best").mkdir()           # retention subdir, not a step
        (d / "7.orbax-checkpoint-tmp-123").mkdir()  # in-flight temp
        assert not has_restorable_checkpoint(str(d))
        (d / "7").mkdir()              # completed step dir
        assert has_restorable_checkpoint(str(d))

    def test_cli_resume_adopts_config_zero_flags(self, tmp_path, capsys):
        """`dcgan_tpu.train --checkpoint_dir ckpt` with NO architecture
        flags resumes a non-default-architecture run: the CLI adopts the
        stored config.json (explicit flags would override)."""
        from dcgan_tpu.train.cli import main as cli_main

        cfg = _tiny_cfg(tmp_path)
        train(cfg, synthetic_data=True, max_steps=1)

        cli_main(["--checkpoint_dir", cfg.checkpoint_dir, "--synthetic",
                  "--max_steps", "2", "--platform", "cpu"])
        out = capsys.readouterr().out
        assert "adopted config.json" in out
        from dcgan_tpu.utils.checkpoint import Checkpointer
        assert Checkpointer(cfg.checkpoint_dir).latest_step() == 2

    def test_stale_config_without_checkpoint_not_binding(self, tmp_path):
        """A config.json left by a run that died before its first save must
        not claim the directory — a fresh run with a different architecture
        proceeds and overwrites it."""
        cfg = _tiny_cfg(tmp_path)
        save_config(cfg, cfg.checkpoint_dir)  # config written, no checkpoint
        fresh = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, gf_dim=16))
        train(fresh, synthetic_data=True, max_steps=1)
        assert load_config(cfg.checkpoint_dir) == fresh

    def test_resume_same_architecture_proceeds(self, tmp_path):
        cfg = _tiny_cfg(tmp_path)
        train(cfg, synthetic_data=True, max_steps=1)
        # run knobs may change between runs; only architecture is pinned
        resumed = dataclasses.replace(cfg, learning_rate=1e-4)
        state = train(resumed, synthetic_data=True, max_steps=2)
        assert int(np.asarray(state["step"])) == 2

    def test_conditional_bn_round_trip(self, tmp_path):
        """ADVICE r1 (medium): a cBN checkpoint must be samplable — its
        [K, C] BN tables restore only if the consumer reconstructs cBN."""
        cfg = _tiny_cfg(tmp_path, num_classes=4, conditional_bn=True)
        train(cfg, synthetic_data=True, max_steps=1)

        args = build_parser().parse_args(
            ["--checkpoint_dir", cfg.checkpoint_dir,
             "--out_dir", str(tmp_path / "out"), "--num_images", "8",
             "--batch_size", "8", "--grid", "0",
             "--npz", str(tmp_path / "gen.npz"), "--class_id", "1"])
        result = generate(args)
        assert result["num_images"] == 8
        assert (np.load(tmp_path / "gen.npz")["labels"] == 1).all()
