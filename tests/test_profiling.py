"""Profiling subsystem: StepTimer math, TraceCapture lifecycle, and the
trainer wiring (SURVEY.md §5 — the tracing/profiling channel the reference
lacks entirely)."""

import pytest

import glob
import json

import jax

from dcgan_tpu.utils.profiling import StepTimer, TraceCapture


class TestStepTimer:
    def test_empty_until_two_ticks(self):
        t = StepTimer()
        assert t.summary() == {}
        t.tick(now=1.0)
        assert t.summary() == {}
        t.tick(now=1.5)
        assert len(t) == 1

    def test_stats(self):
        t = StepTimer(window=10, images_per_step=64)
        for now in [0.0, 0.1, 0.2, 0.3, 0.4]:  # 4 steps of 100ms
            t.tick(now=now)
        s = t.summary()
        assert abs(s["perf/step_ms_mean"] - 100.0) < 1e-6
        assert abs(s["perf/step_ms_p50"] - 100.0) < 1e-6
        assert abs(s["perf/steps_per_sec"] - 10.0) < 1e-6
        assert abs(s["perf/images_per_sec"] - 640.0) < 1e-6

    def test_window_slides(self):
        t = StepTimer(window=2)
        t.tick(now=0.0)
        t.tick(now=10.0)   # slow step, should age out
        t.tick(now=10.1)
        t.tick(now=10.2)
        assert abs(t.summary()["perf/step_ms_max"] - 100.0) < 1e-3

    def test_last_step_accessors(self):
        """The flight recorder reads the most recent per-step numbers
        without forcing a summary() sort."""
        t = StepTimer(window=4)
        assert t.last_step_ms is None and t.last_host_ms is None
        t.tick(now=0.0)
        t.note_host(0.02)
        t.tick(now=0.1)
        assert abs(t.last_step_ms - 100.0) < 1e-6
        assert abs(t.last_host_ms - 20.0) < 1e-6

    def test_p90_on_skewed_window(self):
        t = StepTimer(window=20)
        now = 0.0
        t.tick(now=now)
        for _ in range(19):
            now += 0.010
            t.tick(now=now)
        now += 1.0  # one straggler
        t.tick(now=now)
        s = t.summary()
        assert s["perf/step_ms_p50"] < 20.0
        assert s["perf/step_ms_max"] > 900.0


class TestTraceCapture:
    def test_disabled_when_no_logdir(self):
        tc = TraceCapture("", start_step=0, num_steps=5)
        tc.maybe_start(0)
        assert not tc.active
        tc.maybe_stop(10)  # no-op, must not raise

    def test_capture_window(self, tmp_path):
        logdir = str(tmp_path / "trace")
        tc = TraceCapture(logdir, start_step=2, num_steps=2)
        f = jax.jit(lambda x: x * 2.0)

        for step in range(5):
            tc.maybe_start(step)
            y = f(jax.numpy.ones((8,)))
            if step < 2:
                assert not tc.active
            tc.maybe_stop(step + 1)
        y.block_until_ready()
        assert tc.captures == 1 and not tc.active
        # profiler wrote its event files under the logdir
        assert glob.glob(logdir + "/**/*", recursive=True)

    def test_close_stops_open_trace(self, tmp_path):
        tc = TraceCapture(str(tmp_path / "t"), start_step=0, num_steps=100)
        tc.maybe_start(0)
        assert tc.active
        tc.close()
        assert not tc.active

    def test_trigger_file_starts_and_stops_capture(self, tmp_path):
        """ISSUE 6 on-demand tracing: touch -> capture the next N steps;
        the file is consumed as the ack, on_capture fires with the stop
        step, and a SECOND touch starts a second capture."""
        trig = tmp_path / "trigger"
        logdir = str(tmp_path / "trace")
        captured = []
        tc = TraceCapture(logdir, num_steps=2, schedule=False,
                          trigger_path=str(trig),
                          on_capture=captured.append)
        f = jax.jit(lambda x: x + 1.0)

        y = None
        for step in range(8):
            if step == 2:
                trig.touch()
            tc.maybe_start(step)
            if step < 2:
                assert not tc.active  # untouched: never starts
            y = f(jax.numpy.ones((4,)))
            tc.maybe_stop(step + 1, sync=y)
        assert captured == [4]            # started at 2, N=2 -> stop at 4
        assert not trig.exists()          # consumed as the ack
        assert tc.captures == 1
        assert glob.glob(logdir + "/**/*.trace.json.gz", recursive=True)

        trig.touch()                      # second touch, second capture
        tc.maybe_start(8)
        assert tc.active
        tc.maybe_stop(10, sync=y)
        assert captured == [4, 10] and tc.captures == 2

    def test_no_schedule_means_trigger_only(self, tmp_path):
        """schedule=False (the trainer's trigger-only mode) must never arm
        the scheduled start_step window."""
        tc = TraceCapture(str(tmp_path / "t"), start_step=0, num_steps=5,
                          schedule=False, trigger_path=str(tmp_path / "x"))
        tc.maybe_start(100)
        assert not tc.active

    def test_trigger_without_logdir_is_inert(self, tmp_path):
        trig = tmp_path / "trigger"
        trig.touch()
        tc = TraceCapture("", trigger_path=str(trig))
        tc.maybe_start(0)
        assert not tc.active and trig.exists()  # never consumed

    def test_nonconsuming_process_captures_by_mtime(self, tmp_path):
        """Multi-process shared-FS semantics: consume=False (non-chief)
        captures on a NEW mtime and does NOT delete the file — and one
        mtime serves exactly one capture even if the chief's removal is
        delayed, so losing the remove race can no longer starve the
        digesting process (review fix)."""
        import os
        import time as time_mod

        trig = tmp_path / "trigger"
        trig.touch()
        captured = []
        tc = TraceCapture(str(tmp_path / "tr"), num_steps=1,
                          schedule=False, trigger_path=str(trig),
                          consume=False, on_capture=captured.append)
        f = jax.jit(lambda x: x - 1.0)
        tc.maybe_start(0)
        assert tc.active and trig.exists()      # captured, NOT consumed
        tc.maybe_stop(1, sync=f(jax.numpy.ones(2)))
        tc.maybe_start(2)
        assert not tc.active                    # same mtime: already served
        time_mod.sleep(0.01)
        os.utime(trig)                          # a fresh touch re-arms
        tc.maybe_start(3)
        assert tc.active
        tc.close()
        assert captured == [1]


class TestTrainerWiring:
    @pytest.mark.slow
    def test_trainer_emits_perf_scalars_and_trace(self, tmp_path):
        from dcgan_tpu.config import ModelConfig, TrainConfig
        from dcgan_tpu.train.trainer import train

        cfg = TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32"),
            batch_size=8,
            checkpoint_dir=str(tmp_path / "ckpt"),
            sample_dir=str(tmp_path / "samples"),
            sample_every_steps=0,
            save_summaries_secs=0.0,
            save_model_secs=1e9,
            log_every_steps=1,
            profile_dir=str(tmp_path / "trace"),
            profile_start_step=1,
            profile_num_steps=2)
        train(cfg, synthetic_data=True, max_steps=5)

        events = [json.loads(l) for l in
                  open(tmp_path / "ckpt" / "events.jsonl").read().splitlines()]
        perf_keys = [k for e in events if e["kind"] == "scalars"
                     for k in e["values"] if k.startswith("perf/")]
        assert "perf/images_per_sec" in perf_keys
        assert glob.glob(str(tmp_path / "trace") + "/**/*", recursive=True)
