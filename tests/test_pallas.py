"""Pallas kernels (ops/pallas_kernels.py) — interpret-mode execution on the
CPU test mesh: forward parity against the jnp reference path, custom-VJP
gradients against autodiff, and full-model integration via
ModelConfig.use_pallas."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # see pytest.ini: excluded from the smoke tier

from dcgan_tpu.ops.pallas_kernels import (
    _row_tile,
    channel_moments,
    fused_bn_act,
    scale_shift_act,
)


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.key(key), shape, dtype)


class TestRowTile:
    def test_divides(self):
        for n in [1, 7, 64, 96, 256, 300, 4096, 100000]:
            t = _row_tile(n)
            assert n % t == 0 and 1 <= t <= 256

    def test_power_of_two_hits_256(self):
        assert _row_tile(4096) == 256


class TestChannelMoments:
    def test_matches_jnp(self):
        x = _rand(0, (96, 16))
        mean, msq = channel_moments(x)
        np.testing.assert_allclose(mean, jnp.mean(x, axis=0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(msq, jnp.mean(x * x, axis=0),
                                   rtol=1e-5, atol=1e-6)

    def test_bf16_input_f32_stats(self):
        x = _rand(1, (64, 8), jnp.bfloat16)
        mean, msq = channel_moments(x)
        assert mean.dtype == jnp.float32 and msq.dtype == jnp.float32
        np.testing.assert_allclose(
            mean, jnp.mean(x.astype(jnp.float32), axis=0), atol=1e-2)

    def test_grad_matches_autodiff(self):
        x = _rand(2, (32, 8))

        def via_kernel(x):
            m, s = channel_moments(x)
            return jnp.sum(m * 2.0 - s * 0.5)

        def via_jnp(x):
            return jnp.sum(jnp.mean(x, axis=0) * 2.0
                           - jnp.mean(x * x, axis=0) * 0.5)

        np.testing.assert_allclose(jax.grad(via_kernel)(x),
                                   jax.grad(via_jnp)(x), rtol=1e-5, atol=1e-6)


class TestScaleShiftAct:
    @pytest.mark.parametrize("act", ["none", "relu", "lrelu", "tanh"])
    def test_forward_parity(self, act):
        x = _rand(3, (96, 16))
        scale = _rand(4, (16,)) * 0.5 + 1.0
        shift = _rand(5, (16,)) * 0.1
        got = scale_shift_act(x, scale, shift, act)
        u = x * scale[None, :] + shift[None, :]
        want = {"none": u, "relu": jax.nn.relu(u),
                "lrelu": jnp.maximum(u, 0.2 * u), "tanh": jnp.tanh(u)}[act]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("act", ["relu", "lrelu", "tanh"])
    def test_vjp_matches_autodiff(self, act):
        x = _rand(6, (64, 8))
        scale = _rand(7, (8,)) * 0.5 + 1.0
        shift = _rand(8, (8,)) * 0.1
        g = _rand(9, (64, 8))

        def ref(x, scale, shift):
            u = x * scale[None, :] + shift[None, :]
            return {"relu": jax.nn.relu(u),
                    "lrelu": jnp.maximum(u, 0.2 * u),
                    "tanh": jnp.tanh(u)}[act]

        def loss_k(x, s, b):
            return jnp.sum(scale_shift_act(x, s, b, act) * g)

        def loss_r(x, s, b):
            return jnp.sum(ref(x, s, b) * g)

        gk = jax.grad(loss_k, argnums=(0, 1, 2))(x, scale, shift)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, scale, shift)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_custom_leak(self):
        x = jnp.array([[-1.0, 2.0]])
        y = scale_shift_act(x, jnp.ones(2), jnp.zeros(2), "lrelu", 0.5)
        np.testing.assert_allclose(y, [[-0.5, 2.0]], atol=1e-6)

    def test_bad_act_rejected(self):
        with pytest.raises(ValueError):
            scale_shift_act(jnp.ones((4, 4)), jnp.ones(4), jnp.zeros(4),
                            "gelu")

    def test_bad_act_rejected_under_grad(self):
        # Differentiation bypasses the primal wrapper (custom_vjp routes
        # through the vjp-fwd rule), so validation must live in the shared
        # impl or a typo'd act silently becomes identity.
        def loss(x):
            return jnp.sum(scale_shift_act(x, jnp.ones(4), jnp.zeros(4),
                                           "gelu"))
        with pytest.raises(ValueError):
            jax.grad(loss)(jnp.ones((4, 4)))

    def test_under_jit(self):
        x = _rand(10, (32, 8))
        f = jax.jit(lambda x: scale_shift_act(x, jnp.ones(8), jnp.zeros(8),
                                              "relu"))
        np.testing.assert_allclose(f(x), jax.nn.relu(x), rtol=1e-5, atol=1e-6)


class TestFusedBnAct:
    @pytest.mark.parametrize("train", [True, False])
    def test_matches_unfused_batch_norm(self, train):
        from dcgan_tpu.ops.norm import batch_norm_apply, batch_norm_init

        params, state = batch_norm_init(jax.random.key(0), 8)
        state = {"mean": _rand(11, (8,)) * 0.1,
                 "var": jnp.abs(_rand(12, (8,))) + 0.5}
        x = _rand(13, (4, 6, 6, 8))

        y_ref, st_ref = batch_norm_apply(params, state, x, train=train,
                                         act="lrelu")
        y_pal, st_pal = batch_norm_apply(params, state, x, train=train,
                                         act="lrelu", use_pallas=True)
        np.testing.assert_allclose(y_pal, y_ref, rtol=1e-4, atol=1e-5)
        for k in st_ref:
            np.testing.assert_allclose(st_pal[k], st_ref[k],
                                       rtol=1e-5, atol=1e-6)

    def test_gradients_flow_through_moments(self):
        """BN train-mode grads couple every element through mean/var; the
        pallas path must reproduce autodiff through that coupling."""
        from dcgan_tpu.ops.norm import batch_norm_apply, batch_norm_init

        params, state = batch_norm_init(jax.random.key(0), 4)
        x = _rand(14, (8, 4, 4, 4))

        def loss(x, use_pallas):
            y, _ = batch_norm_apply(params, state, x, train=True,
                                    act="relu", use_pallas=use_pallas)
            return jnp.sum(y * y)

        g_ref = jax.grad(lambda x: loss(x, False))(x)
        g_pal = jax.grad(lambda x: loss(x, True))(x)
        np.testing.assert_allclose(g_pal, g_ref, rtol=1e-4, atol=1e-4)

    def test_direct_fused_bn_act(self):
        x = _rand(15, (16, 8))
        gamma, beta = jnp.ones(8), jnp.zeros(8)
        mean, msq = channel_moments(x)
        var = msq - mean * mean
        y = fused_bn_act(x, gamma, beta, mean, var, eps=1e-5, act="none")
        # normalized output: ~zero mean, ~unit variance per channel
        np.testing.assert_allclose(jnp.mean(y, axis=0), jnp.zeros(8),
                                   atol=1e-5)
        np.testing.assert_allclose(jnp.var(y, axis=0), jnp.ones(8), atol=1e-2)


class TestModelIntegration:
    def test_train_step_parity_with_and_without_pallas(self):
        """One full D+G step, identical inputs: the fused kernels must not
        change the training math (float32 compute for bitwise-comparable
        tolerances)."""
        from dcgan_tpu.config import ModelConfig, TrainConfig
        from dcgan_tpu.train.steps import make_train_step

        def run(use_pallas):
            cfg = TrainConfig(
                model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                                  compute_dtype="float32",
                                  use_pallas=use_pallas),
                batch_size=8)
            fns = make_train_step(cfg)
            state = fns.init(jax.random.key(0))
            images = jnp.asarray(np.random.default_rng(0).uniform(
                -1, 1, size=(8, 16, 16, 3)).astype(np.float32))
            state, metrics = jax.jit(fns.train_step)(
                state, images, jax.random.key(1))
            return metrics

        m_ref = run(False)
        m_pal = run(True)
        for k in m_ref:
            np.testing.assert_allclose(float(m_pal[k]), float(m_ref[k]),
                                       rtol=1e-3, atol=1e-4)



class TestGspmdComposition:
    """use_pallas composes with the gspmd dp8 mesh (VERDICT r1 #5): the
    fused BN kernels run per data-shard inside a shard_map nested in the
    jitted step, and the sharded step stays numerically equivalent to the
    single-device one."""

    def test_dp8_step_matches_single_device(self):
        import dataclasses

        from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
        from dcgan_tpu.parallel import make_parallel_train
        from dcgan_tpu.train import make_train_step

        tiny = ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                           compute_dtype="float32", use_pallas=True)
        cfg = TrainConfig(model=tiny, batch_size=16, mesh=MeshConfig())
        rng = np.random.default_rng(0)
        xs = jnp.asarray(np.tanh(rng.normal(size=(16, 16, 16, 3)))
                         .astype(np.float32))
        key = jax.random.key(3)

        # single-device reference WITHOUT pallas sharding (plain kernels)
        ref_cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            tiny, use_pallas=False))
        fns = make_train_step(ref_cfg)
        s_ref, m_ref = jax.jit(fns.train_step)(fns.init(jax.random.key(0)),
                                               xs, key)

        pt = make_parallel_train(cfg)
        s_par, m_par = pt.step(pt.init(jax.random.key(0)), xs, key)

        np.testing.assert_allclose(float(m_par["d_loss"]),
                                   float(m_ref["d_loss"]), rtol=1e-4)
        np.testing.assert_allclose(float(m_par["g_loss"]),
                                   float(m_ref["g_loss"]), rtol=1e-4)
        # same Adam-sign-flip bound as test_parallel's equivalence cases
        diff = jax.tree_util.tree_map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            jax.device_get(s_ref["params"]), jax.device_get(s_par["params"]))
        assert max(jax.tree_util.tree_leaves(diff)) \
            <= 2 * cfg.learning_rate + 1e-5

        # sample path (inference-mode fused epilogue) runs sharded too
        z = jnp.asarray(rng.uniform(-1, 1, (16, tiny.z_dim)), jnp.float32)
        imgs = jax.device_get(pt.sample(s_par, z))
        assert imgs.shape == (16, 16, 16, 3)
        assert np.isfinite(imgs).all()

    def test_model_axis_still_rejected(self):
        from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
        from dcgan_tpu.parallel import make_parallel_train

        cfg = TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32", use_pallas=True),
            batch_size=16, mesh=MeshConfig(model=2))
        with pytest.raises(ValueError, match="data-parallel meshes only"):
            make_parallel_train(cfg)

    def test_attn_combo_composes_on_dp_mesh(self):
        """use_pallas + attn_res on a multi-device gspmd DP mesh was
        rejected through r4; since r5 the flash kernels run per data-shard
        through attn_apply's pallas_mesh nested shard_map (the rev-2
        attention presets' execution form), so construction must succeed.
        Numerical equivalence against the single-device step is pinned by
        tests/test_parallel.py::test_sharded_step_matches_single_device
        [dp8-flash]."""
        from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
        from dcgan_tpu.parallel import make_parallel_train

        cfg = TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32", use_pallas=True,
                              attn_res=8),
            batch_size=16, mesh=MeshConfig())
        pt = make_parallel_train(cfg)
        assert pt is not None
