"""True multi-process distributed training over localhost DCN.

The reference's multi-node story was hand-launched ps/worker processes over
gRPC (SURVEY.md §2.5). Here two real OS processes, each owning 4 virtual CPU
devices, form one 8-device job via jax.distributed.initialize and run the
actual trainer: sharded step over the global mesh, process-0-only metrics and
sample grids, collective Orbax checkpoint at exit. This is the closest a
single machine gets to exercising the multi-host path for real — everything
(coordination service, cross-process GSPMD, make_array_from_process_local_data,
chief gating, collective save) is the code multi-host TPU runs.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # see pytest.ini: excluded from the smoke tier

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    from dcgan_tpu.testing.multihost import free_port

    return free_port()


def _run_job(tmp_path, backend: str, *, fid: bool = False,
             steps_per_call: int = 1, spatial: int = 0,
             nproc: int = 2, local_devices: int = 4,
             use_pallas: bool = False, nan: str = "",
             timeout: float = 600) -> None:
    """spatial: size of the spatial ("model") mesh axis (0 = pure DP);
    nproc x local_devices virtual CPU devices form the global mesh, so
    spatial > local_devices forces ring hops across process boundaries."""
    port = _free_port()
    procs = []
    for pid in range(nproc):
        env = dict(os.environ)
        env.pop("JAX_COORDINATOR_ADDRESS", None)
        env.update({
            "MH_COORD": f"127.0.0.1:{port}",
            "MH_NPROC": str(nproc),
            "MH_PID": str(pid),
            "MH_DIR": str(tmp_path),
            "MH_BACKEND": backend,
            "MH_FID": "1" if fid else "0",
            "MH_SPC": str(steps_per_call),
            "MH_SPATIAL": str(spatial),
            "MH_PALLAS": "1" if use_pallas else "0",
            "MH_NAN": nan,
            "MH_LOCAL_DEVICES": str(local_devices),
            "PYTHONPATH": _REPO,
        })
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER], env=env, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
    for pid, out in enumerate(outs):
        assert f"MH_OK pid={pid} step=4" in out, out[-2000:]

    # chief-only observability artifacts
    ckpt_dir = tmp_path / "ckpt"
    assert (ckpt_dir / "events.jsonl").exists()
    assert [f for f in os.listdir(ckpt_dir) if "tfevents" in f]
    assert any(f.endswith(".png") for f in os.listdir(tmp_path / "samples"))
    # collective final checkpoint at step 4 restorable-on-disk
    assert (ckpt_dir / "4").exists()


def test_two_process_gspmd(tmp_path):
    _run_job(tmp_path, "gspmd")


def test_two_process_scanned_dispatch(tmp_path):
    """steps_per_call=2 under a real 2-process job: the scanned multi_step
    program compiles and executes over the cross-process mesh, the
    pre-staged synthetic device pool feeds it through
    make_array_from_process_local_data on every process, and the
    K-aligned cadences (log/sample) fire on schedule."""
    _run_job(tmp_path, "gspmd", steps_per_call=2)


def test_two_process_fid_probe_and_best_retention(tmp_path):
    """Distributed in-training FID probe (VERDICT r2 #5): the budget splits
    per process through the evals rig's distributed path, the gathered
    score is identical on every process, eval/fid scalars land in the
    chief's events, and checkpoint_dir/best holds the best-scoring
    snapshot + score.json — under a real 2-process job."""
    import json

    _run_job(tmp_path, "gspmd", fid=True)

    ckpt_dir = tmp_path / "ckpt"
    # chief wrote eval/fid + eval/kid scalars for the probe steps (2, 4)
    events = [json.loads(l) for l in
              (ckpt_dir / "events.jsonl").read_text().splitlines()]
    fid_events = [e for e in events if "eval/fid" in e.get("values", {})]
    fid_steps = sorted(e["step"] for e in fid_events)
    assert fid_steps, [e.get("values") for e in events[:8]]
    assert set(fid_steps) <= {2, 4}
    # best-checkpoint retention under multihost: a step dir + score record
    best = ckpt_dir / "best"
    assert (best / "score.json").exists()
    score = json.loads((best / "score.json").read_text())
    assert (best / str(score["step"])).exists()
    assert (best / "config.json").exists()
    # the retained score matches one of the probed eval/fid values
    probed = {round(e["values"]["eval/fid"], 6) for e in fid_events}
    assert round(score["fid"], 6) in probed


def test_two_process_spatial_ring(tmp_path):
    """The distributed long-context path for real: image height sharded over
    the 2-way "model" axis, ring attention's ppermute k/v hops and the
    data-axis gradient psums both running under one 2-OS-process
    jax.distributed job — the multi-host form of the sequence parallelism
    the dryrun zoo proves single-process (__graft_entry__.py)."""
    _run_job(tmp_path, "gspmd", spatial=2)


def test_four_process_ring_flash_multihop(tmp_path):
    """A ring that actually rings, across real process boundaries
    (VERDICT r4 #3b): four OS processes x two virtual devices form an
    8-device job with a 4-way spatial ("model") axis — the sequence axis
    spans ALL FOUR processes, so the forward's 3-hop ppermute rotation and
    the flash backward's full 4-rotation grad-homing cycle
    (ops/pallas_attention.py::_ring_flash_vjp_bwd — (dk, dv) riding the
    ring back to their blocks' home devices) each cross real DCN process
    boundaries on every scan iteration, not just once. use_pallas routes
    the per-hop fold through the flash kernels (ring x flash,
    ops/attention.py), the composition the 2-process test and the dryrun
    zoo cover only at one hop / single-process."""
    # ~480 s measured on an idle single-core host; the shared host swings
    # ~2x under concurrent harvests, so the margin is deliberate
    _run_job(tmp_path, "gspmd", spatial=4, nproc=4, local_devices=2,
             use_pallas=True, timeout=1500)


def test_two_process_rollback_parity_ab(tmp_path):
    """ISSUE 4 acceptance: a rollback-ARMED no-fault multi-host run (per-
    step gate consensus, device-resident snapshots refreshed every 2
    steps) emits JSONL metric VALUES identical to nan_policy='abort' —
    the whole coordination layer reads state, never perturbs it."""
    import json

    def run(name, nan):
        root = tmp_path / name
        root.mkdir()
        _run_job(root, "gspmd", nan=nan)
        rows = {}
        for line in (root / "ckpt" / "events.jsonl").read_text() \
                .splitlines():
            e = json.loads(line)
            if e["kind"] == "scalars":
                rows[e["step"]] = {k: v for k, v in e["values"].items()
                                   if not k.startswith("perf/")}
        return rows

    a = run("abort", "abort")
    b = run("rollback", "rollback")
    assert a and a == b


@pytest.mark.skipif(os.environ.get("DCGAN_TPU_FULL_MH") != "1",
                    reason="second 2-process compile round is slow; set "
                           "DCGAN_TPU_FULL_MH=1 to run the shard_map job too")
def test_two_process_shard_map(tmp_path):
    _run_job(tmp_path, "shard_map")
