"""Input-pipeline tests: TFRecord framing + CRC, Example codec, native C++
loader vs pure-Python loader, sharded device delivery
(reference behavior: image_input.py; SURVEY.md §2.2)."""

import os

import jax
import numpy as np
import pytest

from dcgan_tpu.data import tfrecord
from dcgan_tpu.data.example_proto import parse_example, serialize_example
from dcgan_tpu.data.pipeline import (
    DataConfig,
    PythonLoader,
    list_shards,
    make_dataset,
    shard_for_process,
)
from dcgan_tpu.data.synthetic import synthetic_batches, write_image_tfrecords


class TestTFRecord:
    def test_crc32c_known_vectors(self):
        # public CRC32C test vectors
        assert tfrecord.crc32c(b"") == 0
        assert tfrecord.crc32c(b"123456789") == 0xE3069283
        assert tfrecord.crc32c(b"\x00" * 32) == 0x8A9136AA

    def test_roundtrip_with_crc(self, tmp_path):
        path = str(tmp_path / "x.tfrecord")
        recs = [b"alpha", b"", b"\x00\xff" * 100]
        assert tfrecord.write_tfrecords(path, recs) == 3
        out = list(tfrecord.read_tfrecords(path, verify_crc=True))
        assert out == recs

    def test_corruption_detected(self, tmp_path):
        path = str(tmp_path / "x.tfrecord")
        tfrecord.write_tfrecords(path, [b"payload-payload"])
        raw = bytearray(open(path, "rb").read())
        raw[14] ^= 0xFF  # flip a data byte
        open(path, "wb").write(bytes(raw))
        with pytest.raises(IOError):
            list(tfrecord.read_tfrecords(path, verify_crc=True))

    def test_missing_file_raises(self):
        with pytest.raises(FileNotFoundError):
            list(tfrecord.read_tfrecords("/nonexistent/shard"))


class TestExampleProto:
    def test_bytes_roundtrip(self):
        msg = serialize_example({"image_raw": [b"\x01\x02\x03"]})
        assert parse_example(msg) == {"image_raw": [b"\x01\x02\x03"]}

    def test_mixed_features(self):
        msg = serialize_example({
            "image_raw": [b"pixels"],
            "label": [3],
            "scores": [0.5, -1.5],
        })
        out = parse_example(msg)
        assert out["image_raw"] == [b"pixels"]
        assert out["label"] == [3]
        np.testing.assert_allclose(out["scores"], [0.5, -1.5])

    def test_cross_check_against_tensorflow(self):
        """Our codec must interoperate with the real tf.train.Example."""
        tf = pytest.importorskip("tensorflow")
        feats = {"image_raw": [b"\x00" * 16], "label": [7]}
        ours = serialize_example(feats)
        theirs = tf.train.Example()
        theirs.ParseFromString(ours)
        assert theirs.features.feature["image_raw"].bytes_list.value[0] \
            == b"\x00" * 16
        assert theirs.features.feature["label"].int64_list.value[0] == 7
        # and the reverse: parse TF's serialization with our parser
        assert parse_example(theirs.SerializeToString()) == feats


def _write_dataset(tmp_path, n=48, size=8, dtype="float64", shards=3):
    return write_image_tfrecords(
        str(tmp_path / "data"), num_examples=n, image_size=size,
        channels=3, num_shards=shards, record_dtype=dtype)


LOADER_KW = dict(batch=16, example_shape=(8, 8, 3), min_after_dequeue=8,
                 n_threads=3, seed=0, normalize=True, loop=True)


class TestLoaders:
    @pytest.mark.parametrize("dtype", ["float64", "float32", "uint8"])
    def test_native_loader_batches(self, tmp_path, dtype):
        native = pytest.importorskip("dcgan_tpu.data.native")
        paths = _write_dataset(tmp_path, dtype=dtype)
        with native.NativeLoader(paths, record_dtype=dtype,
                                 **LOADER_KW) as ld:
            for _ in range(5):
                b = ld.next()
                assert b.shape == (16, 8, 8, 3) and b.dtype == np.float32
                assert -1.0 <= b.min() and b.max() <= 1.0
                assert b.std() > 0.1  # actually data, not zeros

    def test_native_missing_feature_error(self, tmp_path):
        native = pytest.importorskip("dcgan_tpu.data.native")
        path = str(tmp_path / "bad.tfrecord")
        tfrecord.write_tfrecords(
            path, [serialize_example({"other": [b"\x00" * 8]})])
        with native.NativeLoader([path], **LOADER_KW) as ld:
            with pytest.raises(native.NativeLoaderError,
                               match="image_raw"):
                ld.next()

    def test_native_crc_error(self, tmp_path):
        native = pytest.importorskip("dcgan_tpu.data.native")
        paths = _write_dataset(tmp_path, n=4, shards=1)
        raw = bytearray(open(paths[0], "rb").read())
        raw[40] ^= 0xFF
        open(paths[0], "wb").write(bytes(raw))
        with native.NativeLoader(paths, **LOADER_KW) as ld:
            with pytest.raises(native.NativeLoaderError, match="CRC"):
                ld.next()

    def test_python_loader_matches_semantics(self, tmp_path):
        paths = _write_dataset(tmp_path)
        ld = PythonLoader(paths, record_dtype="float64", **LOADER_KW)
        b = ld.next()
        assert b.shape == (16, 8, 8, 3)
        assert -1.0 <= b.min() and b.max() <= 1.0
        ld.close()

    def test_no_normalize_keeps_raw_scale(self, tmp_path):
        """normalize=False reproduces the reference's raw-pixel feed
        (SURVEY.md §2.4 #1)."""
        paths = _write_dataset(tmp_path)
        kw = dict(LOADER_KW, normalize=False)
        ld = PythonLoader(paths, record_dtype="float64", **kw)
        b = ld.next()
        assert b.max() > 10.0  # raw [0,255] scale
        ld.close()

    def test_one_epoch_mode(self, tmp_path):
        paths = _write_dataset(tmp_path, n=40)
        kw = dict(LOADER_KW, loop=False)
        ld = PythonLoader(paths, record_dtype="float64", **kw)
        batches = list(ld)
        assert len(batches) == 2  # 40 examples -> 2 full batches of 16
        ld.close()


class TestPipeline:
    def test_shard_for_process(self):
        paths = [f"s{i}" for i in range(5)]
        assert shard_for_process(paths, 0, 2) == ["s0", "s2", "s4"]
        assert shard_for_process(paths, 1, 2) == ["s1", "s3"]
        # fewer shards than processes: everyone reads everything
        assert shard_for_process(["s0"], 3, 8) == ["s0"]

    def test_list_shards_empty_dir(self, tmp_path):
        os.makedirs(tmp_path / "empty", exist_ok=True)
        with pytest.raises(FileNotFoundError):
            list_shards(str(tmp_path / "empty"))

    def test_make_dataset_sharded_delivery(self, tmp_path):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from dcgan_tpu.parallel import make_mesh
        _write_dataset(tmp_path)
        cfg = DataConfig(data_dir=str(tmp_path / "data"), image_size=8,
                         batch_size=16, min_after_dequeue=8, n_threads=2,
                         use_native=True)
        mesh = make_mesh()
        sh = NamedSharding(mesh, P("data", None, None, None))
        it = make_dataset(cfg, sh)
        b = next(it)
        assert b.shape == (16, 8, 8, 3)
        assert b.sharding == sh
        # each of the 8 data-axis shards holds 2 examples
        assert {s.data.shape for s in b.addressable_shards} == {(2, 8, 8, 3)}
        b2 = next(it)
        assert b2.shape == (16, 8, 8, 3)

    def test_synthetic_batches(self):
        it = synthetic_batches(4, image_size=8)
        b = next(it)
        assert b.shape == (4, 8, 8, 3) and b.dtype == np.float32
        assert -1.0 <= b.min() and b.max() <= 1.0
