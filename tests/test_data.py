"""Input-pipeline tests: TFRecord framing + CRC, Example codec, native C++
loader vs pure-Python loader, sharded device delivery
(reference behavior: image_input.py; SURVEY.md §2.2)."""

import os

import jax
import numpy as np
import pytest

from dcgan_tpu.data import tfrecord
from dcgan_tpu.data.example_proto import parse_example, serialize_example
from dcgan_tpu.data.pipeline import (
    DataConfig,
    DevicePrefetcher,
    PythonLoader,
    list_shards,
    make_dataset,
    shard_for_process,
)
from dcgan_tpu.data.synthetic import synthetic_batches, write_image_tfrecords


class TestTFRecord:
    def test_crc32c_known_vectors(self):
        # public CRC32C test vectors
        assert tfrecord.crc32c(b"") == 0
        assert tfrecord.crc32c(b"123456789") == 0xE3069283
        assert tfrecord.crc32c(b"\x00" * 32) == 0x8A9136AA

    def test_roundtrip_with_crc(self, tmp_path):
        path = str(tmp_path / "x.tfrecord")
        recs = [b"alpha", b"", b"\x00\xff" * 100]
        assert tfrecord.write_tfrecords(path, recs) == 3
        out = list(tfrecord.read_tfrecords(path, verify_crc=True))
        assert out == recs

    def test_corruption_detected(self, tmp_path):
        path = str(tmp_path / "x.tfrecord")
        tfrecord.write_tfrecords(path, [b"payload-payload"])
        raw = bytearray(open(path, "rb").read())
        raw[14] ^= 0xFF  # flip a data byte
        open(path, "wb").write(bytes(raw))
        with pytest.raises(IOError):
            list(tfrecord.read_tfrecords(path, verify_crc=True))

    def test_missing_file_raises(self):
        with pytest.raises(FileNotFoundError):
            list(tfrecord.read_tfrecords("/nonexistent/shard"))


class TestExampleProto:
    def test_bytes_roundtrip(self):
        msg = serialize_example({"image_raw": [b"\x01\x02\x03"]})
        assert parse_example(msg) == {"image_raw": [b"\x01\x02\x03"]}

    def test_mixed_features(self):
        msg = serialize_example({
            "image_raw": [b"pixels"],
            "label": [3],
            "scores": [0.5, -1.5],
        })
        out = parse_example(msg)
        assert out["image_raw"] == [b"pixels"]
        assert out["label"] == [3]
        np.testing.assert_allclose(out["scores"], [0.5, -1.5])

    @pytest.mark.slow
    def test_cross_check_against_tensorflow(self):
        """Our codec must interoperate with the real tf.train.Example."""
        tf = pytest.importorskip("tensorflow")
        feats = {"image_raw": [b"\x00" * 16], "label": [7]}
        ours = serialize_example(feats)
        theirs = tf.train.Example()
        theirs.ParseFromString(ours)
        assert theirs.features.feature["image_raw"].bytes_list.value[0] \
            == b"\x00" * 16
        assert theirs.features.feature["label"].int64_list.value[0] == 7
        # and the reverse: parse TF's serialization with our parser
        assert parse_example(theirs.SerializeToString()) == feats


def _write_dataset(tmp_path, n=48, size=8, dtype="float64", shards=3):
    return write_image_tfrecords(
        str(tmp_path / "data"), num_examples=n, image_size=size,
        channels=3, num_shards=shards, record_dtype=dtype)


LOADER_KW = dict(batch=16, example_shape=(8, 8, 3), min_after_dequeue=8,
                 n_threads=3, seed=0, normalize=True, loop=True)


class TestLoaders:
    @pytest.mark.parametrize("dtype", ["float64", "float32", "uint8"])
    def test_native_loader_batches(self, tmp_path, dtype):
        native = pytest.importorskip("dcgan_tpu.data.native")
        paths = _write_dataset(tmp_path, dtype=dtype)
        with native.NativeLoader(paths, record_dtype=dtype,
                                 **LOADER_KW) as ld:
            for _ in range(5):
                b = ld.next()
                assert b.shape == (16, 8, 8, 3) and b.dtype == np.float32
                assert -1.0 <= b.min() and b.max() <= 1.0
                assert b.std() > 0.1  # actually data, not zeros

    def test_stop_terminates_looping_consumer_thread(self, tmp_path):
        """`stop()` must end a loop=True stream (which never reaches EOF on
        its own) from another thread WITHOUT freeing the handle, whether the
        consumer is parked inside `next()` or between calls — the
        destroy-safety contract DevicePrefetcher relies on
        (owner.stop -> join -> owner.close)."""
        import threading

        native = pytest.importorskip("dcgan_tpu.data.native")
        paths = _write_dataset(tmp_path)
        ld = native.NativeLoader(paths, **LOADER_KW)
        first = threading.Event()
        consumed = []

        def consume():
            while True:
                b = ld.next()
                first.set()
                if b is None:
                    return
                consumed.append(b.shape)

        t = threading.Thread(target=consume)
        t.start()
        assert first.wait(timeout=10.0)  # stream is live before the stop
        ld.stop()
        t.join(timeout=5.0)
        assert not t.is_alive()  # next() drained to None — no park, no hang
        assert all(s == (16, 8, 8, 3) for s in consumed)
        ld.close()  # safe: the consumer thread is out of the native call

    def test_native_large_record_crc_roundtrip(self, tmp_path):
        """64px float64 records (98 KB payloads) exercise the 3-way
        interleaved hardware-CRC path (blocks >= 12 KB) against CRCs written
        by the independent Python implementation — the tiny records every
        other test uses only ever hit the serial tail loop. Values must also
        round-trip exactly (decode is a cast, normalize=False)."""
        native = pytest.importorskip("dcgan_tpu.data.native")
        rng = np.random.default_rng(7)
        img = rng.uniform(0.0, 255.0, size=(64, 64, 3)).astype(np.float64)
        path = str(tmp_path / "big.tfrecord")
        tfrecord.write_tfrecords(
            path, [serialize_example({"image_raw": [img.tobytes()]})] * 4)
        kw = dict(batch=4, example_shape=(64, 64, 3), min_after_dequeue=4,
                  n_threads=1, seed=0, normalize=False, loop=True,
                  record_dtype="float64")
        with native.NativeLoader([path], **kw) as ld:
            b = ld.next()
        np.testing.assert_array_equal(b[0], img.astype(np.float32))

    def test_native_large_record_crc_detects_corruption(self, tmp_path):
        """A bit flip deep inside a >=12 KB payload must still be caught —
        pins the interleaved-CRC combine, not just the tail path."""
        native = pytest.importorskip("dcgan_tpu.data.native")
        img = np.zeros((64, 64, 3), np.float64)
        path = str(tmp_path / "big.tfrecord")
        tfrecord.write_tfrecords(
            path, [serialize_example({"image_raw": [img.tobytes()]})])
        raw = bytearray(open(path, "rb").read())
        raw[20_000] ^= 0x01  # inside the first 4 KB-chunk triple
        open(path, "wb").write(bytes(raw))
        kw = dict(batch=1, example_shape=(64, 64, 3), min_after_dequeue=1,
                  n_threads=1, seed=0, normalize=False, loop=True,
                  record_dtype="float64")
        with native.NativeLoader([path], **kw) as ld:
            with pytest.raises(native.NativeLoaderError, match="CRC"):
                ld.next()

    def test_native_missing_feature_error(self, tmp_path):
        native = pytest.importorskip("dcgan_tpu.data.native")
        path = str(tmp_path / "bad.tfrecord")
        tfrecord.write_tfrecords(
            path, [serialize_example({"other": [b"\x00" * 8]})])
        with native.NativeLoader([path], **LOADER_KW) as ld:
            with pytest.raises(native.NativeLoaderError,
                               match="image_raw"):
                ld.next()

    def test_native_crc_error(self, tmp_path):
        native = pytest.importorskip("dcgan_tpu.data.native")
        paths = _write_dataset(tmp_path, n=4, shards=1)
        raw = bytearray(open(paths[0], "rb").read())
        raw[40] ^= 0xFF
        open(paths[0], "wb").write(bytes(raw))
        with native.NativeLoader(paths, **LOADER_KW) as ld:
            with pytest.raises(native.NativeLoaderError, match="CRC"):
                ld.next()

    def test_python_loader_matches_semantics(self, tmp_path):
        paths = _write_dataset(tmp_path)
        ld = PythonLoader(paths, record_dtype="float64", **LOADER_KW)
        b = ld.next()
        assert b.shape == (16, 8, 8, 3)
        assert -1.0 <= b.min() and b.max() <= 1.0
        ld.close()

    def test_no_normalize_keeps_raw_scale(self, tmp_path):
        """normalize=False reproduces the reference's raw-pixel feed
        (SURVEY.md §2.4 #1)."""
        paths = _write_dataset(tmp_path)
        kw = dict(LOADER_KW, normalize=False)
        ld = PythonLoader(paths, record_dtype="float64", **kw)
        b = ld.next()
        assert b.max() > 10.0  # raw [0,255] scale
        ld.close()

    def test_one_epoch_mode(self, tmp_path):
        paths = _write_dataset(tmp_path, n=40)
        kw = dict(LOADER_KW, loop=False)
        ld = PythonLoader(paths, record_dtype="float64", **kw)
        batches = list(ld)
        assert len(batches) == 2  # 40 examples -> 2 full batches of 16
        ld.close()

    @pytest.mark.parametrize("loader_kind", ["native", "python"])
    def test_labeled_batches(self, tmp_path, loader_kind):
        """label_feature yields (images, int32 labels) pairs — the int64
        feature the reference comments out (image_input.py:44)."""
        paths = write_image_tfrecords(
            str(tmp_path / "data"), num_examples=48, image_size=8,
            channels=3, num_shards=3, num_classes=10)
        kw = dict(LOADER_KW, label_feature="label")
        if loader_kind == "native":
            native = pytest.importorskip("dcgan_tpu.data.native")
            ld = native.NativeLoader(paths, record_dtype="float64", **kw)
        else:
            ld = PythonLoader(paths, record_dtype="float64", **kw)
        try:
            for _ in range(3):
                imgs, labels = ld.next()
                assert imgs.shape == (16, 8, 8, 3)
                assert imgs.dtype == np.float32
                assert -1.0 <= imgs.min() and imgs.max() <= 1.0
                assert labels.shape == (16,) and labels.dtype == np.int32
                assert (0 <= labels).all() and (labels < 10).all()
        finally:
            ld.close()

    def test_tiny_dataset_no_deadlock(self, tmp_path):
        """Regression: exactly batch-many examples, one shard, loop=False.

        The reader-completion check used to compare readers_done_ against
        readers_.size(), which the spawned thread can read stale (emplace_back
        publishes the vector size unsynchronized with the thread it starts) —
        a reader finishing a tiny shard before the constructor returned would
        never set done_ and Next() hung forever. 30 fresh loaders catch the
        race reliably; each must yield its single batch then EOF."""
        native = pytest.importorskip("dcgan_tpu.data.native")
        paths = write_image_tfrecords(
            str(tmp_path / "tiny"), num_examples=6, image_size=8,
            num_shards=1, num_classes=2)
        for trial in range(30):
            ld = native.NativeLoader(
                paths, batch=6, example_shape=(8, 8, 3),
                min_after_dequeue=2, n_threads=1, seed=trial,
                normalize=False, loop=False, label_feature="label")
            try:
                first = ld.next()
                assert first is not None, f"trial {trial}: lost final batch"
                imgs, labels = first
                assert imgs.shape == (6, 8, 8, 3)
                assert sorted(labels.tolist()).count(0) + \
                    sorted(labels.tolist()).count(1) == 6
                assert ld.next() is None  # clean EOF after the only batch
            finally:
                ld.close()

    def test_empty_feature_name_skips_non_bytes_entries(self, tmp_path):
        """feature_name='' means 'first bytes feature' — an int64 entry that
        happens to precede the image in map order must be skipped, not fail."""
        native = pytest.importorskip("dcgan_tpu.data.native")
        img = np.full((8, 8, 3), 128.0).astype("float64").tobytes()
        path = str(tmp_path / "mixed.tfrecord")
        # label entry serialized before the image entry
        tfrecord.write_tfrecords(path, [serialize_example(
            {"label": [3], "image_raw": [img]}) for _ in range(16)])
        kw = dict(LOADER_KW, feature_name="", min_after_dequeue=4)
        with native.NativeLoader([path], record_dtype="float64", **kw) as ld:
            b = ld.next()
            assert b.shape == (16, 8, 8, 3)
            np.testing.assert_allclose(b, 128.0 / 127.5 - 1.0, atol=1e-6)

    def test_native_label_out_of_range_errors(self, tmp_path):
        """Labels ride a float32 slot; ids beyond 2^24 must hard-error rather
        than silently round."""
        native = pytest.importorskip("dcgan_tpu.data.native")
        img = np.zeros((8, 8, 3)).astype("float64").tobytes()
        path = str(tmp_path / "big.tfrecord")
        tfrecord.write_tfrecords(path, [serialize_example(
            {"image_raw": [img], "label": [(1 << 24) + 1]})])
        kw = dict(LOADER_KW, label_feature="label")
        with native.NativeLoader([path], record_dtype="float64", **kw) as ld:
            with pytest.raises(native.NativeLoaderError, match="out of range"):
                ld.next()

    def test_labeled_missing_label_feature_errors(self, tmp_path):
        # unlabeled shards + label_feature set -> hard error, not zeros
        paths = _write_dataset(tmp_path, n=8, shards=1)
        kw = dict(LOADER_KW, label_feature="label")
        native = pytest.importorskip("dcgan_tpu.data.native")
        with native.NativeLoader(paths, record_dtype="float64", **kw) as ld:
            with pytest.raises(native.NativeLoaderError, match="label"):
                ld.next()

    def test_python_label_out_of_range_errors(self, tmp_path):
        # the fallback loader enforces the same bound as the native one
        img = np.zeros((8, 8, 3)).astype("float64").tobytes()
        path = str(tmp_path / "big.tfrecord")
        tfrecord.write_tfrecords(path, [serialize_example(
            {"image_raw": [img], "label": [-1]})])
        ld = PythonLoader([path], record_dtype="float64",
                          **dict(LOADER_KW, label_feature="label"))
        try:
            with pytest.raises(RuntimeError, match="out of range"):
                ld.next()
        finally:
            ld.close()


class TestPipeline:
    def test_shard_for_process(self):
        paths = [f"s{i}" for i in range(5)]
        assert shard_for_process(paths, 0, 2) == ["s0", "s2", "s4"]
        assert shard_for_process(paths, 1, 2) == ["s1", "s3"]
        # fewer shards than processes: everyone reads everything
        assert shard_for_process(["s0"], 3, 8) == ["s0"]

    def test_list_shards_empty_dir(self, tmp_path):
        os.makedirs(tmp_path / "empty", exist_ok=True)
        with pytest.raises(FileNotFoundError):
            list_shards(str(tmp_path / "empty"))

    def test_process_local_box_spatial_block(self):
        """The geometry behind the 4-process ring test (VERDICT r4 #3b):
        with a (data=2, model=4) spatial mesh split across 4 hypothetical
        2-device processes, a process owns a batch-slice x height-slice
        BLOCK, not batch/nproc x full height — the assumption that
        silently mis-assembled global arrays before process_local_box."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dcgan_tpu.data.pipeline import process_local_box
        from dcgan_tpu.parallel import make_mesh
        from dcgan_tpu.config import MeshConfig

        mesh = make_mesh(MeshConfig(model=4, spatial=True))  # (2, 4)
        sh = NamedSharding(mesh, P("data", "model", None, None))
        shape = (16, 16, 16, 3)
        dev = mesh.devices  # [2, 4] grid
        # "process 0" = first half of data-row 0: batch 0:8, height 0:8
        box = process_local_box(sh, shape, devices=dev[0, :2])
        assert box == (slice(0, 8), slice(0, 8), slice(0, 16), slice(0, 3))
        # "process 3" = second half of data-row 1: batch 8:16, height 8:16
        box = process_local_box(sh, shape, devices=dev[1, 2:])
        assert box == (slice(8, 16), slice(8, 16), slice(0, 16),
                       slice(0, 3))
        # a full mesh row (the 2-process-x-4-device layout): full height
        box = process_local_box(sh, shape, devices=dev[0, :])
        assert box == (slice(0, 8), slice(0, 16), slice(0, 16),
                       slice(0, 3))
        # labels replicate over "model": same batch slice whichever half
        # of the row the process owns
        lsh = NamedSharding(mesh, P("data"))
        assert process_local_box(lsh, (16,), devices=dev[0, :2]) == \
            process_local_box(lsh, (16,), devices=dev[0, 2:]) == \
            (slice(0, 8),)
        # a diagonal (non-box) device set is rejected, not mis-assembled
        with pytest.raises(ValueError, match="tile a box"):
            process_local_box(sh, shape,
                              devices=[dev[0, 0], dev[1, 1]])

    def test_make_dataset_sharded_delivery(self, tmp_path):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from dcgan_tpu.parallel import make_mesh
        _write_dataset(tmp_path)
        cfg = DataConfig(data_dir=str(tmp_path / "data"), image_size=8,
                         batch_size=16, min_after_dequeue=8, n_threads=2,
                         use_native=True)
        mesh = make_mesh()
        sh = NamedSharding(mesh, P("data", None, None, None))
        it = make_dataset(cfg, sh)
        b = next(it)
        assert b.shape == (16, 8, 8, 3)
        assert b.sharding == sh
        # each of the 8 data-axis shards holds 2 examples
        assert {s.data.shape for s in b.addressable_shards} == {(2, 8, 8, 3)}
        b2 = next(it)
        assert b2.shape == (16, 8, 8, 3)

    def test_float64_on_accelerator_warns(self, tmp_path, monkeypatch):
        """The parity wire format is input-bound at chip rates (BASELINE.md);
        the pipeline must say so when a float64 corpus meets a non-CPU
        consumer (VERDICT r3 #6) — and stay quiet for uint8."""
        import types
        import warnings

        import jax

        _write_dataset(tmp_path)
        fake_tpu = [types.SimpleNamespace(platform="tpu")]
        monkeypatch.setattr(jax, "devices", lambda *a, **k: fake_tpu)
        cfg = DataConfig(data_dir=str(tmp_path / "data"), image_size=8,
                         batch_size=16, min_after_dequeue=8, n_threads=2)
        with pytest.warns(RuntimeWarning, match="float64"):
            next(make_dataset(cfg))
        # uint8 records: no warning
        write_image_tfrecords(
            str(tmp_path / "data8"), num_examples=48, image_size=8,
            channels=3, num_shards=3, record_dtype="uint8")
        cfg8 = DataConfig(data_dir=str(tmp_path / "data8"), image_size=8,
                          batch_size=16, min_after_dequeue=8, n_threads=2,
                          record_dtype="uint8")
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            next(make_dataset(cfg8))

    def test_make_dataset_labeled_delivery(self, tmp_path):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from dcgan_tpu.parallel import make_mesh
        write_image_tfrecords(
            str(tmp_path / "data"), num_examples=48, image_size=8,
            channels=3, num_shards=3, num_classes=4)
        cfg = DataConfig(data_dir=str(tmp_path / "data"), image_size=8,
                         batch_size=16, min_after_dequeue=8, n_threads=2,
                         label_feature="label")
        mesh = make_mesh()
        sh = NamedSharding(mesh, P("data", None, None, None))
        lsh = NamedSharding(mesh, P("data"))
        imgs, labels = next(make_dataset(cfg, sh, lsh))
        assert imgs.shape == (16, 8, 8, 3) and imgs.sharding == sh
        assert labels.shape == (16,) and labels.sharding == lsh
        assert (np.asarray(labels) < 4).all()

    def test_make_dataset_label_range_guard(self, tmp_path):
        """num_classes mismatch (e.g. a 10-class dataset fed to a 4-class
        model) must fail host-side: on device an out-of-range label silently
        one-hots to zeros or clamps the cBN table gather."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from dcgan_tpu.parallel import make_mesh
        write_image_tfrecords(
            str(tmp_path / "data"), num_examples=48, image_size=8,
            channels=3, num_shards=3, num_classes=10)
        cfg = DataConfig(data_dir=str(tmp_path / "data"), image_size=8,
                         batch_size=16, min_after_dequeue=8, n_threads=2,
                         label_feature="label", num_classes=4)
        mesh = make_mesh()
        sh = NamedSharding(mesh, P("data", None, None, None))
        lsh = NamedSharding(mesh, P("data"))
        with pytest.raises(ValueError, match="out of range for num_classes"):
            next(make_dataset(cfg, sh, lsh))
        # matching num_classes passes
        ok = DataConfig(data_dir=str(tmp_path / "data"), image_size=8,
                        batch_size=16, min_after_dequeue=8, n_threads=2,
                        label_feature="label", num_classes=10)
        imgs, labels = next(make_dataset(ok, sh, lsh))
        assert (np.asarray(labels) < 10).all()

    def test_make_dataset_labeled_requires_label_sharding(self, tmp_path):
        write_image_tfrecords(
            str(tmp_path / "data"), num_examples=8, image_size=8,
            channels=3, num_shards=1, num_classes=4)
        from jax.sharding import NamedSharding, PartitionSpec as P
        from dcgan_tpu.parallel import make_mesh
        cfg = DataConfig(data_dir=str(tmp_path / "data"), image_size=8,
                         batch_size=8, min_after_dequeue=4,
                         label_feature="label")
        sh = NamedSharding(make_mesh(), P("data", None, None, None))
        with pytest.raises(ValueError, match="label_sharding"):
            next(make_dataset(cfg, sh))

    def test_synthetic_batches(self):
        it = synthetic_batches(4, image_size=8)
        b = next(it)
        assert b.shape == (4, 8, 8, 3) and b.dtype == np.float32
        assert -1.0 <= b.min() and b.max() <= 1.0

    def test_synthetic_batches_pool_cycles(self):
        """After `pool` fresh batches the stream cycles them (host-RNG cost
        bounded); pool=0 keeps every batch fresh."""
        it = synthetic_batches(2, image_size=8, pool=3)
        first = [next(it) for _ in range(3)]
        second = [next(it) for _ in range(3)]
        for a, b in zip(first, second):
            np.testing.assert_array_equal(a, b)
        # distinct batches within the pool
        assert np.abs(first[0] - first[1]).max() > 0
        fresh = synthetic_batches(2, image_size=8, pool=0)
        a = [next(fresh) for _ in range(4)]
        assert np.abs(a[0] - a[3]).max() > 0
        with pytest.raises(ValueError, match="pool"):
            next(synthetic_batches(2, image_size=8, pool=-1))

    def test_synthetic_labeled_batches(self):
        imgs, labels = next(synthetic_batches(4, image_size=8, num_classes=5))
        assert imgs.shape == (4, 8, 8, 3)
        assert labels.shape == (4,) and labels.dtype == np.int32
        assert (labels < 5).all()


class TestManifestAdoption:
    """read_manifest (pipeline.py): read-only consumers (evals,
    fid_trajectory) adopt the dataset's recorded wire format instead of
    requiring a --record_dtype flag they don't have."""

    def test_read_manifest_absent_then_present(self, tmp_path):
        from dcgan_tpu.data.pipeline import read_manifest

        assert read_manifest(str(tmp_path)) == {}
        (tmp_path / "dataset.json").write_text(
            '{"record_dtype": "uint8", "feature_name": "image_raw"}')
        assert read_manifest(str(tmp_path))["record_dtype"] == "uint8"

    def test_uint8_dataset_loads_via_manifest(self, tmp_path):
        """The evals-side construction: DataConfig derived from dataset.json
        must load a uint8-record dataset that the float64 default would
        reject at the manifest check."""
        import json

        from dcgan_tpu.data.pipeline import read_manifest

        d = str(tmp_path)
        write_image_tfrecords(d, num_examples=8, image_size=8,
                              record_dtype="uint8", num_shards=1)
        (tmp_path / "dataset.json").write_text(json.dumps(
            {"record_dtype": "uint8", "image_size": 8, "channels": 3,
             "feature_name": "image_raw"}))
        m = read_manifest(d)
        cfg = DataConfig(data_dir=d, image_size=8, batch_size=4,
                         min_after_dequeue=4,
                         record_dtype=m.get("record_dtype", "float64"),
                         feature_name=m.get("feature_name", "image_raw"))
        batch = next(iter(make_dataset(cfg)))
        assert batch.shape == (4, 8, 8, 3)
        # float64 default would have been rejected by check_manifest
        bad = DataConfig(data_dir=d, image_size=8, batch_size=4,
                         min_after_dequeue=4)
        with pytest.raises(ValueError, match="record_dtype"):
            next(iter(make_dataset(bad)))


class TestDevicePrefetcher:
    """The background device-feed queue (ISSUE 2 tentpole): depth bound,
    ordering, mid-epoch shutdown, and producer-error propagation."""

    @staticmethod
    def _sharding():
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dcgan_tpu.parallel import make_mesh
        return NamedSharding(make_mesh(), P("data", None, None, None))

    @staticmethod
    def _host_batches(n, size=8):
        rng = np.random.default_rng(0)
        return [rng.uniform(-1, 1, (16, size, size, 3)).astype(np.float32)
                for _ in range(n)]

    def test_ordering_and_delivery(self):
        sh = self._sharding()
        batches = self._host_batches(6)
        pf = DevicePrefetcher(iter(batches), sh, depth=2)
        out = list(pf)
        assert len(out) == 6
        for host, dev in zip(batches, out):
            assert dev.sharding == sh
            np.testing.assert_array_equal(np.asarray(dev), host)
        pf.close()

    def test_depth_bounds_producer_runahead(self):
        """With a stalled consumer the producer parks at depth batches
        queued (+1 in flight) — the queue is bounded, not a hoard of
        device memory."""
        import itertools
        import time as _time

        sh = self._sharding()
        produced = itertools.count()
        count = {"n": 0}

        def host_iter():
            for b in self._host_batches(50):
                count["n"] = next(produced) + 1
                yield b

        pf = DevicePrefetcher(host_iter(), sh, depth=3)
        deadline = _time.time() + 5.0
        while count["n"] < 4 and _time.time() < deadline:
            _time.sleep(0.01)
        _time.sleep(0.3)  # give an unbounded producer time to run away
        assert count["n"] <= 3 + 2  # depth queued + one assembling + slack
        first = next(pf)
        assert first.shape == (16, 8, 8, 3)
        pf.close()

    def test_mid_epoch_close_stops_producer(self):
        closed = {"owner": False}

        class Owner:
            def close(self):
                closed["owner"] = True

        sh = self._sharding()
        pf = DevicePrefetcher(iter(self._host_batches(50)), sh, depth=2,
                              owner=Owner())
        next(pf)  # mid-epoch
        pf.close()
        assert closed["owner"]
        assert not pf._thread.is_alive()
        with pytest.raises(StopIteration):
            next(pf)
        pf.close()  # idempotent

    def test_producer_error_propagates_with_type(self):
        sh = self._sharding()

        def bad_iter():
            yield self._host_batches(1)[0]
            raise ValueError("decode exploded")

        pf = DevicePrefetcher(bad_iter(), sh, depth=2)
        next(pf)
        with pytest.raises(ValueError, match="decode exploded"):
            while True:
                next(pf)

    def test_label_gate_runs_on_producer_thread(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dcgan_tpu.parallel import make_mesh

        mesh = make_mesh()
        sh = NamedSharding(mesh, P("data", None, None, None))
        lsh = NamedSharding(mesh, P("data"))
        imgs = self._host_batches(1)[0]
        labels = np.asarray([7] * 16, dtype=np.int32)  # >= num_classes
        pf = DevicePrefetcher(iter([(imgs, labels)]), sh, lsh, depth=2,
                              num_classes=4)
        with pytest.raises(ValueError, match="out of range"):
            next(pf)

    def test_make_dataset_returns_prefetcher_and_legacy_path(self, tmp_path):
        _write_dataset(tmp_path)
        sh = self._sharding()
        cfg = DataConfig(data_dir=str(tmp_path / "data"), image_size=8,
                         batch_size=16, min_after_dequeue=8, n_threads=2,
                         prefetch_device_batches=2)
        it = make_dataset(cfg, sh)
        assert isinstance(it, DevicePrefetcher)
        b = next(it)
        assert b.shape == (16, 8, 8, 3) and b.sharding == sh
        it.close()
        legacy = DataConfig(data_dir=str(tmp_path / "data"), image_size=8,
                            batch_size=16, min_after_dequeue=8, n_threads=2,
                            prefetch_device_batches=0)
        it2 = make_dataset(legacy, sh)
        assert not isinstance(it2, DevicePrefetcher)
        b2 = next(it2)
        assert b2.shape == (16, 8, 8, 3) and b2.sharding == sh

    def test_close_joins_producer_before_release(self, tmp_path):
        """Regression: close() used to destroy the native loader while the
        producer thread could still be inside `dcgan_loader_next` — a
        use-after-free that segfaulted the whole test process
        intermittently. The fixed order (owner.stop -> join -> owner.close)
        must leave the producer joined on every close."""
        _write_dataset(tmp_path)
        sh = self._sharding()
        cfg = DataConfig(data_dir=str(tmp_path / "data"), image_size=8,
                         batch_size=16, min_after_dequeue=8, n_threads=2,
                         prefetch_device_batches=2)
        for _ in range(10):
            it = make_dataset(cfg, sh)
            next(it)
            it.close()
            assert not it._thread.is_alive()
            assert it._owner is None

    def test_one_epoch_drains_to_stop_iteration(self, tmp_path):
        _write_dataset(tmp_path, n=32, shards=2)
        sh = self._sharding()
        cfg = DataConfig(data_dir=str(tmp_path / "data"), image_size=8,
                         batch_size=16, min_after_dequeue=8, n_threads=2,
                         loop=False, prefetch_device_batches=2)
        out = list(make_dataset(cfg, sh))
        assert len(out) == 2  # 32 examples / 16 per batch, no repeat
