"""Explicit-collective (shard_map) backend tests on the 8-virtual-CPU mesh.

The GSPMD backend states shardings and lets the partitioner place collectives;
this backend writes them by hand (parallel/shard_map_backend.py). The tests
pin down what the two implementations must agree on exactly — real-batch loss
(synced BN + pmean placement), cross-shard parameter consistency (psum
placement) — and that per-shard Pallas kernels compose with data parallelism
here (the capability the GSPMD backend rejects).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # see pytest.ini: excluded from the smoke tier

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.parallel import make_parallel_train, make_shard_map_train
from dcgan_tpu.train import make_train_step

TINY = ModelConfig(output_size=16, gf_dim=8, df_dim=8, compute_dtype="float32")


def real_batch(n=16, size=16):
    rng = np.random.default_rng(0)
    return jnp.asarray(
        np.tanh(rng.normal(size=(n, size, size, 3))).astype(np.float32))


def test_backend_dispatch_and_validation():
    cfg = TrainConfig(model=TINY, batch_size=16, backend="shard_map")
    pt = make_parallel_train(cfg)
    assert pt.cfg.backend == "shard_map"
    with pytest.raises(ValueError, match="data-parallel only"):
        TrainConfig(model=TINY, backend="shard_map", mesh=MeshConfig(model=2))
    with pytest.raises(ValueError, match="unknown backend"):
        TrainConfig(model=TINY, backend="pmap")


def test_real_loss_matches_gspmd():
    """d_loss_real depends only on (params, BN stats, real images) — no
    per-shard randomness — so the explicit pmean of losses and BN moments must
    reproduce the GSPMD backend's numbers on the same global batch."""
    xs, key = real_batch(), jax.random.key(3)
    cfg = TrainConfig(model=TINY, batch_size=16)
    ref = make_parallel_train(cfg)
    s_ref, m_ref = ref.step(ref.init(jax.random.key(0)), xs, key)

    cfg_sm = TrainConfig(model=TINY, batch_size=16, backend="shard_map")
    sm = make_shard_map_train(cfg_sm)
    s_sm, m_sm = sm.step(sm.init(jax.random.key(0)), xs, key)

    np.testing.assert_allclose(float(m_sm["d_loss_real"]),
                               float(m_ref["d_loss_real"]), rtol=1e-5)


def test_params_replicated_consistent_after_steps():
    """After psum'd updates every shard must hold identical parameters — the
    sync-DP guarantee the reference's async PS never had (SURVEY.md §2.5)."""
    cfg = TrainConfig(model=TINY, batch_size=16, backend="shard_map")
    pt = make_shard_map_train(cfg)
    s = pt.init(jax.random.key(0))
    xs = real_batch()
    for i in range(3):
        s, m = pt.step(s, xs, jax.random.fold_in(jax.random.key(1), i))
    assert int(s["step"]) == 3
    for path, leaf in jax.tree_util.tree_leaves_with_path(s["params"]):
        shards = [np.asarray(sh.data) for sh in leaf.addressable_shards]
        for other in shards[1:]:
            np.testing.assert_array_equal(shards[0], other, err_msg=str(path))
    assert all(np.isfinite(float(v)) for v in m.values())


def test_sample_and_summarize():
    cfg = TrainConfig(model=TINY, batch_size=16, backend="shard_map")
    pt = make_shard_map_train(cfg)
    s = pt.init(jax.random.key(0))
    z = jax.random.uniform(jax.random.key(2), (16, 100), minval=-1, maxval=1)
    img = pt.sample(s, z)
    assert img.shape == (16, 16, 16, 3)
    assert float(jnp.max(jnp.abs(img))) <= 1.0

    stats = jax.device_get(pt.summarize(s, real_batch(), jax.random.key(4)))
    some = next(iter(stats.values()))
    # global count: 8 shards x (16/8 sub-batch) worth of activations
    assert int(some["count"]) == int(np.sum(some["bin_counts"]))
    assert np.isfinite(float(some["std"]))

    # held-out loss probe through explicit collectives
    ev = pt.eval_losses(s, real_batch(), z)
    assert np.isfinite(float(ev["d_loss"])) and np.isfinite(float(ev["g_loss"]))


def test_global_histogram_matches_unsharded():
    """activation_stats under axis_name must bin against global min/max and
    psum counts — the result equals the single-device histogram of the full
    batch exactly (integer counts)."""
    from dcgan_tpu.utils.metrics import activation_stats

    cfg = TrainConfig(model=TINY, batch_size=16)
    fns = make_train_step(cfg)
    state = fns.init(jax.random.key(0))
    xs, key = real_batch(), jax.random.key(5)
    ref = jax.device_get(jax.jit(fns.summarize)(state, xs, key))

    cfg_sm = TrainConfig(model=TINY, batch_size=16, backend="shard_map")
    pt = make_shard_map_train(cfg_sm)
    sm = jax.device_get(pt.summarize(pt.init(jax.random.key(0)), xs, key))

    # summarize draws z from the key; G activations differ per shard (folded
    # keys), but D activations come from the same real batch — those must
    # match bin-for-bin.
    for name in ref:
        if not name.startswith("disc/"):
            continue
        np.testing.assert_allclose(sm[name]["min"], ref[name]["min"],
                                   rtol=1e-6, err_msg=name)
        np.testing.assert_allclose(sm[name]["max"], ref[name]["max"],
                                   rtol=1e-6, err_msg=name)
        np.testing.assert_array_equal(sm[name]["bin_counts"],
                                      ref[name]["bin_counts"], err_msg=name)


def test_pallas_composes_with_data_parallelism():
    """use_pallas + 8-device DP under shard_map (per-shard kernels, explicit
    moment pmean). The gspmd backend composes too since VERDICT r1 #5 —
    tests/test_pallas.py::TestGspmdComposition covers that side."""
    pallas_model = ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                               compute_dtype="float32", use_pallas=True)
    cfg = TrainConfig(model=pallas_model, batch_size=16, backend="shard_map")
    pt = make_shard_map_train(cfg)
    s = pt.init(jax.random.key(0))
    s, m = pt.step(s, real_batch(), jax.random.key(1))
    assert np.isfinite(float(m["d_loss"])) and np.isfinite(float(m["g_loss"]))

    # and the fused-kernel step agrees with the jnp step on the same batch
    # where no per-shard randomness enters: the real-branch loss
    cfg_jnp = TrainConfig(model=TINY, batch_size=16, backend="shard_map")
    pt_jnp = make_shard_map_train(cfg_jnp)
    _, m_jnp = pt_jnp.step(pt_jnp.init(jax.random.key(0)), real_batch(),
                           jax.random.key(1))
    np.testing.assert_allclose(float(m["d_loss_real"]),
                               float(m_jnp["d_loss_real"]), rtol=1e-4)


def test_multi_step():
    """K scanned steps, one dispatch, through the explicit-collective
    backend: equals K per-step calls with the same keys."""
    cfg = TrainConfig(model=TINY, batch_size=16, backend="shard_map")
    xs = real_batch()
    keys = jax.random.split(jax.random.key(7), 3)

    pt = make_shard_map_train(cfg)
    s_seq = pt.init(jax.random.key(0))
    for i in range(3):
        s_seq, m_seq = pt.step(s_seq, xs, keys[i])
    s_scan = pt.init(jax.random.key(0))
    s_scan, m_scan = pt.multi_step(
        s_scan, jnp.broadcast_to(xs, (3,) + xs.shape), keys)
    assert int(s_scan["step"]) == 3
    np.testing.assert_allclose(float(m_scan["d_loss"]),
                               float(m_seq["d_loss"]), rtol=1e-4)
    for path, leaf in jax.tree_util.tree_leaves_with_path(s_scan["params"]):
        shards = [np.asarray(sh.data) for sh in leaf.addressable_shards]
        for other in shards[1:]:
            np.testing.assert_array_equal(shards[0], other, err_msg=str(path))


def test_wgan_gp_and_conditional():
    cfg = TrainConfig(model=TINY, batch_size=16, loss="wgan-gp",
                      backend="shard_map")
    pt = make_shard_map_train(cfg)
    s, m = pt.step(pt.init(jax.random.key(0)), real_batch(),
                   jax.random.key(1))
    assert np.isfinite(float(m["gp"]))

    cond = ModelConfig(output_size=16, gf_dim=8, df_dim=8, num_classes=4,
                       compute_dtype="float32")
    cfg_c = TrainConfig(model=cond, batch_size=16, backend="shard_map")
    pt_c = make_shard_map_train(cfg_c)
    y = jnp.arange(16) % 4
    s_c, m_c = pt_c.step(pt_c.init(jax.random.key(0)), real_batch(),
                         jax.random.key(1), y)
    assert np.isfinite(float(m_c["d_loss"]))
    img = pt_c.sample(s_c, jax.random.uniform(jax.random.key(2), (16, 100),
                                              minval=-1, maxval=1), y)
    assert img.shape == (16, 16, 16, 3)
